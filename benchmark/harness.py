"""Shared bench scaffolding for bench.py's measured configs.

The seven serving/training configs (serving, coldstart, generation,
paged, speculative, multitenant, and the `_time_loop` training suite)
accreted one copy each of the same three disciplines, all grown from
measured incidents on this 2-core CPU-share-throttled host (PERF.md):

* **interleaved best-of-N** — single-pass walls swing ~3x with the
  host's multi-second throttle windows, so competing legs must
  ALTERNATE (adjacent legs share a window) and ratios must be the
  best PAIRED ones, never a ratio of global bests (one leg's lucky
  window vs another's throttled one reports 2x-off);
* **fail-fast backend probing** — a wedged TPU tunnel HANGS jax
  backend init instead of raising; the probe child is abandoned on
  timeout (killing a mid-handshake TPU process is what wedges the
  tunnel) and the driver exits 3 instead of hanging;
* **telemetry snapshots** — every BENCH_SELF_*.json carries the r12
  `telemetry` key (metrics exposition + runtime stats + flight
  summary) so future rounds read counter context next to the
  headline number.

This module is that scaffolding ONCE. It changes no measured
semantics: call orders, leg interleavings, and best-of selections are
the ones the configs already used — `write_bench_self` additionally
asserts the emitted record keeps the SAME top-level schema as the
committed BENCH_SELF file it replaces, so a refactor that silently
drops a recorded field fails loudly.

Reference counterpart: reference benchmark/fluid/fluid_benchmark.py
is the per-model harness; a cross-config measurement-discipline layer
has no reference analogue (single-tenant, dedicated-host era).
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
from typing import Callable, Dict, List, Sequence, Tuple

__all__ = ["telemetry_snapshot", "write_bench_self", "probe_backend",
           "best_of", "interleave_rounds", "best_leg",
           "paired_ratio_max", "paired_median_ab", "BENCH_DIR"]

# BENCH_SELF records live next to bench.py at the repo root
BENCH_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def telemetry_snapshot(stats_json_dict=None) -> dict:
    """The `telemetry` key every BENCH_SELF_*.json carries from r12
    on: the central metrics exposition (observability/metrics.py) +
    the runtime's stats_json() dict, so future perf rounds read the
    counter context (compiles, cache tiers, occupancy) next to the
    headline number instead of re-deriving it.

    The flag is flipped to `metrics` just for the expose() call: the
    counters behind the exposition (executor compiles/hits, cache
    residency, server histograms) are live pull providers that count
    at EVERY level, so benches that ran at `off` still snapshot real
    values — only the exposition rendering itself is gated.

    Reference counterpart: the reference had no cross-config telemetry
    record (per-model prints only, reference benchmark/fluid/
    fluid_benchmark.py:296-300); the r12 BENCH_SELF contract is ours.
    """
    from paddle_tpu import observability as obs
    from paddle_tpu.flags import FLAGS, set_flags

    prev = FLAGS.observability
    set_flags({"FLAGS_observability": "metrics"})
    try:
        exposition = obs.metrics.expose()
    finally:
        set_flags({"FLAGS_observability": prev})
    return {
        "metrics_expose": exposition,
        "stats_json": stats_json_dict,
        "flight": {
            "recorded_total": obs.RECORDER.recorded_total,
            "incidents_total": obs.RECORDER.incidents_total,
        },
    }


def write_bench_self(filename: str, result: dict,
                     stats_json_dict=None,
                     allow_schema_change: bool = False) -> dict:
    """Write a BENCH_SELF_*.json next to bench.py, injecting the r12
    `telemetry` key (telemetry_snapshot). When the file already exists
    (the committed record of the last measured round), the new
    result's TOP-LEVEL key set must match it — the BENCH_SELF schema
    is a contract later rounds diff against, and a refactor dropping
    or renaming a recorded field must fail the run, not silently thin
    the record. Intentional schema evolution passes
    ``allow_schema_change=True`` (and reviews the diff). Returns the
    result dict (with telemetry attached).

    Reference counterpart: reference benchmark/fluid/fluid_benchmark.py
    prints per-pass speed lines; a committed machine-readable record
    with a schema contract has no reference analogue.
    """
    result["telemetry"] = telemetry_snapshot(stats_json_dict)
    out_path = os.path.join(BENCH_DIR, filename)
    if os.path.exists(out_path) and not allow_schema_change:
        try:
            with open(out_path) as f:
                old_keys = set(json.load(f))
        except (OSError, ValueError):
            old_keys = None  # unreadable/corrupt: nothing to hold to
        if old_keys is not None and set(result) != old_keys:
            missing = sorted(old_keys - set(result))
            added = sorted(set(result) - old_keys)
            raise AssertionError(
                f"{filename} schema drifted: missing keys {missing}, "
                f"new keys {added}; pass allow_schema_change=True if "
                f"this is an intentional record evolution")
    with open(out_path, "w") as f:
        json.dump(result, f, indent=1)
    # perf-trend sentinel hookup (benchmark/trend.py): a freshly
    # measured record that regresses the committed trajectory must
    # never land silently — the warning prints at write time, the
    # committed bench_trend.json still gates in CI (`bench.py trend`)
    # until refreshed intentionally with --write-trend. Best-effort:
    # trend problems must not fail a bench run that just measured.
    try:
        from .trend import (_cross_round_warnings, build_records,
                            extract_record)

        _ = extract_record(out_path)  # record must stay extractable
        for w in _cross_round_warnings(build_records()):
            print(f"# trend WARNING: {w}")
    except Exception as e:
        print(f"# trend: sentinel skipped ({type(e).__name__}: {e})")
    return result


def probe_backend(timeout_s: float = 180) -> str:
    """Fail fast (instead of hanging the driver) when the TPU tunnel
    is wedged: jax backend init HANGS rather than raising in that
    state (see CLAUDE.md tunnel rules). The probe runs in a child
    process; on timeout the child is ABANDONED, not killed — killing
    a mid-handshake TPU process is exactly what wedges the tunnel.
    Healthy runs pay one extra ~seconds backend init in the child;
    the returned device_kind is reused so the parent only initializes
    once more for the actual benches. Exits 3 on a dead backend.

    Reference counterpart: none — the reference assumed a dedicated
    healthy GPU; the wedgeable-TPU-tunnel probe is this repo's own
    (CLAUDE.md tunnel rules).
    """
    child = subprocess.Popen(
        [sys.executable, "-c",
         "import jax; print(jax.devices()[0].device_kind)"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        start_new_session=True)
    try:
        out, err = child.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        # leave the child running: it either completes harmlessly or
        # was already hung on a dead tunnel
        print("# bench: device backend unresponsive after "
              f"{timeout_s}s (wedged TPU tunnel?) -- aborting instead "
              "of hanging; see BENCH_SELF_r02.json for the last "
              "healthy run", file=sys.stderr)
        sys.exit(3)
    if child.returncode != 0:
        print(f"# bench: backend probe failed: {err[-400:]}",
              file=sys.stderr)
        sys.exit(3)
    return out.strip().splitlines()[-1] if out.strip() else "unknown"


def best_of(fn: Callable[[], float], n: int = 3,
            better=max) -> float:
    """Sequential best-of-N for a SCALAR leg (naive rps floors, child
    process timing loops): this host's single-pass swings are ~3x, so
    anything recorded in a BENCH file is a best-of-N (CLAUDE.md r9).
    For RATIOS between competing legs use interleave_rounds — a
    sequential best-of-N compares throttle-window luck.

    Reference counterpart: reference benchmark/fluid/fluid_benchmark.py
    :296 averages one pass; best-of-N is the throttled-shared-host
    discipline (PERF.md), no reference analogue.
    """
    return better(fn() for _ in range(n))


def interleave_rounds(legs: Sequence[Tuple[str, Callable[[], dict]]],
                      rounds: int = 3) -> List[Dict[str, dict]]:
    """Run the named legs IN ORDER, `rounds` times: adjacent legs of a
    round share this host's multi-second CPU-throttle windows, so
    cross-leg ratios taken WITHIN a round compare modes, not windows
    (the r10 discipline; sequential per-leg best-of-3 measured
    2x-off ratios). Returns one {name: result} dict per round.

    Reference counterpart: none — single-tenant dedicated-host era;
    grown from this repo's r10 measured 2x-off sequential ratios.
    """
    out: List[Dict[str, dict]] = []
    for _ in range(rounds):
        out.append({name: fn() for name, fn in legs})
    return out


def best_leg(rounds: List[Dict[str, dict]], name: str,
             key=lambda r: r["wall_s"]):
    """Best result of ONE leg across rounds (headline numbers).

    Reference counterpart: none (see interleave_rounds).
    """
    return min((r[name] for r in rounds), key=key)


def paired_ratio_max(rounds: List[Dict[str, dict]], num: str,
                     den: str,
                     value=lambda r: r["tok_s"]) -> float:
    """Best PAIRED ratio num/den: each ratio uses the two legs of ONE
    round (shared throttle window). This is the only ratio form the
    configs assert on — best(num)/best(den) across different rounds
    pits one leg's lucky window against another's throttled one.

    Reference counterpart: none (see interleave_rounds); the r10
    guard-test method.
    """
    return max(value(r[num]) / value(r[den]) for r in rounds)


def paired_median_ab(run_leg: Callable[[], tuple],
                     set_mode: Callable[[str], None],
                     mode_a: str, mode_b: str, reps: int):
    """Median of PAIRED adjacent-leg ratios mode_a/mode_b for A/B'ing
    a process-global mode (the r12 observability gate). Three
    defenses against the throttle: the two modes run back-to-back
    (shared throttle state); the order alternates per rep (the second
    leg of a pair trends measurably warmer); and the median over reps
    rejects window-boundary outliers. `run_leg` returns (scalar,
    extra); returns (median_ratio, ratios, legs_by_mode).

    Reference counterpart: none — the r12 observability acceptance
    protocol (PERF.md 'Observability overhead').
    """
    ratios: List[float] = []
    legs: Dict[str, list] = {mode_a: [], mode_b: []}
    for rep in range(reps):
        order = ((mode_a, mode_b) if rep % 2 == 0
                 else (mode_b, mode_a))
        res = {}
        for mode in order:
            set_mode(mode)
            res[mode] = run_leg()
        for m in (mode_a, mode_b):
            legs[m].append(res[m])
        ratios.append(res[mode_a][0] / res[mode_b][0])
    srt = sorted(ratios)
    mid = len(srt) // 2
    med = (srt[mid] if len(srt) % 2
           else 0.5 * (srt[mid - 1] + srt[mid]))
    return med, ratios, legs
