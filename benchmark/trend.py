"""Perf-trend sentinel over the committed BENCH_SELF_r*.json history.

The repo's measured record is a trajectory: every round commits a
BENCH_SELF_r*.json whose headline (``metric``/``unit``/``value``),
ratio fields (``speedup_*``/``ratio_*``) and parity flags
(``*parity*``, ``steady_state_compiles``) are the claims later rounds
build on. Nothing guarded them: a refactor could silently thin a
record, and a regressed headline in a fresh record looked exactly
like an intentional one. This module is the drift gate, in the style
of ``analysis_baseline.json`` (analysis/baseline.py):

* ``build_records()`` extracts a normalized trajectory record per
  BENCH_SELF file — tolerant of every historical schema (r02's
  ``results`` list, r10's nested ``generation`` dict, the r11+ flat
  headline) — including a per-file **noise band** derived from the
  recorded interleaved legs (``rps_legs`` / ``triple_tok_s``): on
  this 2-core CPU-share-throttled host identical legs swing ~3x
  (PERF.md), so the band is wide by design and the sentinel catches
  silent COLLAPSES, not percent-level drift.
* ``diff_against_store()`` compares the files on disk against the
  committed ``bench_trend.json``: a headline that dropped below the
  committed value by more than the noise band, a parity flag that
  went false, or steady-state compiles that became nonzero is a
  **REGRESSION** (loud, named); any other mismatch — new record,
  changed value, drifted schema — is **STALE** (the store must be
  refreshed intentionally). Either fails the gate.
* ``write_store()`` refreshes intentionally (``bench.py trend
  --write-trend``), printing a cross-round warning when a new record
  regresses the previous committed record of the same metric — the
  measurement stands (it IS the record), but it can never land
  silently.

``bench.py trend`` is the CLI; tests/test_benchmark_harness.py runs
the same gate in-process over the committed set (tier-adjacent: the
fast lane asserts the committed store is current).

Reference counterpart: none — reference benchmark/fluid/
fluid_benchmark.py prints per-pass speeds; a committed, gated
perf trajectory has no reference analogue.
"""
from __future__ import annotations

import json
import os
import re
from typing import Dict, List, Optional, Tuple

from . import harness

__all__ = ["STORE_SCHEMA_VERSION", "TREND_STORE", "build_records",
           "extract_record", "load_store", "write_store",
           "diff_against_store", "default_store_path", "main"]

STORE_SCHEMA_VERSION = 1
TREND_STORE = "bench_trend.json"

_FILE_RE = re.compile(r"BENCH_SELF_r(\d+)\.json$")

# when a file records no leg spread (single-pass rounds), assume the
# host's documented worst-case: regressions must clear a 2x drop to
# fire (PERF.md: single-pass walls swing ~3x; the sentinel exists for
# collapses, not percent drift)
_DEFAULT_NOISE_BAND = 0.5
_MIN_NOISE_BAND, _MAX_NOISE_BAND = 0.2, 0.6


def default_store_path() -> str:
    """Committed store location (repo root, beside the BENCH files).
    Reference counterpart: none — the reference commits no benchmark
    trajectory."""
    # late-bound through the module: tests monkeypatch
    # harness.BENCH_DIR, and a value import frozen at whatever dir was
    # active when trend was FIRST imported would point there forever
    return os.path.join(harness.BENCH_DIR, TREND_STORE)


def _headline_dicts(data: dict) -> List[dict]:
    """Every {metric, value[, unit]} headline a record carries, in a
    stable order: the top-level headline (r11+), nested config dicts
    (r10 'generation'), and 'results'/'runs' list entries (r02-r09)."""
    out = []

    def take(d):
        if isinstance(d, dict) and "metric" in d and "value" in d \
                and isinstance(d.get("value"), (int, float)):
            out.append({"metric": str(d["metric"]),
                        "unit": str(d.get("unit", "")),
                        "value": float(d["value"])})

    take(data)
    for key in sorted(data):
        v = data[key]
        if isinstance(v, dict):
            take(v)
        elif isinstance(v, list) and key in ("results", "runs"):
            for entry in v:
                take(entry)
    # one headline per metric name: first (most-authoritative) wins
    seen, uniq = set(), []
    for h in out:
        if h["metric"] not in seen:
            seen.add(h["metric"])
            uniq.append(h)
    return uniq


def _walk_flags(data, path="", depth=0, out=None) -> Dict[str, object]:
    """Parity flags + steady-state-compile counts, recursively (dotted
    paths), bounded depth — the booleans later rounds must not lose."""
    if out is None:
        out = {}
    if depth > 3 or not isinstance(data, dict):
        return out
    for k in sorted(data):
        v = data[k]
        p = f"{path}{k}"
        if isinstance(v, bool) and ("parity" in k or k == "loss_decreased"):
            out[p] = v
        elif k == "steady_state_compiles" and isinstance(v, (int, float)):
            out[p] = int(v)
        elif isinstance(v, dict):
            _walk_flags(v, p + ".", depth + 1, out)
    return out


def _noise_band(data: dict, headline_value: Optional[float]) -> float:
    """1 - min/max over the recorded interleaved legs of the headline
    mode, clamped: the spread the committed legs actually showed is
    the spread a regression must exceed to be a claim and not
    weather."""
    spreads = []

    def spread(vals):
        vals = [v for v in vals if isinstance(v, (int, float)) and v > 0]
        if len(vals) >= 2:
            spreads.append(1.0 - min(vals) / max(vals))

    for key, v in data.items():
        if not isinstance(v, list) or not v:
            continue
        if all(isinstance(x, (int, float)) for x in v) \
                and ("legs" in key or key.endswith("_s")):
            spread(v)
        elif all(isinstance(x, list) for x in v):
            # interleaved triples: [round][leg]; the headline column
            # is the one containing the headline value, else the
            # widest column
            cols = list(zip(*[r for r in v if r]))
            pick = None
            if headline_value is not None:
                for c in cols:
                    if any(abs(float(x) - headline_value) < 1e-6
                           for x in c):
                        pick = c
                        break
            for c in cols if pick is None else [pick]:
                spread(c)
    band = max(spreads) if spreads else _DEFAULT_NOISE_BAND
    return round(min(_MAX_NOISE_BAND, max(_MIN_NOISE_BAND, band)), 4)


def extract_record(path: str) -> dict:
    """One normalized trajectory record for a BENCH_SELF file.
    Reference counterpart: benchmark/fluid/fluid_benchmark.py prints
    per-pass speeds only; normalized committed records are this
    repo's addition."""
    fname = os.path.basename(path)
    m = _FILE_RE.search(fname)
    with open(path) as f:
        data = json.load(f)
    headlines = _headline_dicts(data)
    ratios = {k: float(v) for k, v in data.items()
              if isinstance(v, (int, float)) and not isinstance(v, bool)
              and (k.startswith("speedup") or k.startswith("ratio_"))}
    head_val = headlines[0]["value"] if headlines else None
    return {
        "file": fname,
        "round": int(m.group(1)) if m else None,
        "schema_keys": sorted(str(k) for k in data),
        "headlines": headlines,
        "ratios": ratios,
        "parity": _walk_flags(data),
        "noise_band": _noise_band(data, head_val),
    }


def build_records(bench_dir: Optional[str] = None) -> List[dict]:
    """Trajectory records for every BENCH_SELF_r*.json on disk,
    sorted by round. Reference counterpart: none (see
    extract_record)."""
    bench_dir = bench_dir or harness.BENCH_DIR
    files = sorted(
        (f for f in os.listdir(bench_dir) if _FILE_RE.search(f)),
        key=lambda f: int(_FILE_RE.search(f).group(1)))
    return [extract_record(os.path.join(bench_dir, f)) for f in files]


def load_store(path: Optional[str] = None) -> Optional[dict]:
    """The committed store, schema-guarded (the write_bench_self
    discipline). Reference counterpart: none."""
    path = path or default_store_path()
    if not os.path.exists(path):
        return None
    with open(path) as f:
        store = json.load(f)
    if store.get("schema_version") != STORE_SCHEMA_VERSION:
        raise ValueError(
            f"{TREND_STORE} schema_version "
            f"{store.get('schema_version')!r} != "
            f"{STORE_SCHEMA_VERSION} supported by this checkout — "
            f"refresh with `python bench.py trend --write-trend` and "
            f"review the diff (the write_bench_self schema-guard "
            f"discipline)")
    return store


def _cross_round_warnings(records: List[dict]) -> List[str]:
    """New-record-vs-previous-committed-record regressions of the
    SAME metric name (printed at write time: the measurement stands,
    but it can never land silently)."""
    warnings = []
    last: Dict[str, Tuple[int, float]] = {}
    for rec in records:
        for h in rec["headlines"]:
            prev = last.get(h["metric"])
            band = rec.get("noise_band", _DEFAULT_NOISE_BAND)
            if prev is not None and h["value"] < prev[1] * (1 - band):
                warnings.append(
                    f"cross-round regression: {h['metric']} "
                    f"{prev[1]:g} (r{prev[0]}) -> {h['value']:g} "
                    f"(r{rec['round']}), beyond the {band:.0%} noise "
                    f"band")
            last[h["metric"]] = (rec["round"], h["value"])
    return warnings


def diff_against_store(records: List[dict],
                       store: Optional[dict]) -> Tuple[List[str],
                                                       List[str]]:
    """(regressions, stale) between the files on disk and the
    committed store. Regressions are the loud class — a value
    collapse or a lost parity claim; stale means the store must be
    refreshed intentionally (--write-trend). Both fail the gate.
    Reference counterpart: none — drift gating mirrors
    analysis/baseline.py diff_baseline."""
    regressions: List[str] = []
    stale: List[str] = []
    if store is None:
        return regressions, [f"no committed {TREND_STORE}; create it "
                             f"with `python bench.py trend "
                             f"--write-trend`"]
    by_file = {r["file"]: r for r in records}
    committed = {r["file"]: r for r in store.get("records", [])}
    for fname, old in committed.items():
        new = by_file.get(fname)
        if new is None:
            stale.append(f"{fname}: committed in {TREND_STORE} but "
                         f"missing on disk")
            continue
        band = old.get("noise_band", _DEFAULT_NOISE_BAND)
        new_heads = {h["metric"]: h for h in new["headlines"]}
        for h in old.get("headlines", []):
            got = new_heads.get(h["metric"])
            if got is None:
                stale.append(f"{fname}: headline {h['metric']!r} "
                             f"disappeared from the record")
                continue
            if abs(got["value"] - h["value"]) <= 1e-9 * max(
                    1.0, abs(h["value"])):
                continue
            if got["value"] < h["value"] * (1 - band):
                regressions.append(
                    f"{fname}: headline {h['metric']} REGRESSED "
                    f"{h['value']:g} -> {got['value']:g} (beyond the "
                    f"{band:.0%} recorded noise band)")
            else:
                stale.append(
                    f"{fname}: headline {h['metric']} changed "
                    f"{h['value']:g} -> {got['value']:g}; refresh "
                    f"the store if intentional")
        for key, v in (old.get("ratios") or {}).items():
            got_v = (new.get("ratios") or {}).get(key)
            if got_v is None:
                stale.append(f"{fname}: ratio {key!r} disappeared")
            elif got_v < v * (1 - band):
                regressions.append(
                    f"{fname}: ratio {key} REGRESSED {v:g} -> "
                    f"{got_v:g}")
            elif abs(got_v - v) > 1e-9 * max(1.0, abs(v)):
                stale.append(f"{fname}: ratio {key} changed "
                             f"{v:g} -> {got_v:g}")
        for key, v in (old.get("parity") or {}).items():
            got_v = (new.get("parity") or {}).get(key)
            if isinstance(v, bool):
                if v and got_v is not True:
                    regressions.append(
                        f"{fname}: parity flag {key} was true, now "
                        f"{got_v!r} — a correctness claim was lost")
            elif isinstance(v, int) and v == 0:
                if got_v is None or int(got_v) != 0:
                    regressions.append(
                        f"{fname}: {key} was 0, now {got_v!r} — "
                        f"steady-state compiles appeared")
        if new["schema_keys"] != old.get("schema_keys"):
            missing = sorted(set(old.get("schema_keys", []))
                             - set(new["schema_keys"]))
            added = sorted(set(new["schema_keys"])
                           - set(old.get("schema_keys", [])))
            stale.append(f"{fname}: schema drifted (missing "
                         f"{missing}, new {added})")
    for fname in sorted(set(by_file) - set(committed)):
        stale.append(f"{fname}: new record not in {TREND_STORE}; "
                     f"append with `python bench.py trend "
                     f"--write-trend`")
    return regressions, stale


def write_store(path: Optional[str] = None,
                bench_dir: Optional[str] = None) -> dict:
    """Intentional refresh: rebuild the trajectory from disk, print
    cross-round regression warnings (never silent), write the store,
    return it. Reference counterpart: none — the
    intentional-refresh workflow mirrors analysis/baseline.py
    --write-baseline."""
    records = build_records(bench_dir)
    for w in _cross_round_warnings(records):
        print(f"# trend WARNING: {w}")
    store = {"schema_version": STORE_SCHEMA_VERSION,
             "records": records}
    path = path or default_store_path()
    with open(path, "w") as f:
        json.dump(store, f, indent=1)
        f.write("\n")
    return store


def check(path: Optional[str] = None,
          bench_dir: Optional[str] = None,
          quiet: bool = False) -> int:
    """The gate: 0 green, 2 on any regression or staleness.
    Reference counterpart: none (the analysis_baseline.json gate
    pattern applied to perf)."""
    records = build_records(bench_dir)
    try:
        store = load_store(path)
    except ValueError as e:
        print(f"# trend STALE: {e}")
        return 2
    regressions, stale = diff_against_store(records, store)
    for r in regressions:
        print(f"# trend REGRESSION: {r}")
    for s in stale:
        print(f"# trend STALE: {s}")
    if not regressions and not stale and not quiet:
        n_heads = sum(len(r["headlines"]) for r in records)
        print(f"# trend OK: {len(records)} record(s), {n_heads} "
              f"headline(s), store current")
    return 2 if (regressions or stale) else 0


def main(argv: List[str]) -> int:
    """CLI body for ``python bench.py trend [--write-trend]``.
    Reference counterpart: none."""
    if "--write-trend" in argv or "--write" in argv:
        store = write_store()
        print(f"# trend: wrote {TREND_STORE} with "
              f"{len(store['records'])} record(s)")
        return 0
    return check()
