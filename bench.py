"""Benchmark: Transformer-base training throughput (tokens/sec/chip).

Mirrors the reference harness semantics (reference benchmark/fluid/
fluid_benchmark.py:296-300: examples/sec = num_samples / elapsed) on the
flagship BASELINE.md config 3 workload (Transformer base: d_model=512,
8 heads, 6+6 layers, ffn 2048, Adam). Runs on whatever accelerator jax
exposes (the driver provides one real TPU chip).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline: measured tokens/sec/chip vs the BASELINE.json north-star
per-chip target (v5e-16 pod >= 1x H100 => H100-equivalent 100k tok/s
/ 16 chips = 6250 tok/s/chip).
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np

PER_CHIP_TARGET_TOKENS_PER_SEC = 6250.0


def main():
    import jax

    import paddle_tpu as fluid
    from paddle_tpu.models import transformer as T

    seq, batch = 128, 16
    steps, warmup = 10, 3

    main_prog, startup, cost = T.build_program(
        seq_len=seq, d_model=512, n_heads=8, n_layers=6, d_inner=2048,
        vocab=32000, dropout_rate=0.0, with_optimizer=True,
        learning_rate=2.0, warmup_steps=4000)
    exe = fluid.Executor(fluid.TPUPlace())
    exe.run(startup)
    r = np.random.RandomState(0)
    feed = {
        "src_ids": r.randint(0, 32000, (batch, seq)).astype(np.int64),
        "tgt_ids": r.randint(0, 32000, (batch, seq)).astype(np.int64),
        "label": r.randint(0, 32000, (batch, seq)).astype(np.int64),
    }
    for _ in range(warmup):
        out = exe.run(main_prog, feed=feed, fetch_list=[cost])
    loss0 = float(np.asarray(out[0]).reshape(-1)[0])
    t0 = time.perf_counter()
    for _ in range(steps):
        out = exe.run(main_prog, feed=feed, fetch_list=[cost])
    # fetch forces sync (numpy conversion)
    elapsed = time.perf_counter() - t0
    loss1 = float(np.asarray(out[0]).reshape(-1)[0])
    tokens_per_sec = steps * batch * seq / elapsed
    result = {
        "metric": "transformer_base_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/sec",
        "vs_baseline": round(
            tokens_per_sec / PER_CHIP_TARGET_TOKENS_PER_SEC, 3),
    }
    print(json.dumps(result))
    print(f"# device={jax.devices()[0].device_kind} "
          f"loss {loss0:.4f}->{loss1:.4f} elapsed {elapsed:.2f}s",
          file=sys.stderr)


if __name__ == "__main__":
    main()
