"""Benchmark: all 5 BASELINE.md configs on the real chip.

Mirrors the reference harness semantics (reference benchmark/fluid/
fluid_benchmark.py:296-300: examples/sec = num_samples / elapsed), one
JSON line per config, the flagship Transformer-base line FIRST (the
driver's headline metric). Each config also asserts its loss decreases
over the timed window (the reference's loss-parity oracle, reduced to
the single-chip case).

Transformer runs under bf16 AMP (paddle_tpu/amp.py) with the Pallas
flash-attention forward+backward kernels and reports achieved MFU
against the chip's bf16 peak. vs_baseline for the two north-star
configs (BASELINE.json: v5e-16 pod >= 1x H100) is measured-per-chip /
(H100-equivalent / 16 chips): transformer 100k tok/s -> 6250 tok/s/chip,
ResNet-50 2500 imgs/s -> 156.25 imgs/s/chip. The other three configs
have no reference absolute number (BASELINE.md: "trains with loss
parity"); their vs_baseline is measured / the same per-chip-sliced
self-derived target recorded in TARGETS below.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

TARGETS = {
    # per-chip north-star slices (see module docstring)
    "transformer": 6250.0,     # tokens/sec/chip
    "resnet50": 156.25,        # imgs/sec/chip
    # self-derived: no reference absolute exists (BASELINE.md)
    "stacked_lstm": 3125.0,    # words/sec/chip (50k wps H100-class / 16)
    "ctr": 6250.0,             # examples/sec/chip (100k eps / 16)
    "mnist": 10000.0,          # examples/sec/chip
}

# bf16 peak FLOP/s by device kind substring
_PEAKS = (("v6", 918e12), ("v5p", 459e12), ("v5", 197e12),
          ("v4", 275e12), ("h100", 989e12))


# Shared measurement scaffolding (benchmark/harness.py): interleaved
# best-of-N legs, fail-fast backend probing, telemetry snapshots, and
# the BENCH_SELF schema guard — one implementation for all configs.
from benchmark import harness as _harness

_telemetry_snapshot = _harness.telemetry_snapshot
_write_bench_self = _harness.write_bench_self


def _peak_flops(device_kind: str) -> float:
    kind = device_kind.lower().replace(" ", "")
    for sub, peak in _PEAKS:
        if sub in kind:
            return peak
    return 197e12  # assume v5e-class if unrecognized


def _analytic_train_flops(prog, batch, seq=None):
    """FLOPs per TRAINING step from the program graph: walk the
    forward ops and count the matmul-class work (conv2d, mul/matmul,
    lstm recurrent matmuls) from declared shapes, then apply the
    standard train = 3x forward (backward re-does each matmul twice).
    Elementwise/norm work is ignored — on TPU it is fused into the
    matmuls and contributes negligibly to the FLOP count (not
    necessarily to the runtime; that gap IS what MFU exposes).

    Dynamic dims resolve positionally: a leading -1 is the batch;
    later -1s are the (padded) sequence length `seq`."""
    block = prog.global_block

    def shape_of(name):
        v = block._find_var_recursive(name)
        if v is None or not v.shape:
            return None
        out = []
        for i, d in enumerate(v.shape):
            if d != -1:
                out.append(d)
            elif i == 0:
                out.append(batch)
            else:
                if seq is None:
                    return None
                out.append(seq)
        return tuple(out)

    total = 0.0
    for op in block.ops:
        if op.attrs.get("op_role") in ("backward", "optimize"):
            continue
        if op.type in ("conv2d", "depthwise_conv2d"):
            w = shape_of(op.inputs["Filter"][0])
            out = shape_of(op.outputs["Output"][0])
            if w and out:
                # [F, Cin/g, kh, kw] x [B, F, Ho, Wo]
                total += 2.0 * out[0] * out[2] * out[3] * out[1] \
                    * w[1] * w[2] * w[3]
        elif op.type in ("mul", "matmul", "matmul_v2"):
            x = shape_of(op.inputs["X"][0])
            y = shape_of(op.inputs["Y"][0])
            if x and y and len(y) >= 2:
                numel_x = 1
                for d in x:
                    numel_x *= d
                if op.type == "mul":
                    # mul flattens x's trailing dims into the
                    # contraction (x_num_col_dims semantics):
                    # FLOPs = 2 * |x| * cols
                    y_ncd = op.attrs.get("y_num_col_dims", 1)
                    cols = 1
                    for d in y[y_ncd:]:
                        cols *= d
                else:
                    # matmul: output columns depend on transpose_Y
                    # (QK^T-style calls contract y's LAST dim)
                    ty = op.attrs.get("transpose_Y",
                                      op.attrs.get("transpose_y",
                                                   False))
                    cols = y[-2] if ty else y[-1]
                total += 2.0 * numel_x * cols
        elif op.type in ("dynamic_lstm", "lstm", "cudnn_lstm"):
            x = shape_of(op.inputs.get("Input", [None])[0]
                         or op.inputs.get("X", [None])[0])
            w = shape_of(op.inputs.get("Weight", [None])[0])
            if x and w:
                # recurrent matmul per timestep: [B, h] x [h, 4h]
                t_steps = x[1] if len(x) >= 3 else 1
                b = x[0]
                total += 2.0 * b * t_steps * w[0] * w[1]
        elif op.type == "switch_moe":
            x = shape_of(op.inputs["X"][0])
            w1 = shape_of(op.inputs["W1"][0])
            if x and w1:
                toks = 1
                for d in x[:-1]:
                    toks *= d
                k = op.attrs.get("top_k", 1)
                # each routed token does up+down expert matmuls
                total += 2.0 * 2 * toks * k * w1[1] * w1[2]
    return 3.0 * total


def _mfu(value_per_sec, flops_per_unit):
    import jax

    peak = _peak_flops(jax.devices()[0].device_kind)
    return round(value_per_sec * flops_per_unit / peak, 4)


def _transformer_flops_tok(d_model, d_inner, seq, n_layers, vocab):
    """Analytic matmul+attention FLOPs per token (fwd); train = 3x."""
    d, di, t = d_model, d_inner, seq
    enc = n_layers * (8 * d * d + 4 * d * di + 4 * t * d)
    dec = n_layers * (16 * d * d + 4 * d * di + 8 * t * d)
    logits = 2 * d * vocab
    return 3.0 * (enc + dec + logits)


def _time_loop(exe, prog, feed, fetch, steps, warmup):
    """Timed window = ONE prepared K-step scan call: the whole K-step
    loop is a single device-resident lax.scan, so the window holds
    zero Python dispatches and exactly one host readback (vs one
    pipelined dispatch per step before -- PERF.md "Host dispatch &
    the multi-step scan"). Programs that cannot scan fall back to the
    per-step path inside the prepared handle (named reason on
    exe.last_run_steps_fallback) and this loop still measures them.

    Warmup-K trap, guarded at the source (CLAUDE.md r6 learning): the
    scan executable is specialized on K, so a warmup at a different K
    silently times a cold compile. Here warmup and the timed window
    go through ONE Executor.prepare(steps=K) handle -- the same K by
    construction -- and a belt-and-braces assertion verifies the
    timed window compiled nothing.
    """
    import jax

    # the same batch is fed every step (reference fluid_benchmark feeds
    # synthetic batches too); transfer it once so the timed window
    # measures training, not repeated uploads of identical bytes
    feed = {k: jax.device_put(v) for k, v in feed.items()}
    # prepared dispatch: executable + binding plans resolve once (and
    # load from the warm-start disk cache under FLAGS_compile_cache)
    prepared = exe.prepare(prog, feed, fetch_list=[fetch], steps=steps)
    loss0 = None
    if warmup > 0:
        # pays the XLA compile of the K-step scan (or the disk load)
        out = prepared.run(feed, return_numpy=False)
        loss0 = float(np.asarray(out[0][-1]).reshape(-1)[0])
    compiles_before = exe.compile_count
    t0 = time.perf_counter()
    out = prepared.run(feed, return_numpy=False)
    # fetching ONE element of the stacked losses drains the scan --
    # the single host round-trip of the whole window
    loss1 = float(np.asarray(out[0][-1]).reshape(-1)[0])
    elapsed = time.perf_counter() - t0
    if warmup > 0 and exe.compile_count != compiles_before:
        raise AssertionError(
            f"bench _time_loop: the timed window compiled "
            f"{exe.compile_count - compiles_before} executable(s) -- "
            f"warmup did not warm the K={steps} scan cache "
            f"(warmup-K mismatch trap); the measurement timed a cold "
            f"compile and is invalid")
    if loss0 is None:
        loss0 = float(np.asarray(out[0][0]).reshape(-1)[0])
    return elapsed, loss0, loss1


def bench_transformer():
    import jax

    import paddle_tpu as fluid
    from paddle_tpu import amp
    from paddle_tpu.models import transformer as T

    seq, batch, vocab = 256, 128, 32000
    d_model, n_heads, n_layers, d_inner = 512, 8, 6, 2048
    steps, warmup = 15, 5

    main_prog, startup, cost = T.build_program(
        seq_len=seq, d_model=d_model, n_heads=n_heads, n_layers=n_layers,
        d_inner=d_inner, vocab=vocab, dropout_rate=0.0,
        with_optimizer=True, learning_rate=2.0, warmup_steps=8000)
    exe = fluid.Executor(fluid.TPUPlace())
    r = np.random.RandomState(0)
    feed = {
        "src_ids": r.randint(0, vocab, (batch, seq)).astype(np.int64),
        "tgt_ids": r.randint(0, vocab, (batch, seq)).astype(np.int64),
        "label": r.randint(0, vocab, (batch, seq)).astype(np.int64),
    }
    with amp.amp_guard(True):
        exe.run(startup)
        elapsed, loss0, loss1 = _time_loop(exe, main_prog, feed, cost,
                                           steps, warmup)
    tokens_per_sec = steps * batch * seq / elapsed
    flops_tok = _transformer_flops_tok(d_model, d_inner, seq,
                                       n_layers, vocab)
    peak = _peak_flops(jax.devices()[0].device_kind)
    mfu = tokens_per_sec * flops_tok / peak
    return {
        "metric": "transformer_base_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/sec",
        "vs_baseline": round(tokens_per_sec / TARGETS["transformer"], 3),
        "mfu": round(mfu, 4),
        "loss0": round(loss0, 4), "loss1": round(loss1, 4),
        "loss_decreased": bool(loss1 < loss0),
        "batch": batch, "seq_len": seq, "amp": "bf16",
    }


def bench_resnet50():
    import paddle_tpu as fluid
    from paddle_tpu import amp
    from paddle_tpu.models import resnet

    batch, steps, warmup = 64, 10, 3
    main_prog, startup, cost = resnet.build_program(
        depth=50, class_dim=1000, image_shape=(3, 224, 224), lr=0.1)
    exe = fluid.Executor(fluid.TPUPlace())
    r = np.random.RandomState(0)
    feed = {
        "img": r.randn(batch, 3, 224, 224).astype(np.float32),
        "label": r.randint(0, 1000, (batch, 1)).astype(np.int64),
    }
    with amp.amp_guard(True):
        exe.run(startup)
        elapsed, loss0, loss1 = _time_loop(exe, main_prog, feed, cost,
                                           steps, warmup)
    imgs_per_sec = steps * batch / elapsed
    flops_img = _analytic_train_flops(main_prog, batch) / batch
    return {
        "metric": "resnet50_train_imgs_per_sec_per_chip",
        "value": round(imgs_per_sec, 1),
        "unit": "imgs/sec",
        "vs_baseline": round(imgs_per_sec / TARGETS["resnet50"], 3),
        "mfu": _mfu(imgs_per_sec, flops_img),
        "loss0": round(loss0, 4), "loss1": round(loss1, 4),
        "loss_decreased": bool(loss1 < loss0),
        "batch": batch, "amp": "bf16",
    }


def bench_stacked_lstm():
    import paddle_tpu as fluid
    from paddle_tpu.models import stacked_dynamic_lstm as M

    batch, seq, steps, warmup = 32, 100, 10, 3
    main_prog, startup, cost, _ = M.build_program(
        dict_dim=10000, emb_dim=512, hid_dim=512, stacked_num=3)
    exe = fluid.Executor(fluid.TPUPlace())
    r = np.random.RandomState(0)
    # variable-length batch, padded + @SEQ_LEN (LoD capability)
    lens = r.randint(seq // 2, seq + 1, (batch,)).astype(np.int32)
    words = np.zeros((batch, seq), dtype=np.int64)
    for i, n in enumerate(lens):
        words[i, :n] = r.randint(1, 10000, (n,))
    feed = {
        "words": words,
        "words@SEQ_LEN": lens,
        "label": r.randint(0, 2, (batch, 1)).astype(np.int64),
    }
    exe.run(startup)
    elapsed, loss0, loss1 = _time_loop(exe, main_prog, feed, cost,
                                       steps, warmup)
    words_per_sec = steps * int(lens.sum()) / elapsed
    # per processed (padded) word: the chip computes padded timesteps
    # regardless, so MFU is vs padded work while words/sec counts real
    # words — both reported, the gap is the padding tax
    flops_word = _analytic_train_flops(main_prog, batch, seq=seq) \
        / (batch * seq)
    padded_words_per_sec = steps * batch * seq / elapsed
    return {
        "metric": "stacked_dynamic_lstm_train_words_per_sec_per_chip",
        "value": round(words_per_sec, 1),
        "unit": "words/sec",
        "vs_baseline": round(words_per_sec / TARGETS["stacked_lstm"], 3),
        "mfu": _mfu(padded_words_per_sec, flops_word),
        "loss0": round(loss0, 4), "loss1": round(loss1, 4),
        "loss_decreased": bool(loss1 < loss0),
        "batch": batch, "amp": "fp32",
    }


def bench_ctr():
    import paddle_tpu as fluid
    from paddle_tpu.models import ctr as M

    batch, slots, steps, warmup = 8192, 10, 10, 3
    # lr raised from the reference's 1e-4 so the loss-decrease oracle
    # moves visibly within the short timed window (throughput is the
    # metric; the oracle needs signal at 4-decimal rounding)
    main_prog, startup, cost, _ = M.build_program(lr=0.05)
    exe = fluid.Executor(fluid.TPUPlace())
    r = np.random.RandomState(0)
    feed = {
        "dnn_data": r.randint(1, 10001, (batch, slots)).astype(np.int64),
        "dnn_data@SEQ_LEN": np.full((batch,), slots, dtype=np.int32),
        "lr_data": r.randint(1, 10001, (batch, slots)).astype(np.int64),
        "lr_data@SEQ_LEN": np.full((batch,), slots, dtype=np.int32),
    }
    # click is a deterministic function of the ids so the loss oracle
    # has actual signal (random labels pin bce at ln2 and the
    # loss_decreased check degenerates to float noise); a per-id
    # threshold is directly learnable by the embeddings in few steps
    feed["click"] = (feed["dnn_data"][:, :1] > 5000).astype(np.int64)
    exe.run(startup)
    elapsed, loss0, loss1 = _time_loop(exe, main_prog, feed, cost,
                                       steps, warmup)
    examples_per_sec = steps * batch / elapsed
    return {
        "metric": "ctr_train_examples_per_sec_per_chip",
        "value": round(examples_per_sec, 1),
        "unit": "examples/sec",
        "vs_baseline": round(examples_per_sec / TARGETS["ctr"], 3),
        "loss0": round(loss0, 4), "loss1": round(loss1, 4),
        "loss_decreased": bool(loss1 < loss0),
        "batch": batch, "amp": "fp32",
        "note": "batch re-baselined 512->8192 in r2 (chip-filling config; r1 value 7.1k eps at 512)",
    }


def bench_mnist():
    import paddle_tpu as fluid
    from paddle_tpu.models import mnist as M

    batch, steps, warmup = 4096, 10, 3
    main_prog, startup, cost, _ = M.build_program(use_conv=True)
    with fluid.program_guard(main_prog, startup):
        fluid.optimizer.SGD(learning_rate=0.01).minimize(cost)
    exe = fluid.Executor(fluid.TPUPlace())
    r = np.random.RandomState(0)
    lab = r.randint(0, 10, (batch, 1)).astype(np.int64)
    img = r.randn(batch, 1, 28, 28).astype(np.float32) * 0.1
    img[np.arange(batch), 0, 0, lab[:, 0]] += 2.0  # separable signal
    feed = {"img": img, "label": lab}
    exe.run(startup)
    elapsed, loss0, loss1 = _time_loop(exe, main_prog, feed, cost,
                                       steps, warmup)
    examples_per_sec = steps * batch / elapsed
    return {
        "metric": "mnist_train_examples_per_sec_per_chip",
        "value": round(examples_per_sec, 1),
        "unit": "examples/sec",
        "vs_baseline": round(examples_per_sec / TARGETS["mnist"], 3),
        "loss0": round(loss0, 4), "loss1": round(loss1, 4),
        "loss_decreased": bool(loss1 < loss0),
        "batch": batch, "amp": "fp32",
        "note": "batch re-baselined 256->4096 in r2 (chip-filling "
                "config; r1 value 3.6k eps at 256)",
    }


def bench_transformer_scan(batch=256, seq=256):
    """Transformer-base trained through scan-over-layers
    (PipelineTrainer pp=1): the HLO stops growing linearly in depth,
    which is the framework-native fix for the remote compile helper
    500ing on the fully-unrolled batch>=256 program (PERF.md). OPT-IN
    (run `python bench.py transformer_scan`): kept out of the default
    driver window until A/B'd on the real chip."""
    import jax

    import paddle_tpu as fluid
    from paddle_tpu import amp
    from paddle_tpu.models import transformer as T
    from paddle_tpu.parallel.pipeline_program import (PipelineTrainer,
                                                      propose_loops)

    vocab = 32000
    d_model, n_heads, n_layers, d_inner = 512, 8, 6, 2048
    steps, warmup = 15, 5
    main_prog, startup, cost = T.build_program(
        seq_len=seq, d_model=d_model, n_heads=n_heads,
        n_layers=n_layers, d_inner=d_inner, vocab=vocab,
        dropout_rate=0.0, with_optimizer=True, learning_rate=2.0,
        warmup_steps=8000)
    loops = propose_loops(main_prog, cost.name)
    exe = fluid.Executor(fluid.TPUPlace())
    scope = fluid.Scope()
    r = np.random.RandomState(0)
    feed = {
        "src_ids": r.randint(0, vocab, (batch, seq)).astype(np.int64),
        "tgt_ids": r.randint(0, vocab, (batch, seq)).astype(np.int64),
        "label": r.randint(0, vocab, (batch, seq)).astype(np.int64),
    }
    with amp.amp_guard(True):
        exe.run(startup, scope=scope)
        tr = PipelineTrainer(main_prog, cost, loops=loops)
        tr.initialize(scope)
        for _ in range(warmup):
            out = tr.run(feed=feed)
        loss0 = float(np.asarray(out[0]).reshape(-1)[0])
        t0 = time.perf_counter()
        for _ in range(steps):
            out = tr.run(feed=feed, return_numpy=False)
        loss1 = float(np.asarray(out[0]).reshape(-1)[0])
        elapsed = time.perf_counter() - t0
    tokens_per_sec = steps * batch * seq / elapsed
    flops_tok = _transformer_flops_tok(d_model, d_inner, seq,
                                       n_layers, vocab)
    return {
        "metric": "transformer_scan_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/sec",
        "vs_baseline": round(tokens_per_sec / TARGETS["transformer"], 3),
        "mfu": _mfu(tokens_per_sec, flops_tok),
        "loss0": round(loss0, 4), "loss1": round(loss1, 4),
        "loss_decreased": bool(loss1 < loss0),
        "batch": batch, "seq_len": seq, "amp": "bf16",
        "lowering": "scan-over-layers",
    }


def bench_moe_transformer(batch=64, seq=256):
    """Switch-MoE decoder LM (models/moe_transformer.py): dense FLOPs
    of a 4-layer model, 8x expert capacity on the alternating layers.
    Reports tokens/s + the per-layer drop fractions. OPT-IN
    (`python bench.py moe_transformer`)."""
    import paddle_tpu as fluid
    from paddle_tpu import amp
    from paddle_tpu.models import moe_transformer as M

    vocab = 32000
    d_model, n_heads, n_layers, d_inner = 512, 8, 4, 2048
    steps, warmup = 15, 5
    main_prog, startup, cost = M.build_program(
        seq_len=seq, vocab=vocab, d_model=d_model, n_heads=n_heads,
        n_layers=n_layers, d_inner=d_inner, n_experts=8, top_k=1,
        capacity_factor=2.0, dropout_rate=0.0, learning_rate=2.0,
        warmup_steps=8000)
    exe = fluid.Executor(fluid.TPUPlace())
    r = np.random.RandomState(0)
    feed = {
        "src_ids": r.randint(0, vocab, (batch, seq)).astype(np.int64),
        "label": r.randint(0, vocab, (batch, seq)).astype(np.int64),
    }
    drops = main_prog._moe_drop_vars
    with amp.amp_guard(True):
        exe.run(startup)
        elapsed, loss0, loss1 = _time_loop(exe, main_prog, feed, cost,
                                           steps, warmup)
        drop_vals = [
            float(np.asarray(v).reshape(-1)[0])
            for v in exe.run(main_prog, feed=feed, fetch_list=drops)]
    tokens_per_sec = steps * batch * seq / elapsed
    # dense-equivalent FLOPs: attention stack + top-1 expert FFN per
    # token (same matmul work per token as a dense FFN) + logits
    d, di = d_model, d_inner
    flops_tok = 3.0 * (n_layers * (8 * d * d + 4 * d * di
                                   + 4 * seq * d)
                       + 2 * d * vocab)
    return {
        "metric": "moe_transformer_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/sec",
        "vs_baseline": round(tokens_per_sec / TARGETS["transformer"], 3),
        "mfu": _mfu(tokens_per_sec, flops_tok),
        "loss0": round(loss0, 4), "loss1": round(loss1, 4),
        "loss_decreased": bool(loss1 < loss0),
        "drop_fracs": [round(v, 4) for v in drop_vals],
        "batch": batch, "seq_len": seq, "amp": "bf16",
        "n_experts": 8,
    }


BENCHES = [("transformer", bench_transformer),
           ("resnet50", bench_resnet50),
           ("stacked_lstm", bench_stacked_lstm),
           ("ctr", bench_ctr),
           ("mnist", bench_mnist)]

def bench_transformer_fused():
    """Transformer-base with the whole-layer fused attention block
    (PADDLE_TPU_FUSE_ATTN_BLOCK=1 -> ops/pallas/attention_block.py):
    the PERF.md MFU lever, prepped in r5 while the tunnel was down.
    A/B recipe when the chip returns:
        python bench.py transformer        # unfused baseline
        python bench.py transformer_fused  # fused block
    Same params/init/math (tests/test_attention_block.py), so the
    tokens/s and mfu fields are directly comparable."""
    import os

    prev = os.environ.get("PADDLE_TPU_FUSE_ATTN_BLOCK")
    os.environ["PADDLE_TPU_FUSE_ATTN_BLOCK"] = "1"
    try:
        res = bench_transformer()
    finally:
        if prev is None:
            os.environ.pop("PADDLE_TPU_FUSE_ATTN_BLOCK", None)
        else:
            os.environ["PADDLE_TPU_FUSE_ATTN_BLOCK"] = prev
    res["metric"] = "transformer_fused_train_tokens_per_sec_per_chip"
    res["lowering"] = "fused-attention-block"
    return res


def bench_transformer_scan_fused():
    """scan-over-layers lowering AND the whole-layer fused kernels
    together — the likely best batch-256 config (the scan dodges the
    compile-service 500, the fused blocks cut the HBM/exp cost);
    parity pinned by tests/test_attention_block.py."""
    import os

    prev = os.environ.get("PADDLE_TPU_FUSE_ATTN_BLOCK")
    os.environ["PADDLE_TPU_FUSE_ATTN_BLOCK"] = "1"
    try:
        res = bench_transformer_scan()
    finally:
        if prev is None:
            os.environ.pop("PADDLE_TPU_FUSE_ATTN_BLOCK", None)
        else:
            os.environ["PADDLE_TPU_FUSE_ATTN_BLOCK"] = prev
    res["metric"] = \
        "transformer_scan_fused_train_tokens_per_sec_per_chip"
    res["lowering"] = "scan-over-layers+fused-blocks"
    return res


def bench_serving(n_requests=400):
    """Inference serving throughput at batch-of-1 arrivals: the naive
    per-request `AnalysisPredictor.run` loop vs the DynamicBatcher
    server (inference/serving.py), cold and AOT-warmed. The win is
    the run_steps dispatch-amortization arithmetic applied to serving
    (PERF.md "Serving path") and is CPU-measurable the same way; on
    the tunneled chip the per-request readback (~75 ms) makes the
    batching factor nearly linear in achieved batch occupancy.
    Fail-fast (exit 3) on a dead backend is inherited from main()'s
    _probe_backend, same as every other config."""
    import tempfile

    import paddle_tpu as fluid
    from paddle_tpu.inference import (AnalysisConfig, InferenceServer,
                                      PaddleTensor,
                                      create_paddle_predictor)

    in_dim, hidden, classes = 256, 512, 32
    max_batch = 16
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.layers.data(name="x", shape=[in_dim],
                              dtype="float32")
        h = fluid.layers.fc(input=x, size=hidden, act="relu")
        out = fluid.layers.fc(input=h, size=classes, act="softmax")
    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(startup)
    mdir = tempfile.mkdtemp(prefix="serving_bench_")
    fluid.save_inference_model(mdir, ["x"], [out], exe,
                               main_program=prog)
    pred = create_paddle_predictor(AnalysisConfig(mdir))
    r = np.random.RandomState(0)
    reqs = [r.randn(1, in_dim).astype(np.float32)
            for _ in range(n_requests)]

    def timed_naive():
        pred.run([PaddleTensor(reqs[0], name="x")])  # warm the shape
        t0 = time.perf_counter()
        for a in reqs:
            pred.run([PaddleTensor(a, name="x")])
        return n_requests / (time.perf_counter() - t0)

    def timed_server(warm):
        # share_cache=False isolates each measurement's compile work
        worker = pred.clone(share_cache=False)
        with InferenceServer(worker, max_batch_size=max_batch,
                             max_wait_ms=2.0) as srv:
            if warm:
                srv.aot_warmup()
            t0 = time.perf_counter()
            replies = [srv.submit({"x": a}) for a in reqs]
            for rep in replies:
                rep.result(timeout=600.0)
            rps = n_requests / (time.perf_counter() - t0)
            st = srv.stats()
        return rps, st

    naive_rps = timed_naive()
    cold_rps, _ = timed_server(warm=False)
    warm_rps, st = timed_server(warm=True)
    return {
        "metric": "serving_requests_per_sec_batch1_arrivals",
        "value": round(warm_rps, 1),
        "unit": "requests/sec",
        "naive_rps": round(naive_rps, 1),
        "batched_rps": round(cold_rps, 1),
        "batched_warmed_rps": round(warm_rps, 1),
        "speedup_batched": round(cold_rps / naive_rps, 2),
        "speedup_warmed": round(warm_rps / naive_rps, 2),
        "batch_occupancy": st["batch_occupancy"],
        "p50_ms": st["latency_ms"]["p50"],
        "p99_ms": st["latency_ms"]["p99"],
        "compile_count": st["compile_count"],
        "max_batch_size": max_batch,
        "n_requests": n_requests,
        "model": f"fc {in_dim}->{hidden}->{classes}",
        "telemetry": _telemetry_snapshot(st),
    }


def _coldstart_child(model_dir, cache_dir, n_requests):
    """Subprocess leg of bench_coldstart: a FRESH process loads the
    exported model, AOT-warms every bucket (loading executables from
    the disk compile cache when populated), and serves. Prints one
    JSON line; the parent interprets it. t_first_response_s counts
    from bench.py entry, so jax/XLA init, model load, warmup, and the
    first request are all inside it."""
    t_start = time.perf_counter()
    # CPU-pinned (see bench_coldstart): parent + children must not
    # both touch the chip, and env vars alone are overridden by the
    # axon sitecustomize
    import jax

    jax.config.update("jax_platforms", "cpu")
    from paddle_tpu.flags import set_flags

    set_flags({"FLAGS_compile_cache": "rw",
               "FLAGS_compile_cache_dir": cache_dir})
    from paddle_tpu.core.compile_cache import active_cache
    from paddle_tpu.inference import (AnalysisConfig, InferenceServer,
                                      create_paddle_predictor)

    pred = create_paddle_predictor(AnalysisConfig(model_dir))
    r = np.random.RandomState(0)
    in_dim = 256
    with InferenceServer(pred, max_batch_size=16,
                         max_wait_ms=2.0) as srv:
        srv.aot_warmup()
        srv.infer({"x": r.randn(1, in_dim).astype(np.float32)})
        t_first = time.perf_counter() - t_start
        reqs = [r.randn(1, in_dim).astype(np.float32)
                for _ in range(n_requests)]

        def _served_pass():
            t0 = time.perf_counter()
            replies = [srv.submit({"x": a}) for a in reqs]
            for rep in replies:
                rep.result(timeout=600.0)
            return n_requests / (time.perf_counter() - t0)

        # best-of-3, same as the naive leg (shared-CPU hosts are
        # noisy; harness discipline)
        rps = _harness.best_of(_served_pass, 3)
        st = srv.stats()
    cc = active_cache()
    print(json.dumps({
        "t_first_response_s": round(t_first, 3),
        "rps": round(rps, 1),
        "compile_count": st["compile_count"],
        "disk_load_count": st["disk_load_count"],
        "p50_ms": st["latency_ms"]["p50"],
        "p99_ms": st["latency_ms"]["p99"],
        "disk_cache": cc.stats() if cc is not None else None,
    }), flush=True)


def bench_coldstart(n_requests=400):
    """Warm-start bench: time-to-first-response and compile/disk-hit
    counts for (a) a cold process and (b) a cold process whose disk
    compile cache was populated by (a) -- the PERF.md cold-path cost
    the warm-start layer (core/compile_cache.py) eliminates --
    alongside the naive per-request leg for the rps floor. Each leg
    is a REAL fresh python process (subprocess), so jax/XLA init and
    model load are honestly inside the measurement. Fail-fast (exit
    3) on a dead backend is inherited from main()'s _probe_backend.

    CPU-PINNED by design: compile-time and dispatch-overhead wins are
    honestly CPU-measurable (PERF.md "Warm start"), and the parent +
    two child processes must never hold the TPU tunnel claim
    concurrently (CLAUDE.md tunnel rules) — so this config pins every
    process to the CPU backend explicitly."""
    import subprocess
    import tempfile

    import jax

    jax.config.update("jax_platforms", "cpu")

    import paddle_tpu as fluid
    from paddle_tpu.inference import (AnalysisConfig, PaddleTensor,
                                      create_paddle_predictor)

    in_dim, hidden, classes = 256, 512, 32
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.layers.data(name="x", shape=[in_dim],
                              dtype="float32")
        h = fluid.layers.fc(input=x, size=hidden, act="relu")
        out = fluid.layers.fc(input=h, size=classes, act="softmax")
    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(startup)
    mdir = tempfile.mkdtemp(prefix="coldstart_bench_")
    fluid.save_inference_model(mdir, ["x"], [out], exe,
                               main_program=prog)

    # naive per-request floor (same model/arrivals as bench_serving)
    pred = create_paddle_predictor(AnalysisConfig(mdir))
    r = np.random.RandomState(0)
    reqs = [r.randn(1, in_dim).astype(np.float32)
            for _ in range(n_requests)]
    pred.run([PaddleTensor(reqs[0], name="x")])  # warm the shape

    def _naive_pass():
        t0 = time.perf_counter()
        for a in reqs:
            pred.run([PaddleTensor(a, name="x")])
        return n_requests / (time.perf_counter() - t0)

    # best-of-3 (harness discipline): shared-CPU hosts are noisy
    naive_rps = _harness.best_of(_naive_pass, 3)

    cache_dir = tempfile.mkdtemp(prefix="coldstart_cache_")

    def child(tag):
        t0 = time.perf_counter()
        proc = subprocess.run(
            [sys.executable, __file__, "_coldstart_child", mdir,
             cache_dir, str(n_requests)],
            capture_output=True, text=True, timeout=900)
        wall = time.perf_counter() - t0
        if proc.returncode != 0:
            raise RuntimeError(
                f"coldstart child ({tag}) failed: "
                f"{proc.stderr[-2000:]}")
        res = json.loads(proc.stdout.strip().splitlines()[-1])
        res["process_wall_s"] = round(wall, 3)
        return res

    cold = child("cold")           # populates cache_dir
    warm = child("disk-warmed")    # must serve with ZERO compiles
    return {
        "metric": "serving_coldstart_time_to_first_response",
        "value": warm["t_first_response_s"],
        "unit": "seconds",
        "cold": cold,
        "disk_warmed": warm,
        "naive_rps": round(naive_rps, 1),
        "warm_speedup_vs_naive": round(warm["rps"] / naive_rps, 2),
        "coldstart_speedup": round(
            cold["t_first_response_s"] / warm["t_first_response_s"],
            2),
        "zero_compile_warm_start": warm["compile_count"] == 0,
        "max_batch_size": 16,
        "n_requests": n_requests,
        "model": f"fc {in_dim}->{hidden}->{classes}",
        "telemetry": _telemetry_snapshot(),
    }


def bench_generation(n_requests=96):
    """Generation serving on a mixed-length (Zipf-ish) workload:
    static whole-loop GenerationServer vs ContinuousGenerationServer
    (slot pool + fused admission/decode-burst cycles). The static
    server pays head-of-line blocking — every batch runs to its
    LONGEST member's length — while the slot pool retires EOS'd lanes
    immediately and refills from the queue, so its advantage scales
    with the workload's length variance (PERF.md "Continuous
    batching").

    CPU-PINNED by design (same reasoning as bench_coldstart): the
    scheduler-vs-executable arithmetic is honestly CPU-measurable,
    and per-cycle dispatches through the tunneled chip would measure
    the ~75 ms tunnel readback, not the serving design. Best-of-3 per
    leg: this 2-core host swings single-pass walls ~3x. Fail-fast
    (exit 3) on a dead backend is inherited from main()'s
    _probe_backend."""
    import jax

    jax.config.update("jax_platforms", "cpu")

    import paddle_tpu as fluid
    from paddle_tpu import unique_name
    from paddle_tpu.core.scope import Scope
    from paddle_tpu.inference import (ContinuousGenerationServer,
                                      GenerationServer,
                                      apply_eos_sentinel,
                                      count_generated_tokens)
    from paddle_tpu.models import transformer as T

    V, D, L, S, maxT = 16, 128, 2, 12, 64
    n_slots = 8
    end_id = 1
    rng = np.random.RandomState(7)

    def zipf_prompts(n, r):
        # terminator-copy prompts: EOS planted early for most rows
        # (short generations), none for a ~1-in-8 tail (full-buffer
        # runs) — the Zipf-ish mix where almost every static batch is
        # poisoned by one long member while most of its rows idle
        src = r.randint(3, V, (n, S)).astype(np.int64)
        for i in range(n):
            p = int(r.choice([1, 2, 3, S], p=[.45, .25, .175, .125]))
            if p < S:
                src[i, p:] = end_id
        return src

    # train the terminator-copy task so decode lengths are
    # model-driven (EOS mid-stream), then build both serving paths
    # over the same weights
    scope = Scope()
    with unique_name.guard():
        main_p, startup, loss = T.build_program(
            seq_len=S, d_model=D, n_heads=2, n_layers=L, d_inner=128,
            vocab=V, with_optimizer=False, dropout_rate=0.0)
        with fluid.program_guard(main_p, startup):
            fluid.optimizer.Adam(learning_rate=0.002).minimize(loss)
    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(startup, scope=scope)
    for _ in range(600):
        src = zipf_prompts(8, rng)
        tgt_in = np.concatenate(
            [np.full((8, 1), 2, np.int64), src[:, :-1]], 1)
        exe.run(main_p, feed={"src_ids": src, "tgt_ids": tgt_in,
                              "label": src}, fetch_list=[loss],
                scope=scope)
    kwargs = dict(seq_len=S, max_out_len=maxT, d_model=D, n_heads=2,
                  n_layers=L, d_inner=128, vocab=V, start_id=2,
                  end_id=end_id)
    with unique_name.guard():
        inc_m, _, _, inc_buf = T.build_incremental_decode_program(
            **kwargs)
    with unique_name.guard():
        bundle = T.build_decode_step_program(n_slots=n_slots,
                                             **kwargs)

    srcs = zipf_prompts(n_requests, np.random.RandomState(31))
    ref, = exe.run(inc_m, feed={"src_ids": srcs},
                   fetch_list=[inc_buf], scope=scope)
    want = apply_eos_sentinel(np.asarray(ref), end_id)
    lens = count_generated_tokens(want, end_id)
    total_tokens = int(lens.sum())
    short = lens <= int(np.median(lens))

    def run_leg(make_server, submit):
        srv = make_server()
        try:
            done_at = [None] * n_requests
            t0 = time.perf_counter()
            replies = [submit(srv, s) for s in srcs]
            for i, rep in enumerate(replies):
                rep.add_done_callback(
                    lambda _f, i=i: done_at.__setitem__(
                        i, time.perf_counter()))
            outs = [rep.result(600.0) for rep in replies]
            wall = time.perf_counter() - t0
            # done-callbacks fire on the server thread AFTER result()
            # unblocks; wait for the stragglers before reading
            deadline = time.perf_counter() + 5.0
            while any(d is None for d in done_at) \
                    and time.perf_counter() < deadline:
                time.sleep(0.001)
            st = srv.stats()
        finally:
            srv.close()
        comp_ms = np.array([((d if d is not None else t0 + wall)
                             - t0) * 1e3 for d in done_at])
        return {"wall_s": wall, "tok_s": total_tokens / wall,
                "short_p50_ms": float(np.median(comp_ms[short])),
                "stats": st, "outs": outs}

    def static_leg():
        return run_leg(
            lambda: GenerationServer(
                inc_m, inc_buf, executor=exe, scope=scope,
                end_id=end_id, max_batch_size=n_slots,
                max_wait_ms=2.0),
            lambda srv, s: srv.submit({"src_ids": s[None]}))

    def continuous_leg():
        return run_leg(
            lambda: ContinuousGenerationServer(
                bundle, executor=exe, scope=scope, steps_per_tick=8),
            lambda srv, s: srv.submit(s))

    static_leg()       # warm the static bucket executables
    compiles_before = exe.compile_count
    warm_leg = continuous_leg()  # warms the serve executables
    # INTERLEAVED best-of-3 (harness.interleave_rounds): this host's
    # CPU-share throttle windows last seconds, so alternating legs
    # samples both servers under the same conditions — a sequential
    # best-of-3 can land one whole server inside a slow window and
    # report a 2x-off ratio. The two warm legs above are excluded
    # from the mins so BOTH sides are a best-of-3 over the same
    # interleaved windows (no sample-count asymmetry flattering
    # either ratio).
    rounds = _harness.interleave_rounds(
        [("static", static_leg), ("continuous", continuous_leg)],
        rounds=3)
    sbest = _harness.best_leg(rounds, "static")
    cbest = _harness.best_leg(rounds, "continuous")
    # warmup happens in the first server __init__; later legs and all
    # steady-state traffic must compile NOTHING
    steady_compiles = exe.compile_count - compiles_before \
        - warm_leg["stats"]["warmed_compiles"]
    # token-exact parity of the measured leg (sentinel rows vs the
    # whole-loop oracle) — a fast continuous leg that decoded wrong
    # tokens would be meaningless
    parity = all(
        np.array_equal(np.asarray(o), want[i])
        for leg in [warm_leg] + [r["continuous"] for r in rounds]
        for i, o in enumerate(leg["outs"]))
    cst = cbest["stats"]
    return {
        "metric": "generation_tokens_per_sec_mixed_len",
        "value": round(cbest["tok_s"], 1),
        "unit": "tokens/sec",
        "static_tok_s": round(sbest["tok_s"], 1),
        "continuous_tok_s": round(cbest["tok_s"], 1),
        "speedup_continuous": round(cbest["tok_s"] / sbest["tok_s"],
                                    2),
        "short_req_p50_ms": {
            "static": round(sbest["short_p50_ms"], 1),
            "continuous": round(cbest["short_p50_ms"], 1)},
        "token_parity_vs_whole_loop": parity,
        "steady_state_compiles": int(steady_compiles),
        "slot_occupancy": cst["slot_occupancy"],
        "ttft_p50_ms": cst["ttft_ms"]["p50"],
        "retired_per_s": cst["retired_per_s"],
        "serve_executables": len(bundle.serves),
        "n_requests": n_requests,
        "total_tokens": total_tokens,
        "len_histogram": {int(k): int(v) for k, v in
                          zip(*np.unique(lens, return_counts=True))},
        "workload": "zipf-ish terminator-copy",
        "model": (f"transformer d{D} L{L} S{S} maxT{maxT} "
                  f"slots{n_slots}"),
        "best_of": 3,
        "telemetry": _telemetry_snapshot(cst),
    }


def bench_paged(n_requests=192):
    """Paged KV cache + prefix reuse vs the r10 dense slot pool
    (models/decode_engine.py paged layout +
    PagedContinuousGenerationServer), at MATCHED KV byte budgets —
    the capacity story: the dense layout reserves the full
    [maxT, ...] self-KV and a private cross-KV per lane, so its KV
    budget carries 8 lanes; the same bytes as a shared block pool +
    refcounted prompt entries carry 16 lanes at this workload's
    mixed lengths, and a shared system prompt prefills ONCE (hit
    admissions skip the encoder entirely).

    Workload: 80% of requests use one of a few common prompts
    (Zipf-weighted "system prompts" with model-driven mixed output
    lengths via the terminator-copy task), 20% are unique — the
    million-user traffic shape ROADMAP names.

    Three INTERLEAVED legs (throttled-host discipline): the
    whole-loop GenerationServer (the r10 baseline), the dense-slot
    continuous server, and the paged server. Asserted (r13
    acceptance, not just reported): token-exact parity vs the dense
    whole-loop decode in the SAME measured legs, KV bytes per
    admitted request >= 2x lower paged vs dense-slot, zero
    steady-state compiles, and paged >= 1.5x the WHOLE-LOOP dense
    decode's tok/s. The paged-vs-dense-SLOT ratio is recorded
    unasserted: on this 2-core host, per-tick cost is LINEAR in
    static lanes, so doubling lanes at matched KV bytes roughly
    doubles tick cost and the capacity lever cannot show up as CPU
    tok/s — on the real chip the decode matmuls underutilize the MXU
    and extra lanes are nearly free, which is where requests-per-
    HBM-byte converts to throughput (PERF.md "Paged KV + prefix
    reuse" has the arithmetic).

    CPU-PINNED by design (same reasoning as bench_generation).
    Fail-fast (exit 3) on a dead backend is inherited from main()'s
    _probe_backend. Writes BENCH_SELF_r13.json."""
    import jax

    jax.config.update("jax_platforms", "cpu")

    import paddle_tpu as fluid
    from paddle_tpu import unique_name
    from paddle_tpu.core.scope import Scope
    from paddle_tpu.inference import (ContinuousGenerationServer,
                                      GenerationServer,
                                      PagedContinuousGenerationServer,
                                      apply_eos_sentinel,
                                      count_generated_tokens)
    from paddle_tpu.models import transformer as T
    from paddle_tpu.models.decode_engine import CacheConfig

    V, D, L, S, maxT = 16, 128, 2, 12, 64
    end_id = 1
    dense_slots, paged_slots = 8, 12
    rng = np.random.RandomState(7)

    def term_prompt(r, p):
        src = r.randint(3, V, (S,)).astype(np.int64)
        if p < S:
            src[p:] = end_id
        return src

    # train the terminator-copy task (d128/L2 needs the lr/steps
    # ladder from CLAUDE.md) so output lengths are model-driven; the
    # workload below must only use terminator placements the model
    # SAW here, or untrained placements decode to full buffers and
    # silently flip the length mix
    scope = Scope()
    with unique_name.guard():
        main_p, startup, loss = T.build_program(
            seq_len=S, d_model=D, n_heads=2, n_layers=L, d_inner=128,
            vocab=V, with_optimizer=False, dropout_rate=0.0)
        with fluid.program_guard(main_p, startup):
            fluid.optimizer.Adam(learning_rate=0.002).minimize(loss)
    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(startup, scope=scope)
    for _ in range(600):
        src = np.stack([term_prompt(
            rng, int(rng.choice([2, 3, 5, S], p=[.4, .25, .15, .2])))
            for _ in range(8)])
        tgt_in = np.concatenate(
            [np.full((8, 1), 2, np.int64), src[:, :-1]], 1)
        exe.run(main_p, feed={"src_ids": src, "tgt_ids": tgt_in,
                              "label": src}, fetch_list=[loss],
                scope=scope)

    kwargs = dict(seq_len=S, max_out_len=maxT, d_model=D, n_heads=2,
                  n_layers=L, d_inner=128, vocab=V, start_id=2,
                  end_id=end_id)
    with unique_name.guard():
        inc_m, _, _, inc_buf = T.build_incremental_decode_program(
            **kwargs)
    with unique_name.guard():
        dense = T.build_decode_step_program(n_slots=dense_slots,
                                            **kwargs)
    # 12 lanes / 24 blocks: ~2.1x fewer KV bytes per admitted
    # request than dense-8, with the static-row count low enough that
    # the CPU's lane-linear tick cost doesn't eat the whole capacity
    # win (16 lanes measured 1.1x the whole-loop leg; the full
    # CPU-vs-TPU arithmetic is in PERF.md), and enough blocks that
    # the 20%-long Zipf tail paginates without preemption thrash
    cache = CacheConfig(layout="paged", block_size=16, n_blocks=24,
                        n_prompt_entries=8)
    with unique_name.guard():
        paged = T.build_decode_step_program(
            n_slots=paged_slots, state_prefix="@pgb/", cache=cache,
            **kwargs)
    # the capacity premise: 2x the lanes in FEWER KV bytes
    assert paged.kv_state_bytes() <= dense.kv_state_bytes(), (
        paged.kv_state_bytes(), dense.kv_state_bytes())

    # shared-prefix workload: 80% of traffic uses one of 4 common
    # "system prompts" (Zipf-weighted, mixed model-driven lengths),
    # 20% unique prompts
    wl_rng = np.random.RandomState(31)
    common = [term_prompt(wl_rng, p) for p in (1, 2, 3, S)]
    zipf = np.array([1.0 / (r + 1) ** 1.1 for r in range(4)])
    zipf = 0.8 * zipf / zipf.sum()
    srcs = []
    for _ in range(n_requests):
        u = wl_rng.rand()
        acc = 0.0
        row = None
        for k in range(4):
            acc += zipf[k]
            if u < acc:
                row = common[k]
                break
        if row is None:
            row = term_prompt(wl_rng, int(wl_rng.choice(
                [1, 2, 3, S], p=[.4, .25, .15, .2])))
        srcs.append(row)
    srcs = np.stack(srcs)
    ref, = exe.run(inc_m, feed={"src_ids": srcs},
                   fetch_list=[inc_buf], scope=scope)
    want = apply_eos_sentinel(np.asarray(ref), end_id)
    lens = count_generated_tokens(want, end_id)
    total_tokens = int(lens.sum())

    def run_leg(make_server):
        srv = make_server()
        try:
            t0 = time.perf_counter()
            replies = [srv.submit(s) for s in srcs]
            outs = [rep.result(600.0) for rep in replies]
            wall = time.perf_counter() - t0
            st = srv.stats()
        finally:
            srv.close()
        # parity IN the measured leg: a fast leg that decoded wrong
        # tokens would be meaningless
        assert all(np.array_equal(np.asarray(o), want[i])
                   for i, o in enumerate(outs)), \
            "token parity vs the whole-loop decode failed"
        return {"wall_s": wall, "tok_s": total_tokens / wall,
                "stats": st}

    def whole_loop_leg():
        srv = GenerationServer(
            inc_m, inc_buf, executor=exe, scope=scope, end_id=end_id,
            max_batch_size=dense_slots, max_wait_ms=2.0)
        try:
            t0 = time.perf_counter()
            replies = [srv.submit({"src_ids": s[None]}) for s in srcs]
            outs = [apply_eos_sentinel(
                np.asarray(rep.result(600.0)[0]), end_id)[0]
                for rep in replies]
            wall = time.perf_counter() - t0
            st = srv.stats()
        finally:
            srv.close()
        assert all(np.array_equal(o, want[i])
                   for i, o in enumerate(outs)), \
            "whole-loop leg parity failed"
        return {"wall_s": wall, "tok_s": total_tokens / wall,
                "stats": st}

    def dense_leg():
        return run_leg(lambda: ContinuousGenerationServer(
            dense, executor=exe, scope=scope, steps_per_tick=8))

    def paged_leg():
        return run_leg(lambda: PagedContinuousGenerationServer(
            paged, executor=exe, scope=scope, steps_per_tick=8))

    whole_loop_leg()  # warm all three serve sets (all compiles here)
    dense_leg()
    paged_leg()
    compiles_before = exe.compile_count
    # INTERLEAVED best-of-3 (r10 discipline, harness.interleave_
    # rounds): adjacent legs share this host's CPU-share throttle
    # windows
    rounds = _harness.interleave_rounds(
        [("whole", whole_loop_leg), ("dense", dense_leg),
         ("paged", paged_leg)], rounds=3)
    steady_compiles = exe.compile_count - compiles_before
    assert steady_compiles == 0, (
        f"steady-state legs compiled {steady_compiles}")
    wbest = _harness.best_leg(rounds, "whole")
    dbest = _harness.best_leg(rounds, "dense")
    pbest = _harness.best_leg(rounds, "paged")
    # the ASSERTED ratio is the best PAIRED one (the r10 guard-test
    # method, harness.paired_ratio_max): adjacent legs of a round
    # share this host's throttle window, while ratios of global bests
    # can pit one leg's lucky window against another's throttled one
    speedup_vs_whole = _harness.paired_ratio_max(rounds, "paged",
                                                 "whole")
    ratio_vs_dense_slot = _harness.paired_ratio_max(rounds, "paged",
                                                    "dense")
    triples = [(r["whole"], r["dense"], r["paged"]) for r in rounds]
    triple_toks = [(round(w["tok_s"]), round(d["tok_s"]),
                    round(p["tok_s"])) for w, d, p in triples]
    assert speedup_vs_whole >= 1.5, (
        f"paged tok/s only {speedup_vs_whole:.2f}x the whole-loop "
        f"decode on the shared-prefix workload (paired triples: "
        f"{triple_toks})")

    dense_kv_req = dense.kv_state_bytes() / dense_slots
    paged_kv_req = paged.kv_state_bytes() / paged_slots
    kv_ratio = dense_kv_req / paged_kv_req
    assert kv_ratio >= 2.0, (
        f"KV bytes per admitted request only {kv_ratio:.2f}x lower")
    pst = pbest["stats"]
    bp = pst["block_pool"]
    hit_rate = bp["prefix_hits"] / max(
        1, bp["prefix_hits"] + bp["prefix_misses"] + bp["cow_copies"])
    result = {
        "metric": "paged_kv_tokens_per_sec_shared_prefix",
        "value": round(pbest["tok_s"], 1),
        "unit": "tokens/sec",
        "whole_loop_tok_s": round(wbest["tok_s"], 1),
        "dense_slot_tok_s": round(dbest["tok_s"], 1),
        "paged_tok_s": round(pbest["tok_s"], 1),
        "speedup_vs_whole_loop": round(speedup_vs_whole, 2),
        "ratio_vs_dense_slot": round(ratio_vs_dense_slot, 2),
        "ratio_vs_dense_slot_note": (
            "unasserted: CPU tick cost is linear in static lanes, so "
            "2x lanes at matched KV bytes ~2x the tick — the "
            "capacity lever converts to tok/s only where lanes are "
            "near-free (real-chip MXU; PERF.md)"),
        "triple_tok_s": [[round(w["tok_s"], 1), round(d["tok_s"], 1),
                          round(p["tok_s"], 1)]
                         for w, d, p in triples],
        "token_parity_vs_whole_loop": True,  # asserted per leg
        "steady_state_compiles": int(steady_compiles),
        "kv_bytes_per_request": {
            "dense": int(dense_kv_req), "paged": int(paged_kv_req),
            "ratio": round(kv_ratio, 2)},
        "requests_per_kv_byte": {
            "dense": dense_slots / dense.kv_state_bytes(),
            "paged": paged_slots / paged.kv_state_bytes()},
        "prefix_hit_rate": round(hit_rate, 3),
        "block_pool": bp,
        "slots": {"dense": dense_slots, "paged": paged_slots},
        "cache": {"block_size": cache.block_size,
                  "n_blocks": cache.n_blocks,
                  "n_prompt_entries": cache.n_prompt_entries},
        "workload": "80% shared system prompts (Zipf over 4), "
                    "20% unique; terminator-copy mixed lengths",
        "len_histogram": {int(k): int(v) for k, v in
                          zip(*np.unique(lens, return_counts=True))},
        "n_requests": n_requests,
        "total_tokens": total_tokens,
        "model": f"transformer d{D} L{L} S{S} maxT{maxT}",
        "best_of": 3,
    }
    return _write_bench_self("BENCH_SELF_r13.json", result,
                             stats_json_dict=pst)


def bench_multiturn(n_conversations=12, n_turns=3):
    """Multi-turn chat sessions over the radix block-prefix tree
    (ISSUE 16): each conversation submits a prompt, then extends the
    RETAINED decoded history turn by turn (``submit(session_id=,
    extend_tokens=)``). The radix leg resumes from the longest
    shared block prefix — only the divergent tail is chunk-
    prefilled; the re-prefill leg (``radix_reuse=False``, same
    programs, same session API) replays every turn's FULL history
    into fresh blocks, which is what every turn costs without the
    tree.

    Workload: conversations share prompts Zipf-weighted over 4
    "personas" (greedy decode is deterministic, so same-prompt
    conversations share turn chains CROSS-session through the tree,
    not just within one session). Each turn's extension ends in the
    terminator, so histories grow by a bounded amount and the turn
    structure is model-independent.

    Measured per interleaved round (best-of-3, throttled-host
    discipline): prefilled KV bytes per turn (the radix win:
    ``radix_hit_blocks`` pages are NOT re-computed), TTFT
    percentiles (the replay leg spends P forcing ticks before its
    first new token; radix spends P - h*BS), the prefix hit-DEPTH
    histogram, and BYTE-EXACT token parity radix-vs-replay on every
    turn of every conversation (the replay leg IS the cold decode).
    Zero steady-state compiles across the measured rounds.

    CPU-PINNED by design (same reasoning as bench_generation).
    Writes BENCH_SELF_r16.json."""
    import jax

    jax.config.update("jax_platforms", "cpu")

    import paddle_tpu as fluid
    from paddle_tpu import unique_name
    from paddle_tpu.core.scope import Scope
    from paddle_tpu.inference import PagedContinuousGenerationServer
    from paddle_tpu.models import transformer as T
    from paddle_tpu.models.decode_engine import CacheConfig

    V, D, H, L, S, maxT = 16, 64, 2, 1, 10, 48
    end_id = 1
    BS, NB, E, n_slots = 4, 72, 6, 4
    rng = np.random.RandomState(7)

    def term_prompt(r, p):
        src = r.randint(3, V, (S,)).astype(np.int64)
        if p < S:
            src[p:] = end_id
        return src

    # terminator-copy training (d64 needs the CLAUDE.md lr/steps
    # ladder) — turn-1 lengths are model-driven copies
    fluid.seed(0)
    scope = Scope()
    with unique_name.guard():
        main_p, startup, loss = T.build_program(
            seq_len=S, d_model=D, n_heads=H, n_layers=L, d_inner=128,
            vocab=V, with_optimizer=False, dropout_rate=0.0)
        with fluid.program_guard(main_p, startup):
            fluid.optimizer.Adam(learning_rate=0.005).minimize(loss)
    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(startup, scope=scope)
    for _ in range(400):
        src = np.stack([term_prompt(
            rng, int(rng.choice([5, 6, 7, 8], p=[.25, .25, .25, .25])))
            for _ in range(8)])
        tgt_in = np.concatenate(
            [np.full((8, 1), 2, np.int64), src[:, :-1]], 1)
        exe.run(main_p, feed={"src_ids": src, "tgt_ids": tgt_in,
                              "label": src}, fetch_list=[loss],
                scope=scope)

    kwargs = dict(seq_len=S, max_out_len=maxT, d_model=D, n_heads=H,
                  n_layers=L, d_inner=128, vocab=V, start_id=2,
                  end_id=end_id)
    cache = CacheConfig(layout="paged", block_size=BS, n_blocks=NB,
                        n_prompt_entries=E)
    with unique_name.guard():
        paged = T.build_decode_step_program(
            n_slots=n_slots, state_prefix="@mt/", cache=cache,
            **kwargs)

    # Zipf persona prompts (all conversations draw from 4 personas:
    # entries stay bounded by the persona count, since same-prompt
    # sessions PIN one shared refcounted entry)
    wl = np.random.RandomState(31)
    personas = [term_prompt(wl, p) for p in (5, 6, 7, 8)]
    zipf = np.array([1.0 / (r + 1) ** 1.1 for r in range(4)])
    zipf = zipf / zipf.sum()
    conv_prompt = [personas[int(wl.choice(4, p=zipf))]
                   for _ in range(n_conversations)]
    # per-turn extensions, terminator-closed (bounded histories) and
    # drawn from a small shared pool so same-persona conversations
    # extend identically and share turn-2+ chains cross-session
    ext_pool = [[4, 9, end_id], [6, 3, end_id], [11, 5, end_id]]
    conv_ext = [[ext_pool[int(wl.choice(3))]
                 for _ in range(n_turns - 1)]
                for _ in range(n_conversations)]

    ptok_bytes = L * 2 * H * (D // H) * 4  # self-KV bytes per token

    def leg(radix):
        srv = PagedContinuousGenerationServer(
            paged, executor=exe, scope=scope, steps_per_tick=4,
            radix_reuse=radix)
        turns = [[] for _ in range(n_conversations)]
        positions = 0  # total (history + emitted) positions, for
        #                the prefilled-KV accounting below
        try:
            t0 = time.perf_counter()
            for t in range(n_turns):
                reps = []
                for c in range(n_conversations):
                    if t == 0:
                        reps.append(srv.submit(
                            conv_prompt[c], session_id=c))
                    else:
                        reps.append(srv.submit(
                            conv_prompt[c], session_id=c,
                            extend_tokens=conv_ext[c][t - 1]))
                for c, rep in enumerate(reps):
                    out = np.asarray(rep.result(600.0))
                    turns[c].append(out)
                    positions += int((out != -1).sum())
            wall = time.perf_counter() - t0
            st = srv.stats()
            pst = srv.pool_stats()
            hd = srv._hit_depth
            hit_hist = {str(b): int(n) for b, n in
                        zip(list(hd.buckets) + ["inf"], hd._counts)}
            for c in range(n_conversations):
                srv.close_session(c)
        finally:
            srv.close()
        # prefilled-KV accounting: every (history + emitted) position
        # was WRITTEN except the radix_hit_blocks pages mapped
        # read-only from the tree
        kv_written = (positions - BS * pst["radix_hit_blocks"]) \
            * ptok_bytes
        return {"wall_s": wall, "turns": turns,
                "kv_bytes_per_turn":
                    kv_written / (n_conversations * n_turns),
                "ttft_p50_ms": st["ttft_ms"]["p50"],
                "ttft_p99_ms": st["ttft_ms"]["p99"],
                "hit_depth_histogram": hit_hist,
                "pool": pst, "stats": st}

    def radix_leg():
        return leg(True)

    def replay_leg():
        return leg(False)

    replay_leg()   # warm both serve-tier sets (all compiles here)
    radix_leg()
    compiles_before = exe.compile_count
    rounds = _harness.interleave_rounds(
        [("replay", replay_leg), ("radix", radix_leg)], rounds=3)
    steady_compiles = exe.compile_count - compiles_before
    assert steady_compiles == 0, (
        f"steady-state legs compiled {steady_compiles}")
    # BYTE-EXACT parity on every turn of every conversation, per
    # round: the replay leg is the cold full-history decode
    for r in rounds:
        for c in range(n_conversations):
            for t in range(n_turns):
                assert np.array_equal(r["radix"]["turns"][c][t],
                                      r["replay"]["turns"][c][t]), (
                    f"conv {c} turn {t}: radix decode diverged from "
                    f"cold re-prefill")
    rbest = _harness.best_leg(rounds, "radix")
    pbest = _harness.best_leg(rounds, "replay")
    # paired ratios (the r10 discipline): KV-per-turn is
    # deterministic, TTFT rides the throttle windows
    kv_ratio = min(r["radix"]["kv_bytes_per_turn"]
                   / r["replay"]["kv_bytes_per_turn"]
                   for r in rounds)
    ttft_ratio = min(r["radix"]["ttft_p50_ms"]
                     / r["replay"]["ttft_p50_ms"]
                     for r in rounds)
    assert kv_ratio < 0.8, (
        f"radix leg prefilled {kv_ratio:.2f}x the replay leg's KV "
        f"bytes per turn — the tree is not reusing blocks")
    assert ttft_ratio < 1.0, (
        f"radix TTFT p50 {ttft_ratio:.2f}x replay — resume did not "
        f"shorten time-to-first-token in any paired round")
    result = {
        "metric": "multiturn_kv_bytes_per_turn_radix",
        "value": round(rbest["kv_bytes_per_turn"], 1),
        "unit": "bytes/turn",
        "replay_kv_bytes_per_turn":
            round(pbest["kv_bytes_per_turn"], 1),
        "kv_per_turn_ratio": round(kv_ratio, 3),
        "ttft_p50_ms": {"radix": round(rbest["ttft_p50_ms"], 2),
                        "replay": round(pbest["ttft_p50_ms"], 2),
                        "paired_ratio": round(ttft_ratio, 3)},
        "ttft_p99_ms": {"radix": round(rbest["ttft_p99_ms"], 2),
                        "replay": round(pbest["ttft_p99_ms"], 2)},
        "token_parity_radix_vs_replay": True,  # asserted per round
        "steady_state_compiles": int(steady_compiles),
        "hit_depth_histogram": rbest["hit_depth_histogram"],
        "radix_pool": {k: rbest["pool"][k] for k in
                       ("radix_nodes", "radix_hit_blocks",
                        "radix_inserts", "radix_adoptions",
                        "radix_evicted_blocks", "radix_admissions",
                        "shared_blocks")},
        "workload": f"{n_conversations} conversations x {n_turns} "
                    f"turns, Zipf over 4 personas, terminator-"
                    f"closed extensions",
        "cache": {"block_size": BS, "n_blocks": NB,
                  "n_prompt_entries": E},
        "model": f"transformer d{D} L{L} S{S} maxT{maxT}",
        "best_of": 3,
    }
    return _write_bench_self("BENCH_SELF_r16.json", result,
                             stats_json_dict=rbest["stats"])


def bench_prefill(n_longs=3, shorts_per_long=6):
    """Chunked prefill vs monolithic admission (ISSUE 17): the
    TTFT-vs-ITL coupling. Today a miss-tier admission runs the FULL
    encoder prefill inside the serve program, so one 2k-token
    arrival stalls every live lane's decode tick; chunked prefill
    (Sarathi-style, C prompt tokens per tick through the
    ``("chunked", p)`` phase programs) bounds the stall at one
    chunk.

    ONE bundle (seq_len=2048, chunk_tokens=256 -> 8 chunks x 4
    phases), TWO legs over the same executor/scope:

    * ``chunked`` — the default two-tier schedule: chunk ticks
      interleave with decode bursts;
    * ``mono``    — ``chunked_prefill=False``: the same programs
      minus the chunk tier; cold admissions prefill monolithically.

    Each leg measures two windows (stats(reset=True) between them):
    a LONG-ONLY window (two cold 2k prompts back-to-back -> server
    ttft_ms is long-only by construction) and the INTERLEAVED window
    — hit-tier shorts stream while a cold 2k prompt arrives; each
    short's inter-token latency is client-side wall / tokens, so
    the monolithic stall lands in the short ITL p99 directly.

    Discipline (PERF.md, throttled 2-core host): both legs warmed
    once (all compiles), then interleave_rounds best-of-3 — paired
    per-round ITL ratios only; BYTE-EXACT token parity chunked vs
    mono on every request of every round (phase-major chunking is
    exact, not approximate); zero steady-state compiles across the
    measured rounds; executable count bounded by the bundle's serve
    programs (#bucket tiers + #chunk phases) + slot-state init.

    ``radix_reuse=False`` on BOTH legs: identical repeat shorts
    would otherwise resume from the radix tree (near-free decode)
    and thin the very decode traffic the stall is measured against.

    CPU-PINNED by design (the stall is host-observable wall time;
    same reasoning as bench_generation). Writes BENCH_SELF_r18.json.
    """
    import jax

    jax.config.update("jax_platforms", "cpu")

    import paddle_tpu as fluid
    from paddle_tpu import unique_name
    from paddle_tpu.core.scope import Scope
    from paddle_tpu.inference import PagedContinuousGenerationServer
    from paddle_tpu.models import transformer as T
    from paddle_tpu.models.decode_engine import CacheConfig

    V, D, H, L, S, maxT = 16, 32, 2, 1, 2048, 16
    BS, NB, E, n_slots, C = 8, 24, 6, 4, 256
    NC = (S + C - 1) // C
    NPH = 2 * L + 2

    # untrained, seed-pinned: greedy decode is deterministic either
    # way, and parity/latency need no trained weights at S=2048
    fluid.seed(0)
    scope = Scope()
    with unique_name.guard():
        _, startup, _ = T.build_program(
            seq_len=S, d_model=D, n_heads=H, n_layers=L, d_inner=64,
            vocab=V, with_optimizer=False, dropout_rate=0.0)
    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(startup, scope=scope)
    with unique_name.guard():
        bundle = T.build_decode_step_program(
            n_slots=n_slots, admit_buckets=[1], state_prefix="@pf/",
            seq_len=S, max_out_len=maxT, d_model=D, n_heads=H,
            n_layers=L, d_inner=64, vocab=V, start_id=2, end_id=1,
            cache=CacheConfig(layout="paged", block_size=BS,
                              n_blocks=NB, n_prompt_entries=E,
                              chunk_tokens=C))
    compiles0 = exe.compile_count

    # fixed prompt sets, identical across legs and rounds: 2 shorts
    # (hit tier after the warm pass) + 5 distinct cold 2k longs
    # (2 for the long-only TTFT window, n_longs for the interleaved
    # one). E=6 entries: the shorts stay MRU through the interleaved
    # stream, so entry eviction only ever recycles a long's entry.
    rng = np.random.RandomState(11)
    shorts = [rng.randint(3, V, (1, S)).astype(np.int64)
              for _ in range(2)]
    longs = [rng.randint(3, V, (1, S)).astype(np.int64)
             for _ in range(2 + n_longs)]

    def _p99(vals):
        srt = sorted(vals)
        return srt[max(0, int(np.ceil(0.99 * len(srt))) - 1)]

    def leg(chunked):
        srv = PagedContinuousGenerationServer(
            bundle, executor=exe, scope=scope, steps_per_tick=4,
            chunked_prefill=chunked, radix_reuse=False)
        toks = []
        try:
            for p in shorts:  # warm the hit tier (cold exactly once)
                toks.append(np.asarray(srv.submit(p).result(600.0)))
            srv.stats(reset=True)
            # LONG-ONLY window: server ttft_ms sees only cold 2k
            # prompts here
            long_walls = []
            for p in longs[:2]:
                t0 = time.perf_counter()
                toks.append(np.asarray(srv.submit(p).result(600.0)))
                long_walls.append((time.perf_counter() - t0) * 1e3)
            st_long = srv.stats(reset=True)
            # INTERLEAVED window: shorts stream while a cold 2k
            # prompt chunks in (or stalls the loop, mono leg)
            itl = []
            for k in range(n_longs):
                rep = srv.submit(longs[2 + k])
                for j in range(shorts_per_long):
                    t0 = time.perf_counter()
                    out = np.asarray(
                        srv.submit(shorts[j % 2]).result(600.0))
                    ntok = max(int((out != -1).sum()), 1)
                    itl.append(
                        (time.perf_counter() - t0) * 1e3 / ntok)
                    toks.append(out)
                toks.append(np.asarray(rep.result(600.0)))
            st = srv.stats()
            pst = srv.pool_stats()
        finally:
            srv.close()
        return {"wall_s": sum(long_walls) / 1e3, "toks": toks,
                "itl_p99_ms": _p99(itl), "itl_ms": itl,
                "long_ttft_ms": st_long["ttft_ms"],
                "long_wall_p50_ms": sorted(long_walls)[
                    len(long_walls) // 2],
                "stats": st, "pool": pst}

    def chunked_leg():
        return leg(True)

    def mono_leg():
        return leg(False)

    mono_leg()     # warm both serve-tier sets (all compiles here)
    chunked_leg()
    warm_compiles = exe.compile_count - compiles0
    # #bucket tiers + #chunk phases (+ slot-state init/reset bits):
    # the whole point of the two-tier schedule is that chunking adds
    # NPH programs, not NC x NPH
    exe_bound = len(bundle.serves) + 4
    assert warm_compiles <= exe_bound, (
        f"warm legs compiled {warm_compiles} executables — bound is "
        f"{len(bundle.serves)} serve programs + 4 init")
    compiles_before = exe.compile_count
    rounds = _harness.interleave_rounds(
        [("mono", mono_leg), ("chunked", chunked_leg)], rounds=3)
    steady_compiles = exe.compile_count - compiles_before
    assert steady_compiles == 0, (
        f"steady-state legs compiled {steady_compiles}")
    # BYTE-EXACT parity on every request of every round: phase-major
    # chunking must not change one served token
    for r in rounds:
        assert len(r["chunked"]["toks"]) == len(r["mono"]["toks"])
        for i, (a, b) in enumerate(zip(r["chunked"]["toks"],
                                       r["mono"]["toks"])):
            assert np.array_equal(a, b), (
                f"request {i}: chunked decode diverged from "
                f"monolithic admission")
    # paired per-round ITL ratios (the r10 discipline)
    ratios = [r["chunked"]["itl_p99_ms"] / r["mono"]["itl_p99_ms"]
              for r in rounds]
    med_ratio = sorted(ratios)[len(ratios) // 2]
    assert min(ratios) < 1.0 and med_ratio < 1.0, (
        f"short-request ITL p99 paired ratios {ratios}: chunked "
        f"prefill did not beat the monolithic stall")
    cbest = _harness.best_leg(rounds, "chunked",
                              key=lambda r: r["itl_p99_ms"])
    mbest = _harness.best_leg(rounds, "mono",
                              key=lambda r: r["itl_p99_ms"])
    result = {
        "metric": "prefill_short_itl_p99_chunked",
        "value": round(cbest["itl_p99_ms"], 2),
        "unit": "ms/token",
        "mono_itl_p99_ms": round(mbest["itl_p99_ms"], 2),
        "itl_p99_paired_ratios": [round(r, 3) for r in ratios],
        "itl_p99_ratio_median": round(med_ratio, 3),
        "long_ttft_ms": {
            "chunked": cbest["long_ttft_ms"],
            "mono": mbest["long_ttft_ms"],
        },
        "long_wall_p50_ms": {
            "chunked": round(cbest["long_wall_p50_ms"], 1),
            "mono": round(mbest["long_wall_p50_ms"], 1),
        },
        "token_parity_chunked_vs_mono": True,  # asserted per round
        "steady_state_compiles": int(steady_compiles),
        "warm_compiles": int(warm_compiles),
        "executable_bound": int(exe_bound),
        "chunk": {
            "chunk_tokens": C, "n_chunks": NC, "phases": NPH,
            "chunk_jobs": cbest["pool"]["chunk_jobs"],
            "chunk_ticks": cbest["pool"]["chunk_ticks"],
        },
        "workload": f"{shorts_per_long} hit-tier shorts streamed per "
                    f"cold {S}-token arrival x {n_longs} arrivals; "
                    f"2-long TTFT window per leg",
        "cache": {"block_size": BS, "n_blocks": NB,
                  "n_prompt_entries": E},
        "model": f"transformer d{D} L{L} S{S} maxT{maxT}",
        "best_of": 3,
    }
    return _write_bench_self("BENCH_SELF_r18.json", result,
                             stats_json_dict=cbest["stats"])


def bench_sharded(n_requests=120):
    """Sharded serving: tensor-parallel decode + data-parallel lanes
    on the virtual 8-device mesh (models/decode_engine.ShardingConfig
    + core/sharding_plan.py + runtime/placement.py).

    XLA fixes the host-platform device count at backend init, and the
    driver's probe already initialized jax in THIS process — so the
    measurement runs in a CHILD process with
    ``--xla_force_host_platform_device_count=8`` set (the
    _coldstart_child discipline), which also CPU-pins it by
    construction. The child writes BENCH_SELF_r17.json and prints the
    record; this parent relays it.

    Three INTERLEAVED legs (throttled-host discipline), all on the
    paged serve path with identical geometry and token-exact parity
    vs the whole-loop decode asserted per leg:

      single — the r13 paged server, one device;
      tp2    — the same bundle tensor-parallel over devices [0,1]
               (head-sharded KV pool, row/column-parallel
               projections, vocab-sharded logits);
      tp2+dp — TWO tp=2 models on disjoint slices [0,1] / [2,3],
               traffic split between them (the runtime placement
               carve, minus the fc lanes the tests cover).

    The ASSERTED wins are per-device KV bytes (pool shard bytes
    exactly 1/tp, >= 1.8x smaller) and the dp-lane AGGREGATE over one
    tp model; the tp2-vs-single tok/s ratio is recorded UNASSERTED
    with the CPU caveat: on this 2-core host every psum is a
    same-core memcpy + sync that costs a visible slice of the tick,
    while on the real chip the decode matmuls underutilize the MXU
    and the collectives ride the ICI (PERF.md "Sharded serving" has
    the arithmetic)."""
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               XLA_FLAGS=(os.environ.get("XLA_FLAGS", "")
                          + " --xla_force_host_platform_device_count"
                            "=8").strip())
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "_sharded_child",
         str(n_requests)],
        env=env, capture_output=True, text=True, timeout=3600)
    sys.stderr.write(proc.stderr[-4000:])
    if proc.returncode != 0:
        raise RuntimeError(
            f"sharded child failed (rc {proc.returncode}); stderr "
            f"tail above")
    return json.loads(proc.stdout.strip().splitlines()[-1])


def _bench_sharded_impl(n_requests):
    """The child-process body of bench_sharded (8 virtual devices)."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    assert jax.device_count() >= 8, jax.device_count()

    import paddle_tpu as fluid
    from paddle_tpu import unique_name
    from paddle_tpu.core.scope import Scope
    from paddle_tpu.inference import (PagedContinuousGenerationServer,
                                      apply_eos_sentinel,
                                      count_generated_tokens)
    from paddle_tpu.models import transformer as T
    from paddle_tpu.models.decode_engine import (CacheConfig,
                                                 ShardingConfig)

    V, D, H, L, S, maxT = 16, 64, 4, 1, 12, 64
    end_id = 1
    n_slots = 8
    rng = np.random.RandomState(7)

    def term_prompt(r, p):
        src = r.randint(3, V, (S,)).astype(np.int64)
        if p < S:
            src[p:] = end_id
        return src

    scope = Scope()
    with unique_name.guard():
        main_p, startup, loss = T.build_program(
            seq_len=S, d_model=D, n_heads=H, n_layers=L, d_inner=128,
            vocab=V, with_optimizer=False, dropout_rate=0.0)
        with fluid.program_guard(main_p, startup):
            fluid.optimizer.Adam(learning_rate=0.005).minimize(loss)
    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(startup, scope=scope)
    for _ in range(400):
        src = np.stack([term_prompt(
            rng, int(rng.choice([2, 3, 5, S], p=[.4, .25, .15, .2])))
            for _ in range(8)])
        tgt_in = np.concatenate(
            [np.full((8, 1), 2, np.int64), src[:, :-1]], 1)
        exe.run(main_p, feed={"src_ids": src, "tgt_ids": tgt_in,
                              "label": src}, fetch_list=[loss],
                scope=scope)

    kwargs = dict(seq_len=S, max_out_len=maxT, d_model=D, n_heads=H,
                  n_layers=L, d_inner=128, vocab=V, start_id=2,
                  end_id=end_id)
    cache = CacheConfig(layout="paged", block_size=16, n_blocks=24,
                        n_prompt_entries=8)
    with unique_name.guard():
        inc_m, _, _, inc_buf = T.build_incremental_decode_program(
            **kwargs)
    with unique_name.guard():
        b_single = T.build_decode_step_program(
            n_slots=n_slots, state_prefix="@sg/", cache=cache,
            **kwargs)
    with unique_name.guard():
        b_tp = T.build_decode_step_program(
            n_slots=n_slots, state_prefix="@tp/", cache=cache,
            sharding=ShardingConfig(tp=2), **kwargs)
    with unique_name.guard():
        b_tp2 = T.build_decode_step_program(
            n_slots=n_slots, state_prefix="@tq/", cache=cache,
            sharding=ShardingConfig(tp=2), **kwargs)

    # shared-prefix workload (the r13 shape: 80% Zipf over 4 system
    # prompts, 20% unique, model-driven mixed lengths)
    wl_rng = np.random.RandomState(31)
    common = [term_prompt(wl_rng, p) for p in (2, 3, 5, S)]
    srcs = []
    for _ in range(n_requests):
        u = wl_rng.rand()
        if u < 0.8:
            zipf = np.array([1.0 / (r + 1) ** 1.1 for r in range(4)])
            zipf = zipf / zipf.sum()
            srcs.append(common[int(wl_rng.choice(4, p=zipf))])
        else:
            srcs.append(term_prompt(wl_rng, int(wl_rng.choice(
                [2, 3, 5, S], p=[.4, .25, .15, .2]))))
    srcs = np.stack(srcs)
    ref, = exe.run(inc_m, feed={"src_ids": srcs},
                   fetch_list=[inc_buf], scope=scope)
    want = apply_eos_sentinel(np.asarray(ref), end_id)
    total_tokens = int(count_generated_tokens(want, end_id).sum())

    def fork_scope():
        fork = Scope()
        for name in list(scope._vars):
            val = scope._get(name)
            fork._set(name, np.asarray(val)
                      if hasattr(val, "shape") else val)
        return fork

    def run_one(bundle, devices, prompts, expect):
        srv = PagedContinuousGenerationServer(
            bundle, executor=exe, scope=fork_scope(),
            steps_per_tick=8, mesh_devices=devices)
        try:
            t0 = time.perf_counter()
            replies = [srv.submit(s) for s in prompts]
            outs = [rep.result(600.0) for rep in replies]
            wall = time.perf_counter() - t0
            st = srv.stats()
        finally:
            srv.close()
        assert all(np.array_equal(np.asarray(o), expect[i])
                   for i, o in enumerate(outs)), \
            "token parity vs the whole-loop decode failed"
        return wall, st

    def single_leg():
        wall, st = run_one(b_single, None, srcs, want)
        return {"wall_s": wall, "tok_s": total_tokens / wall,
                "stats": st}

    def tp2_leg():
        wall, st = run_one(b_tp, jax.devices()[:2], srcs, want)
        return {"wall_s": wall, "tok_s": total_tokens / wall,
                "stats": st}

    def tp2dp_leg():
        # two tp=2 models on disjoint slices, traffic split: the
        # dp-lane aggregate (run concurrently via the servers' own
        # scheduler threads)
        import threading

        half = len(srcs) // 2
        walls, stats, errs = [None, None], [None, None], []

        def lane(i, bundle, devices, prompts, expect):
            try:
                walls[i], stats[i] = run_one(bundle, devices,
                                             prompts, expect)
            except BaseException as e:  # surfaced below
                errs.append(e)

        t0 = time.perf_counter()
        threads = [
            threading.Thread(target=lane, args=(
                0, b_tp, jax.devices()[:2], srcs[:half],
                want[:half])),
            threading.Thread(target=lane, args=(
                1, b_tp2, jax.devices()[2:4], srcs[half:],
                want[half:]))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        if errs:
            raise errs[0]
        # the leg's headline is the TWO-lane aggregate, so the pool
        # accounting must cover both lanes (lane 0's stats alone
        # described half the traffic); telemetry keeps lane 0's full
        # stats dict as the per-lane sample
        pools = [st["block_pool"] for st in stats]
        return {"wall_s": wall, "tok_s": total_tokens / wall,
                "stats": stats[0],
                "pool_sum": {k: sum(p[k] for p in pools)
                             for k in ("prefix_hits", "prefix_misses",
                                       "cow_copies")}}

    # per-device KV bytes: the placed pool's addressable shard is
    # EXACTLY total/tp (heads divide evenly)
    probe = PagedContinuousGenerationServer(
        b_tp, executor=exe, scope=fork_scope(),
        mesh_devices=jax.devices()[:2], start=False)
    pool = probe.scope._get("@tp/self_k0@POOL")
    per_dev = int(pool.addressable_shards[0].data.nbytes)
    full = int(np.prod(pool.shape)) * pool.dtype.itemsize
    probe.close()
    kv_ratio = full / per_dev
    assert kv_ratio >= 1.8, (full, per_dev)

    single_leg()
    tp2_leg()
    tp2dp_leg()  # warm (all compiles land here)
    compiles_before = exe.compile_count
    rounds = _harness.interleave_rounds(
        [("single", single_leg), ("tp2", tp2_leg),
         ("tp2dp", tp2dp_leg)], rounds=3)
    steady_compiles = exe.compile_count - compiles_before
    assert steady_compiles == 0, steady_compiles
    sbest = _harness.best_leg(rounds, "single")
    tbest = _harness.best_leg(rounds, "tp2")
    dbest = _harness.best_leg(rounds, "tp2dp")
    dp_over_tp2 = _harness.paired_ratio_max(rounds, "tp2dp", "tp2")
    tp2_over_single = _harness.paired_ratio_max(rounds, "tp2",
                                                "single")
    # BOTH throughput ratios are recorded UNASSERTED beyond sanity
    # floors: all 8 virtual devices share 2 throttled cores, so the
    # dp lanes compete for the same cycles (paired dp/tp2 measured
    # 0.76x-1.52x across runs — unresolvable, the PERF.md r12
    # discipline) and tp trades latency for per-device bytes. The
    # HARD assertions of this bench are the layout/compile
    # invariants: per-device KV exactly 1/tp, parity per leg, zero
    # steady-state compiles. On disjoint REAL chips dp lanes scale
    # by construction (PERF.md "Sharded serving").
    assert dp_over_tp2 >= 0.5, (
        f"dp aggregate collapsed to {dp_over_tp2:.2f}x one tp model")
    bp = dbest["pool_sum"]  # both dp lanes' pools (the aggregate leg)
    result = {
        "metric": "sharded_dp_aggregate_tokens_per_sec",
        "value": round(dbest["tok_s"], 1),
        "unit": "tokens/sec",
        "single_tok_s": round(sbest["tok_s"], 1),
        "tp2_tok_s": round(tbest["tok_s"], 1),
        "tp2dp_tok_s": round(dbest["tok_s"], 1),
        "dp_aggregate_over_tp2": round(dp_over_tp2, 2),
        "dp_aggregate_note": (
            "unasserted beyond a 0.5 sanity floor: the dp lanes "
            "share this host's 2 cores, paired ratios swing "
            "0.76-1.52x across runs (unresolvable); on disjoint "
            "real chips lanes scale by construction"),
        "tp2_over_single": round(tp2_over_single, 2),
        "tp2_over_single_note": (
            "unasserted: on this 2-core host every per-tick psum is "
            "a same-core copy+sync, so tp trades latency for the "
            "per-device KV bytes; the real-chip tok/s arithmetic is "
            "argued in PERF.md 'Sharded serving'"),
        "per_device_kv": {"full_pool_bytes": full,
                          "per_device_bytes": per_dev,
                          "ratio": round(kv_ratio, 2)},
        "token_parity_vs_whole_loop": True,  # asserted per leg
        "steady_state_compiles": int(steady_compiles),
        "triple_tok_s": [[round(r["single"]["tok_s"], 1),
                          round(r["tp2"]["tok_s"], 1),
                          round(r["tp2dp"]["tok_s"], 1)]
                         for r in rounds],
        "mesh": {"devices": 8, "tp": 2, "tp_models": 2,
                 "slices": [[0, 1], [2, 3]]},
        "cache": {"block_size": cache.block_size,
                  "n_blocks": cache.n_blocks,
                  "n_prompt_entries": cache.n_prompt_entries},
        "workload": "80% shared system prompts (Zipf over 4), "
                    "20% unique; terminator-copy mixed lengths",
        "n_requests": n_requests,
        "total_tokens": total_tokens,
        "model": f"transformer d{D} L{L} S{S} maxT{maxT}",
        "best_of": 3,
        "prefix_hit_rate": round(
            bp["prefix_hits"] / max(1, bp["prefix_hits"]
                                    + bp["prefix_misses"]
                                    + bp["cow_copies"]), 3),
    }
    return _write_bench_self("BENCH_SELF_r17.json", result,
                             stats_json_dict=dbest["stats"])


def bench_speculative(n_requests=96, spec_k=3):
    """Speculative draft-and-verify decoding vs the plain decode
    burst and the whole-loop server (models/decode_engine.py
    DraftConfig; BENCH_SELF_r14.json).

    Workload: the terminator-copy task where BOTH the d128/L2 target
    and the d32/L1 draft learn near-deterministic copying, so the
    draft's k proposals mostly match the target's greedy stream —
    the high-acceptance regime speculative decoding amortizes: per
    device tick, k tiny draft steps + ONE batched (k+1)-query target
    step emit up to k+1 tokens where the plain burst's tick emits 1.
    Greedy acceptance is TOKEN-EXACT vs the whole-loop decode, so
    every measured leg asserts byte parity (a fast leg with wrong
    tokens would be meaningless).

    Three INTERLEAVED legs per triple (r10/r13 throttled-host
    discipline), best PAIRED ratios asserted: speculative > 1x the
    plain burst's tok/s, zero steady-state compiles. Draft-vs-target
    step accounting (the real cost model: CPU time is ~linear in
    FLOPs, so the win is k*draft_cost + verify_cost vs
    tokens-per-tick — PERF.md "Speculative decoding" has the
    arithmetic for this host and the real chip). CPU-PINNED like
    bench_generation; fail-fast exit 3 inherited from main()."""
    import jax

    jax.config.update("jax_platforms", "cpu")

    import paddle_tpu as fluid
    from paddle_tpu import unique_name
    from paddle_tpu.core.scope import Scope
    from paddle_tpu.inference import (ContinuousGenerationServer,
                                      GenerationServer,
                                      apply_eos_sentinel,
                                      count_generated_tokens)
    from paddle_tpu.models import transformer as T
    from paddle_tpu.models.decode_engine import DraftConfig

    V, D, L, S, maxT = 16, 128, 2, 12, 64
    DD, DL = 64, 1   # draft dims: ~8x fewer decode FLOPs/step — a
    #                  d32 draft measured acceptance 0.69/accepted
    #                  len 2.81, UNDER the 2.54 tick-cost threshold;
    #                  d64 hits 0.89/3.42 and clears it
    n_slots = 8
    end_id = 1
    rng = np.random.RandomState(7)

    # FIXED prompt pool (the ISSUE's "repeated-suffix mix"): 8
    # memorizable sequences with varied planted EOS. Random-content
    # terminator-copy leaves both models' CONTENT tokens noisy
    # (measured: loss plateaus ~1.7 and draft/target agreement sits
    # at chance), which starves acceptance; a small pool is
    # memorized by BOTH capacities, so the draft accepts — the
    # production analogue is templated / repeated-system-prompt
    # traffic, the same shape bench_paged's prefix cache exploits.
    # EVERY row terminates within the trained S-token horizon: a
    # no-EOS row would decode ~maxT-S positions PAST anything either
    # model saw in training, where their extrapolations disagree
    # chaotically — measured mean accepted length collapsed to ~1.75
    # (< the 2.54 spec-vs-plain tick-cost ratio on this host) with
    # 25% no-EOS traffic, vs ~3+ when generations stay on-horizon.
    pool_rng = np.random.RandomState(5)
    pool = []
    for p in (4, 5, 6, 7, 8, 9, 10, 11):
        row = pool_rng.randint(3, V, (S,)).astype(np.int64)
        row[p:] = end_id
        pool.append(row)
    pool = np.stack(pool)

    def term_prompts(n, r):
        return pool[r.randint(0, len(pool), n)]

    # train target AND draft on the same stream into ONE scope
    # (disjoint names via the draft_ prefix; ONE unique_name guard so
    # their auto-named optimizer moments cannot collide). Target per
    # the CLAUDE.md size ladder (d128/L2 lr.002x600); the draft gets
    # an lr DECAY (.01 x300 then .003 x300, two programs sharing the
    # scope with separate moments — both startups run BEFORE any
    # training): acceptance is the whole game, and the flat-lr draft
    # plateaued ~0.1 loss above the target, costing ~0.2 of mean
    # accepted length.
    scope = Scope()
    with unique_name.guard():
        t_main, t_st, t_loss = T.build_program(
            seq_len=S, d_model=D, n_heads=2, n_layers=L, d_inner=128,
            vocab=V, with_optimizer=False, dropout_rate=0.0)
        with fluid.program_guard(t_main, t_st):
            fluid.optimizer.Adam(learning_rate=0.002).minimize(
                t_loss)
        d_main, d_st, d_loss = T.build_program(
            seq_len=S, d_model=DD, n_heads=2, n_layers=DL,
            d_inner=128, vocab=V, with_optimizer=False,
            dropout_rate=0.0, name_prefix="draft_")
        with fluid.program_guard(d_main, d_st):
            fluid.optimizer.Adam(learning_rate=0.01).minimize(d_loss)
        d_main2, d_st2, d_loss2 = T.build_program(
            seq_len=S, d_model=DD, n_heads=2, n_layers=DL,
            d_inner=128, vocab=V, with_optimizer=False,
            dropout_rate=0.0, name_prefix="draft_")
        with fluid.program_guard(d_main2, d_st2):
            fluid.optimizer.Adam(learning_rate=0.003).minimize(
                d_loss2)
    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(t_st, scope=scope)
    exe.run(d_st, scope=scope)
    exe.run(d_st2, scope=scope)  # fine-tune moments (re-inits draft
    #                              params — runs BEFORE training)
    for i in range(600):
        src = term_prompts(8, rng)
        tgt_in = np.concatenate(
            [np.full((8, 1), 2, np.int64), src[:, :-1]], 1)
        feed = {"src_ids": src, "tgt_ids": tgt_in, "label": src}
        exe.run(t_main, feed=feed, fetch_list=[t_loss], scope=scope)
        if i < 300:
            exe.run(d_main, feed=feed, fetch_list=[d_loss],
                    scope=scope)
        else:
            exe.run(d_main2, feed=feed, fetch_list=[d_loss2],
                    scope=scope)

    kwargs = dict(seq_len=S, max_out_len=maxT, d_model=D, n_heads=2,
                  n_layers=L, d_inner=128, vocab=V, start_id=2,
                  end_id=end_id)
    with unique_name.guard():
        inc_m, _, _, inc_buf = T.build_incremental_decode_program(
            **kwargs)
    with unique_name.guard():
        plain = T.build_decode_step_program(n_slots=n_slots, **kwargs)
    with unique_name.guard():
        spec = T.build_decode_step_program(
            n_slots=n_slots, state_prefix="@spec/",
            draft=DraftConfig(d_model=DD, n_heads=2, n_layers=DL,
                              d_inner=128, k=spec_k), **kwargs)

    srcs = term_prompts(n_requests, np.random.RandomState(31))
    ref, = exe.run(inc_m, feed={"src_ids": srcs},
                   fetch_list=[inc_buf], scope=scope)
    want = apply_eos_sentinel(np.asarray(ref), end_id)
    lens = count_generated_tokens(want, end_id)
    total_tokens = int(lens.sum())

    def run_leg(make_server):
        srv = make_server()
        try:
            t0 = time.perf_counter()
            replies = [srv.submit(s) for s in srcs]
            outs = [rep.result(600.0) for rep in replies]
            wall = time.perf_counter() - t0
            st = srv.stats()
        finally:
            srv.close()
        assert all(np.array_equal(np.asarray(o), want[i])
                   for i, o in enumerate(outs)), \
            "token parity vs the whole-loop decode failed"
        return {"wall_s": wall, "tok_s": total_tokens / wall,
                "stats": st}

    def whole_loop_leg():
        srv = GenerationServer(
            inc_m, inc_buf, executor=exe, scope=scope, end_id=end_id,
            max_batch_size=n_slots, max_wait_ms=2.0)
        try:
            t0 = time.perf_counter()
            replies = [srv.submit({"src_ids": s[None]}) for s in srcs]
            outs = [apply_eos_sentinel(
                np.asarray(rep.result(600.0)[0]), end_id)[0]
                for rep in replies]
            wall = time.perf_counter() - t0
            st = srv.stats()
        finally:
            srv.close()
        assert all(np.array_equal(o, want[i])
                   for i, o in enumerate(outs)), \
            "whole-loop leg parity failed"
        return {"wall_s": wall, "tok_s": total_tokens / wall,
                "stats": st}

    def plain_leg():
        return run_leg(lambda: ContinuousGenerationServer(
            plain, executor=exe, scope=scope, steps_per_tick=8))

    def spec_leg():
        return run_leg(lambda: ContinuousGenerationServer(
            spec, executor=exe, scope=scope, steps_per_tick=8))

    whole_loop_leg()  # warm all three serve sets (all compiles here)
    plain_leg()
    spec_leg()
    compiles_before = exe.compile_count
    rounds = _harness.interleave_rounds(
        [("whole", whole_loop_leg), ("plain", plain_leg),
         ("spec", spec_leg)], rounds=3)
    steady_compiles = exe.compile_count - compiles_before
    assert steady_compiles == 0, (
        f"steady-state legs compiled {steady_compiles}")
    wbest = _harness.best_leg(rounds, "whole")
    pbest = _harness.best_leg(rounds, "plain")
    sbest = _harness.best_leg(rounds, "spec")
    # asserted ratios are the best PAIRED ones (adjacent legs share
    # this host's CPU-throttle windows — the r10 method,
    # harness.paired_ratio_max)
    speedup_vs_plain = _harness.paired_ratio_max(rounds, "spec",
                                                 "plain")
    speedup_vs_whole = _harness.paired_ratio_max(rounds, "spec",
                                                 "whole")
    triple_toks = [(round(r["whole"]["tok_s"]),
                    round(r["plain"]["tok_s"]),
                    round(r["spec"]["tok_s"])) for r in rounds]
    sp = sbest["stats"]["speculative"]
    assert speedup_vs_plain > 1.0, (
        f"speculative tok/s only {speedup_vs_plain:.2f}x the plain "
        f"decode burst on the high-acceptance workload (paired "
        f"triples: {triple_toks}; acceptance_rate="
        f"{sp['acceptance_rate']}, mean_accepted_len="
        f"{sp['mean_accepted_len']} — PERF.md 'Speculative "
        f"decoding' has the a > c_spec/c_1 threshold arithmetic)")
    result = {
        "metric": "speculative_tokens_per_sec_terminator_copy",
        "value": round(sbest["tok_s"], 1),
        "unit": "tokens/sec",
        "whole_loop_tok_s": round(wbest["tok_s"], 1),
        "plain_burst_tok_s": round(pbest["tok_s"], 1),
        "speculative_tok_s": round(sbest["tok_s"], 1),
        "speedup_vs_plain_burst": round(speedup_vs_plain, 2),
        "speedup_vs_whole_loop": round(speedup_vs_whole, 2),
        "triple_tok_s": [[round(r["whole"]["tok_s"], 1),
                          round(r["plain"]["tok_s"], 1),
                          round(r["spec"]["tok_s"], 1)]
                         for r in rounds],
        "token_parity_vs_whole_loop": True,  # asserted per leg
        "steady_state_compiles": int(steady_compiles),
        "spec": {
            "k": spec_k,
            "draft_model": f"d{DD} L{DL}",
            "target_model": f"d{D} L{L}",
            "acceptance_rate": sp["acceptance_rate"],
            "mean_accepted_len": sp["mean_accepted_len"],
            "proposed": sp["proposed"],
            "accepted": sp["accepted"],
            "emitted": sp["emitted"],
            "draft_steps": sp["draft_steps"],
            "target_steps": sp["target_steps"],
            "tokens_per_target_step": (
                round(sp["emitted"] / sp["target_steps"], 2)
                if sp["target_steps"] else None),
        },
        "n_requests": n_requests,
        "total_tokens": total_tokens,
        "len_histogram": {int(k): int(v) for k, v in
                          zip(*np.unique(lens, return_counts=True))},
        "workload": "terminator-copy over an 8-prompt pool "
                    "(repeated-suffix mix; high draft acceptance)",
        "model": (f"transformer d{D} L{L} S{S} maxT{maxT} "
                  f"slots{n_slots}, draft d{DD} L{DL} k{spec_k}"),
        "best_of": 3,
    }
    return _write_bench_self("BENCH_SELF_r14.json", result,
                             stats_json_dict=sbest["stats"])


def bench_speculative_adaptive(n_easy=48, n_hard=48):
    """Adaptive speculation (r19): distilled draft + per-lane
    acceptance controller + model-free n-gram lane
    (BENCH_SELF_r19.json; inference/spec_controller.py,
    models/distill.py, DraftConfig k_options).

    Narrative measured end to end: task training alone leaves the
    d128/L2-target x d64/L1-draft pair at LOW serve acceptance (the
    r14 recipe's outcome is training-luck bistable on this tiny
    memorization task — at current head it lands near chance), so
    (1) `distill_draft` trains the draft on the TARGET's own greedy
    pool streams + softened logits — acceptance is manufactured, not
    hoped for; (2) the `SpecController` reads per-lane device
    acceptance counters each dispatch and re-buckets lanes across
    the PRE-BUILT k in {0,3,4} serve variants — it holds a positive
    rung on easy (pool) traffic and parks at the k=0 plain burst
    (with periodic re-probes) on off-horizon traffic where
    acceptance collapses; (3) the n-gram lane drafts from each
    lane's own emitted suffix (zero draft FLOPs) through the same
    verify path.

    Legs (interleaved best-of-3, r10/r13 throttled-host discipline;
    BYTE PARITY vs the whole-loop decode asserted inside every leg):
    fixed-k3 vs adaptive on PHASED MIXED traffic (easy pool wave,
    then hard off-horizon wave), fixed-k3 vs pinned-k0 vs adaptive
    on hard-only traffic (the degradation claim), and the n-gram
    lane on pool traffic. Asserted: adaptive > fixed-k3 on mixed
    tok/s (best paired) AND on spec-window tokens/target-step;
    adaptive-hard > fixed-k3-hard (paired) and within 0.6x of the
    pinned plain burst; distilled acceptance lifts > +0.15 absolute;
    ZERO steady-state compiles across all legs (the executable bill
    is fixed at build — re-bucketing is pure program selection).
    Honest accounting caveat: the k=0 rung deliberately bumps NO
    spec counters, so adaptive per-leg acceptance/emitted cover only
    its spec-rung dispatches (PERF.md "Adaptive speculation")."""
    import jax

    jax.config.update("jax_platforms", "cpu")

    import paddle_tpu as fluid
    from paddle_tpu import unique_name
    from paddle_tpu.core.scope import Scope
    from paddle_tpu.inference import (ContinuousGenerationServer,
                                      SpecController,
                                      apply_eos_sentinel,
                                      count_generated_tokens)
    from paddle_tpu.models import transformer as T
    from paddle_tpu.models.distill import distill_draft
    from paddle_tpu.models.decode_engine import DraftConfig

    V, D, L, S, maxT = 16, 128, 2, 12, 64
    DD, DL = 64, 1
    n_slots = 8
    end_id = 1
    rng = np.random.RandomState(7)

    # the r14 8-prompt repeated-suffix pool (easy/templated traffic)
    pool_rng = np.random.RandomState(5)
    pool = []
    for p in (4, 5, 6, 7, 8, 9, 10, 11):
        row = pool_rng.randint(3, V, (S,)).astype(np.int64)
        row[p:] = end_id
        pool.append(row)
    pool = np.stack(pool)

    def term_prompts(n, r):
        return pool[r.randint(0, len(pool), n)]

    def hard_prompts(n, r):
        # off-horizon: random content with NO planted EOS — the
        # generation runs past anything either model trained on, so
        # draft/target extrapolations disagree and acceptance
        # collapses (PERF.md r14 "dead end (2)")
        return r.randint(3, V, (n, S)).astype(np.int64)

    # same training recipe as bench_speculative (d128/L2 lr.002x600
    # target; d64/L1 draft with the .01x300/.003x300 lr decay)
    scope = Scope()
    with unique_name.guard():
        t_main, t_st, t_loss = T.build_program(
            seq_len=S, d_model=D, n_heads=2, n_layers=L, d_inner=128,
            vocab=V, with_optimizer=False, dropout_rate=0.0)
        with fluid.program_guard(t_main, t_st):
            fluid.optimizer.Adam(learning_rate=0.002).minimize(
                t_loss)
        d_main, d_st, d_loss = T.build_program(
            seq_len=S, d_model=DD, n_heads=2, n_layers=DL,
            d_inner=128, vocab=V, with_optimizer=False,
            dropout_rate=0.0, name_prefix="draft_")
        with fluid.program_guard(d_main, d_st):
            fluid.optimizer.Adam(learning_rate=0.01).minimize(d_loss)
        d_main2, d_st2, d_loss2 = T.build_program(
            seq_len=S, d_model=DD, n_heads=2, n_layers=DL,
            d_inner=128, vocab=V, with_optimizer=False,
            dropout_rate=0.0, name_prefix="draft_")
        with fluid.program_guard(d_main2, d_st2):
            fluid.optimizer.Adam(learning_rate=0.003).minimize(
                d_loss2)
    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(t_st, scope=scope)
    exe.run(d_st, scope=scope)
    exe.run(d_st2, scope=scope)
    for i in range(600):
        src = term_prompts(8, rng)
        tgt_in = np.concatenate(
            [np.full((8, 1), 2, np.int64), src[:, :-1]], 1)
        feed = {"src_ids": src, "tgt_ids": tgt_in, "label": src}
        exe.run(t_main, feed=feed, fetch_list=[t_loss], scope=scope)
        if i < 300:
            exe.run(d_main, feed=feed, fetch_list=[d_loss],
                    scope=scope)
        else:
            exe.run(d_main2, feed=feed, fetch_list=[d_loss2],
                    scope=scope)

    kwargs = dict(seq_len=S, max_out_len=maxT, d_model=D, n_heads=2,
                  n_layers=L, d_inner=128, vocab=V, start_id=2,
                  end_id=end_id)
    LADDER = (0, 3, 4)
    draft_cfg = DraftConfig(d_model=DD, n_heads=2, n_layers=DL,
                            d_inner=128, k=3, k_options=LADDER)
    with unique_name.guard():
        inc_m, _, _, inc_buf = T.build_incremental_decode_program(
            **kwargs)
    with unique_name.guard():
        adapt = T.build_decode_step_program(
            n_slots=n_slots, state_prefix="@ak/",
            admit_buckets=[n_slots], draft=draft_cfg, **kwargs)
    with unique_name.guard():
        ngram = T.build_decode_step_program(
            n_slots=n_slots, state_prefix="@an/",
            admit_buckets=[n_slots],
            draft=DraftConfig(k=2, kind="ngram", ngram=2,
                              k_options=(0, 2)), **kwargs)

    def oracle(srcs):
        ref, = exe.run(inc_m, feed={"src_ids": srcs},
                       fetch_list=[inc_buf], scope=scope)
        return apply_eos_sentinel(np.asarray(ref), end_id)

    easy = term_prompts(n_easy, np.random.RandomState(31))
    hard = hard_prompts(n_hard, np.random.RandomState(33))
    w_easy, w_hard = oracle(easy), oracle(hard)
    easy_tokens = int(count_generated_tokens(w_easy, end_id).sum())
    hard_tokens = int(count_generated_tokens(w_hard, end_id).sum())

    class _Pinned:
        """Constant-k controller — the fixed-k baselines route
        through the SAME bundle and programs (zero extra compiles),
        isolating the adaptation policy as the only variable."""

        def __init__(self, k):
            self.k = k

        def choose(self):
            return self.k

        def observe(self, accepted, proposed, k):
            pass

        def reset_lane(self, lane):
            pass

        def stats(self):
            return {"pinned_k": self.k}

    def _auto():
        # draft_cost_ratio = the honest d64/L1-vs-d128/L2 per-step
        # FLOPs ratio (~1/8); the objective is expected tokens per
        # VERIFY step net of draft cost — the real-chip lever (on
        # CPU the (k+1)-query verify also scales with k, which the
        # wall-clock legs below price in). ewma=0.5: one observation
        # here is a WHOLE fused dispatch (~8 ticks x 8 lanes x k
        # proposals pooled), so the fast constant still averages
        # hundreds of proposals — at the library default 0.25 the
        # estimate needs ~5 dispatches to cross the park threshold
        # after a traffic shift, which is most of a wave at this
        # burst size (measured; the r10 lesson again: everything
        # must amortize against BIG dispatches).
        return SpecController(LADDER, default_k=3,
                              draft_cost_ratio=0.125, ewma=0.5,
                              probe_every=8)

    def run_leg(bundle, make_ctl, phases, tag):
        srv = ContinuousGenerationServer(
            bundle, executor=exe, scope=scope, steps_per_tick=8,
            spec_controller=make_ctl())
        try:
            t0 = time.perf_counter()
            for srcs, want in phases:
                replies = [srv.submit(s) for s in srcs]
                outs = [rep.result(600.0) for rep in replies]
                assert all(
                    np.array_equal(np.asarray(o), want[i])
                    for i, o in enumerate(outs)), \
                    f"{tag}: token parity vs whole-loop decode failed"
            wall = time.perf_counter() - t0
            st = srv.stats()
        finally:
            srv.close()
        toks = sum(int(count_generated_tokens(w, end_id).sum())
                   for _, w in phases)
        sp = st["speculative"]
        tps = (round(sp["emitted"] / sp["target_steps"], 2)
               if sp.get("target_steps") else None)
        return {"wall_s": wall, "tok_s": toks / wall, "stats": st,
                "acceptance": sp["acceptance_rate"],
                "mean_accepted_len": sp["mean_accepted_len"],
                "tokens_per_target_step": tps,
                "per_k_dispatches": {
                    k: v["dispatches"]
                    for k, v in (sp.get("per_k") or {}).items()}}

    mixed = [(easy, w_easy), (hard, w_hard)]
    legs = {
        "fixed3_mixed": lambda: run_leg(
            adapt, lambda: _Pinned(3), mixed, "fixed3_mixed"),
        "adaptive_mixed": lambda: run_leg(
            adapt, _auto, mixed, "adaptive_mixed"),
        "fixed3_hard": lambda: run_leg(
            adapt, lambda: _Pinned(3), [(hard, w_hard)],
            "fixed3_hard"),
        "plain_hard": lambda: run_leg(
            adapt, lambda: _Pinned(0), [(hard, w_hard)],
            "plain_hard"),
        "adaptive_hard": lambda: run_leg(
            adapt, _auto, [(hard, w_hard)], "adaptive_hard"),
        "ngram_easy": lambda: run_leg(
            ngram, lambda: _Pinned(2), [(easy, w_easy)],
            "ngram_easy"),
    }

    # warm every serve rung of both bundles (all compiles land here)
    for k in (3, 4, 0):
        run_leg(adapt, lambda k=k: _Pinned(k),
                [(easy[:n_slots], w_easy[:n_slots])], f"warm_k{k}")
    for k in (2, 0):
        run_leg(ngram, lambda k=k: _Pinned(k),
                [(easy[:n_slots], w_easy[:n_slots])],
                f"warm_ng{k}")

    # BEFORE: task-training-only acceptance at the default rung
    pre = run_leg(adapt, lambda: _Pinned(3), [(easy, w_easy)],
                  "pre_distill")
    acc_before = pre["acceptance"]

    # the tentpole: distill the draft on the TARGET's own greedy
    # pool streams (draft params update in place in the live scope;
    # target params untouched, so every oracle/want above stays
    # valid — asserted again by per-leg parity below)
    t0 = time.perf_counter()
    dres = distill_draft(
        exe, scope, draft_cfg, decode_fn=oracle,
        prompts_fn=lambda r, n: term_prompts(n, r),
        rounds=12, batch=8, inner_steps=4, learning_rate=0.005,
        seed=3, **kwargs)
    distill_wall = time.perf_counter() - t0

    post = run_leg(adapt, lambda: _Pinned(3), [(easy, w_easy)],
                   "post_distill")
    acc_after = post["acceptance"]
    assert acc_after > acc_before + 0.15, (
        f"distillation lifted pool acceptance only {acc_before} -> "
        f"{acc_after} (teacher-forced agree trajectory: "
        f"{dres['agree']})")

    compiles_before = exe.compile_count
    rounds = _harness.interleave_rounds(
        list(legs.items()), rounds=3)
    steady_compiles = exe.compile_count - compiles_before
    assert steady_compiles == 0, (
        f"steady-state legs compiled {steady_compiles} — the k "
        f"ladder must be fully pre-built")

    best = {name: _harness.best_leg(rounds, name) for name in legs}
    adaptive_vs_fixed = _harness.paired_ratio_max(
        rounds, "adaptive_mixed", "fixed3_mixed")
    # the max can ride a throttle window the OTHER leg fell into even
    # with interleaving; the min is the claim's floor — record both
    adaptive_vs_fixed_min = min(
        r["adaptive_mixed"]["tok_s"] / r["fixed3_mixed"]["tok_s"]
        for r in rounds)
    adaptive_vs_fixed_hard = _harness.paired_ratio_max(
        rounds, "adaptive_hard", "fixed3_hard")
    degradation = _harness.paired_ratio_max(
        rounds, "adaptive_hard", "plain_hard")
    pair_toks = [[round(r["fixed3_mixed"]["tok_s"], 1),
                  round(r["adaptive_mixed"]["tok_s"], 1)]
                 for r in rounds]
    assert adaptive_vs_fixed > 1.0, (
        f"adaptive tok/s only {adaptive_vs_fixed:.2f}x fixed-k3 on "
        f"the phased mixed traffic (paired [fixed, adaptive]: "
        f"{pair_toks})")
    ab, fb = best["adaptive_mixed"], best["fixed3_mixed"]
    assert ab["tokens_per_target_step"] > fb[
        "tokens_per_target_step"], (
        f"adaptive spec-window tokens/target-step "
        f"{ab['tokens_per_target_step']} did not beat fixed-k3's "
        f"{fb['tokens_per_target_step']}")
    assert adaptive_vs_fixed_hard > 1.0, (
        f"adaptive only {adaptive_vs_fixed_hard:.2f}x fixed-k3 on "
        f"off-horizon traffic — the controller failed to park")
    assert degradation > 0.6, (
        f"adaptive off-horizon throughput {degradation:.2f}x the "
        f"pinned k=0 plain burst — parking overhead too high")
    # the adaptive mixed leg must actually EXERCISE the ladder:
    # a positive rung during the pool wave, k=0 during the hard wave
    adisp = ab["per_k_dispatches"]
    assert adisp.get(0, 0) > 0 and (
        adisp.get(3, 0) + adisp.get(4, 0)) > 0, adisp
    ng = best["ngram_easy"]
    ng_sp = ng["stats"]["speculative"]
    assert ng_sp["draft_steps"] == 0 and ng_sp["proposed"] > 0, ng_sp

    result = {
        "metric": "adaptive_spec_tokens_per_sec_mixed",
        "value": round(ab["tok_s"], 1),
        "unit": "tokens/sec",
        "adaptive_mixed_tok_s": round(ab["tok_s"], 1),
        "fixed3_mixed_tok_s": round(fb["tok_s"], 1),
        "adaptive_vs_fixed3_mixed": round(adaptive_vs_fixed, 2),
        "adaptive_vs_fixed3_mixed_min": round(
            adaptive_vs_fixed_min, 2),
        "adaptive_vs_fixed3_hard": round(adaptive_vs_fixed_hard, 2),
        "adaptive_hard_vs_plain_burst": round(degradation, 2),
        "paired_mixed_tok_s": pair_toks,
        "tokens_per_target_step": {
            "fixed3_mixed": fb["tokens_per_target_step"],
            "adaptive_mixed_spec_window":
                ab["tokens_per_target_step"]},
        "adaptive_per_k_dispatches": adisp,
        "controller": {"k_options": list(LADDER), "default_k": 3,
                       "draft_cost_ratio": 0.125},
        "distillation": {
            "acceptance_before": acc_before,
            "acceptance_after": acc_after,
            "mean_accepted_len_before": pre["mean_accepted_len"],
            "mean_accepted_len_after": post["mean_accepted_len"],
            "teacher_forced_agree": [round(a, 3)
                                     for a in dres["agree"]],
            "rounds": 12, "inner_steps": 4, "batch": 8,
            "wall_s": round(distill_wall, 1)},
        "ngram": {
            "tok_s": round(ng["tok_s"], 1),
            "acceptance": ng_sp["acceptance_rate"],
            "mean_accepted_len": ng_sp["mean_accepted_len"],
            "draft_steps": ng_sp["draft_steps"],
            "proposed": ng_sp["proposed"]},
        "token_parity_vs_whole_loop": True,  # asserted per leg
        "steady_state_compiles": int(steady_compiles),
        "workload": {
            "easy": f"{n_easy} reqs / {easy_tokens} toks from the "
                    "8-prompt repeated-suffix pool",
            "hard": f"{n_hard} reqs / {hard_tokens} toks "
                    "off-horizon (random content, no planted EOS)"},
        "model": (f"target d{D} L{L}, draft d{DD} L{DL} distilled, "
                  f"k_options={list(LADDER)}, slots{n_slots}"),
        "best_of": 3,
    }
    return _write_bench_self("BENCH_SELF_r19.json", result,
                             stats_json_dict=ab["stats"])


def bench_multitenant(n_requests=900):
    """Restore-safe wrapper: the body flips FLAGS_observability
    across legs with hard asserts in between, and main() keeps going
    after a failed config — a tripped assert must not leave the flag
    at metrics/trace for every later bench in the process."""
    from paddle_tpu.flags import FLAGS, set_flags

    prev = FLAGS.observability
    try:
        return _bench_multitenant_body(n_requests=n_requests)
    finally:
        set_flags({"FLAGS_observability": prev})


def _bench_multitenant_body(n_requests=900):
    """Multi-tenant serving runtime (inference/runtime): ONE process
    serves the 3-model runtime zoo under mixed Zipf traffic from 3
    tenants through the ModelRegistry + SLO-aware Router, then hot-
    swaps the most popular model mid-traffic. Asserted invariants
    (the r11 acceptance criteria, not just reported): bounded
    executable count (<= N x (buckets + 1) in the SHARED LRU), ZERO
    steady-state compiles after warm, zero accepted-request loss
    across the swap, and (r12) a complete slow-request span tree from
    the observability layer. Writes BENCH_SELF_r12.json next to this
    file, including the off/metrics/trace interleaved A/B and the
    `telemetry` snapshot.

    CPU-PINNED by design (same reasoning as bench_coldstart): the
    scheduling/arbitration arithmetic is honestly CPU-measurable and
    the tunnel must never be held by a long bench. Best-of-3 traffic
    legs: this 2-core host swings single-pass walls ~3x (the
    interleave discipline is for A/B server comparisons; one system
    best-of-N is the PERF.md fallback)."""
    import jax

    jax.config.update("jax_platforms", "cpu")

    from paddle_tpu.inference.runtime import ServingRuntime, zoo

    max_batch = 16
    rt = ServingRuntime()
    models = []
    for prefix, in_dim, hidden, classes in zoo.DEFAULT_ZOO:
        server, _scope = zoo.make_fc_server(
            prefix, in_dim, hidden, classes, executor=rt.executor(),
            max_batch_size=max_batch, max_wait_ms=2.0)
        rt.load_model(prefix, server)
        models.append((prefix, in_dim, hidden, classes))
    n_models = len(models)
    ladder = len(rt.registry.get(models[0][0]).server.batch_buckets)

    def total_compiles():
        return sum(h.executor.compile_count
                   for h in rt.registry.aliases().values())

    compiles_after_warm = total_compiles()

    # tenants: a heavy free tier (70% of traffic), a mid tier (20%),
    # and a small paid tenant (10%, 2x weight, tight SLO) — the
    # noisy-neighbor mix the WDRR scheduler exists for
    rt.add_tenant("heavy", weight=1.0, max_queue=1 << 16)
    rt.add_tenant("mid", weight=1.0, max_queue=1 << 16)
    rt.add_tenant("small", weight=2.0, max_queue=1 << 16,
                  target_p99_ms=500.0)
    rng = np.random.RandomState(0)
    zipf = np.array([1.0 / (r + 1) ** 1.1 for r in range(n_models)])
    zipf /= zipf.sum()
    tenant_mix = rng.choice(["heavy", "mid", "small"],
                            size=n_requests, p=[0.7, 0.2, 0.1])
    model_mix = rng.choice(n_models, size=n_requests, p=zipf)
    schedule = []
    for k in range(n_requests):
        prefix, in_dim = models[model_mix[k]][:2]
        schedule.append(
            (str(tenant_mix[k]), prefix,
             {f"{prefix}_x": rng.randn(1, in_dim).astype(np.float32)}))

    def leg(repeat=1):
        t0 = time.perf_counter()
        replies = [rt.submit(t, m, f)
                   for _ in range(repeat)
                   for t, m, f in schedule]
        for rep in replies:
            rep.result(600.0)
        wall = time.perf_counter() - t0
        return repeat * n_requests / wall, rt.stats(reset=True)

    # observability-overhead A/B (the r12 acceptance gate): the SAME
    # traffic leg alternating FLAGS_observability off/metrics/trace,
    # interleaved best-of-3 per the PERF.md discipline (sequential
    # legs land in different throttle windows on this 2-core host and
    # report 2x-off ratios). The metrics level is pull-based
    # (weakref providers read at expose() time), so the expected
    # delta is noise-level; the interleave is what makes 3% resolvable.
    from paddle_tpu import observability as obs
    from paddle_tpu.flags import FLAGS, set_flags

    leg()  # discard: very first traffic leg is cold (thread pools,
    #        allocator)
    # headline: best-of-3 at the r11 leg length, observability off —
    # the value stays comparable across rounds
    set_flags({"FLAGS_observability": "off"})
    legs = [leg() for _ in range(3)]
    best_rps, best_st = max(legs, key=lambda x: x[0])

    def ab_pair(mode_a, mode_b, reps, repeat=4):
        """Paired-median A/B over FLAGS_observability modes
        (harness.paired_median_ab has the throttle-defense
        rationale); legs run the schedule ``repeat``x so each spans
        multiple throttle windows instead of landing inside one."""
        return _harness.paired_median_ab(
            lambda: leg(repeat=repeat),
            lambda mode: set_flags({"FLAGS_observability": mode}),
            mode_a, mode_b, reps)

    obs_ratio, metrics_ratios, mo_legs = ab_pair("metrics", "off", 6)
    trace_ratio, trace_ratios, to_legs = ab_pair("trace", "off", 4)
    ab_legs = {"off": mo_legs["off"] + to_legs["off"],
               "metrics": mo_legs["metrics"],
               "trace": to_legs["trace"]}

    # The A/B above records the acceptance protocol, but this host's
    # CPU-share throttle swings IDENTICAL adjacent legs up to 1.7x
    # (see the recorded pair ratios) — no end-to-end estimator tried
    # here (paired median, ABBA quads, best-of-20 interleaved, 15 s
    # legs) resolves 3% run-to-run. The budget is therefore checked
    # against a DIRECT measurement: time the exact per-request work
    # the metrics level adds (the flag gate, the request id, and the
    # coarse flight-recorder entry — everything else runs at off too)
    # and compare it to the measured per-request wall. This is
    # deterministic to a few percent where the macro ratio is not.
    from paddle_tpu.observability import flight as obs_flight
    from paddle_tpu.observability import tracing as obs_tracing
    from paddle_tpu.observability.metrics import metrics_on

    set_flags({"FLAGS_observability": "metrics"})
    scratch = obs_flight.FlightRecorder(max_recent=8)  # not the
    #   global ring: the telemetry snapshot must not count bench spins
    K = 50_000
    t0 = time.perf_counter()
    for _ in range(K):
        metrics_on()
        rid = obs_tracing.TRACER.next_request_id()
        scratch.record(
            {"request_id": rid, "status": "ok",
             "slo_violated": False, "tenant": "bench",
             "model": "tiny", "latency_ms": 12.3, "queue_ms": 1.2},
            incident=False)
    direct_us = (time.perf_counter() - t0) / K * 1e6
    mean_off_rps = (sum(r for r, _ in ab_legs["off"])
                    / len(ab_legs["off"]))
    wall_us = 1e6 / mean_off_rps  # conservative: per-request WALL,
    #   not the 2-core CPU budget (which is ~2x larger)
    overhead_frac = direct_us / wall_us
    # back to the headline level: the hot-swap phase below (swap_s,
    # post-swap compile window, zero-loss leg) must run at the SAME
    # observability level as the headline legs and the r11 record it
    # is compared against — not at the microbench's metrics level
    set_flags({"FLAGS_observability": "off"})

    # forensic demo (acceptance): the SLOWEST traced request's span
    # tree must be complete — router.queue -> server.queue ->
    # server.dispatch -> execute -> readback under the request root,
    # with cache-tier annotations — and the whole sink dumps to one
    # chrome trace (written under /tmp; the timeline summary is
    # recorded in the result JSON)
    with obs.TRACER._lock:
        traced = list(obs.TRACER.completed)
    slow = max(traced, key=lambda t: (t.t_end or t.t_start) - t.t_start)
    slow_tl = slow.timeline()
    slow_names = {s["name"] for s in slow_tl["spans"]}
    need = {"request", "router.queue", "server.queue",
            "server.dispatch", "execute", "readback"}
    assert need <= slow_names, (
        f"slow-request trace incomplete: missing "
        f"{sorted(need - slow_names)} in {sorted(slow_names)}")
    obs.dump_trace("/tmp/paddle_tpu_multitenant_trace_r12")
    steady_compiles = total_compiles() - compiles_after_warm
    assert steady_compiles == 0, (
        f"steady-state traffic compiled {steady_compiles} fresh "
        f"executable(s)")
    exe_count = best_st["cache"]["executable"]["size"]
    bound = n_models * (ladder + 1)
    assert exe_count <= bound, (
        f"executable count {exe_count} exceeds the "
        f"N x (buckets + 1) bound {bound}")

    # --- mid-traffic hot swap of the most popular model -------------
    popular, pop_dim, pop_hidden, pop_classes = models[0]
    import threading

    accepted, rejected, stop = [], [], [False]

    def traffic():
        # A submit exception must not kill the thread silently: the
        # zero-loss assertion below would then pass vacuously against
        # near-zero traffic. Rejections are collected and asserted
        # empty after the window.
        while not stop[0]:
            try:
                accepted.append(rt.submit(
                    "heavy", popular,
                    {f"{popular}_x": rng.randn(1, pop_dim).astype(
                        np.float32)}))
            except Exception as e:
                rejected.append(repr(e))
            time.sleep(0.0005)

    th = threading.Thread(target=traffic)
    th.start()
    time.sleep(0.3)
    new_server, _ = zoo.make_fc_server(
        popular, pop_dim, pop_hidden + 64, pop_classes,
        executor=rt.executor(), max_batch_size=max_batch,
        max_wait_ms=2.0)
    t0 = time.perf_counter()
    rt.load_model(popular, new_server)     # warm -> flip -> drain
    swap_s = time.perf_counter() - t0
    compiles_post_swap_warm = total_compiles()
    time.sleep(0.3)
    stop[0] = True
    th.join()
    lost = []
    for rep in accepted:
        try:
            rep.result(600.0)
        except Exception as e:
            lost.append(repr(e))
    swap_steady = total_compiles() - compiles_post_swap_warm
    assert swap_steady == 0, (
        f"post-swap steady state compiled {swap_steady}")
    assert not rejected, (
        f"hot swap rejected {len(rejected)} submission(s) at "
        f"admission: {rejected[:3]}")
    swap_st = rt.stats()
    zero_loss = (not lost
                 and swap_st["tenants"]["heavy"]["failed"] == 0)
    assert zero_loss, (
        f"hot swap lost {len(lost)} accepted request(s): {lost[:3]}")
    rt.close()

    result = {
        "metric": "multitenant_aggregate_requests_per_sec",
        "value": round(best_rps, 1),
        "unit": "requests/sec",
        "rps_legs": [round(r, 1) for r, _ in legs],
        "n_models": n_models,
        "models": [f"{p} fc {i}->{h}->{c}"
                   for p, i, h, c in models],
        "zipf_model_probs": [round(float(p), 3) for p in zipf],
        "tenant_mix": {"heavy": 0.7, "mid": 0.2, "small": 0.1},
        "per_tenant": {
            name: {
                "completed": ts["completed"],
                "p50_ms": ts["latency_ms"]["p50"],
                "p99_ms": ts["latency_ms"]["p99"],
                "queue_p99_ms": ts["queue_ms"]["p99"],
                "slo_violations": ts["slo_violations"],
                "target_p99_ms": ts["target_p99_ms"],
            } for name, ts in best_st["tenants"].items()},
        "p99_isolation_small_over_heavy": round(
            best_st["tenants"]["small"]["latency_ms"]["p99"]
            / best_st["tenants"]["heavy"]["latency_ms"]["p99"], 3),
        "executable_count": exe_count,
        "executable_bound": bound,
        "steady_state_compiles": int(steady_compiles),
        "hot_swap": {
            "swap_s": round(swap_s, 3),
            "accepted_during_leg": len(accepted),
            "completed": len(accepted) - len(lost),
            "zero_loss": bool(zero_loss),
            "post_swap_steady_compiles": int(swap_steady),
            "swaps": swap_st["registry"]["swaps"],
        },
        "cache": best_st["cache"]["executable"],
        "observability_overhead": {
            "ab_method": ("median of paired adjacent-leg ratios, "
                          "order alternated per pair; evidence only "
                          "— host throttle noise floor >> 3% (see "
                          "PERF.md 'Observability overhead')"),
            "metrics_over_off": round(obs_ratio, 4),
            "trace_over_off": round(trace_ratio, 4),
            "metrics_pair_ratios": [round(r, 4)
                                    for r in metrics_ratios],
            "trace_pair_ratios": [round(r, 4) for r in trace_ratios],
            "rps_legs": {m: [round(r, 1) for r, _ in ab_legs[m]]
                         for m in ("off", "metrics", "trace")},
            "budget": "metrics within 3% of off",
            "direct_overhead_us_per_request": round(direct_us, 3),
            "per_request_wall_us_at_off": round(wall_us, 1),
            "direct_overhead_fraction": round(overhead_frac, 5),
            "within_budget": bool(overhead_frac < 0.03),
        },
        "slow_request_trace": slow_tl,
        "trace_dump": "/tmp/paddle_tpu_multitenant_trace_r12.json",
        "n_requests": n_requests,
        "max_batch_size": max_batch,
        "best_of": 3,
    }
    return _write_bench_self("BENCH_SELF_r12.json", result,
                             stats_json_dict=best_st)


def bench_frontdoor():
    """Streaming front door under overload (ISSUE 20): per-token
    delivery, cancellation that frees device state, and
    deadline-aware shedding. Four leg families, interleaved
    best-of-3 (throttled-host discipline):

    * ``stream`` / ``whole`` — the SAME long prompts decoded
      sequentially on an idle server, delivered per burst
      (``submit(stream=True)``; TTFT = client-observed first-burst
      latency, ``StreamingReply.ttft_s``) vs as one whole-response
      future (there "TTFT" IS completion latency — the thing
      streaming exists to fix). Byte parity streamed-vs-whole and
      vs the incremental-decode oracle asserted per leg.
    * ``shed_Mx`` / ``noshed_Mx``, M in 1, 2, 4 — a cancel-heavy
      open-loop workload offered at M x measured idle capacity:
      every 3rd request is an ABANDONER (streamed at the server,
      cancelled right after its first burst — the teardown returns
      its lane/blocks/entry MID-decode), the rest carry a completion
      deadline (5 x the calibrated per-request service estimate —
      the SLO is stated in the controller's own units) through
      ``router.submit(deadline_ms=)``. Every request in a leg is a
      DISTINCT prompt: a repeated prompt re-admits through the
      radix-reuse tier and decodes nearly for free, which silently
      deflates the very service cost the overload is supposed to
      stress. The shed leg rejects
      unmeetable deadlines PRE-SLOT on the calibrated costmodel
      estimate (typed ``DeadlineUnmeetable``); the noshed leg is the
      same front door with the estimator uncalibrated (an
      uncalibrated estimator must not shed anyone), so it admits
      everything and burns prefills + decode bursts on requests that
      then expire at burst boundaries. Goodput = deadline-met
      completions / wall-to-all-resolved. The PAIRED shed/noshed
      goodput ratio must exceed 1 at >= 2x overload — under
      overload the box must spend capacity only on requests that
      can still meet their SLO.

    Every leg drains its pools to fully-free before closing
    (radix-aware: plain retirements ADOPT full blocks into the
    tree, so the gauge contract is prefix.in_use == 0 and
    radix-evicted == blocks held), and the measured rounds compile
    NOTHING (streaming adds no fetches and no programs).

    CPU-PINNED by design (the shed/cancel/stream mechanics are
    host-side; PERF.md 'Streaming & overload' covers the ~75 ms
    tunneled-readback quantum that makes per-BURST the right
    streaming granularity on the real chip). Writes
    BENCH_SELF_r20.json."""
    import jax

    jax.config.update("jax_platforms", "cpu")

    import paddle_tpu as fluid
    from paddle_tpu import observability as obs
    from paddle_tpu import unique_name
    from paddle_tpu.core.scope import Scope
    from paddle_tpu.flags import FLAGS, set_flags
    from paddle_tpu.inference import (PagedContinuousGenerationServer,
                                      apply_eos_sentinel,
                                      count_generated_tokens)
    from paddle_tpu.inference.runtime import (AdmissionError,
                                              DeadlineUnmeetable,
                                              ModelRegistry, Router)
    from paddle_tpu.models import transformer as T
    from paddle_tpu.models.decode_engine import CacheConfig

    # metrics level for the whole bench: the costmodel calibration
    # behind the shed estimate and the flight-recorder incident trail
    # are both front-door features under measure here
    prev_obs = FLAGS.observability
    set_flags({"FLAGS_observability": "metrics"})
    obs.reset()

    V, D, H, L, S, maxT = 16, 32, 2, 1, 10, 32
    end_id = 1
    BS, NB, E, n_slots = 8, 24, 6, 4
    rng = np.random.RandomState(7)

    def term_prompt(r, p):
        src = r.randint(3, V, (S,)).astype(np.int64)
        if p < S:
            src[p:] = end_id
        return src

    # terminator-copy training (the d32 lr/steps point of the
    # CLAUDE.md ladder): planted-EOS prompts give model-driven
    # mixed-length generations; the p=10 rows never plant one, so
    # their decodes run long — the abandoners' mid-decode window
    fluid.seed(0)
    scope = Scope()
    with unique_name.guard():
        main_p, startup, loss = T.build_program(
            seq_len=S, d_model=D, n_heads=H, n_layers=L, d_inner=64,
            vocab=V, with_optimizer=False, dropout_rate=0.0)
        with fluid.program_guard(main_p, startup):
            fluid.optimizer.Adam(learning_rate=0.02).minimize(loss)
    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(startup, scope=scope)
    for _ in range(150):
        src = np.stack([term_prompt(rng, int(rng.choice(
            [1, 2, 3, 4, 6, 8, 10, 10]))) for _ in range(8)])
        tgt_in = np.concatenate(
            [np.full((8, 1), 2, np.int64), src[:, :-1]], 1)
        exe.run(main_p, feed={"src_ids": src, "tgt_ids": tgt_in,
                              "label": src}, fetch_list=[loss],
                scope=scope)

    kwargs = dict(seq_len=S, max_out_len=maxT, d_model=D, n_heads=H,
                  n_layers=L, d_inner=64, vocab=V, start_id=2,
                  end_id=end_id)
    with unique_name.guard():
        inc_m, _, _, inc_buf = T.build_incremental_decode_program(
            **kwargs)
    # ONE admission bucket: every admission pads to n_slots (dustbin
    # lanes), so the warm round deterministically covers the whole
    # compile set — the zero-steady-compiles assert never rides on
    # which queue depths a throttle window happened to produce
    with unique_name.guard():
        paged = T.build_decode_step_program(
            n_slots=n_slots, state_prefix="@fdb/",
            admit_buckets=[n_slots],
            cache=CacheConfig(layout="paged", block_size=BS,
                              n_blocks=NB, n_prompt_entries=E),
            **kwargs)

    def oracle(srcs):
        ref, = exe.run(inc_m, feed={"src_ids": np.asarray(srcs)},
                       fetch_list=[inc_buf], scope=scope)
        return apply_eos_sentinel(np.asarray(ref), end_id=end_id)

    # pick prompts BY DECODE: the mixed pool is the SLO traffic, the
    # long generations (>= 16 tokens) feed the TTFT contrast and the
    # abandoners (a cancel must land mid-decode to return anything)
    mix_prompts = np.stack(
        [term_prompt(rng, p) for p in (1, 2, 3, 4, 6, 8, 10, 10)]
        + [rng.randint(3, V, (S,)).astype(np.int64)
           for _ in range(16)])
    mix_rows = oracle(mix_prompts)
    mix_lens = count_generated_tokens(mix_rows, end_id)
    long_idx = [i for i in range(len(mix_prompts))
                if mix_lens[i] >= 16][:6]
    assert long_idx, f"no long-decode prompt in the pool: {mix_lens}"
    long_prompts = mix_prompts[long_idx]
    long_rows = mix_rows[long_idx]

    def oracle_many(srcs, chunk=24):
        # oracle the per-leg prompt sets in fixed-size chunks during
        # SETUP (one compiled shape; padding rows decode + discard)
        srcs = np.asarray(srcs)
        pad = (-len(srcs)) % chunk
        if pad:
            srcs = np.concatenate(
                [srcs, np.repeat(srcs[-1:], pad, 0)])
        rows = np.concatenate([oracle(srcs[k:k + chunk])
                               for k in range(0, len(srcs), chunk)])
        return rows[:len(rows) - pad] if pad else rows

    def fresh_server(shed):
        srv = PagedContinuousGenerationServer(
            paged, executor=exe, scope=scope, steps_per_tick=2,
            drain_steps=2)
        if not shed:
            # the r20 contract verbatim: an uncalibrated estimator
            # must not shed anyone — disabling the estimator IS the
            # no-shed front door, not a parallel code path
            srv.expected_service_ms = lambda n_tokens=None: None
        return srv

    def assert_drained(srv, leg):
        # every reply resolved -> lanes freed at the resolving burst;
        # poll briefly for the scheduler's final bookkeeping, then
        # apply the radix-aware gauge contract: plain retirements
        # ADOPT full blocks into the tree, cancels adopt nothing
        for _ in range(400):
            with srv._cv:
                idle = all(l is None for l in srv._lanes) \
                    and not srv._queue
            if idle:
                break
            time.sleep(0.005)
        held = srv._blocks.in_use
        assert srv._prefix.in_use == 0, (
            f"{leg}: {srv._prefix.in_use} prompt-entry refs leaked")
        evicted = srv._radix.evict(NB)
        assert evicted == held, (
            f"{leg}: {held} blocks held but only {evicted} were "
            f"radix adoptions — a cancel/deadline teardown leaked")
        assert srv._blocks.free_count == NB, (
            f"{leg}: block pool not fully free after evict: "
            f"{srv._blocks.free_count}/{NB}")

    # --- TTFT legs: streamed vs whole-response delivery --------------
    def stream_leg():
        srv = fresh_server(shed=True)
        try:
            ttfts = []
            t0 = time.perf_counter()
            for k in range(len(long_prompts)):
                rep = srv.submit(long_prompts[k], stream=True)
                toks = np.array([t for _, t in rep], np.int64)
                row = np.asarray(rep.result(120.0))
                n = int(count_generated_tokens(row[None], end_id)[0])
                assert np.array_equal(toks, row[1:1 + n]), (
                    f"stream/whole parity broke on prompt {k}")
                assert np.array_equal(row, long_rows[k]), (
                    f"streamed decode diverged from oracle on {k}")
                ttfts.append(rep.ttft_s * 1e3)
            wall = time.perf_counter() - t0
            st = srv.stats()
            assert_drained(srv, "stream")
        finally:
            srv.close()
        return {"wall_s": wall, "ttft_ms": ttfts, "stats": st}

    def whole_leg():
        srv = fresh_server(shed=True)
        try:
            ttfts = []
            t0 = time.perf_counter()
            for k in range(len(long_prompts)):
                t1 = time.perf_counter()
                row = np.asarray(
                    srv.submit(long_prompts[k]).result(120.0))
                ttfts.append((time.perf_counter() - t1) * 1e3)
                assert np.array_equal(row, long_rows[k]), (
                    f"whole-response decode diverged from oracle on "
                    f"{k}")
            wall = time.perf_counter() - t0
            st = srv.stats()
            assert_drained(srv, "whole")
        finally:
            srv.close()
        return {"wall_s": wall, "ttft_ms": ttfts, "stats": st}

    # --- overload legs: shed vs noshed goodput -----------------------
    # capacity + idle latency + the per-mult DISTINCT prompt sets are
    # produced once after warmup (below); closed over via these
    load = {"n_base": 16, "window_s": 1.0, "deadline_ms": 100.0}
    traffic = {}  # mult -> (slo_prompts, slo_rows, abandoner_prompts)

    def overload_leg(mult, shed):
        srv = fresh_server(shed)
        if shed:
            assert srv.expected_service_ms() is not None, (
                "costmodel not calibrated — the shed leg would "
                "silently degrade to no-shed")
        registry = ModelRegistry()
        # max_inflight = lane count: a forwarded request is a lane
        # occupant, so "ahead of you" in the shed predicate counts
        # real contention, not a router-side buffer
        registry.load("gen", srv, warm=False, max_inflight=n_slots)
        router = Router(registry)
        router.add_tenant("fd", max_queue=4096)
        slo_p, slo_r, ab_p = traffic[mult]
        n_offered = int(round(mult * load["n_base"]))
        gap = load["window_s"] / n_offered
        ddl = load["deadline_ms"]
        pend, abandoners = [], []
        n_shed = n_qfull = n_cancelled = 0
        i_slo = i_ab = 0
        try:
            t0 = time.perf_counter()
            for i in range(n_offered):
                if i % 3 == 2:
                    # cancel-heavy slice: stream a (fresh) decode,
                    # the cancel fires below once its first burst
                    # lands
                    abandoners.append(srv.submit(
                        ab_p[i_ab], stream=True))
                    i_ab += 1
                else:
                    try:
                        pend.append((router.submit(
                            "fd", "gen", slo_p[i_slo],
                            deadline_ms=ddl), i_slo))
                    except DeadlineUnmeetable:
                        n_shed += 1
                    except AdmissionError:
                        n_qfull += 1
                    i_slo += 1
                live = []
                for rep in abandoners:
                    if rep.ttft_s is not None:
                        if rep.cancel():
                            n_cancelled += 1
                    else:
                        live.append(rep)
                abandoners = live
                # absolute schedule: offered rate stays mult x base
                # even when a submit/cancel pass runs long
                lag = t0 + (i + 1) * gap - time.perf_counter()
                if lag > 0:
                    time.sleep(lag)
            for rep in abandoners:  # still pre-first-burst: cancel
                if rep.cancel():    # queued (or just-live) teardown
                    n_cancelled += 1
                try:
                    rep.result(60.0)
                except Exception:
                    pass
            n_ok = n_deadline = 0
            for fut, pi in pend:
                try:
                    row = np.asarray(fut.result(120.0))
                except Exception:
                    n_deadline += 1
                    continue
                assert np.array_equal(row, slo_r[pi]), (
                    f"goodput leg decode diverged from oracle on "
                    f"prompt {pi}")
                n_ok += 1
            wall = time.perf_counter() - t0
            st = srv.stats()
            pst = srv.pool_stats()
            router.close()
            print(f"# frontdoor {'shed' if shed else 'noshed'}_"
                  f"{mult}x: ok={n_ok}/{n_offered} shed={n_shed} "
                  f"expired={n_deadline} cancelled={n_cancelled} "
                  f"wall={wall:.2f}s goodput={n_ok / wall:.1f} rps",
                  file=sys.stderr)
            assert_drained(srv, f"{'shed' if shed else 'noshed'}_"
                                f"{mult}x")
        finally:
            registry.close()
        return {"wall_s": wall, "goodput_rps": n_ok / wall,
                "ok": n_ok, "offered": n_offered, "shed": n_shed,
                "queue_full": n_qfull, "cancelled": n_cancelled,
                "expired": n_deadline, "stats": st, "pool": pst}

    legs = [("stream", stream_leg), ("whole", whole_leg)]
    for m in (1, 2, 4):
        legs.append((f"shed_{m}x",
                     lambda m=m: overload_leg(m, True)))
        legs.append((f"noshed_{m}x",
                     lambda m=m: overload_leg(m, False)))

    try:
        # warmup: one saturated burst compiles the serve tier and
        # calibrates the costmodel; repeating the pool hits the
        # radix admission tier (plain retirements adopted the
        # prefixes); idle capacity + latency scale the offered load
        warm = fresh_server(shed=True)
        try:
            for _pass in range(2):  # compiles: miss tier, then the
                #                     radix tier the adoptions feed
                reps = [warm.submit(p) for p in mix_prompts]
                rows = [np.asarray(r.result(120.0)) for r in reps]
            for k in range(len(mix_prompts)):
                assert np.array_equal(rows[k], mix_rows[k])
            # TRUE capacity: timed saturated passes over FRESH rows
            # once everything is warm — timing the compile passes
            # would understate capacity several-fold, and re-running
            # the warm pool would hit its radix adoptions and
            # OVERSTATE it just as badly
            cap_p = rng.randint(3, V, (48, S)).astype(np.int64)
            t0 = time.perf_counter()
            reps = [warm.submit(p) for p in cap_p]
            for r in reps:
                r.result(120.0)
            cap_wall = time.perf_counter() - t0
            lat = []
            for p in rng.randint(3, V, (8, S)).astype(np.int64):
                t1 = time.perf_counter()
                warm.submit(p).result(120.0)
                lat.append(time.perf_counter() - t1)
            svc = warm.expected_service_ms()
            assert svc is not None and svc > 0, (
                "costmodel did not calibrate from the warmup burst")
            assert_drained(warm, "warmup")
        finally:
            warm.close()
        cap_rps = len(cap_p) / cap_wall
        idle_lat_ms = 1e3 * float(np.median(lat))
        del rows, reps
        # the SLO in the CONTROLLER'S units: the shed predicate
        # compares svc_est x queue-depth against the deadline, so a
        # deadline of 5 x svc_est makes the threshold land at ~16
        # outstanding — reachable under real overload. (Stating it as
        # k x measured idle latency does not: the estimator omits
        # fixed host dispatch cost, runs ~2x low on this host, and
        # the implied depth drifts past what the router's cheap
        # expiry of queued requests lets the queue ever reach.)
        load["deadline_ms"] = 5.0 * svc
        # sustain the overload well past both the transient and the
        # deadline, or the no-shed leg drains its whole backlog
        # before the expiry regime ever sets in
        window_s = max(0.8, 15 * load["deadline_ms"] / 1e3)
        n_base = int(round(cap_rps * window_s))
        if n_base > 150:  # bound the 4x leg's request count
            n_base = 150
            window_s = n_base / cap_rps
        load["n_base"] = max(16, n_base)
        load["window_s"] = window_s
        print(f"# frontdoor: capacity {cap_rps:.1f} rps, idle "
              f"latency {idle_lat_ms:.1f} ms, svc_est {svc:.1f} ms, "
              f"deadline {load['deadline_ms']:.1f} ms, window "
              f"{window_s:.2f} s, n_base {load['n_base']}",
              file=sys.stderr)

        # per-mult DISTINCT traffic (fresh random rows decode long
        # with high probability — no planted EOS, no repeats, so no
        # radix-tier resumption inside a measured leg)
        for m in (1, 2, 4):
            n_off = int(round(m * load["n_base"]))
            n_ab = n_off // 3
            trng = np.random.RandomState(100 + m)
            slo_p = trng.randint(
                3, V, (n_off - n_ab, S)).astype(np.int64)
            ab_p = trng.randint(3, V, (n_ab, S)).astype(np.int64)
            traffic[m] = (slo_p, oracle_many(slo_p), ab_p)

        for _name, fn in legs:  # warm round: remaining compiles
            fn()                # (router path, radix admissions)
        compiles_before = exe.compile_count
        rounds = _harness.interleave_rounds(legs, rounds=3)
        steady_compiles = exe.compile_count - compiles_before
        assert steady_compiles == 0, (
            f"steady-state legs compiled {steady_compiles}")

        ratios = {m: _harness.paired_ratio_max(
            rounds, f"shed_{m}x", f"noshed_{m}x",
            value=lambda r: r["goodput_rps"]) for m in (1, 2, 4)}
        for m in (2, 4):
            assert ratios[m] > 1.0, (
                f"shedding did not beat no-shed at {m}x overload in "
                f"any paired round: {ratios[m]:.3f}")
        ttft_ratio = min(
            np.percentile(r["stream"]["ttft_ms"], 50)
            / np.percentile(r["whole"]["ttft_ms"], 50)
            for r in rounds)
        assert ttft_ratio < 1.0, (
            f"streamed first-burst TTFT p50 {ttft_ratio:.2f}x the "
            f"whole-response latency — streaming bought nothing")

        sbest = _harness.best_leg(rounds, "stream")
        wbest = _harness.best_leg(rounds, "whole")
        shed4 = _harness.best_leg(
            rounds, "shed_4x", key=lambda r: -r["goodput_rps"])
        noshed4 = _harness.best_leg(
            rounds, "noshed_4x", key=lambda r: -r["goodput_rps"])
        inc_rep = obs.incident_report()
        inc = inc_rep["incidents"]
        # the deque retains the LAST max_incidents timelines — by the
        # final leg's drain tail that window is deadline-heavy, so
        # carry the all-legs total beside the window histogram
        n_canc_inc = sum(1 for e in inc
                         if e.get("reason") == "cancelled")
        n_ddl_inc = sum(1 for e in inc
                        if e.get("reason") == "deadline")
        result = {
            "metric": "frontdoor_goodput_shed_over_noshed_4x",
            "value": round(ratios[4], 3),
            "unit": "x",
            "goodput_rps": {
                f"{m}x": {
                    "shed": round(_harness.best_leg(
                        rounds, f"shed_{m}x",
                        key=lambda r: -r["goodput_rps"])
                        ["goodput_rps"], 1),
                    "noshed": round(_harness.best_leg(
                        rounds, f"noshed_{m}x",
                        key=lambda r: -r["goodput_rps"])
                        ["goodput_rps"], 1),
                    "paired_ratio": round(ratios[m], 3),
                } for m in (1, 2, 4)},
            "ttft_ms": {
                "streamed_p50": round(float(np.percentile(
                    sbest["ttft_ms"], 50)), 2),
                "streamed_p99": round(float(np.percentile(
                    sbest["ttft_ms"], 99)), 2),
                "whole_p50": round(float(np.percentile(
                    wbest["ttft_ms"], 50)), 2),
                "whole_p99": round(float(np.percentile(
                    wbest["ttft_ms"], 99)), 2),
                "paired_p50_ratio": round(float(ttft_ratio), 3),
            },
            "token_parity_streamed_vs_whole": True,  # per leg
            "token_parity_vs_oracle": True,          # per leg
            "pools_drained_to_free_every_leg": True,  # asserted
            "steady_state_compiles": int(steady_compiles),
            "shed_4x": {k: shed4[k] for k in
                        ("ok", "offered", "shed", "cancelled",
                         "expired")},
            "noshed_4x": {k: noshed4[k] for k in
                          ("ok", "offered", "shed", "cancelled",
                           "expired")},
            "incidents": {"total": inc_rep["incidents_total"],
                          "retained": len(inc),
                          "retained_cancelled": n_canc_inc,
                          "retained_deadline": n_ddl_inc},
            "offered_load": {
                "capacity_rps": round(cap_rps, 1),
                "idle_latency_ms": round(idle_lat_ms, 2),
                "service_estimate_ms": round(svc, 2),
                "deadline_ms": round(load["deadline_ms"], 2),
                "n_base": load["n_base"],
                "window_s": round(load["window_s"], 3),
                "abandoner_fraction": 1 / 3},
            "workload": "cancel-heavy open loop at 1x/2x/4x offered "
                        "load, every prompt distinct; every 3rd "
                        "request streamed + cancelled after first "
                        "burst, rest carry deadline_ms = 5 x the "
                        "calibrated service estimate",
            "cache": {"block_size": BS, "n_blocks": NB,
                      "n_prompt_entries": E},
            "model": f"transformer d{D} L{L} S{S} maxT{maxT}, "
                     f"{n_slots} lanes, paged",
            "best_of": 3,
        }
        return _write_bench_self("BENCH_SELF_r20.json", result,
                                 stats_json_dict=shed4["stats"])
    finally:
        set_flags({"FLAGS_observability": prev_obs})


# opt-in configs (argv-selectable only; never in the driver's default
# window)
EXTRA_BENCHES = {"transformer_scan": bench_transformer_scan,
                 "moe_transformer": bench_moe_transformer,
                 "transformer_fused": bench_transformer_fused,
                 "transformer_scan_fused": bench_transformer_scan_fused,
                 "serving": bench_serving,
                 "coldstart": bench_coldstart,
                 "generation": bench_generation,
                 "paged": bench_paged,
                 "speculative": bench_speculative,
                 "speculative_adaptive": bench_speculative_adaptive,
                 "sharded": bench_sharded,
                 "multitenant": bench_multitenant,
                 "multiturn": bench_multiturn,
                 "prefill": bench_prefill,
                 "frontdoor": bench_frontdoor}


_probe_backend = _harness.probe_backend


def main():
    if len(sys.argv) > 1 and sys.argv[1] == "_coldstart_child":
        # internal: spawned by bench_coldstart; parent already probed
        # the backend
        _coldstart_child(sys.argv[2], sys.argv[3], int(sys.argv[4]))
        return
    if len(sys.argv) > 1 and sys.argv[1] == "_sharded_child":
        # internal: spawned by bench_sharded with the 8-virtual-device
        # XLA_FLAGS (device count is fixed at backend init, so the
        # parent cannot host the mesh itself)
        print(json.dumps(_bench_sharded_impl(int(sys.argv[2]))),
              flush=True)
        return
    if len(sys.argv) > 1 and sys.argv[1] == "trend":
        # perf-trend sentinel over the committed BENCH_SELF history
        # (benchmark/trend.py): pure file processing — no backend
        # probe, no TPU claim. Exit 2 on a regressed/stale store;
        # --write-trend refreshes intentionally.
        from benchmark import trend

        sys.exit(trend.main(sys.argv[2:]))
    device = _probe_backend()
    import jax

    only = sys.argv[1] if len(sys.argv) > 1 else None
    benches = list(BENCHES)
    if only in EXTRA_BENCHES:
        benches = [(only, EXTRA_BENCHES[only])]
    for name, fn in benches:
        if only and name != only:
            continue
        try:
            res = fn()
        except Exception as e:  # one config failing must not hide others
            print(f"# {name} FAILED: {type(e).__name__}: {e}",
                  file=sys.stderr)
            continue
        print(json.dumps(res), flush=True)
        if "loss0" in res:
            print(f"# {name}: device={device} loss {res['loss0']:.4f}"
                  f"->{res['loss1']:.4f} "
                  f"decreased={res['loss_decreased']}",
                  file=sys.stderr)
        else:
            print(f"# {name}: device={device} "
                  f"{res['value']} {res['unit']}", file=sys.stderr)


if __name__ == "__main__":
    main()
