"""PTA170 memory-planner validation: the static plan vs the XLA
compiler's own accounting, on the CPU backend (the r5-proven
schedule-level comparison surface — CLAUDE.md "memory_analysis works
on the CPU backend").

Three surfaces:

* **argument bytes EXACT** — `MemoryPlan.argument_bytes`
  (state + feeds + the threaded PRNG key) must equal
  ``compiled.memory_analysis().argument_size_in_bytes`` bit-for-bit
  on ≥ 5 zoo programs: the planner walks the same state_in contract
  as core/executor.py `_analyze_block_py`, so any drift between the
  two is a planner bug, not an estimate missing.
* **temp bytes within 25%** — the peak-liveness estimate with the
  elementwise aliasing model vs ``temp_size_in_bytes`` on the same
  programs (measured ratios at the time of writing: mnist-mlp 0.98,
  the three zoo-fc programs ~1.04, word2vec 1.22).
* **the ~1/tp KV shrink** — on the tp-sharded decoder fixture the
  per-device KV-pool bytes must be exactly total/tp (heads divide
  evenly), the ROADMAP's sharded-serving capacity claim as a number.

Plus the PTA170 checker itself: an opt-in budget turns an over-budget
plan into an ERROR diagnostic; in-budget and budget-less programs
stay silent.
"""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers, unique_name
from paddle_tpu.analysis import ERROR, absint, memplan, run_checks
from paddle_tpu.core import executor as E

BATCH = 4


def _auto_feeds(program, batch=BATCH):
    """(shape, dtype) per declared data var, -1 dims -> `batch`."""
    feeds = {}
    for v in program.global_block.vars.values():
        if v.is_data:
            shape = tuple(batch if (d is None or d < 0) else d
                          for d in v.shape)
            feeds[v.name] = (shape, v.dtype.value)
    return feeds


def _xla_memory(program, fetch_names, batch=BATCH):
    """Compile the program the way Executor.run does (same
    state_in/feed/rng signature the planner prices) and return the
    compiled executable's memory_analysis."""
    import jax

    block = program.global_block
    feed_shapes = _auto_feeds(program, batch)
    feed_names = list(feed_shapes)
    mutated, const, state_out = E._analyze_block_py(
        block, feed_names, fetch_names)
    step = E._build_step_fn(block, feed_names, mutated, const,
                            state_out, fetch_names)

    def arr_of(name):
        v = block._find_var_recursive(name)
        shape = tuple(batch if (d is None or d < 0) else d
                      for d in (v.shape or ()))
        return np.zeros(shape, v.dtype.value if v.dtype else "float32")

    mut = {n: arr_of(n) for n in mutated}
    cst = {n: arr_of(n) for n in const}
    feeds = {n: np.zeros(s, dt) for n, (s, dt) in feed_shapes.items()}
    rng = jax.random.PRNGKey(0)
    return jax.jit(step).lower(mut, cst, feeds, rng) \
        .compile().memory_analysis()


def _plan_of(program, fetch_names, batch=BATCH):
    facts = absint.analyze(program)
    return memplan.build_plan(facts, batch=batch,
                              fetch_names=tuple(fetch_names))


def _zoo_programs():
    """label -> (program, fetch_names): the ≥5-program validation
    set. Builders run under unique_name.guard so param names do not
    collide across pytest collection order."""
    out = {}
    with unique_name.guard():
        from paddle_tpu.models import mnist

        main, _startup, loss, _acc = mnist.build_program(
            use_conv=False)
        out["mnist-mlp"] = (main, [loss.name])
    from paddle_tpu.inference.runtime import zoo

    for prefix, in_dim, hidden, classes in zoo.DEFAULT_ZOO:
        m, _s, _f, fetches = zoo.build_fc_program(
            prefix, in_dim, hidden, classes)
        name = fetches[0] if isinstance(fetches[0], str) \
            else fetches[0].name
        out[f"zoo-{prefix}"] = (m, [name])
    with unique_name.guard():
        from paddle_tpu.models import word2vec

        wm, _ws, *rest = word2vec.build_program(
            dict_size=500, embed_size=16, hidden_size=32)
        out["word2vec"] = (wm, [rest[0].name])
    return out


@pytest.fixture(scope="module")
def zoo_results():
    """Plan + XLA accounting per validation program (one compile
    each, shared by the exact/ratio tests)."""
    results = {}
    for label, (prog, fetch) in _zoo_programs().items():
        results[label] = (_plan_of(prog, fetch),
                          _xla_memory(prog, fetch))
    return results


class TestPlannerVsXLA:
    def test_covers_at_least_five_programs(self, zoo_results):
        assert len(zoo_results) >= 5

    def test_argument_bytes_exact(self, zoo_results):
        for label, (plan, m) in zoo_results.items():
            assert plan.argument_bytes == m.argument_size_in_bytes, (
                label, plan.summary())

    def test_temp_bytes_within_25pct(self, zoo_results):
        for label, (plan, m) in zoo_results.items():
            xla = m.temp_size_in_bytes
            assert xla > 0, label
            ratio = plan.temp_bytes / xla
            assert 0.75 <= ratio <= 1.25, (label, plan.temp_bytes,
                                           xla, ratio)


class TestShardedKVShrink:
    def test_kv_pool_prices_at_one_over_tp(self):
        from paddle_tpu.models import sharded_decoder

        tp = 2
        fx = sharded_decoder.build_tp_sharded_decoder_step(tp=tp)
        facts = absint.analyze(fx.program)
        plan = facts.device_memory_plan(batch=1)
        assert fx.kv_names
        full = dev = 0
        for name in fx.kv_names:
            entry = plan.entry(name)
            assert entry is not None and entry.klass == "state", name
            full += entry.bytes
            dev += entry.device_bytes
        # heads divide evenly over tp, so the shrink is EXACTLY 1/tp
        assert dev * tp == full
        # and the planner's full-size accounting agrees with the
        # bundle's own KV bookkeeping (dense layout: the self_/cross_
        # state IS the kv_names set)
        assert full == fx.kv_state_bytes()

    def test_unsharded_state_unchanged_per_device(self):
        from paddle_tpu.models import sharded_decoder

        fx = sharded_decoder.build_tp_sharded_decoder_step()
        plan = absint.analyze(fx.program).device_memory_plan(batch=1)
        tok = plan.entry(fx.bundle.state["tok_buf"])
        assert tok is not None
        assert tok.device_bytes == tok.bytes


class TestPTA170Budget:
    def _program(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = layers.data("x", shape=[8, 64], dtype="float32",
                            append_batch_size=False)
            layers.fc(x, size=64)
        return main

    def test_over_budget_is_error(self):
        main = self._program()
        absint.set_device_memory_budget(main, 100)
        ds = [d for d in run_checks(main) if d.code == "PTA170"]
        assert ds and ds[0].severity == ERROR
        assert "exceeds the declared budget" in ds[0].message

    def test_within_budget_is_silent(self):
        main = self._program()
        absint.set_device_memory_budget(main, 10 * 1024 * 1024)
        assert not [d for d in run_checks(main)
                    if d.code == "PTA170"]

    def test_no_budget_is_silent(self):
        main = self._program()
        assert not [d for d in run_checks(main) if d.code == "PTA170"]
