"""Oracle sweep, part 2: loss / norm / vision families.

Parity model: reference tests/unittests/test_hinge_loss_op.py,
test_log_loss_op.py, test_smooth_l1_loss_op.py, test_kldiv_loss_op.py,
test_margin_rank_loss_op.py, test_dice_loss-era, test_lrn_op.py,
test_group_norm_op.py, test_instance_norm-era, test_l2_normalize-era,
test_affine_channel_op.py, test_temporal_shift_op.py,
test_strided_slice-era, test_unfold-era, test_spectral_norm_op.py.
Forward oracles via the OpTest harness with fd grad checks where the
op is smooth at the sampled points.
"""
import numpy as np
import pytest

from op_test import OpTest  # noqa: F401 (re-exported style)
from test_op_sweep import _case


@pytest.fixture()
def R():
    # per-test generator: shared module state would make data depend
    # on test selection/ordering and flake the tolerance checks
    return np.random.RandomState(11)


def test_hinge_loss(R):
    logits = R.randn(8, 1).astype("float32")
    labels = (R.rand(8, 1) > 0.5).astype("float32")
    expect = np.maximum(0.0, 1.0 - (2 * labels - 1) * logits)
    _case("hinge_loss", {"Logits": logits, "Labels": labels},
          {"Loss": expect}, grad=("Logits",), no_grad=("Labels",))


def test_log_loss(R):
    p = R.uniform(0.1, 0.9, (8, 1)).astype("float32")
    y = (R.rand(8, 1) > 0.5).astype("float32")
    eps = 1e-4
    expect = -y * np.log(p + eps) - (1 - y) * np.log(1 - p + eps)
    _case("log_loss", {"Predicted": p, "Labels": y},
          {"Loss": expect}, {"epsilon": eps}, grad=("Predicted",),
          no_grad=("Labels",))


def test_smooth_l1_loss(R):
    x = R.randn(6, 4).astype("float32")
    y = x + R.randn(6, 4).astype("float32") * 2  # mix |d|<1 and >1
    sigma = 1.0
    d = x - y
    expect = np.where(np.abs(d) < 1.0 / sigma ** 2,
                      0.5 * (sigma * d) ** 2,
                      np.abs(d) - 0.5 / sigma ** 2).sum(
                          1, keepdims=True)
    _case("smooth_l1_loss", {"X": x, "Y": y}, {"Out": expect},
          {"sigma": sigma}, grad=("X",), no_grad=("Y",))


def test_kldiv_loss(R):
    logp = np.log(R.dirichlet(np.ones(5), 6).astype("float32"))
    t = R.dirichlet(np.ones(5), 6).astype("float32")
    expect = (t * (np.log(t) - logp)).mean().reshape(1)
    _case("kldiv_loss", {"X": logp, "Target": t},
          {"Loss": expect.astype("float32")}, {"reduction": "mean"},
          atol=1e-4, grad=("X",), no_grad=("Target",))


def test_margin_rank_loss(R):
    x1 = R.randn(8, 1).astype("float32")
    x2 = R.randn(8, 1).astype("float32")
    lab = np.where(R.rand(8, 1) > 0.5, 1.0, -1.0).astype("float32")
    out = np.maximum(0.0, -lab * (x1 - x2) + 0.1)
    _case("margin_rank_loss",
          {"X1": x1, "X2": x2, "Label": lab},
          {"Out": out, "Activated": (out > 0).astype("float32")},
          {"margin": 0.1}, grad=("X1", "X2"), no_grad=("Label",))


def test_dice_loss(R):
    x = R.uniform(0.1, 0.9, (4, 9)).astype("float32")
    lab = (R.rand(4, 9) > 0.5).astype("int64")
    eps = 1e-5
    inter = (x * lab).sum(-1) * 2
    union = x.sum(-1) + lab.sum(-1)
    expect = (1 - (inter + eps) / (union + eps)).mean().reshape(1)
    _case("dice_loss", {"X": x, "Label": lab},
          {"Out": expect.astype("float32")}, {"epsilon": eps},
          grad=("X",), no_grad=("Label",))


def test_bpr_loss(R):
    x = R.uniform(0.05, 0.95, (4, 5)).astype("float32")
    x = x / x.sum(1, keepdims=True)
    lab = R.randint(0, 5, (4, 1)).astype("int64")
    # reference bpr_loss_op.h: -mean_j!=y log(sigmoid(x_y - x_j))
    expect = np.zeros((4, 1), np.float32)
    for i in range(4):
        y = int(lab[i, 0])
        others = [j for j in range(5) if j != y]
        diffs = x[i, y] - x[i, others]
        expect[i, 0] = -np.mean(np.log(1 / (1 + np.exp(-diffs))))
    _case("bpr_loss", {"X": x, "Label": lab}, {"Out": expect},
          atol=1e-4, grad=("X",), no_grad=("Label",))


def test_l2_normalize_and_lrn(R):
    x = R.randn(3, 8).astype("float32")
    expect = x / np.sqrt((x ** 2).sum(1, keepdims=True) + 1e-10)
    _case("l2_normalize", {"X": x}, {"Out": expect}, {"axis": 1},
          grad=("X",))

    # lrn (reference lrn_op.cc): out = x / (k + alpha*sum_window)^beta
    xi = R.rand(2, 6, 3, 3).astype("float32")
    n, alpha, beta, k = 5, 1e-4, 0.75, 1.0
    sq = xi ** 2
    acc = np.zeros_like(xi)
    for c in range(6):
        lo, hi = max(0, c - n // 2), min(6, c + n // 2 + 1)
        acc[:, c] = sq[:, lo:hi].sum(1)
    expect = xi / np.power(k + alpha * acc, beta)
    _case("lrn", {"X": xi}, {"Out": expect},
          {"n": n, "alpha": alpha, "beta": beta, "k": k},
          grad=("X",))


def test_group_and_instance_norm(R):
    x = R.randn(2, 6, 4, 4).astype("float32")
    g = 3
    xr = x.reshape(2, g, -1)
    mean = xr.mean(-1, keepdims=True)
    var = xr.var(-1, keepdims=True)
    yn = ((xr - mean) / np.sqrt(var + 1e-5)).reshape(x.shape)
    scale = R.rand(6).astype("float32")
    bias = R.rand(6).astype("float32")
    expect = yn * scale[None, :, None, None] + bias[None, :, None, None]
    _case("group_norm", {"X": x, "Scale": scale, "Bias": bias},
          {"Y": expect}, {"groups": g, "epsilon": 1e-5},
          atol=1e-4, grad=("X",), out_name="Y",
          no_grad=("Scale", "Bias"))

    xr = x.reshape(2, 6, -1)
    mean = xr.mean(-1, keepdims=True)
    var = xr.var(-1, keepdims=True)
    yn = ((xr - mean) / np.sqrt(var + 1e-5)).reshape(x.shape)
    expect = yn * scale[None, :, None, None] + bias[None, :, None, None]
    _case("instance_norm", {"X": x, "Scale": scale, "Bias": bias},
          {"Y": expect}, {"epsilon": 1e-5}, atol=1e-4,
          grad=("X",), out_name="Y", no_grad=("Scale", "Bias"))


def test_affine_channel_and_temporal_shift(R):
    x = R.randn(2, 4, 3, 3).astype("float32")
    scale = R.rand(4).astype("float32")
    bias = R.rand(4).astype("float32")
    expect = x * scale[None, :, None, None] + bias[None, :, None, None]
    _case("affine_channel", {"X": x, "Scale": scale, "Bias": bias},
          {"Out": expect}, {"data_layout": "NCHW"}, grad=("X",),
          no_grad=("Scale", "Bias"))

    # temporal_shift (reference temporal_shift_op.h:60-66): channels
    # < C/4 read the PAST frame (src_it = it-1), next C/4 the future
    nt, c, h, w = 4, 8, 2, 2
    seg = 2
    xt = R.randn(nt, c, h, w).astype("float32")
    x5 = xt.reshape(nt // seg, seg, c, h, w)
    out = np.zeros_like(x5)
    c1, c2 = c // 4, c // 2
    out[:, 1:, :c1] = x5[:, :-1, :c1]          # past frame
    out[:, :-1, c1:c2] = x5[:, 1:, c1:c2]      # future frame
    out[:, :, c2:] = x5[:, :, c2:]
    expect = out.reshape(nt, c, h, w)
    _case("temporal_shift", {"X": xt}, {"Out": expect},
          {"seg_num": seg, "shift_ratio": 0.25}, grad=("X",))


def test_strided_slice_and_unfold(R):
    x = np.arange(48, dtype=np.float32).reshape(4, 12)
    _case("strided_slice", {"Input": x}, {"Out": x[1:4:2, 2:10:3]},
          {"axes": [0, 1], "starts": [1, 2], "ends": [4, 10],
           "strides": [2, 3]}, grad=("Input",))

    xi = R.randn(1, 2, 4, 4).astype("float32")
    # unfold 2x2 patches stride 2: im2col oracle [1, C*k*k, L]
    expect = np.transpose(
        np.asarray([xi[0, :, i:i+2, j:j+2].reshape(-1)
                    for i in (0, 2) for j in (0, 2)]), (1, 0))[None]
    _case("unfold", {"X": xi}, {"Y": expect},
          {"kernel_sizes": [2, 2], "strides": [2, 2],
           "paddings": [0, 0], "dilations": [1, 1]},
          grad=("X",), out_name="Y")


def test_spectral_norm_contract(R):
    # reference spectral_norm_op.h: weight / sigma with sigma from
    # power iteration; check ||W/sigma||_2 ~= 1
    from test_op_sweep import _run

    w = R.randn(6, 4).astype("float32")
    u = R.randn(6).astype("float32")
    v = R.randn(4).astype("float32")
    out = _run("spectral_norm", {"Weight": w, "U": u, "V": v},
               {"dim": 0, "power_iters": 20, "eps": 1e-12})
    sigma = np.linalg.svd(np.asarray(out), compute_uv=False)[0]
    np.testing.assert_allclose(sigma, 1.0, atol=1e-3, rtol=1e-3)


if __name__ == "__main__":
    pytest.main([__file__, "-q"])
