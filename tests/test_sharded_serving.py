"""Sharded serving: tensor-parallel decode + data-parallel lanes on
the virtual 8-device mesh (models/decode_engine.ShardingConfig +
core/sharding_plan.py + inference/runtime/placement.py).

The invariants this module pins (the r17 acceptance criteria):

* token-exact greedy parity sharded-vs-single across every decode
  front — whole-loop incremental, plain dense burst, paged,
  speculative — and BIT-exact sampled streams (the noise keying is
  (seed, position), so a tp mesh must not move a single draw);
* per-device self-KV bytes ~1/tp at tp=2: exactly 1/tp per pool in
  the PTA170 static plan, and <= 0.55x end-to-end argument bytes via
  the compiled executable's ``memory_analysis()``;
* zero steady-state compiles under 100-request churn with tp models
  AND dp replica lanes serving concurrently through the runtime
  registry/router;
* warm start survives sharded programs: a fresh process rehydrates a
  sharded serve executable from the disk compile cache with ZERO
  compiles, and a mesh-mismatched entry is a NAMED discard, never a
  crash;
* fingerprints/cache keys separate sharded from dense builds (they
  must never dedupe or hot-swap as the same model).
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import unique_name
from paddle_tpu.core.scope import Scope
from paddle_tpu.inference import (ContinuousGenerationServer,
                                  PagedContinuousGenerationServer,
                                  apply_eos_sentinel)
from paddle_tpu.models import transformer as T
from paddle_tpu.models.decode_engine import (CacheConfig, DraftConfig,
                                             SamplingConfig,
                                             ShardingConfig,
                                             place_sharded_program)

V, D, DD, H, L, S, MAXT = 16, 32, 16, 4, 1, 12, 16
END_ID = 2
N_SLOTS = 4
TP = 2
BS, NB, E = 4, 64, 6

# fixed prompt pool (the r14 discipline): planted EOS at varied
# positions gives MODEL-DRIVEN mixed-length generations, and the
# repeated prompts give the speculative draft real agreement
_POOL_RNG = np.random.RandomState(5)
PROMPT_POOL = []
for _p in (1, 2, 3, 4, 6, 8, 10, 10):
    _src = _POOL_RNG.randint(3, V, (S,)).astype(np.int64)
    if _p < S:
        _src[_p:] = END_ID
    PROMPT_POOL.append(_src)
PROMPT_POOL = np.stack(PROMPT_POOL)


def _mixed_len_prompts(rng, n):
    return PROMPT_POOL[rng.randint(0, len(PROMPT_POOL), n)]


def _fork_scope(scope):
    """Copy every scope value to host numpy in a FRESH scope: each
    sharded server places ITS OWN copy on its mesh slice, and the
    trained oracle scope stays plain host arrays (placement must
    never leak into the single-device reference leg)."""
    import jax

    fork = Scope()
    for name in list(scope._vars):
        val = scope._get(name)
        if isinstance(val, jax.Array):
            val = np.asarray(val)
        fork._set(name, np.copy(val) if isinstance(val, np.ndarray)
                  else val)
    return fork


@pytest.fixture(scope="module")
def trained():
    """Train target (d32/L1) + draft (d16/L1) terminator-copy models
    into one scope; build the unsharded whole-loop oracle and the
    sharded bundle flavors."""
    fluid.seed(0)
    scope = Scope()
    exe = fluid.Executor(fluid.TPUPlace(0))
    with unique_name.guard():
        t_main, t_st, t_loss = T.build_program(
            seq_len=S, d_model=D, n_heads=H, n_layers=L, d_inner=64,
            vocab=V, with_optimizer=False, dropout_rate=0.0)
        with fluid.program_guard(t_main, t_st):
            fluid.optimizer.Adam(learning_rate=0.02).minimize(t_loss)
        d_main, d_st, d_loss = T.build_program(
            seq_len=S, d_model=DD, n_heads=H, n_layers=L, d_inner=32,
            vocab=V, with_optimizer=False, dropout_rate=0.0,
            name_prefix="draft_")
        with fluid.program_guard(d_main, d_st):
            fluid.optimizer.Adam(learning_rate=0.02).minimize(d_loss)
    exe.run(t_st, scope=scope)
    exe.run(d_st, scope=scope)
    rng = np.random.RandomState(7)
    for _ in range(150):
        src = _mixed_len_prompts(rng, 8)
        tgt_in = np.concatenate(
            [np.full((8, 1), 1, np.int64), src[:, :-1]], 1)
        feed = {"src_ids": src, "tgt_ids": tgt_in, "label": src}
        exe.run(t_main, feed=feed, fetch_list=[t_loss], scope=scope)
        exe.run(d_main, feed=feed, fetch_list=[d_loss], scope=scope)

    kwargs = dict(seq_len=S, max_out_len=MAXT, d_model=D, n_heads=H,
                  n_layers=L, d_inner=64, vocab=V, start_id=1,
                  end_id=END_ID)
    with unique_name.guard():
        inc_m, _, _, inc_buf = T.build_incremental_decode_program(
            **kwargs)
    return {"exe": exe, "scope": scope, "inc_m": inc_m,
            "inc_buf": inc_buf, "kwargs": kwargs}


def _oracle(tr, srcs):
    ref, = tr["exe"].run(tr["inc_m"], feed={"src_ids": srcs},
                         fetch_list=[tr["inc_buf"]],
                         scope=tr["scope"])
    return apply_eos_sentinel(np.asarray(ref), end_id=END_ID)


def _build(tr, prefix, **kw):
    args = dict(tr["kwargs"])
    args.update(kw)
    with unique_name.guard():
        return T.build_decode_step_program(
            n_slots=N_SLOTS, admit_buckets=[N_SLOTS],
            state_prefix=prefix, **args)


def _serve(tr, bundle, srcs, seeds=None, **srv_kw):
    cls = (PagedContinuousGenerationServer
           if bundle.cache.layout == "paged"
           else ContinuousGenerationServer)
    fork = _fork_scope(tr["scope"])
    with cls(bundle, executor=tr["exe"], scope=fork,
             **srv_kw) as srv:
        replies = []
        for i, s in enumerate(srcs):
            kw = {"seed": int(seeds[i])} if seeds is not None else {}
            replies.append(srv.submit(s, **kw))
        got = np.stack([r.result(timeout=300.0) for r in replies])
        st = srv.stats()
    return got, st


# ---------------------------------------------------------------------------
# token-exact parity sharded-vs-single, every decode front
# ---------------------------------------------------------------------------
class TestParity:
    def test_whole_loop_sharded_vs_single(self, trained):
        srcs = _mixed_len_prompts(np.random.RandomState(11), 8)
        want = _oracle(trained, srcs)
        assert len(set(int((w != -1).sum()) for w in want)) > 1, \
            "workload must have mixed output lengths"
        with unique_name.guard():
            sh_m, _, _, sh_buf = T.build_incremental_decode_program(
                sharding=ShardingConfig(tp=TP), **trained["kwargs"])
        fork = _fork_scope(trained["scope"])
        placed = place_sharded_program(sh_m, fork)
        assert placed > 0
        got, = trained["exe"].run(sh_m, feed={"src_ids": srcs},
                                  fetch_list=[sh_buf], scope=fork)
        got = apply_eos_sentinel(np.asarray(got), END_ID)
        np.testing.assert_array_equal(got, want)

    def test_greedy_full_recompute_sharded_vs_single(self, trained):
        """The greedy FULL-RECOMPUTE whole-loop front takes
        ``sharding=`` too (params-only tp layout — it holds no
        persistable KV, so the fused attention ops take head
        sharding purely from GSPMD param propagation): token parity
        against the single-device incremental oracle."""
        srcs = _mixed_len_prompts(np.random.RandomState(29), 8)
        want = _oracle(trained, srcs)
        with unique_name.guard():
            g_m, _, _, g_buf = T.build_greedy_decode_program(
                sharding=ShardingConfig(tp=TP), **trained["kwargs"])
        fork = _fork_scope(trained["scope"])
        placed = place_sharded_program(g_m, fork)
        assert placed > 0
        got, = trained["exe"].run(g_m, feed={"src_ids": srcs},
                                  fetch_list=[g_buf], scope=fork)
        got = apply_eos_sentinel(np.asarray(got), END_ID)
        np.testing.assert_array_equal(got, want)

    def test_dense_burst_sharded_vs_single(self, trained):
        srcs = _mixed_len_prompts(np.random.RandomState(13), 12)
        want = _oracle(trained, srcs)
        b = _build(trained, "@shd/", sharding=ShardingConfig(tp=TP))
        got, _ = _serve(trained, b, srcs)
        np.testing.assert_array_equal(got, want)

    def test_paged_sharded_vs_single_with_prefix_hits(self, trained):
        srcs = _mixed_len_prompts(np.random.RandomState(17), 16)
        want = _oracle(trained, srcs)
        b = _build(trained, "@shp/", sharding=ShardingConfig(tp=TP),
                   cache=CacheConfig(layout="paged", block_size=BS,
                                     n_blocks=NB,
                                     n_prompt_entries=E))
        got, st = _serve(trained, b, srcs)
        np.testing.assert_array_equal(got, want)
        # the pooled prompts repeat: the prefix-reuse fast path must
        # have served some admissions encoder-free on the tp mesh too
        assert st["block_pool"]["prefix_hits"] > 0

    def test_speculative_sharded_vs_single(self, trained):
        srcs = _mixed_len_prompts(np.random.RandomState(19), 12)
        want = _oracle(trained, srcs)
        b = _build(trained, "@shs/", sharding=ShardingConfig(tp=TP),
                   draft=DraftConfig(d_model=DD, n_heads=H,
                                     n_layers=L, d_inner=32, k=2))
        got, st = _serve(trained, b, srcs)
        np.testing.assert_array_equal(got, want)
        # the trained draft must actually accept on the tp mesh (the
        # sharded verify step's acceptance math is unchanged)
        assert st["speculative"]["acceptance_rate"] > 0.5

    def test_sampled_bit_repro_sharded_vs_single(self, trained):
        """Sampled emission is keyed purely on (seed, position): the
        tp mesh must not move a single draw — byte equality against
        the UNSHARDED sampled bundle, same seeds."""
        rng = np.random.RandomState(23)
        srcs = _mixed_len_prompts(rng, 12)
        seeds = rng.randint(0, 2 ** 31, 12)
        samp = SamplingConfig(temperature=1.0, top_k=8)
        b1 = _build(trained, "@sm1/", sampling=samp)
        b2 = _build(trained, "@sm2/", sampling=samp,
                    sharding=ShardingConfig(tp=TP))
        single, _ = _serve(trained, b1, srcs, seeds=seeds)
        sharded, _ = _serve(trained, b2, srcs, seeds=seeds)
        np.testing.assert_array_equal(sharded, single)


# ---------------------------------------------------------------------------
# per-device KV bytes: PTA170 static plan + compiled memory_analysis
# ---------------------------------------------------------------------------
class TestPerDeviceKV:
    def test_pta170_plan_prices_pools_at_one_over_tp(self, trained):
        from paddle_tpu.analysis import absint

        b = _build(trained, "@kvp/", sharding=ShardingConfig(tp=TP),
                   cache=CacheConfig(layout="paged", block_size=BS,
                                     n_blocks=NB,
                                     n_prompt_entries=E))
        facts = absint.analyze(b.step)
        plan = facts.device_memory_plan(batch=1)
        pools = [n for n in b._state_specs if "@POOL" in n]
        assert pools
        for name in pools:
            entry = plan.entry(name)
            assert entry is not None, name
            assert entry.device_bytes * TP == entry.bytes, name

    def test_memory_analysis_argument_bytes_shrink(self, trained,
                                                   tmp_path):
        """End-to-end corroboration: the compiled serve executable's
        per-device argument bytes at tp=2 are <= 0.55x the
        single-device build (the pool geometry dominates the
        argument set by construction)."""
        from paddle_tpu.flags import set_flags

        # the disk cache turns on the AOT compile path, whose
        # Compiled exposes memory_analysis() (conftest forces off)
        set_flags({"FLAGS_compile_cache": "rw",
                   "FLAGS_compile_cache_dir": str(tmp_path / "cc")})
        try:
            # serving-scale pool (the capacity regime the claim is
            # about): self-KV dominates the argument set, so the
            # END-TO-END ratio lands at ~0.5 + the replicated
            # remainder (tables, embeddings, fused projections)
            geo = dict(cache=CacheConfig(layout="paged",
                                         block_size=BS, n_blocks=160,
                                         n_prompt_entries=E))
            sizes = {}
            for tag, sh in (("single", None),
                            ("tp", ShardingConfig(tp=TP))):
                b = _build(trained, f"@ma{tag}/", sharding=sh, **geo)
                fork = _fork_scope(trained["scope"])
                with PagedContinuousGenerationServer(
                        b, executor=trained["exe"],
                        scope=fork) as srv:
                    fn = srv._serves[0]._compiled.fn
                    ma = getattr(fn, "memory_analysis", None)
                    assert ma is not None, \
                        "AOT path did not engage (no memory_analysis)"
                    sizes[tag] = int(ma().argument_size_in_bytes)
            ratio = sizes["tp"] / sizes["single"]
            assert ratio <= 0.55, sizes
        finally:
            set_flags({"FLAGS_compile_cache": "off"})


# ---------------------------------------------------------------------------
# tp + dp through the runtime: placement, churn, zero compiles
# ---------------------------------------------------------------------------
class TestRuntimeMesh:
    def test_churn_zero_steady_state_compiles_tp_and_dp(self, trained):
        """2 tp-2 decode models on devices [0,1]/[2,3] + 4 dp fc
        lanes on devices 4..7, loaded through the registry and routed
        100 requests each way: ZERO compiles in the traffic window,
        and every piece lands on its assigned slice."""
        import jax

        from paddle_tpu.inference.runtime import (ModelRegistry,
                                                  ReplicaSet,
                                                  plan_mesh,
                                                  place_scope_on_device,
                                                  zoo)

        mp = plan_mesh(n_tp_models=2, tp=TP, n_dp_lanes=4)
        registry = ModelRegistry()
        exe = registry.executor()
        # --- 2 tensor-parallel decode models on their slices ---
        decode = []
        for i, devices in enumerate(mp.tp_slices):
            b = _build(trained, f"@mesh{i}/",
                       sharding=ShardingConfig(tp=TP))
            fork = _fork_scope(trained["scope"])
            srv = ContinuousGenerationServer(
                b, executor=exe, scope=fork, mesh_devices=devices)
            registry.load(f"decode-{i}", srv, warm=False)
            decode.append((b, fork, srv, devices))
            # the bundle's state really lives on this slice
            pool = fork._get(b.state["tok_buf"])
            assert {d.id for d in pool.sharding.mesh.devices.flat} \
                == {d.id for d in devices}
        # --- 4 dp fc replica lanes behind one alias ---
        lanes, lane_scopes = [], []
        for j, dev in enumerate(mp.dp_devices):
            srv, sc = zoo.make_fc_server(f"lane{j}", 16, 32, 4,
                                         executor=exe,
                                         max_wait_ms=0.5)
            place_scope_on_device(sc, dev)
            assert list(sc._get(f"lane{j}_fc1.w").devices())[0].id \
                == dev.id
            lanes.append(srv)
            lane_scopes.append(sc)
        # warm=True: ReplicaSet.aot_warmup fans out and seeds every
        # lane's whole bucket ladder (churn batches land on arbitrary
        # buckets; an unwarmed bucket would be a steady-state compile)
        registry.load("fc", ReplicaSet(lanes, mp.dp_devices),
                      warm=True)

        # decode warm: one admission per tp model (the serve set was
        # already prepared — compiled — at server construction)
        rng = np.random.RandomState(29)
        for _b, _f, srv, _d in decode:
            srv.submit(_mixed_len_prompts(rng, 1)[0]).result(120)

        warm = exe.compile_count
        fc = registry.get("fc")
        replies, fc_replies = [], []
        for i in range(100):
            srv = decode[i % 2][2]
            replies.append(srv.submit(_mixed_len_prompts(rng, 1)[0]))
            j = i % 4
            fc_replies.append(fc.submit(
                {f"lane{j}_x": rng.rand(1, 16).astype(np.float32)}))
        for r in replies:
            r.result(timeout=300.0)
        for r in fc_replies:
            r.result(timeout=300.0)
        assert exe.compile_count == warm, \
            "steady-state traffic compiled under tp+dp"
        registry.close()

    def test_server_reconstruction_hits_warm_executables(self,
                                                         trained):
        """A SECOND server over the same bundle + same device slice
        (fresh scope) must serve entirely from the warmed
        executables: placement is idempotent — an unconditional
        plan re-attach used to version-bump every program and
        recompile the whole serve set per server construction
        (caught by bench.py sharded)."""
        srcs = _mixed_len_prompts(np.random.RandomState(31), 4)
        b = _build(trained, "@warm2/", sharding=ShardingConfig(tp=TP))
        _serve(trained, b, srcs)
        c0 = trained["exe"].compile_count
        got, _ = _serve(trained, b, srcs)
        assert trained["exe"].compile_count == c0, \
            "server re-construction recompiled the serve set"
        np.testing.assert_array_equal(got, _oracle(trained, srcs))


# (fingerprint/validation/carve/mesh-discard units live in the
# fast-lane tests/test_sharding_plan.py)
# ---------------------------------------------------------------------------
# warm start: disk rehydration of a sharded serve program
# ---------------------------------------------------------------------------
_SUBPROCESS_SCRIPT = r"""
import json
import numpy as np
import paddle_tpu as fluid
from paddle_tpu.core.scope import Scope
from paddle_tpu.inference import ContinuousGenerationServer
from paddle_tpu.models import transformer as T
from paddle_tpu.models.decode_engine import ShardingConfig

fluid.seed(11)
scope = Scope()
exe = fluid.Executor(fluid.TPUPlace(0))
from paddle_tpu import unique_name
with unique_name.guard():
    # serving runs against a trained scope: the train build's startup
    # initializes EVERY decoder param (deterministic under seed 11)
    _m, t_st, _loss = T.build_program(
        seq_len=6, d_model=16, n_heads=2, n_layers=1, d_inner=32,
        vocab=16, with_optimizer=False, dropout_rate=0.0)
exe.run(t_st, scope=scope)
with unique_name.guard():
    bundle = T.build_decode_step_program(
        seq_len=6, max_out_len=8, d_model=16, n_heads=2, n_layers=1,
        d_inner=32, vocab=16, start_id=1, end_id=2, n_slots=2,
        admit_buckets=[2], state_prefix="@sub/",
        sharding=ShardingConfig(tp=2))
src = np.arange(3, 9, dtype=np.int64)[None].repeat(2, 0)[0]
with ContinuousGenerationServer(bundle, executor=exe,
                                scope=scope) as srv:
    toks = [srv.submit(src).result(120).tolist() for _ in range(2)]
print(json.dumps({"compiles": exe.compile_count,
                  "disk_loads": exe.disk_load_count,
                  "toks": toks}))
"""


class TestShardedWarmStart:
    def test_subprocess_rehydrates_sharded_serves(self, tmp_path):
        env = dict(os.environ,
                   JAX_PLATFORMS="cpu",
                   XLA_FLAGS="--xla_force_host_platform_device_count"
                             "=8",
                   FLAGS_compile_cache="rw",
                   FLAGS_compile_cache_dir=str(tmp_path / "cc"))

        def run_once(tag):
            proc = subprocess.run(
                [sys.executable, "-c", _SUBPROCESS_SCRIPT],
                capture_output=True, text=True, env=env, timeout=600)
            assert proc.returncode == 0, \
                f"{tag} failed:\n{proc.stderr[-2000:]}"
            return json.loads(proc.stdout.strip().splitlines()[-1])

        a = run_once("process A (cold)")
        assert a["compiles"] > 0
        b = run_once("process B (disk-warmed)")
        assert b["compiles"] == 0, b
        assert b["disk_loads"] > 0
        assert b["toks"] == a["toks"]

    # (the mesh-mismatch named-discard unit lives in the fast-lane
    # tests/test_sharding_plan.py)
