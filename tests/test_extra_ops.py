"""OpTests for the round-2 op-gap ops (numpy oracles + fd grad checks).

Parity model: reference tests/unittests/test_pool3d_op.py,
test_pool_max_op.py, test_conv3d_transpose_op.py, test_unpool_op.py,
test_spp_op.py, test_bilinear_tensor_product_op.py,
test_rank_loss_op.py, test_modified_huber_loss_op.py,
test_squared_l2_distance_op.py, test_conv_shift_op.py,
test_add_position_encoding_op.py, test_data_norm_op.py,
test_random_crop_op.py, test_is_empty_op.py, test_lstmp_op.py,
test_lod_rank_table.py, test_lod_tensor_array_ops.py.
"""
import numpy as np
import pytest

import paddle_tpu as fluid
from op_test import OpTest


def _np_pool3d(x, ksize, strides, pads, ptype):
    n, c, d, h, w = x.shape
    od = (d + 2 * pads[0] - ksize[0]) // strides[0] + 1
    oh = (h + 2 * pads[1] - ksize[1]) // strides[1] + 1
    ow = (w + 2 * pads[2] - ksize[2]) // strides[2] + 1
    out = np.zeros((n, c, od, oh, ow), np.float32)
    xp = np.pad(x, ((0, 0), (0, 0)) + tuple((p, p) for p in pads),
                constant_values=-np.inf if ptype == "max" else 0.0)
    for i in range(od):
        for j in range(oh):
            for k in range(ow):
                win = xp[:, :,
                         i * strides[0]:i * strides[0] + ksize[0],
                         j * strides[1]:j * strides[1] + ksize[1],
                         k * strides[2]:k * strides[2] + ksize[2]]
                if ptype == "max":
                    out[:, :, i, j, k] = win.max(axis=(2, 3, 4))
                else:
                    out[:, :, i, j, k] = win.mean(axis=(2, 3, 4))
    return out


class TestPool3dMax(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "pool3d"
        x = np.random.random((2, 3, 6, 6, 6)).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"pooling_type": "max", "ksize": [2, 2, 2],
                      "strides": [2, 2, 2], "paddings": [0, 0, 0]}
        self.outputs = {"Out": _np_pool3d(x, [2] * 3, [2] * 3, [0] * 3,
                                          "max")}

    def test_output(self):
        self.check_output()


class TestPool3dAvgPadded(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "pool3d"
        x = np.random.random((1, 2, 4, 4, 4)).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"pooling_type": "avg", "ksize": [2, 2, 2],
                      "strides": [2, 2, 2], "paddings": [0, 0, 0],
                      "exclusive": True}
        self.outputs = {"Out": _np_pool3d(x, [2] * 3, [2] * 3, [0] * 3,
                                          "avg")}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        # fd grad on the smooth avg pool (max has kink points where
        # central differences disagree with the subgradient)
        self.check_grad(["X"], "Out")


class TestMaxPool2dWithIndex(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "max_pool2d_with_index"
        x = np.random.random((2, 3, 6, 6)).astype("float32")
        n, c, h, w = x.shape
        out = np.zeros((n, c, 3, 3), np.float32)
        mask = np.zeros((n, c, 3, 3), np.int32)
        for i in range(3):
            for j in range(3):
                win = x[:, :, 2 * i:2 * i + 2, 2 * j:2 * j + 2]
                wf = win.reshape(n, c, -1)
                arg = wf.argmax(-1)
                out[:, :, i, j] = wf.max(-1)
                dh, dw = np.unravel_index(arg, (2, 2))
                mask[:, :, i, j] = (2 * i + dh) * w + (2 * j + dw)
        self.inputs = {"X": x}
        self.attrs = {"ksize": [2, 2], "strides": [2, 2],
                      "paddings": [0, 0]}
        self.outputs = {"Out": out, "Mask": mask}

    def test_output(self):
        self.check_output()


class TestMaxPool3dWithIndex(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "max_pool3d_with_index"
        x = np.random.random((1, 2, 4, 4, 4)).astype("float32")
        n, c, d, h, w = x.shape
        out = np.zeros((n, c, 2, 2, 2), np.float32)
        mask = np.zeros((n, c, 2, 2, 2), np.int32)
        for i in range(2):
            for j in range(2):
                for k in range(2):
                    win = x[:, :, 2 * i:2 * i + 2, 2 * j:2 * j + 2,
                            2 * k:2 * k + 2]
                    wf = win.reshape(n, c, -1)
                    arg = wf.argmax(-1)
                    out[:, :, i, j, k] = wf.max(-1)
                    dd, dh, dw = np.unravel_index(arg, (2, 2, 2))
                    mask[:, :, i, j, k] = ((2 * i + dd) * h +
                                           (2 * j + dh)) * w + \
                        (2 * k + dw)
        self.inputs = {"X": x}
        self.attrs = {"ksize": [2, 2, 2], "strides": [2, 2, 2],
                      "paddings": [0, 0, 0]}
        self.outputs = {"Out": out, "Mask": mask}

    def test_output(self):
        self.check_output()


class TestUnpoolRoundTrip:
    def test_unpool_inverts_max_pool(self):
        x = np.arange(32, dtype=np.float32).reshape(1, 2, 4, 4)
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            xin = fluid.layers.data(name="x", shape=[2, 4, 4],
                                    dtype="float32")
            pooled, mask = fluid.layers.max_pool2d_with_index(
                xin, pool_size=2, pool_stride=2)
            restored = fluid.layers.unpool(pooled, mask, pool_size=2,
                                           pool_stride=2)
        exe = fluid.Executor(fluid.CPUPlace())
        p, m, r = exe.run(prog, feed={"x": x},
                          fetch_list=[pooled, mask, restored])
        assert p.shape == (1, 2, 2, 2)
        # restored has pooled max values at their original positions
        expect = np.zeros_like(x)
        for ci in range(2):
            for i in range(2):
                for j in range(2):
                    idx = m[0, ci, i, j]
                    expect[0, ci, idx // 4, idx % 4] = p[0, ci, i, j]
        np.testing.assert_allclose(r, expect)


class TestSpp(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "spp"
        x = np.random.random((2, 3, 4, 4)).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"pyramid_height": 2, "pooling_type": "max"}
        # level 0: global max [N,C]; level 1: 2x2 max bins [N,C*4]
        l0 = x.max(axis=(2, 3)).reshape(2, -1)
        l1 = np.zeros((2, 3, 2, 2), np.float32)
        for i in range(2):
            for j in range(2):
                l1[:, :, i, j] = x[:, :, 2 * i:2 * i + 2,
                                   2 * j:2 * j + 2].max(axis=(2, 3))
        self.outputs = {"Out": np.concatenate(
            [l0, l1.reshape(2, -1)], axis=1)}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["X"], "Out")


class TestConv3dTranspose:
    def test_matches_scipy_style_oracle(self):
        # stride-2 transpose conv of a delta kernel = upsample + copy
        x = np.random.randn(1, 1, 3, 3, 3).astype(np.float32)
        w = np.zeros((1, 1, 2, 2, 2), np.float32)
        w[0, 0, 0, 0, 0] = 1.0
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            xin = fluid.layers.data(name="x", shape=[1, 3, 3, 3],
                                    dtype="float32")
            out = fluid.layers.conv3d_transpose(
                xin, num_filters=1, filter_size=2, stride=2,
                param_attr=fluid.ParamAttr(
                    name="w3t",
                    initializer=fluid.initializer.NumpyArrayInitializer(
                        w)),
                bias_attr=False)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        got, = exe.run(prog, feed={"x": x}, fetch_list=[out])
        assert got.shape == (1, 1, 6, 6, 6)
        np.testing.assert_allclose(got[0, 0, ::2, ::2, ::2],
                                   x[0, 0], rtol=1e-5)
        assert abs(got[0, 0, 1::2].sum()) < 1e-5


class TestBilinearTensorProduct(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "bilinear_tensor_product"
        x = np.random.random((4, 5)).astype("float32")
        y = np.random.random((4, 6)).astype("float32")
        w = np.random.random((3, 5, 6)).astype("float32")
        b = np.random.random((1, 3)).astype("float32")
        self.inputs = {"X": x, "Y": y, "Weight": w, "Bias": b}
        self.outputs = {"Out": np.einsum("bi,kij,bj->bk", x, w, y) + b}

    def test_output(self):
        self.check_output(atol=1e-4)

    def test_grad(self):
        self.check_grad(["X", "Y", "Weight"], "Out")


class TestRankLoss(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "rank_loss"
        label = np.random.randint(0, 2, (8, 1)).astype("float32")
        left = np.random.random((8, 1)).astype("float32")
        right = np.random.random((8, 1)).astype("float32")
        o = left - right
        self.inputs = {"Label": label, "Left": left, "Right": right}
        self.outputs = {"Out": np.log1p(np.exp(o)) - label * o}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["Left", "Right"], "Out")


class TestModifiedHuberLoss(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "modified_huber_loss"
        x = np.random.uniform(-2, 2, (10, 1)).astype("float32")
        y = np.random.randint(0, 2, (10, 1)).astype("float32")
        z = x * (2 * y - 1)
        loss = np.where(z < -1, -4.0 * z,
                        np.where(z < 1, (1 - z) ** 2, 0.0))
        self.inputs = {"X": x, "Y": y}
        self.outputs = {"IntermediateVal": z,
                        "Out": loss.astype(np.float32)}

    def test_output(self):
        self.check_output()


class TestSquaredL2Distance(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "squared_l2_distance"
        x = np.random.random((6, 4)).astype("float32")
        y = np.random.random((6, 4)).astype("float32")
        sub = x - y
        self.inputs = {"X": x, "Y": y}
        self.outputs = {"sub_result": sub,
                        "Out": (sub * sub).sum(1, keepdims=True)}

    def test_output(self):
        self.check_output(atol=1e-4)

    def test_grad(self):
        self.check_grad(["X", "Y"], "Out")


class TestTeacherStudentSigmoidLoss(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "teacher_student_sigmoid_loss"
        x = np.random.uniform(-3, 3, (12, 1)).astype("float32")
        label = np.array([[-2.0], [-1.5], [-1.0], [-0.5], [0.0],
                          [0.3], [0.7], [1.0], [1.2], [1.9], [-2.0],
                          [0.5]], np.float32)
        sp = np.maximum(x, 0) + np.log1p(np.exp(-np.abs(x)))
        y = np.where(label < -1.0, sp,
                     np.where(label < 0.0, sp - x,
                              np.where(label < 1.0,
                                       2 * sp - x * label,
                                       2 * sp - x - x * (label - 1))))
        self.inputs = {"X": x, "Label": label}
        self.outputs = {"Y": y}

    def test_output(self):
        self.check_output(atol=1e-4)


class TestConvShift(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "conv_shift"
        x = np.random.random((3, 8)).astype("float32")
        y = np.random.random((3, 3)).astype("float32")
        n, w = 8, 3
        out = np.zeros_like(x)
        for b in range(3):
            for i in range(n):
                for j in range(w):
                    out[b, i] += x[b, (i + j - w // 2) % n] * y[b, j]
        self.inputs = {"X": x, "Y": y}
        self.outputs = {"Out": out}

    def test_output(self):
        self.check_output(atol=1e-4)

    def test_grad(self):
        self.check_grad(["X", "Y"], "Out")


class TestAddPositionEncoding(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "add_position_encoding"
        x = np.random.random((2, 5, 8)).astype("float32")
        alpha, beta = 0.7, 1.3
        half = 4
        pe = np.zeros((5, 8), np.float32)
        for j in range(5):
            for k in range(half):
                val = j / np.power(10000.0, k / (half - 1))
                pe[j, k] = np.sin(val)
                pe[j, half + k] = np.cos(val)
        self.inputs = {"X": x}
        self.attrs = {"alpha": alpha, "beta": beta}
        self.outputs = {"Out": alpha * x + beta * pe[None]}

    def test_output(self):
        self.check_output(atol=1e-4)

    def test_grad(self):
        self.check_grad(["X"], "Out")


class TestDataNorm(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "data_norm"
        x = np.random.random((6, 3)).astype("float32")
        bsize = np.full((3,), 10.0, np.float32)
        bsum = np.random.random((3,)).astype("float32") * 10
        bsq = np.full((3,), 40.0, np.float32)
        means = bsum / bsize
        scales = np.sqrt(bsize / bsq)
        self.inputs = {"X": x, "BatchSize": bsize, "BatchSum": bsum,
                       "BatchSquareSum": bsq}
        self.outputs = {"Y": (x - means) * scales, "Means": means,
                        "Scales": scales}

    def test_output(self):
        self.check_output(atol=1e-5)


class TestRandomCropAndIsEmpty:
    def test_random_crop_shape_and_content(self):
        x = np.arange(2 * 8 * 8, dtype=np.float32).reshape(2, 8, 8)
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            xin = fluid.layers.data(name="x", shape=[8, 8],
                                    dtype="float32")
            out = fluid.layers.random_crop(xin, shape=[5, 5])
        exe = fluid.Executor(fluid.CPUPlace())
        got, = exe.run(prog, feed={"x": x}, fetch_list=[out])
        assert got.shape == (2, 5, 5)
        # each crop is a contiguous sub-grid of the source instance
        for b in range(2):
            r0 = got[b, 0, 0]
            i, j = divmod(int(r0) - 64 * b, 8)
            np.testing.assert_array_equal(
                got[b], x[b, i:i + 5, j:j + 5])

    def test_is_empty(self):
        x = np.zeros((0, 3), np.float32)
        y = np.ones((2, 3), np.float32)
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            xin = fluid.layers.data(name="x", shape=[3],
                                    dtype="float32")
            yin = fluid.layers.data(name="y", shape=[3],
                                    dtype="float32")
            ex = fluid.layers.is_empty(xin)
            ey = fluid.layers.is_empty(yin)
        exe = fluid.Executor(fluid.CPUPlace())
        a, b = exe.run(prog, feed={"x": x, "y": y},
                       fetch_list=[ex, ey])
        assert bool(a) is True and bool(b) is False


class TestLstmp:
    def test_projection_shapes_and_masking(self):
        b, t, h, p = 3, 6, 8, 4
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            x = fluid.layers.data(name="x", shape=[t, 4 * h],
                                  dtype="float32")
            proj, cell = fluid.layers.dynamic_lstmp(
                x, size=4 * h, proj_size=p, use_peepholes=False)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        xs = np.random.randn(b, t, 4 * h).astype(np.float32)
        lens = np.array([6, 3, 1], np.int32)
        pr, cl = exe.run(prog, feed={"x": xs, "x@SEQ_LEN": lens},
                         fetch_list=[proj, cell])
        assert pr.shape == (b, t, p) and cl.shape == (b, t, h)
        # beyond each row's length the projection is held constant
        np.testing.assert_allclose(pr[1, 3], pr[1, 2], rtol=1e-6)
        np.testing.assert_allclose(pr[2, 5], pr[2, 0], rtol=1e-6)

    def test_trains(self):
        b, t, h, p = 4, 5, 8, 4
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            x = fluid.layers.data(name="x", shape=[t, 4 * h],
                                  dtype="float32")
            y = fluid.layers.data(name="y", shape=[1], dtype="float32")
            proj, _ = fluid.layers.dynamic_lstmp(
                x, size=4 * h, proj_size=p, use_peepholes=False)
            last = fluid.layers.sequence_last_step(proj)
            pred = fluid.layers.fc(last, size=1)
            loss = fluid.layers.mean(
                fluid.layers.square_error_cost(pred, y))
            fluid.optimizer.SGD(0.1).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        xs = np.random.randn(b, t, 4 * h).astype(np.float32)
        ys = np.random.randn(b, 1).astype(np.float32)
        lens = np.full((b,), t, np.int32)
        ls = [float(exe.run(prog,
                            feed={"x": xs, "x@SEQ_LEN": lens, "y": ys},
                            fetch_list=[loss])[0]) for _ in range(15)]
        assert ls[-1] < ls[0]


class TestLodMachinery:
    def test_rank_table_array_roundtrip(self):
        b, t, d = 4, 5, 2
        x = np.random.randn(b, t, d).astype(np.float32)
        lens = np.array([2, 5, 3, 1], np.int32)
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            xin = fluid.layers.data(name="x", shape=[t, d],
                                    dtype="float32")
            table = fluid.layers.lod_rank_table(xin)
            maxlen = fluid.layers.max_sequence_len(table)
            arr = fluid.layers.lod_tensor_to_array(xin, table)
            back = fluid.layers.array_to_lod_tensor(arr, table)
            reord = fluid.layers.reorder_lod_tensor_by_rank(xin, table)
        exe = fluid.Executor(fluid.CPUPlace())
        tb, ml, bk, ro = exe.run(
            prog, feed={"x": x, "x@SEQ_LEN": lens},
            fetch_list=[table, maxlen, back, reord])
        # rank table: indices sorted by length desc (stable)
        np.testing.assert_array_equal(tb[:, 0], [1, 2, 0, 3])
        np.testing.assert_array_equal(tb[:, 1], [5, 3, 2, 1])
        assert int(ml) == 5
        np.testing.assert_allclose(bk, x, rtol=1e-6)  # round trip
        np.testing.assert_allclose(ro, x[[1, 2, 0, 3]], rtol=1e-6)


class TestSaveLoadOps:
    def test_save_load_roundtrip(self, tmp_path):
        x = np.random.randn(3, 4).astype(np.float32)
        path = str(tmp_path / "var_x")
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            xin = fluid.layers.data(name="x", shape=[4],
                                    dtype="float32")
            helper = fluid.layers.nn.LayerHelper("save", input=xin)
            helper.append_op("save", {"X": xin}, {},
                             {"file_path": path})
            out = prog.global_block.create_var(
                name="loaded", shape=(3, 4), dtype="float32")
            helper.append_op("load", {}, {"Out": out},
                             {"file_path": path, "shape": [3, 4],
                              "dtype": "float32"})
        exe = fluid.Executor(fluid.CPUPlace())
        got, = exe.run(prog, feed={"x": x}, fetch_list=[out])
        np.testing.assert_allclose(got, x, rtol=1e-6)

    def test_save_combine_load_combine(self, tmp_path):
        a = np.random.randn(2, 3).astype(np.float32)
        b = np.random.randn(4).astype(np.float32)
        path = str(tmp_path / "combined")
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            ain = fluid.layers.data(name="a", shape=[3],
                                    dtype="float32")
            bin_ = fluid.layers.data(name="b", shape=[4],
                                     dtype="float32",
                                     append_batch_size=False)
            helper = fluid.layers.nn.LayerHelper("save_combine",
                                                 input=ain)
            helper.append_op("save_combine",
                             {"X": [ain, bin_]}, {},
                             {"file_path": path})
            la = prog.global_block.create_var(name="a", shape=(2, 3),
                                              dtype="float32")
            lb = prog.global_block.create_var(name="b", shape=(4,),
                                              dtype="float32")
            out_a = prog.global_block.create_var(
                name="la", shape=(2, 3), dtype="float32")
            out_b = prog.global_block.create_var(
                name="lb", shape=(4,), dtype="float32")
            helper.append_op("load_combine", {},
                             {"Out": [out_a, out_b]},
                             {"file_path": path,
                              "names": ["a", "b"],
                              "shapes": [[2, 3], [4]],
                              "dtypes": ["float32", "float32"]})
        exe = fluid.Executor(fluid.CPUPlace())
        ga, gb = exe.run(prog, feed={"a": a, "b": b},
                         fetch_list=[out_a, out_b])
        np.testing.assert_allclose(ga, a, rtol=1e-6)
        np.testing.assert_allclose(gb, b, rtol=1e-6)


class TestSelectedRowsBridges:
    def test_merge_and_densify(self):
        rows = np.array([3, 1, 3, 0], np.int64)
        vals = np.arange(8, dtype=np.float32).reshape(4, 2)
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            rin = fluid.layers.data(name="r", shape=[4], dtype="int64",
                                    append_batch_size=False)
            vin = fluid.layers.data(name="v", shape=[4, 2],
                                    dtype="float32",
                                    append_batch_size=False)
            helper = fluid.layers.nn.LayerHelper("msr", input=rin)
            orow = prog.global_block.create_var(name="orow")
            oval = prog.global_block.create_var(name="oval")
            helper.append_op("merge_selected_rows",
                             {"Rows": rin, "Values": vin},
                             {"OutRows": orow, "OutValues": oval}, {})
            dense = prog.global_block.create_var(name="dense")
            helper.append_op("get_tensor_from_selected_rows",
                             {"Rows": orow, "Values": oval},
                             {"Out": dense}, {"height": 5})
        exe = fluid.Executor(fluid.CPUPlace())
        gr, gv, gd = exe.run(prog, feed={"r": rows, "v": vals},
                             fetch_list=[orow, oval, dense])
        np.testing.assert_array_equal(gr, [3, 1, -1, 0])
        np.testing.assert_allclose(gv[0], vals[0] + vals[2])
        np.testing.assert_allclose(gv[2], 0)
        expect = np.zeros((5, 2), np.float32)
        expect[3] = vals[0] + vals[2]
        expect[1] = vals[1]
        expect[0] = vals[3]
        np.testing.assert_allclose(gd, expect)


class TestPrintOp:
    def test_print_passthrough(self, capfd):
        x = np.ones((2, 2), np.float32)
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            xin = fluid.layers.data(name="x", shape=[2],
                                    dtype="float32")
            out = fluid.layers.Print(xin, message="dbg:", summarize=2)
            out2 = fluid.layers.scale(out, scale=2.0)
        exe = fluid.Executor(fluid.CPUPlace())
        got, = exe.run(prog, feed={"x": x}, fetch_list=[out2])
        np.testing.assert_allclose(got, 2 * x)


class TestConvTransposeVsTorch:
    """Kernel-orientation regression (review finding): fluid filter
    layout is [C_in, C_out/g, *k]; outputs must match
    torch.conv_transpose{2,3}d for C_in != C_out, groups, dilation."""

    def _run2d(self, x, w, stride, pad, dilation, groups):
        from paddle_tpu.ops.nn_ops import _conv_transpose_nd

        return np.asarray(_conv_transpose_nd(
            x, w, [stride] * 2, [pad] * 2, [dilation] * 2, groups, 2))

    def test_channels_differ(self):
        import torch
        import torch.nn.functional as F

        rng = np.random.RandomState(0)
        x = rng.randn(2, 3, 7, 7).astype(np.float32)
        w = rng.randn(3, 5, 3, 3).astype(np.float32)
        ref = F.conv_transpose2d(torch.tensor(x), torch.tensor(w),
                                 stride=2, padding=1).numpy()
        np.testing.assert_allclose(self._run2d(x, w, 2, 1, 1, 1), ref,
                                   atol=1e-4)

    def test_grouped(self):
        import torch
        import torch.nn.functional as F

        rng = np.random.RandomState(1)
        x = rng.randn(1, 4, 5, 5).astype(np.float32)
        w = rng.randn(4, 3, 3, 3).astype(np.float32)
        ref = F.conv_transpose2d(torch.tensor(x), torch.tensor(w),
                                 groups=2).numpy()
        np.testing.assert_allclose(self._run2d(x, w, 1, 0, 1, 2), ref,
                                   atol=1e-4)

    def test_layer_conv2d_transpose_c_in_ne_c_out(self):
        # end-to-end through the layer (used to crash at trace time)
        x = np.random.randn(1, 3, 4, 4).astype(np.float32)
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            xin = fluid.layers.data(name="x", shape=[3, 4, 4],
                                    dtype="float32")
            out = fluid.layers.conv2d_transpose(
                xin, num_filters=4, filter_size=3, stride=2,
                bias_attr=False)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        got, = exe.run(prog, feed={"x": x}, fetch_list=[out])
        assert got.shape == (1, 4, 9, 9)
