"""Oracle sweep, part 3: sequence / roi / sampling-grid families.

Parity model: reference tests/unittests/test_sequence_pad_op.py,
test_sequence_unpad_op.py, test_sequence_slice_op.py,
test_sequence_enumerate_op.py, test_sequence_concat-era,
test_sequence_reshape.py, test_roi_pool_op.py, test_roi_align_op.py,
test_grid_sampler_op.py, test_affine_grid-era. Sequences use the
repo's padded [B,T,...] + lengths design (SURVEY §5 LoD inversion).
"""
import numpy as np
import pytest

from test_op_sweep import _case, _run


@pytest.fixture()
def R():
    return np.random.RandomState(13)


def test_sequence_pad_unpad(R):
    x = R.randn(2, 5, 3).astype("float32")
    sl = np.array([3, 5], np.int32)
    pad_val = np.array([0.5], np.float32)
    m = (np.arange(5)[None, :] < sl[:, None])[..., None]
    expect = np.where(m, x, 0.5)
    got, lens = _run("sequence_pad",
                     {"X": x, "SeqLen": sl, "PadValue": pad_val},
                     {"padded_length": 5},
                     out_slots=("Out", "Length"))
    np.testing.assert_allclose(got, expect, atol=1e-6)
    np.testing.assert_array_equal(np.asarray(lens).reshape(-1), sl)

    unp = _run("sequence_unpad", {"X": got, "Length": sl})
    np.testing.assert_allclose(unp, np.where(m, x, 0.0), atol=1e-6)


def test_sequence_slice_and_reshape(R):
    x = R.randn(2, 6, 2).astype("float32")
    off = np.array([[1], [2]], np.int64)
    ln = np.array([[3], [2]], np.int64)
    got = _run("sequence_slice",
               {"X": x, "Offset": off, "Length": ln})
    # padded output: row b holds x[b, off:off+len] at the front
    np.testing.assert_allclose(got[0, :3], x[0, 1:4], atol=1e-6)
    np.testing.assert_allclose(got[1, :2], x[1, 2:4], atol=1e-6)
    assert np.all(np.asarray(got)[0, 3:] == 0)

    _case("sequence_reshape", {"X": x}, {"Out": x.reshape(2, 3, 4)},
          {"new_dim": 4}, atol=1e-6, grad=("X",))


def test_sequence_enumerate_and_concat(R):
    ids = np.array([[1, 2, 3, 4]], np.int64)
    got = _run("sequence_enumerate", {"X": ids},
               {"win_size": 2, "pad_value": 0})
    expect = np.array([[[1, 2], [2, 3], [3, 4], [4, 0]]])
    np.testing.assert_array_equal(np.asarray(got), expect)

    a = R.randn(2, 2, 3).astype("float32")
    b = R.randn(2, 3, 3).astype("float32")
    _case("sequence_concat", {"X": [("sa", a), ("sb", b)]},
          {"Out": np.concatenate([a, b], axis=1)}, atol=1e-6,
          grad=("sa", "sb"))


def test_roi_pool_and_align(R):
    x = np.arange(36, dtype=np.float32).reshape(1, 1, 6, 6)
    rois = np.array([[0, 0, 4, 4]], np.float32)
    got = _run("roi_pool", {"X": x, "ROIs": rois},
               {"spatial_scale": 1.0, "pooled_height": 2,
                "pooled_width": 2}, out_slots=("Out",))
    # reference roi_pool_op.h: inclusive roi (w = x2-x1+1 = 5), bin
    # boundaries floor/ceil -> bin0 covers rows/cols 0..2, bin1 2..4
    region = x[0, 0, :5, :5]
    expect = np.array([[region[:3, :3].max(), region[:3, 2:5].max()],
                       [region[2:5, :3].max(), region[2:5, 2:5].max()]])
    np.testing.assert_allclose(np.asarray(got)[0, 0], expect)

    # roi_align: TWO channels so a swapped layout transpose cannot
    # pass; bin centers (1,1),(1,3),(3,1),(3,3) -> exact pixels
    x2 = np.stack([x[0, 0], x[0, 0] * 10 + 1])[None]  # 1,2,6,6
    centers = np.array([[x[0, 0, 1, 1], x[0, 0, 1, 3]],
                        [x[0, 0, 3, 1], x[0, 0, 3, 3]]])
    expect2 = np.stack([centers, centers * 10 + 1])[None]
    _case("roi_align", {"X": x2, "ROIs": rois}, {"Out": expect2},
          {"spatial_scale": 1.0, "pooled_height": 2,
           "pooled_width": 2}, atol=1e-5, grad=("X",),
          no_grad=("ROIs",))


def test_grid_sampler_and_affine_grid(R):
    x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    # identity grid: normalized coords over the output plane
    ys, xs = np.meshgrid(np.linspace(-1, 1, 4),
                         np.linspace(-1, 1, 4), indexing="ij")
    grid = np.stack([xs, ys], -1)[None].astype("float32")
    # TWO channels: the kernel returns NCHW (misc_ops.py transposes
    # back from its NHWC gather); identity grid must reproduce both
    # planes in place
    x2 = np.stack([x[0, 0], x[0, 0] * 3 - 2])[None]  # 1,2,4,4
    _case("grid_sampler", {"X": x2, "Grid": grid}, {"Output": x2},
          atol=1e-5, grad=("X",), no_grad=("Grid",))

    theta = np.array([[[1, 0, 0], [0, 1, 0]]], np.float32)  # identity
    ag = _run("affine_grid", {"Theta": theta},
              {"output_shape": [1, 1, 4, 4]}, out_slots=("Output",))
    np.testing.assert_allclose(np.asarray(ag), grid, atol=1e-5)

    # composition: identity affine grid + sampler == input
    got = _run("grid_sampler", {"X": x, "Grid": np.asarray(ag)},
               out_slots=("Output",))
    np.testing.assert_allclose(np.asarray(got), x, atol=1e-5)


def test_row_conv(R):
    # lookahead conv (reference row_conv_op.cc): out[t] = sum_{i=0..k}
    # x[t+i] * w[i] -- through the OpTest harness with fd grads
    x = R.randn(1, 5, 3).astype("float32")
    w = R.randn(3, 3).astype("float32")  # (ctx+1)=3 taps
    expect = np.zeros_like(x)
    for t in range(5):
        for i in range(3):
            if t + i < 5:
                expect[0, t] += x[0, t + i] * w[i]
    _case("row_conv", {"X": x, "Filter": w}, {"Out": expect},
          atol=1e-5, grad=("X", "Filter"))


def test_im2sequence(R):
    x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    got = _run("im2sequence", {"X": x},
               {"kernels": [2, 2], "strides": [2, 2],
                "paddings": [0, 0, 0, 0]})
    g = np.asarray(got)
    # 4 patches of 2x2, row-major
    expect = np.asarray([x[0, 0, i:i+2, j:j+2].reshape(-1)
                         for i in (0, 2) for j in (0, 2)])
    np.testing.assert_allclose(g.reshape(4, 4), expect, atol=1e-6)


if __name__ == "__main__":
    pytest.main([__file__, "-q"])


def test_hierarchical_sigmoid(R):
    """Oracle: full-tree path product of sigmoids (reference
    hierarchical_sigmoid_op.h / matrix_bit_code.h node derivation)."""
    b, d, v = 3, 4, 8
    x = (R.randn(b, d) * 0.5).astype("float32")
    w = (R.randn(v - 1, d) * 0.5).astype("float32")
    bias = (R.randn(v - 1) * 0.5).astype("float32")
    lab = R.randint(0, v, (b, 1)).astype("int64")

    def sig(t):
        return 1 / (1 + np.exp(-t))

    expect = np.zeros((b, 1), np.float32)
    for i in range(b):
        node = int(lab[i, 0]) + v - 1
        loss = 0.0
        while node > 0:
            parent = (node - 1) // 2
            code = 1.0 if node == 2 * parent + 2 else 0.0  # right child
            pre = float(x[i] @ w[parent] + bias[parent])
            p = sig(pre)
            prob = p if code else (1 - p)
            loss += -np.log(max(prob, 1e-12))
            node = parent
        expect[i, 0] = loss
    got = _run("hierarchical_sigmoid",
               {"X": x, "W": w, "Label": lab, "Bias": bias},
               {"num_classes": v}, out_slots=("Out", "PreOut"))[0]
    np.testing.assert_allclose(np.asarray(got), expect,
                               atol=1e-4, rtol=1e-4)


def test_sample_logits(R):
    """True classes ride first with exact logit gather; sampled tail
    stays within range; deterministic under a fixed seed."""
    b, c, ns, nt = 4, 20, 6, 1
    logits = R.randn(b, c).astype("float32")
    labels = R.randint(0, c, (b, nt)).astype("int64")
    outs = _run("sample_logits", {"Logits": logits, "Labels": labels},
                {"num_samples": ns, "seed": 9},
                out_slots=("SampledLogits", "SampledLabels",
                           "Samples", "Probabilities"))
    slog, slab, samples, probs = [np.asarray(o) for o in outs]
    assert slog.shape == (b, nt + ns)
    assert samples.shape == (b, nt + ns)
    np.testing.assert_array_equal(samples[:, :nt], labels)
    # the true class's sampled-axis position is recorded
    assert np.all(slab[:, 0] == 0)
    # gathered logits match (up to log-Q correction applied uniformly)
    corr = slog[:, :nt] - logits[np.arange(b), labels[:, 0]][:, None]
    np.testing.assert_allclose(corr - corr[0, 0], 0.0, atol=1e-5)
    # deterministic with the same seed
    outs2 = _run("sample_logits", {"Logits": logits, "Labels": labels},
                 {"num_samples": ns, "seed": 9},
                 out_slots=("SampledLogits", "SampledLabels",
                            "Samples", "Probabilities"))
    np.testing.assert_array_equal(samples, np.asarray(outs2[2]))


def test_interpolate_nearest_and_bilinear(R):
    x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    got = _run("interpolate", {"X": x},
               {"out_h": 8, "out_w": 8, "interp_method": "nearest",
                "align_corners": False})
    np.testing.assert_allclose(np.asarray(got),
                               x.repeat(2, 2).repeat(2, 3), atol=1e-6)
    got = _run("interpolate", {"X": x},
               {"out_h": 4, "out_w": 4, "interp_method": "bilinear",
                "align_corners": True})
    np.testing.assert_allclose(np.asarray(got), x, atol=1e-5)
