"""Structural TP rules derived from the program graph
(parallel/sharding.py derive_sharding_rules). VERDICT r2 #5: replace
the max(shape)>=1024 size heuristic with column-then-row Megatron
pairing read off the op graph, and assert the collective count — one
psum per down-projection, not one per matmul.
"""
import numpy as np
import pytest

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu as fluid
from paddle_tpu.models import transformer as T
from paddle_tpu.parallel.mesh import make_mesh, MeshConfig
from paddle_tpu.parallel.sharding import derive_sharding_rules


def _fresh():
    fluid._reset_global_scope()
    from paddle_tpu import unique_name
    unique_name.switch()


def _transformer(n_layers=2, with_optimizer=True):
    _fresh()
    main, startup, cost = T.build_program(
        seq_len=8, d_model=32, n_heads=2, n_layers=n_layers,
        d_inner=64, vocab=64, dropout_rate=0.0,
        with_optimizer=with_optimizer, learning_rate=0.5,
        warmup_steps=20)
    return main, startup, cost


class TestDerivedRules:
    def test_megatron_pairing_on_transformer(self):
        main, _, _ = _transformer()
        t = derive_sharding_rules(main).table
        # qkv / q / kv projections: column; out-projections: row
        assert t["enc0_self_qkv.w"] == P(None, "tp")
        assert t["enc0_self_out.w"] == P("tp", None)
        assert t["dec0_cross_q.w"] == P(None, "tp")
        assert t["dec0_cross_kv.w"] == P(None, "tp")
        assert t["dec0_cross_out.w"] == P("tp", None)
        # FFN pair: up column (+ sharded bias), down row (repl bias)
        assert t["enc0_fc1.w"] == P(None, "tp")
        assert t["enc0_fc1.b"] == P("tp")
        assert t["enc0_fc2.w"] == P("tp", None)
        assert "enc0_fc2.b" not in t
        # embeddings vocab-row; logits head vocab-column
        assert t["src_word_emb"] == P("tp", None)
        assert t["logits.w"] == P(None, "tp")
        # layer norms replicated (absent from the table)
        assert "enc0_a_ln.w" not in t

    def test_residual_escape_blocks_column_sharding(self):
        """An fc whose output feeds a residual add (not another
        projection) must stay replicated — a column shard there would
        gather per matmul."""
        _fresh()
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            x = fluid.layers.data(name="x", shape=[16],
                                  dtype="float32")
            y = fluid.layers.data(name="y", shape=[1], dtype="int64")
            h = fluid.layers.fc(
                x, size=16, act="relu",
                param_attr=fluid.ParamAttr(name="solo_w"),
                bias_attr=False)
            h = fluid.layers.elementwise_add(h, x)   # residual escape
            logits = fluid.layers.fc(
                h, size=4, param_attr=fluid.ParamAttr(name="head_w"),
                bias_attr=False)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, y))
            fluid.optimizer.SGD(0.1).minimize(loss)
        t = derive_sharding_rules(prog).table
        assert "solo_w" not in t
        assert "head_w" not in t

    def test_plain_ffn_pair_detected(self):
        _fresh()
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            x = fluid.layers.data(name="x", shape=[16],
                                  dtype="float32")
            y = fluid.layers.data(name="y", shape=[1], dtype="int64")
            h = fluid.layers.fc(
                x, size=64, act="relu",
                param_attr=fluid.ParamAttr(name="up_w"),
                bias_attr=fluid.ParamAttr(name="up_b"))
            h = fluid.layers.fc(
                h, size=16, param_attr=fluid.ParamAttr(name="down_w"),
                bias_attr=fluid.ParamAttr(name="down_b"))
            logits = fluid.layers.fc(h, size=4, bias_attr=False)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, y))
            fluid.optimizer.SGD(0.1).minimize(loss)
        t = derive_sharding_rules(prog).table
        assert t["up_w"] == P(None, "tp")
        assert t["up_b"] == P("tp")
        assert t["down_w"] == P("tp", None)
        assert "down_b" not in t


def _sharded_train_setup(mesh, rules):
    import __graft_entry__ as g

    main, startup, cost = _transformer()
    state = g._build_state(startup)
    feed_names = ("label", "src_ids", "tgt_ids")
    step, mutated, const = g._make_step(main, feed_names, [cost.name])

    def place(name, val):
        from paddle_tpu.parallel.sharding import safe_spec

        if mesh is None:
            return val
        shape = getattr(val, "shape", ())
        spec = safe_spec(mesh, rules.spec_for(name, len(shape)), shape)
        return jax.device_put(val, NamedSharding(mesh, spec))

    mut = {n: place(n, state[n]) for n in mutated}
    const_st = {n: place(n, state[n]) for n in const}
    r = np.random.RandomState(0)
    feeds = {k: r.randint(0, 64, (8, 8)).astype(np.int32)
             for k in ("src_ids", "tgt_ids", "label")}
    if mesh is not None:
        feeds = {k: jax.device_put(v, NamedSharding(mesh, P("dp")))
                 for k, v in feeds.items()}
    rng = jax.random.PRNGKey(0)
    return step, mut, const_st, feeds, rng


class TestShardedExecution:
    def test_tp2_losses_match_unsharded(self):
        mesh = make_mesh(MeshConfig(dp=2, tp=2),
                         devices=jax.devices()[:4])
        main, startup, cost = _transformer()
        rules = derive_sharding_rules(main)
        step, mut, const_st, feeds, rng = _sharded_train_setup(
            mesh, rules)
        with mesh:
            jitted = jax.jit(step)
            losses_tp = []
            st = mut
            for _ in range(3):
                st, fetches, rng = jitted(st, const_st, feeds, rng)
                losses_tp.append(
                    float(np.asarray(fetches[0]).reshape(-1)[0]))

        # unsharded single-device baseline
        step2, mut2, const2, feeds2, rng2 = _sharded_train_setup(
            None, rules)
        feeds2 = {k: np.asarray(v) for k, v in feeds2.items()}
        jitted2 = jax.jit(step2)
        losses_1 = []
        st = mut2
        for _ in range(3):
            st, fetches, rng2 = jitted2(st, const2, feeds2, rng2)
            losses_1.append(
                float(np.asarray(fetches[0]).reshape(-1)[0]))
        np.testing.assert_allclose(losses_tp, losses_1, rtol=2e-4,
                                   atol=2e-5)

    def test_collective_count_one_psum_per_down_proj(self):
        """The point of column-then-row pairing: the FORWARD pass
        all-reduces once per row-projection (+ the embedding gathers
        and the vocab-parallel loss), nowhere near once per matmul."""
        mesh = make_mesh(MeshConfig(tp=2), devices=jax.devices()[:2])
        main, startup, cost = _transformer(with_optimizer=False)
        rules = derive_sharding_rules(main)
        n_muls = sum(1 for op in main.global_block.ops
                     if op.type == "mul")
        row_projs = [k for k, v in rules.table.items()
                     if v == P("tp", None) and not k.endswith("emb")]

        import __graft_entry__ as g
        state = g._build_state(startup)
        feed_names = ("label", "src_ids", "tgt_ids")
        step, mutated, const = g._make_step(main, feed_names,
                                            [cost.name])

        def place(name, val):
            from paddle_tpu.parallel.sharding import safe_spec

            shape = getattr(val, "shape", ())
            spec = safe_spec(mesh, rules.spec_for(name, len(shape)),
                             shape)
            return jax.device_put(val, NamedSharding(mesh, spec))

        mut = {n: place(n, state[n]) for n in mutated}
        const_st = {n: place(n, state[n]) for n in const}
        r = np.random.RandomState(0)
        feeds = {k: jax.device_put(
            r.randint(0, 64, (8, 8)).astype(np.int32),
            NamedSharding(mesh, P()))
            for k in ("src_ids", "tgt_ids", "label")}
        rng = jax.random.PRNGKey(0)
        with mesh:
            compiled = jax.jit(step).lower(
                mut, const_st, feeds, rng).compile()
        hlo = compiled.as_text()
        n_ar = hlo.count("all-reduce(") + hlo.count("all-reduce-start(")
        # forward-only: expect ~1 all-reduce per row projection plus a
        # small constant for embeddings + vocab-parallel loss; far
        # below one per matmul
        assert n_ar >= len(row_projs) // 2, (n_ar, len(row_projs))
        assert n_ar <= len(row_projs) + 8, (n_ar, len(row_projs))
        assert n_ar < n_muls, (n_ar, n_muls)


class TestDerivedRulesInheritance:
    def test_optimizer_accumulators_inherit_param_spec(self):
        main, _, _ = _transformer()
        rules = derive_sharding_rules(main)
        # moment accumulators are param-shaped -> param's spec
        assert rules.spec_for("enc0_fc1.w_moment1_0", 2) == \
            P(None, "tp")
        assert rules.spec_for("enc0_self_out.w_moment2_0", 2) == \
            P("tp", None)
        # rank-1 beta-pow accumulators can't take a rank-2 spec
        assert rules.spec_for("enc0_fc1.w_beta1_pow_acc_0", 1) == P()
        # a bias accumulator of shape (1,) inherits P('tp') by name but
        # safe_spec replicates it (1 % tp != 0)
        from paddle_tpu.parallel.mesh import make_mesh, MeshConfig
        from paddle_tpu.parallel.sharding import safe_spec
        import jax as _jax
        m = make_mesh(MeshConfig(tp=2), devices=_jax.devices()[:2])
        assert safe_spec(m, rules.spec_for("enc0_fc1.b_beta1_pow_acc_0",
                                           1), (1,)) == P()

    def test_table_is_exhaustive_no_size_heuristic(self):
        from paddle_tpu.parallel.sharding import spec_for_param
        main, _, _ = _transformer()
        rules = derive_sharding_rules(main)
        # a big 2-D weight the structural pass left replicated must
        # STAY replicated through spec_for_param (no size heuristic)
        assert spec_for_param("some_escaped_w", (2048, 2048),
                              rules) == P()


class TestLoudFailureModes:
    """VERDICT r3 weak #6/#7 + ADVICE #4: TP failure modes must warn,
    name-extension params must not inherit, pre-norm gets real TP."""

    def test_pre_norm_transformer_gets_tp_rules(self):
        """Pre-norm (LN before each sublayer, plain residual after)
        must yield the same Megatron pairing as post-norm: the pair
        chase starts at the projection, so the LN sits OUTSIDE the
        chased path."""
        _fresh()
        from paddle_tpu.models.transformer import (multi_head_attention,
                                                   _ffn)
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            src = fluid.layers.data(name="src", shape=[8],
                                    dtype="int64")
            label = fluid.layers.data(name="label", shape=[8],
                                      dtype="int64")
            emb = fluid.layers.embedding(
                src, size=[64, 32],
                param_attr=fluid.ParamAttr(name="pn_emb"))
            x = emb
            for li in range(2):
                h = fluid.layers.layer_norm(
                    x, begin_norm_axis=2,
                    param_attr=f"pn{li}_ln1.w",
                    bias_attr=f"pn{li}_ln1.b")
                attn = multi_head_attention(h, h, 32, 2, 0.0,
                                            is_test=True,
                                            name=f"pn{li}_self")
                x = fluid.layers.elementwise_add(x, attn)
                h = fluid.layers.layer_norm(
                    x, begin_norm_axis=2,
                    param_attr=f"pn{li}_ln2.w",
                    bias_attr=f"pn{li}_ln2.b")
                ffn = _ffn(h, 32, 128, 0.0, True, name=f"pn{li}")
                x = fluid.layers.elementwise_add(x, ffn)
            logits = fluid.layers.fc(x, 64, num_flatten_dims=2,
                                     bias_attr=False,
                                     param_attr="pn_logits.w")
            cost = fluid.layers.softmax_with_cross_entropy(
                logits, fluid.layers.unsqueeze(label, [2]))
            fluid.layers.mean(cost)
        t = derive_sharding_rules(prog).table
        for li in range(2):
            assert t[f"pn{li}_self_qkv.w"] == P(None, "tp")
            assert t[f"pn{li}_self_out.w"] == P("tp", None)
            assert t[f"pn{li}_fc1.w"] == P(None, "tp")
            assert t[f"pn{li}_fc2.w"] == P("tp", None)
        assert t["pn_emb"] == P("tp", None)
        assert t["pn_logits.w"] == P(None, "tp")

    def test_safe_spec_warns_on_real_downgrade(self):
        import warnings as w
        from paddle_tpu.parallel.mesh import make_mesh, MeshConfig
        from paddle_tpu.parallel.sharding import (safe_spec,
                                                  _downgrade_warned)
        m = make_mesh(MeshConfig(tp=8), devices=jax.devices()[:8])
        _downgrade_warned.clear()
        with w.catch_warnings(record=True) as rec:
            w.simplefilter("always")
            # 100 % 8 != 0 -> downgrade, real param -> warn
            assert safe_spec(m, P(None, "tp"), (32, 100),
                             name="odd_w") == P()
        assert any("odd_w" in str(r.message) for r in rec)
        # trivial (1,)-dim accumulator downgrade stays silent
        with w.catch_warnings(record=True) as rec:
            w.simplefilter("always")
            assert safe_spec(m, P("tp"), (1,), name="b_beta_pow") == P()
        assert not rec

    def test_empty_table_warns_on_projection_heavy_program(self):
        import warnings as w
        _fresh()
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            x = fluid.layers.data(name="x", shape=[16],
                                  dtype="float32")
            y = fluid.layers.data(name="y", shape=[1], dtype="int64")
            h = x
            # four chained fc+residual blocks: every pair chase escapes
            for i in range(4):
                f = fluid.layers.fc(
                    h, size=16, act="relu",
                    param_attr=fluid.ParamAttr(name=f"res{i}_w"),
                    bias_attr=False)
                h = fluid.layers.elementwise_add(f, h)
            logits = fluid.layers.fc(h, size=4, bias_attr=False)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, y))
        with w.catch_warnings(record=True) as rec:
            w.simplefilter("always")
            t = derive_sharding_rules(prog).table
        assert not t
        assert any("no tensor-parallel rules" in str(r.message)
                   for r in rec)

    def test_name_extension_param_does_not_inherit(self):
        """ADVICE #4: fc_w_scale must not inherit fc_w's spec — only
        the optimizer-accumulator naming pattern inherits."""
        from paddle_tpu.parallel.sharding import DerivedRules
        rules = DerivedRules({"fc_w": P(None, "tp")})
        # accumulator pattern inherits
        assert rules.spec_for("fc_w_moment1_0", 2) == P(None, "tp")
        assert rules.spec_for("fc_w_velocity_0", 2) == P(None, "tp")
        # arbitrary name extensions do NOT
        assert rules.spec_for("fc_w_scale", 2) == P()
        assert rules.spec_for("fc_w_scale_0", 2) == P()
        assert rules.spec_for("fc_w_mask", 2) == P()
