"""Control-flow lowering + decode/structured-loss ops.

Mirrors the reference's test_while_op.py, test_beam_search_op.py,
test_edit_distance_op.py, test_warpctc_op.py, test_linear_chain_crf_op.py,
test_crf_decoding_op.py, test_nce.py, test_hsigmoid.py (reference
python/paddle/fluid/tests/unittests/) — numpy oracles computed in-test,
framework output compared against them.
"""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers


def _run(main, startup, feed, fetch):
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    return exe.run(main, feed=feed, fetch_list=fetch)


class TestWhile:
    def test_while_sums_to_limit(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            i = layers.fill_constant([1], "float32", 0.0)
            acc = layers.fill_constant([1], "float32", 0.0)
            limit = layers.fill_constant([1], "float32", 10.0)
            cond = layers.less_than(i, limit)
            w = layers.While(cond)
            with w.block():
                layers.increment(acc, 2.0)
                layers.increment(i, 1.0)
                layers.less_than(i, limit, cond=cond)
        out, = _run(main, startup, {}, [acc])
        assert float(np.ravel(out)[0]) == pytest.approx(20.0)

    def test_while_with_external_read(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = layers.data(name="x", shape=[4], dtype="float32")
            i = layers.fill_constant([1], "float32", 0.0)
            acc = layers.fill_constant([1, 4], "float32", 0.0)
            limit = layers.fill_constant([1], "float32", 3.0)
            cond = layers.less_than(i, limit)
            w = layers.While(cond)
            with w.block():
                s = layers.elementwise_add(acc, x)
                layers.assign(s, acc)
                layers.increment(i, 1.0)
                layers.less_than(i, limit, cond=cond)
        xv = np.arange(4, dtype="float32").reshape(1, 4)
        out, = _run(main, startup, {"x": xv}, [acc])
        np.testing.assert_allclose(np.asarray(out), 3 * xv)


class TestCond:
    def test_cond_branches(self):
        for flag, expect in ((1.0, 30.0), (-1.0, 8.0)):
            main, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(main, startup):
                p = layers.data(name="p", shape=[1], dtype="float32",
                                append_batch_size=False)
                zero = layers.fill_constant([1], "float32", 0.0)
                pred = layers.greater_than(p, zero)
                out = layers.cond(
                    pred,
                    lambda: layers.fill_constant([1], "float32", 30.0),
                    lambda: layers.fill_constant([1], "float32", 8.0))
            got, = _run(main, startup,
                        {"p": np.asarray([flag], "float32")}, [out])
            assert float(np.ravel(got)[0]) == expect


class TestTensorArray:
    def test_write_read_stack(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = layers.data(name="x", shape=[3], dtype="float32")
            arr = layers.create_array("float32")
            i0 = layers.fill_constant([1], "int64", 0)
            i1 = layers.fill_constant([1], "int64", 1)
            layers.array_write(x, i0, array=arr)
            two = layers.scale(x, scale=2.0)
            layers.array_write(two, i1, array=arr)
            n = layers.array_length(arr)
            back = layers.array_read(arr, i1)
        xv = np.ones((2, 3), "float32")
        nv, bv = _run(main, startup, {"x": xv}, [n, back])
        assert int(np.ravel(nv)[0]) == 2
        np.testing.assert_allclose(np.asarray(bv), 2 * xv)


class TestBeamSearch:
    def test_step_and_decode(self):
        # 1 batch, beam 2, vocab 5; hand-computed oracle
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            pre_ids = layers.data(name="pre_ids", shape=[2, 1],
                                  dtype="int64", append_batch_size=False)
            pre_scores = layers.data(name="pre_scores", shape=[2, 1],
                                     dtype="float32",
                                     append_batch_size=False)
            ids = layers.data(name="ids", shape=[2, 3], dtype="int64",
                              append_batch_size=False)
            scores = layers.data(name="scores", shape=[2, 3],
                                 dtype="float32", append_batch_size=False)
            s_ids, s_scores, parent = layers.beam_search(
                pre_ids, pre_scores, ids, scores, beam_size=2, end_id=0,
                is_accumulated=False, return_parent_idx=True)
        feed = {
            "pre_ids": np.array([[1], [2]], dtype="int64"),
            "pre_scores": np.array([[-1.0], [-2.0]], dtype="float32"),
            "ids": np.array([[3, 4, 2], [4, 2, 1]], dtype="int64"),
            # raw per-step probabilities: the op accumulates
            # pre + log(p) itself under is_accumulated=False
            "scores": np.array([[0.6, 0.3, 0.1],
                                [0.5, 0.3, 0.2]], "float32"),
        }
        si, ss, pi = _run(main, startup, feed, [s_ids, s_scores, parent])
        # candidates: beam0: -1+log(.6/.3/.1); beam1: -2+log(.5/.3/.2)
        # best two: beam0 tok3 (-1.51), beam0 tok4 (-2.20)
        assert list(np.ravel(si)) == [3, 4]
        assert list(np.ravel(pi)) == [0, 0]
        np.testing.assert_allclose(
            np.ravel(ss), [-1 + np.log(0.6), -1 + np.log(0.3)],
            rtol=1e-5)

    def test_finished_beam_frozen(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            pre_ids = layers.data(name="pre_ids", shape=[2, 1],
                                  dtype="int64", append_batch_size=False)
            pre_scores = layers.data(name="pre_scores", shape=[2, 1],
                                     dtype="float32",
                                     append_batch_size=False)
            ids = layers.data(name="ids", shape=[2, 2], dtype="int64",
                              append_batch_size=False)
            scores = layers.data(name="scores", shape=[2, 2],
                                 dtype="float32", append_batch_size=False)
            s_ids, s_scores = layers.beam_search(
                pre_ids, pre_scores, ids, scores, beam_size=2, end_id=0,
                is_accumulated=False)
        feed = {
            "pre_ids": np.array([[0], [2]], dtype="int64"),  # beam0 done
            "pre_scores": np.array([[-0.5], [-3.0]], dtype="float32"),
            "ids": np.array([[3, 4], [4, 2]], dtype="int64"),
            "scores": np.exp(np.array([[-0.1, -0.2],
                                       [-0.4, -0.9]], "float32")),
        }
        si, ss = _run(main, startup, feed, [s_ids, s_scores])
        # finished beam keeps end_id at unchanged score -0.5 (best)
        assert np.ravel(si)[0] == 0
        assert np.ravel(ss)[0] == pytest.approx(-0.5)

    def test_decode_backtrack(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            ids = layers.data(name="ids", shape=[2, 2, 1], dtype="int64",
                              append_batch_size=False)
            parents = layers.data(name="par", shape=[2, 2, 1],
                                  dtype="int64", append_batch_size=False)
            scores = layers.data(name="sc", shape=[2, 2, 1],
                                 dtype="float32", append_batch_size=False)
            out_ids, out_scores = layers.beam_search_decode(
                ids, scores, beam_size=2, end_id=0)
            # wire parents through the op's optional input
            main.global_block.ops[-1].inputs["Parents"] = ["par"]
        # step0 picks tokens [5, 6]; step1 beams both extend beam 1
        feed = {
            "ids": np.array([[[5], [6]], [[7], [8]]], "int64"),
            "par": np.array([[[0], [1]], [[1], [1]]], "int64"),
            "sc": np.array([[[-1.], [-2.]], [[-3.], [-4.]]], "float32"),
        }
        oi, osc = _run(main, startup, feed, [out_ids, out_scores])
        oi = np.asarray(oi)  # [T, rows]
        # row0 final: step1 tok 7 from parent beam 1 (tok 6)
        assert list(oi[:, 0]) == [6, 7]
        assert list(oi[:, 1]) == [6, 8]
        np.testing.assert_allclose(np.ravel(osc), [-3.0, -4.0])


class TestEditDistance:
    @staticmethod
    def _lev(a, b):
        la, lb = len(a), len(b)
        d = np.zeros((la + 1, lb + 1))
        d[:, 0] = np.arange(la + 1)
        d[0, :] = np.arange(lb + 1)
        for i in range(1, la + 1):
            for j in range(1, lb + 1):
                d[i, j] = min(d[i - 1, j] + 1, d[i, j - 1] + 1,
                              d[i - 1, j - 1] + (a[i - 1] != b[j - 1]))
        return d[la, lb]

    def test_matches_numpy_oracle(self):
        rng = np.random.RandomState(0)
        hyps = rng.randint(1, 6, (4, 7)).astype("int64")
        refs = rng.randint(1, 6, (4, 9)).astype("int64")
        hlen = np.array([7, 5, 3, 1], "int32")
        rlen = np.array([9, 4, 3, 2], "int32")
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            h = layers.data(name="h", shape=[4, 7], dtype="int64",
                            append_batch_size=False)
            r = layers.data(name="r", shape=[4, 9], dtype="int64",
                            append_batch_size=False)
            dist, seq_num = layers.edit_distance(h, r, normalized=False)
        feed = {"h": hyps, "r": refs, "h@SEQ_LEN": hlen,
                "r@SEQ_LEN": rlen}
        out, n = _run(main, startup, feed, [dist, seq_num])
        expect = [self._lev(hyps[i, :hlen[i]], refs[i, :rlen[i]])
                  for i in range(4)]
        np.testing.assert_allclose(np.ravel(out), expect)
        assert int(np.ravel(n)[0]) == 4


class TestCTC:
    def test_ctc_align_greedy_decode(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = layers.data(name="x", shape=[2, 6], dtype="int64",
                            append_batch_size=False)
            out = layers.ctc_greedy_decoder(x, blank=0)
        xv = np.array([[1, 1, 0, 2, 2, 0],
                       [0, 3, 0, 3, 3, 1]], dtype="int64")
        feed = {"x": xv, "x@SEQ_LEN": np.array([6, 6], "int32")}
        got, = _run(main, startup, feed, [out])
        got = np.asarray(got)
        assert list(got[0][:2]) == [1, 2]
        assert list(got[1][:3]) == [3, 3, 1]

    @staticmethod
    def _ctc_loss_brute(logits, label, blank):
        # brute-force: sum prob over all alignments (tiny T)
        from itertools import product
        t, c = logits.shape
        p = np.exp(logits - logits.max(-1, keepdims=True))
        p = p / p.sum(-1, keepdims=True)

        def collapse(path):
            out = []
            prev = -1
            for s in path:
                if s != prev and s != blank:
                    out.append(s)
                prev = s
            return out

        total = 0.0
        for path in product(range(c), repeat=t):
            if collapse(path) == list(label):
                pr = 1.0
                for i, s in enumerate(path):
                    pr *= p[i, s]
                total += pr
        return -np.log(total)

    def test_warpctc_matches_bruteforce(self):
        rng = np.random.RandomState(3)
        t, c = 4, 3
        logits = rng.randn(1, t, c).astype("float32")
        label = np.array([[1, 2]], dtype="int64")
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            lg = layers.data(name="lg", shape=[1, t, c], dtype="float32",
                             append_batch_size=False)
            lb = layers.data(name="lb", shape=[1, 2], dtype="int64",
                             append_batch_size=False)
            loss = layers.warpctc(lg, lb, blank=0)
        feed = {"lg": logits, "lb": label,
                "lg@SEQ_LEN": np.array([t], "int32"),
                "lb@SEQ_LEN": np.array([2], "int32")}
        got, = _run(main, startup, feed, [loss])
        expect = self._ctc_loss_brute(logits[0], label[0], 0)
        np.testing.assert_allclose(np.ravel(got)[0], expect, rtol=1e-4)

    def test_warpctc_trains(self):
        rng = np.random.RandomState(0)
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            feat = layers.data(name="feat", shape=[2, 8, 16],
                               dtype="float32", append_batch_size=False)
            lb = layers.data(name="lb", shape=[2, 3], dtype="int64",
                             append_batch_size=False)
            logits = layers.fc(feat, size=5, num_flatten_dims=2)
            layers.sequence.bind_seq_len(logits, feat)
            loss = layers.mean(layers.warpctc(logits, lb, blank=0))
            fluid.optimizer.Adam(0.05).minimize(loss)
        feat = rng.randn(2, 8, 16).astype("float32")
        lb = np.array([[1, 2, 3], [2, 1, 4]], "int64")
        feed = {"feat": feat, "lb": lb,
                "logits" : None}
        feed.pop("logits")
        feed["feat@SEQ_LEN"] = np.array([8, 8], "int32")
        feed["lb@SEQ_LEN"] = np.array([3, 3], "int32")
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        ls = [float(np.ravel(exe.run(main, feed=feed,
                                     fetch_list=[loss])[0])[0])
              for _ in range(25)]
        assert ls[-1] < ls[0] * 0.5


class TestCRF:
    @staticmethod
    def _crf_oracle(em, trans, label):
        # enumerate all paths (tiny)
        from itertools import product
        t, c = em.shape
        start_w, end_w, pair = trans[0], trans[1], trans[2:]

        def score(path):
            s = start_w[path[0]] + em[0, path[0]] + end_w[path[-1]]
            for i in range(1, t):
                s += pair[path[i - 1], path[i]] + em[i, path[i]]
            return s

        logz = np.log(sum(np.exp(score(p))
                          for p in product(range(c), repeat=t)))
        best = max(product(range(c), repeat=t), key=score)
        return score(tuple(label)) - logz, list(best)

    def test_crf_ll_and_viterbi(self):
        rng = np.random.RandomState(1)
        t, c = 4, 3
        em = rng.randn(1, t, c).astype("float32")
        trans = (0.1 * rng.randn(c + 2, c)).astype("float32")
        label = np.array([[0, 2, 1, 1]], dtype="int64")
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            e = layers.data(name="e", shape=[1, t, c], dtype="float32",
                            append_batch_size=False)
            tr = layers.data(name="tr", shape=[c + 2, c],
                             dtype="float32", append_batch_size=False)
            lb = layers.data(name="lb", shape=[1, t], dtype="int64",
                             append_batch_size=False)
            nll = layers.linear_chain_crf_raw(e, tr, lb)
            path = layers.crf_decoding_raw(e, tr)
        feed = {"e": em, "tr": trans, "lb": label}
        got_nll, got_path = _run(main, startup, feed, [nll, path])
        ll, best = self._crf_oracle(em[0], trans, label[0])
        np.testing.assert_allclose(np.ravel(got_nll)[0], -ll, rtol=1e-4)
        assert list(np.asarray(got_path)[0]) == best


class TestSampledLosses:
    def _train(self, build_loss, steps=30, lr=0.1):
        rng = np.random.RandomState(0)
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = layers.data(name="x", shape=[16], dtype="float32")
            y = layers.data(name="y", shape=[1], dtype="int64")
            loss = build_loss(x, y)
            fluid.optimizer.Adam(lr).minimize(loss)
        X = rng.randn(32, 16).astype("float32")
        Y = rng.randint(0, 8, (32, 1)).astype("int64")
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        ls = [float(np.ravel(exe.run(main, feed={"x": X, "y": Y},
                                     fetch_list=[loss])[0])[0])
              for _ in range(steps)]
        return ls

    def test_nce_trains(self):
        ls = self._train(lambda x, y: layers.mean(
            layers.nce(x, y, num_total_classes=8, num_neg_samples=4)))
        assert ls[-1] < ls[0] * 0.7

    def test_hsigmoid_trains(self):
        ls = self._train(lambda x, y: layers.mean(
            layers.hsigmoid(x, y, num_classes=8)))
        assert ls[-1] < ls[0] * 0.7

    def test_sampled_softmax_trains(self):
        ls = self._train(lambda x, y: layers.mean(
            layers.sampled_softmax_with_cross_entropy(
                layers.fc(x, size=8), y, num_samples=4)))
        assert ls[-1] < ls[0] * 0.9


class TestReviewRegressions:
    def test_while_write_only_var_persists(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            i = layers.fill_constant([1], "float32", 0.0)
            s = layers.fill_constant([1], "float32", -7.0)
            limit = layers.fill_constant([1], "float32", 3.0)
            cond = layers.less_than(i, limit)
            w = layers.While(cond)
            with w.block():
                t = layers.scale(i, scale=10.0)
                layers.assign(t, s)  # write-only from the loop's view
                layers.increment(i, 1.0)
                layers.less_than(i, limit, cond=cond)
        out, = _run(main, startup, {}, [s])
        assert float(np.ravel(out)[0]) == pytest.approx(20.0)

    def test_while_unwritten_condition_rejected(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            i = layers.fill_constant([1], "float32", 0.0)
            limit = layers.fill_constant([1], "float32", 3.0)
            cond = layers.less_than(i, limit)
            w = layers.While(cond)
            with pytest.raises(ValueError, match="condition"):
                with w.block():
                    layers.increment(i, 1.0)  # forgot to update cond

    def test_beam_search_decode_public_api_with_parents(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            ids = layers.data(name="ids", shape=[2, 2], dtype="int64",
                              append_batch_size=False)
            parents = layers.data(name="par", shape=[2, 2],
                                  dtype="int64",
                                  append_batch_size=False)
            scores = layers.data(name="sc", shape=[2, 2],
                                 dtype="float32",
                                 append_batch_size=False)
            out_ids, _ = layers.beam_search_decode(
                ids, scores, beam_size=2, end_id=0, parents=parents)
        feed = {"ids": np.array([[5, 6], [7, 8]], "int64"),
                "par": np.array([[0, 1], [1, 1]], "int64"),
                "sc": np.array([[-1.0, -2.0], [-3.0, -4.0]], "float32")}
        oi, = _run(main, startup, feed, [out_ids])
        assert list(np.asarray(oi)[:, 0]) == [6, 7]

    def test_beam_search_decode_without_parents(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            ids = layers.data(name="ids", shape=[2, 2], dtype="int64",
                              append_batch_size=False)
            scores = layers.data(name="sc", shape=[2, 2],
                                 dtype="float32",
                                 append_batch_size=False)
            out_ids, _ = layers.beam_search_decode(ids, scores,
                                                   beam_size=2, end_id=0)
        feed = {"ids": np.array([[5, 6], [7, 8]], "int64"),
                "sc": np.array([[-1.0, -2.0], [-3.0, -4.0]], "float32")}
        oi, = _run(main, startup, feed, [out_ids])
        # identity lineage: column i is just ids[:, i]
        assert list(np.asarray(oi)[:, 0]) == [5, 7]
