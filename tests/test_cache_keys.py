"""Executable-cache identity regressions (VERDICT r3 weak #3 /
ADVICE #1): id()-keyed caches are unsound — a GC'd Program/Mesh whose
address is reused by a new object (whose _version also starts at 0)
must NOT be served a stale executable. Keys now use a process-unique
Program._uid and a structural mesh token."""
import gc

import numpy as np
import pytest

import jax
from jax.sharding import Mesh, PartitionSpec as P

import paddle_tpu as fluid


def _fresh():
    fluid._reset_global_scope()
    from paddle_tpu import unique_name
    unique_name.switch()


def _build(scale):
    """A one-op program: out = x * scale (scale baked as attr)."""
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        out = fluid.layers.scale(x, scale=scale)
    return prog, startup, out


class TestProgramUid:
    def test_uids_are_unique_and_survive_clone(self):
        p1 = fluid.Program()
        p2 = fluid.Program()
        assert p1._uid != p2._uid
        c = p1.clone()
        assert c._uid != p1._uid  # a clone is a DIFFERENT program

    def test_gc_lookalike_program_gets_fresh_compile(self):
        """Two same-shaped programs built/GC'd in sequence through ONE
        executor must produce their own numerics even if the second
        reuses the first's heap address (the id() bug this guards)."""
        _fresh()
        exe = fluid.Executor(fluid.TPUPlace())
        feed = {"x": np.ones((2, 4), np.float32)}

        prog1, startup1, out1 = _build(2.0)
        exe.run(startup1)
        r1 = exe.run(prog1, feed=feed, fetch_list=[out1])[0]
        addr1 = id(prog1)
        del prog1, startup1, out1
        gc.collect()

        # allocate lookalikes until one lands on the old address (or
        # give up — the uid key is correct either way; landing on the
        # address makes the regression real)
        progs = []
        hit = None
        for scale in range(3, 40):
            p, s, o = _build(float(scale))
            if id(p) == addr1:
                hit = (p, s, o, float(scale))
                break
            progs.append((p, s, o))
        if hit is None:
            # couldn't provoke address reuse; still assert basic
            # correctness of a second program through the same cache
            p, s, o = progs[0]
            exe.run(s)
            r2 = exe.run(p, feed=feed, fetch_list=[o])[0]
            np.testing.assert_allclose(r2, np.ones((2, 4)) * 3.0)
            return
        p, s, o, scale = hit
        assert p._version == 0  # same version as the dead program had
        exe.run(s)
        r2 = exe.run(p, feed=feed, fetch_list=[o])[0]
        np.testing.assert_allclose(r2, np.ones((2, 4)) * scale)
        np.testing.assert_allclose(r1, np.ones((2, 4)) * 2.0)


class TestRunStepsCacheKey:
    def test_version_bump_invalidates_scan_executable(self):
        """The K-step scan executable is cached under the program
        _uid/_version (plus feed specs / fetch set / K): mutating the
        program after a run_steps call -- same fetch name, same feed
        specs -- must recompile, not serve the stale scan (the same
        contract Pass.apply relies on for run())."""
        _fresh()
        exe = fluid.Executor(fluid.TPUPlace())
        feed = {"x": np.ones((2, 4), np.float32)}
        prog, startup, out = _build(2.0)
        exe.run(startup)
        r1 = exe.run_steps(prog, feed=feed, fetch_list=[out], steps=3)
        assert exe.last_run_steps_fallback is None
        np.testing.assert_allclose(np.asarray(r1[0]),
                                   np.full((3, 2, 4), 2.0))
        # in-place program mutation: rewrite the fetched var x10
        # (append_op bumps _version; feed specs and fetch set are
        # unchanged, so ONLY the version distinguishes the keys)
        v0 = prog._version
        prog.global_block.append_op(
            "scale", {"X": [out.name]}, {"Out": [out.name]},
            {"scale": 10.0})
        assert prog._version > v0
        r2 = exe.run_steps(prog, feed=feed, fetch_list=[out], steps=3)
        np.testing.assert_allclose(np.asarray(r2[0]),
                                   np.full((3, 2, 4), 20.0))

    def test_distinct_k_compiles_are_isolated(self):
        """steps=K is part of the key: a K=2 window then a K=4 window
        through one executor must each return their own stack."""
        _fresh()
        exe = fluid.Executor(fluid.TPUPlace())
        feed = {"x": np.ones((2, 4), np.float32)}
        prog, startup, out = _build(3.0)
        exe.run(startup)
        r2 = exe.run_steps(prog, feed=feed, fetch_list=[out], steps=2)
        r4 = exe.run_steps(prog, feed=feed, fetch_list=[out], steps=4)
        assert np.asarray(r2[0]).shape == (2, 2, 4)
        assert np.asarray(r4[0]).shape == (4, 2, 4)


class TestServingCompileBound:
    def test_mixed_traffic_compiles_at_most_bucket_count(self):
        """100 mixed-shape batch-of-1..4 requests through a 4-bucket
        InferenceServer produce AT MOST #buckets executables (the
        bucket ladder bounds the executable cache; unbucketed serving
        would compile one per distinct batch size). Uses the Executor
        compile counter."""
        from paddle_tpu.inference.serving import (InferenceServer,
                                                  ProgramRunner)

        _fresh()
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            x = fluid.layers.data("x", shape=[6], dtype="float32")
            h = fluid.layers.fc(x, size=8, act="relu")
            out = fluid.layers.fc(h, size=3)
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(startup)
        assert exe.compile_count == 1  # the startup program
        runner = ProgramRunner(prog, ["x"], [out.name], executor=exe,
                               scope=fluid.global_scope())
        r = np.random.RandomState(0)
        sizes = r.randint(1, 9, size=100)
        with InferenceServer(runner, max_batch_size=8,
                             max_wait_ms=1.0) as srv:
            assert srv.batch_buckets == [1, 2, 4, 8]
            replies = [srv.submit(
                {"x": r.randn(int(n), 6).astype(np.float32)})
                for n in sizes]
            outs = [rep.result(timeout=60.0) for rep in replies]
        for n, o in zip(sizes, outs):
            assert o[0].shape == (n, 3)
        # <= 4 serving executables on top of the startup compile
        assert exe.compile_count - 1 <= len(srv.batch_buckets), \
            f"compile_count={exe.compile_count}"


class TestMeshToken:
    def test_token_is_structural_not_identity(self):
        from paddle_tpu.core.executor import _mesh_token
        devs = np.array(jax.devices()[:4]).reshape(2, 2)
        m1 = Mesh(devs, ("dp", "tp"))
        tok1 = _mesh_token(m1)
        del m1
        gc.collect()
        m2 = Mesh(devs, ("dp", "tp"))
        assert _mesh_token(m2) == tok1  # same structure, same token
        m3 = Mesh(devs.reshape(4, 1), ("dp", "tp"))
        assert _mesh_token(m3) != tok1  # different shape, new token
        m4 = Mesh(devs, ("dp", "sp"))
        assert _mesh_token(m4) != tok1  # different axes, new token

    def test_scope_token_uses_mesh_token(self):
        """Entering context_parallel with a structurally different
        mesh must change the scope token (stale-executable guard)."""
        from paddle_tpu.core.executor import _parallel_scope_token
        from paddle_tpu.parallel.ring_attention import context_parallel
        devs = jax.devices()
        m_a = Mesh(np.array(devs[:2]), ("sp",))
        m_b = Mesh(np.array(devs[2:4]), ("sp",))
        with context_parallel(m_a, "sp"):
            tok_a = _parallel_scope_token()
        with context_parallel(m_b, "sp"):
            tok_b = _parallel_scope_token()
        assert tok_a != tok_b
        with context_parallel(m_a, "sp"):
            assert _parallel_scope_token() == tok_a
        assert _parallel_scope_token() == ()


class TestReconfigurePlacement:
    def test_state_replaced_on_config_epoch_change(self):
        """ADVICE #3: after a reconfiguring with_data_parallel(), state
        placed under the OLD config must be re-placed by the new rules
        (the executable cache is busted by the epoch; the scope arrays
        must follow)."""
        _fresh()
        from paddle_tpu.parallel.mesh import make_mesh, MeshConfig

        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            x = fluid.layers.data(name="x", shape=[16],
                                  dtype="float32")
            y = fluid.layers.data(name="y", shape=[1], dtype="int64")
            h = fluid.layers.fc(
                x, size=64, act="relu",
                param_attr=fluid.ParamAttr(name="up_w"),
                bias_attr=False)
            h = fluid.layers.fc(
                h, size=16, param_attr=fluid.ParamAttr(name="down_w"),
                bias_attr=False)
            logits = fluid.layers.fc(h, size=4, bias_attr=False)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, y))
            fluid.optimizer.SGD(0.1).minimize(loss)

        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(startup)
        feed = {"x": np.random.RandomState(0).randn(8, 16).astype(
            np.float32),
            "y": np.zeros((8, 1), np.int64)}

        mesh_tp = make_mesh(MeshConfig(dp=2, tp=2),
                            devices=jax.devices()[:4])
        cp = fluid.CompiledProgram(prog).with_data_parallel(
            loss_name=loss.name, mesh=mesh_tp)
        exe.run(cp, feed=feed, fetch_list=[loss])
        scope = fluid.global_scope()
        up_w = scope._get("up_w")
        spec = up_w.sharding.spec
        assert any(s == "tp" for s in spec), spec  # TP-sharded now

        # reconfigure to plain dp (tp=1): params must come back to
        # replicated, not stay sharded under the dead config
        mesh_dp = make_mesh(MeshConfig(dp=2),
                            devices=jax.devices()[:2])
        cp.with_data_parallel(loss_name=loss.name, mesh=mesh_dp)
        exe.run(cp, feed=feed, fetch_list=[loss])
        up_w2 = scope._get("up_w")
        spec2 = getattr(up_w2.sharding, "spec", P())
        assert not any(s == "tp" for s in tuple(spec2)), spec2
