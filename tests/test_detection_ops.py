"""Detection op family tests vs numpy oracles.

Parity model: reference tests/unittests/test_iou_similarity_op.py,
test_box_coder_op.py, test_prior_box_op.py, test_multiclass_nms_op.py,
test_bipartite_match_op.py, test_yolov3_loss_op.py (OpTest numeric
comparisons); shapes here are fixed/padded per the TPU design note in
ops/detection_ops.py.
"""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.layers import detection as det


def _run(fetches, feed=None):
    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(fluid.default_startup_program())
    return exe.run(feed=feed or {}, fetch_list=fetches)


def _np_iou(a, b):
    out = np.zeros((len(a), len(b)), np.float32)
    for i, x in enumerate(a):
        for j, y in enumerate(b):
            ix1, iy1 = max(x[0], y[0]), max(x[1], y[1])
            ix2, iy2 = min(x[2], y[2]), min(x[3], y[3])
            inter = max(ix2 - ix1, 0) * max(iy2 - iy1, 0)
            ua = ((x[2] - x[0]) * (x[3] - x[1])
                  + (y[2] - y[0]) * (y[3] - y[1]) - inter)
            out[i, j] = inter / ua if ua > 0 else 0
    return out


class TestGeometry:
    def test_iou_similarity_matches_numpy(self):
        rng = np.random.RandomState(0)
        a = np.sort(rng.uniform(0, 1, (5, 4)).astype(np.float32),
                    axis=-1)[:, [0, 2, 1, 3]]
        b = np.sort(rng.uniform(0, 1, (7, 4)).astype(np.float32),
                    axis=-1)[:, [0, 2, 1, 3]]
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        y = fluid.layers.data(name="y", shape=[4], dtype="float32")
        out = det.iou_similarity(x, y)
        got, = _run([out], {"x": a, "y": b})
        np.testing.assert_allclose(got, _np_iou(a, b), rtol=1e-5,
                                   atol=1e-6)

    def test_box_coder_roundtrip(self):
        rng = np.random.RandomState(1)
        prior = np.sort(rng.uniform(0, 1, (6, 4)).astype(np.float32),
                        axis=-1)[:, [0, 2, 1, 3]]
        pvar = np.full((6, 4), 0.1, np.float32)
        gt = np.sort(rng.uniform(0, 1, (3, 4)).astype(np.float32),
                     axis=-1)[:, [0, 2, 1, 3]]
        pb = fluid.layers.data(name="pb", shape=[4], dtype="float32")
        pv = fluid.layers.data(name="pv", shape=[4], dtype="float32")
        tb = fluid.layers.data(name="tb", shape=[4], dtype="float32")
        enc = det.box_coder(pb, pv, tb, code_type="encode_center_size")
        got_enc, = _run([enc], {"pb": prior, "pv": pvar, "tb": gt})
        assert got_enc.shape == (3, 6, 4)
        # decode the encodings back -> original gt boxes
        tb2 = fluid.layers.data(name="tb2", shape=[6, 4],
                                dtype="float32")
        dec = det.box_coder(pb, pv, tb2, code_type="decode_center_size")
        got_dec, = _run([dec], {"pb": prior, "pv": pvar, "tb": gt,
                                "tb2": got_enc})
        for i in range(3):
            for j in range(6):
                np.testing.assert_allclose(got_dec[i, j], gt[i],
                                           rtol=1e-4, atol=1e-5)

    def test_box_clip(self):
        boxes = np.array([[[-5.0, -5, 50, 50], [10, 10, 400, 300]]],
                         np.float32)
        im = np.array([[100.0, 200, 1.0]], np.float32)
        b = fluid.layers.data(name="b", shape=[2, 4], dtype="float32")
        i = fluid.layers.data(name="i", shape=[3], dtype="float32")
        out = det.box_clip(b, i)
        got, = _run([out], {"b": boxes, "i": im})
        assert got.min() >= 0
        assert got[0, 1, 2] == 199.0 and got[0, 1, 3] == 99.0


class TestPriors:
    def test_prior_box_shapes_and_centers(self):
        img = fluid.layers.data(name="img", shape=[3, 32, 32],
                                dtype="float32")
        feat = fluid.layers.data(name="feat", shape=[8, 4, 4],
                                 dtype="float32")
        box, var = det.prior_box(feat, img, min_sizes=[4.0],
                                 max_sizes=[8.0],
                                 aspect_ratios=[2.0], flip=True)
        bnp, vnp = _run(
            [box, var],
            {"img": np.zeros((1, 3, 32, 32), np.float32),
             "feat": np.zeros((1, 8, 4, 4), np.float32)})
        # priors: ar {1, 2, 0.5} + max_size sqrt box = 4 per cell
        assert bnp.shape == (4, 4, 4, 4)
        assert vnp.shape == (4, 4, 4, 4)
        # first cell center at offset 0.5 * step(8px) = (4, 4) px
        cx = (bnp[0, 0, 0, 0] + bnp[0, 0, 0, 2]) / 2 * 32
        assert cx == pytest.approx(4.0, abs=1e-4)
        assert (bnp >= -1).all() and (bnp <= 2).all()

    def test_density_prior_box_count(self):
        img = fluid.layers.data(name="img", shape=[3, 32, 32],
                                dtype="float32")
        feat = fluid.layers.data(name="feat", shape=[8, 4, 4],
                                 dtype="float32")
        box, var = det.density_prior_box(
            feat, img, densities=[2, 1], fixed_sizes=[4.0, 8.0],
            fixed_ratios=[1.0])
        bnp, = _run([box], {"img": np.zeros((1, 3, 32, 32), np.float32),
                            "feat": np.zeros((1, 8, 4, 4), np.float32)})
        # 2^2*1 + 1^2*1 = 5 priors per cell
        assert bnp.shape == (4, 4, 5, 4)

    def test_anchor_generator(self):
        feat = fluid.layers.data(name="feat", shape=[8, 4, 4],
                                 dtype="float32")
        anchors, var = det.anchor_generator(
            feat, anchor_sizes=[32.0], aspect_ratios=[1.0],
            stride=[16.0, 16.0])
        anp, = _run([anchors],
                    {"feat": np.zeros((1, 8, 4, 4), np.float32)})
        assert anp.shape == (4, 4, 1, 4)
        w = anp[0, 0, 0, 2] - anp[0, 0, 0, 0]
        assert w == pytest.approx(32.0, rel=1e-5)


class TestMatching:
    def test_bipartite_match_greedy(self):
        dist = np.array([[[0.9, 0.2, 0.1],
                          [0.8, 0.7, 0.3]]], np.float32)  # [1, 2, 3]
        d = fluid.layers.data(name="d", shape=[2, 3], dtype="float32")
        mi, md = det.bipartite_match(d)
        got_i, got_d = _run([mi, md], {"d": dist})
        # greedy: (row0,col0)=0.9 then (row1,col1)=0.7
        assert got_i[0].tolist() == [0, 1, -1]
        np.testing.assert_allclose(got_d[0], [0.9, 0.7, 0.0], rtol=1e-6)

    def test_bipartite_match_per_prediction(self):
        dist = np.array([[[0.9, 0.6, 0.1],
                          [0.2, 0.7, 0.3]]], np.float32)
        d = fluid.layers.data(name="d", shape=[2, 3], dtype="float32")
        mi, md = det.bipartite_match(d, match_type="per_prediction",
                                     dist_threshold=0.5)
        got_i, _ = _run([mi], {"d": dist}), None
        # col1: bipartite gives row1 (0.7); col0 row0; col2 best row is
        # row1 (0.3 < 0.5 threshold) -> unmatched
        assert got_i[0][0].tolist() == [0, 1, -1]

    def test_target_assign(self):
        x = np.arange(12, dtype=np.float32).reshape(3, 4)  # 3 gt rows
        match = np.array([[2, -1, 0, 1]], np.int32)
        xv = fluid.layers.data(name="xv", shape=[3, 4],
                               dtype="float32")
        xv.shape = (3, 4)  # static gt table
        mv = fluid.layers.data(name="mv", shape=[4], dtype="int32")
        out, w = det.target_assign(xv, mv, mismatch_value=0)
        got, gw = _run([out, w], {"xv": x, "mv": match})
        np.testing.assert_allclose(got[0, 0], x[2])
        np.testing.assert_allclose(got[0, 1], np.zeros(4))
        assert gw[0, :, 0].tolist() == [1.0, 0.0, 1.0, 1.0]


class TestNMS:
    def test_multiclass_nms_suppresses(self):
        # two overlapping boxes + one distinct, single class (class 1;
        # class 0 is background)
        boxes = np.array([[[0, 0, 10, 10], [1, 1, 11, 11],
                           [50, 50, 60, 60]]], np.float32)
        scores = np.zeros((1, 2, 3), np.float32)
        scores[0, 1] = [0.9, 0.8, 0.7]
        b = fluid.layers.data(name="b", shape=[3, 4], dtype="float32")
        s = fluid.layers.data(name="s", shape=[2, 3], dtype="float32")
        out = det.multiclass_nms(b, s, score_threshold=0.1,
                                 nms_top_k=3, keep_top_k=3,
                                 nms_threshold=0.5, normalized=False)
        got, = _run([out], {"b": boxes, "s": scores})
        assert got.shape == (1, 3, 6)
        kept = got[0][got[0, :, 0] >= 0]
        assert len(kept) == 2  # the 0.8 box suppressed by the 0.9 one
        assert kept[0, 1] == pytest.approx(0.9)
        assert kept[1, 1] == pytest.approx(0.7)
        np.testing.assert_allclose(kept[0, 2:], [0, 0, 10, 10])

    def test_background_class_excluded(self):
        boxes = np.array([[[0, 0, 10, 10]]], np.float32)
        scores = np.zeros((1, 2, 1), np.float32)
        scores[0, 0, 0] = 0.9  # background only
        b = fluid.layers.data(name="b", shape=[1, 4], dtype="float32")
        s = fluid.layers.data(name="s", shape=[2, 1], dtype="float32")
        out = det.multiclass_nms(b, s, score_threshold=0.1, nms_top_k=1,
                                 keep_top_k=1)
        got, = _run([out], {"b": boxes, "s": scores})
        assert (got[0, :, 0] == -1).all()


class TestYolo:
    def test_yolo_box_decodes(self):
        np.random.seed(0)
        xx = np.random.randn(1, 2 * 7, 2, 2).astype(np.float32)
        x = fluid.layers.data(name="x", shape=[14, 2, 2],
                              dtype="float32")
        sz = fluid.layers.data(name="sz", shape=[2], dtype="int32")
        boxes, scores = det.yolo_box(x, sz, anchors=[10, 13, 16, 30],
                                     class_num=2, conf_thresh=0.0,
                                     downsample_ratio=32)
        bnp, snp = _run([boxes, scores],
                        {"x": xx, "sz": np.array([[64, 64]], np.int32)})
        assert bnp.shape == (1, 8, 4)
        assert snp.shape == (1, 8, 2)
        assert (snp >= 0).all() and (snp <= 1).all()

    def test_yolov3_loss_positive_and_differentiable(self):
        np.random.seed(1)
        xx = np.random.randn(2, 3 * 7, 4, 4).astype(np.float32) * 0.5
        gtb = np.zeros((2, 2, 4), np.float32)
        gtb[:, 0] = [0.5, 0.5, 0.3, 0.4]  # cx cy w h in [0,1]
        gtl = np.zeros((2, 2), np.int32)
        x = fluid.layers.data(name="x", shape=[21, 4, 4],
                              dtype="float32")
        gb = fluid.layers.data(name="gb", shape=[2, 4],
                               dtype="float32")
        gl = fluid.layers.data(name="gl", shape=[2], dtype="int32")
        loss = det.yolov3_loss(x, gb, gl,
                               anchors=[10, 13, 16, 30, 33, 23],
                               anchor_mask=[0, 1, 2], class_num=2,
                               ignore_thresh=0.5,
                               downsample_ratio=32)
        mean = fluid.layers.mean(loss)
        grads = fluid.gradients(mean, [x])
        lnp, gnp = _run([mean, grads[0]],
                        {"x": xx, "gb": gtb, "gl": gtl})
        assert float(lnp) > 0
        assert np.abs(gnp).sum() > 0
        assert gnp.shape == xx.shape


class TestSSDLoss:
    def test_ssd_loss_trains(self):
        rng = np.random.RandomState(0)
        m, c = 8, 3
        prior = np.stack([
            np.linspace(0, 0.8, m), np.linspace(0, 0.8, m),
            np.linspace(0.2, 1.0, m), np.linspace(0.2, 1.0, m)],
            -1).astype(np.float32)
        prior[0] = [0.1, 0.1, 0.4, 0.4]  # coincide with the gt boxes
        prior[1] = [0.5, 0.5, 0.9, 0.9]  # so matching is guaranteed
        loc = fluid.layers.data(name="loc", shape=[m, 4],
                                dtype="float32")
        conf = fluid.layers.data(name="conf", shape=[m, c],
                                 dtype="float32")
        gtb = fluid.layers.data(name="gtb", shape=[2, 4],
                                dtype="float32")
        gtl = fluid.layers.data(name="gtl", shape=[2, 1],
                                dtype="int64")
        pb = fluid.layers.data(name="pb", shape=[4], dtype="float32")
        loc.stop_gradient = False
        conf.stop_gradient = False
        loss = det.ssd_loss(loc, conf, gtb, gtl, pb)
        mean = fluid.layers.mean(loss)
        g = fluid.gradients(mean, [loc, conf])
        feed = {
            "loc": rng.randn(2, m, 4).astype(np.float32) * 0.1,
            "conf": rng.randn(2, m, c).astype(np.float32),
            "gtb": np.tile(np.array([[0.1, 0.1, 0.4, 0.4],
                                     [0.5, 0.5, 0.9, 0.9]],
                                    np.float32), (2, 1, 1)),
            "gtl": np.ones((2, 2, 1), np.int64),
            "pb": prior}
        lnp, g0, g1 = _run([mean, g[0], g[1]], feed)
        assert float(lnp) > 0
        assert np.abs(g0).sum() > 0 and np.abs(g1).sum() > 0


class TestRPN:
    def test_generate_proposals_fixed_shape(self):
        np.random.seed(0)
        h = w = 4
        a = 3
        sc = fluid.layers.data(name="sc", shape=[a, h, w],
                               dtype="float32")
        dl = fluid.layers.data(name="dl", shape=[a * 4, h, w],
                               dtype="float32")
        im = fluid.layers.data(name="im", shape=[3], dtype="float32")
        feat = fluid.layers.data(name="feat", shape=[8, h, w],
                                 dtype="float32")
        anchors, _ = det.anchor_generator(
            feat, anchor_sizes=[16.0], aspect_ratios=[0.5, 1.0, 2.0],
            stride=[16.0, 16.0])
        rois, probs = det.generate_proposals(
            sc, dl, im, anchors, pre_nms_top_n=20, post_nms_top_n=5,
            nms_thresh=0.7, min_size=1.0)
        rnp, pnp = _run(
            [rois, probs],
            {"sc": np.random.rand(1, a, h, w).astype(np.float32),
             "dl": np.random.randn(1, a * 4, h, w).astype(
                 np.float32) * 0.1,
             "im": np.array([[64.0, 64, 1]], np.float32),
             "feat": np.zeros((1, 8, h, w), np.float32)})
        assert rnp.shape == (1, 5, 4)
        assert (rnp[..., 2] >= rnp[..., 0] - 1e-5).all()

    def test_rpn_target_assign_labels(self):
        anchors = np.array([[0, 0, 10, 10], [20, 20, 30, 30],
                            [100, 100, 110, 110]], np.float32)
        gt = np.array([[[0, 0, 10, 10], [0, 0, 0, 0]]], np.float32)
        an = fluid.layers.data(name="an", shape=[3, 4],
                               dtype="float32")
        an.shape = (3, 4)
        g = fluid.layers.data(name="g", shape=[2, 4], dtype="float32")
        labels, targets, iw = det.rpn_target_assign(
            None, None, an, None, g, rpn_batch_size_per_im=4)
        lnp, = _run([labels], {"an": anchors, "g": gt})
        assert lnp[0, 0] == 1  # perfect-IoU anchor is fg
        assert lnp.shape == (1, 3)


class TestProposalLabels:
    def test_generate_proposal_labels_batched(self):
        rois = np.array([[[0, 0, 10, 10], [20, 20, 30, 30],
                          [0, 0, 9, 9]]], np.float32)
        gtc = np.array([[3, 5]], np.int32)
        gtb = np.array([[[0, 0, 10, 10], [20, 20, 30, 30]]],
                       np.float32)
        r = fluid.layers.data(name="r", shape=[3, 4], dtype="float32")
        c = fluid.layers.data(name="c", shape=[2], dtype="int32")
        b = fluid.layers.data(name="b", shape=[2, 4], dtype="float32")
        out = det.generate_proposal_labels(
            r, c, None, b, None, batch_size_per_im=3, fg_thresh=0.5,
            use_random=False)
        rois_o, labels, targets, iw, ow = out
        ln, tn, iwn = _run([labels, targets, iw],
                           {"r": rois, "c": gtc, "b": gtb})
        assert ln.shape == (1, 3)
        assert ln[0, 0] == 3 and ln[0, 1] == 5  # fg with gt classes
        # fg rois that exactly coincide with gt encode to ~zero targets
        np.testing.assert_allclose(tn[0, 0], np.zeros(4), atol=1e-5)
        assert iwn[0, 0].tolist() == [1, 1, 1, 1]

    def test_rpn_use_random_false_deterministic(self):
        anchors = np.array([[0, 0, 10, 10], [20, 20, 30, 30],
                            [5, 5, 15, 15]], np.float32)
        gt = np.array([[[0, 0, 10, 10], [0, 0, 0, 0]]], np.float32)
        an = fluid.layers.data(name="an", shape=[3, 4],
                               dtype="float32")
        an.shape = (3, 4)
        g = fluid.layers.data(name="g", shape=[2, 4], dtype="float32")
        labels, _, _ = det.rpn_target_assign(
            None, None, an, None, g, rpn_batch_size_per_im=4,
            use_random=False)
        a1, = _run([labels], {"an": anchors, "g": gt})
        a2, = _run([labels], {"an": anchors, "g": gt})
        np.testing.assert_array_equal(a1, a2)


class TestDetectionMap:
    def test_perfect_detection_map_is_one(self):
        det_res = np.array([[[1, 0.9, 0, 0, 10, 10],
                             [-1, 0, 0, 0, 0, 0]]], np.float32)
        label = np.array([[[1, 0, 0, 10, 10]]], np.float32)
        d = fluid.layers.data(name="d", shape=[2, 6], dtype="float32")
        l = fluid.layers.data(name="l", shape=[1, 5], dtype="float32")
        helper = fluid.layers.detection.LayerHelper("detection_map",
                                                    input=d)
        out = helper.create_variable_for_type_inference("float32", True)
        helper.append_op("detection_map", {"DetectRes": d, "Label": l},
                         {"MAP": out}, {"overlap_threshold": 0.5})
        got, = _run([out], {"d": det_res, "l": label})
        assert float(got) == pytest.approx(1.0)


class TestAdviceRegressions:
    """Round-1 advisor findings (ADVICE.md): contested-prior target
    assignment in ssd_loss, duplicate min_sizes in prior_box,
    negative_indices in target_assign."""

    def test_ssd_loss_contested_prior_uses_claiming_gt(self):
        # gt1 claims P1 first (IoU .92); gt0 then claims P0 (.56) even
        # though the argmax-IoU gt at P0 is gt1 (.64). Encoding loc as
        # the bipartite assignment (P0->gt0, P1->gt1) must give a
        # strictly lower loss than encoding the stale argmax
        # (P0->gt1, P1->gt1).
        m = 4
        prior = np.array([[0.0, 0.0, 0.4, 0.4],
                          [0.0, 0.0, 0.52, 0.52],
                          [0.9, 0.9, 1.0, 1.0],
                          [0.8, 0.0, 1.0, 0.2]], np.float32)
        gts = np.array([[0.0, 0.0, 0.3, 0.3],
                        [0.0, 0.0, 0.5, 0.5]], np.float32)
        var = np.array([0.1, 0.1, 0.2, 0.2], np.float32)

        def encode(tgt, pb):
            pw, ph = pb[2] - pb[0], pb[3] - pb[1]
            pcx, pcy = pb[0] + pw / 2, pb[1] + ph / 2
            tw, th = tgt[2] - tgt[0], tgt[3] - tgt[1]
            tcx, tcy = tgt[0] + tw / 2, tgt[1] + th / 2
            return np.array([(tcx - pcx) / pw / var[0],
                             (tcy - pcy) / ph / var[1],
                             np.log(tw / pw) / var[2],
                             np.log(th / ph) / var[3]], np.float32)

        loc_claim = np.zeros((1, m, 4), np.float32)
        loc_claim[0, 0] = encode(gts[0], prior[0])
        loc_claim[0, 1] = encode(gts[1], prior[1])
        loc_argmax = np.zeros((1, m, 4), np.float32)
        loc_argmax[0, 0] = encode(gts[1], prior[0])
        loc_argmax[0, 1] = encode(gts[1], prior[1])

        def build_and_run(loc_np):
            prog = fluid.Program()
            with fluid.program_guard(prog, fluid.Program()):
                loc = fluid.layers.data(name="loc", shape=[m, 4],
                                        dtype="float32")
                conf = fluid.layers.data(name="conf", shape=[m, 3],
                                         dtype="float32")
                gtb = fluid.layers.data(name="gtb", shape=[2, 4],
                                        dtype="float32")
                gtl = fluid.layers.data(name="gtl", shape=[2, 1],
                                        dtype="int64")
                pb = fluid.layers.data(name="pb", shape=[4],
                                       dtype="float32")
                loss = det.ssd_loss(loc, conf, gtb, gtl, pb,
                                    match_type="bipartite")
                mean = fluid.layers.mean(loss)
            exe = fluid.Executor(fluid.TPUPlace(0))
            out, = exe.run(prog, feed={
                "loc": loc_np,
                "conf": np.zeros((1, m, 3), np.float32),
                "gtb": gts[None],
                "gtl": np.array([[[1], [2]]], np.int64),
                "pb": prior}, fetch_list=[mean])
            return float(out)

        assert build_and_run(loc_claim) < build_and_run(loc_argmax)

    def test_prior_box_duplicate_min_sizes(self):
        # duplicate min_sizes must pair max_sizes positionally, not by
        # first-occurrence (ADVICE: min_sizes.index bug)
        img = fluid.layers.data(name="imgd", shape=[3, 16, 16],
                                dtype="float32")
        feat = fluid.layers.data(name="featd", shape=[8, 4, 4],
                                 dtype="float32")
        box, _ = det.prior_box(feat, img, min_sizes=[4.0, 4.0],
                               max_sizes=[8.0, 16.0],
                               aspect_ratios=[1.0], clip=False)
        got, = _run([box], {
            "imgd": np.zeros((1, 3, 16, 16), np.float32),
            "featd": np.zeros((1, 8, 4, 4), np.float32)})
        # per cell: (min,max) pairs -> widths 4, sqrt(32), 4, sqrt(64)
        w = (got[0, 0, :, 2] - got[0, 0, :, 0]) * 16.0
        np.testing.assert_allclose(
            sorted(w), sorted([4.0, np.sqrt(32), 4.0, 8.0]), rtol=1e-5)

    def test_target_assign_negative_indices(self):
        x = fluid.layers.data(name="xta", shape=[3, 2], dtype="float32")
        mi = fluid.layers.data(name="mita", shape=[4], dtype="int32")
        ni = fluid.layers.data(name="nita", shape=[2], dtype="int32")
        out, w = det.target_assign(x, mi, negative_indices=ni,
                                   mismatch_value=7)
        got, wgt = _run([out, w], {
            "xta": np.arange(6, dtype=np.float32).reshape(1, 3, 2),
            "mita": np.array([[1, -1, -1, 0]], np.int32),
            "nita": np.array([[2, -1]], np.int32)})
        # matched rows gather X; negatives keep mismatch but weight 1
        np.testing.assert_allclose(got[0, 0], [2, 3])
        np.testing.assert_allclose(got[0, 3], [0, 1])
        np.testing.assert_allclose(got[0, 1], [7, 7])
        np.testing.assert_allclose(got[0, 2], [7, 7])
        np.testing.assert_allclose(wgt[0, :, 0], [1, 0, 1, 1])


def test_detection_map_layer_and_metric():
    """layers.detection.detection_map + metrics.DetectionMAP
    (reference metrics.py:566): perfect detections -> mAP 1.0;
    accumulation pools TP/FP across batches."""
    import paddle_tpu as fluid
    from paddle_tpu import unique_name

    fluid._reset_global_scope()
    unique_name.switch()
    prog = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(prog, startup):
        det = fluid.layers.data("det", shape=(3, 6), dtype="float32")
        gt = fluid.layers.data("gt", shape=(2, 5), dtype="float32")
        m = fluid.metrics.DetectionMAP(det, gt, None,
                                       overlap_threshold=0.5)
        cur_map, accum_map = m.get_map_var()
    gt_np = np.array([[[1, 0, 0, 10, 10], [2, 20, 20, 30, 30]]],
                     np.float32)
    det_np = np.array([[[1, 0.9, 0, 0, 10, 10],
                        [2, 0.8, 20, 20, 30, 30],
                        [-1, 0, 0, 0, 0, 0]]], np.float32)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    out = exe.run(prog, feed={"det": det_np, "gt": gt_np},
                  fetch_list=[cur_map.name])
    val = float(np.asarray(out[0]).reshape(-1)[0])
    assert abs(val - 1.0) < 1e-5, val
    # pooled accumulation: perfect batch + all-miss batch
    m.update(det_np, gt_np)
    miss = det_np.copy()
    miss[:, :, 2:] += 100  # boxes nowhere near gt
    m.update(miss, gt_np)
    pooled = m.eval()
    assert 0.0 < pooled < 1.0


def test_detection_map_background_and_difficult():
    from paddle_tpu.ops.detection_ops import compute_map_np

    det = [np.array([[1, 0.9, 0, 0, 10, 10],
                     [0, 0.8, 20, 20, 30, 30]], np.float32)]
    # gt: one class-1 box + one background(0) row + one difficult
    # class-1 box layout [label, difficult, x1, y1, x2, y2]
    gt = [np.array([[1, 0, 0, 0, 10, 10],
                    [0, 0, 20, 20, 30, 30],
                    [1, 1, 50, 50, 60, 60]], np.float32)]
    # background rows must not create a class; difficult box with
    # evaluate_difficult=False must not count toward npos
    v = compute_map_np(det, gt, overlap=0.5, background_label=0,
                       evaluate_difficult=False, has_difficult=True)
    assert abs(v - 1.0) < 1e-6, v
    # evaluating difficult: the unmatched difficult gt lowers recall
    v2 = compute_map_np(det, gt, overlap=0.5, background_label=0,
                        evaluate_difficult=True, has_difficult=True)
    assert v2 < 1.0
