"""Multi-process distributed training tests.

Parity model: reference tests/unittests/test_dist_base.py:236
TestDistBase — launch trainer subprocesses on localhost with the
PADDLE_* env contract (:382 _run_cluster / :475 _run_cluster_nccl2),
collect their loss sequences, and assert they match a single-process
run within a small delta (the sync-mode oracle).

Here the collective ("nccl2") mode is exercised: 2 OS processes join
jax.distributed (Gloo on CPU; ICI/DCN on real TPU pods), each trains
on half the global batch with in-graph allreduce(mean) gradient sync.
mean-of-half-batch-grads == full-batch grad, so losses must match the
single-process full-batch run almost exactly.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as fluid

WORKER = os.path.join(os.path.dirname(__file__), "dist_worker.py")


def _find_free_port():
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_cluster(n_trainers, timeout=240):
    """reference _run_cluster_nccl2 :475: spawn trainer subprocesses
    with the PADDLE_* env contract."""
    port = _find_free_port()
    eps = ",".join(f"127.0.0.1:{port + i}" for i in range(n_trainers))
    procs = []
    for tid in range(n_trainers):
        env = dict(os.environ)
        env.update({
            "PADDLE_TRAINER_ID": str(tid),
            "PADDLE_TRAINERS_NUM": str(n_trainers),
            "PADDLE_TRAINER_ENDPOINTS": eps,
            "PADDLE_TRAINING_ROLE": "TRAINER",
            "JAX_PLATFORMS": "cpu",
        })
        env.pop("XLA_FLAGS", None)  # 1 device per process
        procs.append(subprocess.Popen(
            [sys.executable, WORKER], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE))
    results = {}
    try:
        for p in procs:
            out, err = p.communicate(timeout=timeout)
            assert p.returncode == 0, \
                f"trainer failed:\n{err.decode()[-3000:]}"
            for line in out.decode().splitlines():
                if line.startswith("DIST_RESULT "):
                    r = json.loads(line[len("DIST_RESULT "):])
                    results[r["trainer_id"]] = r["losses"]
    finally:
        for p in procs:  # a failed peer leaves others in rendezvous
            if p.poll() is None:
                p.kill()
    return results


def _run_local():
    """Single-process full-batch baseline (the reference's
    check_with_place local run)."""
    import tests.dist_worker as W

    np.random.seed(90)
    loss = W.build_model()
    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(fluid.default_startup_program())
    losses = []
    for xs, ys in W.global_batches(W.STEPS):
        l, = exe.run(feed={"x": xs, "y": ys}, fetch_list=[loss.name])
        losses.append(float(np.asarray(l).reshape(-1)[0]))
    return losses


class TestDistCollective:
    def test_two_process_loss_parity(self):
        local = _run_local()
        dist = _run_cluster(2)
        assert set(dist) == {0, 1}
        # trainers see different half-batches -> different local
        # losses, but allreduced grads keep PARAMS in lockstep: the
        # average of the two trainers' losses equals the full-batch
        # loss at every step (mean decomposition), which only holds if
        # both trainers hold identical params throughout
        merged = [(a + b) / 2 for a, b in zip(dist[0], dist[1])]
        np.testing.assert_allclose(merged, local, rtol=2e-3,
                                   atol=1e-4)
        # and training progressed
        assert merged[-1] < merged[0]


class TestDistCollectiveFourRank:
    def test_four_process_loss_parity(self):
        """4-way collective (reference test_dist_base runs 2 trainers;
        the 4-rank case exercises >2 rendezvous + allreduce)."""
        local = _run_local()
        dist = _run_cluster(4)
        assert set(dist) == {0, 1, 2, 3}
        merged = [sum(vals) / 4.0
                  for vals in zip(*(dist[i] for i in range(4)))]
        np.testing.assert_allclose(merged, local, rtol=5e-3,
                                   atol=2e-4)
        assert merged[-1] < merged[0]


class TestDistTransformerPayload:
    def test_two_process_transformer_parity(self):
        """Real-model payload (reference test_dist_transformer.py):
        tiny models/transformer.py config across 2 collective
        trainers; merged loss matches the single-process full-batch
        run."""
        os.environ["DIST_MODEL"] = "transformer"
        try:
            import importlib

            import tests.dist_worker as W

            importlib.reload(W)
            np.random.seed(90)
            loss = W.build_model()
            exe = fluid.Executor(fluid.TPUPlace(0))
            exe.run(fluid.default_startup_program())
            local = []
            for feed in W.transformer_batches(W.STEPS):
                l, = exe.run(feed=feed, fetch_list=[loss.name])
                local.append(float(np.asarray(l).reshape(-1)[0]))
            dist = _run_cluster(2)
        finally:
            os.environ.pop("DIST_MODEL", None)
        assert set(dist) == {0, 1}
        merged = [(a + b) / 2 for a, b in zip(dist[0], dist[1])]
        np.testing.assert_allclose(merged, local, rtol=5e-3,
                                   atol=5e-3)
        assert merged[-1] < merged[0]


PS_WORKER = os.path.join(os.path.dirname(__file__), "dist_ps_worker.py")


def _run_ps_cluster(n_trainers, n_pservers=1, sync=False,
                    timeout=240):
    """reference _run_cluster :382: pserver processes + trainer
    processes over the TCP transport."""
    base = _find_free_port()
    ps_eps = ",".join(f"127.0.0.1:{base + i}" for i in range(n_pservers))
    common = {
        "PADDLE_PSERVER_ENDPOINTS": ps_eps,
        "PADDLE_TRAINERS_NUM": str(n_trainers),
        "DIST_SYNC": "1" if sync else "0",
        "JAX_PLATFORMS": "cpu",
    }
    procs = []
    try:
        for i, ep in enumerate(ps_eps.split(",")):
            env = dict(os.environ)
            env.update(common)
            env.update({"PADDLE_TRAINING_ROLE": "PSERVER",
                        "PADDLE_CURRENT_ENDPOINT": ep})
            env.pop("XLA_FLAGS", None)
            p = subprocess.Popen([sys.executable, PS_WORKER], env=env,
                                 stdout=subprocess.PIPE,
                                 stderr=subprocess.PIPE)
            procs.append(("ps", p))
            # wait for READY before starting trainers
            line = p.stdout.readline().decode()
            assert "PSERVER_READY" in line, \
                f"pserver failed to start: {line}" + \
                p.stderr.read(4000).decode(errors="replace")
        results = {}
        trainers = []
        for tid in range(n_trainers):
            env = dict(os.environ)
            env.update(common)
            env.update({"PADDLE_TRAINING_ROLE": "TRAINER",
                        "PADDLE_TRAINER_ID": str(tid)})
            env.pop("XLA_FLAGS", None)
            p = subprocess.Popen([sys.executable, PS_WORKER], env=env,
                                 stdout=subprocess.PIPE,
                                 stderr=subprocess.PIPE)
            trainers.append(p)
            procs.append(("tr", p))
        for p in trainers:
            out, err = p.communicate(timeout=timeout)
            assert p.returncode == 0, \
                f"trainer failed:\n{err.decode()[-3000:]}"
            for line in out.decode().splitlines():
                if line.startswith("DIST_RESULT "):
                    r = json.loads(line[len("DIST_RESULT "):])
                    results[r["trainer_id"]] = r["losses"]
        return results
    finally:
        for _, p in procs:
            if p.poll() is None:
                p.kill()


class TestDistPserverProcesses:
    def test_async_pserver_two_trainers(self):
        """Async PS mode as REAL OS processes over the TCP transport
        (reference test_dist_base async matrix): both trainers make
        progress against the shared pserver params."""
        results = _run_ps_cluster(n_trainers=2, sync=False)
        assert set(results) == {0, 1}
        for tid, losses in results.items():
            assert np.mean(losses[-3:]) < np.mean(losses[:3]), \
                f"trainer {tid} did not progress: {losses}"

    def test_sync_pserver_two_trainers_loss_parity(self):
        """Sync PS mode: the pserver barrier merges both trainers'
        half-batch grads each step (mean == full-batch grad), so
        params stay in lockstep and the trainer-averaged loss matches
        a single-process full-batch run -- the same oracle as the
        collective test, which an async-behaving regression of the
        barrier would fail."""
        import importlib

        import tests.dist_ps_worker as PW

        importlib.reload(PW)
        np.random.seed(90)
        loss = PW.build_model()
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(fluid.default_startup_program())
        local = []
        for xs, ys in PW.batches(PW.STEPS, seed=11):
            l, = exe.run(feed={"x": xs, "y": ys},
                         fetch_list=[loss.name])
            local.append(float(np.asarray(l).reshape(-1)[0]))

        results = _run_ps_cluster(n_trainers=2, sync=True)
        assert set(results) == {0, 1}
        merged = [(a + b) / 2
                  for a, b in zip(results[0], results[1])]
        np.testing.assert_allclose(merged, local, rtol=2e-3,
                                   atol=1e-4)
        assert merged[-1] < merged[0]


def test_allreduce_reduce_types_two_process():
    """All five reduce types across 2 real processes (reference
    distributed_ops/allreduce_op.cc)."""
    import subprocess
    import sys

    port = _find_free_port()
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.update({
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": "2",
            "PADDLE_TRAINER_ENDPOINTS":
                f"127.0.0.1:{port},127.0.0.1:{port + 1}",
            "PADDLE_TRAINING_ROLE": "TRAINER",
            "JAX_PLATFORMS": "cpu",
        })
        env.pop("XLA_FLAGS", None)
        procs.append(subprocess.Popen(
            [sys.executable,
             os.path.join(os.path.dirname(__file__),
                          "dist_allreduce_worker.py")],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, env=env))
    try:
        outs = [p.communicate(timeout=180) for p in procs]
    finally:
        for p in procs:  # a hung rendezvous must not leak workers
            if p.poll() is None:
                p.kill()
    for p, (o, e) in zip(procs, outs):
        assert p.returncode == 0, e[-800:]
    import json as _json

    expected = {"sum": 3.0, "mean": 1.5, "max": 2.0, "min": 1.0,
                "prod": 2.0}
    for o, _ in outs:
        line = [l for l in o.splitlines()
                if l.startswith("RESULT ")][0]
        res = _json.loads(line[len("RESULT "):])["results"]
        assert res == expected, res
