"""contrib package tests: model_stat, extend_optimizer, quantize
transpiler, Trainer/Inferencer, ctr_reader, utils, int8 calibration,
and the dynamic decoding framework.

Parity model: reference contrib/tests/ + the book machine-translation
decode usage of contrib/decoder/beam_search_decoder.py.
"""
import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import contrib


class TestModelStat:
    def test_summary_totals(self, capsys):
        main = fluid.Program()
        with fluid.program_guard(main, fluid.Program()):
            img = fluid.layers.data(name="img", shape=[3, 8, 8],
                                    dtype="float32")
            c = fluid.layers.conv2d(img, 4, 3, padding=1)
            p = fluid.layers.pool2d(c, 2, pool_stride=2)
            fluid.layers.fc(p, 10)
        params, flops = contrib.summary(main)
        out = capsys.readouterr().out
        assert "conv2d" in out and "Total PARAMs" in out
        # conv: 4*3*3*3 + 4 bias; fc: 4*4*4*10 + 10
        assert params == 108 + 4 + 640 + 10
        assert flops > 0


class TestExtendOptimizer:
    def test_decoupled_weight_decay_shrinks_params(self):
        def build():
            main, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(main, startup):
                x = fluid.layers.data(name="x", shape=[4],
                                      dtype="float32")
                y = fluid.layers.data(name="y", shape=[1],
                                      dtype="float32")
                pred = fluid.layers.fc(
                    x, 1, param_attr=fluid.ParamAttr(name="w"),
                    bias_attr=False)
                loss = fluid.layers.mean(
                    fluid.layers.square(pred - y))
            return main, startup, loss

        # zero gradient signal (y == pred target impossible to move):
        # feed y = pred so grads vanish? simpler: lr=0 optimizer ->
        # update is PURE decay: w <- w - coeff*w
        AdamW = contrib.extend_with_decoupled_weight_decay(
            fluid.optimizer.AdamOptimizer)
        main, startup, loss = build()
        with fluid.program_guard(main, startup):
            AdamW(coeff=0.1, learning_rate=0.0).minimize(loss)
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(startup)
        scope = fluid.global_scope()
        w0 = np.array(scope._get("w"))
        r = np.random.RandomState(0)
        exe.run(main, feed={"x": r.randn(8, 4).astype(np.float32),
                            "y": r.randn(8, 1).astype(np.float32)},
                fetch_list=[loss])
        w1 = np.asarray(scope._get("w"))
        np.testing.assert_allclose(w1, w0 * 0.9, rtol=1e-5)

    def test_rejects_non_optimizer(self):
        with pytest.raises(TypeError):
            contrib.extend_with_decoupled_weight_decay(dict)


class TestQuantizeTranspiler:
    def test_training_freeze_int8_cycle(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data(name="x", shape=[8],
                                  dtype="float32")
            h = fluid.layers.fc(x, 16, act="relu")
            logits = fluid.layers.fc(h, 4)
        t = contrib.QuantizeTranspiler(
            activation_quantize_type="abs_max")
        with fluid.program_guard(main, startup):
            t.training_transpile(main, startup)
        assert any(op.type.startswith("fake_quantize")
                   for op in main.global_block.ops)
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(startup)
        scope = fluid.global_scope()
        infer = main.clone(for_test=True)
        t.freeze_program(infer, scope=scope)
        t.convert_to_int8(infer, scope=scope)
        int8_ws = [n for n in scope.local_var_names()
                   if n.endswith("@SCALE")]
        assert int8_ws, "no int8 scale companions written"
        base = int8_ws[0][:-len("@SCALE")]
        assert np.asarray(scope._get(base)).dtype == np.int8


class TestTrainerInferencer:
    def test_train_save_infer_cycle(self, tmp_path):
        rng = np.random.RandomState(3)
        w_true = rng.randn(4, 1).astype(np.float32)

        def train_func():
            x = fluid.layers.data(name="x", shape=[4],
                                  dtype="float32")
            y = fluid.layers.data(name="y", shape=[1],
                                  dtype="float32")
            pred = fluid.layers.fc(
                x, 1, param_attr=fluid.ParamAttr(name="w"),
                bias_attr=fluid.ParamAttr(name="b"))
            return fluid.layers.mean(fluid.layers.square(pred - y))

        def optimizer_func():
            return fluid.optimizer.AdamOptimizer(0.05)

        def reader():
            for _ in range(6):
                xb = rng.randn(16, 4).astype(np.float32)
                yield {"x": xb, "y": (xb @ w_true).astype(np.float32)}

        trainer = contrib.Trainer(train_func, optimizer_func,
                                  place=fluid.TPUPlace(0))
        events = []
        losses = []

        def handler(ev):
            events.append(type(ev).__name__)
            if isinstance(ev, contrib.EndStepEvent):
                losses.append(float(np.mean(ev.metrics[0])))

        trainer.train(num_epochs=4, event_handler=handler,
                      reader=reader)
        assert losses[-1] < losses[0]
        assert "BeginEpochEvent" in events and "EndStepEvent" in events
        test_metrics = trainer.test(reader)
        assert np.isfinite(test_metrics).all()
        pdir = str(tmp_path / "params")
        trainer.save_params(pdir)

        def infer_func():
            x = fluid.layers.data(name="x", shape=[4],
                                  dtype="float32")
            return fluid.layers.fc(
                x, 1, param_attr=fluid.ParamAttr(name="w"),
                bias_attr=fluid.ParamAttr(name="b"))

        inferencer = contrib.Inferencer(infer_func, pdir,
                                        place=fluid.TPUPlace(0))
        xb = rng.randn(8, 4).astype(np.float32)
        out = inferencer.infer({"x": xb})[0]
        ref = trainer.exe.run(
            trainer.test_program, feed={"x": xb,
                                        "y": np.zeros((8, 1),
                                                      np.float32)},
            fetch_list=[trainer.train_func_outputs[0].name],
            scope=trainer.scope)
        assert out.shape == (8, 1)

    def test_trainer_stop(self):
        def train_func():
            x = fluid.layers.data(name="x", shape=[2],
                                  dtype="float32")
            return fluid.layers.mean(fluid.layers.fc(x, 1))

        trainer = contrib.Trainer(
            train_func, lambda: fluid.optimizer.SGDOptimizer(0.1))
        seen = []

        def handler(ev):
            seen.append(ev)
            if isinstance(ev, contrib.EndStepEvent) and \
                    ev.step == 1:
                trainer.stop()

        def reader():
            for _ in range(100):
                yield {"x": np.ones((4, 2), np.float32)}

        trainer.train(3, handler, reader=reader)
        steps = [e for e in seen
                 if isinstance(e, contrib.EndStepEvent)]
        assert len(steps) == 2  # stopped after step 1


class TestCtrReader:
    def test_reads_multislot_file(self, tmp_path):
        # format: per slot "<n> v1..vn"; slots: label(float dense 1),
        # feat (sparse uint64)
        path = os.path.join(str(tmp_path), "ctr.txt")
        with open(path, "w") as f:
            for i in range(8):
                f.write(f"1 {i % 2}.0 3 {i} {i+1} {i+2}\n")
        label = fluid.layers.data(name="click", shape=[1],
                                  dtype="float32",
                                  append_batch_size=False)
        label.shape = (4, 1)
        feat = fluid.layers.data(name="feat", shape=[3],
                                 dtype="int64",
                                 append_batch_size=False)
        feat.shape = (4, 3)  # sparse: reader buckets the width to 4
        reader = contrib.reader.ctr_reader(
            [label, feat], capacity=8, thread_num=1, batch_size=4,
            file_list=[path], slots=["click", "feat"], name="ctr_r")
        x, y = fluid.layers.read_file(reader)
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(fluid.default_startup_program())
        lab, ft = exe.run(fetch_list=[x, y])
        assert lab.shape == (4, 1) and ft.shape == (4, 4)
        np.testing.assert_allclose(np.ravel(lab)[:2], [0.0, 1.0])
        np.testing.assert_array_equal(ft[1][:3], [1, 2, 3])


class TestUtils:
    def test_hdfs_client_requires_hadoop(self):
        with pytest.raises(RuntimeError):
            contrib.utils.HDFSClient("/nonexistent/hadoop", {})

    def test_convert_dist_requires_table(self):
        with pytest.raises(ValueError):
            contrib.utils.convert_dist_to_sparse_program(
                fluid.Program())

    def test_table_shard_concat(self, tmp_path):
        from paddle_tpu.contrib.utils.lookup_table_utils import \
            _load_table_shards

        d = str(tmp_path)
        np.save(os.path.join(d, "emb.block0.npy"),
                np.ones((2, 3), np.float32))
        np.save(os.path.join(d, "emb.block1.npy"),
                np.full((2, 3), 2.0, np.float32))
        # np.save appends .npy; shard loader globs the stored names
        for f in os.listdir(d):
            os.rename(os.path.join(d, f),
                      os.path.join(d, f[:-4]))
        scope = fluid.Scope()
        ok = _load_table_shards(d, "emb", scope)
        assert ok
        table = np.asarray(scope._get("emb"))
        assert table.shape == (4, 3)
        np.testing.assert_allclose(table[2:], 2.0)


class TestInt8Calibrator:
    def test_calibrate_and_emit(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data(name="x", shape=[6],
                                  dtype="float32")
            h = fluid.layers.fc(x, 8, act="relu")
            fluid.layers.fc(h, 3)
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(startup)
        calib = contrib.int8_inference.Calibrator(main, iterations=2)
        r = np.random.RandomState(1)
        ranges = calib.sample_data(
            exe, ({"x": r.randn(4, 6).astype(np.float32)}
                  for _ in range(3)))
        assert ranges and all(v > 0 for v in ranges.values())
        q = calib.save_int8_model()
        # activations use range_abs_max in TEST mode so the pinned
        # calibrated InScale is actually READ (review regression:
        # abs_max would silently ignore the calibration)
        act_quants = [op for op in q.global_block.ops
                      if op.type == "fake_quantize_range_abs_max"]
        assert act_quants and all(op.attr("is_test")
                                  for op in act_quants)
        scope = fluid.global_scope()
        some_act = next(iter(ranges))
        np.testing.assert_allclose(
            np.asarray(scope._get(some_act + ".quant_scale")),
            [ranges[some_act]], rtol=1e-6)


class TestDecoderFramework:
    def _state_cell(self, hidden, fixed_batch=None):
        if fixed_batch is not None:
            # beam decode runs at STATIC [beam, H] shapes
            init_h = fluid.layers.data(
                name="init_h", shape=[fixed_batch, hidden],
                dtype="float32", append_batch_size=False)
        else:
            init_h = fluid.layers.data(name="init_h", shape=[hidden],
                                       dtype="float32")
        cell = contrib.StateCell(
            inputs={"word": None},
            states={"h": contrib.InitState(init=init_h)},
            out_state="h")

        @cell.state_updater
        def updater(c):
            word = c.get_input("word")
            h_prev = c.get_state("h")
            h = fluid.layers.fc(
                [word, h_prev], hidden, act="tanh",
                param_attr=[fluid.ParamAttr(name="cell_w_x"),
                            fluid.ParamAttr(name="cell_w_h")],
                bias_attr=fluid.ParamAttr(name="cell_b"))
            c.set_state("h", h)

        return cell

    def test_training_decoder_trains(self):
        H, V, E = 8, 12, 6
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            cell = self._state_cell(H)
            tgt = fluid.layers.data(name="tgt", shape=[5],
                                    dtype="int64")
            label = fluid.layers.data(name="label", shape=[5],
                                      dtype="int64")
            emb = fluid.layers.embedding(
                tgt, size=[V, E],
                param_attr=fluid.ParamAttr(name="trg_emb"))
            from paddle_tpu.layers.sequence import bind_seq_len

            bind_seq_len(emb, tgt)
            decoder = contrib.TrainingDecoder(cell)
            with decoder.block():
                w = decoder.step_input(emb)
                cell.compute_state({"word": w})
                cur = cell.get_state("h")
                logits = fluid.layers.fc(
                    cur, V, param_attr=fluid.ParamAttr(
                        name="softmax_w"),
                    bias_attr=fluid.ParamAttr(name="softmax_b"))
                cell.update_states()
                decoder.output(logits)
            out = decoder()  # [B, T, V]
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(
                    out, fluid.layers.reshape(label, [-1, 5, 1])))
            fluid.optimizer.AdamOptimizer(0.05).minimize(loss)
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(startup)
        r = np.random.RandomState(0)
        B = 4
        feed = {"tgt": r.randint(0, V, (B, 5)).astype(np.int64),
                "label": r.randint(0, V, (B, 5)).astype(np.int64),
                "init_h": np.zeros((B, H), np.float32),
                "tgt@SEQ_LEN": np.full((B,), 5, np.int32)}
        losses = [float(np.mean(exe.run(main, feed=feed,
                                        fetch_list=[loss])[0]))
                  for _ in range(15)]
        assert losses[-1] < losses[0]

    def test_beam_search_decoder_decodes(self):
        H, V, E, BEAM = 8, 12, 6, 3
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            cell = self._state_cell(H, fixed_batch=BEAM)
            init_ids = fluid.layers.data(
                name="init_ids", shape=[BEAM, 1], dtype="int64",
                append_batch_size=False)
            init_scores = fluid.layers.data(
                name="init_scores", shape=[BEAM, 1], dtype="float32",
                append_batch_size=False)
            decoder = contrib.BeamSearchDecoder(
                cell, init_ids, init_scores, target_dict_dim=V,
                word_dim=E, max_len=6, beam_size=BEAM, end_id=0,
                topk_size=V)
            out_ids, out_scores = decoder.decode()
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(startup)
        feed = {"init_ids": np.full((BEAM, 1), 1, np.int64),
                "init_scores": np.zeros((BEAM, 1), np.float32),
                "init_h": np.zeros((BEAM, H), np.float32)}
        ids, scores = exe.run(main, feed=feed,
                              fetch_list=[out_ids, out_scores])
        ids = np.asarray(ids)
        assert ids.ndim >= 1 and ids.size > 0
        assert np.isfinite(np.asarray(scores)).all()


class TestMachineTranslationDecode:
    """Book-style MT flow (reference tests/book/
    test_machine_translation.py): teacher-forced training, then
    beam-search decode on the SAME weights (shared by param name)."""

    def test_train_then_beam_decode(self):
        from paddle_tpu.models import machine_translation as mt

        V, E, H = 20, 8, 10
        main, startup, loss = mt.build_program(
            src_dict_dim=V, tgt_dict_dim=V, lr=0.01,
            embedding_dim=E, encoder_size=H, decoder_size=H)
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(startup)
        rng = np.random.RandomState(0)
        B, T = 8, 5

        def feed():
            src = rng.randint(2, V, (B, T)).astype(np.int64)
            lens = np.full((B,), T, np.int32)
            return {"src_word_id": src,
                    "src_word_id@SEQ_LEN": lens,
                    "target_language_word": src,
                    "target_language_word@SEQ_LEN": lens,
                    "target_language_next_word": src,
                    "target_language_next_word@SEQ_LEN": lens}

        f = feed()
        losses = [float(np.mean(exe.run(main, feed=f,
                                        fetch_list=[loss])[0]))
                  for _ in range(10)]
        assert losses[-1] < losses[0]

        dec_main, dec_startup, feeds, (out_ids, out_scores) = \
            mt.build_decode_program(
                src_dict_dim=V, tgt_dict_dim=V, embedding_dim=E,
                encoder_size=H, decoder_size=H, beam_size=3,
                max_len=6, start_id=0, end_id=1, src_len=T)
        # weight sharing: every decode param already lives in the
        # scope from training — do NOT run dec_startup
        scope = fluid.global_scope()
        for p in dec_main.all_parameters():
            assert scope._get(p.name) is not None, \
                f"decode param {p.name} not shared from training"
        src1 = rng.randint(2, V, (1, T)).astype(np.int64)
        ids, scores = exe.run(
            dec_main,
            feed={"src_word_id": src1,
                  "src_word_id@SEQ_LEN": np.full((1,), T, np.int32)},
            fetch_list=[out_ids, out_scores])
        ids = np.asarray(ids)
        assert ids.size > 0 and (ids >= 0).all() and (ids < V).all()
        assert np.isfinite(np.asarray(scores)).all()


class TestTransformerGreedyDecode:
    """Transformer generation (reference dist_transformer inference
    semantics): train a tiny copy task, then greedily decode with
    weights shared by identical unique-name sequences."""

    def test_train_then_generate(self):
        from paddle_tpu import unique_name
        from paddle_tpu.models import transformer as T

        V, D, L, S = 12, 16, 1, 4
        with unique_name.guard():
            main, startup, loss = T.build_program(
                seq_len=S, d_model=D, n_heads=2, n_layers=L,
                d_inner=32, vocab=V, with_optimizer=False,
                dropout_rate=0.0)
            with fluid.program_guard(main, startup):
                fluid.optimizer.Adam(learning_rate=0.02).minimize(loss)
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(startup)
        # one fixed sentence; teacher-forced next-token memorization
        src = np.array([[4, 7, 9, 1]], np.int64)
        tgt_in = np.array([[2, 4, 7, 9]], np.int64)  # GO=2 shifted
        feed = {"src_ids": src, "tgt_ids": tgt_in, "label": src}
        ls = [float(np.mean(exe.run(main, feed=feed,
                                    fetch_list=[loss])[0]))
              for _ in range(60)]
        assert ls[-1] < ls[0] * 0.5, (ls[0], ls[-1])

        with unique_name.guard():
            dmain, dstartup, feeds, out_buf = \
                T.build_greedy_decode_program(
                    seq_len=S, max_out_len=S + 3, d_model=D,
                    n_heads=2, n_layers=L, d_inner=32, vocab=V,
                    start_id=2, end_id=1)
        scope = fluid.global_scope()
        missing = [p.name for p in dmain.all_parameters()
                   if scope._get(p.name) is None]
        assert not missing, f"decode params not shared: {missing}"
        ids, steps = exe.run(dmain, feed={"src_ids": src},
                             fetch_list=[out_buf, T.DECODE_STEPS_VAR])
        ids = np.asarray(ids)
        assert ids.shape == (1, S + 3)
        # greedy generation reproduces the memorized sequence (whose
        # last copied token IS end_id=1 — the EOS terminator)
        assert ids[0, 0] == 2  # GO
        np.testing.assert_array_equal(ids[0, 1:5], src[0])
        # all-rows-finished early exit: the loop stopped right after
        # the EOS step instead of spinning to max_out_len emitting
        # frozen end_id rows, so the tail positions keep their zero
        # init (apply_eos_sentinel normalizes them to -1 for callers)
        assert int(np.ravel(steps)[0]) == 4 < S + 3 - 1
        np.testing.assert_array_equal(ids[0, 5:], [0, 0])


class TestDecodeEarlyExit:
    """Step-count probe for the all-rows-finished early exit: with
    logits.w zeroed, argmax is token 0 everywhere; at end_id=0 every
    row emits EOS on the FIRST step, so the While must run exactly 1
    iteration instead of max_out_len-1 (both decode builders)."""

    def test_loop_stops_when_all_rows_finish(self):
        from paddle_tpu import unique_name
        from paddle_tpu.models import transformer as T

        V, D, L, S, maxT = 12, 16, 1, 4, 10
        kwargs = dict(seq_len=S, max_out_len=maxT, d_model=D,
                      n_heads=2, n_layers=L, d_inner=32, vocab=V,
                      start_id=2, end_id=0)
        with unique_name.guard():
            gm, gs, _, gbuf = T.build_greedy_decode_program(**kwargs)
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(gs)
        sc = fluid.global_scope()
        sc._set("logits.w",
                np.zeros_like(np.asarray(sc._get("logits.w"))))
        src = np.array([[4, 7, 9, 3], [5, 6, 3, 8]], np.int64)
        ids, steps = exe.run(gm, feed={"src_ids": src},
                             fetch_list=[gbuf, T.DECODE_STEPS_VAR])
        assert int(np.ravel(steps)[0]) == 1, np.asarray(steps)
        assert (np.asarray(ids)[:, 1] == 0).all()  # EOS at step 1
        with unique_name.guard():
            im, _, _, ibuf = T.build_incremental_decode_program(
                **kwargs)
        ids2, steps2 = exe.run(im, feed={"src_ids": src},
                               fetch_list=[ibuf, T.DECODE_STEPS_VAR])
        assert int(np.ravel(steps2)[0]) == 1, np.asarray(steps2)
        np.testing.assert_array_equal(np.asarray(ids2),
                                      np.asarray(ids))


class TestTransformerIncrementalDecode:
    """KV-cached incremental decode must be token-for-token identical
    to the full-recompute greedy decode on the same trained weights."""

    def test_incremental_matches_full(self):
        from paddle_tpu import unique_name
        from paddle_tpu.models import transformer as T

        V, D, L, S = 12, 16, 2, 4
        with unique_name.guard():
            main, startup, loss = T.build_program(
                seq_len=S, d_model=D, n_heads=2, n_layers=L,
                d_inner=32, vocab=V, with_optimizer=False,
                dropout_rate=0.0)
            with fluid.program_guard(main, startup):
                fluid.optimizer.Adam(learning_rate=0.02).minimize(
                    loss)
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(startup)
        rng = np.random.RandomState(1)
        for _ in range(30):
            src = rng.randint(3, V, (4, S)).astype(np.int64)
            tgt_in = np.concatenate(
                [np.full((4, 1), 2, np.int64), src[:, :-1]], 1)
            exe.run(main, feed={"src_ids": src, "tgt_ids": tgt_in,
                                "label": src}, fetch_list=[loss])

        kwargs = dict(seq_len=S, max_out_len=S + 3, d_model=D,
                      n_heads=2, n_layers=L, d_inner=32, vocab=V,
                      start_id=2, end_id=1)
        with unique_name.guard():
            full_m, _, _, full_buf = T.build_greedy_decode_program(
                **kwargs)
        with unique_name.guard():
            inc_m, _, _, inc_buf = \
                T.build_incremental_decode_program(**kwargs)
        scope = fluid.global_scope()
        missing = [p.name for p in inc_m.all_parameters()
                   if scope._get(p.name) is None]
        assert not missing, f"cache-decode params not shared: " \
            f"{missing}"
        src_t = rng.randint(3, V, (2, S)).astype(np.int64)
        full_ids, = exe.run(full_m, feed={"src_ids": src_t},
                            fetch_list=[full_buf])
        inc_ids, = exe.run(inc_m, feed={"src_ids": src_t},
                           fetch_list=[inc_buf])
        np.testing.assert_array_equal(np.asarray(inc_ids),
                                      np.asarray(full_ids))


def test_generation_exports_to_stablehlo(tmp_path):
    """The While-loop generation program round-trips through
    save_inference_model -> StableHLO export -> python-free serving
    (the reference's C++ inference-deploy capability, for GENERATION)."""
    from paddle_tpu.models import transformer as T
    from paddle_tpu.inference.export import (export_stablehlo,
                                             load_stablehlo)

    V, D, L, S = 12, 16, 1, 4
    main, startup, loss = T.build_program(
        seq_len=S, d_model=D, n_heads=2, n_layers=L, d_inner=32,
        vocab=V, with_optimizer=False, dropout_rate=0.0)
    with fluid.program_guard(main, startup):
        fluid.optimizer.Adam(learning_rate=0.02).minimize(loss)
    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(startup)
    src = np.array([[4, 7, 9, 1]], np.int64)
    tgt_in = np.array([[2, 4, 7, 9]], np.int64)
    for _ in range(40):
        exe.run(main, feed={"src_ids": src, "tgt_ids": tgt_in,
                            "label": src}, fetch_list=[loss])
    dm, _, feeds, buf = T.build_greedy_decode_program(
        seq_len=S, max_out_len=S + 2, d_model=D, n_heads=2,
        n_layers=L, d_inner=32, vocab=V, start_id=2, end_id=1)
    direct, = exe.run(dm, feed={"src_ids": src}, fetch_list=[buf])

    mdir = str(tmp_path / "gen_model")
    fluid.io.save_inference_model(
        mdir, ["src_ids"],
        [dm.global_block.var(buf.name)], exe, main_program=dm)
    art = str(tmp_path / "gen.stablehlo")
    export_stablehlo(mdir, {"src_ids": src}, art)
    server = load_stablehlo(art)
    served = server({"src_ids": src})[0]
    np.testing.assert_array_equal(np.asarray(served),
                                  np.asarray(direct))

    # the KV-cached incremental program must export too (its While
    # loop carries in-place cache writes)
    im, _, _, ibuf = T.build_incremental_decode_program(
        seq_len=S, max_out_len=S + 2, d_model=D, n_heads=2,
        n_layers=L, d_inner=32, vocab=V, start_id=2, end_id=1)
    mdir2 = str(tmp_path / "gen_model_inc")
    fluid.io.save_inference_model(
        mdir2, ["src_ids"],
        [im.global_block.var(ibuf.name)], exe, main_program=im)
    art2 = str(tmp_path / "gen_inc.stablehlo")
    export_stablehlo(mdir2, {"src_ids": src}, art2)
    served2 = load_stablehlo(art2)({"src_ids": src})[0]
    np.testing.assert_array_equal(np.asarray(served2),
                                  np.asarray(direct))


def test_transformer_beam_decode_agrees_with_greedy():
    """Beam search at any width must score its best hypothesis at
    least as well as greedy; on a memorized sequence the best beam IS
    the greedy path."""
    from paddle_tpu.models import transformer as T

    V, D, L, S = 12, 16, 1, 4
    main, startup, loss = T.build_program(
        seq_len=S, d_model=D, n_heads=2, n_layers=L, d_inner=32,
        vocab=V, with_optimizer=False, dropout_rate=0.0)
    with fluid.program_guard(main, startup):
        fluid.optimizer.Adam(learning_rate=0.02).minimize(loss)
    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(startup)
    src = np.array([[4, 7, 9, 1]], np.int64)
    tgt_in = np.array([[2, 4, 7, 9]], np.int64)
    for _ in range(60):
        exe.run(main, feed={"src_ids": src, "tgt_ids": tgt_in,
                            "label": src}, fetch_list=[loss])
    kw = dict(seq_len=S, max_out_len=S + 2, d_model=D, n_heads=2,
              n_layers=L, d_inner=32, vocab=V, start_id=2, end_id=1)
    gm, _, _, gbuf = T.build_greedy_decode_program(**kw)
    bm, _, _, (bids, bscores) = T.build_beam_decode_program(
        beam_size=3, **kw)
    greedy, = exe.run(gm, feed={"src_ids": src}, fetch_list=[gbuf])
    beam_ids, beam_scores = exe.run(bm, feed={"src_ids": src},
                                    fetch_list=[bids, bscores])
    beam_ids = np.asarray(beam_ids)          # [T, beam]
    # best beam's sentence (column 0) equals the greedy continuation
    # up to and including the EOS terminator; past it the
    # early-exiting greedy buffer keeps its zero init while the beam
    # backtrack fills end_id — both mean "after the sequence"
    greedy_cont = np.asarray(greedy)[0, 1:]  # after GO
    eos_at = int(np.argmax(greedy_cont == 1)) + 1 \
        if (greedy_cont == 1).any() else len(greedy_cont)
    np.testing.assert_array_equal(beam_ids[1:1 + eos_at, 0],
                                  greedy_cont[:eos_at])
    np.testing.assert_array_equal(beam_ids[1:5, 0], src[0])
    # the beams are a real search, not beam_size copies of greedy:
    # at least one non-top hypothesis must differ from the best
    # (regression for the degenerate equal-seed initialization)
    assert any(not np.array_equal(beam_ids[:, j], beam_ids[:, 0])
               for j in range(1, beam_ids.shape[1])), beam_ids.T
    # scores are true cumulative log-probs: best beam's final score
    # equals the sum of the greedy tokens' log-softmax probabilities
    # (pins the is_accumulated contract — a double-accumulation
    # regression would be exponentially off)
    logits_prog = fluid.Program()
    with fluid.program_guard(logits_prog, fluid.Program()):
        s_in = fluid.layers.data(name="src_ids", shape=[S],
                                 dtype="int64")
        t_in = fluid.layers.data(name="tgt_ids", shape=[S + 2],
                                 dtype="int64")
        lbl = fluid.layers.data(name="label", shape=[S + 2],
                                dtype="int64")
        _, lg = T.transformer(
            s_in, t_in, lbl, src_vocab=V, tgt_vocab=V,
            max_len=max(S, S + 2), d_model=D, n_heads=2,
            n_layers=L, d_inner=32, dropout_rate=0.0, is_test=True,
            label_smooth_eps=0.0)
    tgt_seq = np.asarray(beam_ids)[:, 0][None, :]  # [1, T]
    lg_v, = exe.run(logits_prog,
                    feed={"src_ids": src,
                          "tgt_ids": tgt_seq.astype(np.int64),
                          "label": np.zeros_like(tgt_seq)},
                    fetch_list=[lg])
    logp = lg_v - np.log(np.exp(lg_v).sum(-1, keepdims=True))
    steps = np.asarray(beam_ids).shape[0] - 1
    expected = 0.0
    for t in range(steps):
        tok = int(beam_ids[t + 1, 0])
        expected += logp[0, t, tok]
        if tok == 1:  # frozen after first EOS: no further log-probs
            break
    assert abs(float(np.ravel(beam_scores)[0]) - expected) < 1e-3, (
        float(np.ravel(beam_scores)[0]), expected)


def test_transformer_batched_beam_decode_per_source():
    """Batched beam decode: each source's best hypothesis equals its
    single-source decode (beams must not leak across batch blocks)."""
    from paddle_tpu.models import transformer as T

    V, D, L, S, BEAM = 12, 16, 1, 4, 3
    main, startup, loss = T.build_program(
        seq_len=S, d_model=D, n_heads=2, n_layers=L, d_inner=32,
        vocab=V, with_optimizer=False, dropout_rate=0.0)
    with fluid.program_guard(main, startup):
        fluid.optimizer.Adam(learning_rate=0.02).minimize(loss)
    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(startup)
    rng = np.random.RandomState(2)
    for _ in range(40):
        src = rng.randint(3, V, (8, S)).astype(np.int64)
        tgt_in = np.concatenate(
            [np.full((8, 1), 2, np.int64), src[:, :-1]], 1)
        exe.run(main, feed={"src_ids": src, "tgt_ids": tgt_in,
                            "label": src}, fetch_list=[loss])
    kw = dict(seq_len=S, max_out_len=S + 2, d_model=D, n_heads=2,
              n_layers=L, d_inner=32, vocab=V, start_id=2, end_id=1,
              beam_size=BEAM)
    two = np.array([[4, 7, 9, 1], [5, 3, 8, 1]], np.int64)
    bm2, _, _, (ids2, sc2) = T.build_beam_decode_program(
        batch_size=2, **kw)
    got2, s2 = exe.run(bm2, feed={"src_ids": two},
                       fetch_list=[ids2, sc2])
    got2 = np.asarray(got2)  # [T, 2*BEAM]
    bm1, _, _, (ids1, sc1) = T.build_beam_decode_program(
        batch_size=1, **kw)
    for b in range(2):
        one, _ = exe.run(bm1, feed={"src_ids": two[b:b + 1]},
                         fetch_list=[ids1, sc1])
        np.testing.assert_array_equal(got2[:, b * BEAM],
                                      np.asarray(one)[:, 0])
