"""Multi-process parameter-server worker (parity: reference
tests/unittests/test_dist_base.py:382 _run_cluster launches PSERVER and
TRAINER roles as OS processes wired by the PADDLE_* env contract).

Role PSERVER: transpile the pserver program for this endpoint, serve it
over the TCP transport (pserver_runtime.serve), print READY, run until
shutdown. Role TRAINER: set PADDLE_PSERVER_TRANSPORT=tcp so the
send/recv ops proxy to the pserver processes, train, print losses.
"""
import json
import os
import sys

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402

import paddle_tpu as fluid  # noqa: E402
from paddle_tpu.transpiler import (DistributeTranspiler,  # noqa: E402
                                   DistributeTranspilerConfig)
from paddle_tpu.transpiler import pserver_runtime  # noqa: E402

STEPS = int(os.environ.get("DIST_STEPS", "12"))
GLOBAL_BATCH = 32


def batches(steps, seed=11):
    rng = np.random.RandomState(seed)
    w = rng.randn(16, 1).astype(np.float32)
    for _ in range(steps):
        xs = rng.randn(GLOBAL_BATCH, 16).astype(np.float32)
        ys = xs @ w + 0.05 * rng.randn(GLOBAL_BATCH, 1).astype(
            np.float32)
        yield xs, ys


def build_model():
    np.random.seed(90)
    fluid.seed(90)
    x = fluid.layers.data(name="x", shape=[16], dtype="float32")
    y = fluid.layers.data(name="y", shape=[1], dtype="float32")
    h = fluid.layers.fc(input=x, size=32, act="relu")
    pred = fluid.layers.fc(input=h, size=1)
    loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
    fluid.optimizer.SGDOptimizer(learning_rate=0.05).minimize(loss)
    return loss


def transpile(trainer_id, n_trainers, pservers, sync_mode):
    cfg = DistributeTranspilerConfig()
    cfg.slice_var_up = False
    t = DistributeTranspiler(cfg)
    t.transpile(trainer_id, pservers=pservers, trainers=n_trainers,
                sync_mode=sync_mode)
    return t


def run_pserver():
    ep = os.environ["PADDLE_CURRENT_ENDPOINT"]
    pservers = os.environ["PADDLE_PSERVER_ENDPOINTS"]
    n_trainers = int(os.environ["PADDLE_TRAINERS_NUM"])
    sync_mode = os.environ.get("DIST_SYNC", "0") == "1"
    build_model()
    t = transpile(0, n_trainers, pservers, sync_mode)
    pserver_runtime.configure_endpoint(
        ep, t.get_pserver_program(ep), num_trainers=n_trainers,
        sync_mode=sync_mode)
    print("PSERVER_READY", flush=True)
    pserver_runtime.serve(ep, blocking=True)


def run_trainer():
    os.environ["PADDLE_PSERVER_TRANSPORT"] = "tcp"
    tid = int(os.environ["PADDLE_TRAINER_ID"])
    n_trainers = int(os.environ["PADDLE_TRAINERS_NUM"])
    pservers = os.environ["PADDLE_PSERVER_ENDPOINTS"]
    sync_mode = os.environ.get("DIST_SYNC", "0") == "1"
    loss = build_model()
    t = transpile(tid, n_trainers, pservers, sync_mode)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(t.get_startup_program())
    losses = []
    shard = GLOBAL_BATCH // n_trainers
    lo = tid * shard
    # one SHARED global batch stream, disjoint shards per trainer: in
    # sync mode the merged update then equals the full-batch gradient,
    # which the parity test checks against a single-process run
    for xs, ys in batches(STEPS, seed=11):
        l, = exe.run(t.get_trainer_program(),
                     feed={"x": xs[lo:lo + shard],
                           "y": ys[lo:lo + shard]},
                     fetch_list=[loss.name])
        losses.append(float(np.asarray(l).reshape(-1)[0]))
    print("DIST_RESULT " + json.dumps(
        {"trainer_id": tid, "losses": losses}), flush=True)


if __name__ == "__main__":
    if os.environ.get("PADDLE_TRAINING_ROLE") == "PSERVER":
        run_pserver()
    else:
        run_trainer()
