"""Distributed trainer payload (parity: reference tests/unittests/
dist_mnist.py-style worker sharing TestDistRunnerBase): reads the
PADDLE_* env contract, joins the jax.distributed coordination service
(collective/nccl2 mode), trains a deterministic regression model on its
shard of the global batch with in-graph allreduce(mean) gradient sync,
and prints one JSON line of per-step losses."""
import json
import os
import sys

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402

import paddle_tpu as fluid  # noqa: E402
from paddle_tpu.parallel.env import init_distributed_env  # noqa: E402
from paddle_tpu.transpiler import (DistributeTranspiler,  # noqa: E402
                                   DistributeTranspilerConfig)

STEPS = 6
GLOBAL_BATCH = 32


def global_batches(steps, seed=11):
    rng = np.random.RandomState(seed)
    w = rng.randn(16, 1).astype(np.float32)
    for _ in range(steps):
        xs = rng.randn(GLOBAL_BATCH, 16).astype(np.float32)
        ys = xs @ w + 0.05 * rng.randn(GLOBAL_BATCH, 1).astype(
            np.float32)
        yield xs, ys


def build_model():
    if os.environ.get("DIST_MODEL", "regression") == "transformer":
        return build_transformer_model()
    np.random.seed(90)
    fluid.seed(90)
    x = fluid.layers.data(name="x", shape=[16], dtype="float32")
    y = fluid.layers.data(name="y", shape=[1], dtype="float32")
    h = fluid.layers.fc(input=x, size=64, act="relu")
    pred = fluid.layers.fc(input=h, size=1)
    loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
    fluid.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)
    return loss


SEQ_LEN, VOCAB = 8, 64


def build_transformer_model():
    """Tiny transformer payload (reference test_dist_transformer.py
    uses the real model; this is models/transformer.py at toy size so
    2-4 CPU trainers finish in seconds)."""
    from paddle_tpu.models import transformer as T

    np.random.seed(90)
    fluid.seed(90)
    main, startup, cost = T.build_program(
        seq_len=SEQ_LEN, d_model=16, n_heads=2, n_layers=1, d_inner=32,
        vocab=VOCAB, dropout_rate=0.0, with_optimizer=True,
        learning_rate=0.5, warmup_steps=4)
    # the transpiler + executor below operate on the DEFAULT programs
    fluid.switch_main_program(main)
    fluid.switch_startup_program(startup)
    return cost


def transformer_batches(steps, seed=13):
    rng = np.random.RandomState(seed)
    for _ in range(steps):
        yield {
            "src_ids": rng.randint(0, VOCAB,
                                   (GLOBAL_BATCH, SEQ_LEN)).astype(
                np.int64),
            "tgt_ids": rng.randint(0, VOCAB,
                                   (GLOBAL_BATCH, SEQ_LEN)).astype(
                np.int64),
            "label": rng.randint(0, VOCAB,
                                 (GLOBAL_BATCH, SEQ_LEN)).astype(
                np.int64),
        }


def main():
    env = init_distributed_env()
    loss = build_model()
    cfg = DistributeTranspilerConfig()
    cfg.mode = "collective"
    t = DistributeTranspiler(cfg)
    t.transpile(env.trainer_id, trainers=env.num_trainers)
    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(fluid.default_startup_program())
    losses = []
    shard = GLOBAL_BATCH // env.num_trainers
    lo = env.trainer_id * shard
    if os.environ.get("DIST_MODEL", "regression") == "transformer":
        feeds = ({k: v[lo:lo + shard] for k, v in b.items()}
                 for b in transformer_batches(STEPS))
    else:
        feeds = ({"x": xs[lo:lo + shard], "y": ys[lo:lo + shard]}
                 for xs, ys in global_batches(STEPS))
    for feed in feeds:
        l, = exe.run(t.get_trainer_program(), feed=feed,
                     fetch_list=[loss.name])
        losses.append(float(np.asarray(l).reshape(-1)[0]))
    print("DIST_RESULT " + json.dumps(
        {"trainer_id": env.trainer_id, "losses": losses}), flush=True)


if __name__ == "__main__":
    main()
