"""slim compression framework tests.

Parity model: reference contrib/slim/tests/ — test_graph_wrapper.py,
test_filter_pruning.py, test_distillation_strategy.py,
test_quantization_strategy.py, test_factory.py (the per-technique
Compressor round trips, shrunk to CI size).
"""
import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.contrib import slim

RNG = np.random.RandomState(7)


def _conv_model():
    """conv -> bn(relu) -> conv(relu) -> avgpool -> fc, mirroring the
    shape of the reference slim test net (tests/mobilenet.py at toy
    scale)."""
    img = fluid.layers.data(name="img", shape=[3, 8, 8], dtype="float32")
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    c1 = fluid.layers.conv2d(
        img, 8, 3, padding=1,
        param_attr=fluid.ParamAttr(name="conv1_weights"),
        bias_attr=fluid.ParamAttr(name="conv1_bias"))
    b1 = fluid.layers.batch_norm(
        c1, act="relu", param_attr=fluid.ParamAttr(name="bn1_scale"),
        bias_attr=fluid.ParamAttr(name="bn1_bias"))
    c2 = fluid.layers.conv2d(
        b1, 16, 3, padding=1, act="relu",
        param_attr=fluid.ParamAttr(name="conv2_weights"),
        bias_attr=fluid.ParamAttr(name="conv2_bias"))
    p = fluid.layers.pool2d(c2, 2, pool_type="avg", pool_stride=2)
    logits = fluid.layers.fc(
        p, 10, param_attr=fluid.ParamAttr(name="fc_w"),
        bias_attr=fluid.ParamAttr(name="fc_b"))
    loss = fluid.layers.mean(
        fluid.layers.softmax_with_cross_entropy(logits, label))
    acc = fluid.layers.accuracy(logits, label)
    return img, label, logits, loss, acc


def _conv_batches(n=3, bs=8):
    def reader():
        r = np.random.RandomState(11)
        for _ in range(n):
            yield {"img": r.randn(bs, 3, 8, 8).astype(np.float32),
                   "label": r.randint(0, 10, (bs, 1)).astype(np.int64)}
    return reader


class TestGraphWrapper:
    def test_traversal_and_accounting(self):
        main = fluid.Program()
        startup = fluid.Program()
        with fluid.program_guard(main, startup):
            _conv_model()
        g = slim.GraphWrapper(main)
        params = {p.name() for p in g.all_parameters()}
        assert {"conv1_weights", "conv2_weights", "fc_w"} <= params
        # conv1 -> (bias add) ... conv2 reachable via pre/next ops
        conv_ops = [op for op in g.ops() if op.type == "conv2d"]
        assert len(conv_ops) == 2
        nxt = g.next_ops(conv_ops[0])
        assert nxt, "conv1 has consumers"
        assert g.pre_ops(conv_ops[1]), "conv2 has producers"
        # flops: conv1 = 2*B*8*8*8 * 3*3*3 (+bias) dominated terms > 0
        assert g.flops() > 0
        # numel: exact sum of parameter sizes
        expect = sum(
            int(np.prod(p.shape())) for p in g.all_parameters())
        assert g.numel_params() == expect
        assert g.get_param_by_op(conv_ops[0])[0].name() == \
            "conv1_weights"

    def test_var_wrapper_producers_consumers(self):
        main = fluid.Program()
        with fluid.program_guard(main, fluid.Program()):
            _conv_model()
        g = slim.GraphWrapper(main)
        w = g.var("conv1_weights")
        assert [op.type for op in w.outputs()] == ["conv2d"]
        assert w.inputs() == []  # parameters have no producer op


class TestStructurePruner:
    def test_cal_pruned_idx_l1(self):
        p = slim.StructurePruner()
        w = np.stack([np.full((3, 2, 2), v) for v in
                      (5.0, 1.0, 3.0, 0.5)])  # axis0 l1 order: 3,1,2,0
        idx = p.cal_pruned_idx("w", w, ratio=0.5, axis=0)
        np.testing.assert_array_equal(idx, [1, 3])  # two smallest

    def test_prune_tensor_modes(self):
        w = np.arange(12, dtype=np.float32).reshape(4, 3)
        hard = slim.StructurePruner.prune_tensor(w, [1, 2], 0)
        assert hard.shape == (2, 3)
        np.testing.assert_array_equal(hard[1], [9, 10, 11])
        lazy = slim.StructurePruner.prune_tensor(w, [1], 0, lazy=True)
        assert lazy.shape == (4, 3) and lazy[1].sum() == 0

    def test_keeps_at_least_one_filter(self):
        p = slim.StructurePruner()
        idx = p.cal_pruned_idx("w", np.ones((4, 2, 1, 1)), ratio=1.0,
                               axis=0)
        assert len(idx) == 3


class TestUniformPrune:
    def test_end_to_end_shapes_and_retrain(self):
        main = fluid.Program()
        startup = fluid.Program()
        with fluid.program_guard(main, startup):
            img, label, logits, loss, acc = _conv_model()
        eval_prog = main.clone(for_test=True)
        place = fluid.CPUPlace()
        exe = fluid.Executor(place)
        scope = fluid.global_scope()
        exe.run(startup)
        comp = slim.Compressor(
            place, scope, main, train_reader=_conv_batches(),
            train_feed_list={"img": "img", "label": "label"},
            train_fetch_list={"loss": loss.name},
            eval_program=eval_prog, eval_reader=_conv_batches(),
            eval_feed_list={"img": "img", "label": "label"},
            eval_fetch_list={"acc": acc.name},
            train_optimizer=fluid.optimizer.MomentumOptimizer(0.05,
                                                              0.9))
        comp.epoch = 2
        strategy = slim.UniformPruneStrategy(
            target_ratio=0.5, start_epoch=1,
            pruned_params="conv*weights")
        comp.config([strategy])
        final = comp.run()

        g = slim.GraphWrapper(final)
        shapes = {p.name(): p.shape() for p in g.all_parameters()}
        assert shapes["conv1_weights"] == (4, 3, 3, 3)
        assert shapes["conv1_bias"] == (4,)
        assert shapes["bn1_scale"] == (4,)
        # conv2 loses output filters AND conv1's channels
        assert shapes["conv2_weights"] == (8, 4, 3, 3)
        # fc rows follow the pooled channel count: 8 * 4 * 4
        assert shapes["fc_w"] == (128, 10)
        # scope arrays match the program metadata
        for name, shp in shapes.items():
            assert np.asarray(scope._get(name)).shape == tuple(shp)
        # eval forward still runs on the pruned program (momentum
        # accumulators were pruned in lockstep: the post-prune train
        # epoch inside comp.run() already exercised the update path)
        out = exe.run(final, feed=next(iter(_conv_batches(1)())),
                      fetch_list=[acc.name])
        assert np.isfinite(out[0]).all()

    def test_flops_drop_recorded(self):
        main = fluid.Program()
        startup = fluid.Program()
        with fluid.program_guard(main, startup):
            img, label, logits, loss, acc = _conv_model()
        place = fluid.CPUPlace()
        exe = fluid.Executor(place)
        scope = fluid.global_scope()
        exe.run(startup)
        comp = slim.Compressor(
            place, scope, main, train_reader=_conv_batches(1),
            train_feed_list={"img": "img", "label": "label"},
            train_fetch_list={"loss": loss.name},
            train_optimizer=fluid.optimizer.SGDOptimizer(0.05))
        comp.epoch = 1
        strategy = slim.UniformPruneStrategy(
            target_ratio=0.25, start_epoch=0,
            pruned_params="conv*weights")
        comp.config([strategy])
        comp.run()
        # strategy stashed before/after accounting in the context kv
        # (checked indirectly: pruning happened once, flag set)
        assert strategy._pruned


class TestSensitivePrune:
    def test_sensitivities_and_ratio_search(self, tmp_path):
        main = fluid.Program()
        startup = fluid.Program()
        with fluid.program_guard(main, startup):
            img, label, logits, loss, acc = _conv_model()
        eval_prog = main.clone(for_test=True)
        place = fluid.CPUPlace()
        exe = fluid.Executor(place)
        scope = fluid.global_scope()
        exe.run(startup)
        sfile = str(tmp_path / "sens.pkl")
        comp = slim.Compressor(
            place, scope, main, train_reader=_conv_batches(1),
            train_feed_list={"img": "img", "label": "label"},
            train_fetch_list={"loss": loss.name},
            eval_program=eval_prog, eval_reader=_conv_batches(2),
            eval_feed_list={"img": "img", "label": "label"},
            eval_fetch_list={"acc": acc.name},
            train_optimizer=fluid.optimizer.SGDOptimizer(0.05))
        comp.epoch = 1
        strategy = slim.SensitivePruneStrategy(
            target_ratio=0.4, start_epoch=0, metric_name="acc",
            pruned_params="conv*weights", sensitivities_file=sfile,
            eval_batches=2, ratio_steps=(0.25, 0.5))
        comp.config([strategy])
        comp.run()
        assert os.path.exists(sfile)
        import pickle

        with open(sfile, "rb") as f:
            sens = pickle.load(f)
        assert set(sens) == {"conv1_weights", "conv2_weights"}
        for table in sens.values():
            assert set(table) == {0.25, 0.5}
        # weights were restored between probes then REALLY pruned
        w1 = np.asarray(scope._get("conv1_weights"))
        assert w1.shape[0] < 8 or \
            np.asarray(scope._get("conv2_weights")).shape[0] < 16


class TestDistillation:
    def _fc_net(self, prefix, width):
        img = fluid.layers.data(name="img", shape=[4], dtype="float32")
        label = fluid.layers.data(name="label", shape=[1],
                                  dtype="int64")
        h = fluid.layers.fc(
            img, width, act="relu",
            param_attr=fluid.ParamAttr(name=prefix + "w1"),
            bias_attr=fluid.ParamAttr(name=prefix + "b1"))
        logits = fluid.layers.fc(
            h, 5, param_attr=fluid.ParamAttr(name=prefix + "w2"),
            bias_attr=fluid.ParamAttr(name=prefix + "b2"))
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, label))
        return img, label, logits, loss

    def _reader(self, n=4):
        def reader():
            r = np.random.RandomState(3)
            for _ in range(n):
                x = r.randn(16, 4).astype(np.float32)
                y = (x.sum(1, keepdims=True) > 0).astype(np.int64)
                yield {"img": x, "label": y}
        return reader

    def test_soft_label_distillation_trains_and_freezes_teacher(self):
        teacher = fluid.Program()
        t_start = fluid.Program()
        with fluid.program_guard(teacher, t_start):
            _, _, t_logits, _ = self._fc_net("t_", 32)
        teacher_eval = teacher.clone(for_test=True)
        main = fluid.Program()
        startup = fluid.Program()
        with fluid.program_guard(main, startup):
            img, label, s_logits, loss = self._fc_net("s_", 8)
        place = fluid.CPUPlace()
        exe = fluid.Executor(place)
        scope = fluid.global_scope()
        exe.run(startup)
        exe.run(t_start)
        dist = slim.DistillationStrategy(
            distillers=[slim.SoftLabelDistiller(
                s_logits.name, "teacher_" + t_logits.name,
                teacher_temperature=2.0)],
            start_epoch=0, end_epoch=1)
        comp = slim.Compressor(
            place, scope, main, train_reader=self._reader(),
            train_feed_list={"img": "img", "label": "label"},
            train_fetch_list={"loss": loss.name},
            eval_program=main.clone(for_test=True),
            eval_reader=self._reader(2),
            eval_feed_list={"img": "img", "label": "label"},
            eval_fetch_list={"loss": loss.name},
            teacher_programs=[teacher_eval],
            train_optimizer=fluid.optimizer.SGDOptimizer(0.1),
            distiller_optimizer=fluid.optimizer.SGDOptimizer(0.1))
        comp.epoch = 3
        comp.config([dist])
        t_w = np.array(scope._get("t_w1"))
        comp.run()
        # teacher untouched (both original and merged copy)
        np.testing.assert_array_equal(t_w, scope._get("t_w1"))
        np.testing.assert_array_equal(
            t_w, scope._get("teacher_t_w1"))
        # student learned: loss on fresh data well below chance
        out = exe.run(main.clone(for_test=True),
                      feed=next(iter(self._reader(1)())),
                      fetch_list=[loss.name])
        assert float(np.mean(out[0])) < 1.7  # below -ln(1/5)+slack

    def test_l2_and_fsp_distillers_build(self):
        teacher = fluid.Program()
        t_start = fluid.Program()
        with fluid.program_guard(teacher, t_start):
            _conv_model()
        main = fluid.Program()
        startup = fluid.Program()
        with fluid.program_guard(main, startup):
            img, label, logits, loss, acc = _conv_model()
        place = fluid.CPUPlace()
        exe = fluid.Executor(place)
        scope = fluid.global_scope()
        exe.run(startup)
        exe.run(t_start)
        merged = slim.GraphWrapper(main.clone(), scope=scope,
                                   out_nodes={"loss": loss.name})
        slim.merge(slim.GraphWrapper(teacher.clone(for_test=True)),
                   merged, scope)
        # teacher activations exist under the prefix
        conv_outs = [op._op.output("Output")[0]
                     for op in merged.ops()
                     if op.type == "conv2d" and not
                     op._op.output("Output")[0].startswith("teacher_")]
        t_conv_outs = [n for n in
                       (op._op.output("Output")[0]
                        for op in merged.ops() if op.type == "conv2d")
                       if n.startswith("teacher_")]
        assert len(conv_outs) == 2 and len(t_conv_outs) == 2
        with fluid.program_guard(merged.program):
            l2 = slim.L2Distiller(
                conv_outs[0], t_conv_outs[0]).distiller_loss(merged)
            fsp = slim.FSPDistiller(
                [(conv_outs[0], conv_outs[1])],
                [(t_conv_outs[0], t_conv_outs[1])]).distiller_loss(
                    merged)
        feed = next(iter(_conv_batches(1)()))
        with fluid.scope_guard(scope):
            vals = exe.run(merged.program, feed=feed,
                           fetch_list=[l2.name, fsp.name],
                           scope=scope)
        assert all(np.isfinite(v).all() for v in vals)
        # FSP of identical pairs with itself would be 0; student vs
        # teacher differs
        assert float(vals[1]) >= 0


class TestQuantizationStrategy:
    def test_qat_freeze_export_reload(self, tmp_path):
        main = fluid.Program()
        startup = fluid.Program()
        with fluid.program_guard(main, startup):
            img = fluid.layers.data(name="img", shape=[8],
                                    dtype="float32")
            label = fluid.layers.data(name="label", shape=[1],
                                      dtype="int64")
            h = fluid.layers.fc(img, 16, act="relu")
            logits = fluid.layers.fc(h, 5)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, label))
            acc = fluid.layers.accuracy(logits, label)
        eval_prog = main.clone(for_test=True)
        place = fluid.CPUPlace()
        exe = fluid.Executor(place)
        scope = fluid.global_scope()
        exe.run(startup)

        def reader():
            r = np.random.RandomState(5)
            for _ in range(3):
                x = r.randn(16, 8).astype(np.float32)
                y = (x.sum(1, keepdims=True) > 0).astype(np.int64)
                yield {"img": x, "label": y}

        export = str(tmp_path / "qmodel")
        comp = slim.Compressor(
            place, scope, main, train_reader=reader,
            train_feed_list={"img": "img", "label": "label"},
            train_fetch_list={"loss": loss.name},
            eval_program=eval_prog, eval_reader=reader,
            eval_feed_list={"img": "img", "label": "label"},
            eval_fetch_list={"acc": acc.name},
            train_optimizer=fluid.optimizer.AdamOptimizer(0.01))
        comp.epoch = 2
        comp.config({
            "strategies": {
                "quant": {"class": "QuantizationStrategy",
                          "start_epoch": 0, "end_epoch": 1,
                          "float_model_save_path": export,
                          "weight_quantize_type": "abs_max",
                          "activation_quantize_type":
                              "moving_average_abs_max",
                          "save_in_nodes": ["img"],
                          "save_out_nodes": [logits.name]}},
            "compressor": {"epoch": 2, "strategies": ["quant"]}})
        comp.run()
        # exported artifact reloads and serves
        prog, feeds, fetches = fluid.io.load_inference_model(export,
                                                             exe)
        out = exe.run(prog, feed={
            feeds[0]: np.random.RandomState(9).randn(4, 8).astype(
                np.float32)}, fetch_list=fetches)
        assert np.asarray(out[0]).shape == (4, 5)
        # frozen weights sit on the int8 grid: few distinct values
        w = None
        for v in prog.global_block.vars.values():
            if v.persistable and v.shape and len(v.shape) == 2 and \
                    v.shape[1] == 16:
                w = np.asarray(scope._get(v.name))
                break
        assert w is not None
        scale = np.abs(w).max()
        snapped = np.round(np.clip(w / scale, -1, 1) * 127) / 127 * \
            scale
        np.testing.assert_allclose(w, snapped, atol=1e-6)


class TestConfigFactory:
    def test_unknown_class_raises(self):
        with pytest.raises(KeyError):
            slim.ConfigFactory({"strategies": {
                "x": {"class": "NoSuchStrategy"}}})

    def test_builds_selected_strategies(self):
        f = slim.ConfigFactory({
            "strategies": {
                "p": {"class": "UniformPruneStrategy",
                      "target_ratio": 0.3},
                "q": {"class": "QuantizationStrategy"}},
            "compressor": {"epoch": 7, "strategies": ["p"]}})
        assert f.epoch == 7
        assert len(f.strategies) == 1
        assert isinstance(f.strategies[0], slim.UniformPruneStrategy)
        assert f.strategies[0].target_ratio == pytest.approx(0.3)


class TestCompressorCheckpoint:
    def test_resume_from_checkpoint(self, tmp_path):
        ckpt = str(tmp_path / "ckpt")

        def build_and_run(epochs):
            main = fluid.Program()
            startup = fluid.Program()
            with fluid.program_guard(main, startup):
                img, label, logits, loss, acc = _conv_model()
            place = fluid.CPUPlace()
            exe = fluid.Executor(place)
            scope = fluid.global_scope()
            exe.run(startup)
            comp = slim.Compressor(
                place, scope, main, train_reader=_conv_batches(2),
                train_feed_list={"img": "img", "label": "label"},
                train_fetch_list={"loss": loss.name},
                train_optimizer=fluid.optimizer.SGDOptimizer(0.05),
                checkpoint_path=ckpt)
            comp.epoch = epochs
            comp.config([])
            comp.run()
            return scope

        build_and_run(1)  # writes epoch-0 checkpoint
        assert os.path.isdir(os.path.join(ckpt, "0"))
        # second job resumes at epoch 1 (trains exactly 1 more epoch)
        scope = build_and_run(2)
        assert os.path.isdir(os.path.join(ckpt, "1"))


class TestReviewRegressions:
    """Regression oracles for the round-2 review findings on slim."""

    def test_merged_teacher_ops_get_fresh_uids(self):
        main = fluid.Program()
        with fluid.program_guard(main, fluid.Program()):
            x = fluid.layers.data(name="img", shape=[4],
                                  dtype="float32")
            fluid.layers.dropout(fluid.layers.fc(x, 4), 0.5)
        teacher = fluid.Program()
        with fluid.program_guard(teacher, fluid.Program()):
            x = fluid.layers.data(name="img", shape=[4],
                                  dtype="float32")
            fluid.layers.dropout(fluid.layers.fc(x, 4), 0.5)
        g = slim.GraphWrapper(main.clone())
        slim.merge(slim.GraphWrapper(teacher), g, fluid.global_scope())
        uids = [op._op._uid for op in g.ops()]
        assert len(uids) == len(set(uids)), \
            "student/teacher sampling ops share PRNG salts"

    def test_two_teachers_same_arch_do_not_alias(self):
        def small():
            x = fluid.layers.data(name="img", shape=[4],
                                  dtype="float32")
            return fluid.layers.fc(
                x, 3, param_attr=fluid.ParamAttr(name="w"),
                bias_attr=False)
        main = fluid.Program()
        with fluid.program_guard(main, fluid.Program()):
            small()
        scope = fluid.global_scope()
        teachers = []
        for v in (1.0, 2.0):
            t = fluid.Program()
            with fluid.program_guard(t, fluid.Program()):
                small()
            teachers.append(t)
        scope.var("w")
        scope._set("w", np.full((4, 3), 7.0, np.float32))
        g = slim.GraphWrapper(main.clone(), scope=scope)
        for i, t in enumerate(teachers):
            slim.merge(slim.GraphWrapper(t), g, scope,
                       name_prefix=slim.DistillationStrategy
                       .teacher_prefix(i))
        names = set(g.program.global_block.vars)
        assert "teacher_w" in names and "teacher1_w" in names
        # same prefix twice raises instead of aliasing
        with pytest.raises(ValueError):
            slim.merge(slim.GraphWrapper(teachers[0]), g, scope,
                       name_prefix="teacher_")

    def test_random_criterion_is_process_stable(self):
        p = slim.StructurePruner(criterions={"*": "random"})
        w = np.ones((8, 2, 1, 1), np.float32)
        idx = p.cal_pruned_idx("convX_weights", w, 0.5, axis=0)
        import subprocess, sys
        code = (
            "import sys; sys.path.insert(0, '/root/repo')\n"
            "import numpy as np\n"
            "from paddle_tpu.contrib.slim import StructurePruner\n"
            "p = StructurePruner(criterions={'*': 'random'})\n"
            "w = np.ones((8, 2, 1, 1), np.float32)\n"
            "print(list(p.cal_pruned_idx('convX_weights', w, 0.5,"
            " axis=0)))\n")
        out = subprocess.run(
            [sys.executable, "-c", code], capture_output=True,
            text=True, env={**os.environ, "PYTHONHASHSEED": "123",
                            "JAX_PLATFORMS": "cpu"})
        assert out.returncode == 0, out.stderr
        assert str(list(idx)) == out.stdout.strip()

    def test_resume_syncs_pruned_shapes(self, tmp_path):
        """After prune + checkpoint, a resumed job's program metadata
        must match the loaded (pruned) arrays."""
        ckpt = str(tmp_path / "ck")

        def job(epochs):
            main = fluid.Program()
            startup = fluid.Program()
            with fluid.program_guard(main, startup):
                img, label, logits, loss, acc = _conv_model()
            place = fluid.CPUPlace()
            exe = fluid.Executor(place)
            scope = fluid.global_scope()
            exe.run(startup)
            comp = slim.Compressor(
                place, scope, main, train_reader=_conv_batches(1),
                train_feed_list={"img": "img", "label": "label"},
                train_fetch_list={"loss": loss.name},
                train_optimizer=fluid.optimizer.SGDOptimizer(0.05),
                checkpoint_path=ckpt)
            comp.epoch = epochs
            comp.config([slim.UniformPruneStrategy(
                target_ratio=0.5, start_epoch=0,
                pruned_params="conv*weights")])
            final = comp.run()
            return final

        job(1)
        # simulate a process restart: fresh scope AND fresh name
        # counters, so rebuilt auto-named vars (bn running stats)
        # regenerate the same names the checkpoint holds
        fluid._reset_global_scope()
        fluid.unique_name.switch()
        final = job(2)  # resumes at epoch 1; prune epoch already past
        g = slim.GraphWrapper(final)
        assert g.var("conv1_weights").shape() == (4, 3, 3, 3), \
            "resumed program kept stale pre-prune shapes"


class TestReviewRegressions2:
    """Second review pass: residual pruning, flops accounting, QAT
    resume."""

    def test_residual_two_matched_convs_prune_together(self):
        """Two pattern-matched convs feeding one elementwise_add must
        prune the SAME channels (propagated indices win; no
        'conflicting prune' abort)."""
        main = fluid.Program()
        startup = fluid.Program()
        with fluid.program_guard(main, startup):
            img = fluid.layers.data(name="img", shape=[3, 8, 8],
                                    dtype="float32")
            label = fluid.layers.data(name="label", shape=[1],
                                      dtype="int64")
            a = fluid.layers.conv2d(
                img, 8, 3, padding=1,
                param_attr=fluid.ParamAttr(name="conva_weights"),
                bias_attr=False)
            b = fluid.layers.conv2d(
                img, 8, 3, padding=1,
                param_attr=fluid.ParamAttr(name="convb_weights"),
                bias_attr=False)
            s = fluid.layers.elementwise_add(a, b, act="relu")
            p = fluid.layers.pool2d(s, 8, pool_type="avg")
            logits = fluid.layers.fc(p, 4)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, label))
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.global_scope()
        exe.run(startup)
        comp = slim.Compressor(
            fluid.CPUPlace(), scope, main,
            train_reader=_conv_batches(1),
            train_feed_list={"img": "img", "label": "label"},
            train_fetch_list={"loss": loss.name},
            train_optimizer=fluid.optimizer.SGDOptimizer(0.05))
        comp.epoch = 1
        comp.config([slim.UniformPruneStrategy(
            target_ratio=0.5, start_epoch=0,
            pruned_params="conv*weights")])
        final = comp.run()
        g = slim.GraphWrapper(final)
        assert g.var("conva_weights").shape()[0] == 4
        assert g.var("convb_weights").shape()[0] == 4
        # scope arrays agree
        assert np.asarray(scope._get("conva_weights")).shape[0] == 4
        assert np.asarray(scope._get("convb_weights")).shape[0] == 4

    def test_flops_accounting_reflects_prune(self):
        """post-prune flops must drop by roughly the channel ratio —
        stale intermediate shapes previously overstated them."""
        main = fluid.Program()
        startup = fluid.Program()
        with fluid.program_guard(main, startup):
            img, label, logits, loss, acc = _conv_model()
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.global_scope()
        exe.run(startup)
        comp = slim.Compressor(
            fluid.CPUPlace(), scope, main,
            train_reader=_conv_batches(1),
            train_feed_list={"img": "img", "label": "label"},
            train_fetch_list={"loss": loss.name},
            train_optimizer=fluid.optimizer.SGDOptimizer(0.05))
        comp.epoch = 1
        strategy = slim.UniformPruneStrategy(
            target_ratio=0.5, start_epoch=0,
            pruned_params="conv*weights")
        comp.config([strategy])
        context_kv = {}
        comp.run()
        g = slim.GraphWrapper(comp.train_graph.program)
        # conv1: out 4 (was 8) in 3; conv2: out 8 in 4 (was 16 in 8):
        # conv flops drop ~4x on conv2, 2x on conv1 — total well under
        # 65% of original
        # reconstruct original flops from a fresh build
        main2 = fluid.Program()
        with fluid.program_guard(main2, fluid.Program()):
            _conv_model()
        f_orig = slim.GraphWrapper(main2).flops()
        f_pruned = g.flops()
        assert f_pruned < 0.65 * f_orig, (f_orig, f_pruned)

    def test_qat_applies_on_resume(self, tmp_path):
        ckpt = str(tmp_path / "qck")
        export = str(tmp_path / "qexp")

        def job(epochs, end_epoch):
            main = fluid.Program()
            startup = fluid.Program()
            with fluid.program_guard(main, startup):
                img = fluid.layers.data(name="img", shape=[6],
                                        dtype="float32")
                label = fluid.layers.data(name="label", shape=[1],
                                          dtype="int64")
                logits = fluid.layers.fc(img, 4)
                loss = fluid.layers.mean(
                    fluid.layers.softmax_with_cross_entropy(logits,
                                                            label))
            exe = fluid.Executor(fluid.CPUPlace())
            scope = fluid.global_scope()
            exe.run(startup)

            def reader():
                r = np.random.RandomState(2)
                for _ in range(2):
                    yield {"img": r.randn(8, 6).astype(np.float32),
                           "label": r.randint(0, 4, (8, 1)).astype(
                               np.int64)}

            comp = slim.Compressor(
                fluid.CPUPlace(), scope, main, train_reader=reader,
                train_feed_list={"img": "img", "label": "label"},
                train_fetch_list={"loss": loss.name},
                eval_program=main.clone(for_test=True),
                eval_reader=reader,
                eval_feed_list={"img": "img", "label": "label"},
                eval_fetch_list={"loss": loss.name},
                train_optimizer=fluid.optimizer.SGDOptimizer(0.05),
                checkpoint_path=ckpt)
            comp.epoch = epochs
            strategy = slim.QuantizationStrategy(
                start_epoch=0, end_epoch=end_epoch,
                float_model_save_path=export,
                save_in_nodes=["img"], save_out_nodes=[logits.name])
            comp.config([strategy])
            comp.run()
            return comp, strategy

        job(1, end_epoch=1)  # checkpoint epoch 0; no freeze yet
        fluid._reset_global_scope()
        fluid.unique_name.switch()
        comp, strategy = job(2, end_epoch=1)  # resumes at epoch 1
        assert os.path.isdir(export), \
            "freeze/export must still happen on the resumed job"
        prog, feeds, fetches = fluid.io.load_inference_model(export,
            fluid.Executor(fluid.CPUPlace()))
        assert any(op.type.startswith("fake_quantize")
                   for op in prog.global_block.ops), \
            "exported model lost the QAT rewrite on resume"
