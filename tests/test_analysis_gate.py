"""Tier-1 CI gate: `python -m paddle_tpu.analysis --strict` over every
models/ + benchmark/ program must report ZERO error-severity
diagnostics — builder regressions (a collective slipping into a decode
branch, a dropped @SEQ_LEN companion, an unflagged host op...) fail
here in seconds instead of on-chip (ISSUE 3 acceptance criterion)."""
import numpy as np

import paddle_tpu as fluid
from paddle_tpu import analysis


class TestLintGate:
    def test_cli_strict_all_programs_clean(self):
        # the CLI entrypoint itself (what CI/devs run), in-process:
        # builds and lints models/ + benchmark/ and exits 0 iff no
        # error diagnostics anywhere
        from paddle_tpu.analysis.__main__ import main

        assert main(["--strict", "--registry"]) == 0

    def test_registry_host_effect_complete(self):
        assert analysis.check_registry() == []

    def test_executor_strict_gate_passes_mnist(self):
        # FLAGS_static_check=strict through the REAL Executor path:
        # the gate runs in _build_step_fn before compile and a clean
        # model trains normally
        from paddle_tpu.models import mnist

        main, startup, loss, acc = mnist.build_program(use_conv=False)
        with fluid.program_guard(main, startup):
            fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        fluid.set_flags({"FLAGS_static_check": "strict"})
        try:
            exe.run(startup)
            out = exe.run(
                main,
                feed={"img": np.random.rand(4, 784).astype(
                    np.float32),
                    "label": np.random.randint(
                        0, 10, (4, 1)).astype(np.int64)},
                fetch_list=[loss])
        finally:
            fluid.set_flags({"FLAGS_static_check": "off"})
        assert np.isfinite(out[0]).all()
