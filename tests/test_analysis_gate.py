"""Tier-1 CI gate: the full lint-zoo sweep (per-program checkers incl.
the absint divergence prover, pairwise checks, whole-bundle contracts)
must report ZERO error-severity diagnostics, the prover's findings
must cover the PTA010/011 pattern matchers with zero new false
errors, and the diagnostic set must match the committed
``analysis_baseline.json`` (the drift gate: any NEW error-or-warning
anywhere in the zoo fails here in seconds instead of on-chip). The
zoo builds ONCE per module; the pure analysis phase is timed and
pinned < 60 s so the fixpoint engine never slips the fast lane."""
import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import analysis
from paddle_tpu.analysis import ERROR, WARNING
from paddle_tpu.analysis.baseline import (collect_reports,
                                          diff_against_baseline,
                                          load_baseline)


@pytest.fixture(scope="module")
def zoo():
    """Build every lint target once (the expensive phase — program
    builds, not analysis), then run the sweep ONCE, timing only the
    analysis phase."""
    from paddle_tpu.analysis.targets import iter_lint_targets

    targets = list(iter_lint_targets())
    t0 = time.perf_counter()
    reports = collect_reports(targets=targets, with_plans=True)
    analysis_s = time.perf_counter() - t0
    return {"targets": targets, "reports": reports,
            "analysis_s": analysis_s}


class TestLintGate:
    def test_zoo_is_error_free(self, zoo):
        errs = [(rep.target, d.format())
                for rep in zoo["reports"]
                for d in rep.by_severity(ERROR)]
        assert not errs, f"strict zoo regressed: {errs[:5]}"
        # the zoo is the advertised size: a silently-shrunk target
        # list would make every assertion here vacuous
        assert len(zoo["reports"]) >= 73

    def test_absint_covers_pattern_matchers(self, zoo):
        """Agreement sweep (ISSUE 11 acceptance): over the FULL zoo,
        PTA130 reproduces every PTA010 error and PTA011 warning —
        per program, at >= the matcher's severity — and introduces
        zero new errors anywhere (no false positives from the
        fixpoint engine)."""
        for rep in zoo["reports"]:
            codes = {}
            for d in rep.diagnostics:
                codes.setdefault(d.code, []).append(d)
            p010 = codes.get("PTA010", [])
            p011 = codes.get("PTA011", [])
            p130 = codes.get("PTA130", [])
            p130_err = [d for d in p130 if d.severity == ERROR]
            p130_any = p130_err + [d for d in p130
                                   if d.severity == WARNING]
            assert len(p130_err) >= len(p010), (
                f"{rep.target}: PTA130 errors ({len(p130_err)}) do "
                f"not cover PTA010 ({len(p010)})")
            assert len(p130_any) >= len(p011) + len(p010), (
                f"{rep.target}: PTA130 findings do not cover "
                f"PTA011's")
            # zero new FALSE errors: the zoo is error-free, so the
            # prover must not error anywhere the matcher does not
            assert len(p130_err) == len(p010) == 0, (
                f"{rep.target}: prover found errors in the clean "
                f"zoo: {[d.format() for d in p130_err]}")

    def test_ownership_prover_covers_pta110(self, zoo):
        """Agreement sweep (ISSUE 14 acceptance): over the FULL zoo,
        PTA191 reproduces every PTA110 error — the ownership prover
        subsumes the syntactic declaration checker at every site its
        converged fixpoint covers (twin-dedupe: PTA110 emits only at
        non-covered sites) — and introduces zero new errors anywhere
        (no false positives from the provenance engine on the clean
        zoo)."""
        from paddle_tpu.analysis import ERROR as ERR

        saw_ownership = False
        for rep in zoo["reports"]:
            codes = {}
            for d in rep.diagnostics:
                codes.setdefault(d.code, []).append(d)
            p110 = codes.get("PTA110", [])
            p19x = [d for code in ("PTA190", "PTA191", "PTA192")
                    for d in codes.get(code, [])
                    if d.severity == ERR]
            p191 = [d for d in codes.get("PTA191", [])
                    if d.severity == ERR]
            assert len(p191) >= len(p110), (
                f"{rep.target}: PTA191 errors ({len(p191)}) do not "
                f"cover PTA110 ({len(p110)})")
            # zero new FALSE errors: the zoo is error-free, so the
            # prover must not error anywhere the declaration
            # checker does not
            assert len(p19x) == len(p110) == 0, (
                f"{rep.target}: ownership prover found errors in "
                f"the clean zoo: {[d.format() for d in p19x]}")
            saw_ownership = saw_ownership or bool(rep.ownership)
        # the paged targets actually exercised the domain: proofs
        # with NAMED assumptions landed in the ownership facts
        assert saw_ownership, "no ownership facts anywhere in the zoo"
        assumed = {name
                   for rep in zoo["reports"]
                   for name in (rep.ownership_ledger or {}).get(
                       "assumptions", {})}
        assert "HostBlockPool.alloc-disjoint" in assumed
        assert "PromptPrefixCache.fresh-exclusive" in assumed
        # the clean zoo makes the count comparison above vacuous, so
        # the subsumption is ALSO asserted pairwise on an erroring
        # fixture: every site the PTA110 fallback would flag (prover
        # coverage disabled) must be flagged by PTA191 at the same
        # anchor in the real sweep
        from unittest import mock

        from paddle_tpu import layers
        from paddle_tpu.analysis import checkers as _ck
        from paddle_tpu.analysis import run_checks as _run

        bad = fluid.Program()
        with fluid.program_guard(bad, fluid.Program()):
            blk = bad.global_block
            pool = blk.create_var(
                name="@gate/self_k0@POOL", shape=(4, 2, 2, 8),
                dtype="float32", persistable=True,
                stop_gradient=True)
            zeros = layers.fill_constant([4, 2, 2, 8], "float32",
                                         0.0)
            layers.assign(zeros, output=pool)
        with mock.patch.object(_ck, "_ownership_coverage",
                               lambda program: None):
            p110_anchors = {(d.block_idx, d.op_idx) for d in
                            _ck.check_shared_pool_writes(bad)}
        assert p110_anchors, "fallback fixture flagged nothing"
        p191_anchors = {(d.block_idx, d.op_idx)
                        for d in _run(bad) if d.code == "PTA191"
                        and d.severity == ERROR}
        assert p110_anchors <= p191_anchors, (
            f"PTA191 does not reproduce the PTA110 fallback sites: "
            f"{p110_anchors - p191_anchors}")

    def test_ci_artifacts_ledger_and_memory_plan(self, zoo,
                                                 tmp_path):
        """The ``--json`` assumptions/obligations ledger and the
        ``--memory-plan`` static per-device plans are CI ARTIFACTS:
        the gate writes both JSON files every run (to
        $PTA_GATE_ARTIFACT_DIR when CI sets it, else the test tmp
        dir) so a reviewer can diff WHICH host invariants the pool
        proofs lean on and each program's device-byte footprint
        across commits — and asserts the structural floor that makes
        those artifacts worth archiving."""
        import json
        import os

        art = os.environ.get("PTA_GATE_ARTIFACT_DIR") or str(tmp_path)
        os.makedirs(art, exist_ok=True)

        assumptions, obligations = {}, {}
        per_target, plans = {}, {}
        for rep in zoo["reports"]:
            led = rep.ownership_ledger or {}
            for name, n in (led.get("assumptions") or {}).items():
                assumptions[name] = assumptions.get(name, 0) + n
            for name, n in (led.get("obligations") or {}).items():
                obligations[name] = obligations.get(name, 0) + n
            if rep.ownership:
                per_target[rep.target] = {
                    "facts": dict(rep.ownership),
                    "ledger": dict(led)}
            if rep.plan is not None:
                plans[rep.target] = {
                    "state_bytes": rep.plan.state_bytes,
                    "state_device_bytes":
                        rep.plan.state_device_bytes,
                    "temp_device_bytes": rep.plan.temp_device_bytes,
                    "total_device_bytes":
                        rep.plan.total_device_bytes,
                    "mesh": rep.plan.mesh.describe()
                    if rep.plan.mesh else None}
        ledger = {"assumptions": dict(sorted(assumptions.items())),
                  "obligations": dict(sorted(obligations.items())),
                  "targets": per_target}
        with open(os.path.join(art, "ownership_ledger.json"),
                  "w") as f:
            json.dump(ledger, f, indent=1, sort_keys=True)
        with open(os.path.join(art, "memory_plans.json"), "w") as f:
            json.dump(plans, f, indent=1, sort_keys=True)

        # structural floor: the named allocator invariants the paged
        # + radix/COW proofs rest on are all present (a refactor
        # that silently drops one to the T-spec fallback would
        # shrink this set, not error)
        for name in ("HostBlockPool.alloc-disjoint",
                     "HostBlockPool.cow-fresh-exclusive",
                     "PromptPrefixCache.fresh-exclusive"):
            assert assumptions.get(name, 0) > 0, (
                f"assumption {name!r} vanished from the zoo ledger")
        # every pool access in the zoo is PROVEN (unproven would
        # surface as PTA190 errors, but pin the ledger view too)
        for tgt, own in per_target.items():
            assert own["ledger"].get("unproven", 0) == 0, (
                f"{tgt}: unproven pool accesses in the ledger")
        # the radix/COW/probe programs are IN the artifact set, each
        # with a concrete device-byte plan
        radix_targets = [t for t in plans
                         if "pg_serve_radix" in t or "pg_cow" in t
                         or "pg_probe" in t]
        assert len(radix_targets) >= 3, (
            f"radix-family targets missing from plans: "
            f"{sorted(plans)}")
        for tgt in radix_targets:
            assert plans[tgt]["total_device_bytes"] > 0

    def test_liveness_ledger_zero_unproven(self, zoo, tmp_path):
        """ISSUE 18 acceptance: the liveness ledger is a CI artifact
        beside the ownership one, with ZERO unproven release
        obligations across the whole zoo — every acquire contract a
        zoo program exercises names a registered release site on
        every declared exit path — and the deliberate session-pinning
        wedge (bundle/pg_wedge) surfaces as a COUNTED PTA200
        suppression, never silently."""
        import json
        import os

        art = os.environ.get("PTA_GATE_ARTIFACT_DIR") or str(tmp_path)
        os.makedirs(art, exist_ok=True)

        proven = 0
        unproven = []
        per_target = {}
        for rep in zoo["reports"]:
            led = rep.liveness_ledger or {}
            proven += int(led.get("proven", 0))
            unproven += [f"{rep.target}: {u}"
                         for u in led.get("unproven", [])]
            if rep.liveness:
                per_target[rep.target] = {
                    "facts": dict(rep.liveness),
                    "ledger": dict(led)}
        with open(os.path.join(art, "liveness_ledger.json"),
                  "w") as f:
            json.dump({"proven": proven,
                       "unproven": sorted(unproven),
                       "targets": per_target}, f, indent=1,
                      sort_keys=True)

        assert unproven == [], (
            f"unproven release obligations in the zoo: "
            f"{unproven[:5]} — register the contract/site "
            f"(absint.register_acquire_release / "
            f"register_release_site)")
        assert proven > 0, "no discharged obligations anywhere"
        # the paged programs' serve Whiles all carry proven variants
        # riding the named monotone-mask assumption
        serve_facts = [
            (t, var, desc)
            for t, own in per_target.items()
            for var, desc in own["facts"].items()
            if desc.startswith("serve ")]
        assert serve_facts, "no serve While facts in the zoo"
        for t, var, desc in serve_facts:
            assert "variant[counter bound=" in desc, (t, var, desc)
            assert "+monotone-lane_active_mask" in desc, (t, var)
        # the capacity model proved every SHIPPED config feasible...
        cap = [(t, var, desc)
               for t, own in per_target.items()
               for var, desc in own["facts"].items()
               if var.startswith("@capacity:")]
        assert cap, "no bundle capacity facts in the zoo"
        wedge = [x for x in cap if "pg_wedge" in x[0]]
        for t, var, desc in cap:
            if "pg_wedge" in t:
                continue
            assert "[feasible]" in desc, (t, var, desc)
        # ...and the deliberate wedge is INFEASIBLE with its PTA200
        # error swallowed into the counted suppression set
        assert wedge and all("[INFEASIBLE]" in d
                             for _, v, d in wedge
                             if "PromptPrefixCache" in v)
        wedge_sup = [
            (d, reason)
            for rep in zoo["reports"] if "pg_wedge" in rep.target
            for d, reason in rep.suppressed if d.code == "PTA200"]
        assert wedge_sup, (
            "the pg_wedge PTA200 witness is not in the counted "
            "suppression set")

    def test_baseline_diff_is_clean(self, zoo):
        """The committed analysis_baseline.json matches this sweep:
        no NEW error-or-warning (the CI drift gate, in-process).
        Resolved entries are allowed — they only ask for a refresh."""
        base = load_baseline()
        new, _resolved = diff_against_baseline(zoo["reports"], base)
        assert not new, (
            f"NEW findings vs analysis_baseline.json: {new} — fix "
            f"them, or (if intentional) refresh with `python -m "
            f"paddle_tpu.analysis --write-baseline` and review the "
            f"diff")

    def test_analysis_phase_under_60s(self, zoo):
        """The fixpoint engine + checkers + bundle contracts over the
        whole zoo must stay interactive: < 60 s wall (measured on the
        pre-built programs — program BUILDS are the separately-paid
        cost every lint consumer shares). Re-measured with the
        OWNERSHIP domain (index provenance + PTA190/191/192) joining
        the sharding domain + PTA160/161/170 + memory planner in the
        same fixpoint: still ~2 s cold over the full zoo on this
        host; the pin is the never-slip-the-fast-lane backstop."""
        assert zoo["analysis_s"] < 60.0, (
            f"zoo analysis took {zoo['analysis_s']:.1f}s")

    def test_cli_strict_smoke(self):
        # the CLI entrypoint itself (what CI/devs run), on one model:
        # argparse wiring, strict exit code, registry sweep
        from paddle_tpu.analysis.__main__ import main

        assert main(["--strict", "--registry", "--only",
                     "mnist"]) == 0

    def test_cli_baseline_roundtrip(self, zoo, tmp_path):
        # --write-baseline / --baseline logic against THIS sweep,
        # through the library (the CLI's own sweep would rebuild the
        # zoo); the CLI flag plumbing is covered by test_absint
        from paddle_tpu.analysis.baseline import write_baseline

        path = str(tmp_path / "base.json")
        write_baseline(zoo["reports"], path)
        new, resolved = diff_against_baseline(
            zoo["reports"], load_baseline(path))
        assert new == [] and resolved == []

    def test_registry_host_effect_complete(self):
        assert analysis.check_registry() == []

    def test_executor_strict_gate_passes_mnist(self):
        # FLAGS_static_check=strict through the REAL Executor path:
        # the gate runs in _build_step_fn before compile and a clean
        # model trains normally
        from paddle_tpu.models import mnist

        main, startup, loss, acc = mnist.build_program(use_conv=False)
        with fluid.program_guard(main, startup):
            fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        fluid.set_flags({"FLAGS_static_check": "strict"})
        try:
            exe.run(startup)
            out = exe.run(
                main,
                feed={"img": np.random.rand(4, 784).astype(
                    np.float32),
                    "label": np.random.randint(
                        0, 10, (4, 1)).astype(np.int64)},
                fetch_list=[loss])
        finally:
            fluid.set_flags({"FLAGS_static_check": "off"})
        assert np.isfinite(out[0]).all()
