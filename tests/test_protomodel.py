"""Bounded model checking of the host allocator protocols
(paddle_tpu/analysis/protomodel.py) and the cross-validation grid
that licenses PTA200's static feasibility claim.

The explorer visits EVERY reachable interleaving of each protocol at
small bounds, so a green run here is a proof-up-to-bound, not a
sampled property. The session grid is the load-bearing half: the
declarative ``session_feasible`` predicate (what PTA200 / the serving
preflight evaluate in O(1)) must agree with exhaustive exploration on
every small configuration — that agreement is what lets the static
checker say "provably infeasible" without enumerating states at lint
time."""
import pytest

from paddle_tpu.analysis import liveness, protomodel


class TestExplorerMechanics:
    def test_bfs_counterexample_is_minimal(self):
        # a 3-step machine with a seeded invariant hole: BFS must
        # report the SHORTEST trace into it, not the first DFS path
        def bad(s):
            return "n hit 2" if s["n"] == 2 else None

        proto = protomodel.Protocol(
            name="toy",
            make_init=lambda: {"n": 0},
            actions=[
                protomodel.Action("inc1", lambda s: s["n"] < 3,
                                  lambda s: s.update(n=s["n"] + 1)),
                protomodel.Action("inc2", lambda s: s["n"] < 3,
                                  lambda s: s.update(n=s["n"] + 2)),
            ],
            invariants=[("no-two", bad)],
            fingerprint=lambda s: s["n"])
        r = protomodel.explore(proto)
        assert not r.ok and r.counterexample.kind == "invariant"
        assert r.counterexample.trace == ("inc2",)  # 1 step, not 2
        assert "no-two" in r.counterexample.format()

    def test_truncated_run_is_not_ok(self):
        proto = protomodel.Protocol(
            name="counter",
            make_init=lambda: {"n": 0},
            actions=[protomodel.Action(
                "inc", lambda s: True,
                lambda s: s.update(n=s["n"] + 1))],
            fingerprint=lambda s: s["n"])
        r = protomodel.explore(proto, max_states=10)
        assert r.truncated and not r.ok
        assert r.counterexample is None  # truncation, not a bug

    def test_deadlock_reported_only_on_non_accepting_stuck(self):
        # stuck at n=1 with work outstanding -> deadlock; the same
        # machine with n=1 declared accepting is clean
        def make(accepting):
            return protomodel.Protocol(
                name="stuck",
                make_init=lambda: {"n": 0},
                actions=[protomodel.Action(
                    "step", lambda s: s["n"] == 0,
                    lambda s: s.update(n=1))],
                fingerprint=lambda s: s["n"],
                accepting=accepting)

        r = protomodel.explore(make(lambda s: s["n"] == 1))
        assert r.ok
        r = protomodel.explore(make(lambda s: False))
        assert not r.ok and r.counterexample.kind == "deadlock"


class TestAllocatorProtocolsExhaustive:
    """Every reachable interleaving of the three real allocator
    machines at small bounds: refcount conservation in every state,
    drain-to-free from every state, no deadlock, no lifetime raise.
    These subsume the fast-lane guarantees the randomized sweeps in
    test_block_pool_model.py used to sample."""

    def test_block_pool_all_interleavings(self):
        r = protomodel.explore(protomodel.block_pool_protocol(
            n_blocks=2, n_lanes=2, pages=1))
        assert r.ok and not r.truncated, (
            r.counterexample and r.counterexample.format())
        assert r.n_states >= 50  # the space is genuinely explored

    def test_block_pool_multi_page_chains(self):
        r = protomodel.explore(protomodel.block_pool_protocol(
            n_blocks=3, n_lanes=2, pages=2))
        assert r.ok and not r.truncated, (
            r.counterexample and r.counterexample.format())
        assert r.n_states > 1000

    def test_prefix_cache_all_interleavings(self):
        r = protomodel.explore(protomodel.prefix_cache_protocol(
            n_entries=2, n_prompts=3, n_clients=2))
        assert r.ok and not r.truncated, (
            r.counterexample and r.counterexample.format())
        assert r.n_states > 100

    def test_radix_tree_all_interleavings(self):
        r = protomodel.explore(protomodel.radix_protocol(
            n_blocks=3, n_lanes=2))
        assert r.ok and not r.truncated, (
            r.counterexample and r.counterexample.format())
        assert r.n_states > 500


class TestCancelExit:
    """r20 front door: the cancel/deadline teardown joins every
    allocator machine's action alphabet. The green sweeps above now
    cover cancel in every interleaving; these pin that the cancel
    actions exist as SEPARATE closures and that the explorer really
    watches the path — a seeded dropped-decref-on-cancel mutation
    must fail with a minimal trace that NAMES the cancel action."""

    def test_cancel_actions_present_on_every_machine(self):
        for proto in (protomodel.block_pool_protocol(),
                      protomodel.prefix_cache_protocol(),
                      protomodel.radix_protocol(),
                      protomodel.session_protocol(2, 2, True)):
            assert any(a.name.startswith("cancel[")
                       for a in proto.actions), proto.name

    def test_seeded_dropped_decref_on_cancel_is_caught(self):
        proto = protomodel.block_pool_protocol(
            n_blocks=2, n_lanes=2, pages=1)
        idx, act = next(
            (i, a) for i, a in enumerate(proto.actions)
            if a.name == "cancel[0]")

        def leaky(s):
            lane = s["lanes"][0]
            for b in reversed(lane["shared"]):
                s["pool"].decref(b)
            # seeded BUG: the exclusive chain is forgotten without
            # its decrefs — the one-leak-per-occurrence failure the
            # PTA201 cancel obligation exists to prevent
            lane["blocks"], lane["shared"] = [], []

        proto.actions[idx] = protomodel.Action(
            "cancel[0]", act.guard, leaky)
        r = protomodel.explore(proto)
        assert not r.ok
        assert r.counterexample.kind == "invariant", \
            r.counterexample.format()
        assert "cancel[0]" in r.counterexample.trace
        # BFS minimality: alloc then the buggy cancel, nothing more
        assert r.counterexample.trace == ("alloc[0]", "cancel[0]"), \
            r.counterexample.trace

    def test_session_cancel_returns_entry_and_reopens_want(self):
        # an infeasible pin config stays infeasible WITH cancel in
        # the alphabet (cancel unwinds active turns, never pins), and
        # the minimal wedge trace is unchanged
        r = protomodel.explore(protomodel.session_protocol(1, 2))
        assert not r.ok and r.counterexample.kind == "deadlock"
        assert len(r.counterexample.trace) == 2


class TestSessionPinningGrid:
    """THE cross-validation the module exists for: the declarative
    session-capacity predicate vs exhaustive exploration, on every
    configuration small enough to enumerate."""

    GRID = [(ne, np, close)
            for ne in (1, 2, 3)
            for np in (1, 2, 3, 4)
            for close in (False, True)]

    def test_predicate_agrees_with_explorer_everywhere(self):
        for ne, np, close in self.GRID:
            want = protomodel.session_feasible(ne, np, close)
            r = protomodel.explore(
                protomodel.session_protocol(ne, np, close))
            assert r.ok == want and not r.truncated, (
                ne, np, close,
                r.counterexample and r.counterexample.format())
            if not want:
                assert r.counterexample.kind == "deadlock"

    def test_liveness_predicate_matches_protomodel_oracle(self):
        # session_feasibility (what PTA200 and the serving preflight
        # call) and session_feasible (what the explorer validates)
        # are the same predicate — pin the bridge
        for ne, np, close in self.GRID:
            chk = liveness.session_feasibility(
                ne, np, sessions_close=close)
            assert chk.feasible == protomodel.session_feasible(
                ne, np, close), (ne, np, close)
            if not chk.feasible:
                assert "session-pinning" in chk.witness
                assert "protomodel" in chk.witness

    def test_minimal_deadlock_trace_is_replayable(self):
        # ne=1, np=2, no close: the minimal wedge is admit+harvest of
        # one session — the second admission then waits forever
        r = protomodel.explore(protomodel.session_protocol(1, 2))
        assert not r.ok and r.counterexample.kind == "deadlock"
        trace = r.counterexample.trace
        assert len(trace) == 2
        assert trace[0].startswith("admit[")
        assert trace[1].startswith("harvest[")

    def test_cold_traffic_tightens_the_bound(self):
        # non-session traffic needs one churnable entry on top of the
        # pinned set: exactly-full pinning flips to infeasible
        assert liveness.session_feasibility(2, 2).feasible
        assert not liveness.session_feasibility(
            2, 2, cold_traffic=True).feasible
