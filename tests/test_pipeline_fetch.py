"""Widened pipeline fetch contract (VERDICT r4 next #5 + ADVICE r4):
a 'pp' CompiledProgram can fetch head/tail activations, gradients, and
loop reduce observables (the MoE layerN_moe_drop / aux_mean surface) —
not just the loss and persistables. The named error remains only for
vars the schedule truly drops (per-example loop internals)."""
import numpy as np
import pytest

import jax

import paddle_tpu as fluid
from paddle_tpu.parallel.mesh import make_mesh, MeshConfig


def _fresh():
    fluid._reset_global_scope()
    from paddle_tpu import unique_name
    unique_name.switch()


def _build_mlp(n_layers=4, seed=11):
    prog, startup = fluid.Program(), fluid.Program()
    prog._seed = seed
    with fluid.program_guard(prog, startup):
        x = fluid.layers.data(name="x", shape=[16], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="int64")
        h = x
        for i in range(n_layers):
            h = fluid.layers.fc(
                h, size=16, act="tanh",
                param_attr=fluid.ParamAttr(name=f"l{i}_w"),
                bias_attr=fluid.ParamAttr(name=f"l{i}_b"))
        logits = fluid.layers.fc(
            h, size=3, param_attr=fluid.ParamAttr(name="head_w"),
            bias_attr=fluid.ParamAttr(name="head_b"))
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, y))
        fluid.optimizer.Adam(0.01).minimize(loss)
    return prog, startup, loss, logits


def _mlp_data():
    rng = np.random.RandomState(0)
    xs = rng.randn(32, 16).astype(np.float32)
    ys = np.argmax(xs[:, :3], 1).astype(np.int64)[:, None]
    return {"x": xs, "y": ys}


def _run_n(exe, prog_or_cp, feed, fetch, sc, steps):
    outs = None
    for _ in range(steps):
        outs = exe.run(prog_or_cp, feed=feed, fetch_list=fetch,
                       scope=sc)
    return outs


class TestTailActivationAndGradFetch:
    def _both(self, fetch, schedule, steps=3):
        feed = _mlp_data()
        _fresh()
        prog, startup, loss, logits = _build_mlp()
        exe = fluid.Executor(fluid.CPUPlace())
        sc = fluid.Scope()
        exe.run(startup, scope=sc)
        base = _run_n(exe, prog, feed, [loss] + fetch, sc, steps)
        _fresh()
        prog2, startup2, loss2, logits2 = _build_mlp()
        sc2 = fluid.Scope()
        exe.run(startup2, scope=sc2)
        mesh = make_mesh(MeshConfig(pp=2), devices=jax.devices()[:2])
        cp = fluid.CompiledProgram(prog2).with_data_parallel(
            loss_name=loss2.name, mesh=mesh, n_micro=4,
            pp_schedule=schedule)
        got = _run_n(exe, cp, feed, [loss2] + fetch, sc2, steps)
        return base, got

    def test_gpipe_fetches_logits_matching_executor(self):
        """The verdict's bar: fetch an intermediate activation at pp=2
        and match the Executor's values."""
        feed = _mlp_data()
        _fresh()
        prog, startup, loss, logits = _build_mlp()
        base, got = self._both([logits.name], "gpipe")
        np.testing.assert_allclose(np.asarray(base[1]),
                                   np.asarray(got[1]),
                                   rtol=5e-4, atol=5e-5)
        assert np.asarray(got[1]).shape[0] == 32  # full batch

    def test_gpipe_fetches_grad_matching_executor(self):
        base, got = self._both(["head_w@GRAD"], "gpipe")
        np.testing.assert_allclose(np.asarray(base[1]),
                                   np.asarray(got[1]),
                                   rtol=1e-3, atol=1e-5)

    def test_1f1b_fetches_grad_and_names_tail_restriction(self):
        base, got = self._both(["head_w@GRAD"], "1f1b")
        np.testing.assert_allclose(np.asarray(base[1]),
                                   np.asarray(got[1]),
                                   rtol=1e-3, atol=1e-5)
        # tail activations are per-microbatch under 1f1b: named error
        feed = _mlp_data()
        _fresh()
        prog, startup, loss, logits = _build_mlp()
        exe = fluid.Executor(fluid.CPUPlace())
        sc = fluid.Scope()
        exe.run(startup, scope=sc)
        mesh = make_mesh(MeshConfig(pp=2), devices=jax.devices()[:2])
        cp = fluid.CompiledProgram(prog).with_data_parallel(
            loss_name=loss.name, mesh=mesh, n_micro=4,
            pp_schedule="1f1b")
        with pytest.raises(KeyError, match="gpipe"):
            exe.run(cp, feed=feed, fetch_list=[loss, logits], scope=sc)

    def test_fetch_set_can_widen_after_first_run(self):
        """The trainer rebuilds once when new fetch names appear."""
        feed = _mlp_data()
        _fresh()
        prog, startup, loss, logits = _build_mlp()
        exe = fluid.Executor(fluid.CPUPlace())
        sc = fluid.Scope()
        exe.run(startup, scope=sc)
        mesh = make_mesh(MeshConfig(pp=2), devices=jax.devices()[:2])
        cp = fluid.CompiledProgram(prog).with_data_parallel(
            loss_name=loss.name, mesh=mesh, n_micro=4)
        l0, = exe.run(cp, feed=feed, fetch_list=[loss], scope=sc)
        l1, lg = exe.run(cp, feed=feed, fetch_list=[loss, logits],
                         scope=sc)
        assert np.asarray(lg).shape == (32, 3)
        assert float(np.asarray(l1).reshape(-1)[0]) < float(np.asarray(l0).reshape(-1)[0])


class TestMoEObservability:
    """ADVICE r4 #3: the flagship's advertised layerN_moe_drop /
    aux_mean fetch surface must work on a 'pp' mesh."""

    def _build(self, seed=5):
        from paddle_tpu.models import moe_transformer as M

        _fresh()
        main, startup, cost = M.build_program(
            seq_len=8, vocab=64, d_model=32, n_heads=2, n_layers=4,
            d_inner=64, n_experts=4, dropout_rate=0.0,
            learning_rate=1.0, warmup_steps=40,
            capacity_factor=0.25)  # tight capacity -> nonzero drops
        main._seed = seed
        return main, startup, cost

    @pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
    def test_drop_fracs_and_aux_fetchable_on_pp_mesh(self, schedule):
        r = np.random.RandomState(0)
        feed = {k: r.randint(1, 64, (16, 8)).astype(np.int64)
                for k in ("src_ids", "label")}
        main, startup, cost = self._build()
        drops = main._moe_drop_vars
        exe = fluid.Executor(fluid.CPUPlace())
        sc = fluid.Scope()
        exe.run(startup, scope=sc)
        mesh = make_mesh(MeshConfig(pp=2), devices=jax.devices()[:2])
        cp = fluid.CompiledProgram(main).with_data_parallel(
            loss_name=cost.name, mesh=mesh, n_micro=4,
            pp_schedule=schedule)
        res = exe.run(cp, feed=feed,
                      fetch_list=[cost] + drops + [main._moe_aux_var],
                      scope=sc)
        drop_vals = [float(np.asarray(d).reshape(-1)[0])
                     for d in res[1:1 + len(drops)]]
        aux = float(np.asarray(res[-1]).reshape(-1)[0])
        assert all(0.0 <= v <= 1.0 for v in drop_vals)
        assert any(v > 0.0 for v in drop_vals)  # cf=0.25 drops tokens
        assert np.isfinite(aux) and aux > 0.0
