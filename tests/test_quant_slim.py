"""Quantization ops + slim compression tests.

Parity model: reference tests/unittests/test_fake_quantize_op.py,
test_fake_dequantize_op.py (numeric oracles) and
contrib/slim/tests/test_quantization_pass.py (QAT rewrite + train +
freeze round trip).
"""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.contrib import slim, memory_usage, op_freq_statistic
from paddle_tpu.contrib.slim.quantization import (
    QuantizationFreezePass, QuantizationTransformPass)


def _run(fetches, feed=None):
    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(fluid.default_startup_program())
    return exe.run(feed=feed or {}, fetch_list=fetches)


class TestFakeQuantOps:
    def test_abs_max_matches_numpy(self):
        xnp = np.random.RandomState(0).randn(4, 8).astype(np.float32)
        x = fluid.layers.data(name="x", shape=[8], dtype="float32")
        helper = fluid.layer_helper.LayerHelper("fq", input=x)
        out = helper.create_variable_for_type_inference("float32")
        scale = helper.create_variable_for_type_inference("float32",
                                                          True)
        helper.append_op("fake_quantize_abs_max", {"X": x},
                         {"Out": out, "OutScale": scale},
                         {"bit_length": 8})
        got, s = _run([out, scale], {"x": xnp})
        ref_s = np.abs(xnp).max()
        ref = np.round(np.clip(xnp / ref_s, -1, 1) * 127) / 127 * ref_s
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)
        assert s[0] == pytest.approx(ref_s)
        assert len(np.unique(got)) <= 255  # on the int8 grid

    def test_channel_wise(self):
        xnp = np.random.RandomState(1).randn(3, 4, 2, 2).astype(
            np.float32)
        x = fluid.layers.data(name="x", shape=[4, 2, 2],
                              dtype="float32")
        x.shape = (3, 4, 2, 2)
        helper = fluid.layer_helper.LayerHelper("fq", input=x)
        out = helper.create_variable_for_type_inference("float32")
        scale = helper.create_variable_for_type_inference("float32",
                                                          True)
        helper.append_op("fake_channel_wise_quantize_abs_max",
                         {"X": x}, {"Out": out, "OutScale": scale},
                         {"bit_length": 8})
        got, s = _run([out, scale], {"x": xnp})
        np.testing.assert_allclose(
            s, np.abs(xnp).max(axis=(1, 2, 3)), rtol=1e-6)

    def test_ste_gradient_identity_inside_range(self):
        xnp = np.random.RandomState(2).randn(4, 8).astype(np.float32)
        x = fluid.layers.data(name="x", shape=[8], dtype="float32",
                              stop_gradient=False)
        helper = fluid.layer_helper.LayerHelper("fq", input=x)
        out = helper.create_variable_for_type_inference("float32")
        scale = helper.create_variable_for_type_inference("float32",
                                                          True)
        helper.append_op("fake_quantize_abs_max", {"X": x},
                         {"Out": out, "OutScale": scale},
                         {"bit_length": 8})
        loss = fluid.layers.mean(out)
        g, = fluid.gradients(loss, [x])
        gnp, = _run([g], {"x": xnp})
        np.testing.assert_allclose(gnp, np.full_like(xnp,
                                                     1.0 / xnp.size),
                                   rtol=1e-5)

    def test_int8_roundtrip(self):
        xnp = np.random.RandomState(3).uniform(-1, 1, (4, 4)).astype(
            np.float32)
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        helper = fluid.layer_helper.LayerHelper("q", input=x)
        q = helper.create_variable_for_type_inference("int8")
        dq = helper.create_variable_for_type_inference("float32")
        helper.append_op("quantize", {"Input": x}, {"Output": q},
                         {"Scale": 127.0})
        helper.append_op("dequantize", {"Input": q}, {"Output": dq},
                         {"Scale": 127.0})
        got, = _run([dq], {"x": xnp})
        np.testing.assert_allclose(got, xnp, atol=1.0 / 127)


class TestQATEndToEnd:
    def test_transform_train_freeze(self):
        img = fluid.layers.data(name="img", shape=[784],
                                dtype="float32")
        label = fluid.layers.data(name="label", shape=[1],
                                  dtype="int64")
        h = fluid.layers.fc(input=img, size=32, act="relu")
        out = fluid.layers.fc(input=h, size=10, act="softmax")
        loss = fluid.layers.mean(
            fluid.layers.cross_entropy(input=out, label=label))
        prog = fluid.default_main_program()
        scope = fluid.global_scope()
        # QAT rewrite BEFORE minimize (reference applies to the fwd
        # graph then re-derives grads)
        QuantizationTransformPass(scope=scope).apply(prog)
        types = [o.type for o in prog.global_block.ops]
        assert types.count("fake_quantize_abs_max") == 4  # 2w + 2a
        fluid.optimizer.AdamOptimizer(learning_rate=0.003).minimize(
            loss)
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(fluid.default_startup_program())
        feeder = fluid.DataFeeder(feed_list=[img, label])
        reader = fluid.batch(fluid.dataset.mnist.train(),
                             batch_size=64)
        losses = []
        for i, b in enumerate(reader()):
            if i >= 40:
                break
            l, = exe.run(feed=feeder.feed(b), fetch_list=[loss])
            losses.append(float(np.asarray(l)))
        assert np.mean(losses[-5:]) < np.mean(losses[:5]) - 0.2
        # freeze: weights snapped to the int grid, accuracy survives
        eval_prog = prog.clone(for_test=True)._prune([out.name])
        QuantizationFreezePass(scope).apply(eval_prog)
        w = np.asarray(scope._get("fc_0.w_0"))
        s = np.abs(w).max()
        snapped = np.round(np.clip(w / s, -1, 1) * 127) / 127 * s
        np.testing.assert_allclose(w, snapped, atol=1e-6)
        test_b = next(fluid.batch(fluid.dataset.mnist.test(), 128)())
        xs = np.stack([t[0] for t in test_b])
        ys = np.array([t[1] for t in test_b])
        pred, = exe.run(eval_prog, feed={"img": xs},
                        fetch_list=[out.name])
        acc = (np.argmax(pred, 1) == ys).mean()
        assert acc > 0.75


class TestQATVariants:
    def test_scope_none_inits_via_startup(self):
        x = fluid.layers.data(name="x", shape=[8], dtype="float32")
        out = fluid.layers.fc(input=x, size=4)
        prog = fluid.default_main_program()
        QuantizationTransformPass(
            activation_quantize_type="moving_average_abs_max"
        ).apply(prog)  # scope=None: init must go to startup program
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(fluid.default_startup_program())
        got, = exe.run(feed={"x": np.ones((2, 8), np.float32)},
                       fetch_list=[out])
        assert got.shape == (2, 4)

    def test_range_abs_max_inserted(self):
        x = fluid.layers.data(name="x", shape=[8], dtype="float32")
        out = fluid.layers.fc(input=x, size=4)
        prog = fluid.default_main_program()
        QuantizationTransformPass(
            scope=fluid.global_scope(),
            activation_quantize_type="range_abs_max",
            window_size=100).apply(prog)
        types = [o.type for o in prog.global_block.ops]
        assert "fake_quantize_range_abs_max" in types
        op = next(o for o in prog.global_block.ops
                  if o.type == "fake_quantize_range_abs_max")
        assert op.attr("window_size") == 100

    def test_bad_quant_type_raises(self):
        with pytest.raises(ValueError):
            QuantizationTransformPass(
                activation_quantize_type="nope")

    def test_ste_uses_actual_scale(self):
        # EMA scale (from InScale) below max|x| must zero the clipped
        # elements' grads
        xnp = np.array([[0.1, 0.5, 2.0]], np.float32)
        x = fluid.layers.data(name="x", shape=[3], dtype="float32",
                              stop_gradient=False)
        helper = fluid.layer_helper.LayerHelper("fq", input=x)
        out = helper.create_variable_for_type_inference("float32")
        scale = helper.create_variable_for_type_inference("float32",
                                                          True)
        sc_in = fluid.layers.data(name="sc", shape=[1],
                                  dtype="float32",
                                  append_batch_size=False)
        helper.append_op("fake_quantize_range_abs_max",
                         {"X": x, "InScale": sc_in},
                         {"Out": out, "OutScale": scale},
                         {"bit_length": 8, "window_size": 1})
        loss = fluid.layers.reduce_sum(out)
        g, = fluid.gradients(loss, [x])
        gnp, = _run([g], {"x": xnp, "sc": np.array([1.0], np.float32)})
        # scale = max(cur=2.0, ...) = 2.0 here; all pass. Instead use
        # is_test to pin the frozen scale below max|x|
        x2 = fluid.layers.data(name="x2", shape=[3], dtype="float32",
                               stop_gradient=False)
        out2 = helper.create_variable_for_type_inference("float32")
        scale2 = helper.create_variable_for_type_inference("float32",
                                                           True)
        helper.append_op("fake_quantize_range_abs_max",
                         {"X": x2, "InScale": sc_in},
                         {"Out": out2, "OutScale": scale2},
                         {"bit_length": 8, "window_size": 1,
                          "is_test": True})
        loss2 = fluid.layers.reduce_sum(out2)
        g2, = fluid.gradients(loss2, [x2])
        gnp2, = _run([g2], {"x": xnp, "x2": xnp,
                            "sc": np.array([1.0], np.float32)})
        np.testing.assert_allclose(gnp2, [[1.0, 1.0, 0.0]])


class TestPruner:
    def test_threshold_structured(self):
        scope = fluid.global_scope()
        scope.var("w3")
        w = np.ones((4, 3), np.float32)
        w[1] *= 0.01  # tiny row
        scope._set("w3", w)
        slim.Pruner("threshold").prune(scope, ["w3"], threshold=0.1,
                                       structured_axis=0)
        got = np.asarray(scope._get("w3"))
        assert (got[1] == 0).all() and (got[0] != 0).all()

    def test_ratio_prune(self):
        scope = fluid.global_scope()
        scope.var("w")
        rng = np.random.RandomState(0)
        scope._set("w", rng.randn(32, 32).astype(np.float32))
        sp = slim.Pruner("ratio").prune(scope, ["w"], ratio=0.5)
        assert sp["w"] == pytest.approx(0.5, abs=0.02)

    def test_structured_prune(self):
        scope = fluid.global_scope()
        scope.var("w2")
        scope._set("w2", np.random.RandomState(1).randn(8, 4).astype(
            np.float32))
        slim.Pruner("ratio").prune(scope, ["w2"], ratio=0.25,
                                   structured_axis=0)
        w = np.asarray(scope._get("w2"))
        zero_rows = (w == 0).all(axis=1).sum()
        assert zero_rows == 2


class TestDistillation:
    def test_soft_label_loss_zero_when_equal(self):
        s = fluid.layers.data(name="s", shape=[10], dtype="float32")
        t = fluid.layers.data(name="t", shape=[10], dtype="float32")
        loss = slim.soft_label_loss(s, t)
        logits = np.random.RandomState(0).randn(4, 10).astype(
            np.float32)
        l_same, = _run([loss], {"s": logits, "t": logits})
        # equals entropy of t's softmax; must be smaller than for a
        # mismatched student
        l_diff, = _run([loss], {"s": -logits, "t": logits})
        assert float(l_diff) > float(l_same)

    def test_fsp_matrix_shape(self):
        a = fluid.layers.data(name="a", shape=[4, 3, 3],
                              dtype="float32")
        b = fluid.layers.data(name="b", shape=[6, 3, 3],
                              dtype="float32")
        m = slim.fsp_matrix(a, b)
        got, = _run([m], {"a": np.ones((2, 4, 3, 3), np.float32),
                          "b": np.ones((2, 6, 3, 3), np.float32)})
        assert got.shape == (2, 4, 6)
        np.testing.assert_allclose(got, np.ones((2, 4, 6)), rtol=1e-6)


class TestContribMisc:
    def test_memory_usage_band(self):
        fluid.layers.fc(
            input=fluid.layers.data(name="x", shape=[100],
                                    dtype="float32"), size=50)
        lo, hi = memory_usage(fluid.default_main_program(),
                              batch_size=32)
        assert 0 < lo < hi

    def test_op_freq(self):
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        fluid.layers.fc(input=x, size=4, act="relu")
        uni, adj = op_freq_statistic(fluid.default_main_program())
        assert uni["mul"] == 1
        assert any(k.startswith("mul->") for k in adj)
