"""bf16 automatic-mixed-precision tests.

AMP is the TPU-native answer to the fp32-everywhere reference: WHITE
(MXU) ops compute in bf16 with fp32 master params, BLACK (softmax/norm/
optimizer) ops stay fp32. No GradScaler -- bf16 keeps fp32's exponent.
"""
import numpy as np

import paddle_tpu as fluid
from paddle_tpu import amp, layers


def _mnist_like_program(hidden=32):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[16], dtype="float32")
        y = layers.data("y", shape=[1], dtype="int64")
        h = layers.fc(x, hidden, act="relu")
        logits = layers.fc(h, 4)
        loss = layers.mean(
            layers.softmax_with_cross_entropy(logits, y))
        fluid.optimizer.Adam(learning_rate=0.01).minimize(loss)
    return main, startup, loss


def _separable_batch(n=64, seed=0):
    r = np.random.RandomState(seed)
    y = r.randint(0, 4, (n, 1)).astype(np.int64)
    x = r.randn(n, 16).astype(np.float32) * 0.1
    x[np.arange(n), y[:, 0]] += 2.0
    return x, y


def test_amp_training_converges():
    main, startup, loss = _mnist_like_program()
    exe = fluid.Executor(fluid.TPUPlace())
    x, y = _separable_batch()
    with amp.amp_guard(True):
        exe.run(startup)
        losses = [float(exe.run(main, feed={"x": x, "y": y},
                                fetch_list=[loss])[0])
                  for _ in range(30)]
    assert losses[-1] < losses[0] * 0.5, losses[::10]
    assert np.isfinite(losses[-1])


def test_amp_params_stay_fp32():
    main, startup, loss = _mnist_like_program()
    exe = fluid.Executor(fluid.TPUPlace())
    x, y = _separable_batch()
    with amp.amp_guard(True):
        exe.run(startup)
        exe.run(main, feed={"x": x, "y": y}, fetch_list=[loss])
    sc = fluid.global_scope()
    params = [n for n in sc._vars if n.startswith("fc_")
              and "@" not in n]
    assert params
    for n in params:
        assert np.asarray(sc._get(n)).dtype == np.float32, n


def test_amp_white_op_computes_bf16():
    import jax.numpy as jnp

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[8], dtype="float32")
        out = layers.mul(x, layers.create_parameter([8, 8], "float32"))
    exe = fluid.Executor(fluid.TPUPlace())
    x_np = np.ones((4, 8), dtype=np.float32)
    with amp.amp_guard(True):
        exe.run(startup)
        res = exe.run(main, feed={"x": x_np}, fetch_list=[out],
                      return_numpy=False)
    assert res[0].dtype == jnp.bfloat16


def test_amp_off_is_pure_fp32():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[8], dtype="float32")
        out = layers.mul(x, layers.create_parameter([8, 8], "float32"))
    exe = fluid.Executor(fluid.TPUPlace())
    exe.run(startup)
    res = exe.run(main, feed={"x": np.ones((4, 8), dtype=np.float32)},
                  fetch_list=[out], return_numpy=False)
    assert res[0].dtype == np.float32


def test_amp_matches_fp32_loss_first_step():
    """First-step loss under AMP stays close to the fp32 loss."""
    x, y = _separable_batch()
    main, startup, loss = _mnist_like_program()
    exe = fluid.Executor(fluid.TPUPlace())
    exe.run(startup)
    ref = float(exe.run(main, feed={"x": x, "y": y},
                        fetch_list=[loss])[0])

    fluid.core.program._main_program = fluid.Program()
    fluid.core.program._startup_program = fluid.Program()
    fluid._reset_global_scope()
    fluid.unique_name.switch()
    fluid.seed(90)
    np.random.seed(90)
    main2, startup2, loss2 = _mnist_like_program()
    exe2 = fluid.Executor(fluid.TPUPlace())
    with amp.amp_guard(True):
        exe2.run(startup2)
        got = float(exe2.run(main2, feed={"x": x, "y": y},
                             fetch_list=[loss2])[0])
    assert abs(ref - got) < 0.05, (ref, got)


def test_label_smooth_eps_fused_matches_onehot_path():
    """Fused label_smooth_eps == one_hot + label_smooth + soft CE."""
    r = np.random.RandomState(0)
    logits_np = r.randn(6, 10).astype(np.float32)
    lab_np = r.randint(0, 10, (6, 1)).astype(np.int64)
    eps = 0.1

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        lg = layers.data("lg", shape=[10], dtype="float32")
        lb = layers.data("lb", shape=[1], dtype="int64")
        fused = layers.softmax_with_cross_entropy(
            lg, lb, label_smooth_eps=eps)
        onehot = layers.one_hot(lb, 10)
        soft = layers.label_smooth(onehot, epsilon=eps)
        ref = layers.softmax_with_cross_entropy(lg, soft,
                                                soft_label=True)
    exe = fluid.Executor(fluid.TPUPlace())
    exe.run(startup)
    a, b = exe.run(main, feed={"lg": logits_np, "lb": lab_np},
                   fetch_list=[fused, ref])
    np.testing.assert_allclose(a, b, atol=1e-5, rtol=1e-5)
