"""Activation-checkpointing (recompute) parity tests.

Parity model: the reference line's RecomputeOptimizer tests
(test_recompute_optimizer-era): the checkpointed program must produce
IDENTICAL losses and updates to the plain program -- recompute changes
memory, never math. Includes a dropout layer so the recomputed noise
path (same structural op uid -> same mask) is exercised.
"""
import numpy as np

import paddle_tpu as fluid


def _build(with_dropout):
    prog = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.layers.data("x", shape=(16,), dtype="float32")
        y = fluid.layers.data("y", shape=(1,), dtype="int64")
        h1 = fluid.layers.fc(x, size=32, act="relu")
        if with_dropout:
            h1 = fluid.layers.dropout(
                h1, 0.3, dropout_implementation="upscale_in_train")
        c1 = fluid.layers.fc(h1, size=32, act="relu")  # checkpoint 1
        h2 = fluid.layers.fc(c1, size=32, act="tanh")
        c2 = fluid.layers.fc(h2, size=32, act="relu")  # checkpoint 2
        logits = fluid.layers.fc(c2, size=4)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, y))
    return prog, startup, loss, (c1, c2)


def _train(use_recompute, with_dropout, steps=8):
    from paddle_tpu import unique_name

    fluid._reset_global_scope()
    unique_name.switch()
    fluid.seed(1234)
    prog, startup, loss, ckpts = _build(with_dropout)
    with fluid.program_guard(prog, startup):
        if use_recompute:
            opt = fluid.optimizer.RecomputeOptimizer(
                fluid.optimizer.Adam(learning_rate=0.01))
            opt._set_checkpoints(list(ckpts))
        else:
            opt = fluid.optimizer.Adam(learning_rate=0.01)
        opt.minimize(loss)
    rng = np.random.RandomState(0)
    x = rng.rand(32, 16).astype("float32")
    y = (rng.randint(0, 4, (32, 1))).astype("int64")
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    losses = []
    for _ in range(steps):
        out = exe.run(prog, feed={"x": x, "y": y},
                      fetch_list=[loss.name])
        losses.append(float(np.asarray(out[0])))
    return prog, losses


def test_recompute_matches_plain():
    _, plain = _train(False, with_dropout=False)
    prog, ck = _train(True, with_dropout=False)
    np.testing.assert_allclose(ck, plain, atol=1e-6, rtol=1e-6)
    assert plain[-1] < plain[0]
    # the backward region actually contains recompute clones
    types = [op.type for op in prog.global_block.ops]
    names = [n for op in prog.global_block.ops
             for n in op.output_arg_names]
    assert any("@RECOMP" in n for n in names), "no recompute emitted"


def test_recompute_matches_plain_with_dropout():
    """Recomputed dropout must re-toss the IDENTICAL mask (same
    structural op uid -> same per-step noise)."""
    _, plain = _train(False, with_dropout=True)
    _, ck = _train(True, with_dropout=True)
    np.testing.assert_allclose(ck, plain, atol=1e-6, rtol=1e-6)


def test_recompute_requires_checkpoints():
    import pytest

    prog = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.layers.data("x", shape=(4,), dtype="float32")
        loss = fluid.layers.mean(fluid.layers.fc(x, size=1))
        opt = fluid.optimizer.RecomputeOptimizer(
            fluid.optimizer.SGD(learning_rate=0.1))
        with pytest.raises(ValueError, match="checkpoints"):
            opt.minimize(loss)


if __name__ == "__main__":
    import pytest

    pytest.main([__file__, "-q"])


def test_recompute_emits_barriers():
    """Without optimization_barrier roots, XLA CSE would merge the
    recompute clones back into the forward graph and the memory
    saving would silently vanish."""
    prog, _ = _train(True, with_dropout=False, steps=1)
    types = [op.type for op in prog.global_block.ops]
    assert "optimization_barrier" in types


def test_recompute_parity_survives_program_clone():
    """Program.clone must preserve op uids: a cloned recompute program
    with dropout re-tosses the same masks (salts are uid-derived)."""
    from paddle_tpu import unique_name

    fluid._reset_global_scope()
    unique_name.switch()
    fluid.seed(77)
    prog, startup, loss, ckpts = _build(True)
    with fluid.program_guard(prog, startup):
        opt = fluid.optimizer.RecomputeOptimizer(
            fluid.optimizer.Adam(learning_rate=0.01))
        opt._set_checkpoints(list(ckpts))
        opt.minimize(loss)
    rng = np.random.RandomState(3)
    x = rng.rand(16, 16).astype("float32")
    y = rng.randint(0, 4, (16, 1)).astype("int64")
    exe = fluid.Executor(fluid.CPUPlace())

    uids = {(i, op.type): op._uid
            for i, op in enumerate(prog.global_block.ops)}
    cloned = prog.clone()
    cuids = {(i, op.type): op._uid
             for i, op in enumerate(cloned.global_block.ops)}
    assert uids == cuids

    exe.run(startup)
    l1 = [float(np.asarray(exe.run(prog, feed={"x": x, "y": y},
                                   fetch_list=[loss.name])[0]))
          for _ in range(3)]
    fluid._reset_global_scope()
    fluid.seed(77)
    exe.run(startup)
    l2 = [float(np.asarray(exe.run(cloned, feed={"x": x, "y": y},
                                   fetch_list=[loss.name])[0]))
          for _ in range(3)]
    np.testing.assert_allclose(l1, l2, atol=1e-6, rtol=1e-6)


def test_recompute_with_gradient_merge():
    """Wrapper combo from the reference line: grad-merge over a
    recompute-backed inner optimizer."""
    from paddle_tpu import unique_name

    fluid._reset_global_scope()
    unique_name.switch()
    fluid.seed(5)
    prog, startup, loss, ckpts = _build(False)
    with fluid.program_guard(prog, startup):
        inner = fluid.optimizer.RecomputeOptimizer(
            fluid.optimizer.SGD(learning_rate=0.05))
        inner._set_checkpoints(list(ckpts))
        opt = fluid.optimizer.GradientMergeOptimizer(inner, k_steps=2)
        opt.minimize(loss)
    rng = np.random.RandomState(1)
    x = rng.rand(8, 16).astype("float32")
    y = rng.randint(0, 4, (8, 1)).astype("int64")
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    losses = [float(np.asarray(exe.run(prog, feed={"x": x, "y": y},
                                       fetch_list=[loss.name])[0]))
              for _ in range(8)]
    assert losses[-1] < losses[0]


def test_recompute_skip_connection_parity():
    """A residual read crossing a checkpoint boundary: the bypassed
    activation is treated as saved (spill) and the math is intact."""
    from paddle_tpu import unique_name

    def build_and_train(use_ck):
        fluid._reset_global_scope()
        unique_name.switch()
        fluid.seed(9)
        prog = fluid.Program()
        startup = fluid.Program()
        with fluid.program_guard(prog, startup):
            x = fluid.layers.data("x", shape=(16,), dtype="float32")
            y = fluid.layers.data("y", shape=(1,), dtype="int64")
            h0 = fluid.layers.fc(x, size=32, act="relu")
            c1 = fluid.layers.fc(h0, size=32, act="relu")  # checkpoint
            h2 = fluid.layers.fc(c1, size=32, act="tanh")
            res = fluid.layers.elementwise_add(h2, h0)  # skip over c1
            logits = fluid.layers.fc(res, size=4)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, y))
            if use_ck:
                opt = fluid.optimizer.RecomputeOptimizer(
                    fluid.optimizer.SGD(learning_rate=0.05))
                opt._set_checkpoints([c1])
            else:
                opt = fluid.optimizer.SGD(learning_rate=0.05)
            opt.minimize(loss)
        rng = np.random.RandomState(2)
        xf = rng.rand(16, 16).astype("float32")
        yf = rng.randint(0, 4, (16, 1)).astype("int64")
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        return [float(np.asarray(exe.run(
            prog, feed={"x": xf, "y": yf},
            fetch_list=[loss.name])[0])) for _ in range(6)]

    np.testing.assert_allclose(build_and_train(True),
                               build_and_train(False),
                               atol=1e-6, rtol=1e-6)


def test_recompute_composes_with_data_parallel():
    """Recompute + CompiledProgram.with_data_parallel on the virtual
    8-device mesh: the barriers/clones must shard like any other op
    and match the plain dp run."""
    from paddle_tpu import unique_name

    def run(use_ck):
        fluid._reset_global_scope()
        unique_name.switch()
        fluid.seed(31)
        prog, startup, loss, ckpts = _build(False)
        with fluid.program_guard(prog, startup):
            if use_ck:
                opt = fluid.optimizer.RecomputeOptimizer(
                    fluid.optimizer.SGD(learning_rate=0.05))
                opt._set_checkpoints(list(ckpts))
            else:
                opt = fluid.optimizer.SGD(learning_rate=0.05)
            opt.minimize(loss)
        compiled = fluid.CompiledProgram(prog).with_data_parallel(
            loss_name=loss.name)
        rng = np.random.RandomState(7)
        x = rng.rand(32, 16).astype("float32")
        y = rng.randint(0, 4, (32, 1)).astype("int64")
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        return [float(np.asarray(exe.run(
            compiled, feed={"x": x, "y": y},
            fetch_list=[loss.name])[0]).reshape(-1)[0])
            for _ in range(5)]

    plain = run(False)
    ck = run(True)
    np.testing.assert_allclose(ck, plain, atol=1e-6, rtol=1e-6)
    assert ck[-1] < ck[0]


def test_recompute_dp_program_contains_clones():
    """Guard against the vacuous-parity failure mode: the dp-wrapped
    recompute program must actually carry barriers + @RECOMP clones."""
    from paddle_tpu import unique_name

    fluid._reset_global_scope()
    unique_name.switch()
    fluid.seed(31)
    prog, startup, loss, ckpts = _build(False)
    with fluid.program_guard(prog, startup):
        opt = fluid.optimizer.RecomputeOptimizer(
            fluid.optimizer.SGD(learning_rate=0.05))
        opt._set_checkpoints(list(ckpts))
        opt.minimize(loss)
    fluid.CompiledProgram(prog).with_data_parallel(loss_name=loss.name)
    types = [op.type for op in prog.global_block.ops]
    assert "optimization_barrier" in types
    names = [n for op in prog.global_block.ops
             for n in op.output_arg_names]
    assert any("@RECOMP" in n for n in names)
