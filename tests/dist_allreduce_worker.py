"""Worker exercising every allreduce reduce_type across real
processes (reference distributed_ops/allreduce_op.cc red_type enum).
Rank r contributes value (r+1); prints one JSON line of results."""
import json
import os
import sys

import jax

jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402

import paddle_tpu as fluid  # noqa: E402
from paddle_tpu.parallel.env import init_distributed_env  # noqa: E402


def main():
    init_distributed_env()
    rank = jax.process_index()
    results = {}
    for red in ("sum", "mean", "max", "min", "prod"):
        prog = fluid.Program()
        startup = fluid.Program()
        with fluid.program_guard(prog, startup):
            x = fluid.layers.data("x", shape=(2,), dtype="float32")
            out = fluid.layers.collective._allreduce(
                x, reduce_type=red)
        exe = fluid.Executor(fluid.CPUPlace())
        val = np.full((1, 2), float(rank + 1), np.float32)
        got = exe.run(prog, feed={"x": val},
                      fetch_list=[out.name])[0]
        results[red] = float(np.asarray(got).reshape(-1)[0])
    print("RESULT " + json.dumps({"rank": rank, "results": results}),
          flush=True)


if __name__ == "__main__":
    main()
