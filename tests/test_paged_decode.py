"""Paged KV cache + prefix reuse (models/decode_engine.py paged
layout + inference/serving.py PagedContinuousGenerationServer).

The invariants the paged design must hold:

* token-exact greedy parity with the dense whole-loop decode — through
  slot reuse, admission-order permutations, burst lengths, and across
  the hit/miss admission flavors (a prefix-HIT generation must be
  byte-identical to the cold one);
* the capacity claim is REAL: persistable KV bytes per admitted
  request are >= 2x lower paged vs dense at mixed lengths, and the XLA
  compiler's own ``memory_analysis()`` argument accounting agrees;
* zero steady-state compiles under a 100-request churn;
* block exhaustion fails with the NAMED retryable ``BlockPoolExhausted``
  — never a hang — and the server keeps serving afterwards;
* ``server_fingerprint`` separates KV layouts (paged vs dense, and
  differing block-pool geometry) so the runtime never dedupes/swaps
  them as "the same model";
* the block-pool observability surface (gauges + prefix-tier admission
  spans) exists and counts.
"""
import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.inference import (BlockPoolExhausted,
                                  ContinuousGenerationServer,
                                  PagedContinuousGenerationServer,
                                  apply_eos_sentinel,
                                  count_generated_tokens)
from paddle_tpu.models.decode_engine import (CacheConfig,
                                             HostBlockPool,
                                             PromptPrefixCache)

V, D, H, L, S, MAXT = 16, 32, 2, 1, 10, 32
# serving-bundle paged geometry (NP = 4 pages/lane): NB = n_slots *
# NP makes exhaustion IMPOSSIBLE, so parity/churn tests never see
# victims — the capacity arithmetic is pinned on the TIGHT bundle
# below, exhaustion on its own 1-block bundle
BS, NB, E = 8, 16, 3
END_ID = 1
N_SLOTS = 4


def _mixed_len_prompts(rng, n):
    """Terminator-copy prompts: random tokens with end_id planted at a
    random position — the trained copy model emits EOS there, so
    generations have MIXED lengths (short ones fit one block, the
    no-terminator tail runs to the buffer)."""
    src = rng.randint(3, V, (n, S)).astype(np.int64)
    for r in range(n):
        p = rng.randint(1, S + 1)
        if p < S:
            src[r, p:] = END_ID
    return src


@pytest.fixture(scope="module")
def trained():
    """Train the tiny terminator-copy transformer once; build the
    whole-loop oracle + dense AND paged bundles over the same
    scope-shared weights."""
    from paddle_tpu import unique_name
    from paddle_tpu.core.scope import Scope
    from paddle_tpu.models import transformer as T

    # param-init ops (uniform/gaussian_random) ride the GLOBAL seed,
    # which other suite tests mutate — pin it or the trained model
    # (and the oracle generation lengths the preconditions below rely
    # on) depends on which tests ran first
    fluid.seed(0)
    scope = Scope()
    with unique_name.guard():
        main, startup, loss = T.build_program(
            seq_len=S, d_model=D, n_heads=H, n_layers=L, d_inner=64,
            vocab=V, with_optimizer=False, dropout_rate=0.0)
        with fluid.program_guard(main, startup):
            fluid.optimizer.Adam(learning_rate=0.02).minimize(loss)
    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(7)
    for _ in range(200):
        src = _mixed_len_prompts(rng, 8)
        tgt_in = np.concatenate(
            [np.full((8, 1), 2, np.int64), src[:, :-1]], 1)
        exe.run(main, feed={"src_ids": src, "tgt_ids": tgt_in,
                            "label": src}, fetch_list=[loss],
                scope=scope)
    kwargs = dict(seq_len=S, max_out_len=MAXT, d_model=D, n_heads=H,
                  n_layers=L, d_inner=64, vocab=V, start_id=2,
                  end_id=END_ID)
    with unique_name.guard():
        inc_m, _, _, inc_buf = T.build_incremental_decode_program(
            **kwargs)
    with unique_name.guard():
        dense = T.build_decode_step_program(n_slots=N_SLOTS, **kwargs)
    with unique_name.guard():
        paged = T.build_decode_step_program(
            n_slots=N_SLOTS, state_prefix="@pg/",
            cache=CacheConfig(layout="paged", block_size=BS,
                              n_blocks=NB, n_prompt_entries=E),
            **kwargs)
    # the capacity-claim bundle: 2x the lanes of the dense pool in
    # FEWER KV bytes (blocks oversubscribed vs worst case — the
    # scheduler's pausing/backpressure absorbs the tail)
    with unique_name.guard():
        paged_tight = T.build_decode_step_program(
            n_slots=2 * N_SLOTS, state_prefix="@pgt/",
            cache=CacheConfig(layout="paged", block_size=BS,
                              n_blocks=10, n_prompt_entries=E),
            **kwargs)
    return {"exe": exe, "scope": scope, "inc_m": inc_m,
            "inc_buf": inc_buf, "dense": dense, "paged": paged,
            "paged_tight": paged_tight, "kwargs": kwargs}


def _oracle(tr, srcs):
    ref, = tr["exe"].run(tr["inc_m"], feed={"src_ids": srcs},
                         fetch_list=[tr["inc_buf"]],
                         scope=tr["scope"])
    return apply_eos_sentinel(np.asarray(ref), end_id=END_ID)


def _paged_server(tr, **kw):
    # radix_reuse=False: this module pins the paged-pool contracts
    # proper — full drain after retirement, hit-tier admissions for
    # repeat prompts. Under the default, retired generations' block
    # chains are ADOPTED into the radix tree (cross-request reuse,
    # ISSUE 17) so blocks_in_use stays >0 by design; that behavior
    # has its own coverage (test_radix_reuse, test_chunked_prefill).
    kw.setdefault("radix_reuse", False)
    return PagedContinuousGenerationServer(
        tr["paged"], executor=tr["exe"], scope=tr["scope"], **kw)


def _pick_long_prompts(tr, rng, n, min_tokens):
    """`n` no-terminator prompts whose ORACLE generations exceed
    `min_tokens` — selected by decode, not assumed, so the block-
    pressure scenarios stay valid under small model-init shifts."""
    cands = rng.randint(3, V, (24, S)).astype(np.int64)
    lens = count_generated_tokens(_oracle(tr, cands), END_ID)
    order = np.argsort(-lens)
    picked = cands[order[:n]]
    assert lens[order[n - 1]] > min_tokens, (
        f"model generates too short for the pressure scenario "
        f"(best lengths {sorted(lens)[-n:]})")
    return picked


class TestParity:
    def test_token_exact_vs_whole_loop_with_slot_reuse(self, trained):
        """12 mixed-length requests through 4 slots (3x reuse, block
        churn): every row must equal the whole-loop decode row, -1
        sentinel tails included."""
        srcs = _mixed_len_prompts(np.random.RandomState(11), 12)
        want = _oracle(trained, srcs)
        assert len(set((w != -1).sum() for w in want)) > 1, \
            "workload must have mixed output lengths"
        with _paged_server(trained) as srv:
            replies = [srv.submit(s) for s in srcs]
            got = np.stack([r.result(timeout=120.0) for r in replies])
            st = srv.stats()
        np.testing.assert_array_equal(got, want)
        assert st["completed"] == 12
        # retirement returned every block/entry to the pools
        bp = st["block_pool"]
        assert bp["blocks_in_use"] == 0
        assert bp["prompt_entries_in_use"] == 0

    def test_independent_of_admission_order(self, trained):
        srcs = _mixed_len_prompts(np.random.RandomState(13), 8)
        want = _oracle(trained, srcs)
        with _paged_server(trained) as srv:
            order = list(range(8))[::-1]
            replies = {i: srv.submit(srcs[i]) for i in order}
            got = np.stack([replies[i].result(timeout=120.0)
                            for i in range(8)])
        np.testing.assert_array_equal(got, want)

    def test_burst_length_does_not_move_tokens(self, trained):
        """steps_per_tick=1 vs the default burst vs exit-on-retire:
        dispatch boundaries move, tokens must not."""
        srcs = _mixed_len_prompts(np.random.RandomState(17), 6)
        want = _oracle(trained, srcs)
        for kw in (dict(steps_per_tick=1, drain_steps=1),
                   dict(steps_per_tick=6),
                   dict(exit_on_retire=True)):
            with _paged_server(trained, **kw) as srv:
                replies = [srv.submit(s) for s in srcs]
                got = np.stack([r.result(timeout=120.0)
                                for r in replies])
            np.testing.assert_array_equal(got, want, err_msg=str(kw))

    def _sync_drive(self, srv, srcs):
        """Drive the paged scheduler SINGLE-THREADED (plan -> fail ->
        cycle), so pause/preempt dynamics are deterministic instead
        of depending on submission/scheduler thread interleaving."""
        from paddle_tpu.inference import serving as SV

        replies = []
        for s in srcs:
            req = SV._GenRequest(np.asarray(s)[None].astype(np.int64),
                                 SV._Reply())
            srv._queue.append(req)
            replies.append(req.reply)
        guard = 0
        while srv._queue or any(l is not None for l in srv._lanes):
            guard += 1
            assert guard < 500, "scheduler failed to converge"
            failures = []
            with srv._cv:
                admits = srv._plan_admissions_locked(failures)
                drain = not srv._queue
                n, m, run = srv._plan_burst_locked(admits, drain,
                                                   failures)
            srv._fail_requests(failures)
            if run:
                srv._cycle(admits, n, m)
        return replies

    def test_parity_under_block_pressure_with_pausing(self, trained):
        """A pool too small for the concurrent mix forces the
        scheduler to PAUSE lanes at block boundaries (host-masked
        active flag; no shared-pool writes while parked) and resume
        them as retirements free blocks — tokens must stay exact
        through park/resume cycles (regression: an un-gated EOS latch
        froze paused lanes on garbage tokens; 7/192 wrong tokens)."""
        from paddle_tpu import unique_name
        from paddle_tpu.models import transformer as T

        with unique_name.guard():
            tight = T.build_decode_step_program(
                n_slots=6, state_prefix="@press/",
                cache=CacheConfig(layout="paged", block_size=BS,
                                  n_blocks=8, n_prompt_entries=4),
                **trained["kwargs"])
        rng = np.random.RandomState(43)
        longs = _pick_long_prompts(trained, rng, 2, 3 * BS)
        shorts = rng.randint(3, V, (10, S)).astype(np.int64)
        shorts[:, 3:] = END_ID  # every short fits one block
        srcs = np.concatenate([longs, shorts])
        want = _oracle(trained, srcs)
        assert all((w != -1).sum() > 3 * BS for w in want[:2]), \
            "precondition: the long rows must span all 4 pages"
        srv = PagedContinuousGenerationServer(
            tight, executor=trained["exe"], scope=trained["scope"],
            start=False, radix_reuse=False)  # see _paged_server
        try:
            replies = self._sync_drive(srv, srcs)
            got = np.stack([r.result(0) for r in replies])
            ps = srv.pool_stats()
        finally:
            srv.close()
        np.testing.assert_array_equal(got, want)
        assert ps["pause_events"] > 0, \
            "the pressure geometry must actually have paused a lane"
        assert ps["paused_lanes"] == 0  # everyone resumed + retired
        assert ps["blocks_in_use"] == 0

    def test_parity_under_lockstep_preemption(self, trained):
        """Lockstep full-length generations cross block boundaries
        simultaneously; when every live lane blocks on an empty free
        list the scheduler recompute-PREEMPTS the youngest (requeue,
        not failure), and the admission watermark keeps preempted
        work from stealing its own blocks back. Greedy decode is
        deterministic, so preempted requests re-decode
        byte-identically — parity and completion must survive."""
        from paddle_tpu import unique_name
        from paddle_tpu.models import transformer as T

        with unique_name.guard():
            tight = T.build_decode_step_program(
                n_slots=4, state_prefix="@lock/",
                cache=CacheConfig(layout="paged", block_size=BS,
                                  n_blocks=4, n_prompt_entries=4),
                **trained["kwargs"])
        rng = np.random.RandomState(47)
        longs = _pick_long_prompts(trained, rng, 4, BS)
        want = _oracle(trained, longs)
        assert all((w != -1).sum() > BS for w in want), \
            "precondition: every row must cross a block boundary"
        srv = PagedContinuousGenerationServer(
            tight, executor=trained["exe"], scope=trained["scope"],
            start=False)
        try:
            replies = self._sync_drive(srv, longs)
            got = np.stack([r.result(0) for r in replies])
            ps = srv.pool_stats()
            st = srv.stats()
        finally:
            srv.close()
        np.testing.assert_array_equal(got, want)
        assert st["completed"] == 4
        assert ps["preemptions"] > 0, \
            "lockstep full-buffer rows on a tiny pool must preempt"

    def test_prefix_hit_generation_byte_identical_to_cold(self,
                                                          trained):
        """The same prompt served cold (miss: encoder prefill) and
        again as a prefix HIT (encoder-free admission reusing the
        pooled cross-KV entry) must produce byte-identical rows —
        and the hit must actually have taken the hit path."""
        src = _mixed_len_prompts(np.random.RandomState(19), 1)[0]
        want = _oracle(trained, src[None])[0]
        with _paged_server(trained) as srv:
            cold = srv.submit(src).result(timeout=120.0)
            h0 = srv.pool_stats()["prefix_hits"]
            hot = srv.submit(src).result(timeout=120.0)
            ps = srv.pool_stats()
        np.testing.assert_array_equal(cold, want)
        np.testing.assert_array_equal(hot, want)
        assert ps["prefix_hits"] == h0 + 1
        assert ps["prefix_misses"] >= 1

    def test_partial_prefix_is_cow_not_reuse(self, trained):
        """A prompt sharing only a leading block with a cached one is
        the 'partial' tier: re-prefilled (bidirectional encoder — only
        full-content matches may share) and counted as a COW copy;
        tokens still exact."""
        rng = np.random.RandomState(23)
        a = rng.randint(3, V, (S,)).astype(np.int64)
        b = a.copy()
        b[BS:] = (b[BS:] % (V - 4)) + 3  # same first block, new tail
        want = _oracle(trained, np.stack([a, b]))
        with _paged_server(trained) as srv:
            got_a = srv.submit(a).result(timeout=120.0)
            got_b = srv.submit(b).result(timeout=120.0)
            ps = srv.pool_stats()
        np.testing.assert_array_equal(np.stack([got_a, got_b]), want)
        assert ps["cow_copies"] >= 1


class TestMemory:
    def _kv_per_request(self, bundle):
        return bundle.kv_state_bytes() / bundle.n_slots

    def test_paged_kv_bytes_per_request_at_least_2x_lower(self,
                                                          trained):
        """The capacity lever: the paged pool serves 2x the lanes of
        the dense bundle in FEWER total KV bytes, so KV bytes per
        admitted request drop >= 2x (same claim the bench makes at
        the r10 serving geometry)."""
        assert trained["paged_tight"].kv_state_bytes() \
            <= trained["dense"].kv_state_bytes()
        dense = self._kv_per_request(trained["dense"])
        paged = self._kv_per_request(trained["paged_tight"])
        assert paged * 2 <= dense, (paged, dense)

    def test_memory_analysis_agrees(self, trained):
        """The XLA compiler's own argument accounting must show the
        KV saving (r5 learning: memory_analysis is valid on the CPU
        backend for schedule/state-level comparisons) — the
        spec-derived byte claim above is not just arithmetic."""
        import jax

        from paddle_tpu.core.executor import RNG_VAR

        exe, scope = trained["exe"], trained["scope"]

        def arg_bytes(bundle):
            srv = ContinuousGenerationServer if \
                bundle.cache.layout == "dense" \
                else PagedContinuousGenerationServer
            s = srv(bundle, executor=exe, scope=scope, start=False)
            try:
                c = s._serves[0]._compiled
                mut = exe._scope_state(scope, c.state_in, None)
                const = exe._scope_state(scope, c.const_in, None)
                rng = scope._get(RNG_VAR)
                if rng is None:
                    rng = jax.random.PRNGKey(0)
                feed = {"n_steps": np.array([1], np.int64),
                        "min_active": np.array([0], np.int64)}
                m = c.fn.lower(mut, const, feed,
                               rng).compile().memory_analysis()
                return int(m.argument_size_in_bytes)
            finally:
                s.close()

        dense_b = arg_bytes(trained["dense"])
        paged_b = arg_bytes(trained["paged_tight"])
        predicted = trained["dense"].kv_state_bytes() \
            - trained["paged_tight"].kv_state_bytes()
        assert predicted > 0
        measured = dense_b - paged_b
        # params are identical across layouts, so the argument delta
        # tracks the KV-state delta (slack: the tight bundle carries
        # 2x the token/flag rows, and int64 state canonicalizes to
        # int32 on device)
        assert measured >= 0.7 * predicted, (measured, predicted)


class TestChurnAndCompiles:
    def test_100_request_churn_zero_steady_state_compiles(self,
                                                          trained):
        exe = trained["exe"]
        srv = _paged_server(trained)
        try:
            warmed = exe.compile_count
            srcs = _mixed_len_prompts(np.random.RandomState(29), 100)
            replies = [srv.submit(s) for s in srcs]
            got = [r.result(timeout=300.0) for r in replies]
            st = srv.stats()
        finally:
            srv.close()
        assert len(got) == 100
        assert exe.compile_count == warmed, (
            f"steady-state traffic compiled "
            f"{exe.compile_count - warmed} fresh executable(s)")
        assert st["completed"] == 100
        bp = st["block_pool"]
        assert bp["blocks_in_use"] == 0
        assert bp["prefix_hits"] + bp["prefix_misses"] \
            + bp["cow_copies"] == 100


class TestExhaustion:
    def test_block_exhaustion_named_retryable_error_not_hang(
            self, trained):
        """A 1-block pool cannot hold a full-buffer generation: the
        request must FAIL with the named retryable BlockPoolExhausted
        (not hang), and the server must keep serving block-sized
        requests afterwards."""
        from paddle_tpu import unique_name
        from paddle_tpu.models import transformer as T

        with unique_name.guard():
            tiny = T.build_decode_step_program(
                n_slots=2, state_prefix="@tiny/",
                cache=CacheConfig(layout="paged", block_size=BS,
                                  n_blocks=1, n_prompt_entries=2),
                **trained["kwargs"])
        rng = np.random.RandomState(31)
        long_src = _pick_long_prompts(trained, rng, 1, BS)[0]
        want_long = _oracle(trained, long_src[None])[0]
        assert (want_long != -1).sum() > BS, \
            "precondition: the no-terminator prompt must decode past " \
            "one block"
        short_src = long_src.copy()
        short_src[2:] = END_ID  # copies the terminator early
        want_short = _oracle(trained, short_src[None])[0]
        assert (want_short != -1).sum() <= BS, \
            "precondition: the short prompt must fit one block"
        srv = PagedContinuousGenerationServer(
            tiny, executor=trained["exe"], scope=trained["scope"])
        try:
            t0 = time.monotonic()
            with pytest.raises(BlockPoolExhausted) as ei:
                srv.submit(long_src).result(timeout=60.0)
            assert time.monotonic() - t0 < 60.0  # failed, not hung
            assert ei.value.retryable is True
            got = srv.submit(short_src).result(timeout=60.0)
        finally:
            srv.close()
        np.testing.assert_array_equal(got, want_short)


class TestFingerprints:
    def test_kv_layout_separates_server_fingerprints(self, trained):
        """Two servers differing only in KV layout (or block-pool
        geometry) must not dedupe/hot-swap as the same fingerprint
        (inference/runtime/registry.py)."""
        from paddle_tpu import unique_name
        from paddle_tpu.inference.runtime.registry import \
            server_fingerprint
        from paddle_tpu.models import transformer as T

        exe, scope = trained["exe"], trained["scope"]
        fp_dense = server_fingerprint(ContinuousGenerationServer(
            trained["dense"], executor=exe, scope=scope, start=False))
        fp_paged = server_fingerprint(PagedContinuousGenerationServer(
            trained["paged"], executor=exe, scope=scope, start=False))
        assert fp_dense != fp_paged
        # geometry matters too: same layout, different block_size
        with unique_name.guard():
            other = T.build_decode_step_program(
                n_slots=N_SLOTS, state_prefix="@pg2/",
                cache=CacheConfig(layout="paged", block_size=BS // 2,
                                  n_blocks=NB, n_prompt_entries=E),
                **trained["kwargs"])
        fp_other = server_fingerprint(PagedContinuousGenerationServer(
            other, executor=exe, scope=scope, start=False))
        assert fp_other != fp_paged

    def test_compile_cache_keys_differ_per_layout(self, trained):
        """Program.fingerprint (the disk compile-cache key component)
        must already separate the serve executables — pool var shapes
        and ops are hashed."""
        d = trained["dense"].serves[0].fingerprint()
        p = trained["paged"].serves[0].fingerprint()
        assert d != p


class TestObservability:
    def test_blockpool_gauges_and_admission_tier_spans(self, trained):
        """Block-pool gauges ride the uniquely-labeled pull provider;
        at FLAGS_observability=trace the admission span carries the
        prefix tier so the flight recorder explains slow (miss:
        encoder prefill) vs fast (hit) admissions."""
        from paddle_tpu import observability as obs
        from paddle_tpu.flags import FLAGS, set_flags

        src = _mixed_len_prompts(np.random.RandomState(37), 1)[0]
        prev = FLAGS.observability
        set_flags({"FLAGS_observability": "trace"})
        try:
            with _paged_server(trained) as srv:
                srv.submit(src).result(timeout=120.0)
                srv.submit(src).result(timeout=120.0)  # prefix hit
                label = srv._obs_id
                expo = obs.metrics.expose()
            with obs.TRACER._lock:
                traces = list(obs.TRACER.completed)
        finally:
            set_flags({"FLAGS_observability": prev})
        assert f'paddle_tpu_blockpool_blocks_in_use{{server="' \
               f'{label}"}}' in expo
        assert "paddle_tpu_blockpool_prefix_hits_total" in expo
        tiers = [sp["attrs"]["prefix"] for t in traces
                 for sp in t.timeline()["spans"]
                 if sp["name"] == "slotpool.queue"
                 and "prefix" in sp.get("attrs", {})]
        assert "miss" in tiers and "hit" in tiers, tiers


class TestHostAllocators:
    """The host half of the paging design is plain Python — pin it
    directly (the device tests above exercise it end to end)."""

    def test_block_pool_freelist(self):
        pool = HostBlockPool(3)
        got = [pool.alloc() for _ in range(3)]
        assert sorted(got) == [0, 1, 2] and pool.alloc() is None
        assert pool.in_use == 3
        pool.free(got[:2])
        assert pool.free_count == 2
        with pytest.raises(ValueError):
            pool.free([got[0]])  # double free

    def test_prefix_cache_tiers_refcounts_eviction(self):
        pc = PromptPrefixCache(2, chunk_tokens=2)
        p1, p2, p3 = (1, 2, 3, 4), (1, 2, 9, 9), (5, 6, 7, 8)
        assert pc.lookup(p1) == ("miss", None)
        e1 = pc.acquire_fresh(p1)
        assert pc.lookup(p1) == ("hit", e1)
        assert pc.lookup(p2)[0] == "partial"  # shares chunk (1, 2)
        e2 = pc.acquire_fresh(p2, partial=True)
        assert pc.partials == 1 and pc.misses == 1
        # both pinned: a third cold prompt cannot get an entry
        assert pc.acquire_fresh(p3) is None
        pc.release(e1)
        e3 = pc.acquire_fresh(p3)  # evicts the unpinned p1 entry
        assert e3 == e1 and pc.evictions == 1
        # p1's entry is gone, but the still-cached p2 shares its
        # leading chunk -> the correct post-eviction tier is partial
        assert pc.lookup(p1) == ("partial", None)
        assert pc.acquire_hit(p2) == e2 and pc.hits == 1
        pc.release(e2)
        pc.release(e2)  # acquired twice (fresh + hit): two releases
        pc.release(e3)
        # both unpinned; the hit moved p2 to MRU, so LRU-first is p3
        pc.acquire_fresh((7, 7, 7, 7))  # evicts p3
        # nothing cached shares p3's head (5, 6) -> true miss; the
        # recently-used p2 survived the eviction
        assert pc.lookup(p3) == ("miss", None)
        assert pc.lookup(p2) == ("hit", e2)


class TestMaskedPoolWriteOp:
    def test_numpy_oracle(self):
        """Kernel semantics vs a numpy oracle: gated rows land, keep
        mask preserves untouched cells, out-of-range indices drop,
        gate-0 rows write nothing."""
        from op_test import OpTest

        rng = np.random.RandomState(0)
        pool = rng.randn(3, 4, 2, 5).astype(np.float32)  # lead 2 -> 12
        new = rng.randn(4, 2, 5).astype(np.float32)
        idx = np.array([0, 7, 99, 3], np.int32)   # 99 out of range
        gate = np.array([1.0, 1.0, 1.0, 0.0], np.float32)
        want = pool.reshape(12, 10).copy()
        for r in range(4):
            if gate[r] and 0 <= idx[r] < 12:
                want[idx[r]] = new[r].reshape(10)
        want = want.reshape(3, 4, 2, 5)

        class T(OpTest):
            def runTest(self):
                pass

        t = T()
        t.setUp()
        t.op_type = "masked_pool_write"
        t.inputs = {"Pool": pool, "New": new, "Index": idx,
                    "Gate": gate}
        t.attrs = {"leading_dims": 2,
                   "exclusive_via": "block_table"}
        t.outputs = {"Out": want}
        t.check_output()


class TestPagedAttentionKernel:
    """Interpret-mode validation of the Pallas paged-attention stub
    (ops/pallas/paged_attention.py) against its jnp oracle — the
    kernel is NOT routed into the decode programs yet (CLAUDE.md: A/B
    on the real chip first; the tunnel has been down since r2), but
    its code path must stay correct for when the chip returns."""

    def test_interpret_mode_matches_reference(self):
        import jax.numpy as jnp

        from paddle_tpu.ops.pallas import attention as base
        from paddle_tpu.ops.pallas import paged_attention as pa

        rng = np.random.RandomState(5)
        R, Hh, Dh, NBk, BSk, NP = 5, 2, 64, 7, 8, 3
        q = rng.randn(R, Hh, Dh).astype(np.float32)
        pk = rng.randn(NBk, BSk, Hh, Dh).astype(np.float32)
        pv = rng.randn(NBk, BSk, Hh, Dh).astype(np.float32)
        # distinct blocks per lane (the allocator invariant)
        tab = np.stack([rng.permutation(NBk)[:NP]
                        for _ in range(R)]).astype(np.int32)
        step = rng.randint(0, NP * BSk, (R,)).astype(np.int32)
        assert pa.usable(jnp.asarray(q), jnp.asarray(pk), tab) \
            is False  # CPU without interpret mode: gated off
        base.force_interpret(True)
        try:
            assert pa.usable(jnp.asarray(q), jnp.asarray(pk), tab)
            got = np.asarray(pa.paged_decode_attention(
                jnp.asarray(q), jnp.asarray(pk), jnp.asarray(pv),
                jnp.asarray(tab), jnp.asarray(step), scale=0.125))
        finally:
            base.force_interpret(False)
        want = np.asarray(pa.paged_decode_attention_reference(
            jnp.asarray(q), jnp.asarray(pk), jnp.asarray(pv),
            jnp.asarray(tab), jnp.asarray(step), scale=0.125))
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)
