"""1F1B pipeline schedule (parallel/pipeline_1f1b.py): loss parity
with the Executor and the GPipe schedule, the stashed-activation
memory win (VERDICT r4 next #3 — proved via compiled.memory_analysis()
on the CPU backend, no chip needed), and the named unsupported cases.
"""
import numpy as np
import pytest

import jax

import paddle_tpu as fluid
from paddle_tpu.parallel.mesh import make_mesh, MeshConfig
from paddle_tpu.parallel.pipeline_program import (
    PipelineTrainer, PipelinePartitionError, propose_loops)


def _fresh():
    fluid._reset_global_scope()
    from paddle_tpu import unique_name
    unique_name.switch()


def _build_mlp(n_layers=4, seed=11):
    prog, startup = fluid.Program(), fluid.Program()
    prog._seed = seed
    with fluid.program_guard(prog, startup):
        x = fluid.layers.data(name="x", shape=[16], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="int64")
        h = x
        bounds = [h.name]
        for i in range(n_layers):
            h = fluid.layers.fc(
                h, size=16, act="tanh",
                param_attr=fluid.ParamAttr(name=f"l{i}_w"),
                bias_attr=fluid.ParamAttr(name=f"l{i}_b"))
            bounds.append(h.name)
        logits = fluid.layers.fc(
            h, size=3, param_attr=fluid.ParamAttr(name="head_w"),
            bias_attr=fluid.ParamAttr(name="head_b"))
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, y))
        fluid.optimizer.Adam(0.01).minimize(loss)
    return prog, startup, loss, bounds


def _mlp_data():
    rng = np.random.RandomState(0)
    xs = rng.randn(32, 16).astype(np.float32)
    ys = np.argmax(xs[:, :3], 1).astype(np.int64)[:, None]
    return xs, ys


def _exec_losses(prog, startup, loss, feed, steps):
    exe = fluid.Executor(fluid.CPUPlace())
    sc = fluid.Scope()
    exe.run(startup, scope=sc)
    out = []
    for _ in range(steps):
        l, = exe.run(prog, feed=feed, fetch_list=[loss], scope=sc)
        out.append(float(np.asarray(l).reshape(-1)[0]))
    return out


def _trainer_losses(prog, startup, loss, loops, feed, steps, mesh,
                    n_micro, schedule):
    exe = fluid.Executor(fluid.CPUPlace())
    sc = fluid.Scope()
    exe.run(startup, scope=sc)
    tr = PipelineTrainer(prog, loss, loops=loops, mesh=mesh,
                         n_micro=n_micro, schedule=schedule)
    tr.initialize(sc)
    out = []
    for _ in range(steps):
        l, = tr.run(feed=feed)
        out.append(float(np.asarray(l).reshape(-1)[0]))
    return out, tr, sc


def _build_moe(seed=5, **kw):
    from paddle_tpu.models import moe_transformer as M

    _fresh()
    args = dict(seq_len=8, vocab=64, d_model=32, n_heads=2,
                n_layers=4, d_inner=64, n_experts=4,
                dropout_rate=0.0, learning_rate=1.0, warmup_steps=40)
    args.update(kw)
    main, startup, cost = M.build_program(**args)
    main._seed = seed
    return main, startup, cost


def _moe_data(B=16, T=8, V=64, seed=0):
    r = np.random.RandomState(seed)
    return {k: r.randint(1, V, (B, T)).astype(np.int64)
            for k in ("src_ids", "label")}


class TestMlpParity:
    def test_pp2_parity_with_executor(self):
        xs, ys = _mlp_data()
        prog, startup, loss, bounds = _build_mlp()
        base = _exec_losses(prog, startup, loss, {"x": xs, "y": ys}, 6)
        _fresh()
        prog2, startup2, loss2, bounds2 = _build_mlp()
        mesh = make_mesh(MeshConfig(pp=2), devices=jax.devices()[:2])
        got, _, _ = _trainer_losses(prog2, startup2, loss2, [bounds2],
                                    {"x": xs, "y": ys}, 6, mesh, 4,
                                    "1f1b")
        np.testing.assert_allclose(base, got, rtol=2e-4, atol=2e-5)

    def test_pp4_nmicro8_parity(self):
        xs, ys = _mlp_data()
        prog, startup, loss, bounds = _build_mlp(8)
        base = _exec_losses(prog, startup, loss, {"x": xs, "y": ys}, 5)
        _fresh()
        prog2, startup2, loss2, bounds2 = _build_mlp(8)
        mesh = make_mesh(MeshConfig(pp=4), devices=jax.devices()[:4])
        got, _, _ = _trainer_losses(prog2, startup2, loss2, [bounds2],
                                    {"x": xs, "y": ys}, 5, mesh, 8,
                                    "1f1b")
        np.testing.assert_allclose(base, got, rtol=2e-4, atol=2e-5)

    def test_nmicro_smaller_than_pp(self):
        """Degenerate bubble-heavy case: schedule must stay correct."""
        xs, ys = _mlp_data()
        prog, startup, loss, bounds = _build_mlp(8)
        base = _exec_losses(prog, startup, loss, {"x": xs, "y": ys}, 3)
        _fresh()
        prog2, startup2, loss2, bounds2 = _build_mlp(8)
        mesh = make_mesh(MeshConfig(pp=4), devices=jax.devices()[:4])
        got, _, _ = _trainer_losses(prog2, startup2, loss2, [bounds2],
                                    {"x": xs, "y": ys}, 3, mesh, 2,
                                    "1f1b")
        np.testing.assert_allclose(base, got, rtol=2e-4, atol=2e-5)


class TestMoEFlagship:
    """Head (embedding) vjp, reduce-out cotangent ring (Switch aux in
    the cost), per-microbatch tail — on the round-4 flagship."""

    def test_1f1b_matches_gpipe_exactly(self):
        feed = _moe_data()
        mesh = make_mesh(MeshConfig(pp=2), devices=jax.devices()[:2])
        main, startup, cost = _build_moe()
        loops = propose_loops(main, cost.name)
        gp, _, _ = _trainer_losses(main, startup, cost, loops, feed,
                                   5, mesh, 4, "gpipe")
        main2, startup2, cost2 = _build_moe()
        loops2 = propose_loops(main2, cost2.name)
        f1, _, _ = _trainer_losses(main2, startup2, cost2, loops2,
                                   feed, 5, mesh, 4, "1f1b")
        # the Switch aux is LINEAR in the per-layer auxes, so the
        # per-microbatch tail reproduces GPipe's microbatch-mean
        # semantics to float tolerance
        np.testing.assert_allclose(gp, f1, rtol=5e-5, atol=5e-6)

    def test_near_parity_with_executor_and_trains(self):
        feed = _moe_data()
        main, startup, cost = _build_moe()
        base = _exec_losses(main, startup, cost, feed, 5)
        main2, startup2, cost2 = _build_moe()
        loops = propose_loops(main2, cost2.name)
        mesh = make_mesh(MeshConfig(pp=2), devices=jax.devices()[:2])
        got, _, _ = _trainer_losses(main2, startup2, cost2, loops,
                                    feed, 5, mesh, 4, "1f1b")
        assert all(np.isfinite(got))
        assert got[-1] < got[0]
        assert max(abs(a - b) for a, b in zip(base, got)) < 0.15

    def test_dropout_matches_gpipe(self):
        """The backward tick recomputes the stage with the same rng
        derivation as the forward tick, so dropout masks reproduce and
        GPipe/1F1B agree even with sampling ops in the loop + head."""
        feed = _moe_data()
        mesh = make_mesh(MeshConfig(pp=2), devices=jax.devices()[:2])
        main, startup, cost = _build_moe(dropout_rate=0.1)
        loops = propose_loops(main, cost.name)
        gp, _, _ = _trainer_losses(main, startup, cost, loops, feed,
                                   5, mesh, 4, "gpipe")
        main2, startup2, cost2 = _build_moe(dropout_rate=0.1)
        loops2 = propose_loops(main2, cost2.name)
        f1, _, _ = _trainer_losses(main2, startup2, cost2, loops2,
                                   feed, 5, mesh, 4, "1f1b")
        np.testing.assert_allclose(gp, f1, rtol=5e-5, atol=5e-6)
        assert f1[-1] < f1[0]


class TestMemoryWin:
    """The point of 1F1B: in-flight activations bounded by pp, not
    n_micro. Proved with the XLA compiler's own buffer stats
    (compiled.memory_analysis()), chip-free on the CPU backend."""

    def _compile_temp_bytes(self, schedule, n_micro, mesh):
        main, startup, cost = _build_moe(
            seq_len=32, vocab=128, d_model=64, n_heads=4, n_layers=8,
            d_inner=256)
        loops = propose_loops(main, cost.name)
        exe = fluid.Executor(fluid.CPUPlace())
        sc = fluid.Scope()
        exe.run(startup, scope=sc)
        tr = PipelineTrainer(main, cost, loops=loops, mesh=mesh,
                             n_micro=n_micro, schedule=schedule)
        tr.initialize(sc)
        r = np.random.RandomState(0)
        feeds = {k: r.randint(1, 128, (32, 32)).astype(np.int64)
                 for k in ("src_ids", "label")}
        comp = jax.jit(tr._build_step(), donate_argnums=(0,)).lower(
            tr.state, feeds, tr._rng).compile()
        return comp.memory_analysis().temp_size_in_bytes

    def test_pp4_nmicro8_temp_memory_win(self):
        mesh = make_mesh(MeshConfig(pp=4), devices=jax.devices()[:4])
        tg = self._compile_temp_bytes("gpipe", 8, mesh)
        tf = self._compile_temp_bytes("1f1b", 8, mesh)
        # measured on this config: ~37.5 MB vs ~14.3 MB (2.6x); keep
        # headroom against compiler-version noise
        assert tf < tg / 1.5, (tg, tf)


class TestNamedErrors:
    def test_two_loop_program_rejected(self):
        """Encoder+decoder transformers have two stacks; 1F1B handles
        one loop and must say so."""
        from paddle_tpu.models import transformer as T

        _fresh()
        main, startup, loss = T.build_program(
            seq_len=8, d_model=32, n_heads=2, n_layers=4, d_inner=64,
            vocab=60, dropout_rate=0.0, learning_rate=1.0,
            warmup_steps=40)
        main._seed = 5
        loops = propose_loops(main, loss.name)
        assert len(loops) == 2
        mesh = make_mesh(MeshConfig(pp=2), devices=jax.devices()[:2])
        exe = fluid.Executor(fluid.CPUPlace())
        sc = fluid.Scope()
        exe.run(startup, scope=sc)
        tr = PipelineTrainer(main, loss, loops=loops, mesh=mesh,
                             n_micro=4, schedule="1f1b")
        tr.initialize(sc)
        r = np.random.RandomState(0)
        feed = {k: r.randint(1, 60, (8, 8)).astype(np.int64)
                for k in ("src_ids", "tgt_ids", "label")}
        with pytest.raises(PipelinePartitionError,
                           match="exactly one|gpipe"):
            tr.run(feed=feed)

    def test_tp_composition_rejected(self):
        """tp-sharded params would force GSPMD collectives inside the
        schedule's divergent lax.cond branches — a deadlock on real
        meshes, so it must be a named error pointing at gpipe."""
        xs, ys = _mlp_data()
        _fresh()
        prog, startup, loss, bounds = _build_mlp()
        exe = fluid.Executor(fluid.CPUPlace())
        sc = fluid.Scope()
        exe.run(startup, scope=sc)
        mesh = make_mesh(MeshConfig(pp=2, tp=2),
                         devices=jax.devices()[:4])
        tr = PipelineTrainer(prog, loss, loops=[bounds], mesh=mesh,
                             n_micro=4, schedule="1f1b")
        tr.initialize(sc)
        with pytest.raises(PipelinePartitionError, match="gpipe"):
            tr.run(feed={"x": xs, "y": ys})

    def test_pp1_rejected(self):
        xs, ys = _mlp_data()
        _fresh()
        prog, startup, loss, bounds = _build_mlp()
        exe = fluid.Executor(fluid.CPUPlace())
        sc = fluid.Scope()
        exe.run(startup, scope=sc)
        tr = PipelineTrainer(prog, loss, loops=[bounds],
                             schedule="1f1b")
        tr.initialize(sc)
        with pytest.raises(PipelinePartitionError, match="pp"):
            tr.run(feed={"x": xs, "y": ys})

    def test_bad_schedule_name(self):
        prog, startup, loss, bounds = _build_mlp()
        with pytest.raises(ValueError, match="gpipe"):
            PipelineTrainer(prog, loss, loops=[bounds],
                            schedule="interleaved")


class TestCompiledProgramAPI:
    def test_pp_schedule_flag(self):
        xs, ys = _mlp_data()
        prog, startup, loss, bounds = _build_mlp()
        base = _exec_losses(prog, startup, loss, {"x": xs, "y": ys}, 4)
        _fresh()
        prog2, startup2, loss2, _ = _build_mlp()
        exe = fluid.Executor(fluid.CPUPlace())
        sc = fluid.Scope()
        exe.run(startup2, scope=sc)
        mesh = make_mesh(MeshConfig(pp=2), devices=jax.devices()[:2])
        cp = fluid.CompiledProgram(prog2).with_data_parallel(
            loss_name=loss2.name, mesh=mesh, n_micro=4,
            pp_schedule="1f1b")
        got = []
        for _ in range(4):
            l, = exe.run(cp, feed={"x": xs, "y": ys},
                         fetch_list=[loss2], scope=sc)
            got.append(float(np.asarray(l).reshape(-1)[0]))
        np.testing.assert_allclose(base, got, rtol=5e-4, atol=5e-5)
