"""Batched, bucketed inference serving (inference/serving.py).

Covers: DynamicBatcher demux correctness, bucket-ladder executable
bounds, aot_warmup cache seeding, clone() cache sharing (zero compiles
on a warmed worker), device-resident generation parity at padded
buckets (EOS/-1 sentinel included), observability counters, and the
batched-vs-naive throughput regression guard.
"""
import threading
import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.inference import (AnalysisConfig, GenerationServer,
                                  InferenceServer, PaddleTensor,
                                  apply_eos_sentinel,
                                  create_paddle_predictor,
                                  default_batch_buckets)
from paddle_tpu.inference.serving import ProgramRunner


def _export_tiny_fc(tmpdir, in_dim=8, hidden=16, classes=4):
    """Untrained (but deterministically initialized) fc model exported
    for predictor tests -- serving correctness does not need training."""
    x = fluid.layers.data(name="x", shape=[in_dim], dtype="float32")
    h = fluid.layers.fc(input=x, size=hidden, act="relu")
    out = fluid.layers.fc(input=h, size=classes, act="softmax")
    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(fluid.default_startup_program())
    fluid.save_inference_model(str(tmpdir), ["x"], [out], exe)
    return out


class TestDynamicBatcher:
    def test_demux_matches_naive_per_request(self, tmp_path):
        _export_tiny_fc(tmp_path)
        pred = create_paddle_predictor(AnalysisConfig(str(tmp_path)))
        r = np.random.RandomState(0)
        reqs = [r.randn(rows, 8).astype(np.float32)
                for rows in (1, 3, 2, 1, 4, 2, 1)]
        naive = [pred.run([PaddleTensor(a, name="x")])[0].data
                 for a in reqs]
        with InferenceServer(pred, max_batch_size=8,
                             max_wait_ms=30.0) as srv:
            replies = [srv.submit({"x": a}) for a in reqs]
            got = [rep.result(timeout=60.0)[0] for rep in replies]
        for g, n, a in zip(got, naive, reqs):
            assert g.shape == n.shape == (a.shape[0], 4)
            np.testing.assert_allclose(g, n, rtol=1e-5, atol=1e-6)

    def test_batches_actually_form(self, tmp_path):
        """Requests queued together must ride ONE padded executable
        call, not one dispatch each."""
        _export_tiny_fc(tmp_path)
        pred = create_paddle_predictor(AnalysisConfig(str(tmp_path)))
        srv = InferenceServer(pred, max_batch_size=8, max_wait_ms=60.0,
                              start=False)
        x = np.ones((1, 8), np.float32)
        srv.start()
        replies = [srv.submit({"x": x}) for _ in range(5)]
        for rep in replies:
            rep.result(timeout=60.0)
        st = srv.stats()
        srv.close()
        assert st["requests"] == 5
        assert st["batches"] == 1          # one micro-batch
        assert st["rows"] == 5
        assert st["padded_rows"] == 8      # bucketed 5 -> 8
        assert st["batch_occupancy"] == pytest.approx(5 / 8)

    def test_max_wait_flushes_partial_batch(self, tmp_path):
        _export_tiny_fc(tmp_path)
        pred = create_paddle_predictor(AnalysisConfig(str(tmp_path)))
        with InferenceServer(pred, max_batch_size=8,
                             max_wait_ms=5.0) as srv:
            t0 = time.monotonic()
            out = srv.infer({"x": np.ones((1, 8), np.float32)},
                            timeout=60.0)
            waited = time.monotonic() - t0
        assert out[0].shape == (1, 4)
        assert waited < 30.0  # flushed by deadline, not stuck at 8 rows

    def test_oversize_request_rejected(self, tmp_path):
        _export_tiny_fc(tmp_path)
        pred = create_paddle_predictor(AnalysisConfig(str(tmp_path)))
        with InferenceServer(pred, max_batch_size=4) as srv:
            with pytest.raises(ValueError, match="max_batch_size"):
                srv.submit({"x": np.ones((5, 8), np.float32)})

    def test_closed_server_fails_pending_and_rejects_new(self, tmp_path):
        _export_tiny_fc(tmp_path)
        pred = create_paddle_predictor(AnalysisConfig(str(tmp_path)))
        srv = InferenceServer(pred, max_batch_size=8)
        srv.close()
        with pytest.raises(RuntimeError, match="closed"):
            srv.submit({"x": np.ones((1, 8), np.float32)})

    def test_config_knobs_flow_into_server(self, tmp_path):
        _export_tiny_fc(tmp_path)
        cfg = AnalysisConfig(str(tmp_path))
        cfg.enable_dynamic_batching(max_batch_size=16, max_wait_ms=7.0,
                                    batch_buckets=(2, 16))
        pred = create_paddle_predictor(cfg)
        with InferenceServer(pred) as srv:
            assert srv.max_batch_size == 16
            assert srv.max_wait_ms == 7.0
            assert srv.batch_buckets == [2, 16]
        # explicit constructor args take precedence over the config
        with InferenceServer(pred, max_batch_size=4,
                             batch_buckets=(1, 4)) as srv:
            assert srv.max_batch_size == 4
            assert srv.batch_buckets == [1, 4]
            assert srv.max_wait_ms == 7.0  # config still fills gaps


class TestBucketsAndWarmup:
    def test_default_ladder(self):
        assert default_batch_buckets(8) == [1, 2, 4, 8]
        assert default_batch_buckets(6) == [1, 2, 4, 6]
        assert default_batch_buckets(1) == [1]

    def test_aot_warmup_seeds_every_bucket(self, tmp_path):
        """After warmup, mixed-shape traffic produces ZERO fresh
        compiles: warmup seeded the Executor cache under exactly the
        keys real traffic hits."""
        _export_tiny_fc(tmp_path)
        pred = create_paddle_predictor(AnalysisConfig(str(tmp_path)))
        with InferenceServer(pred, max_batch_size=8,
                             max_wait_ms=1.0) as srv:
            warmed = srv.aot_warmup()
            assert warmed == len(srv.batch_buckets) == 4
            exe = pred._exe
            before = exe.compile_count
            r = np.random.RandomState(1)
            for rows in (1, 2, 3, 5, 8, 4, 7, 1):
                srv.infer({"x": r.randn(rows, 8).astype(np.float32)},
                          timeout=60.0)
            assert exe.compile_count == before  # all cache hits
            assert exe.cache_hit_count > 0

    def test_seq_bucketing_bounds_shapes(self):
        """Declared -1 sequence dims pad up the seq ladder; outputs
        come back at the padded length (fixed-size padded convention)
        and real positions match the unpadded run."""
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            x = fluid.layers.data("x", shape=[-1, 4], dtype="float32")
            out = fluid.layers.scale(x, scale=3.0)
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(startup)
        runner = ProgramRunner(prog, ["x"], [out.name], executor=exe,
                               scope=fluid.global_scope())
        r = np.random.RandomState(2)
        with InferenceServer(runner, max_batch_size=4, max_wait_ms=2.0,
                             seq_buckets=(4, 8)) as srv:
            a3 = r.randn(1, 3, 4).astype(np.float32)   # T=3 -> 4
            a5 = r.randn(2, 5, 4).astype(np.float32)   # T=5 -> 8
            o3 = srv.infer({"x": a3}, timeout=60.0)[0]
            o5 = srv.infer({"x": a5}, timeout=60.0)[0]
        assert o3.shape == (1, 4, 4)
        assert o5.shape == (2, 8, 4)
        np.testing.assert_allclose(o3[:, :3], a3 * 3.0, rtol=1e-6)
        np.testing.assert_allclose(o5[:, :5], a5 * 3.0, rtol=1e-6)
        # both buckets compiled at the batch buckets actually used
        assert exe.compile_count <= 2 * len(srv.batch_buckets)


class TestSharedExecutableCache:
    def test_clone_serves_warmed_buckets_with_zero_compiles(
            self, tmp_path):
        """AnalysisPredictor.clone() shares the parent's compiled
        cache: a warmed bucket costs a cloned worker NOTHING (the old
        behavior recompiled per worker)."""
        _export_tiny_fc(tmp_path)
        pred = create_paddle_predictor(AnalysisConfig(str(tmp_path)))
        with InferenceServer(pred, max_batch_size=8,
                             max_wait_ms=1.0) as srv:
            srv.aot_warmup()
        workers = [pred.clone() for _ in range(3)]
        r = np.random.RandomState(3)
        for w in workers:
            for rows in (1, 3, 8):
                out = w.run([PaddleTensor(
                    r.randn(rows, 8).astype(np.float32), name="x")])
                assert out[0].data.shape == (rows, 4)
        for w in workers:
            # rows pad client-side? no -- direct predictor.run is the
            # unbatched path, so only EXACT warmed shapes hit: 1 and 8
            # hit the warmed cache, 3 compiles fresh in the SHARED
            # cache (so only the first worker pays it)
            assert w._exe.cache_hit_count >= 2
        fresh = [w._exe.compile_count for w in workers]
        assert sum(fresh) <= 1, fresh  # at most the batch-3 shape once
        assert workers[0]._program is pred._program

    def test_clone_through_server_zero_compiles(self, tmp_path):
        """A server over a cloned worker re-uses every warmed bucket:
        0 fresh executables for bucketed traffic."""
        _export_tiny_fc(tmp_path)
        pred = create_paddle_predictor(AnalysisConfig(str(tmp_path)))
        with InferenceServer(pred, max_batch_size=8,
                             max_wait_ms=1.0) as srv:
            srv.aot_warmup()
        worker = pred.clone()
        assert worker._exe.compile_count == 0
        r = np.random.RandomState(4)
        with InferenceServer(worker, max_batch_size=8,
                             max_wait_ms=1.0) as wsrv:
            for rows in (1, 2, 3, 5, 8):
                wsrv.infer({"x": r.randn(rows, 8).astype(np.float32)},
                           timeout=60.0)
        assert worker._exe.compile_count == 0
        assert worker._exe.cache_hit_count >= 5

    def test_unshared_clone_keeps_old_isolation(self, tmp_path):
        _export_tiny_fc(tmp_path)
        pred = create_paddle_predictor(AnalysisConfig(str(tmp_path)))
        x = np.ones((2, 8), np.float32)
        pred.run([PaddleTensor(x, name="x")])
        iso = pred.clone(share_cache=False)
        assert iso._program is not pred._program
        assert iso._exe._cache is not pred._exe._cache
        iso.run([PaddleTensor(x, name="x")])
        assert iso._exe.compile_count == 1  # recompiled privately


class TestGenerationServing:
    def _train_tiny_transformer(self):
        from paddle_tpu import unique_name
        from paddle_tpu.models import transformer as T

        V, D, L, S = 12, 16, 1, 4
        with unique_name.guard():
            main, startup, loss = T.build_program(
                seq_len=S, d_model=D, n_heads=2, n_layers=L,
                d_inner=32, vocab=V, with_optimizer=False,
                dropout_rate=0.0)
            with fluid.program_guard(main, startup):
                fluid.optimizer.Adam(learning_rate=0.02).minimize(loss)
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(startup)
        rng = np.random.RandomState(1)
        for _ in range(30):
            src = rng.randint(3, V, (4, S)).astype(np.int64)
            tgt_in = np.concatenate(
                [np.full((4, 1), 2, np.int64), src[:, :-1]], 1)
            exe.run(main, feed={"src_ids": src, "tgt_ids": tgt_in,
                                "label": src}, fetch_list=[loss])
        kwargs = dict(seq_len=S, max_out_len=S + 3, d_model=D,
                      n_heads=2, n_layers=L, d_inner=32, vocab=V,
                      start_id=2, end_id=1)
        with unique_name.guard():
            inc_m, _, _, inc_buf = \
                T.build_incremental_decode_program(**kwargs)
        return exe, inc_m, inc_buf, V, S

    def test_padded_bucket_decode_parity_with_eos_sentinel(self):
        """Tokens served from a BUCKETED (padded 3->4) batch must be
        exactly the unpadded incremental-decode tokens for the real
        rows; with end_id set, positions past the first EOS come back
        as the -1 sentinel."""
        exe, inc_m, inc_buf, V, S = self._train_tiny_transformer()
        rng = np.random.RandomState(7)
        srcs = rng.randint(3, V, (3, S)).astype(np.int64)
        # unpadded oracle: one batch-3 run of the same program
        ref, = exe.run(inc_m, feed={"src_ids": srcs},
                       fetch_list=[inc_buf])
        ref = np.asarray(ref)

        srv = GenerationServer(
            inc_m, inc_buf, executor=exe, scope=fluid.global_scope(),
            end_id=1, max_batch_size=4, max_wait_ms=250.0)
        got = [None] * 3
        try:
            # concurrent generate() calls so the batcher coalesces
            # them into ONE padded batch-4 decode
            def call(i):
                got[i] = srv.generate(srcs[i], timeout=120.0)

            threads = [threading.Thread(target=call, args=(i,))
                       for i in range(3)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120.0)
            st = srv.stats()
        finally:
            srv.close()
        # the three requests rode ONE padded batch-4 executable
        assert st["batches"] == 1
        assert st["padded_rows"] == 4
        want = apply_eos_sentinel(ref, end_id=1)
        for i in range(3):
            assert got[i] is not None
            np.testing.assert_array_equal(got[i], want[i])
        # sentinel semantics: the EOS terminator is kept, tail is -1
        for r0 in got:
            if (r0 == 1).any():
                t = int(np.argmax(r0[1:] == 1)) + 1
                assert (r0[t + 1:] == -1).all()
                assert r0[t] == 1

    def test_generate_single_row_roundtrip(self):
        exe, inc_m, inc_buf, V, S = self._train_tiny_transformer()
        rng = np.random.RandomState(9)
        src = rng.randint(3, V, (S,)).astype(np.int64)
        ref, = exe.run(inc_m, feed={"src_ids": src[None]},
                       fetch_list=[inc_buf])
        srv = GenerationServer(
            inc_m, inc_buf, executor=exe, scope=fluid.global_scope(),
            end_id=1, max_batch_size=4, max_wait_ms=1.0)
        try:
            toks = srv.generate(src, timeout=120.0)
        finally:
            srv.close()
        assert toks.ndim == 1  # 1-D in, 1-D out
        np.testing.assert_array_equal(
            toks, apply_eos_sentinel(np.asarray(ref), end_id=1)[0])


class TestObservability:
    def test_stats_shape(self, tmp_path):
        _export_tiny_fc(tmp_path)
        pred = create_paddle_predictor(AnalysisConfig(str(tmp_path)))
        with InferenceServer(pred, max_batch_size=4,
                             max_wait_ms=2.0) as srv:
            for rows in (1, 2, 4):
                srv.infer({"x": np.ones((rows, 8), np.float32)},
                          timeout=60.0)
            st = srv.stats()
        assert st["requests"] == 3
        assert st["rows"] == 7
        assert st["queue_depth"] == 0
        assert 0 < st["batch_occupancy"] <= 1.0
        assert st["compile_count"] >= 1
        assert st["latency_ms"]["p50"] is not None
        assert st["latency_ms"]["p99"] >= st["latency_ms"]["p50"]


class TestStatsResetAndLifecycle:
    def test_stats_reset_window_and_uptime(self, tmp_path):
        """stats(reset=True) atomically zeroes the WINDOW counters
        (the runtime aggregator's rate basis) while uptime_s stays
        monotonic from server start — the r11 aggregation contract."""
        _export_tiny_fc(tmp_path)
        pred = create_paddle_predictor(AnalysisConfig(str(tmp_path)))
        with InferenceServer(pred, max_batch_size=4,
                             max_wait_ms=2.0) as srv:
            for rows in (1, 2, 4):
                srv.infer({"x": np.ones((rows, 8), np.float32)},
                          timeout=60.0)
            st = srv.stats(reset=True)
            assert st["requests"] == 3
            assert st["uptime_s"] >= 0
            assert st["window_s"] >= 0
            st2 = srv.stats()
            assert st2["requests"] == 0
            assert st2["rows"] == 0
            assert st2["latency_ms"]["p50"] is None
            assert st2["uptime_s"] >= st["uptime_s"]
            assert st2["window_s"] <= st["window_s"] + 1.0
            # executor counters are cumulative (delta across windows)
            assert st2["compile_count"] == st["compile_count"]
            srv.infer({"x": np.ones((1, 8), np.float32)},
                      timeout=60.0)
            assert srv.stats()["requests"] == 1

    def test_quiesce_drain_close(self, tmp_path):
        """quiesce() stops ACCEPTING with the retryable named error
        while queued work completes; drain() blocks until the queue
        and in-flight batches are empty (the hot-swap retire path)."""
        from paddle_tpu.inference import ServerQuiesced

        _export_tiny_fc(tmp_path)
        pred = create_paddle_predictor(AnalysisConfig(str(tmp_path)))
        srv = InferenceServer(pred, max_batch_size=8,
                              max_wait_ms=50.0)
        reps = [srv.submit({"x": np.ones((1, 8), np.float32)})
                for _ in range(3)]
        srv.quiesce()
        with pytest.raises(ServerQuiesced):
            srv.submit({"x": np.ones((1, 8), np.float32)})
        assert srv.drain(30.0) is True
        for rep in reps:
            assert rep.result(1.0)[0].shape == (1, 4)
        st = srv.stats()
        assert st["completed"] == 3 and st["queue_depth"] == 0
        srv.close()
        with pytest.raises(RuntimeError, match="closed"):
            srv.submit({"x": np.ones((1, 8), np.float32)})
        # explicit restart after close re-opens the server (the
        # pre-lifecycle contract, where submit gated on the batcher
        # thread alone): a fresh start() must clear closed/quiesced
        srv.start()
        try:
            out = srv.infer({"x": np.ones((1, 8), np.float32)},
                            timeout=60.0)
            assert out[0].shape == (1, 4)
        finally:
            srv.close()

    def test_select_group_hook_orders_dispatch(self):
        """The pluggable queue-selection hook overrides the default
        oldest-first group policy: with two shape groups queued, a
        hook preferring the LATER-arrived group gets it dispatched
        (and completed) first."""
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            x = fluid.layers.data("x", shape=[-1, 4], dtype="float32")
            out = fluid.layers.scale(x, scale=2.0)
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(startup)
        runner = ProgramRunner(prog, ["x"], [out.name], executor=exe,
                               scope=fluid.global_scope())

        def prefer_longest(groups):
            # group keys carry the post-bucket shape signature; pick
            # the one with the largest seq dim
            return max(groups, key=lambda k: k[0][1])

        srv = InferenceServer(runner, max_batch_size=4,
                              max_wait_ms=200.0, seq_buckets=(4, 8),
                              select_group=prefer_longest,
                              start=False)
        r = np.random.RandomState(7)
        rep_short = srv.submit({"x": r.randn(1, 3, 4).astype(
            np.float32)})   # T=3 -> bucket 4, arrives FIRST
        rep_long = srv.submit({"x": r.randn(1, 7, 4).astype(
            np.float32)})    # T=7 -> bucket 8
        done_order = []
        rep_short.add_done_callback(lambda f: done_order.append("s"))
        rep_long.add_done_callback(lambda f: done_order.append("l"))
        srv.start()
        rep_short.result(60.0)
        rep_long.result(60.0)
        srv.close()
        assert done_order[0] == "l", (
            f"hook did not reorder dispatch: {done_order}")


class TestThroughputGuard:
    def test_batched_server_not_slower_than_naive_loop(self, tmp_path):
        """Regression guard (CPU analogue of the PERF.md serving
        table): serving N batch-of-1 requests through the warmed
        batched server must sustain >= the naive per-request
        predictor.run loop. The real win measured in bench.py serving
        is ~3-5x; asserting >= 1x keeps the guard robust to loaded CI
        hosts.

        Measured as 3 INTERLEAVED (naive, batched) leg pairs, best
        paired ratio: this host is 2-core and CPU-share throttled in
        multi-second windows (PERF.md), so a single sequential
        naive-then-batched pass can land the two legs in different
        throttle windows and flake under full-lane contention —
        adjacent legs share a window, and three pairs make it
        vanishingly unlikely every pair straddles a transition (the
        PR 13 contention-flake fix; same discipline as the
        continuous-batching guard)."""
        _export_tiny_fc(tmp_path)
        pred = create_paddle_predictor(AnalysisConfig(str(tmp_path)))
        r = np.random.RandomState(5)
        reqs = [r.randn(1, 8).astype(np.float32) for _ in range(100)]

        def naive_leg():
            t0 = time.perf_counter()
            for a in reqs:
                pred.run([PaddleTensor(a, name="x")])
            return time.perf_counter() - t0

        worker = pred.clone()
        with InferenceServer(worker, max_batch_size=16,
                             max_wait_ms=2.0) as srv:
            srv.aot_warmup()

            def batched_leg():
                t0 = time.perf_counter()
                replies = [srv.submit({"x": a}) for a in reqs]
                for rep in replies:
                    rep.result(timeout=60.0)
                return time.perf_counter() - t0

            # warm both paths outside the timed windows
            pred.run([PaddleTensor(reqs[0], name="x")])
            batched_leg()
            pairs = [(naive_leg(), batched_leg())
                     for _ in range(3)]
        best = min(b / n for n, b in pairs)
        assert best <= 1.05, (
            f"batched serving regressed: best paired batched/naive "
            f"ratio {best:.2f} for 100 requests (pairs: "
            f"{[(round(n, 3), round(b, 3)) for n, b in pairs]})")
