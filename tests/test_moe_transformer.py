"""MoE (Switch) transformer flagship (models/moe_transformer.py):
Executor training, scan/GPipe pipeline paths incl. the per-segment
aux-loss reduce outputs, expert-parallel scope, and the drop-fraction
observability surface. VERDICT r3 weak #5."""
import numpy as np
import pytest

import jax

import paddle_tpu as fluid
from paddle_tpu.models import moe_transformer as M
from paddle_tpu.parallel.mesh import make_mesh, MeshConfig
from paddle_tpu.parallel.moe import expert_parallel
from paddle_tpu.parallel.pipeline_program import (PipelineTrainer,
                                                  propose_loops)


def _fresh():
    fluid._reset_global_scope()
    from paddle_tpu import unique_name
    unique_name.switch()


def _build(seed=5, **kw):
    _fresh()
    args = dict(seq_len=8, vocab=64, d_model=32, n_heads=2,
                n_layers=4, d_inner=64, n_experts=4,
                dropout_rate=0.0, learning_rate=1.0, warmup_steps=40)
    args.update(kw)
    main, startup, cost = M.build_program(**args)
    main._seed = seed
    return main, startup, cost


def _data(B=16, T=8, V=64, seed=0):
    r = np.random.RandomState(seed)
    return {k: r.randint(1, V, (B, T)).astype(np.int64)
            for k in ("src_ids", "label")}


def _exec_losses(main, startup, cost, feed, steps, fetch_extra=()):
    exe = fluid.Executor(fluid.CPUPlace())
    sc = fluid.Scope()
    exe.run(startup, scope=sc)
    out = []
    extras = None
    for _ in range(steps):
        res = exe.run(main, feed=feed,
                      fetch_list=[cost] + list(fetch_extra), scope=sc)
        out.append(float(np.asarray(res[0]).reshape(-1)[0]))
        extras = res[1:]
    return out, extras


class TestExecutorPath:
    def test_trains_and_drop_fracs_fetchable(self):
        feed = _data()
        main, startup, cost = _build()
        drops = main._moe_drop_vars
        assert len(drops) == 2  # layers 1 and 3 are MoE
        losses, extras = _exec_losses(main, startup, cost, feed, 20,
                                      fetch_extra=drops)
        assert losses[-1] < losses[0] * 0.8
        for d in extras:
            v = float(np.asarray(d).reshape(-1)[0])
            assert 0.0 <= v <= 1.0

    def test_tight_capacity_reports_drops(self):
        feed = _data()
        main, startup, cost = _build(capacity_factor=0.25)
        drops = main._moe_drop_vars
        _, extras = _exec_losses(main, startup, cost, feed, 2,
                                 fetch_extra=drops)
        assert any(float(np.asarray(d).reshape(-1)[0]) > 0.0
                   for d in extras)

    def test_ep2_scope_matches_dense_numerics(self):
        """ep=N == ep=1 holds in the NO-DROP capacity regime (sharded
        FIFO capacity can drop different tokens when over-subscribed,
        so cf=2.0 configs differ legitimately)."""
        feed = _data()
        main, startup, cost = _build(capacity_factor=8.0)
        base, _ = _exec_losses(main, startup, cost, feed, 3)
        main2, startup2, cost2 = _build(capacity_factor=8.0)
        mesh = make_mesh(MeshConfig(ep=2), devices=jax.devices()[:2])
        exe = fluid.Executor(fluid.CPUPlace())
        sc = fluid.Scope()
        exe.run(startup2, scope=sc)
        got = []
        with expert_parallel(mesh):
            for _ in range(3):
                l, = exe.run(main2, feed=feed, fetch_list=[cost2],
                             scope=sc)
                got.append(float(np.asarray(l).reshape(-1)[0]))
        np.testing.assert_allclose(base, got, rtol=5e-4, atol=5e-5)


class TestPipelinePath:
    """The alternating dense/MoE pair keeps the stack period-2
    isomorphic; per-layer aux losses leave the loop as reduce
    outputs."""

    def test_loop_detection_finds_pairs_and_reduce_outs(self):
        main, _, cost = _build()
        loops = propose_loops(main, cost.name)
        assert len(loops) == 1 and len(loops[0]) - 1 == 2  # 2 pairs
        tr = PipelineTrainer(main, cost, loops=loops)
        loop = next(s.loop for s in tr.sections if s.kind == "loop")
        # each pair exports its MoE aux (the drop fracs are fetch-only
        # and unread by the program, so they are dead-coded, not
        # reduce-outs)
        assert len(loop.reduce_outs) == 1
        assert len(loop.reduce_outs[0]) == 2

    def test_scan_over_layers_exact_parity(self):
        feed = _data()
        main, startup, cost = _build()
        base, _ = _exec_losses(main, startup, cost, feed, 5)
        main2, startup2, cost2 = _build()
        loops = propose_loops(main2, cost2.name)
        exe = fluid.Executor(fluid.CPUPlace())
        sc = fluid.Scope()
        exe.run(startup2, scope=sc)
        tr = PipelineTrainer(main2, cost2, loops=loops)
        tr.initialize(sc)
        got = [float(np.asarray(tr.run(feed=feed)[0]).reshape(-1)[0])
               for _ in range(5)]
        np.testing.assert_allclose(base, got, rtol=5e-4, atol=5e-5)

    def test_gpipe_pp2_trains_near_parity(self):
        """pp>1 microbatches the loop, so the Switch aux (nonlinear in
        the batch) becomes a per-microbatch mean: NEAR parity, and it
        must train."""
        feed = _data()
        main, startup, cost = _build()
        base, _ = _exec_losses(main, startup, cost, feed, 5)
        main2, startup2, cost2 = _build()
        loops = propose_loops(main2, cost2.name)
        mesh = make_mesh(MeshConfig(pp=2), devices=jax.devices()[:2])
        exe = fluid.Executor(fluid.CPUPlace())
        sc = fluid.Scope()
        exe.run(startup2, scope=sc)
        tr = PipelineTrainer(main2, cost2, loops=loops, mesh=mesh,
                             n_micro=4)
        tr.initialize(sc)
        got = [float(np.asarray(tr.run(feed=feed)[0]).reshape(-1)[0])
               for _ in range(5)]
        assert all(np.isfinite(got))
        assert got[-1] < got[0]
        assert max(abs(a - b) for a, b in zip(base, got)) < 0.15

    def test_compiled_program_pp_api(self):
        feed = _data()
        main, startup, cost = _build()
        mesh = make_mesh(MeshConfig(pp=2), devices=jax.devices()[:2])
        exe = fluid.Executor(fluid.CPUPlace())
        sc = fluid.Scope()
        exe.run(startup, scope=sc)
        cp = fluid.CompiledProgram(main).with_data_parallel(
            loss_name=cost.name, mesh=mesh, n_micro=4)
        got = []
        for _ in range(4):
            l, = exe.run(cp, feed=feed, fetch_list=[cost], scope=sc)
            got.append(float(np.asarray(l).reshape(-1)[0]))
        assert all(np.isfinite(got)) and got[-1] < got[0]
