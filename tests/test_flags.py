"""FLAGS_* config system, nan/inf guard, deterministic mode, strict
shape inference (reference python/paddle/fluid/__init__.py:129-180,
framework/operator.cc:975, framework/shape_inference.h)."""
import warnings

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.flags import FLAGS, get_flags, set_flags


@pytest.fixture(autouse=True)
def _reset_flags():
    saved = dict(FLAGS._values)
    yield
    FLAGS._values.update(saved)
    import jax

    jax.config.update("jax_default_matmul_precision", None)


class TestFlagsAPI:
    def test_defaults(self):
        assert FLAGS.check_nan_inf is False
        assert FLAGS.eager_delete_tensor_gb == -1.0

    def test_set_get_roundtrip(self):
        set_flags({"FLAGS_check_nan_inf": 1})
        assert FLAGS.check_nan_inf is True
        assert get_flags("FLAGS_check_nan_inf") == {
            "FLAGS_check_nan_inf": True}

    def test_unknown_flag_raises(self):
        with pytest.raises(ValueError, match="unknown flag"):
            set_flags({"FLAGS_no_such_flag": 1})

    def test_noop_flag_accepted_with_warning(self):
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            set_flags({"FLAGS_fraction_of_gpu_memory_to_use": 0.5})
        assert FLAGS.fraction_of_gpu_memory_to_use == 0.5
        assert any("no effect" in str(x.message) for x in w)

    def test_deterministic_pins_matmul_precision(self):
        import jax

        set_flags({"FLAGS_cpu_deterministic": True})
        assert jax.config.jax_default_matmul_precision == "highest"
        set_flags({"FLAGS_cpu_deterministic": False})


class TestNanInfGuard:
    def _build_div_prog(self):
        prog = fluid.Program()
        startup = fluid.Program()
        with fluid.program_guard(prog, startup):
            x = fluid.layers.data(name="x", shape=[4], dtype="float32")
            y = fluid.layers.data(name="y", shape=[4], dtype="float32")
            out = fluid.layers.elementwise_div(x, y)
        return prog, startup, out

    def test_clean_run_passes(self):
        prog, startup, out = self._build_div_prog()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        set_flags({"FLAGS_check_nan_inf": True})
        res = exe.run(prog,
                      feed={"x": np.ones((2, 4), np.float32),
                            "y": np.full((2, 4), 2.0, np.float32)},
                      fetch_list=[out])
        np.testing.assert_allclose(res[0], 0.5)

    def test_nan_raises_with_var_name(self):
        prog, startup, out = self._build_div_prog()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        set_flags({"FLAGS_check_nan_inf": True})
        with pytest.raises(RuntimeError, match="NaN/Inf"):
            exe.run(prog,
                    feed={"x": np.zeros((2, 4), np.float32),
                          "y": np.zeros((2, 4), np.float32)},
                    fetch_list=[out])

    def test_disabled_does_not_raise(self):
        prog, startup, out = self._build_div_prog()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        res = exe.run(prog,
                      feed={"x": np.zeros((2, 4), np.float32),
                            "y": np.zeros((2, 4), np.float32)},
                      fetch_list=[out])
        assert np.isnan(res[0]).all()


class TestStrictInferShape:
    def _append_broken_op(self):
        from paddle_tpu.core.registry import register_op

        if "always_broken" not in fluid.registered_ops():
            @register_op("always_broken")
            def _broken(ctx):
                raise ValueError("kernel is intentionally broken")

        prog = fluid.Program()
        with fluid.program_guard(prog, fluid.Program()):
            x = fluid.layers.data(name="x", shape=[4], dtype="float32")
            out = prog.global_block.create_var(name="broken_out")
            prog.global_block.append_op(
                type="always_broken", inputs={"X": [x.name]},
                outputs={"Out": [out.name]})

    def test_default_warns_and_defers(self):
        from paddle_tpu.core import registry

        registry._INFER_WARNED.discard("always_broken")
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            self._append_broken_op()
        assert any("always_broken" in str(x.message) for x in w)

    def test_strict_mode_raises_at_append(self):
        set_flags({"FLAGS_strict_infer_shape": True})
        with pytest.raises(RuntimeError, match="always_broken"):
            self._append_broken_op()


def test_enforce_helpers():
    """reference platform/enforce.h check surface."""
    import pytest

    from paddle_tpu import enforce as E

    E.enforce(True)
    E.enforce_eq(3, 3)
    E.enforce_ne(1, 2)
    E.enforce_gt(2, 1)
    E.enforce_ge(2, 2)
    E.enforce_lt(1, 2)
    E.enforce_le(2, 2)
    assert E.enforce_not_none(5) == 5
    with pytest.raises(E.EnforceNotMet, match="shape mismatch"):
        E.enforce_eq((2, 3), (2, 4), "shape mismatch")
    with pytest.raises(E.EnforceNotMet) as ei:
        E.enforce(False, "boom")
    # call-site context recorded
    assert "test_flags.py" in str(ei.value)


def test_collective_allreduce_layer():
    """reference layers/collective.py:19 _allreduce: program-level
    collective append; single-process it reduces to identity."""
    import numpy as np

    import paddle_tpu as fluid

    prog = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.layers.data("x", shape=(4,), dtype="float32")
        y = fluid.layers.collective._allreduce(x, reduce_type="sum")
    assert prog.global_block.ops[-1].type == "allreduce"
    exe = fluid.Executor(fluid.CPUPlace())
    out = exe.run(prog, feed={"x": np.ones((2, 4), np.float32)},
                  fetch_list=[y.name])
    np.testing.assert_allclose(np.asarray(out[0]), np.ones((2, 4)))
    import pytest

    with pytest.raises(TypeError):
        fluid.layers.collective._allreduce(x, reduce_type="bogus")
