"""Native C++ core tests: program serde round-trips, scope semantics,
recordio integrity, dataflow analysis vs the Python oracle, LoD utils.

Mirrors the reference's colocated C++ gtests (reference
framework/lod_tensor_test.cc, framework/program_desc_test.cc,
recordio/*_test.cc) — here driven from Python through the ctypes ABI the
framework itself uses.
"""
import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import native


pytestmark = pytest.mark.skipif(
    not native.available(),
    reason=f"native build unavailable: {native.build_error()}")


def _mnist_program():
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name="img", shape=[784], dtype="float32")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        hidden = fluid.layers.fc(img, size=32, act="relu")
        logits = fluid.layers.fc(hidden, size=10)
        loss = fluid.layers.softmax_with_cross_entropy(logits, label)
        avg = fluid.layers.mean(loss)
        opt = fluid.optimizer.SGD(learning_rate=0.1)
        opt.minimize(avg)
    return main, startup, avg


class TestProgramSerde:
    def test_json_round_trip(self):
        main, _, _ = _mnist_program()
        d = main.to_dict()
        nprog = native.NativeProgram.from_dict(d)
        assert nprog.num_blocks == len(main.blocks)
        assert nprog.num_ops(0) == len(main.global_block.ops)
        back = nprog.to_dict()
        assert [o["type"] for o in back["blocks"][0]["ops"]] == \
            [o["type"] for o in d["blocks"][0]["ops"]]
        # full structural equality through the C++ bridge
        assert back["blocks"][0]["vars"] == d["blocks"][0]["vars"]
        for a, b in zip(back["blocks"][0]["ops"], d["blocks"][0]["ops"]):
            assert a["inputs"] == b["inputs"]
            assert a["outputs"] == b["outputs"]
            assert set(a["attrs"]) == set(b["attrs"])

    def test_binary_round_trip(self):
        main, _, _ = _mnist_program()
        d = main.to_dict()
        blob = native.NativeProgram.from_dict(d).to_bytes()
        assert blob[:4] == b"PTPF"
        back = native.NativeProgram.from_bytes(blob).to_dict()
        prog2 = fluid.Program.from_dict(back)
        assert [op.type for op in prog2.global_block.ops] == \
            [op.type for op in main.global_block.ops]
        assert blob == native.NativeProgram.from_dict(back).to_bytes()

    def test_corrupt_binary_rejected(self):
        main, _, _ = _mnist_program()
        blob = native.NativeProgram.from_dict(main.to_dict()).to_bytes()
        with pytest.raises(RuntimeError):
            native.NativeProgram.from_bytes(blob[:20])
        with pytest.raises(RuntimeError):
            native.NativeProgram.from_bytes(b"XXXX" + blob[4:])

    def test_ndarray_and_float_attrs_survive(self):
        main = fluid.Program()
        arr = np.arange(6, dtype="float32").reshape(2, 3)
        main.global_block.append_op(
            "assign_value", {}, {"Out": ["v"]},
            {"values": arr, "shape": [2, 3], "dtype": "float32",
             "scale": 0.5, "flag": True, "names": ["a", "b"]})
        blob = native.NativeProgram.from_dict(main.to_dict()).to_bytes()
        back = fluid.Program.from_dict(
            native.NativeProgram.from_bytes(blob).to_dict())
        op = back.global_block.ops[0]
        np.testing.assert_allclose(op.attrs["values"], arr)
        assert op.attrs["values"].shape == (2, 3)
        assert op.attrs["scale"] == 0.5
        assert op.attrs["flag"] is True
        assert op.attrs["names"] == ["a", "b"]


class TestAnalysis:
    def test_analyze_matches_python_oracle(self):
        from paddle_tpu.core.executor import _analyze_block_py

        main, _, avg = _mnist_program()
        feed = ("img", "label")
        fetch = [avg.name]
        py = _analyze_block_py(main.global_block, feed, fetch)
        nprog = native.NativeProgram.from_dict(main.to_dict())
        nat = nprog.analyze_block(0, list(feed), fetch, ["feed", "fetch"])
        assert tuple(nat[0]) == tuple(py[0])  # mutated
        assert tuple(nat[1]) == tuple(py[1])  # constant
        assert tuple(nat[2]) == tuple(py[2])  # state_out

    def test_last_use_plan(self):
        main, _, avg = _mnist_program()
        nprog = native.NativeProgram.from_dict(main.to_dict())
        plan = nprog.last_use_plan(0, ["img", "label"], [avg.name])
        assert len(plan) == len(main.global_block.ops)
        freed = [n for names in plan for n in names]
        assert len(freed) == len(set(freed))  # freed exactly once
        assert avg.name not in freed          # fetch protected
        assert "img" not in freed             # feed protected
        persist = {v.name for v in main.list_vars() if v.persistable}
        assert not (set(freed) & persist)     # params never freed
        # every temp freed at its true last use
        for i, names in enumerate(plan):
            for n in names:
                later = [j for j in range(i + 1, len(plan))
                         if n in main.global_block.ops[j].input_arg_names
                         or n in main.global_block.ops[j].output_arg_names]
                assert not later, f"{n} freed at {i} but used at {later}"

    def test_dependency_waves(self):
        main = fluid.Program()
        with fluid.program_guard(main, fluid.Program()):
            a = fluid.layers.fill_constant([2], "float32", 1.0)
            b = fluid.layers.fill_constant([2], "float32", 2.0)
            c = a + b
            d = c * a
        nprog = native.NativeProgram.from_dict(main.to_dict())
        waves = nprog.dependency_waves(0)
        assert waves[0] == 0 and waves[1] == 0  # independent fills
        assert waves[2] == 1                    # add after both
        assert waves[3] == 2                    # mul after add

    def test_executor_uses_native_analysis(self):
        # end-to-end: the executor path runs with the native analyzer on
        main, startup, avg = _mnist_program()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        img = np.random.RandomState(0).rand(8, 784).astype("float32")
        label = np.random.RandomState(1).randint(
            0, 10, (8, 1)).astype("int64")
        l0 = exe.run(main, feed={"img": img, "label": label},
                     fetch_list=[avg])[0]
        l1 = exe.run(main, feed={"img": img, "label": label},
                     fetch_list=[avg])[0]
        assert float(np.ravel(l1)[0]) < float(np.ravel(l0)[0])  # SGD step applied


class TestScope:
    def test_var_and_find(self):
        s = native.NativeScope()
        a = s.var("x")
        assert s.var("x") == a            # find-or-create is stable
        assert s.find_var("x") == a
        assert s.find_var("missing") == -1

    def test_hierarchy(self):
        root = native.NativeScope()
        x = root.var("x")
        child = root.new_scope()
        assert child.find_var("x") == x   # parent fallback
        cx = child.var("x")               # shadows in child
        assert cx != x
        assert child.find_var("x") == cx
        assert root.find_var("x") == x
        assert root.num_kids() == 1
        root.drop_kids()
        assert root.num_kids() == 0

    def test_erase_and_names(self):
        s = native.NativeScope()
        s.var("a")
        s.var("b")
        assert sorted(s.local_var_names()) == ["a", "b"]
        assert s.erase("a")
        assert not s.erase("a")
        assert s.find_var("a") == -1


class TestRecordIO:
    def test_round_trip(self, tmp_path):
        path = tmp_path / "data.recordio"
        records = [os.urandom(np.random.randint(1, 2000))
                   for _ in range(257)]
        with native.RecordIOWriter(path, max_records_per_chunk=100) as w:
            for r in records:
                w.write(r)
        got = list(native.RecordIOScanner(path))
        assert got == records

    def test_uncompressed_and_reset(self, tmp_path):
        path = tmp_path / "plain.recordio"
        with native.RecordIOWriter(path, compressor=0) as w:
            w.write(b"hello")
            w.write(b"world")
        sc = native.RecordIOScanner(path)
        assert list(sc) == [b"hello", b"world"]
        sc.reset()
        assert list(sc) == [b"hello", b"world"]

    def test_corruption_detected(self, tmp_path):
        path = tmp_path / "bad.recordio"
        with native.RecordIOWriter(path, compressor=0) as w:
            for i in range(5):
                w.write(b"payload-%d" % i)
        raw = bytearray(path.read_bytes())
        raw[-3] ^= 0xFF  # flip a payload byte -> CRC mismatch
        path.write_bytes(bytes(raw))
        with pytest.raises(IOError):
            list(native.RecordIOScanner(path))


class TestLoD:
    def test_conversions(self):
        assert native.lengths_to_offsets([3, 1, 2]) == [0, 3, 4, 6]
        assert native.offsets_to_lengths([0, 3, 4, 6]) == [3, 1, 2]
        assert native.offsets_to_segment_ids([0, 3, 4, 6]) == \
            [0, 0, 0, 1, 2, 2]
        assert native.offsets_to_segment_ids([0]) == []


class TestInferenceModelSerde:
    def test_save_load_binary_model(self, tmp_path):
        main, startup, avg = _mnist_program()
        infer_prog = main.clone(for_test=True)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        img = np.random.RandomState(0).rand(4, 784).astype("float32")
        with fluid.program_guard(main, startup):
            logits_name = None
            for op in reversed(infer_prog.global_block.ops):
                if op.type == "softmax_with_cross_entropy":
                    logits_name = op.input("Logits")[0]
                    break
        assert logits_name is not None
        target = infer_prog.global_block.var(logits_name)
        fluid.io.save_inference_model(
            str(tmp_path / "model"), ["img"], [target], exe,
            main_program=infer_prog)
        model_file = tmp_path / "model" / "__model__"
        assert model_file.read_bytes()[:4] == b"PTPF"
        prog2, feeds, fetches = fluid.io.load_inference_model(
            str(tmp_path / "model"), exe)
        out1 = exe.run(infer_prog, feed={"img": img,
                                         "label": np.zeros((4, 1), "int64")},
                       fetch_list=[target])[0]
        out2 = exe.run(prog2, feed={feeds[0]: img}, fetch_list=fetches)[0]
        np.testing.assert_allclose(np.asarray(out1), np.asarray(out2),
                                   rtol=2e-5, atol=2e-5)


class TestHalfPrecisionAttrs:
    def test_fp16_bf16_ndarray_round_trip(self):
        for dt in ("float16", "bfloat16"):
            if dt == "bfloat16":
                import jax.numpy as jnp
                arr = np.asarray(
                    jnp.asarray([1.5, 2.5, -3.5, 4.5], dtype=jnp.bfloat16)
                ).astype("float32")
                src = {"__ndarray__": [1.5, 2.5, -3.5, 4.5],
                       "dtype": "bfloat16", "shape": [4]}
            else:
                src = {"__ndarray__": [1.5, 2.5, -3.5, 4.5],
                       "dtype": "float16", "shape": [4]}
                arr = np.asarray([1.5, 2.5, -3.5, 4.5], "float16")
            d = {"blocks": [{"idx": 0, "parent_idx": -1, "vars": [],
                             "ops": [{"type": "assign_value",
                                      "inputs": {},
                                      "outputs": {"Out": ["v"]},
                                      "attrs": {"values": src}}]}],
                 "parameters": []}
            blob = native.NativeProgram.from_dict(d).to_bytes()
            back = native.NativeProgram.from_bytes(blob).to_dict()
            vals = back["blocks"][0]["ops"][0]["attrs"]["values"]
            assert vals["shape"] == [4]
            np.testing.assert_allclose(
                np.asarray(vals["__ndarray__"], "float32"),
                np.asarray(arr, "float32"))


class TestExecutorNativePlan:
    """The native GC plan is consumed BY DEFAULT in the executor's
    trace loop (VERDICT r2 #6/weak #7)."""

    def _toy(self):
        import paddle_tpu as fluid

        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            x = fluid.layers.data(name="x", shape=[4],
                                  dtype="float32")
            h = fluid.layers.fc(x, size=8, act="relu")
            h2 = fluid.layers.fc(h, size=4)
            loss = fluid.layers.mean(h2)
            fluid.optimizer.SGD(0.1).minimize(loss)
        return prog, startup, loss

    def test_last_use_plan_native_matches_python_oracle(self):
        import paddle_tpu as fluid
        from paddle_tpu import native
        from paddle_tpu.core.executor import _last_use_plan_py

        if not native.available():
            pytest.skip("native lib unavailable")
        prog, startup, loss = self._toy()
        block = prog.global_block
        feeds, fetches = ("x",), [loss.name]
        nprog = native.NativeProgram.from_dict(
            prog._to_analysis_dict())
        got = nprog.last_use_plan(block.idx, list(feeds), fetches)
        want = _last_use_plan_py(block, feeds, fetches)
        assert [sorted(p) for p in got] == want

    def test_trace_env_is_evicted_at_last_use(self):
        """Spy on the trace env through run_op: a var the plan frees
        early must be ABSENT from the env by the time the last op
        traces (the default-on trace GC, not just a non-empty plan)."""
        import numpy as np
        import paddle_tpu as fluid
        from paddle_tpu.core import executor as ex
        from paddle_tpu.core import registry as reg
        from paddle_tpu.core.executor import _last_use_plan

        prog, startup, loss = self._toy()
        block = prog.global_block
        feeds, fetches = ("x",), [loss.name]
        plan = _last_use_plan(block, feeds, fetches)
        freed = [(i, n) for i, p in enumerate(plan) for n in p]
        assert freed, "plan freed nothing on a training block"
        # pick a var freed well before the final op
        last_idx = len(block.ops) - 1
        early = [n for i, n in freed if i < last_idx - 2]
        assert early, freed

        snapshots = []
        orig = reg.run_op

        def spy(op, env, **kw):
            snapshots.append(set(env))
            return orig(op, env, **kw)

        exe = fluid.Executor(fluid.CPUPlace())
        sc = fluid.Scope()
        exe.run(startup, scope=sc)
        xs = np.random.RandomState(0).randn(4, 4).astype(np.float32)
        reg_run_op = ex.run_op
        ex.run_op = spy
        try:
            l, = exe.run(prog, feed={"x": xs}, fetch_list=[loss],
                         scope=sc)
        finally:
            ex.run_op = reg_run_op
        # the final op's env snapshot must NOT contain the early-freed
        # vars (they were evicted right after their last use)
        final_env = snapshots[-1]
        leaked = [n for n in early if n in final_env]
        assert not leaked, f"evicted vars still in trace env: {leaked}"
        assert np.isfinite(float(np.asarray(l).reshape(-1)[0]))

    def test_native_verify_flag_raises_on_divergence(self):
        import paddle_tpu as fluid
        from paddle_tpu import native
        from paddle_tpu.core import executor as ex

        if not native.available():
            pytest.skip("native lib unavailable")
        prog, startup, loss = self._toy()
        exe = fluid.Executor(fluid.CPUPlace())
        sc = fluid.Scope()
        exe.run(startup, scope=sc)
        import numpy as np
        xs = np.zeros((2, 4), np.float32)

        # fabricate a divergence: make the python oracle lie
        orig = ex._analyze_block_py

        def lying(block, feed_names, fetch_names):
            m, c, s = orig(block, feed_names, fetch_names)
            return m + ["bogus_var"], c, s

        fluid.set_flags({"FLAGS_native_verify": 1})
        ex._analyze_block_py = lying
        try:
            with pytest.raises(RuntimeError, match="divergence"):
                exe.run(prog, feed={"x": xs}, fetch_list=[loss],
                        scope=sc)
        finally:
            ex._analyze_block_py = orig
            fluid.set_flags({"FLAGS_native_verify": 0})

    def test_native_verify_passes_clean(self):
        import numpy as np
        import paddle_tpu as fluid

        prog, startup, loss = self._toy()
        exe = fluid.Executor(fluid.CPUPlace())
        sc = fluid.Scope()
        exe.run(startup, scope=sc)
        xs = np.zeros((2, 4), np.float32)
        fluid.set_flags({"FLAGS_native_verify": 1})
        try:
            l, = exe.run(prog, feed={"x": xs}, fetch_list=[loss],
                         scope=sc)
            assert np.isfinite(float(np.asarray(l).reshape(-1)[0]))
        finally:
            fluid.set_flags({"FLAGS_native_verify": 0})
