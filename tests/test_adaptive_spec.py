"""Adaptive speculation (r19): the per-lane acceptance controller over
a pre-built k-ladder, the model-free n-gram drafting lane, and draft
distillation (inference/spec_controller.py, models/distill.py,
models/decode_engine.py DraftConfig.k_options / kind="ngram").

The invariants this layer must hold on top of r14's:

* re-bucketing is PURE PROGRAM SELECTION: every rung of the ladder is
  token-exact vs the whole-loop greedy oracle (the acceptance rule is
  correct at ANY k, for ANY draft — distilled, random, or index
  arithmetic), including switches mid-flight, and steady-state traffic
  never compiles whatever the controller does;
* the n-gram lane proposes from prompt/history suffix matches with
  ZERO draft model steps and still rides the same verify path;
* a controller fed garbage acceptance parks the pool at the k=0 rung
  (plain one-token bursts) and re-probes its way back up;
* the per-k stats windows attribute each fused dispatch to the rung it
  ran, and reset=True re-bases them (the r14 window semantics);
* distillation on the target's OWN outputs lifts draft/target
  agreement — and therefore serve-time acceptance — over a draft that
  never saw the target (the PERF.md before/after).
"""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.inference import (ContinuousGenerationServer,
                                  SpecController,
                                  apply_eos_sentinel,
                                  choose_draft_placement,
                                  count_generated_tokens)
from paddle_tpu.inference.spec_controller import \
    expected_tokens_per_verify
from paddle_tpu.models.decode_engine import (DraftConfig,
                                             ShardingConfig)

V, D, H, L, S, MAXT = 16, 32, 2, 1, 10, 32
DD = 16          # draft width (d16/L1 — the CLAUDE.md tiny-task tier)
END_ID = 1
N_SLOTS = 4
LADDER = (0, 2, 4)

# the fixed memorizable pool from test_speculative_decode.py: planted
# end_id at varied positions gives model-driven mixed-length outputs
# AND high draft/target agreement (both tiny models memorize the same
# streams) — the regime where the k ladder has real rungs to choose
_POOL_RNG = np.random.RandomState(5)
PROMPT_POOL = []
for _p in (1, 2, 3, 4, 6, 8, 10, 10):
    _src = _POOL_RNG.randint(3, V, (S,)).astype(np.int64)
    if _p < S:
        _src[_p:] = END_ID
    PROMPT_POOL.append(_src)
PROMPT_POOL = np.stack(PROMPT_POOL)


def _mixed_len_prompts(rng, n):
    return PROMPT_POOL[rng.randint(0, len(PROMPT_POOL), n)]


class _Scripted:
    """Controller stand-in replaying a fixed k schedule — makes the
    rung sequence a test INPUT instead of a policy outcome, so parity
    is pinned per rung and across mid-flight switches."""

    def __init__(self, schedule):
        self.schedule = list(schedule)
        self.i = 0
        self.observed = []

    def choose(self):
        k = self.schedule[min(self.i, len(self.schedule) - 1)]
        self.i += 1
        return int(k)

    def observe(self, accepted_delta, ticks_delta, k):
        self.observed.append(
            (int(np.asarray(accepted_delta).sum()),
             int(np.asarray(ticks_delta).sum()), int(k)))

    def reset_lane(self, lane):
        pass

    def stats(self):
        return {"scripted": True, "chosen": self.i}


@pytest.fixture(scope="module")
def trained():
    """Train target (d32/L1) + draft (d16/L1) terminator-copy models
    into ONE scope; build the whole-loop oracle, the adaptive-ladder
    bundle, and the n-gram bundle."""
    from paddle_tpu import unique_name
    from paddle_tpu.core.scope import Scope
    from paddle_tpu.models import transformer as T

    fluid.seed(0)
    scope = Scope()
    exe = fluid.Executor(fluid.TPUPlace(0))
    with unique_name.guard():
        t_main, t_st, t_loss = T.build_program(
            seq_len=S, d_model=D, n_heads=H, n_layers=L, d_inner=64,
            vocab=V, with_optimizer=False, dropout_rate=0.0)
        with fluid.program_guard(t_main, t_st):
            fluid.optimizer.Adam(learning_rate=0.02).minimize(t_loss)
        d_main, d_st, d_loss = T.build_program(
            seq_len=S, d_model=DD, n_heads=H, n_layers=L, d_inner=32,
            vocab=V, with_optimizer=False, dropout_rate=0.0,
            name_prefix="draft_")
        with fluid.program_guard(d_main, d_st):
            fluid.optimizer.Adam(learning_rate=0.02).minimize(d_loss)
    exe.run(t_st, scope=scope)
    exe.run(d_st, scope=scope)
    rng = np.random.RandomState(7)
    for _ in range(150):
        src = _mixed_len_prompts(rng, 8)
        tgt_in = np.concatenate(
            [np.full((8, 1), 2, np.int64), src[:, :-1]], 1)
        feed = {"src_ids": src, "tgt_ids": tgt_in, "label": src}
        exe.run(t_main, feed=feed, fetch_list=[t_loss], scope=scope)
        exe.run(d_main, feed=feed, fetch_list=[d_loss], scope=scope)

    kwargs = dict(seq_len=S, max_out_len=MAXT, d_model=D, n_heads=H,
                  n_layers=L, d_inner=64, vocab=V, start_id=2,
                  end_id=END_ID)
    with unique_name.guard():
        inc_m, _, _, inc_buf = T.build_incremental_decode_program(
            **kwargs)
    # single admission bucket [N_SLOTS]: the ladder multiplies the
    # serve-program set (base x rung), so the bucket ladder stays
    # minimal to keep this module inside the tier-1 fast lane
    buckets = [N_SLOTS]
    with unique_name.guard():
        adapt = T.build_decode_step_program(
            n_slots=N_SLOTS, state_prefix="@ad/",
            admit_buckets=buckets,
            draft=DraftConfig(d_model=DD, n_heads=H, n_layers=L,
                              d_inner=32, k=2, k_options=LADDER),
            **kwargs)
    with unique_name.guard():
        ngram = T.build_decode_step_program(
            n_slots=N_SLOTS, state_prefix="@ng/",
            admit_buckets=buckets,
            draft=DraftConfig(k=2, kind="ngram", ngram=2,
                              k_options=(0, 2)),
            **kwargs)
    return {"exe": exe, "scope": scope, "inc_m": inc_m,
            "inc_buf": inc_buf, "adapt": adapt, "ngram": ngram,
            "kwargs": kwargs}


def _oracle(tr, srcs):
    ref, = tr["exe"].run(tr["inc_m"], feed={"src_ids": srcs},
                         fetch_list=[tr["inc_buf"]],
                         scope=tr["scope"])
    return apply_eos_sentinel(np.asarray(ref), end_id=END_ID)


def _serve(tr, bundle, srcs, ctl=None, **srv_kw):
    with ContinuousGenerationServer(
            bundle, executor=tr["exe"], scope=tr["scope"],
            spec_controller=ctl, **srv_kw) as srv:
        replies = [srv.submit(s) for s in srcs]
        got = np.stack([r.result(timeout=300.0) for r in replies])
        st = srv.stats()
    return got, st


# ---------------------------------------------------------------------------
# controller policy (pure host logic — no models)
# ---------------------------------------------------------------------------
class TestControllerPolicy:
    def test_expected_tokens_per_verify(self):
        assert expected_tokens_per_verify(0.0, 4) == 1.0
        assert expected_tokens_per_verify(1.0, 4) == 5.0
        assert expected_tokens_per_verify(0.5, 2) == pytest.approx(
            1.75)  # 1 + .5 + .25

    def _feed(self, ctl, a, k, times=8):
        """Converge the EWMA to acceptance ``a`` via dispatches of 10
        lane-ticks at rung k."""
        for _ in range(times):
            ctl.observe(np.full(4, a * 10 * k), np.full(4, 10), k=k)

    def test_climbs_on_high_acceptance(self):
        ctl = SpecController(LADDER, default_k=2, probe_every=0)
        assert ctl.choose() == 2  # no signal: default rung
        self._feed(ctl, 0.95, 2)
        assert ctl.choose() == 4
        assert ctl.k_now == 4 and ctl.n_switches == 1

    def test_parks_at_zero_on_garbage(self):
        ctl = SpecController(LADDER, default_k=2, probe_every=0)
        self._feed(ctl, 0.0, 2)
        assert ctl.choose() == 0
        # k=0 dispatches carry no signal: the estimate stays put
        a = ctl.acceptance
        ctl.observe(np.zeros(4), np.full(4, 10), k=0)
        assert ctl.acceptance == a and ctl.choose() == 0

    def test_probe_escapes_the_park(self):
        ctl = SpecController(LADDER, default_k=2, probe_every=3)
        self._feed(ctl, 0.0, 2)
        assert ctl.choose() == 0
        seen = [ctl.choose() for _ in range(6)]
        assert 2 in seen and ctl.n_probes >= 1  # min positive rung
        # the probe observed recovered traffic: back up the ladder
        self._feed(ctl, 0.95, 2)
        assert ctl.choose() == 4

    def test_hysteresis_holds_near_ties(self):
        ctl = SpecController(LADDER, default_k=2, margin=0.5,
                             probe_every=0)
        # a=0.6: score(4) beats score(2) by ~4% — inside a 50% margin
        self._feed(ctl, 0.6, 2)
        assert ctl.choose() == 2 and ctl.n_switches == 0

    def test_lane_tracking_and_reset(self):
        ctl = SpecController(LADDER, default_k=2)
        ctl.observe(np.array([20.0, 0.0]), np.array([10.0, 10.0]),
                    k=2)
        rates = ctl.lane_rates()
        assert rates[0] == 1.0 and rates[1] == 0.0
        ctl.reset_lane(0)
        assert 0 not in ctl.lane_rates()
        st = ctl.stats()
        assert st["k_now"] == 2 and st["k_options"] == list(LADDER)

    def test_default_k_joins_the_ladder(self):
        ctl = SpecController((0, 4), default_k=2)
        assert ctl.k_options == (0, 2, 4)
        # the default rung is always a member, so even an empty
        # declared ladder degenerates to the single-rung controller
        assert SpecController((), default_k=2).k_options == (2,)

    def test_draft_placement_policy(self):
        draft = DraftConfig(d_model=DD, n_heads=H, n_layers=L,
                            d_inner=32, k=2)
        tp = ShardingConfig(tp=2)
        assert choose_draft_placement(draft, tp) is draft
        assert choose_draft_placement(None, tp) is None
        assert choose_draft_placement(draft, None) is draft
        ng = DraftConfig(k=2, kind="ngram", ngram=2)
        assert choose_draft_placement(ng, tp) is ng
        bad = DraftConfig(d_model=DD, n_heads=3, n_layers=L,
                          d_inner=32, k=2, sharded=True)
        with pytest.raises(ValueError, match="n_heads"):
            choose_draft_placement(bad, tp)


# ---------------------------------------------------------------------------
# adaptive ladder: parity per rung and across switches
# ---------------------------------------------------------------------------
class TestAdaptiveParity:
    @pytest.mark.parametrize("kv", LADDER)
    def test_token_exact_at_each_rung(self, trained, kv):
        """Every rung of the ladder — the native k=2 program, the
        ("k", 4, *) variant, and the k=0 plain-body variant — is
        byte-exact vs the whole-loop greedy oracle."""
        srcs = _mixed_len_prompts(np.random.RandomState(11 + kv), 8)
        want = _oracle(trained, srcs)
        ctl = _Scripted([kv])
        got, st = _serve(trained, trained["adapt"], srcs, ctl=ctl)
        np.testing.assert_array_equal(got, want)
        sp = st["speculative"]
        per_k = sp["per_k"]
        assert per_k[kv]["dispatches"] > 0
        for other in LADDER:
            if other != kv:
                assert per_k[other]["dispatches"] == 0
        if kv == 0:
            # the plain-body rung proposes nothing — the graceful
            # degradation target (~plain-burst throughput)
            assert per_k[0]["proposed"] == 0
        else:
            assert per_k[kv]["proposed"] > 0
            assert st["device_telemetry"][f"spec_ticks_k{kv}"] > 0

    def test_token_exact_across_midflight_switches(self, trained):
        """The controller re-buckets the pool between dispatches;
        slot state (KV caches, draft caches, counters) is shared by
        construction, so switching rungs never moves a token."""
        srcs = _mixed_len_prompts(np.random.RandomState(17), 12)
        want = _oracle(trained, srcs)
        ctl = _Scripted([4, 0, 2, 0, 4, 2] * 50)
        got, st = _serve(trained, trained["adapt"], srcs, ctl=ctl)
        np.testing.assert_array_equal(got, want)
        per_k = st["speculative"]["per_k"]
        assert sum(1 for kv in LADDER
                   if per_k[kv]["dispatches"] > 0) >= 2
        # the scripted stand-in is surfaced as the controller
        assert st["speculative"]["controller"]["scripted"] is True

    def test_auto_controller_parity_and_convergence(self, trained):
        """No controller passed: the server builds the policy one
        from the bundle's ladder. On the memorized pool the draft
        accepts well — the controller must hold a positive rung, and
        parity still binds."""
        srcs = _mixed_len_prompts(np.random.RandomState(19), 10)
        want = _oracle(trained, srcs)
        got, st = _serve(trained, trained["adapt"], srcs)
        np.testing.assert_array_equal(got, want)
        ctl_st = st["speculative"]["controller"]
        assert ctl_st["k_options"] == list(LADDER)
        assert ctl_st["k_now"] in LADDER and ctl_st["k_now"] > 0
        assert ctl_st["acceptance_ewma"] is not None \
            and ctl_st["acceptance_ewma"] > 0.3

    def test_degrades_to_plain_and_probes_back(self, trained):
        """A controller whose estimate says the draft is useless runs
        the whole workload at the k=0 rung (plain one-token bursts);
        with probing on, the real traffic's acceptance pulls it back
        up the ladder."""
        srcs = _mixed_len_prompts(np.random.RandomState(23), 8)
        want = _oracle(trained, srcs)
        # poisoned estimate, probing off: parked at 0 for good
        parked = SpecController(LADDER, default_k=2, probe_every=0)
        for _ in range(10):
            parked.observe(np.zeros(N_SLOTS + 1),
                           np.full(N_SLOTS + 1, 10.0), k=2)
        got, st = _serve(trained, trained["adapt"], srcs, ctl=parked)
        np.testing.assert_array_equal(got, want)
        per_k = st["speculative"]["per_k"]
        assert per_k[2]["dispatches"] == per_k[4]["dispatches"] == 0
        assert per_k[0]["dispatches"] > 0
        assert st["speculative"]["proposed"] == 0  # no draft ran
        # same poison, probing on: the probe rung observes the real
        # acceptance and the controller leaves the park
        probing = SpecController(LADDER, default_k=2, probe_every=2,
                                 ewma=0.5)
        for _ in range(10):
            probing.observe(np.zeros(N_SLOTS + 1),
                            np.full(N_SLOTS + 1, 10.0), k=2)
        got2, st2 = _serve(trained, trained["adapt"], srcs,
                           ctl=probing)
        np.testing.assert_array_equal(got2, want)
        ctl_st = st2["speculative"]["controller"]
        assert ctl_st["probes"] >= 1
        assert st2["speculative"]["per_k"][2]["dispatches"] > 0
        assert ctl_st["acceptance_ewma"] > 0.1


# ---------------------------------------------------------------------------
# model-free n-gram lane
# ---------------------------------------------------------------------------
class TestNgramLane:
    def test_token_exact_with_zero_draft_steps(self, trained):
        """Suffix-match proposals through the same verify path:
        byte-exact (greedy verify corrects any wrong proposal), real
        acceptance on the repeated-suffix pool, and NO draft model —
        draft_steps stays 0 while proposals flow."""
        srcs = _mixed_len_prompts(np.random.RandomState(29), 10)
        want = _oracle(trained, srcs)
        got, st = _serve(trained, trained["ngram"], srcs)
        np.testing.assert_array_equal(got, want)
        sp = st["speculative"]
        assert sp["draft_steps"] == 0
        assert sp["proposed"] > 0
        # the pool's planted-EOS tails are repeated suffixes — the
        # bigram matcher must land real acceptances there
        assert sp["acceptance_rate"] is not None \
            and sp["acceptance_rate"] > 0.1, sp
        assert sp["emitted"] == int(
            count_generated_tokens(got, END_ID).sum())

    def test_ngram_ladder_switches_token_exact(self, trained):
        """The n-gram bundle's own (0, 2) ladder: rung switches are
        parity-safe with no draft state at all."""
        srcs = _mixed_len_prompts(np.random.RandomState(31), 8)
        want = _oracle(trained, srcs)
        ctl = _Scripted([2, 0] * 100)
        got, st = _serve(trained, trained["ngram"], srcs, ctl=ctl)
        np.testing.assert_array_equal(got, want)
        sp = st["speculative"]
        assert sp["draft_steps"] == 0
        assert sp["per_k"][0]["dispatches"] > 0
        assert sp["per_k"][2]["dispatches"] > 0


# ---------------------------------------------------------------------------
# per-k stats windows + metrics surface
# ---------------------------------------------------------------------------
class TestPerKStats:
    def test_windows_attribute_and_reset_rebases(self, trained):
        srcs = _mixed_len_prompts(np.random.RandomState(37), 6)
        with ContinuousGenerationServer(
                trained["adapt"], executor=trained["exe"],
                scope=trained["scope"]) as srv:
            for s in srcs:
                srv.submit(s).result(timeout=300.0)
            st = srv.stats(reset=True)
            sp = st["speculative"]
            assert sorted(sp["per_k"]) == list(LADDER)
            assert sum(w["dispatches"]
                       for w in sp["per_k"].values()) > 0
            ran = [kv for kv in LADDER if kv > 0
                   and sp["per_k"][kv]["proposed"] > 0]
            assert ran
            for kv in ran:
                w = sp["per_k"][kv]
                assert 0 <= w["accepted"] <= w["proposed"]
                assert w["acceptance_rate"] is not None
                assert w["acceptance_rate_hist"]["p50"] is not None
            # reset=True re-based the window (r14 semantics): the
            # next snapshot shows an empty window, not history
            sp2 = srv.stats()["speculative"]
            for kv in LADDER:
                assert sp2["per_k"][kv]["dispatches"] == 0
                assert sp2["per_k"][kv]["proposed"] == 0
            hist = sp2["per_k"][ran[0]]["acceptance_rate_hist"]
            assert hist["p50"] is None

    def test_metrics_samples_carry_k_labels(self, trained):
        srcs = _mixed_len_prompts(np.random.RandomState(41), 4)
        with ContinuousGenerationServer(
                trained["adapt"], executor=trained["exe"],
                scope=trained["scope"]) as srv:
            for s in srcs:
                srv.submit(s).result(timeout=300.0)
            samples = [(name, lab) for name, lab, _
                       in srv._metrics_samples()]
            sp = srv.stats()["speculative"]
        names = {n for n, _ in samples}
        assert "paddle_tpu_spec_k_dispatches_total" in names
        ks = {lab["k"] for n, lab in samples
              if n == "paddle_tpu_spec_k_dispatches_total"}
        assert ks == {str(kv) for kv in LADDER}
        assert any(n == "paddle_tpu_spec_acceptance_rate_k"
                   for n, _ in samples)
        assert sp["k_options"] == list(LADDER)


# ---------------------------------------------------------------------------
# executable bound: the whole ladder binds at warmup, churn compiles 0
# ---------------------------------------------------------------------------
class TestExecutableBound:
    def test_rung_thrash_compiles_nothing(self, trained):
        """40 requests under a rung-thrashing controller: every
        ("k", kv, base) variant is pre-built and warmed, so
        re-bucketing NEVER reaches the compiler."""
        exe = trained["exe"]
        ctl = _Scripted([2, 4, 0] * 1000)
        srv = ContinuousGenerationServer(
            trained["adapt"], executor=exe, scope=trained["scope"],
            spec_controller=ctl)
        try:
            assert srv._warmed_compiles <= len(
                trained["adapt"].serves)
            warmed = exe.compile_count
            srcs = _mixed_len_prompts(np.random.RandomState(43), 40)
            replies = [srv.submit(s) for s in srcs]
            got = [r.result(timeout=600.0) for r in replies]
            st = srv.stats()
        finally:
            srv.close()
        assert len(got) == 40
        assert exe.compile_count == warmed, (
            f"rung thrash compiled "
            f"{exe.compile_count - warmed} executable(s)")
        per_k = st["speculative"]["per_k"]
        assert all(per_k[kv]["dispatches"] > 0 for kv in LADDER)

    def test_controller_requires_a_ladder(self, trained):
        """A controller on a ladderless bundle is a config error, not
        a silent no-op (the re-bucket would quietly never happen)."""
        from paddle_tpu.models import transformer as T
        from paddle_tpu import unique_name

        with unique_name.guard():
            fixed = T.build_decode_step_program(
                n_slots=2, state_prefix="@fx/", admit_buckets=[2],
                draft=DraftConfig(d_model=DD, n_heads=H, n_layers=L,
                                  d_inner=32, k=2),
                **trained["kwargs"])
        with pytest.raises(ValueError, match="k ladder"):
            ContinuousGenerationServer(
                fixed, executor=trained["exe"],
                scope=trained["scope"],
                spec_controller=SpecController((0, 2), default_k=2),
                start=False)


# ---------------------------------------------------------------------------
# cache keys / fingerprints
# ---------------------------------------------------------------------------
class TestTokensAndFingerprints:
    def test_draft_and_sharding_tokens_separate(self):
        base = DraftConfig(d_model=DD, n_heads=H, n_layers=L,
                           d_inner=32, k=2)
        tokens = {base.token(),
                  DraftConfig(d_model=DD, n_heads=H, n_layers=L,
                              d_inner=32, k=2,
                              k_options=LADDER).token(),
                  DraftConfig(k=2, kind="ngram", ngram=2).token(),
                  DraftConfig(k=2, kind="ngram", ngram=3).token(),
                  DraftConfig(d_model=DD, n_heads=H, n_layers=L,
                              d_inner=32, k=2,
                              sharded=True).token()}
        assert len(tokens) == 5
        assert ShardingConfig(tp=2).token() != \
            ShardingConfig(tp=2, qkv_interleaved=True).token()

    def test_bundle_fingerprints_never_dedupe(self, trained):
        from types import SimpleNamespace

        from paddle_tpu.inference.runtime.registry import \
            server_fingerprint

        fps = {name: server_fingerprint(
                   SimpleNamespace(bundle=trained[name]))
               for name in ("adapt", "ngram")}
        assert len(set(fps.values())) == 2
        assert trained["adapt"].spec_k_options == LADDER
        assert trained["ngram"].spec_k_options == (0, 2)


# ---------------------------------------------------------------------------
# distillation: the draft learns the TARGET, acceptance follows
# ---------------------------------------------------------------------------
class TestDistillation:
    def test_distill_lifts_agreement_and_acceptance(self, trained):
        """A fresh never-trained draft ("raw_") serves speculative
        traffic token-exactly (correctness never depended on the
        draft) but accepts ~nothing; distilling it on the target's
        own greedy streams lifts both the in-program agreement metric
        and the serve-time acceptance. Parity holds before AND after
        — distillation moves only the speed, never the tokens."""
        from paddle_tpu import unique_name
        from paddle_tpu.models import transformer as T
        from paddle_tpu.models.distill import distill_draft

        exe, scope = trained["exe"], trained["scope"]
        raw = DraftConfig(d_model=DD, n_heads=H, n_layers=L,
                          d_inner=32, k=2, prefix="raw_")
        with unique_name.guard():
            _, r_st, _ = T.build_program(
                seq_len=S, d_model=DD, n_heads=H, n_layers=L,
                d_inner=32, vocab=V, with_optimizer=False,
                dropout_rate=0.0, name_prefix="raw_")
            exe.run(r_st, scope=scope)  # raw_ params only
            bundle = T.build_decode_step_program(
                n_slots=N_SLOTS, state_prefix="@rw/",
                admit_buckets=[N_SLOTS], draft=raw,
                **trained["kwargs"])
        srcs = _mixed_len_prompts(np.random.RandomState(47), 8)
        want = _oracle(trained, srcs)
        got, st = _serve(trained, bundle, srcs)
        np.testing.assert_array_equal(got, want)
        before = st["speculative"]["accepted"] \
            / max(st["speculative"]["proposed"], 1)

        res = distill_draft(
            exe, scope, raw,
            decode_fn=lambda b: _oracle(trained, b),
            prompts_fn=_mixed_len_prompts,
            **trained["kwargs"], rounds=8, batch=8, inner_steps=4,
            learning_rate=0.01, seed=3)
        assert len(res["agree"]) == 8
        # trajectory values are END-of-round (post inner steps), and
        # the tiny pair saturates within round 1 — the before/after
        # claim lives at the SERVE level below, not between rounds
        assert res["agree_last"] > 0.4, res

        got2, st2 = _serve(trained, bundle, srcs)
        np.testing.assert_array_equal(got2, want)
        after = st2["speculative"]["accepted"] \
            / max(st2["speculative"]["proposed"], 1)
        assert after > before + 0.1, (before, after)
        assert after > 0.25, (before, after)
