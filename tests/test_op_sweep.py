"""Numeric oracle sweep over registered ops without dedicated tests.

Parity model: the reference's one-OpTest-file-per-op pattern
(tests/unittests/test_activation_op.py runs ~25 ops through one
harness). One table drives the REAL OpTest harness (Executor-compiled
programs + finite-difference grad checks, tests/op_test.py) for the
elementwise / logical / comparison / reduction / shape families, plus
statistical checks for the random ops and reference-formula oracles
for a sample of optimizer ops.
"""
import math

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as fluid
from op_test import OpTest
from paddle_tpu.core.program import Operator
from paddle_tpu.core.registry import run_op

R = np.random.RandomState(7)
X = (R.rand(4, 6).astype("float32") * 2 - 1)
XP = np.abs(X) + 0.1                       # strictly positive
Y = (R.rand(4, 6).astype("float32") * 2 - 1)
YP = np.abs(Y) + 0.1
B1 = (X > 0)
B2 = (Y > 0)


def _sig(v):
    return 1.0 / (1.0 + np.exp(-v))


def _case(op_type, inputs, outputs, attrs=None, grad=(), atol=2e-5,
          no_grad=(), out_name=None):
    """Run one op through the OpTest harness: Executor-compiled
    forward vs oracle, then fd grad check for `grad` inputs. Shared
    with test_op_sweep2."""
    t = OpTest("setUp")
    t.setUp()
    t.op_type = op_type
    t.inputs = inputs
    t.outputs = outputs
    t.attrs = attrs or {}
    t.check_output(atol=atol, rtol=atol)
    if grad:
        t.check_grad(list(grad), out_name or next(iter(outputs)),
                     no_grad_set=set(no_grad))


def _run(op_type, inputs, attrs=None, out_slots=("Out",)):
    """Eager path for ops whose outputs aren't compared elementwise
    (random draws, multi-slot helpers)."""
    prog = fluid.Program()
    block = prog.global_block
    in_names = {}
    env = {}
    for slot, vals in inputs.items():
        if not isinstance(vals, list):
            vals = [(slot.lower(), vals)]
        names = []
        for name, arr in vals:
            env[name] = jnp.asarray(np.asarray(arr))
            names.append(name)
        in_names[slot] = names
    out_names = {s: [f"out_{s.lower()}"] for s in out_slots}
    op = Operator(block, op_type, in_names, out_names, attrs or {})
    run_op(op, env)
    outs = [np.asarray(env[f"out_{s.lower()}"]) for s in out_slots]
    return outs[0] if len(outs) == 1 else outs


# op, input, oracle, attrs, grad-checkable
UNARY_CASES = [
    ("acos", np.clip(X, -0.9, 0.9), np.arccos(np.clip(X, -0.9, 0.9)),
     {}, True),
    ("atan", X, np.arctan(X), {}, True),
    ("ceil", X, np.ceil(X), {}, False),
    ("reciprocal", XP, 1.0 / XP, {}, True),
    ("rsqrt", XP, 1.0 / np.sqrt(XP), {}, True),
    ("gelu", X, 0.5 * X * (1 + np.vectorize(math.erf)(X / np.sqrt(2))),
     {}, True),
    # kink-avoiding inputs: fd-vs-analytic grads disagree at the
    # non-differentiable points, so samples stay >=0.05 away
    ("leaky_relu", np.where(np.abs(X) < 0.05, 0.2, X),
     np.where(np.where(np.abs(X) < 0.05, 0.2, X) > 0,
              np.where(np.abs(X) < 0.05, 0.2, X),
              0.02 * np.where(np.abs(X) < 0.05, 0.2, X)),
     {"alpha": 0.02}, True),
    ("relu6",
     (lambda v: v + np.where(np.abs(v) < 0.1, 0.25, 0)
      + np.where(np.abs(v - 6) < 0.1, 0.3, 0))(X * 8),
     np.clip((lambda v: v + np.where(np.abs(v) < 0.1, 0.25, 0)
              + np.where(np.abs(v - 6) < 0.1, 0.3, 0))(X * 8), 0, 6),
     {}, True),
    ("softplus", X, np.log1p(np.exp(X)), {}, True),
    ("softsign", X, X / (1 + np.abs(X)), {}, True),
    ("swish", X, X * _sig(X), {"beta": 1.0}, True),
    ("hard_sigmoid", X / 2, np.clip(0.2 * (X / 2) + 0.5, 0, 1), {},
     True),
    ("hard_swish", X * 4, X * 4 * np.clip(X * 4 + 3, 0, 6) / 6, {},
     True),
    ("brelu", X * 30, np.clip(X * 30, 0.0, 24.0),
     {"t_min": 0.0, "t_max": 24.0}, True),
    ("soft_relu", X, np.log1p(np.exp(np.clip(X, -40, 40))),
     {"threshold": 40.0}, True),
    ("thresholded_relu", X, np.where(X > 0.3, X, 0.0),
     {"threshold": 0.3}, True),
    ("fill_zeros_like", X, np.zeros_like(X), {}, False),
    ("fill_any_like", X, np.full_like(X, 2.5), {"value": 2.5}, False),
    ("log_softmax", X,
     X - np.log(np.exp(X - X.max(-1, keepdims=True)).sum(
         -1, keepdims=True)) - X.max(-1, keepdims=True),
     {"axis": -1}, True),
]


@pytest.mark.parametrize("op_type,x,expect,attrs,diff",
                         UNARY_CASES,
                         ids=[c[0] for c in UNARY_CASES])
def test_unary_oracles(op_type, x, expect, attrs, diff):
    _case(op_type, {"X": x}, {"Out": expect}, attrs,
          grad=("X",) if diff else ())


BINARY_CASES = [
    ("elementwise_sub", X, Y, X - Y, {}, True),
    ("elementwise_max", X, Y + 0.05, np.maximum(X, Y + 0.05), {}, True),
    ("elementwise_min", X, Y + 0.05, np.minimum(X, Y + 0.05), {}, True),
    ("elementwise_mod", (XP * 10), (YP * 3),
     np.mod(XP * 10, YP * 3), {}, False),
    ("elementwise_pow", XP, YP, np.power(XP, YP), {}, True),
    ("elementwise_floordiv", (XP * 10), (YP * 3),
     np.floor_divide(XP * 10, YP * 3), {}, False),
]


@pytest.mark.parametrize("op_type,x,y,expect,attrs,diff",
                         BINARY_CASES,
                         ids=[c[0] for c in BINARY_CASES])
def test_binary_oracles(op_type, x, y, expect, attrs, diff):
    _case(op_type, {"X": x, "Y": y}, {"Out": expect}, attrs,
          grad=("X", "Y") if diff else ())


LOGICAL_CASES = [
    ("logical_and", B1, B2, B1 & B2),
    ("logical_or", B1, B2, B1 | B2),
    ("logical_xor", B1, B2, B1 ^ B2),
    ("greater_equal", X, Y, X >= Y),
    ("less_equal", X, Y, X <= Y),
    ("not_equal", X.round(1), Y.round(1), X.round(1) != Y.round(1)),
]


@pytest.mark.parametrize("op_type,x,y,expect",
                         LOGICAL_CASES,
                         ids=[c[0] for c in LOGICAL_CASES])
def test_logical_compare_oracles(op_type, x, y, expect):
    got = _run(op_type, {"X": x, "Y": y})
    np.testing.assert_array_equal(got.astype(bool), expect)


def test_logical_not():
    np.testing.assert_array_equal(
        _run("logical_not", {"X": B1}).astype(bool), ~B1)


REDUCE_CASES = [
    ("reduce_max", X, {"dim": [1], "keep_dim": False}, X.max(1), True),
    ("reduce_min", X, {"dim": [1], "keep_dim": False}, X.min(1), True),
    ("reduce_prod", XP, {"dim": [1], "keep_dim": False}, XP.prod(1),
     True),
    ("reduce_any", B1, {"dim": [1], "keep_dim": False}, B1.any(1),
     False),
]


@pytest.mark.parametrize("op_type,x,attrs,expect,diff",
                         REDUCE_CASES,
                         ids=[c[0] for c in REDUCE_CASES])
def test_reduce_oracles(op_type, x, attrs, expect, diff):
    _case(op_type, {"X": x}, {"Out": expect}, attrs,
          grad=("X",) if diff else ())


def test_norm_family():
    _case("frobenius_norm", {"X": X},
          {"Out": np.asarray(np.linalg.norm(X), np.float32)},
          {"dim": [0, 1]}, grad=("X",))
    _case("squared_l2_norm", {"X": X},
          {"Out": np.asarray((X ** 2).sum(), np.float32)},
          atol=1e-4, grad=("X",))
    _case("p_norm", {"X": X}, {"Out": np.linalg.norm(X, axis=1)},
          {"porder": 2.0, "axis": 1}, grad=("X",))
    out = _run("clip_by_norm", {"X": X}, {"max_norm": 0.5})
    np.testing.assert_allclose(np.linalg.norm(out), 0.5,
                               atol=1e-5, rtol=1e-4)


def test_shape_family():
    x3 = X.reshape(4, 6, 1)
    got = _run("squeeze2", {"X": x3}, {"axes": [2]},
               out_slots=("Out", "XShape"))[0]
    np.testing.assert_allclose(got, X)
    got = _run("unsqueeze2", {"X": X}, {"axes": [0]},
               out_slots=("Out", "XShape"))[0]
    np.testing.assert_allclose(got, X[None])
    got = _run("reshape2", {"X": X}, {"shape": [2, 12]},
               out_slots=("Out", "XShape"))[0]
    np.testing.assert_allclose(got, X.reshape(2, 12))
    got = _run("flatten2", {"X": x3}, {"axis": 1},
               out_slots=("Out", "XShape"))[0]
    np.testing.assert_allclose(got, X)
    prog = fluid.Program()
    op = Operator(prog.global_block, "unstack", {"X": ["ux"]},
                  {"Y": [f"uy{i}" for i in range(4)]},
                  {"axis": 0, "num": 4})
    env = {"ux": jnp.asarray(X)}
    run_op(op, env)
    for i in range(4):
        np.testing.assert_allclose(np.asarray(env[f"uy{i}"]), X[i])


def test_gather_scatter_multiplex_argminmax():
    idx = np.array([[0], [2]], np.int64)
    np.testing.assert_allclose(_run("gather_nd", {"X": X, "Index": idx}),
                               X[[0, 2]])
    ids = np.array([1, 3], np.int64)
    upd = np.ones((2, 6), np.float32)
    expect = X.copy()
    expect[[1, 3]] = 1.0
    np.testing.assert_allclose(
        _run("scatter", {"X": X, "Ids": ids, "Updates": upd},
             {"overwrite": True}), expect)
    xs = [("m0", X), ("m1", Y)]
    sel = np.array([[0], [1], [0], [1]], np.int64)
    got = _run("multiplex", {"X": xs, "Ids": sel})
    np.testing.assert_allclose(got[1], Y[1])
    np.testing.assert_allclose(got[0], X[0])
    np.testing.assert_array_equal(
        np.asarray(_run("arg_max", {"X": X}, {"axis": 1})).reshape(-1),
        X.argmax(1))
    np.testing.assert_array_equal(
        np.asarray(_run("arg_min", {"X": X}, {"axis": 1})).reshape(-1),
        X.argmin(1))


def test_image_layout_ops():
    x = R.rand(2, 8, 4, 4).astype("float32")
    got = _run("pixel_shuffle", {"X": x}, {"upscale_factor": 2})
    assert got.shape == (2, 2, 8, 8)
    back = _run("pixel_unshuffle", {"X": got}, {"downscale_factor": 2})
    np.testing.assert_allclose(back, x, atol=1e-6)
    got = _run("shuffle_channel", {"X": x}, {"group": 2})
    assert got.shape == x.shape
    np.testing.assert_allclose(got[:, 0], x[:, 0])
    np.testing.assert_allclose(got[:, 1], x[:, 4])
    got = _run("maxout", {"X": x}, {"groups": 2})
    assert got.shape == (2, 4, 4, 4)
    np.testing.assert_allclose(got[:, 0], np.maximum(x[:, 0], x[:, 1]))
    p = _run("pad2d", {"X": x}, {"paddings": [1, 1, 2, 2],
                                 "mode": "constant", "pad_value": 0.0})
    assert p.shape == (2, 8, 6, 8)


def test_random_ops_statistics():
    shape = [2048]
    g = _run("gaussian_random", {}, {"shape": shape, "mean": 1.0,
                                     "std": 2.0, "seed": 5})
    assert abs(float(g.mean()) - 1.0) < 0.2
    assert abs(float(g.std()) - 2.0) < 0.2
    u = _run("uniform_random", {}, {"shape": shape, "min": -1.0,
                                    "max": 3.0, "seed": 5})
    assert float(u.min()) >= -1.0 and float(u.max()) <= 3.0
    assert abs(float(u.mean()) - 1.0) < 0.2
    t = _run("truncated_gaussian_random", {},
             {"shape": shape, "mean": 0.0, "std": 1.0, "seed": 5})
    assert float(np.abs(t).max()) <= 2.0 + 1e-5
    probs = np.tile(np.array([[0.0, 1.0, 0.0]], np.float32), (8, 1))
    s = _run("sampling_id", {"X": probs}, {"seed": 3})
    assert np.all(np.asarray(s).reshape(-1) == 1)


def test_optimizer_op_formulas():
    """Single-step parity with the reference update rules
    (operators/optimizers/*.h)."""
    p = R.rand(6).astype("float32")
    g = R.rand(6).astype("float32")
    lr = np.array([0.1], np.float32)

    # rmsprop (rmsprop_op.h)
    ms = np.full(6, 0.5, np.float32)
    mom = np.zeros(6, np.float32)
    outs = _run("rmsprop",
                {"Param": p, "Grad": g, "MeanSquare": ms,
                 "Moment": mom, "LearningRate": lr},
                {"decay": 0.9, "momentum": 0.0, "epsilon": 1e-6},
                out_slots=("ParamOut", "MeanSquareOut", "MomentOut"))
    ms2 = 0.9 * ms + 0.1 * g * g
    mom2 = 0.1 * g / np.sqrt(ms2 + 1e-6)
    np.testing.assert_allclose(outs[1], ms2, atol=1e-6, rtol=1e-5)
    np.testing.assert_allclose(outs[0], p - mom2, atol=1e-6, rtol=1e-5)

    # adadelta (adadelta_op.h)
    ag = np.full(6, 0.3, np.float32)
    au = np.full(6, 0.2, np.float32)
    outs = _run("adadelta",
                {"Param": p, "Grad": g, "AvgSquaredGrad": ag,
                 "AvgSquaredUpdate": au},
                {"rho": 0.95, "epsilon": 1e-6},
                out_slots=("ParamOut", "AvgSquaredGradOut",
                           "AvgSquaredUpdateOut"))
    ag2 = 0.95 * ag + 0.05 * g * g
    upd = np.sqrt(au + 1e-6) / np.sqrt(ag2 + 1e-6) * g
    np.testing.assert_allclose(outs[1], ag2, atol=1e-6, rtol=1e-5)
    np.testing.assert_allclose(outs[0], p - upd, atol=1e-6, rtol=1e-5)

    # adamax (adamax_op.h)
    m = np.zeros(6, np.float32)
    inf = np.full(6, 0.01, np.float32)
    b1p = np.array([0.9], np.float32)
    outs = _run("adamax",
                {"Param": p, "Grad": g, "Moment": m, "InfNorm": inf,
                 "LearningRate": lr, "Beta1Pow": b1p},
                {"beta1": 0.9, "beta2": 0.999, "epsilon": 1e-8},
                out_slots=("ParamOut", "MomentOut", "InfNormOut"))
    m2 = 0.9 * m + 0.1 * g
    inf2 = np.maximum(0.999 * inf, np.abs(g))
    lr_t = 0.1 / (1 - 0.9)
    np.testing.assert_allclose(outs[1], m2, atol=1e-6)
    np.testing.assert_allclose(outs[2], inf2, atol=1e-6)
    np.testing.assert_allclose(outs[0], p - lr_t * m2 / (inf2 + 1e-8),
                               atol=1e-5, rtol=1e-5)


if __name__ == "__main__":
    pytest.main([__file__, "-q"])
