"""Pipeline (pp) and expert-parallel MoE (ep) numerics on the virtual
8-device CPU mesh — beyond-reference parallelism (SURVEY.md §2.4 marks
PP/EP absent upstream)."""
import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.parallel import make_mesh, MeshConfig
from paddle_tpu.parallel.pipeline import pipeline_apply
from paddle_tpu.parallel.moe import moe_apply


class TestPipeline:
    def _setup(self, pp, d=8, batch=16, seed=3):
        mesh = make_mesh(MeshConfig(pp=pp),
                         devices=jax.devices()[:pp])
        r = np.random.RandomState(seed)
        w = jnp.asarray(r.randn(pp, d, d).astype(np.float32) * 0.3)
        b = jnp.asarray(r.randn(pp, d).astype(np.float32) * 0.1)
        x = jnp.asarray(r.randn(batch, d).astype(np.float32))
        return mesh, w, b, x

    @staticmethod
    def _stage(params, h):
        wi, bi = params
        return jnp.tanh(h @ wi + bi)

    def _sequential(self, w, b, x):
        for i in range(w.shape[0]):
            x = jnp.tanh(x @ w[i] + b[i])
        return x

    def test_4stage_matches_sequential(self):
        mesh, w, b, x = self._setup(pp=4)
        got = pipeline_apply(self._stage, (w, b), x, mesh, n_micro=8)
        want = self._sequential(w, b, x)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-5, rtol=1e-5)

    def test_8stage_single_micro_per_tick(self):
        mesh, w, b, x = self._setup(pp=8)
        got = pipeline_apply(self._stage, (w, b), x, mesh, n_micro=4)
        want = self._sequential(w, b, x)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-5, rtol=1e-5)

    def test_pipeline_grads_match_sequential(self):
        mesh, w, b, x = self._setup(pp=4)

        def loss_pipe(w, b):
            y = pipeline_apply(self._stage, (w, b), x, mesh, n_micro=4)
            return (y ** 2).sum()

        def loss_seq(w, b):
            return (self._sequential(w, b, x) ** 2).sum()

        gp = jax.grad(loss_pipe, argnums=(0, 1))(w, b)
        gs = jax.grad(loss_seq, argnums=(0, 1))(w, b)
        for a, e in zip(gp, gs):
            np.testing.assert_allclose(np.asarray(a), np.asarray(e),
                                       atol=1e-4, rtol=1e-4)


class TestMoE:
    def _setup(self, ep, t=32, d=8, f=16, E=8, seed=1):
        mesh = make_mesh(MeshConfig(ep=ep),
                         devices=jax.devices()[:ep])
        r = np.random.RandomState(seed)
        x = jnp.asarray(r.randn(t, d).astype(np.float32))
        wg = jnp.asarray(r.randn(d, E).astype(np.float32))
        w1 = jnp.asarray(r.randn(E, d, f).astype(np.float32) * 0.3)
        w2 = jnp.asarray(r.randn(E, f, d).astype(np.float32) * 0.3)
        return mesh, x, wg, w1, w2

    @staticmethod
    def _dense(x, wg, w1, w2):
        gates = jax.nn.softmax(x @ wg, axis=-1)
        idx = jnp.argmax(gates, axis=-1)
        return jnp.stack([
            gates[i, idx[i]] *
            (jax.nn.relu(x[i] @ w1[idx[i]]) @ w2[idx[i]])
            for i in range(x.shape[0])])

    def test_ep4_matches_dense_when_no_drops(self):
        mesh, x, wg, w1, w2 = self._setup(ep=4)
        got, _, drop = moe_apply(x, wg, w1, w2, mesh,
                                 capacity_factor=64.0)
        want = self._dense(x, wg, w1, w2)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-5, rtol=1e-4)

    def test_capacity_drops_zero_tokens(self):
        """Tiny capacity: over-capacity tokens produce zero rows, and
        every produced row matches its dense counterpart."""
        mesh, x, wg, w1, w2 = self._setup(ep=2)
        got = np.asarray(moe_apply(x, wg, w1, w2, mesh,
                                   capacity_factor=0.25)[0])
        want = np.asarray(self._dense(x, wg, w1, w2))
        for i in range(got.shape[0]):
            if np.allclose(got[i], 0.0, atol=1e-7):
                continue
            np.testing.assert_allclose(got[i], want[i], atol=1e-5,
                                       rtol=1e-4)
        assert (np.abs(got).sum(axis=1) > 1e-7).sum() >= 4

    def test_moe_grads_flow(self):
        mesh, x, wg, w1, w2 = self._setup(ep=2)

        def loss(w1, w2):
            return (moe_apply(x, wg, w1, w2, mesh,
                              capacity_factor=64.0)[0] ** 2).sum()

        g1, g2 = jax.grad(loss, argnums=(0, 1))(w1, w2)
        assert np.isfinite(np.asarray(g1)).all()
        assert np.abs(np.asarray(g1)).sum() > 0
        assert np.abs(np.asarray(g2)).sum() > 0
