"""Admission-capacity preflights (the PTA200 model at runtime) and
the chunk-size arithmetic helper.

The static model (analysis/liveness.session_feasibility, validated
against the protomodel explorer) gets two serving enforcement points:

* construction — a bundle DECLARING its session workload
  (``bundle.workload = {"distinct_session_prompts": K, ...}``) is
  checked at server construction, so a provably-infeasible deployment
  raises the named, non-retryable ``AdmissionInfeasible`` before a
  single request instead of wedging admissions at steady state;
* per-submit — opening a session whose prompt would push the
  distinct-open-prompt count past the prompt-entry pool raises the
  same error synchronously from ``submit`` (pinned entries are
  unevictable, so the request could NEVER be satisfied until a close;
  == entries is feasible, and ``close_session`` restores capacity).

``CacheConfig.suggest_chunk_tokens`` closes the PR 17 ROADMAP
leftover (chunk size was hand-tuned per shape): the PERF.md worked
example is pinned here as arithmetic."""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import unique_name
from paddle_tpu.core.scope import Scope
from paddle_tpu.inference import (AdmissionInfeasible,
                                  PagedContinuousGenerationServer)
from paddle_tpu.models.decode_engine import CacheConfig

V, D, H, L, S, MAXT = 16, 32, 2, 1, 8, 8
BS, NB, E = 4, 12, 2
N_SLOTS = 2


@pytest.fixture(scope="module")
def paged():
    """Untrained tiny paged bundle + warm scope: the preflights fire
    on capacity arithmetic, not on token quality."""
    from paddle_tpu.models import transformer as T

    fluid.seed(0)
    scope = Scope()
    with unique_name.guard():
        _, startup, _ = T.build_program(
            seq_len=S, d_model=D, n_heads=H, n_layers=L, d_inner=32,
            vocab=V, with_optimizer=False, dropout_rate=0.0)
    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(startup, scope=scope)  # weights exist; training irrelevant
    with unique_name.guard():
        bundle = T.build_decode_step_program(
            seq_len=S, max_out_len=MAXT, d_model=D, n_heads=H,
            n_layers=L, d_inner=32, vocab=V, n_slots=N_SLOTS,
            state_prefix="@adm/",
            cache=CacheConfig(layout="paged", block_size=BS,
                              n_blocks=NB, n_prompt_entries=E))
    return {"bundle": bundle, "exe": exe, "scope": scope}


def _server(p, **kw):
    return PagedContinuousGenerationServer(
        p["bundle"], executor=p["exe"], scope=p["scope"], **kw)


def _prompt(i):
    row = np.full((S,), 1, np.int64)
    row[0] = 3 + i
    return row


class TestConstructionPreflight:
    def test_infeasible_declared_workload_raises_named_error(
            self, paged):
        bundle = paged["bundle"]
        bundle.workload = {"distinct_session_prompts": E + 1,
                           "sessions_close": False}
        try:
            with pytest.raises(AdmissionInfeasible,
                               match="session-pinning"):
                _server(paged)
        finally:
            del bundle.workload
        # the verdict is a capacity fact, not a transient: callers
        # must not retry their way around it
        assert AdmissionInfeasible("x").retryable is False

    def test_feasible_declared_workload_constructs(self, paged):
        bundle = paged["bundle"]
        bundle.workload = {"distinct_session_prompts": E}
        try:
            with _server(paged):
                pass
        finally:
            del bundle.workload

    def test_closing_sessions_make_any_count_feasible(self, paged):
        bundle = paged["bundle"]
        bundle.workload = {"distinct_session_prompts": E + 3,
                           "sessions_close": True}
        try:
            with _server(paged):
                pass
        finally:
            del bundle.workload


class TestSubmitPreflight:
    def test_session_overflow_raises_and_close_restores(self, paged):
        with _server(paged) as srv:
            for i in range(E):
                srv.submit(_prompt(i),
                           session_id=f"s{i}").result(120.0)
            # E distinct prompts pinned == E entries: at capacity but
            # feasible; one MORE distinct prompt can never admit
            with pytest.raises(AdmissionInfeasible,
                               match="close_session"):
                srv.submit(_prompt(E), session_id="extra")
            # the refused session was NOT registered
            assert srv.session_history("extra") is None
            # a close releases the pin and the same submit succeeds
            srv.close_session("s0")
            srv.submit(_prompt(E), session_id="extra").result(120.0)

    @pytest.mark.slow
    def test_duplicate_prompt_shares_entry_and_admits(self, paged):
        # distinct-prompt counting: a new session re-using an OPEN
        # session's prompt shares its refcounted entry and must pass
        # the preflight even at full pinning
        with _server(paged) as srv:
            for i in range(E):
                srv.submit(_prompt(i),
                           session_id=f"t{i}").result(120.0)
            srv.submit(_prompt(0), session_id="twin").result(120.0)

    @pytest.mark.slow
    def test_non_session_traffic_unaffected(self, paged):
        # plain requests churn entries (release on retire): no pin,
        # no preflight, even many distinct prompts
        with _server(paged) as srv:
            for i in range(E + 2):
                srv.submit(_prompt(i)).result(120.0)


class TestSuggestChunkTokens:
    def _duck(self, seq_len, n_layers):
        class B:
            pass

        b = B()
        b.seq_len = seq_len
        b._state_specs = {f"@x/cross_k{i}": ((1,), "float32")
                          for i in range(n_layers)}
        return b

    def test_perf_md_worked_example(self):
        # seq_len=2048, L=1 -> 4 phases; 150 ms monolithic prefill;
        # 5 ms budget -> C=256 (tick 4.69 ms; 512 would be 9.38 ms)
        b = self._duck(2048, 1)
        assert CacheConfig.suggest_chunk_tokens(b, 5.0) == 256

    def test_budget_scales_and_caps_at_seq_len(self):
        b = self._duck(2048, 1)
        assert CacheConfig.suggest_chunk_tokens(b, 10.0) == 512
        # a huge budget never suggests more than one full prefill
        assert CacheConfig.suggest_chunk_tokens(b, 1e9) == 2048

    def test_floor_is_two(self):
        # validate() rejects C=1 (accumulation-order drift breaks
        # byte-exact parity): even an impossible budget floors at 2
        b = self._duck(2048, 1)
        assert CacheConfig.suggest_chunk_tokens(b, 1e-6) == 2

    def test_more_layers_mean_more_phases_and_bigger_chunks(self):
        # 2L+2 phases each touch C tokens once: deeper models do less
        # work per phase-tick, so the same budget fits a bigger chunk
        shallow = CacheConfig.suggest_chunk_tokens(
            self._duck(2048, 1), 2.5)
        deep = CacheConfig.suggest_chunk_tokens(
            self._duck(2048, 3), 2.5)
        assert deep > shallow

    def test_bad_budget_raises(self):
        with pytest.raises(ValueError, match="tick_budget_ms"):
            CacheConfig.suggest_chunk_tokens(self._duck(2048, 1), 0.0)
