"""API-surface completion tests: the last reference layers/* __all__
entries (stanh, adaptive_pool3d, mean_iou, tree_conv, the reader layer
family, range, append_LARS, SSD multi_box_head...).

Parity model: reference tests/unittests/test_layers.py (build-and-run
surface checks) + the per-op numeric oracles of op_test.py.
"""
import os

import numpy as np
import pytest

import paddle_tpu as fluid


def _run(fetches, feed=None, main=None, startup=None):
    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(startup or fluid.default_startup_program())
    return exe.run(main or fluid.default_main_program(),
                   feed=feed or {}, fetch_list=fetches)


class TestNewNNLayers:
    def test_stanh_oracle(self):
        x = fluid.layers.data(name="x", shape=[6], dtype="float32")
        out = fluid.layers.stanh(x, scale_a=0.5, scale_b=2.0)
        xnp = np.random.RandomState(0).randn(3, 6).astype(np.float32)
        got, = _run([out], {"x": xnp})
        np.testing.assert_allclose(got, 2.0 * np.tanh(0.5 * xnp),
                                   rtol=1e-5)

    def test_adaptive_pool3d_oracle(self):
        x = fluid.layers.data(name="x", shape=[2, 4, 4, 4],
                              dtype="float32")
        avg = fluid.layers.adaptive_pool3d(x, 2, pool_type="avg")
        xnp = np.random.RandomState(1).randn(1, 2, 4, 4, 4).astype(
            np.float32)
        got, = _run([avg], {"x": xnp})
        ref = xnp.reshape(1, 2, 2, 2, 2, 2, 2, 2).mean(axis=(3, 5, 7))
        np.testing.assert_allclose(got, ref, rtol=1e-5)
        assert got.shape == (1, 2, 2, 2, 2)

    def test_gaussian_random_batch_size_like(self):
        x = fluid.layers.data(name="x", shape=[3], dtype="float32")
        out = fluid.layers.gaussian_random_batch_size_like(
            x, shape=[-1, 50], mean=2.0, std=0.1, seed=7)
        xnp = np.zeros((9, 3), np.float32)
        got, = _run([out], {"x": xnp})
        assert got.shape == (9, 50)
        assert abs(float(got.mean()) - 2.0) < 0.05

    def test_autoincreased_step_counter(self):
        counter = fluid.layers.autoincreased_step_counter()
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(fluid.default_startup_program())
        vals = [int(exe.run(fetch_list=[counter])[0][0])
                for _ in range(3)]
        assert vals == [1, 2, 3]

    def test_image_resize_short(self):
        x = fluid.layers.data(name="x", shape=[3, 8, 16],
                              dtype="float32")
        out = fluid.layers.image_resize_short(x, 4,
                                              resample="NEAREST")
        xnp = np.random.RandomState(2).randn(2, 3, 8, 16).astype(
            np.float32)
        got, = _run([out], {"x": xnp})
        assert got.shape == (2, 3, 4, 8)  # short edge 8 -> 4, ratio .5

    def test_mean_iou_oracle(self):
        pred = fluid.layers.data(name="p", shape=[4], dtype="int64")
        lab = fluid.layers.data(name="l", shape=[4], dtype="int64")
        miou, _, _ = fluid.layers.mean_iou(pred, lab, num_classes=3)
        p = np.array([[0, 0, 1, 2]], np.int64)
        g = np.array([[0, 1, 1, 2]], np.int64)
        got, = _run([miou], {"p": p, "l": g})
        # class0: i1/u2, class1: i1/u2, class2: i1/u1
        np.testing.assert_allclose(got, [(0.5 + 0.5 + 1) / 3],
                                   rtol=1e-5)

    def test_lod_reset_passthrough(self):
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        y = fluid.layers.data(name="y", shape=[4], dtype="float32")
        out = fluid.layers.lod_reset(x, y=y)
        xnp = np.random.RandomState(3).randn(2, 4).astype(np.float32)
        got, = _run([out], {"x": xnp, "y": xnp * 0})
        np.testing.assert_array_equal(got, xnp)

    def test_selected_rows_pair(self):
        vals = fluid.layers.data(name="v", shape=[3], dtype="float32")
        rows = fluid.layers.data(name="v@ROWS", shape=[-1],
                                 dtype="int64",
                                 append_batch_size=False)
        dense = fluid.layers.get_tensor_from_selected_rows(vals,
                                                           height=5)
        v = np.array([[1, 1, 1], [2, 2, 2], [3, 3, 3]], np.float32)
        r = np.array([0, 2, 2], np.int64)
        got, = _run([dense], {"v": v, "v@ROWS": r})
        assert got.shape[0] == 5
        np.testing.assert_allclose(got[0], [1, 1, 1])
        np.testing.assert_allclose(got[2], [5, 5, 5])  # merged rows

    def test_tree_conv_builds_and_runs(self):
        nodes = fluid.layers.data(name="nodes", shape=[5, 6],
                                  dtype="float32")
        edges = fluid.layers.data(name="edges", shape=[4, 2],
                                  dtype="int32")
        out = fluid.layers.tree_conv(nodes, edges, output_size=7,
                                     num_filters=2, max_depth=2)
        n = np.random.RandomState(4).randn(1, 5, 6).astype(np.float32)
        e = np.array([[[1, 2], [1, 3], [2, 4], [2, 5]]], np.int32)
        got, = _run([out], {"nodes": n, "edges": e})
        assert got.shape == (1, 5, 7, 2)
        assert np.isfinite(got).all()


class TestTensorRangeAndArray:
    def test_range_static(self):
        out = fluid.layers.range(1, 10, 2)
        got, = _run([out])
        np.testing.assert_allclose(got, np.arange(1.0, 10.0, 2.0))
        assert out.shape == (5,)

    def test_range_int_dtype_matches_declared_var(self):
        # ADVICE r2: range(dtype="int64") used to yield a float array
        # under an int-typed var — breaks while-loop carry dtypes
        out = fluid.layers.range(0, 6, 2, dtype="int32")
        got, = _run([out])
        assert got.dtype == np.int32
        np.testing.assert_array_equal(got, [0, 2, 4])

    def test_tensor_array_to_tensor(self):
        a = fluid.layers.fill_constant([2, 3], "float32", 1.0)
        b = fluid.layers.fill_constant([2, 3], "float32", 2.0)
        out, idx = fluid.layers.tensor_array_to_tensor([a, b], axis=0)
        got, gidx = _run([out, idx])
        assert got.shape == (4, 3)
        np.testing.assert_array_equal(gidx, [2, 2])

    def test_tensor_array_to_tensor_single_entry(self):
        a = fluid.layers.fill_constant([2, 3], "float32", 1.5)
        out, idx = fluid.layers.tensor_array_to_tensor([a], axis=0)
        got, gidx = _run([out, idx])
        assert got.shape == (2, 3)  # NOT flattened by the legacy path
        np.testing.assert_array_equal(gidx, [2])


class TestReaderLayerFamily:
    def test_py_reader_train_loop(self):
        reader = fluid.layers.py_reader(
            capacity=8, shapes=[(8, 4), (8, 1)],
            dtypes=["float32", "float32"], name="r1",
            use_double_buffer=False)
        x, y = fluid.layers.read_file(reader)
        pred = fluid.layers.fc(x, 1)
        loss = fluid.layers.mean(fluid.layers.square(pred - y))
        fluid.optimizer.SGDOptimizer(0.1).minimize(loss)

        rng = np.random.RandomState(5)

        def batches():
            for _ in range(4):
                xb = rng.randn(8, 4).astype(np.float32)
                yield xb, xb.sum(1, keepdims=True).astype(np.float32)

        reader.decorate_tensor_provider(batches)
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(fluid.default_startup_program())
        reader.start()
        losses = [float(np.mean(exe.run(fetch_list=[loss])[0]))
                  for _ in range(8)]
        assert losses[-1] < losses[0]

    def test_py_reader_paddle_reader_and_double_buffer(self):
        reader = fluid.layers.py_reader(
            capacity=4, shapes=[(2, 2)], dtypes=["float32"],
            name="r2", use_double_buffer=True)
        (x,) = [fluid.layers.read_file(reader)]
        s = fluid.layers.reduce_sum(x)

        def paddle_reader():  # batches of sample tuples
            yield [(np.ones(2, np.float32),),
                   (np.ones(2, np.float32) * 2,)]

        reader.decorate_paddle_reader(paddle_reader)
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(fluid.default_startup_program())
        reader.start()
        got = exe.run(fetch_list=[s])[0]
        assert float(np.asarray(got)) == pytest.approx(6.0)

    def test_batch_and_shuffle_chain(self):
        base = fluid.layers.py_reader(
            capacity=4, shapes=[(1,)], dtypes=["float32"],
            name="r3", use_double_buffer=False)
        chained = fluid.layers.batch(
            fluid.layers.shuffle(base, buffer_size=16), batch_size=4)
        # batch() prepends the batch dim to the static specs itself
        assert chained.shapes == [(4, 1)]
        x = fluid.layers.read_file(chained)
        s = fluid.layers.reduce_sum(x)

        def provider():
            for i in range(16):
                yield (np.full((1,), float(i), np.float32),)

        base.decorate_tensor_provider(provider)
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(fluid.default_startup_program())
        got = exe.run(fetch_list=[s])[0]
        assert np.isfinite(np.asarray(got)).all()

    def test_random_data_generator(self):
        reader = fluid.layers.random_data_generator(
            0.0, 1.0, shapes=[(4, 3)])
        x = fluid.layers.read_file(reader)
        got, = _run([x])
        assert got.shape == (4, 3)
        assert (got >= 0).all() and (got <= 1).all()

    def test_preprocessor(self):
        base = fluid.layers.py_reader(
            capacity=4, shapes=[(2, 3)], dtypes=["float32"],
            name="r4", use_double_buffer=False)
        pre = fluid.layers.Preprocessor(base, name="pp")
        with pre.block():
            (inp,) = pre.inputs()
            pre.outputs(fluid.layers.scale(inp, scale=10.0))
        out_reader = pre()
        x = fluid.layers.read_file(out_reader)
        s = fluid.layers.reduce_sum(x)

        def provider():
            yield (np.ones((2, 3), np.float32),)

        base.decorate_tensor_provider(provider)
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(fluid.default_startup_program())
        got = exe.run(fetch_list=[s])[0]
        assert float(np.asarray(got)) == pytest.approx(60.0)

    def test_load_layer_roundtrip(self, tmp_path):
        import os

        # save a var with the in-graph save op, reload via layers.load
        v = fluid.layers.fill_constant([3], "float32", 4.25)
        path = os.path.join(str(tmp_path), "blob")
        main = fluid.default_main_program()
        main.global_block.append_op("save", {"X": v},
                                    {}, {"file_path": path})
        _run([v])
        main2 = fluid.Program()
        with fluid.program_guard(main2, fluid.Program()):
            dst = fluid.layers.create_tensor("float32", name="dst")
            dst.shape = (3,)
            fluid.layers.load(dst, path)
        exe = fluid.Executor(fluid.TPUPlace(0))
        got = exe.run(main2, fetch_list=["dst"])[0]
        np.testing.assert_allclose(got, [4.25] * 3)


class TestDetectionWrappers:
    def test_box_decoder_and_assign_builds_runs(self):
        pb = fluid.layers.data(name="pb", shape=[4], dtype="float32")
        pbv = fluid.layers.data(name="pbv", shape=[-1],
                                dtype="float32",
                                append_batch_size=False)
        tb = fluid.layers.data(name="tb", shape=[8], dtype="float32")
        bs = fluid.layers.data(name="bs", shape=[2], dtype="float32")
        dec, asg = fluid.layers.box_decoder_and_assign(
            pb, pbv, tb, bs, box_clip=2.0)
        r = np.random.RandomState(6)
        feed = {"pb": np.abs(r.randn(5, 4)).astype(np.float32),
                "pbv": np.array([0.1, 0.1, 0.2, 0.2], np.float32),
                "tb": r.randn(5, 8).astype(np.float32),
                "bs": r.rand(5, 2).astype(np.float32)}
        d, a = _run([dec, asg], feed)
        assert d.shape == (5, 8) and a.shape == (5, 4)

    def test_distribute_fpn_proposals_builds_runs(self):
        rois = fluid.layers.data(name="rois", shape=[4],
                                 dtype="float32")
        multi, restore = fluid.layers.distribute_fpn_proposals(
            rois, 2, 5, 4, 224)
        assert len(multi) == 4
        r = np.random.RandomState(7)
        base = np.abs(r.rand(6, 2)) * 100
        feed = {"rois": np.concatenate(
            [base, base + np.abs(r.rand(6, 2)) * 200], 1).astype(
                np.float32)}
        outs = _run([m.name for m in multi] + [restore], feed)
        assert all(o.shape == (6, 4) for o in outs[:4])
        assert sorted(outs[4].reshape(-1).tolist()) == list(range(6))

    def test_roi_perspective_transform_builds_runs(self):
        x = fluid.layers.data(name="x", shape=[2, 8, 8],
                              dtype="float32")
        rois = fluid.layers.data(name="rois", shape=[8],
                                 dtype="float32")
        out = fluid.layers.roi_perspective_transform(x, rois, 4, 4,
                                                     1.0)
        r = np.random.RandomState(8)
        quad = np.array([[1, 1, 6, 1, 6, 6, 1, 6]], np.float32)
        got, = _run([out], {"x": r.randn(1, 2, 8, 8).astype(
            np.float32), "rois": quad})
        assert got.shape == (1, 2, 4, 4)

    def test_multi_box_head_shapes(self):
        img = fluid.layers.data(name="img", shape=[3, 32, 32],
                                dtype="float32")
        f1 = fluid.layers.conv2d(img, 8, 3, padding=1, stride=2)
        f2 = fluid.layers.conv2d(f1, 8, 3, padding=1, stride=2)
        locs, confs, boxes, vars_ = fluid.layers.multi_box_head(
            [f1, f2], img, base_size=32, num_classes=4,
            aspect_ratios=[[2.0], [2.0]], min_ratio=20, max_ratio=90,
            offset=0.5, flip=True)
        assert boxes.shape[-1] == 4 and vars_.shape[-1] == 4
        r = np.random.RandomState(9)
        lo, co, bo = _run(
            [locs, confs, boxes],
            {"img": r.randn(2, 3, 32, 32).astype(np.float32)})
        assert lo.shape[0] == 2 and lo.shape[2] == 4
        assert co.shape[2] == 4
        assert bo.shape[0] == lo.shape[1]  # priors align with locs


class TestAppendLARS:
    def test_lars_local_lr_value(self):
        w = fluid.layers.create_parameter([4], "float32", name="w0",
                                          default_initializer=
                                          fluid.initializer.Constant(
                                              2.0))
        g = fluid.layers.fill_constant([4], "float32", 1.0)
        lrs = fluid.layers.append_LARS([(w, g)], learning_rate=0.1,
                                       weight_decay=0.25)
        got, = _run([lrs[0]])
        # ||w||=4, ||g||=2 -> 0.1 * 4 / (2 + 0.25*4) = 0.4/3
        np.testing.assert_allclose(np.asarray(got).reshape(()),
                                   0.4 / 3, rtol=1e-5)


def test_reference_layer_all_coverage():
    """Every name in the reference layers/* __all__ lists must exist
    on fluid.layers (the user-visible capability contract)."""
    import re

    missing = []
    for mod in ["nn", "tensor", "control_flow", "io", "detection",
                "metric_op", "learning_rate_scheduler", "ops"]:
        path = f"/root/reference/python/paddle/fluid/layers/{mod}.py"
        try:
            src = open(path).read()
        except OSError:
            continue
        m = re.search(r"__all__ = \[(.*?)\]", src, re.S)
        if not m:
            continue
        for name in re.findall(r"['\"]([A-Za-z0-9_]+)['\"]", m.group(1)):
            if not hasattr(fluid.layers, name):
                missing.append(f"{mod}.{name}")
    assert not missing, missing


def test_reference_module_all_coverage():
    """Every name in the reference fluid top-level module __all__ lists
    must resolve on the corresponding paddle_tpu namespace."""
    import re, os

    base = "/root/reference/python/paddle/fluid"
    targets = {
        "__init__": fluid, "framework": fluid, "executor": fluid,
        "optimizer": fluid.optimizer, "backward": fluid,
        "regularizer": fluid.regularizer,
        "initializer": fluid.initializer, "clip": fluid.clip,
        "metrics": fluid.metrics, "nets": fluid.nets,
        "profiler": fluid.profiler, "io": fluid.io,
        "data_feeder": fluid, "reader": fluid, "average": fluid,
        "evaluator": fluid.evaluator, "param_attr": fluid,
        "unique_name": fluid.unique_name, "lod_tensor": fluid,
        "parallel_executor": fluid, "compiler": fluid,
        "debugger": fluid, "transpiler/__init__": fluid.transpiler,
        "dygraph/__init__": fluid.dygraph,
        "dygraph/base": fluid.dygraph, "dygraph/nn": fluid.dygraph,
        "dygraph/layers": fluid.dygraph,
        "dygraph/checkpoint": fluid.dygraph,
    }
    missing = []
    for mod, target in targets.items():
        path = os.path.join(base, mod + ".py")
        if not os.path.exists(path):
            continue
        m = re.search(r"__all__\s*=\s*\[(.*?)\]", open(path).read(),
                      re.S)
        if not m:
            continue
        for name in re.findall(r"['\"]([A-Za-z0-9_]+)['\"]", m.group(1)):
            if not hasattr(target, name) and not hasattr(fluid, name):
                missing.append(f"{mod}.{name}")
    assert not missing, missing


@pytest.mark.skipif(
    not os.path.exists("/root/reference/python/paddle/fluid/__init__.py"),
    reason="reference checkout not present in this environment")
def test_reference_root_all_coverage():
    """The reference fluid/__init__ composes its __all__ from module
    lists (checked above) plus a literal tail — check the tail too."""
    import re

    src = open("/root/reference/python/paddle/fluid/__init__.py").read()
    m = re.search(r"__all__\s*=.*?\[(.*?)\]", src, re.S)
    names = re.findall(r"['\"]([A-Za-z0-9_]+)['\"]", m.group(1))
    missing = [n for n in names if not hasattr(fluid, n)]
    assert not missing, missing


def test_recordio_writer_roundtrip(tmp_path):
    import os

    def reader():
        for i in range(7):
            yield (np.full((2,), i, np.float32),
                   np.array([i], np.int64))

    path = os.path.join(str(tmp_path), "data.recordio")
    n = fluid.recordio_writer.convert_reader_to_recordio_file(
        path, reader)
    assert n == 7
    from paddle_tpu import native
    from paddle_tpu.recordio_writer import read_recordio_sample

    recs = [read_recordio_sample(r)
            for r in native.RecordIOScanner(path)]
    assert len(recs) == 7
    np.testing.assert_allclose(recs[3][0], [3, 3])
    assert int(recs[3][1][0]) == 3
    # sharded variant
    paths = fluid.recordio_writer.convert_reader_to_recordio_files(
        os.path.join(str(tmp_path), "shard"), 3, reader)
    assert len(paths) == 3  # 3+3+1


def test_reference_contrib_coverage():
    """Every reference contrib submodule + its main public classes
    resolve on paddle_tpu.contrib."""
    from paddle_tpu import contrib

    for mod in ["decoder", "memory_usage_calc", "op_frequence",
                "quantize", "int8_inference", "reader", "slim",
                "utils", "extend_optimizer"]:
        assert hasattr(contrib, mod), mod
    for name in ["BeamSearchDecoder", "TrainingDecoder", "StateCell",
                 "InitState", "QuantizeTranspiler", "Trainer",
                 "Inferencer", "summary",
                 "extend_with_decoupled_weight_decay",
                 "memory_usage", "op_freq_statistic"]:
        assert hasattr(contrib, name), name
    assert hasattr(contrib.utils, "HDFSClient")
    assert hasattr(contrib.reader, "ctr_reader")
    assert hasattr(contrib.int8_inference, "Calibrator")


def test_feed_shape_mismatch_raises_clearly():
    """A wrong-rank or wrong-dim feed must fail at Executor.run with a
    named ValueError, not a raw jax broadcast error mid-trace."""
    x = fluid.layers.data(name="x", shape=[4], dtype="float32")
    out = fluid.layers.fc(x, 2)
    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(fluid.default_startup_program())
    with pytest.raises(ValueError, match="feed 'x' has shape"):
        exe.run(feed={"x": np.zeros((3,), np.float32)},
                fetch_list=[out])  # rank 1 vs declared rank 2
    with pytest.raises(ValueError, match="feed 'x' has shape"):
        exe.run(feed={"x": np.zeros((3, 5), np.float32)},
                fetch_list=[out])  # wrong fixed dim
    got = exe.run(feed={"x": np.zeros((3, 4), np.float32)},
                  fetch_list=[out])  # -1 batch accepts any size
    assert np.asarray(got[0]).shape == (3, 2)
    # legacy (data, lod) tuple and LoDTensor feeds still pass through
    got = exe.run(feed={"x": (np.zeros((2, 4), np.float32),
                              [[0, 2]])}, fetch_list=[out])
    assert np.asarray(got[0]).shape == (2, 2)
    lt = fluid.LoDTensor(np.zeros((2, 4), np.float32), [[1, 1]])
    got = exe.run(feed={"x": lt}, fetch_list=[out])
    assert np.asarray(got[0]).shape == (2, 2)
