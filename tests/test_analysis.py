"""Program-verifier tests: one positive + one negative case per
checker (paddle_tpu/analysis). Reference counterpart of the validation
the C++ side does in op_desc.cc/operator.cc — here the failure classes
come from CLAUDE.md session learnings, so each test doubles as a
regression pin for a real incident."""
import re

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import analysis, layers
from paddle_tpu.analysis import (ERROR, INFO, WARNING, check_clone_uids,
                                 check_registry, check_shared_params,
                                 run_checks)


def _codes(diags, severity=None):
    return {d.code for d in diags
            if severity is None or d.severity == severity}


def _diags(program, code):
    return [d for d in run_checks(program) if d.code == code]


def _guarded():
    main, startup = fluid.Program(), fluid.Program()
    return main, startup, fluid.program_guard(main, startup)


# ---------------------------------------------------------------------------
# PTA001 uninitialized read
# ---------------------------------------------------------------------------
class TestUninitializedRead:
    def test_positive(self):
        main, startup, g = _guarded()
        with g:
            blk = main.global_block
            blk.append_op("scale", {"X": ["ghost"]}, {"Out": ["y"]},
                          {"scale": 2.0})
        ds = _diags(main, "PTA001")
        assert ds and ds[0].severity == WARNING
        assert ds[0].var == "ghost"

    def test_negative_data_and_order(self):
        main, startup, g = _guarded()
        with g:
            x = layers.data("x", shape=[4], dtype="float32")
            h = layers.scale(x, 2.0)
            layers.scale(h, 0.5)
        assert not _diags(main, "PTA001")


# ---------------------------------------------------------------------------
# PTA002 multi-writer
# ---------------------------------------------------------------------------
class TestMultiWriter:
    def test_positive(self):
        main, startup, g = _guarded()
        with g:
            x = layers.data("x", shape=[4], dtype="float32")
            blk = main.global_block
            blk.append_op("scale", {"X": x}, {"Out": ["t"]},
                          {"scale": 2.0})
            blk.append_op("scale", {"X": x}, {"Out": ["t"]},
                          {"scale": 3.0})
        ds = _diags(main, "PTA002")
        assert ds and ds[0].severity == INFO and ds[0].var == "t"

    def test_negative_persistable_update(self):
        main, startup, g = _guarded()
        with g:
            x = layers.data("x", shape=[4], dtype="float32")
            acc = main.global_block.create_var(
                name="acc", shape=(4,), dtype="float32",
                persistable=True)
            blk = main.global_block
            blk.append_op("elementwise_add", {"X": acc, "Y": x},
                          {"Out": acc}, {})
            blk.append_op("elementwise_add", {"X": acc, "Y": x},
                          {"Out": acc}, {})
        assert not _diags(main, "PTA002")


# ---------------------------------------------------------------------------
# PTA003 dead op
# ---------------------------------------------------------------------------
class TestDeadOp:
    def test_positive(self):
        main, startup, g = _guarded()
        with g:
            x = layers.data("x", shape=[4], dtype="float32")
            layers.scale(x, 2.0)  # result never consumed
        ds = _diags(main, "PTA003")
        assert ds and ds[0].severity == INFO

    def test_negative_consumed(self):
        main, startup, g = _guarded()
        with g:
            x = layers.data("x", shape=[4], dtype="float32")
            h = layers.scale(x, 2.0)
            out = main.global_block.create_var(
                name="out", shape=(4,), dtype="float32",
                persistable=True)
            main.global_block.append_op("assign", {"X": h},
                                        {"Out": out}, {})
        assert not _diags(main, "PTA003")


# ---------------------------------------------------------------------------
# PTA004 go-capture hazards (the _launch_go_ops bug class, static)
# ---------------------------------------------------------------------------
class TestGoCapture:
    def test_positive_late_writer(self):
        main, startup, g = _guarded()
        with g:
            x = layers.data("x", shape=[4], dtype="float32")
            sub = main.create_block()
            sub.append_op("scale", {"X": ["late"]}, {"Out": ["s"]},
                          {"scale": 1.0})
            main.rollback()
            blk = main.global_block
            blk.append_op("go", {"X": ["late"]}, {},
                          {"sub_block": sub})
            blk.append_op("scale", {"X": x}, {"Out": ["late"]},
                          {"scale": 1.0})
        ds = _diags(main, "PTA004")
        assert ds and ds[0].severity == ERROR
        assert "AFTER the go op" in ds[0].message

    def test_negative_clean_capture(self):
        main, startup, g = _guarded()
        with g:
            x = layers.data("x", shape=[4], dtype="float32")
            h = layers.scale(x, 2.0)
            with layers.Go():
                layers.scale(h, 1.0)
        assert not _diags(main, "PTA004")


# ---------------------------------------------------------------------------
# PTA010 collective inside divergent control flow (the r5 deadlock)
# ---------------------------------------------------------------------------
def _collective_in_cond_program():
    """Crafted pp-style program: a per-stage predicate gating a branch
    that contains an allreduce — the shape of program that is KNOWN to
    deadlock on a real mesh (CLAUDE.md round-5 learnings: no
    collective may live in a divergent lax.cond branch)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        from paddle_tpu.layers.collective import _allreduce

        x = layers.data("x", shape=[4], dtype="float32")
        stage = layers.fill_constant([1], "float32", 0.0)
        pred = layers.less_than_value(stage, 1.0)
        layers.cond(pred,
                    lambda: _allreduce(layers.scale(x, 2.0)),
                    lambda: layers.scale(x, 1.0))
    return main


class TestCollectiveInBranch:
    """Since the PTA010<->PTA130 twin dedupe, the legacy pattern
    matcher DEFERS to the prover at every site the fixpoint engine
    covers (which is every reachable site of a convergent program):
    the incident surfaces exactly once, as the proof-carrying PTA130
    error. PTA010 remains the fallback for programs the prover cannot
    analyze (non-convergence) — the gate test pins the superset
    relation over the whole zoo."""

    def test_positive_cond_allreduce_dedupes_to_prover(self):
        main = _collective_in_cond_program()
        assert not _diags(main, "PTA010")  # deferred to the prover
        ds = _diags(main, "PTA130")
        assert ds and ds[0].severity == ERROR
        assert ds[0].op_type == "allreduce"

    def test_positive_axis_name_in_while(self):
        main, startup, g = _guarded()
        with g:
            sub = main.create_block()
            sub.append_op("sync_batch_norm", {"X": ["h"]},
                          {"Y": ["h2"]}, {"axis_name": "dp"})
            main.rollback()
            main.global_block.append_op(
                "while", {"Condition": ["c"], "X": [], "Init": []},
                {"Out": []},
                {"sub_block": sub, "carried": [], "externals": []})
        assert not _diags(main, "PTA010")
        ds = _diags(main, "PTA130")
        assert ds and ds[0].severity == ERROR

    def test_legacy_matcher_fires_when_prover_unavailable(self,
                                                          monkeypatch):
        # the non-convergence fallback: when absint cannot analyze
        # the program, the pattern matcher still catches the deadlock
        from paddle_tpu.analysis import absint as ai

        def boom(program):
            raise RuntimeError("crafted prover outage")

        monkeypatch.setattr(ai, "analyze", boom)
        main = _collective_in_cond_program()
        ds = [d for d in analysis.run_checks(main, only=["PTA010"])
              if d.code == "PTA010"]
        assert ds and ds[0].severity == ERROR
        assert "allreduce" in ds[0].message

    def test_negative_top_level_allreduce(self):
        main, startup, g = _guarded()
        with g:
            from paddle_tpu.layers.collective import _allreduce

            x = layers.data("x", shape=[4], dtype="float32")
            _allreduce(layers.scale(x, 2.0))
        assert not _diags(main, "PTA010")


# ---------------------------------------------------------------------------
# PTA011 scope-dependent collectives in branches (r6 generalized trap)
# ---------------------------------------------------------------------------
class TestScopeCollectiveInBranch:
    def test_positive_attention_in_while_dedupes_to_prover(self):
        # the twin dedupe: the prover covers the site, so the legacy
        # matcher stays silent and PTA130 carries the (one) warning
        main, startup, g = _guarded()
        with g:
            sub = main.create_block()
            sub.append_op("attention", {"Q": ["q"]}, {"Out": ["o"]}, {})
            main.rollback()
            main.global_block.append_op(
                "while", {"Condition": ["c"], "X": [], "Init": []},
                {"Out": []},
                {"sub_block": sub, "carried": [], "externals": []})
        assert not _diags(main, "PTA011")
        ds = _diags(main, "PTA130")
        assert ds and ds[0].severity == WARNING
        assert "attention" in ds[0].message

    def test_negative_attention_top_level(self):
        main, startup, g = _guarded()
        with g:
            main.global_block.append_op("attention", {"Q": ["q"]},
                                        {"Out": ["o"]}, {})
        assert not _diags(main, "PTA011")


# ---------------------------------------------------------------------------
# PTA020 while-carry dtype promotion (increment int->float trap)
# ---------------------------------------------------------------------------
def _while_counter_program(step):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        i = layers.fill_constant([1], "int64", 0)
        limit = layers.fill_constant([1], "int64", 10)
        cond = layers.less_than(i, limit)
        w = layers.While(cond)
        with w.block():
            blk = main.current_block()
            blk.append_op("increment", {"X": i.name}, {"Out": i.name},
                          {"step": step})
            layers.less_than(i, limit, cond=cond)
    return main


class TestWhileCarryDtype:
    def test_positive_float_step_in_while(self):
        ds = _diags(_while_counter_program(1.0), "PTA020")
        assert ds and ds[0].severity == ERROR
        assert "while" in ds[0].message.lower() or \
            "carry" in ds[0].message

    def test_negative_int_step(self):
        assert not _diags(_while_counter_program(1), "PTA020")

    def test_layer_coerces_integral_float_step(self):
        # the satellite fix: layers.increment(int_var, 1.0) must not
        # emit a float step for integer counters
        main, startup, g = _guarded()
        with g:
            i = layers.fill_constant([1], "int64", 0)
            layers.increment(i, 1.0)
        ops = [op for op in main.global_block.ops
               if op.type == "increment"]
        assert ops and isinstance(ops[0].attrs["step"], int)
        assert not _diags(main, "PTA020")

    def test_warning_outside_while(self):
        main, startup, g = _guarded()
        with g:
            i = layers.fill_constant([1], "int64", 0)
            main.global_block.append_op(
                "increment", {"X": i.name}, {"Out": i.name},
                {"step": 1.0})
        ds = _diags(main, "PTA020")
        assert ds and ds[0].severity == WARNING


# ---------------------------------------------------------------------------
# PTA030 / PTA031 sampling-op uid preservation
# ---------------------------------------------------------------------------
def _dropout_program():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[8], dtype="float32")
        h = layers.dropout(x, dropout_prob=0.5)
        layers.mean(layers.dropout(h, dropout_prob=0.5))
    return main


class TestSamplingUids:
    def test_positive_uid_collision(self):
        main = _dropout_program()
        drops = [op for op in main.global_block.ops
                 if op.type == "dropout"]
        assert len(drops) == 2
        drops[1]._uid = drops[0]._uid
        ds = _diags(main, "PTA030")
        assert ds and ds[0].severity == ERROR

    def test_negative_distinct_uids(self):
        assert not _diags(_dropout_program(), "PTA030")

    def test_negative_recompute_clone_share_is_legal(self):
        # a backward-role clone sharing its forward op's uid is the
        # INTENDED recompute contract, not a collision
        main = _dropout_program()
        blk = main.global_block
        fwd = [op for op in blk.ops if op.type == "dropout"][0]
        clone = blk.append_op(
            "dropout", dict(fwd.inputs),
            {"Out": [n + "@RECOMP0_0" for n in fwd.outputs["Out"]]},
            dict(fwd.attrs, op_role="backward"))
        clone._uid = fwd._uid
        assert not _diags(main, "PTA030")

    def test_clone_preserves_uids(self):
        main = _dropout_program()
        assert check_clone_uids(main, main.clone()) == []
        assert check_clone_uids(main, main.clone(for_test=True)) == []

    def test_clone_uid_mutation_detected(self):
        main = _dropout_program()
        cloned = main.clone()
        for op in cloned.global_block.ops:
            if op.type == "dropout":
                op._uid += 991
        ds = check_clone_uids(main, cloned)
        assert ds and all(d.code == "PTA031" and d.severity == ERROR
                          for d in ds)


# ---------------------------------------------------------------------------
# PTA040 recompute clones rooted in optimization_barrier
# ---------------------------------------------------------------------------
class TestRecomputeBarriers:
    def test_positive_unbarriered_clone(self):
        main, startup, g = _guarded()
        with g:
            x = layers.data("x", shape=[4], dtype="float32")
            h = layers.scale(x, 2.0)
            main.global_block.append_op(
                "scale", {"X": [h.name]},
                {"Out": [h.name + "@RECOMP0_0"]}, {"scale": 2.0})
        ds = _diags(main, "PTA040")
        assert ds and ds[0].severity == ERROR
        assert "CSE" in ds[0].message

    def test_negative_real_recompute(self):
        # backward.py's own checkpointing must satisfy its checker
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = layers.data("x", shape=[8], dtype="float32")
            h1 = layers.fc(x, 8, act="relu")
            h2 = layers.fc(h1, 8, act="relu")
            loss = layers.mean(layers.fc(h2, 1))
            from paddle_tpu.backward import append_backward

            append_backward(loss, checkpoints=[h1])
        has_recomp = any("@RECOMP" in n for op in main.global_block.ops
                         for n in op.output_arg_names)
        assert has_recomp  # the plan actually emitted clones
        assert not _diags(main, "PTA040")


# ---------------------------------------------------------------------------
# PTA050 / PTA051 parameter naming across builds
# ---------------------------------------------------------------------------
class TestParamNaming:
    def test_positive_auto_names(self):
        main, startup, g = _guarded()
        with g:
            x = layers.data("x", shape=[4], dtype="float32")
            layers.fc(x, 4)
        ds = _diags(main, "PTA050")
        assert ds and ds[0].severity == INFO

    def test_negative_explicit_names(self):
        from paddle_tpu.param_attr import ParamAttr

        main, startup, g = _guarded()
        with g:
            x = layers.data("x", shape=[4], dtype="float32")
            layers.fc(x, 4, param_attr=ParamAttr(name="proj_w"),
                      bias_attr=ParamAttr(name="proj_b"))
        assert not _diags(main, "PTA050")

    def _prog_with_param(self, name, shape):
        p = fluid.Program()
        p.global_block.create_parameter(name=name, shape=shape,
                                        dtype="float32")
        return p

    def test_pair_shape_mismatch_is_error(self):
        a = self._prog_with_param("fc_0.w_0", [4, 4])
        b = self._prog_with_param("fc_0.w_0", [8, 4])
        ds = check_shared_params(a, b)
        assert ds and ds[0].code == "PTA051" \
            and ds[0].severity == ERROR

    def test_pair_auto_name_share_is_warning(self):
        a = self._prog_with_param("fc_0.w_0", [4, 4])
        b = self._prog_with_param("fc_0.w_0", [4, 4])
        ds = check_shared_params(a, b)
        assert ds and ds[0].severity == WARNING

    def test_pair_explicit_share_is_clean(self):
        a = self._prog_with_param("enc0_q.w", [4, 4])
        b = self._prog_with_param("enc0_q.w", [4, 4])
        assert check_shared_params(a, b) == []


# ---------------------------------------------------------------------------
# PTA100 cross-model param collision (co-resident serving runtime)
# ---------------------------------------------------------------------------
class TestCrossModelCollision:
    def _prog_with_param(self, name, shape):
        p = fluid.Program()
        p.global_block.create_parameter(name=name, shape=shape,
                                        dtype="float32")
        return p

    def test_shape_mismatch_is_error(self):
        from paddle_tpu.analysis import check_cross_model_collision

        a = self._prog_with_param("proj.w", [4, 4])
        b = self._prog_with_param("proj.w", [8, 4])
        ds = check_cross_model_collision(a, b)
        assert ds and ds[0].code == "PTA100" \
            and ds[0].severity == ERROR

    def test_same_shape_alias_is_error_unlike_pta051(self):
        """The intent inversion vs PTA051: for UNRELATED co-resident
        models, an explicit shared name at the same shape is silent
        weight aliasing — the WORSE defect (wrong answers, no error
        anywhere), so it is ERROR severity like the loud shape
        mismatch; check_shared_params stays silent on the same
        pair."""
        from paddle_tpu.analysis import check_cross_model_collision

        a = self._prog_with_param("proj.w", [4, 4])
        b = self._prog_with_param("proj.w", [4, 4])
        ds = check_cross_model_collision(a, b)
        assert ds and ds[0].code == "PTA100" \
            and ds[0].severity == ERROR
        assert check_shared_params(a, b) == []  # the PTA051 contrast

    def test_prefixed_models_are_clean(self):
        from paddle_tpu.analysis import check_cross_model_collision

        a = self._prog_with_param("m1_proj.w", [4, 4])
        b = self._prog_with_param("m2_proj.w", [4, 4])
        assert check_cross_model_collision(a, b) == []

    def test_non_parameter_persistable_collision_is_error(self):
        """batch_norm-style running statistics are persistables
        created OUTSIDE ``_parameters`` (create_global_variable), and
        two models saved from fresh processes both carry the same
        auto names — a parameters-only intersection would stay
        silent on exactly that aliasing."""
        from paddle_tpu.analysis import check_cross_model_collision

        def prog():
            p = fluid.Program()
            p.global_block.create_var(
                name="batch_norm_0.w_1", shape=[16],
                dtype="float32", persistable=True)
            return p

        a, b = prog(), prog()
        assert not (set(a._parameters) & set(b._parameters))
        ds = check_cross_model_collision(a, b)
        assert ds and ds[0].code == "PTA100" \
            and ds[0].severity == ERROR

    def test_runtime_zoo_is_collision_free(self):
        """The shipped runtime zoo (distinct per-model prefixes) must
        be pairwise clean — the property the analysis target pins."""
        from paddle_tpu.analysis import check_cross_model_collision
        from paddle_tpu.inference.runtime import zoo

        progs = []
        for prefix, i, h, c in zoo.DEFAULT_ZOO:
            main, _startup, _f, _o = zoo.build_fc_program(
                prefix, i, h, c)
            progs.append(main)
        for i, a in enumerate(progs):
            for b in progs[i + 1:]:
                assert check_cross_model_collision(a, b) == []


# ---------------------------------------------------------------------------
# PTA060 @SEQ_LEN companion batch consistency
# ---------------------------------------------------------------------------
class TestSeqLenCompanion:
    def _prog(self, companion_shape):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = layers.data("x", shape=[2, 8], dtype="int64",
                            append_batch_size=False)
            main.global_block.create_var(
                name="x@SEQ_LEN", shape=companion_shape, dtype="int32",
                is_data=True, stop_gradient=True)
            layers.scale(layers.cast(x, "float32"), 1.0)
        return main

    def test_positive_dynamic_companion_static_batch(self):
        ds = _diags(self._prog((-1,)), "PTA060")
        assert ds and ds[0].severity == ERROR

    def test_negative_matching_batch(self):
        assert not _diags(self._prog((2,)), "PTA060")

    def test_positive_read_but_undeclared_companion(self):
        main, startup, g = _guarded()
        with g:
            main.global_block.append_op(
                "cast", {"X": ["w@SEQ_LEN"]}, {"Out": ["lens_f"]},
                {"out_dtype": "float32"})
        ds = _diags(main, "PTA060")
        assert ds and ds[0].severity == WARNING
        assert "declares" in ds[0].message


# ---------------------------------------------------------------------------
# PTA070 host_effect completeness + registration-time assert
# ---------------------------------------------------------------------------
class TestHostEffectFlag:
    def test_kernel_bridges_host_detection(self):
        from paddle_tpu.core.registry import kernel_bridges_host

        def bridging(ctx):
            def inner(v):
                return io_callback(None, None, v)  # noqa: F821

            return inner

        def plain(ctx):
            return ctx.input("X")

        assert kernel_bridges_host(bridging)
        assert not kernel_bridges_host(plain)

    def test_kernel_bridges_host_follows_module_helpers(self):
        # a kernel factoring its callback into a same-module helper
        # must still trip the assert (review finding: co_names of the
        # kernel alone only sees the helper's name)
        import types

        mod = types.ModuleType("_pta070_helper_mod")
        src = ("def _helper(v):\n"
               "    return io_callback(None, None, v)\n"
               "def kernel(ctx):\n"
               "    return _helper(ctx)\n"
               "def clean_kernel(ctx):\n"
               "    return str(ctx)\n")
        exec(compile(src, "<pta070>", "exec"), mod.__dict__)
        from paddle_tpu.core.registry import kernel_bridges_host

        assert kernel_bridges_host(mod.kernel)
        assert not kernel_bridges_host(mod.clean_kernel)

    def test_register_op_asserts_flag(self):
        from paddle_tpu.core.registry import (_REGISTRY, is_registered,
                                              register_op)

        with pytest.raises(RuntimeError, match="host_effect"):
            @register_op("_pta070_bad_op")
            def bad(ctx):
                return io_callback(None, None)  # noqa: F821

        assert not is_registered("_pta070_bad_op")

        @register_op("_pta070_good_op", host_effect=True)
        def good(ctx):
            return io_callback(None, None)  # noqa: F821

        try:
            assert is_registered("_pta070_good_op")
        finally:
            del _REGISTRY["_pta070_good_op"]

    def test_positive_registry_sweep(self):
        from paddle_tpu.core.registry import OpInfo, _REGISTRY

        def sneaky(ctx):
            return io_callback(None, None)  # noqa: F821

        _REGISTRY["_pta070_sneaky"] = OpInfo("_pta070_sneaky", sneaky)
        try:
            ds = check_registry(["_pta070_sneaky"])
            assert ds and ds[0].code == "PTA070" \
                and ds[0].severity == ERROR
            # program-level checker finds it through the used-op sweep
            main = fluid.Program()
            main.global_block.append_op("_pta070_sneaky", {}, {}, {})
            assert "PTA070" in _codes(run_checks(main))
        finally:
            del _REGISTRY["_pta070_sneaky"]

    def test_negative_shipped_registry_clean(self):
        assert check_registry() == []


# ---------------------------------------------------------------------------
# PTA080 unregistered op
# ---------------------------------------------------------------------------
class TestWriteOnlyCarry:
    """PTA090: write-only persistables must be carry-declarable (the
    r6 run_steps scan-carry trap: they join the lax.scan carry seeded
    with zeros of the DECLARED shape/dtype)."""

    def _write_only(self, data_shape, append_batch):
        """Write-only persistable sink fed by a scale of `x`; shape
        inference propagates x's shape onto the sink (batch -1 when
        append_batch, concrete otherwise)."""
        main, startup, g = _guarded()
        with g:
            x = layers.data("x", shape=list(data_shape),
                            dtype="float32",
                            append_batch_size=append_batch)
            sink = main.global_block.create_var(
                name="@stats_sink", shape=None, dtype="float32",
                persistable=True, stop_gradient=True)
            layers.assign(layers.scale(x, 2.0), output=sink)
        return main

    def test_positive_batch_dim_shape(self):
        ds = _diags(self._write_only((4,), True), "PTA090")
        assert ds and ds[0].severity == ERROR
        assert ds[0].var == "@stats_sink"
        assert "carry-declarable" in ds[0].message

    def test_positive_missing_dtype(self):
        main, startup, g = _guarded()
        with g:
            x = layers.data("x", shape=[4], dtype="float32")
            sink = main.global_block.create_var(
                name="@stats_sink", shape=(8, 4), persistable=True,
                stop_gradient=True)
            layers.assign(layers.scale(x, 2.0), output=sink)
        ds = _diags(main, "PTA090")
        assert ds and ds[0].severity == ERROR

    def test_negative_concrete_shape(self):
        # concrete (static-batch) declaration: the zeros carry slot
        # is well-defined
        assert not _diags(self._write_only((8, 4), False), "PTA090")

    def test_negative_read_modify_write(self):
        # read-AND-written persistables ride state_in; declaration
        # shape is irrelevant (ordinary params/counters)
        main, startup, g = _guarded()
        with g:
            acc = main.global_block.create_var(
                name="@acc", shape=(-1, 4), dtype="float32",
                persistable=True, stop_gradient=True)
            x = layers.data("x", shape=[4], dtype="float32")
            layers.assign(layers.elementwise_add(acc, x), output=acc)
        assert not _diags(main, "PTA090")

    def test_negative_read_inside_sub_block(self):
        # a read from inside a While body surfaces as the while op's
        # input slots — not write-only
        main, startup, g = _guarded()
        with g:
            state = main.global_block.create_var(
                name="@loop_state", shape=(-1, 4), dtype="float32",
                persistable=True, stop_gradient=True)
            x = layers.data("x", shape=[4], dtype="float32")
            layers.assign(x, output=state)
            i = layers.fill_constant([1], "float32", 0.0)
            limit = layers.fill_constant([1], "float32", 2.0)
            cond = layers.less_than(i, limit)
            w = layers.While(cond)
            with w.block():
                layers.assign(layers.scale(state, 2.0), output=state)
                layers.increment(i, 1.0)
                layers.less_than(i, limit, cond=cond)
        assert not _diags(main, "PTA090")

    def test_slot_pool_step_program_is_clean(self):
        # the continuous-batching bundle is the canonical all-state
        # step program: every slot var is read+written and declared
        # concrete — PTA090-clean by construction
        from paddle_tpu.models import transformer as T

        from paddle_tpu import unique_name

        with unique_name.guard():
            bundle = T.build_decode_step_program(
                seq_len=4, max_out_len=6, d_model=16, n_heads=2,
                n_layers=1, d_inner=32, vocab=16, n_slots=2)
        assert not _diags(bundle.step, "PTA090")
        assert not _diags(bundle.prefill, "PTA090")


class TestUnregisteredOp:
    def test_positive(self):
        main = fluid.Program()
        main.global_block.append_op("definitely_not_an_op", {}, {}, {})
        ds = _diags(main, "PTA080")
        assert ds and ds[0].severity == ERROR

    def test_negative_feed_fetch_plumbing(self):
        main = fluid.Program()
        main.global_block.append_op("feed", {}, {"Out": ["x"]}, {})
        main.global_block.append_op("fetch", {"X": ["x"]}, {}, {})
        assert not _diags(main, "PTA080")


# ---------------------------------------------------------------------------
# Executor gate: FLAGS_static_check={off,warn,strict}
# ---------------------------------------------------------------------------
class TestExecutorGate:
    def _int_promotion_program(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = layers.data("x", shape=[1], dtype="int64",
                            append_batch_size=False)
            y = main.global_block.create_var(name="y", shape=(1,),
                                             dtype="int64")
            main.global_block.append_op("increment", {"X": x},
                                        {"Out": y}, {"step": 1.0})
        return main

    def test_strict_raises_enforce(self):
        from paddle_tpu.enforce import EnforceNotMet

        main = _collective_in_cond_program()
        exe = fluid.Executor(fluid.CPUPlace())
        fluid.set_flags({"FLAGS_static_check": "strict"})
        try:
            with pytest.raises(EnforceNotMet, match="PTA130"):
                exe.run(main,
                        feed={"x": np.zeros((1, 4), np.float32)},
                        fetch_list=[])
        finally:
            fluid.set_flags({"FLAGS_static_check": "off"})

    def test_warn_mode_warns_and_runs(self):
        main = self._int_promotion_program()
        exe = fluid.Executor(fluid.CPUPlace())
        fluid.set_flags({"FLAGS_static_check": "warn"})
        try:
            with pytest.warns(UserWarning, match="PTA020"):
                out = exe.run(main,
                              feed={"x": np.zeros((1,), np.int64)},
                              fetch_list=["y"])
        finally:
            fluid.set_flags({"FLAGS_static_check": "off"})
        assert out[0].shape == (1,)

    def test_off_mode_is_silent(self):
        import warnings as W

        main = self._int_promotion_program()
        exe = fluid.Executor(fluid.CPUPlace())
        with W.catch_warnings(record=True) as caught:
            W.simplefilter("always")
            out = exe.run(main, feed={"x": np.zeros((1,), np.int64)},
                          fetch_list=["y"])
        assert not [w for w in caught
                    if "static_check" in str(w.message)]
        assert out[0].shape == (1,)

    def test_strict_passes_clean_program(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = layers.data("x", shape=[4], dtype="float32")
            y = layers.scale(x, 2.0)
        exe = fluid.Executor(fluid.CPUPlace())
        fluid.set_flags({"FLAGS_static_check": "strict"})
        try:
            out = exe.run(main,
                          feed={"x": np.ones((2, 4), np.float32)},
                          fetch_list=[y])
        finally:
            fluid.set_flags({"FLAGS_static_check": "off"})
        np.testing.assert_allclose(out[0], 2.0 * np.ones((2, 4)))

    def test_flag_rejects_bogus_mode(self):
        with pytest.raises(ValueError):
            fluid.set_flags({"FLAGS_static_check": "bogus"})


# ---------------------------------------------------------------------------
# suite plumbing
# ---------------------------------------------------------------------------
class TestSuitePlumbing:
    def test_eight_plus_checkers_with_stable_codes(self):
        codes = [c.code for c in analysis.registered_checkers()]
        assert len(codes) >= 8
        assert codes == sorted(codes)
        # PTA0xx ran out at PTA100/PTA110: the stable prefix is PTA
        assert all(re.fullmatch(r"PTA\d{3}", c) for c in codes)

    def test_diagnostics_sorted_error_first(self):
        main = _collective_in_cond_program()
        main.global_block.append_op("definitely_not_an_op", {}, {}, {})
        ds = run_checks(main)
        sevs = [d.severity for d in ds]
        order = {ERROR: 0, WARNING: 1, INFO: 2}
        assert sevs == sorted(sevs, key=order.get)

    def test_only_filter(self):
        main = _collective_in_cond_program()
        ds = run_checks(main, only=["PTA130"])
        assert ds and _codes(ds) == {"PTA130"}

    def test_checker_timings_collected(self):
        main = _collective_in_cond_program()
        timings = {}
        run_checks(main, collect_timings=timings)
        assert "PTA130" in timings and "PTA001" in timings
        assert all(v >= 0.0 for v in timings.values())

    def test_dataflow_facts(self):
        main, startup, g = _guarded()
        with g:
            x = layers.data("x", shape=[4], dtype="float32")
            h = layers.scale(x, 2.0)
            layers.scale(h, 0.5)
        df = analysis.analyze_block(main.global_block)
        assert df.first_write[h.name] == 0
        assert df.readers[h.name] == [1]


# ---------------------------------------------------------------------------
# PTA110 shared-pool write exclusivity (paged KV block pools)
# ---------------------------------------------------------------------------
class TestSharedPoolWrites:
    """PTA110: writes into @POOL-marked shared block pools must go
    through masked_pool_write with the lane-exclusivity contract —
    anything else is the silent cross-request KV corruption class
    (models/decode_engine.py paged layout).

    Since the ownership prover landed (PTA190/191/192), sites the
    converged fixpoint covers surface as PTA191 proof-carrying
    diagnostics and PTA110 stays silent there (twin-dedupe, the
    PTA010/PTA130 pattern) — these tests pin BOTH halves: the
    defect classes still fire (as PTA191) and PTA110 still exists
    as the non-convergence fallback (tests/test_ownership.py pins
    the fallback path itself)."""

    def _pool_prog(self, mark_idx=None, mark_gate=True):
        from paddle_tpu.analysis import absint

        main, startup, g = _guarded()
        with g:
            pool = main.global_block.create_var(
                name="@p/self_k0@POOL", shape=(4, 2, 2, 8),
                dtype="float32", persistable=True,
                stop_gradient=True)
            new = layers.data("new", shape=[3, 2, 8],
                              dtype="float32",
                              append_batch_size=False)
            idx = layers.data("idx", shape=[3], dtype="int32",
                              append_batch_size=False)
            gate = layers.data("gate", shape=[3], dtype="float32",
                               append_batch_size=False)
            if mark_idx:
                absint.mark_pool_index_source(idx, mark_idx, bound=8)
            if mark_gate:
                absint.mark_pool_index_source(gate, "lane_active")
        # program_guard CMs are single-use: hand back a fresh one
        return main, pool, new, idx, gate, fluid.program_guard(main)

    def _pool_diags(self, program):
        return [d for d in run_checks(program)
                if d.code in ("PTA110", "PTA190", "PTA191",
                              "PTA192")]

    def test_raw_assign_write_is_error(self):
        main, pool, new, idx, gate, g = self._pool_prog()
        with g:
            zeros = layers.fill_constant([4, 2, 2, 8], "float32", 0.0)
            layers.assign(zeros, output=pool)
        ds = _diags(main, "PTA191")
        assert ds and ds[0].severity == ERROR
        assert "@POOL" in ds[0].var
        assert not _diags(main, "PTA110")  # twin-dedupe

    def test_missing_exclusive_via_is_error(self):
        main, pool, new, idx, gate, g = self._pool_prog(
            mark_idx="block_table")
        with g:
            # bypass the layer wrapper (which refuses at build time)
            # to pin the checker's own sweep
            main.global_block.append_op(
                "masked_pool_write",
                {"Pool": [pool.name], "New": [new.name],
                 "Index": [idx.name], "Gate": [gate.name]},
                {"Out": [pool.name]}, {"leading_dims": 2})
        ds = _diags(main, "PTA191")
        assert ds and ds[0].severity == ERROR
        assert "exclusive_via" in ds[0].message

    def test_ungated_block_table_write_is_error(self):
        main, pool, new, idx, gate, g = self._pool_prog(
            mark_idx="block_table")
        with g:
            main.global_block.append_op(
                "masked_pool_write",
                {"Pool": [pool.name], "New": [new.name],
                 "Index": [idx.name]},
                {"Out": [pool.name]},
                {"leading_dims": 2, "exclusive_via": "block_table"})
        ds = _diags(main, "PTA191")
        assert ds and ds[0].severity == ERROR
        assert "Gate" in ds[0].message

    def test_blessed_write_is_clean(self):
        main, pool, new, idx, gate, g = self._pool_prog(
            mark_idx="block_table")
        with g:
            layers.masked_pool_write(pool, new, idx, gate=gate,
                                     leading_dims=2,
                                     exclusive_via="block_table")
        assert not self._pool_diags(main)

    def test_layer_wrapper_refuses_bad_contracts(self):
        main, pool, new, idx, gate, g = self._pool_prog()
        with g:
            with pytest.raises(ValueError, match="exclusive_via"):
                layers.masked_pool_write(pool, new, idx, gate=gate)
            with pytest.raises(ValueError, match="gate"):
                layers.masked_pool_write(
                    pool, new, idx, exclusive_via="block_table")

    def test_paged_bundle_programs_are_clean(self):
        """The shipped paged decode programs pass the WHOLE pool
        sweep — declaration checker AND ownership provers (also
        pinned by the strict lint zoo, analysis/targets.py)."""
        from paddle_tpu.models import transformer as T
        from paddle_tpu.models.decode_engine import CacheConfig

        bundle = T.build_decode_step_program(
            seq_len=8, max_out_len=8, d_model=32, n_heads=2,
            n_layers=1, d_inner=64, vocab=50, n_slots=2,
            state_prefix="@pta110/",
            cache=CacheConfig(layout="paged", block_size=4,
                              n_blocks=4, n_prompt_entries=2))
        for key in (0, ("miss", 2), ("hit", 2)):
            assert not self._pool_diags(bundle.serves[key]), key
        assert not self._pool_diags(bundle.step)
        assert not self._pool_diags(bundle.prefill)


class TestPTA120SpecAdvanceBounded:
    """spec_accept shape/attr agreement: the counter-advance <= k+1
    clamp and the accepted-prefix room clip are only provable when
    the declared k/max_len match the wired tensors (r14)."""

    def _spec_prog(self, k_attr=2, props_w=2, tprobs_w=3, buf_w=16,
                   max_len=16):
        main, startup, g = _guarded()
        with g:
            props = layers.data("props", shape=[4, props_w],
                                dtype="int64",
                                append_batch_size=False)
            dprobs = layers.data("dprobs", shape=[4, props_w, 8],
                                 dtype="float32",
                                 append_batch_size=False)
            tprobs = layers.data("tprobs", shape=[4, tprobs_w, 8],
                                 dtype="float32",
                                 append_batch_size=False)
            seed = layers.data("seed", shape=[4], dtype="int64",
                               append_batch_size=False)
            pos = layers.data("pos", shape=[4], dtype="int64",
                              append_batch_size=False)
            adv, toks, acc, fin = layers.spec_accept(
                props, dprobs, tprobs, seed, pos, k=k_attr,
                end_id=1, max_len=max_len, greedy=True)
            buf = main.global_block.create_var(
                name="@pta120/tok_buf", shape=(4, buf_w),
                dtype="int64", persistable=True,
                stop_gradient=True)
            layers.span_scatter(buf, toks, pos, adv)
        return main

    def test_negative_consistent_wiring_is_clean(self):
        assert not _diags(self._spec_prog(), "PTA120")

    def test_positive_k_attr_disagrees_with_proposals(self):
        ds = _diags(self._spec_prog(k_attr=3, props_w=2,
                                    tprobs_w=4), "PTA120")
        assert ds and all(d.severity == ERROR for d in ds)
        assert any("k=3" in d.message for d in ds)

    def test_positive_target_probs_width_mismatch(self):
        ds = _diags(self._spec_prog(tprobs_w=2), "PTA120")
        assert ds and ds[0].severity == ERROR

    def test_positive_scatter_buffer_width_vs_max_len(self):
        ds = _diags(self._spec_prog(buf_w=8, max_len=16), "PTA120")
        assert ds and ds[0].severity == ERROR
        assert "max_len=16" in ds[0].message

    def test_shipped_spec_bundle_is_clean(self):
        """The real draft-and-verify programs pass the sweep (also
        pinned by the strict lint zoo)."""
        from paddle_tpu.models import transformer as T
        from paddle_tpu.models.decode_engine import DraftConfig

        bundle = T.build_decode_step_program(
            seq_len=8, max_out_len=8, d_model=32, n_heads=2,
            n_layers=1, d_inner=64, vocab=50, n_slots=2,
            state_prefix="@pta120b/",
            draft=DraftConfig(d_model=16, n_heads=2, n_layers=1,
                              d_inner=32, k=2))
        for key in (0, 2):
            assert not _diags(bundle.serves[key], "PTA120"), key
        assert not _diags(bundle.step, "PTA120")


# ---------------------------------------------------------------------------
# PTA180 device-telemetry counter contract (observability/devtel.py)
# ---------------------------------------------------------------------------
class TestTelemetryCounterContract:
    """PTA180: every @TEL-marked counter must be an int64, concretely
    declared, persistable, read-modify-write var — the PTA020 (weak-
    typing carry promotion) and PTA090 (write-only scan carry)
    lessons applied to the devtel subsystem, where a drifted counter
    silently poisons every stats window instead of erroring."""

    def _tel_var(self, main, name="@t/tel_ticks@TEL", dtype="int64",
                 shape=(1,), persistable=True):
        return main.global_block.create_var(
            name=name, shape=shape, dtype=dtype,
            persistable=persistable, stop_gradient=True)

    def test_rmw_int64_counter_is_clean(self):
        main, startup, g = _guarded()
        with g:
            var = self._tel_var(main)
            layers.assign(
                layers.elementwise_add(
                    var, layers.fill_constant([1], "int64", 1.0)),
                output=var)
        assert not _diags(main, "PTA180")

    def test_write_only_counter_is_error(self):
        main, startup, g = _guarded()
        with g:
            var = self._tel_var(main)
            # overwrites the cumulative total: per-dispatch deltas of
            # the serving layer go negative
            layers.assign(layers.fill_constant([1], "int64", 7.0),
                          output=var)
        ds = _diags(main, "PTA180")
        assert ds and ds[0].severity == ERROR
        assert "without reading" in ds[0].message

    def test_rmw_elsewhere_does_not_whitewash_clobber(self):
        """The RMW check is PER WRITING SITE via the producer chain:
        a legitimate bump elsewhere in the program must not mask a
        clobbering overwrite of the same counter (the program-global
        read-set version of this check passed exactly that)."""
        main, startup, g = _guarded()
        with g:
            var = self._tel_var(main)
            layers.assign(
                layers.elementwise_add(
                    var, layers.fill_constant([1], "int64", 1.0)),
                output=var)                       # good RMW bump
            layers.assign(layers.fill_constant([1], "int64", 0.0),
                          output=var)             # clobber: resets it
        ds = _diags(main, "PTA180")
        assert ds and ds[0].severity == ERROR
        assert "without reading" in ds[0].message

    def test_float_counter_is_error(self):
        main, startup, g = _guarded()
        with g:
            var = self._tel_var(main, dtype="float32")
            layers.assign(
                layers.elementwise_add(
                    var, layers.fill_constant([1], "float32", 1.0)),
                output=var)
        ds = _diags(main, "PTA180")
        assert ds and ds[0].severity == ERROR
        assert "int64" in ds[0].message

    def test_nonconcrete_shape_is_error(self):
        main, startup, g = _guarded()
        with g:
            self._tel_var(main, shape=(-1,))
        ds = _diags(main, "PTA180")
        assert ds and ds[0].severity == ERROR
        assert "carry-declarable" in ds[0].message

    def test_non_persistable_counter_is_error(self):
        main, startup, g = _guarded()
        with g:
            var = self._tel_var(main, persistable=False)
            layers.assign(
                layers.elementwise_add(
                    var, layers.fill_constant([1], "int64", 1.0)),
                output=var)
        ds = _diags(main, "PTA180")
        assert ds and ds[0].severity == ERROR
        assert "persistable" in ds[0].message

    def test_declared_but_untouched_counter_is_clean(self):
        """Admission-only programs declare counters the step bodies
        own (shared slot-state table): declared-but-unwritten must
        not trip the RMW rule."""
        main, startup, g = _guarded()
        with g:
            self._tel_var(main)
        assert not _diags(main, "PTA180")

    def test_in_while_increment_counts_as_rmw(self):
        """The serve programs bump counters INSIDE the burst While;
        reads/writes surface through the container — the shipped
        bundle programs are the fixture."""
        from paddle_tpu.models import transformer as T

        bundle = T.build_decode_step_program(
            seq_len=8, max_out_len=8, d_model=32, n_heads=2,
            n_layers=1, d_inner=64, vocab=50, n_slots=2,
            state_prefix="@pta180/")
        for key, prog in bundle.serves.items():
            assert not _diags(prog, "PTA180"), key
        assert not _diags(bundle.step, "PTA180")
        assert not _diags(bundle.prefill, "PTA180")

    def test_bundle_state_carries_devtel_logicals(self):
        """The devtel registry's logical names ride bundle.state (the
        serving layer's fetch-name contract) and the spec table
        declares them in every program (the PTA150 sweep's input)."""
        from paddle_tpu.models import transformer as T
        from paddle_tpu.observability import devtel

        bundle = T.build_decode_step_program(
            seq_len=8, max_out_len=8, d_model=32, n_heads=2,
            n_layers=1, d_inner=64, vocab=50, n_slots=2,
            state_prefix="@pta180b/")
        for spec in devtel.bundle_counters(paged=False):
            assert spec.logical in bundle.state
            name = bundle.state[spec.logical]
            assert devtel.TEL_MARK in name
            assert bundle._state_specs[name] == ((1,), "int64")


# ---------------------------------------------------------------------------
# PTA200/PTA201/PTA202 — the liveness domain (analysis/liveness.py)
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def small_paged_bundle():
    """One tiny shipped paged bundle for the liveness sweeps: PTA200
    reads only its static shape (cache/n_slots/max_out_len/workload),
    PTA201 its programs' pool accesses."""
    from paddle_tpu.models import transformer as T
    from paddle_tpu.models.decode_engine import CacheConfig

    return T.build_decode_step_program(
        seq_len=8, max_out_len=8, d_model=32, n_heads=2,
        n_layers=1, d_inner=64, vocab=50, n_slots=2,
        state_prefix="@pta200/",
        cache=CacheConfig(layout="paged", block_size=4,
                          n_blocks=4, n_prompt_entries=2))


class TestAdmissionCapacity:
    """PTA200 (bundle-level, via check_bundle): the capacity model's
    verdict on the session-pinning deadlock — the protomodel-validated
    witness — plus the counted bundle-level suppression convention."""

    def _bundle(self, base, **over):
        import copy

        b = copy.copy(base)
        for k, v in over.items():
            setattr(b, k, v)
        return b

    def test_infeasible_session_workload_is_error(
            self, small_paged_bundle):
        b = self._bundle(small_paged_bundle,
                         workload={"distinct_session_prompts": 3})
        ds = [d for d in analysis.check_bundle(b)
              if d.code == "PTA200"]
        assert ds and ds[0].severity == ERROR
        assert "session-pinning" in ds[0].message
        assert "protomodel" in ds[0].message  # oracle-backed witness
        assert ds[0].var == "PromptPrefixCache"

    def test_feasible_workloads_are_clean(self, small_paged_bundle):
        for wl in ({"distinct_session_prompts": 2},
                   {"distinct_session_prompts": 9,
                    "sessions_close": True},
                   None):
            b = self._bundle(small_paged_bundle, workload=wl) \
                if wl is not None else small_paged_bundle
            assert not [d for d in analysis.check_bundle(b)
                        if d.code == "PTA200"], wl

    def test_cold_traffic_tightens_the_entry_bound(
            self, small_paged_bundle):
        # == entries is feasible alone but not with churn traffic
        b = self._bundle(small_paged_bundle,
                         workload={"distinct_session_prompts": 2,
                                   "cold_traffic": True})
        ds = [d for d in analysis.check_bundle(b)
              if d.code == "PTA200"]
        assert ds and "churn entry" in ds[0].message

    def test_block_pool_demand_is_checked_too(
            self, small_paged_bundle):
        b = self._bundle(small_paged_bundle, n_slots=4)  # 4x2 > 4
        ds = [d for d in analysis.check_bundle(b)
              if d.code == "PTA200"]
        assert ds and ds[0].var == "HostBlockPool"
        assert "preemption" in ds[0].message

    def test_bundle_suppression_is_counted_not_silent(
            self, small_paged_bundle):
        b = self._bundle(
            small_paged_bundle,
            workload={"distinct_session_prompts": 3},
            _pta_suppress=("PTA200", "deliberate capacity wedge"))
        sup = []
        ds = [d for d in analysis.check_bundle(
            b, collect_suppressed=sup) if d.code == "PTA200"]
        assert not ds
        assert len(sup) == 1
        d, reason = sup[0]
        assert d.code == "PTA200" and reason == \
            "deliberate capacity wedge"

    def test_malformed_bundle_suppress_warns_and_ignores(
            self, small_paged_bundle):
        b = self._bundle(
            small_paged_bundle,
            workload={"distinct_session_prompts": 3},
            _pta_suppress="PTA200")  # not a (code, reason) pair
        ds = analysis.check_bundle(b)
        assert any(d.code == "PTA199" and d.severity == WARNING
                   for d in ds)
        assert any(d.code == "PTA200" and d.severity == ERROR
                   for d in ds)  # nothing suppressed


class TestReleaseObligations:
    """PTA201: every ownership tag a program's pool accesses exercise
    must carry an acquire/release contract with a registered release
    site on EVERY declared exit path."""

    def _pool_prog(self, tag):
        from paddle_tpu.analysis import absint

        main, startup, g = _guarded()
        with g:
            pool = main.global_block.create_var(
                name="@p201/self_k0@POOL", shape=(4, 2, 2, 8),
                dtype="float32", persistable=True,
                stop_gradient=True)
            new = layers.data("new", shape=[3, 2, 8],
                              dtype="float32",
                              append_batch_size=False)
            idx = layers.data("idx", shape=[3], dtype="int32",
                              append_batch_size=False)
            gate = layers.data("gate", shape=[3], dtype="float32",
                               append_batch_size=False)
            absint.mark_pool_index_source(idx, tag, bound=8)
            absint.mark_pool_index_source(gate, "lane_active")
            # raw op: the layer wrapper only blesses the shipped
            # exclusive_via names, and the ledger keys off the INDEX
            # provenance tag, not the declaration
            main.global_block.append_op(
                "masked_pool_write",
                {"Pool": [pool.name], "New": [new.name],
                 "Index": [idx.name], "Gate": [gate.name]},
                {"Out": [pool.name]},
                {"leading_dims": 2, "exclusive_via": tag})
        return main

    @staticmethod
    def _register_source(tag):
        from paddle_tpu.analysis import absint

        # registries are process-global and idempotent-identical:
        # re-registering the same definition is legal, so repeated
        # in-process runs of this module stay green
        absint.register_pool_index_source(
            tag, "test-only resource hold", absint.TS_EXCLUSIVE,
            assumption="HostBlockPool.alloc-disjoint")

    def test_tag_without_contract_is_error(self):
        self._register_source("pta201_nocontract_tab")
        main = self._pool_prog("pta201_nocontract_tab")
        ds = _diags(main, "PTA201")
        assert ds and ds[0].severity == ERROR
        assert "no acquire/release contract" in ds[0].message
        assert "pta201_nocontract_tab" in ds[0].message
        assert ds[0].op_idx is not None  # anchored at the access

    def test_declared_exit_without_site_is_error(self):
        from paddle_tpu.analysis import absint

        self._register_source("pta201_noexit_tab")
        absint.register_acquire_release(
            "pta201_noexit_tab", acquire="TestPool.alloc",
            release="TestPool.free", exits=("retire", "abort"),
            resource="TestPool")
        absint.register_release_site(
            "pta201_noexit_tab", "retire", "TestServer.retire")
        main = self._pool_prog("pta201_noexit_tab")
        ds = _diags(main, "PTA201")
        assert ds and ds[0].severity == ERROR
        assert "'abort'" in ds[0].message
        assert "no registered release site" in ds[0].message

    def test_fully_discharged_contract_is_clean(self):
        from paddle_tpu.analysis import absint

        self._register_source("pta201_clean_tab")
        absint.register_acquire_release(
            "pta201_clean_tab", acquire="TestPool.alloc",
            release="TestPool.free", exits=("retire",),
            resource="TestPool")
        absint.register_release_site(
            "pta201_clean_tab", "retire", "TestServer.retire")
        assert not _diags(self._pool_prog("pta201_clean_tab"),
                          "PTA201")

    def test_shipped_paged_bundle_is_clean(self, small_paged_bundle):
        # the serving layer's module-scope release-site registrations
        # discharge every contract the real programs exercise (also
        # pinned zoo-wide by test_analysis_gate)
        b = small_paged_bundle
        for label in ("step", "prefill"):
            assert not _diags(getattr(b, label), "PTA201"), label
        for key, prog in b.serves.items():
            assert not _diags(prog, "PTA201"), key

    def test_contract_api_rejects_bad_registrations(self):
        from paddle_tpu.analysis import absint

        with pytest.raises(ValueError, match="not a registered"):
            absint.register_acquire_release(
                "pta201_never_registered", "a", "r", ("x",), "P")
        with pytest.raises(ValueError, match="gate"):
            absint.register_acquire_release(
                "lane_active", "a", "r", ("x",), "P")
        with pytest.raises(ValueError, match="no exit paths"):
            self._register_source("pta201_noexits_tab")
            absint.register_acquire_release(
                "pta201_noexits_tab", "a", "r", (), "P")
        with pytest.raises(ValueError, match="no acquire contract"):
            absint.register_release_site(
                "pta201_never_registered", "x", "S.m")
        self._register_source("pta201_drift_tab")
        absint.register_acquire_release(
            "pta201_drift_tab", "a", "r", ("retire",), "P")
        with pytest.raises(ValueError,
                           match="does not declare exit path"):
            absint.register_release_site(
                "pta201_drift_tab", "preempt", "S.m")


class TestWhileProgress:
    """PTA202: While loops must carry a provable termination variant
    (increment counter + loop-invariant bound in the condition's
    backward slice); serve Whiles (lane_active_mask-marked condition)
    are held to ERROR, others to WARNING."""

    def _no_counter_while(self, serve=False):
        from paddle_tpu.analysis import absint

        main, startup, g = _guarded()
        with g:
            i = layers.fill_constant([1], "int64", 0)
            limit = layers.fill_constant([1], "int64", 10)
            cond = layers.less_than(i, limit)
            w = layers.While(cond)
            with w.block():
                # recomputes the condition but never steps a counter
                layers.less_than(i, limit, cond=cond)
                if serve:
                    # mark INSIDE the body so the producer search
                    # finds the in-body writer (the _serve_cond
                    # pattern), not the pre-loop one
                    absint.mark_divergence_source(
                        cond, "lane_active_mask")
        return main

    def test_plain_unproven_while_warns(self):
        ds = _diags(self._no_counter_while(), "PTA202")
        assert ds and ds[0].severity == WARNING
        assert "no increment-driven counter" in ds[0].message

    def test_serve_unproven_while_is_error(self):
        ds = _diags(self._no_counter_while(serve=True), "PTA202")
        assert ds and ds[0].severity == ERROR
        assert "serve/burst" in ds[0].message

    def test_spinning_while_is_flagged(self):
        # the While LAYER refuses a body that never rewrites the
        # condition at build time; append the raw op to pin the
        # checker's own sweep on the same defect
        main, startup, g = _guarded()
        with g:
            i = layers.fill_constant([1], "int64", 0)
            limit = layers.fill_constant([1], "int64", 10)
            cond = layers.less_than(i, limit)
            sub = main.create_block()
            sub.append_op("increment", {"X": [i.name]},
                          {"Out": [i.name]}, {"step": 1})
            main.rollback()
            main.global_block.append_op(
                "while", {"Condition": [cond.name], "X": [],
                          "Init": []}, {"Out": []},
                {"sub_block": sub, "carried": [], "externals": []})
        ds = _diags(main, "PTA202")
        assert ds and "only spin" in ds[0].message

    def test_counter_bounded_while_is_proven(self):
        assert not _diags(_while_counter_program(1), "PTA202")

    def test_shipped_serve_whiles_are_proven(self,
                                             small_paged_bundle):
        from paddle_tpu.analysis import liveness

        for key, prog in small_paged_bundle.serves.items():
            assert not _diags(prog, "PTA202"), key
            vs = [v for v in liveness.while_variants(prog)
                  if v.kind == "serve"]
            assert vs, key  # the serve While is detected as such
            for v in vs:
                assert v.proven
                assert v.assumption == "monotone-lane_active_mask"
                assert "min_active" in v.bound_terms \
                    and "n_steps" in v.bound_terms


class TestExplainCLI:
    """--explain PTA0xx: checker contract docs at the CLI, no zoo
    build (tribal knowledge must be one command away from a red
    finding)."""

    def test_explain_prints_contract_doc(self, capsys):
        from paddle_tpu.analysis.__main__ import main as cli_main

        rc = cli_main(["--explain", "PTA201"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "PTA201 — release-on-every-exit-path" in out
        assert "register_acquire_release" in out
        assert "_pta_suppress" in out  # the suppression footer

    def test_explain_is_case_insensitive_and_multi(self, capsys):
        from paddle_tpu.analysis.__main__ import main as cli_main

        rc = cli_main(["--explain", "pta200", "PTA202"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "PTA200 — admission-capacity-feasibility" in out
        assert "PTA202 — while-variant-progress" in out

    def test_explain_unknown_code_exits_2(self, capsys):
        from paddle_tpu.analysis.__main__ import main as cli_main

        rc = cli_main(["--explain", "PTA999"])
        err = capsys.readouterr().err
        assert rc == 2
        assert "unknown checker code" in err
