"""Disaggregated prefill/decode serving, sharded half (ISSUE 17
tentpole): ONE chunked bundle, TWO ShardingPlans over two scopes on
disjoint slices of the 8-device CPU mesh.

The contracts this module pins (slow lane — two full tp=2 serving
stacks compile):

* ``apply_phase_sharding`` attaches the ``("chunked", p)`` phase
  programs to a PREFILL plan (tp over the encoder projections — the
  MXU-bound phase) and everything else to a DECODE plan (tp over KV
  bytes), with DIFFERENT plan tokens: no executable/disk-cache entry
  can dedup across phases;
* ``place_disaggregated_bundle`` binds the plans to DISJOINT device
  slices, syncs params decode-scope -> prefill-scope, and places each
  phase's state under its plan;
* the KV handoff is token-exact: entry rows the worker wrote on the
  prefill slice read back BIT-IDENTICAL from the decode scope, and
  the served tokens match the unsharded monolithic baseline exactly;
* zero steady-state compiles with BOTH servers live: a second traffic
  wave (fresh cold prompt included — chunk dispatches on the prefill
  slice, decode bursts on the decode slice) compiles nothing;
* the server constructor enforces the placement discipline: a
  disaggregated bundle must be placed BEFORE construction, and
  ``mesh_devices=`` (the single-plan path) is rejected for it.
"""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import unique_name
from paddle_tpu.core.scope import Scope
from paddle_tpu.inference.runtime.placement import \
    place_disaggregated_bundle
from paddle_tpu.inference.serving import (DisaggregatedPrefillWorker,
                                          PagedContinuousGenerationServer)
from paddle_tpu.models import transformer as T
from paddle_tpu.models.decode_engine import (POOL_MARK, CacheConfig,
                                             ShardingConfig,
                                             apply_phase_sharding)

V, D, H, L, S, MAXT = 16, 32, 2, 2, 10, 32
BS, NB, E, C = 8, 24, 3, 4
NC = (S + C - 1) // C
NPH = 2 * L + 2
PREFIX = "@dsg/"
TP = 2


def _build(phase_shard):
    """Seed-pinned build: params are initialized identically for the
    baseline and the disaggregated stack, so token parity is exact."""
    fluid.seed(0)
    scope = Scope()
    with unique_name.guard():
        _, t_st, _ = T.build_program(
            seq_len=S, d_model=D, n_heads=H, n_layers=L, d_inner=64,
            vocab=V, with_optimizer=False, dropout_rate=0.0)
    with unique_name.guard():
        bundle = T.build_decode_step_program(
            n_slots=4, admit_buckets=[1, 4], state_prefix=PREFIX,
            seq_len=S, max_out_len=MAXT, d_model=D, n_heads=H,
            n_layers=L, d_inner=64, vocab=V, start_id=2, end_id=1,
            cache=CacheConfig(layout="paged", block_size=BS,
                              n_blocks=NB, n_prompt_entries=E,
                              chunk_tokens=C))
    if phase_shard:
        apply_phase_sharding(bundle, ShardingConfig(tp=TP),
                             ShardingConfig(tp=TP), L)
    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(t_st, scope=scope)
    return bundle, exe, scope


def _prompts():
    rng = np.random.RandomState(7)
    return [rng.randint(3, V, (1, S)).astype(np.int64)
            for _ in range(4)]


ORDER = [0, 1, 0, 2, 1, 3, 2, 0]


@pytest.fixture(scope="module")
def mono_ref():
    """Unsharded monolithic baseline tokens over the standard wave."""
    bundle, exe, scope = _build(phase_shard=False)
    prompts = _prompts()
    with PagedContinuousGenerationServer(
            bundle, executor=exe, scope=scope, steps_per_tick=4,
            chunked_prefill=False) as srv:
        return [np.asarray(srv.submit(prompts[i]).result(240.0))
                for i in ORDER]


@pytest.fixture(scope="module")
def disagg():
    """The full sharded stack: phase plans bound to disjoint slices,
    worker on the prefill scope, server on the decode scope."""
    bundle, exe, scope = _build(phase_shard=True)
    bundle.init_slot_state(scope)
    pre_scope = Scope()
    placed = place_disaggregated_bundle(bundle, scope, pre_scope)
    worker = DisaggregatedPrefillWorker(bundle, executor=exe,
                                        scope=pre_scope)
    srv = PagedContinuousGenerationServer(
        bundle, executor=exe, scope=scope, steps_per_tick=4,
        prefill_worker=worker)
    yield {"bundle": bundle, "exe": exe, "scope": scope,
           "pre_scope": pre_scope, "worker": worker, "srv": srv,
           "placed": placed, "prompts": _prompts()}
    srv.close()
    worker.close()


class TestPhasePlans:
    def test_distinct_plans_on_disjoint_slices(self, disagg):
        b = disagg["bundle"]
        assert b.sharding_plan is not None
        assert b.prefill_plan is not None
        # different placements + different device ids: the executor
        # key, disk-cache digest and server fingerprint all differ by
        # construction — no cross-phase dedup anywhere
        assert b.prefill_plan.token() != b.sharding_plan.token()
        dec_ids = set(b.sharding_plan._device_ids)
        pre_ids = set(b.prefill_plan._device_ids)
        assert len(dec_ids) == TP and len(pre_ids) == TP
        assert not (dec_ids & pre_ids)
        assert disagg["placed"] > 0

    def test_chunk_programs_ride_the_prefill_plan(self, disagg):
        from paddle_tpu.core import sharding_plan as sp

        b = disagg["bundle"]
        for key, prog in b.serves.items():
            want = b.prefill_plan \
                if isinstance(key, tuple) and key[0] == "chunked" \
                else b.sharding_plan
            assert sp.plan_of(prog) is want, key

    def test_apply_phase_sharding_needs_chunked_bundle(self):
        with unique_name.guard():
            plain = T.build_decode_step_program(
                n_slots=2, admit_buckets=[1], state_prefix="@dsgp/",
                seq_len=S, max_out_len=MAXT, d_model=D, n_heads=H,
                n_layers=1, d_inner=64, vocab=V, start_id=2, end_id=1,
                cache=CacheConfig(layout="paged", block_size=BS,
                                  n_blocks=8, n_prompt_entries=2))
        with pytest.raises(ValueError, match="chunked-prefill"):
            apply_phase_sharding(plain, ShardingConfig(tp=TP),
                                 ShardingConfig(tp=TP), 1)


class TestConstructionDiscipline:
    def test_unplaced_disagg_bundle_rejected(self):
        bundle, exe, scope = _build(phase_shard=True)
        with pytest.raises(ValueError, match="unplaced"):
            PagedContinuousGenerationServer(bundle, executor=exe,
                                            scope=scope)

    def test_mesh_devices_rejected_for_disagg_bundle(self):
        import jax

        bundle, exe, scope = _build(phase_shard=True)
        bundle.init_slot_state(scope)
        place_disaggregated_bundle(bundle, scope, Scope())
        with pytest.raises(ValueError, match="place_disaggregated"):
            PagedContinuousGenerationServer(
                bundle, executor=exe, scope=scope,
                mesh_devices=jax.devices()[:TP])


class TestServing:
    def test_wave_token_exact_vs_monolithic(self, disagg, mono_ref):
        srv, prompts = disagg["srv"], disagg["prompts"]
        toks = [np.asarray(srv.submit(prompts[i]).result(240.0))
                for i in ORDER]
        for got, want in zip(toks, mono_ref):
            assert np.array_equal(got, want)
        stats = srv.pool_stats()
        assert stats["disaggregated"] is True
        # 4 distinct prompts with E=3 entries: >= 4 jobs (a repeat of
        # an LRU-evicted prompt re-chunks — timing-dependent), every
        # job handed off, tick arithmetic exact per job
        assert stats["chunk_jobs"] >= 4
        assert stats["disagg_handoffs"] == stats["chunk_jobs"]
        ws = disagg["worker"].stats()
        assert ws["jobs_done"] == stats["chunk_jobs"]
        assert ws["chunk_ticks"] == ws["jobs_done"] * NC * NPH

    def test_handoff_rows_bit_exact_across_scopes(self, disagg):
        """Runs after the wave: every cross-KV entry row the worker
        wrote on the prefill slice must read back bit-identical from
        the decode scope (the handoff is a copy, not a recompute)."""
        import re

        b = disagg["bundle"]
        pat = re.compile(re.escape(PREFIX) + r"cross_[kv]\d+"
                         + re.escape(POOL_MARK))
        names = sorted(n for n in b._state_specs if pat.fullmatch(n))
        assert len(names) == 2 * L
        for n in names:
            dec = np.asarray(disagg["scope"]._get(n))[:E]
            pre = np.asarray(disagg["pre_scope"]._get(n))[:E]
            np.testing.assert_array_equal(dec, pre, err_msg=n)

    def test_second_wave_zero_compiles_both_servers_live(
            self, disagg, mono_ref):
        """Steady state with BOTH phases serving: re-running the wave
        (hits + radix re-admissions on the decode slice; the repeat
        submissions of already-evicted prompts may chunk again on the
        prefill slice) must compile NOTHING anywhere."""
        srv, exe = disagg["srv"], disagg["exe"]
        prompts = disagg["prompts"]
        warmed = exe.compile_count
        toks = [np.asarray(srv.submit(prompts[i]).result(240.0))
                for i in ORDER]
        assert exe.compile_count == warmed
        for got, want in zip(toks, mono_ref):
            assert np.array_equal(got, want)
