"""OpTests for op-gap batch 3 (fused-op family + utility ops).

Parity model: reference tests/unittests/test_fill_op.py,
test_fused_elemwise_activation_op.py, test_fusion_squared_mat_sub_op.py,
test_fusion_repeated_fc_relu_op.py, test_fusion_seqconv_eltadd_relu_op.py,
test_fusion_seqpool_concat_op.py, test_fusion_seqexpand_concat_fc_op.py,
test_fusion_transpose_flatten_concat_op.py, test_fusion_gru_op.py,
test_fusion_lstm_op.py, test_fused_embedding_seq_pool_op.py,
test_attention_lstm_op.py, test_tree_conv_op.py,
test_similarity_focus_op.py, test_box_decoder_and_assign_op.py,
test_distribute_fpn_proposals_op.py, test_cross_entropy2_op.py.
"""
import numpy as np
import pytest

import paddle_tpu as fluid
from op_test import OpTest


class TestFill(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "fill"
        vals = np.arange(6, dtype=np.float32)
        self.inputs = {}
        self.attrs = {"value": [float(v) for v in vals],
                      "shape": [2, 3], "dtype": "float32"}
        self.outputs = {"Out": vals.reshape(2, 3)}

    def test_output(self):
        self.check_output()


class TestFakeInit(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "fake_init"
        self.inputs = {}
        self.attrs = {"shape": [3, 4]}
        self.outputs = {"Out": np.zeros((3, 4), np.float32)}

    def test_output(self):
        self.check_output()


class TestAllocContinuousSpace(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "alloc_continuous_space"
        a = np.random.rand(2, 3).astype("float32")
        b = np.random.rand(4).astype("float32")
        self.inputs = {"Input": [("a", a), ("b", b)]}
        self.attrs = {}
        self.outputs = {
            "Output": [("a_out", a), ("b_out", b)],
            "FusedOutput": np.concatenate([a.ravel(), b.ravel()])}

    def test_output(self):
        self.check_output()


class TestCrossEntropy2(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "cross_entropy2"
        x = np.random.uniform(0.1, 1.0, (5, 7)).astype("float32")
        x = x / x.sum(1, keepdims=True)
        label = np.random.randint(0, 7, (5, 1)).astype("int64")
        match = np.take_along_axis(x, label, axis=1)
        y = -np.log(match)
        self.inputs = {"X": x, "Label": label}
        self.attrs = {}
        self.outputs = {"Y": y, "MatchX": match}

    def test_output(self):
        self.check_output(atol=1e-5)

    def test_grad(self):
        self.check_grad(["X"], "Y", no_grad_set={"Label"})


class TestFusedElemwiseActivation(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "fused_elemwise_activation"
        x = np.random.randn(3, 4).astype("float32")
        y = np.random.randn(3, 4).astype("float32")
        inter = x + y
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"functor_list": ["relu", "elementwise_add"]}
        self.outputs = {"Out": np.maximum(inter, 0),
                        "IntermediateOut": inter}

    def test_output(self):
        self.check_output(atol=1e-6)

    def test_grad(self):
        self.check_grad(["X", "Y"], "Out")


class TestFusedElemwiseActivationScale(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "fused_elemwise_activation"
        x = np.random.randn(3, 4).astype("float32")
        y = np.random.randn(3, 4).astype("float32")
        inter = y * 3.0
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"functor_list": ["elementwise_mul", "scale"],
                      "scale": 3.0}
        self.outputs = {"Out": x * inter, "IntermediateOut": inter}

    def test_output(self):
        self.check_output(atol=1e-6)


class TestFusionSquaredMatSub(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "fusion_squared_mat_sub"
        x = np.random.randn(3, 4).astype("float32")
        y = np.random.randn(4, 5).astype("float32")
        sxy = (x @ y) ** 2
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"scalar": 0.5}
        self.outputs = {"Out": (sxy - (x * x) @ (y * y)) * 0.5,
                        "SquaredX": x * x, "SquaredY": y * y,
                        "SquaredXY": sxy}

    def test_output(self):
        self.check_output(atol=1e-4)


class TestFusionRepeatedFCRelu(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "fusion_repeated_fc_relu"
        x = np.random.randn(4, 5).astype("float32")
        w1 = np.random.randn(5, 6).astype("float32")
        b1 = np.random.randn(6).astype("float32")
        w2 = np.random.randn(6, 3).astype("float32")
        b2 = np.random.randn(3).astype("float32")
        h1 = np.maximum(x @ w1 + b1, 0)
        h2 = np.maximum(h1 @ w2 + b2, 0)
        self.inputs = {"X": x, "W": [("w1", w1), ("w2", w2)],
                       "Bias": [("b1", b1), ("b2", b2)]}
        self.attrs = {}
        self.outputs = {"Out": h2, "ReluOut": [("r1", h1)]}

    def test_output(self):
        self.check_output(atol=1e-5)


class TestFusionSeqpoolConcat(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "fusion_seqpool_concat"
        x0 = np.random.randn(2, 4, 3).astype("float32")
        x1 = np.random.randn(2, 4, 5).astype("float32")
        l0 = np.array([2, 4], np.int32)
        l1 = np.array([3, 1], np.int32)

        def pool(x, sl):
            m = (np.arange(x.shape[1])[None, :] < sl[:, None])
            return (x * m[..., None]).sum(1)

        self.inputs = {"X": [("x0", x0), ("x1", x1)],
                       "SeqLen": [("l0", l0), ("l1", l1)]}
        self.attrs = {"pooltype": "SUM"}
        self.outputs = {
            "Out": np.concatenate([pool(x0, l0), pool(x1, l1)], 1)}

    def test_output(self):
        self.check_output(atol=1e-5)


class TestFusionSeqExpandConcatFC(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "fusion_seqexpand_concat_fc"
        ref = np.random.randn(2, 3, 4).astype("float32")
        v = np.random.randn(2, 5).astype("float32")
        w = np.random.randn(9, 6).astype("float32")
        b = np.random.randn(6).astype("float32")
        cat = np.concatenate(
            [ref, np.broadcast_to(v[:, None], (2, 3, 5))], -1)
        out = np.maximum(cat @ w + b, 0)
        self.inputs = {"X": [("ref", ref), ("v", v)],
                       "FCWeight": w, "FCBias": b}
        self.attrs = {"fc_activation": "relu"}
        self.outputs = {"Out": out}

    def test_output(self):
        self.check_output(atol=1e-5)


class TestFusionTransposeFlattenConcat(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "fusion_transpose_flatten_concat"
        x0 = np.random.randn(2, 3, 4).astype("float32")
        x1 = np.random.randn(2, 3, 4).astype("float32")
        t0 = x0.transpose(0, 2, 1).reshape(2, -1)
        t1 = x1.transpose(0, 2, 1).reshape(2, -1)
        self.inputs = {"X": [("x0", x0), ("x1", x1)]}
        self.attrs = {"trans_axis": [0, 2, 1], "flatten_axis": 1,
                      "concat_axis": 1}
        self.outputs = {"Out": np.concatenate([t0, t1], 1)}

    def test_output(self):
        self.check_output(atol=1e-6)


class TestFusedEmbeddingSeqPool(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "fused_embedding_seq_pool"
        w = np.random.randn(10, 4).astype("float32")
        ids = np.random.randint(0, 10, (2, 3, 1)).astype("int64")
        sl = np.array([2, 3], np.int32)
        emb = w[ids[..., 0]]
        m = (np.arange(3)[None, :] < sl[:, None])
        self.inputs = {"W": w, "Ids": ids, "SeqLen": sl}
        self.attrs = {}
        self.outputs = {"Out": (emb * m[..., None]).sum(1)}

    def test_output(self):
        self.check_output(atol=1e-5)

    def test_grad(self):
        self.check_grad(["W"], "Out", no_grad_set={"Ids", "SeqLen"})


def _np_lstm(xx, wh, bias, h0, c0):
    """Oracle: i,f,c,o gate order, sigmoid gates, tanh cell/cand."""
    b, t, fourh = xx.shape
    d = fourh // 4
    h = h0.copy()
    c = c0.copy()
    hs = np.zeros((b, t, d), np.float32)
    cs = np.zeros((b, t, d), np.float32)
    sig = lambda v: 1 / (1 + np.exp(-v))
    for step in range(t):
        g = xx[:, step] + h @ wh + bias[:, :4 * d]
        gi, gf, gc, go = np.split(g, 4, axis=1)
        i, f, o = sig(gi), sig(gf), sig(go)
        c = f * c + i * np.tanh(gc)
        h = o * np.tanh(c)
        hs[:, step] = h
        cs[:, step] = c
    return hs, cs


class TestFusionLSTM(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "fusion_lstm"
        b, t, m, d = 2, 3, 4, 5
        x = np.random.randn(b, t, m).astype("float32") * 0.1
        wx = np.random.randn(m, 4 * d).astype("float32") * 0.1
        wh = np.random.randn(d, 4 * d).astype("float32") * 0.1
        bias = np.random.randn(1, 4 * d).astype("float32") * 0.1
        xx = x @ wx
        hs, cs = _np_lstm(xx, wh, bias,
                          np.zeros((b, d), np.float32),
                          np.zeros((b, d), np.float32))
        self.inputs = {"X": x, "WeightX": wx, "WeightH": wh,
                       "Bias": bias}
        self.attrs = {"use_peepholes": False}
        self.outputs = {"Hidden": hs, "Cell": cs}

    def test_output(self):
        self.check_output(atol=1e-5)


class TestFusionGRU(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "fusion_gru"
        b, t, m, d = 2, 3, 4, 5
        x = np.random.randn(b, t, m).astype("float32") * 0.1
        wx = np.random.randn(m, 3 * d).astype("float32") * 0.1
        wh = np.random.randn(d, 3 * d).astype("float32") * 0.1
        bias = np.random.randn(1, 3 * d).astype("float32") * 0.1
        xx = x @ wx + bias
        sig = lambda v: 1 / (1 + np.exp(-v))
        h = np.zeros((b, d), np.float32)
        hs = np.zeros((b, t, d), np.float32)
        w_rz, w_c = wh[:, :2 * d], wh[:, 2 * d:]
        for step in range(t):
            xu, xr, xc = np.split(xx[:, step], 3, axis=1)
            rz = np.concatenate([xu, xr], 1) + h @ w_rz
            u = sig(rz[:, :d])
            r = sig(rz[:, d:])
            cand = np.tanh(xc + (r * h) @ w_c)
            h = (1 - u) * h + u * cand
            hs[:, step] = h
        self.inputs = {"X": x, "WeightX": wx, "WeightH": wh,
                       "Bias": bias}
        self.attrs = {}
        self.outputs = {"Hidden": hs}

    def test_output(self):
        self.check_output(atol=1e-5)


class TestFusedEmbeddingFCLSTM(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "fused_embedding_fc_lstm"
        b, t, v, d = 2, 3, 7, 4
        ids = np.random.randint(0, v, (b, t, 1)).astype("int64")
        table = (np.random.randn(v, 4 * d) * 0.1).astype("float32")
        wh = (np.random.randn(d, 4 * d) * 0.1).astype("float32")
        bias = (np.random.randn(1, 4 * d) * 0.1).astype("float32")
        xx = table[ids[..., 0]]
        hs, cs = _np_lstm(xx, wh, bias,
                          np.zeros((b, d), np.float32),
                          np.zeros((b, d), np.float32))
        self.inputs = {"Ids": ids, "Embeddings": table, "WeightH": wh,
                       "Bias": bias}
        self.attrs = {"use_peepholes": False}
        self.outputs = {"Hidden": hs, "Cell": cs}

    def test_output(self):
        self.check_output(atol=1e-5)


class TestAttentionLSTM(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "attention_lstm"
        b, t, m, d = 2, 4, 3, 5
        x = (np.random.randn(b, t, m) * 0.2).astype("float32")
        c0 = (np.random.randn(b, d) * 0.2).astype("float32")
        h0 = (np.random.randn(b, d) * 0.2).astype("float32")
        aw = (np.random.randn(m + d, 1) * 0.2).astype("float32")
        lw = (np.random.randn(d + m, 4 * d) * 0.2).astype("float32")
        lb = (np.random.randn(1, 4 * d) * 0.2).astype("float32")
        sig = lambda v: 1 / (1 + np.exp(-v))
        h, c = h0.copy(), c0.copy()
        hs = np.zeros((b, t, d), np.float32)
        cs = np.zeros((b, t, d), np.float32)
        for step in range(t):
            sc = x @ aw[:m, 0] + (c @ aw[m:, 0])[:, None]
            sc = np.maximum(sc, 0)
            e = np.exp(sc - sc.max(1, keepdims=True))
            p = e / e.sum(1, keepdims=True)
            lx = np.einsum("bt,btm->bm", p, x)
            g = np.concatenate([lx, h], 1) @ lw + lb
            gi, gf, gc, go = np.split(g, 4, axis=1)
            c = sig(gf) * c + sig(gi) * np.tanh(gc)
            h = sig(go) * np.tanh(c)
            hs[:, step] = h
            cs[:, step] = c
        self.inputs = {"X": x, "C0": c0, "H0": h0,
                       "AttentionWeight": aw,
                       "LSTMWeight": lw, "LSTMBias": lb}
        self.attrs = {}
        self.outputs = {"Hidden": hs, "Cell": cs}

    def test_output(self):
        self.check_output(atol=1e-5)


class TestConv2DFusion(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "conv2d_fusion"
        import torch
        import torch.nn.functional as F

        x = np.random.randn(2, 3, 5, 5).astype("float32")
        w = np.random.randn(4, 3, 3, 3).astype("float32")
        b = np.random.randn(4).astype("float32")
        out = F.conv2d(torch.from_numpy(x), torch.from_numpy(w),
                       padding=1).numpy()
        out = np.maximum(out + b.reshape(1, -1, 1, 1), 0)
        self.inputs = {"Input": x, "Filter": w, "Bias": b}
        self.attrs = {"paddings": [1, 1], "activation": "relu"}
        self.outputs = {"Output": out}

    def test_output(self):
        self.check_output(atol=1e-4)


class TestConv2DInceptionFusion(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "conv2d_inception_fusion"
        import torch
        import torch.nn.functional as F

        cin, h, w = 4, 6, 6
        x = np.random.randn(1, cin, h, w).astype("float32")
        # f2 takes 2*3 channels in 2 groups; f3 takes 4 channels
        f0 = np.random.randn(5, cin, 1, 1).astype("float32")
        f1 = np.random.randn(8, cin, 1, 1).astype("float32")  # oc1=8-6=2
        f2 = np.random.randn(6, 3, 3, 3).astype("float32")    # groups=2
        f3 = np.random.randn(7, 2, 3, 3).astype("float32")
        b0 = np.random.randn(5).astype("float32")
        b1 = np.random.randn(8).astype("float32")
        b2 = np.random.randn(6).astype("float32")
        b3 = np.random.randn(7).astype("float32")

        tt = torch.from_numpy
        pooled = F.avg_pool2d(tt(x), 3, stride=1, padding=1,
                              count_include_pad=True)
        y0 = F.conv2d(pooled, tt(f0), tt(b0))
        y1 = F.conv2d(tt(x), tt(f1), tt(b1))
        y1h, y1t = y1[:, :2], y1[:, 2:]
        y2 = F.conv2d(y1t, tt(f2), tt(b2), padding=1, groups=2)
        y2h, y2t = y2[:, :4], y2[:, 4:]
        y3 = F.conv2d(y2t, tt(f3), tt(b3), padding=1)
        out = torch.relu(torch.cat([y0, y1h, y2h, y3], 1)).numpy()
        self.inputs = {
            "Input": x,
            "Filter": [("f0", f0), ("f1", f1), ("f2", f2), ("f3", f3)],
            "Bias": [("b0", b0), ("b1", b1), ("b2", b2), ("b3", b3)]}
        self.attrs = {}
        self.outputs = {"Output": out}

    def test_output(self):
        self.check_output(atol=1e-4)


class TestSimilarityFocus(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "similarity_focus"
        n, a, b, c = 2, 3, 3, 4
        x = np.random.rand(n, a, b, c).astype("float32")
        out = np.zeros_like(x)
        for bi in range(n):
            t = x[bi, 0]
            mask = np.zeros((b, c))
            used_r = np.zeros(b, bool)
            used_c = np.zeros(c, bool)
            for _ in range(min(b, c)):
                avail = t.copy()
                avail[used_r, :] = -np.inf
                avail[:, used_c] = -np.inf
                r, cc = np.unravel_index(np.argmax(avail), t.shape)
                mask[r, cc] = 1
                used_r[r] = True
                used_c[cc] = True
            out[bi] = mask[None]
        self.inputs = {"X": x}
        self.attrs = {"axis": 1, "indexes": [0]}
        self.outputs = {"Out": out}

    def test_output(self):
        self.check_output(atol=1e-6)


class TestTreeConv(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "tree_conv"
        # tree: 1 -> (2, 3), 2 -> (4); 4 nodes, features F=2
        n, f, s, m = 4, 2, 3, 2
        md = 2
        edges = np.array([[[1, 2], [1, 3], [2, 4]]], np.int32)
        feats = np.random.randn(1, n, f).astype("float32")
        filt = np.random.randn(f, 3, s, m).astype("float32")

        # independent numpy oracle: DFS patches per root, depth<md
        children = {1: [2, 3], 2: [4], 3: [], 4: []}
        parentpos = {2: (1, 2), 3: (2, 2), 4: (1, 1)}  # (idx, pclen)

        def patch(root):
            # (node, idx, pclen, depth); root has (1,1,0)
            items = [(root, 1, 1, 0)]
            frontier = [(root, 0)]
            while frontier:
                u, du = frontier.pop()
                if du + 1 >= md:
                    continue
                for v in children[u]:
                    idx, pc = parentpos[v]
                    items.append((v, idx, pc, du + 1))
                    frontier.append((v, du + 1))
            return items

        w2 = filt.transpose(1, 0, 2, 3).reshape(3 * f, s * m)
        out = np.zeros((1, n, s, m), np.float32)
        for root in range(1, n + 1):
            pl = np.zeros(f)
            pr = np.zeros(f)
            pt = np.zeros(f)
            for (node, idx, pc, depth) in patch(root):
                eta_t = (md - depth) / md
                frac = 0.5 if pc == 1 else (idx - 1.0) / (pc - 1.0)
                eta_l = (1 - eta_t) * frac
                eta_r = (1 - eta_t) * (1 - frac)
                fv = feats[0, node - 1]
                pl += eta_l * fv
                pr += eta_r * fv
                pt += eta_t * fv
            vec = np.concatenate([pl, pr, pt])
            out[0, root - 1] = (vec @ w2).reshape(s, m)
        self.inputs = {"EdgeSet": edges, "NodesVector": feats,
                       "Filter": filt}
        self.attrs = {"max_depth": md}
        self.outputs = {"Out": out}

    def test_output(self):
        self.check_output(atol=1e-4)

    def test_grad(self):
        self.check_grad(["NodesVector", "Filter"], "Out",
                        no_grad_set={"EdgeSet"})


class TestBoxDecoderAndAssign(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "box_decoder_and_assign"
        n, c = 4, 3
        prior = np.abs(np.random.rand(n, 4).astype("float32")) * 10
        prior[:, 2:] += prior[:, :2] + 1
        pvar = np.array([0.1, 0.1, 0.2, 0.2], np.float32)
        tgt = (np.random.randn(n, c * 4) * 0.3).astype("float32")
        score = np.random.rand(n, c).astype("float32")
        clip = np.log(10.0)

        dec = np.zeros((n, c * 4), np.float32)
        assign = np.zeros((n, 4), np.float32)
        for i in range(n):
            pw = prior[i, 2] - prior[i, 0] + 1
            ph = prior[i, 3] - prior[i, 1] + 1
            pcx = prior[i, 0] + pw / 2
            pcy = prior[i, 1] + ph / 2
            for j in range(c):
                o = j * 4
                dw = min(pvar[2] * tgt[i, o + 2], clip)
                dh = min(pvar[3] * tgt[i, o + 3], clip)
                cx = pvar[0] * tgt[i, o] * pw + pcx
                cy = pvar[1] * tgt[i, o + 1] * ph + pcy
                w = np.exp(dw) * pw
                h = np.exp(dh) * ph
                dec[i, o:o + 4] = [cx - w / 2, cy - h / 2,
                                   cx + w / 2 - 1, cy + h / 2 - 1]
            mj = 1 + int(np.argmax(score[i, 1:]))
            assign[i] = dec[i, mj * 4:mj * 4 + 4]
        self.inputs = {"PriorBox": prior, "PriorBoxVar": pvar,
                       "TargetBox": tgt, "BoxScore": score}
        self.attrs = {"box_clip": float(clip)}
        self.outputs = {"DecodeBox": dec, "OutputAssignBox": assign}

    def test_output(self):
        self.check_output(atol=1e-4)


class TestDistributeFpnProposals(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "distribute_fpn_proposals"
        n = 6
        rois = np.zeros((n, 4), np.float32)
        sizes = [20, 300, 60, 500, 100, 40]  # sqrt(area) targets
        for i, s in enumerate(sizes):
            rois[i] = [10, 10, 10 + s, 10 + s]
        min_l, max_l, ref_l, ref_s = 2, 5, 4, 224
        # +1 pixel offset (reference BBoxArea normalized=false)
        lvl = np.clip(np.floor(
            np.log2((np.asarray(sizes, np.float64) + 1) / ref_s) + ref_l),
            min_l, max_l).astype(int)
        outs = []
        for l in range(min_l, max_l + 1):
            sel = rois[lvl == l]
            pad = np.zeros((n, 4), np.float32)
            pad[:sel.shape[0]] = sel
            outs.append(pad)
        counts = np.array([(lvl == l).sum()
                           for l in range(min_l, max_l + 1)], np.int32)
        order = np.argsort(lvl * (n + 1) + np.arange(n))
        restore = np.argsort(order).astype(np.int32).reshape(n, 1)
        self.inputs = {"FpnRois": rois}
        self.attrs = {"min_level": min_l, "max_level": max_l,
                      "refer_level": ref_l, "refer_scale": ref_s}
        self.outputs = {
            "MultiFpnRois": [(f"lvl{l}", outs[l - min_l])
                             for l in range(min_l, max_l + 1)],
            "MultiLevelCounts": counts,
            "RestoreIndex": restore}

    def test_output(self):
        self.check_output(atol=1e-5)


class TestRoiPerspectiveTransform(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "roi_perspective_transform"
        # axis-aligned square quad: transform degenerates to bilinear
        # resampling of the box -- oracle via the same matrix math in
        # numpy on an explicit grid
        c, h, w = 2, 8, 8
        x = np.random.rand(1, c, h, w).astype("float32")
        rois = np.array([[1, 1, 5, 1, 5, 5, 1, 5]], np.float32)
        th = tw = 4
        # matrix for an axis-aligned box (est_w == est_h == 4):
        # nw = th; grid maps linearly
        out = np.zeros((1, c, th, tw), np.float32)
        for oy in range(th):
            for ox in range(tw):
                in_x = 1 + ox * (5 - 1) / (tw - 1)
                in_y = 1 + oy * (5 - 1) / (th - 1)
                x0, y0 = int(np.floor(in_x)), int(np.floor(in_y))
                x1, y1 = min(x0 + 1, w - 1), min(y0 + 1, h - 1)
                ax, ay = in_x - x0, in_y - y0
                out[0, :, oy, ox] = (
                    x[0, :, y0, x0] * (1 - ay) * (1 - ax)
                    + x[0, :, y0, x1] * (1 - ay) * ax
                    + x[0, :, y1, x0] * ay * (1 - ax)
                    + x[0, :, y1, x1] * ay * ax)
        self.inputs = {"X": x, "ROIs": rois}
        self.attrs = {"transformed_height": th, "transformed_width": tw,
                      "spatial_scale": 1.0}
        self.outputs = {"Out": out}

    def test_output(self):
        self.check_output(atol=1e-4)


class TestGenerateMaskLabels(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "generate_mask_labels"
        res, ncls = 4, 3
        rois = np.array([[0, 0, 8, 8], [0, 0, 2, 2]], np.float32)
        labels = np.array([1, 0], np.int32)  # roi1 fg cls 1, roi2 bg
        gt_boxes = np.array([[0, 0, 8, 8]], np.float32)
        gt_classes = np.array([1], np.int32)
        # polygon covering the left half of the gt box
        polys = np.array([[[0, 0], [4, 0], [4, 8], [0, 8]]], np.float32)
        poly_len = np.array([4], np.int32)
        masks = np.zeros((2, ncls * res * res), np.int32)
        slab = masks[0].reshape(ncls, res, res)
        # grid centers at x = 1,3,5,7: first two columns inside
        slab[1, :, :2] = 1
        masks[0] = slab.reshape(-1)
        self.inputs = {"Rois": rois, "LabelsInt32": labels,
                       "GtBoxes": gt_boxes, "GtClasses": gt_classes,
                       "GtSegms": polys, "PolyLen": poly_len}
        self.attrs = {"num_classes": ncls, "resolution": res}
        self.outputs = {"MaskRois": rois,
                        "RoiHasMaskInt32": np.array([1, 0], np.int32),
                        "MaskInt32": masks}

    def test_output(self):
        self.check_output(atol=0)


class TestFusionSeqconvEltaddRelu(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "fusion_seqconv_eltadd_relu"
        b, t, d, m = 2, 4, 3, 5
        clen, cstart = 3, -1
        x = np.random.randn(b, t, d).astype("float32")
        w = np.random.randn(clen * d, m).astype("float32")
        bias = np.random.randn(m).astype("float32")
        sl = np.array([3, 4], np.int32)
        xm = x * (np.arange(t)[None, :, None] < sl[:, None, None])
        cols = []
        for i in range(clen):
            off = cstart + i
            sh = np.zeros_like(xm)
            if off < 0:
                sh[:, -off:] = xm[:, :t + off]
            elif off > 0:
                sh[:, :t - off] = xm[:, off:]
            else:
                sh = xm
            cols.append(sh)
        ctxmat = np.concatenate(cols, -1)
        colmat = ctxmat @ w
        colmat = colmat * (np.arange(t)[None, :, None]
                           < sl[:, None, None])
        out = np.maximum(colmat + bias, 0)
        self.inputs = {"X": x, "Filter": w, "Bias": bias, "SeqLen": sl}
        self.attrs = {"contextLength": clen, "contextStart": cstart}
        self.outputs = {"Out": out, "ColMat": colmat}

    def test_output(self):
        self.check_output(atol=1e-5)


class TestBoxDecoderAndAssignPerPriorVar(OpTest):
    """PriorBoxVar as per-prior [N,4] rows (box_coder convention)."""

    def setUp(self):
        super().setUp()
        self.op_type = "box_decoder_and_assign"
        n, c = 3, 2
        prior = np.abs(np.random.rand(n, 4).astype("float32")) * 10
        prior[:, 2:] += prior[:, :2] + 1
        pvar = np.random.uniform(0.05, 0.3, (n, 4)).astype("float32")
        tgt = (np.random.randn(n, c * 4) * 0.3).astype("float32")
        score = np.random.rand(n, c).astype("float32")
        clip = np.log(10.0)
        dec = np.zeros((n, c * 4), np.float32)
        assign = np.zeros((n, 4), np.float32)
        for i in range(n):
            pw = prior[i, 2] - prior[i, 0] + 1
            ph = prior[i, 3] - prior[i, 1] + 1
            pcx = prior[i, 0] + pw / 2
            pcy = prior[i, 1] + ph / 2
            for j in range(c):
                o = j * 4
                dw = min(pvar[i, 2] * tgt[i, o + 2], clip)
                dh = min(pvar[i, 3] * tgt[i, o + 3], clip)
                cx = pvar[i, 0] * tgt[i, o] * pw + pcx
                cy = pvar[i, 1] * tgt[i, o + 1] * ph + pcy
                w = np.exp(dw) * pw
                h = np.exp(dh) * ph
                dec[i, o:o + 4] = [cx - w / 2, cy - h / 2,
                                   cx + w / 2 - 1, cy + h / 2 - 1]
            assign[i] = dec[i, 4:8]  # argmax over classes 1..C-1 == 1
        self.inputs = {"PriorBox": prior, "PriorBoxVar": pvar,
                       "TargetBox": tgt, "BoxScore": score}
        self.attrs = {"box_clip": float(clip)}
        self.outputs = {"DecodeBox": dec, "OutputAssignBox": assign}

    def test_output(self):
        self.check_output(atol=1e-4)


class TestFusionRepeatedFCReluNoBias(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "fusion_repeated_fc_relu"
        x = np.random.randn(4, 5).astype("float32")
        w1 = np.random.randn(5, 6).astype("float32")
        w2 = np.random.randn(6, 3).astype("float32")
        h1 = np.maximum(x @ w1, 0)
        h2 = np.maximum(h1 @ w2, 0)
        self.inputs = {"X": x, "W": [("w1", w1), ("w2", w2)]}
        self.attrs = {}
        self.outputs = {"Out": h2, "ReluOut": [("r1", h1)]}

    def test_output(self):
        self.check_output(atol=1e-5)


def test_custom_reader_decorator():
    from paddle_tpu.core.program import Operator
    from paddle_tpu.core.registry import run_op
    from paddle_tpu.ops.extra_ops3 import (_HOST_READERS,
                                           register_host_reader)
    from paddle_tpu.ops.host_ops import register_py_func

    batches = [(np.full((2, 2), i, np.float32),) for i in range(2)]
    register_host_reader("base_r", lambda: iter(batches))
    fid = register_py_func(lambda b: (b[0] * 2.0,))

    prog = fluid.Program()
    block = prog.global_block
    op = Operator(block, "create_custom_reader",
                  {"UnderlyingReader": ["base_r"]},
                  {"Out": ["deco_r"]}, {"decorator_id": fid})
    run_op(op, {"base_r": np.zeros(1, np.float32)})
    assert "deco_r" in _HOST_READERS
    got = list(_HOST_READERS["deco_r"]["factory"]())
    np.testing.assert_allclose(got[1][0], batches[1][0] * 2.0)


def test_get_places_and_feed_fetch_and_delete_var():
    import jax

    from paddle_tpu.core.program import Operator
    from paddle_tpu.core.registry import run_op

    prog = fluid.Program()
    block = prog.global_block
    op = Operator(block, "get_places", {}, {"Out": ["places"]},
                  {"device_count": 2})
    env = {}
    run_op(op, env)
    assert len(np.asarray(env["places"])) >= 1

    x = np.arange(4, dtype=np.float32)
    for t in ("feed", "fetch"):
        op = Operator(block, t, {"X": ["in"]}, {"Out": ["out"]},
                      {"col": 0})
        env = {"in": x}
        run_op(op, env)
        np.testing.assert_allclose(np.asarray(env["out"]), x)

    op = Operator(block, "delete_var", {"X": ["in"]}, {}, {})
    run_op(op, {"in": x})  # no outputs, must not raise


def test_read_op_and_custom_reader():
    from paddle_tpu.core.program import Operator
    from paddle_tpu.core.registry import run_op
    from paddle_tpu.ops.extra_ops3 import register_host_reader

    prog = fluid.Program()
    block = prog.global_block
    block.create_var(name="img", shape=(2, 3), dtype="float32")
    block.create_var(name="lbl", shape=(2, 1), dtype="int64")

    batches = [
        (np.full((2, 3), i, np.float32),
         np.full((2, 1), i, np.int64)) for i in range(3)]
    register_host_reader("r0", lambda: iter(batches))

    op = Operator(block, "read", {"Reader": ["r0"]},
                  {"Out": ["img", "lbl"]}, {})
    env = {"r0": np.zeros(1, np.float32)}
    run_op(op, env)
    np.testing.assert_allclose(np.asarray(env["img"]),
                               batches[0][0])
    run_op(op, env)
    np.testing.assert_allclose(np.asarray(env["lbl"]),
                               batches[1][1])
    # exhaustion restarts
    run_op(op, env)
    run_op(op, env)
    np.testing.assert_allclose(np.asarray(env["img"]),
                               batches[0][0])


if __name__ == "__main__":
    import pytest as _pytest

    _pytest.main([__file__, "-q"])


def test_reader_op_family_pipeline():
    """recordio file -> parse -> shuffle -> batch -> multi_pass ->
    double_buffer -> read op (reference reader op chain,
    operators/reader/)."""
    import tempfile, os as _os

    from paddle_tpu import native
    from paddle_tpu.core.program import Operator
    from paddle_tpu.core.registry import run_op
    from paddle_tpu.ops.extra_ops3 import _HOST_READERS
    from paddle_tpu.ops.host_ops import register_py_func

    prog = fluid.Program()
    block = prog.global_block

    with tempfile.TemporaryDirectory() as d:
        path = _os.path.join(d, "data.recordio")
        w = native.RecordIOWriter(path)
        for i in range(8):
            w.write(bytes([i]))
        w.close()

        pid = register_py_func(
            lambda rec: (np.full((2,), rec[0], np.float32),))

        def op(type_, ins, outs, attrs):
            o = Operator(block, type_, ins, outs, attrs)
            run_op(o, {n: np.zeros(1, np.float32)
                       for ns in ins.values() for n in ns})

        op("create_recordio_file_reader", {}, {"Out": ["file_r"]},
           {"filename": path, "parser_id": pid})
        op("create_shuffle_reader", {"UnderlyingReader": ["file_r"]},
           {"Out": ["shuf_r"]}, {"buffer_size": 4, "seed": 7})
        op("create_batch_reader", {"UnderlyingReader": ["shuf_r"]},
           {"Out": ["batch_r"]}, {"batch_size": 2})
        op("create_multi_pass_reader", {"UnderlyingReader": ["batch_r"]},
           {"Out": ["mp_r"]}, {"pass_num": 2})
        op("create_double_buffer_reader", {"UnderlyingReader": ["mp_r"]},
           {"Out": ["db_r"]}, {"buffer_size": 2})

        batches = list(_HOST_READERS["db_r"]["factory"]())
        # 8 samples -> 4 batches/pass -> 2 passes
        assert len(batches) == 8
        assert batches[0][0].shape == (2, 2)
        seen = sorted({int(v) for b in batches for v in b[0].ravel()})
        assert seen == list(range(8))

        # the read op pops through the io_callback bridge
        block.create_var(name="vals", shape=(2, 2), dtype="float32")
        rd = Operator(block, "read", {"Reader": ["db_r"]},
                      {"Out": ["vals"]}, {})
        env = {"db_r": np.zeros(1, np.float32)}
        run_op(rd, env)
        assert np.asarray(env["vals"]).shape == (2, 2)


def test_create_py_reader_and_open_files():
    import tempfile, os as _os

    from paddle_tpu import native
    from paddle_tpu.core.program import Operator
    from paddle_tpu.core.registry import run_op
    from paddle_tpu.ops.extra_ops3 import (_HOST_READERS,
                                           register_host_reader)

    prog = fluid.Program()
    block = prog.global_block

    batches = [(np.full((3,), i, np.float32),) for i in range(2)]
    register_host_reader("gen_src", lambda: iter(batches))
    op = Operator(block, "create_py_reader", {}, {"Out": ["py_r"]},
                  {"source": "gen_src"})
    run_op(op, {})
    got = list(_HOST_READERS["py_r"]["factory"]())
    assert len(got) == 2

    with tempfile.TemporaryDirectory() as d:
        paths = []
        for f in range(2):
            p = _os.path.join(d, f"f{f}.recordio")
            w = native.RecordIOWriter(p)
            for i in range(3):
                w.write(bytes([f * 3 + i]))
            w.close()
            paths.append(p)
        op = Operator(block, "open_files", {}, {"Out": ["files_r"]},
                      {"file_names": paths})
        run_op(op, {})
        recs = [r[0] for r in _HOST_READERS["files_r"]["factory"]()]
        assert [b[0] for b in recs] == list(range(6))


def test_batch_reader_keeps_partial_tail_and_shuffle_reshuffles():
    from paddle_tpu.core.program import Operator
    from paddle_tpu.core.registry import run_op
    from paddle_tpu.ops.extra_ops3 import (_HOST_READERS,
                                           register_host_reader)

    prog = fluid.Program()
    block = prog.global_block
    samples = [(np.full((1,), i, np.float32),) for i in range(9)]
    register_host_reader("src9", lambda: iter(samples))
    op = Operator(block, "create_batch_reader",
                  {"UnderlyingReader": ["src9"]}, {"Out": ["b9"]},
                  {"batch_size": 2})
    run_op(op, {"src9": np.zeros(1, np.float32)})
    got = list(_HOST_READERS["b9"]["factory"]())
    assert len(got) == 5 and got[-1][0].shape == (1, 1)  # tail kept

    op = Operator(block, "create_batch_reader",
                  {"UnderlyingReader": ["src9"]}, {"Out": ["b9d"]},
                  {"batch_size": 2, "drop_last": True})
    run_op(op, {"src9": np.zeros(1, np.float32)})
    assert len(list(_HOST_READERS["b9d"]["factory"]())) == 4

    # shuffle order must differ across passes (persistent engine)
    register_host_reader("src16", lambda: iter(
        [(np.full((1,), i, np.float32),) for i in range(16)]))
    op = Operator(block, "create_shuffle_reader",
                  {"UnderlyingReader": ["src16"]}, {"Out": ["sh16"]},
                  {"buffer_size": 16, "seed": 11})
    run_op(op, {"src16": np.zeros(1, np.float32)})
    pass1 = [int(x[0][0]) for x in _HOST_READERS["sh16"]["factory"]()]
    pass2 = [int(x[0][0]) for x in _HOST_READERS["sh16"]["factory"]()]
    assert sorted(pass1) == sorted(pass2) == list(range(16))
    assert pass1 != pass2


def test_double_buffer_propagates_reader_errors():
    from paddle_tpu.core.program import Operator
    from paddle_tpu.core.registry import run_op
    from paddle_tpu.ops.extra_ops3 import (_HOST_READERS,
                                           register_host_reader)

    def bad():
        yield (np.zeros((1,), np.float32),)
        raise IOError("corrupt record")

    register_host_reader("bad_src", bad)
    prog = fluid.Program()
    op = Operator(prog.global_block, "create_double_buffer_reader",
                  {"UnderlyingReader": ["bad_src"]}, {"Out": ["db_bad"]},
                  {"buffer_size": 2})
    run_op(op, {"bad_src": np.zeros(1, np.float32)})
    it = _HOST_READERS["db_bad"]["factory"]()
    next(it)
    with pytest.raises(IOError, match="corrupt record"):
        next(it)


def test_swce_ignore_index_paths_agree():
    import jax
    import jax.numpy as jnp

    from paddle_tpu.core.program import Operator
    from paddle_tpu.core.registry import run_op
    from paddle_tpu.core.program import grad_var_name
    from paddle_tpu.core.registry import make_grad_ops
    from paddle_tpu.ops.pallas import attention as fa

    fa.force_interpret(True)
    try:
        import os

        n, v = 64, 256
        r = np.random.RandomState(8)
        logits = r.randn(n, v).astype(np.float32)
        label = r.randint(0, v, (n, 1)).astype(np.int64)
        label[::4] = -100  # ignored rows
        prog = fluid.Program()
        block = prog.global_block
        block.create_var(name="lg", shape=(n, v), dtype="float32")
        block.create_var(name="lb", shape=(n, 1), dtype="int64")
        op = Operator(block, "softmax_with_cross_entropy",
                      {"Logits": ["lg"], "Label": ["lb"]},
                      {"Loss": ["loss"], "Softmax": ["sm"]},
                      {"ignore_index": -100})

        def run_path(disable):
            if disable:
                os.environ["PADDLE_TPU_DISABLE_PALLAS_XENT"] = "1"
            try:
                env = {"lg": jnp.asarray(logits),
                       "lb": jnp.asarray(label)}
                run_op(op, env)
                genv = dict(env)
                genv[grad_var_name("loss")] = jnp.ones((n, 1),
                                                       jnp.float32)
                genv[grad_var_name("sm")] = jnp.zeros((n, v),
                                                      jnp.float32)
                for gop in make_grad_ops(op, no_grad_set={"lb"}):
                    run_op(gop, genv)
                return (np.asarray(env["loss"]),
                        np.asarray(genv[grad_var_name("lg")]))
            finally:
                os.environ.pop("PADDLE_TPU_DISABLE_PALLAS_XENT", None)

        loss_p, grad_p = run_path(disable=False)
        loss_j, grad_j = run_path(disable=True)
        assert np.all(loss_p[::4] == 0.0)
        assert np.all(grad_p[::4] == 0.0)
        np.testing.assert_allclose(loss_p, loss_j, atol=1e-5, rtol=1e-5)
        np.testing.assert_allclose(grad_p, grad_j, atol=1e-5, rtol=1e-5)
    finally:
        fa.force_interpret(False)


def _np_deform_conv(x, offset, w, mask, stride, pad, dilation, groups,
                    dg):
    """Direct-loop numpy oracle for deformable_conv (bilinear sampling
    with zero outside the image)."""
    B, C, H, W = x.shape
    F, _, kh, kw = w.shape
    K = kh * kw
    Ho = (H + 2 * pad - (dilation * (kh - 1) + 1)) // stride + 1
    Wo = (W + 2 * pad - (dilation * (kw - 1) + 1)) // stride + 1
    off = offset.reshape(B, dg, K, 2, Ho, Wo)
    out = np.zeros((B, F, Ho, Wo), np.float64)

    def sample(b, c, y, xx):
        y0, x0 = int(np.floor(y)), int(np.floor(xx))
        v = 0.0
        for dy in (0, 1):
            for dx in (0, 1):
                yi, xi = y0 + dy, x0 + dx
                if 0 <= yi < H and 0 <= xi < W:
                    wgt = (1 - abs(y - yi)) * (1 - abs(xx - xi))
                    v += wgt * x[b, c, yi, xi]
        return v

    cg = C // groups
    fg = F // groups
    for b in range(B):
        for f in range(F):
            g = f // fg
            for ho in range(Ho):
                for wo in range(Wo):
                    acc = 0.0
                    for i in range(kh):
                        for j in range(kw):
                            k = i * kw + j
                            for cc in range(cg):
                                c = g * cg + cc
                                d = c // (C // dg)
                                y = (ho * stride - pad + i * dilation +
                                     off[b, d, k, 0, ho, wo])
                                xx = (wo * stride - pad + j * dilation +
                                      off[b, d, k, 1, ho, wo])
                                v = sample(b, c, y, xx)
                                if mask is not None:
                                    v *= mask.reshape(
                                        B, dg, K, Ho, Wo)[b, d, k, ho, wo]
                                acc += v * w[f, cc, i, j]
                    out[b, f, ho, wo] = acc
    return out.astype(x.dtype)


class TestDeformableConvV1(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "deformable_conv"
        rng = np.random.RandomState(11)
        x = rng.randn(2, 4, 5, 5).astype(np.float32)
        w = rng.randn(3, 4, 3, 3).astype(np.float32)
        # keep offsets off integer lattice points (fd-grad stability)
        offset = (rng.rand(2, 2 * 2 * 9, 5, 5).astype(np.float32)
                  * 0.8 + 0.1)
        attrs = dict(stride=1, pad=1, dilation=1, groups=1, dg=2)
        out = _np_deform_conv(x, offset, w, None, **attrs)
        self.inputs = {"Input": x, "Offset": offset, "Filter": w}
        self.attrs = {"strides": [1, 1], "paddings": [1, 1],
                      "dilations": [1, 1], "groups": 1,
                      "deformable_groups": 2}
        self.outputs = {"Output": out}

    def test_output(self):
        self.check_output(atol=1e-4, rtol=1e-4)

    def test_grad(self):
        self.check_grad(["Input", "Offset", "Filter"], "Output",
                        max_relative_error=0.02)


class TestDeformableConvV2Modulated(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "deformable_conv"
        rng = np.random.RandomState(12)
        x = rng.randn(1, 2, 4, 4).astype(np.float32)
        w = rng.randn(4, 1, 3, 3).astype(np.float32)  # groups=2
        offset = (rng.rand(1, 2 * 1 * 9, 2, 2).astype(np.float32)
                  * 0.8 + 0.1)
        mask = rng.rand(1, 1 * 9, 2, 2).astype(np.float32)
        out = _np_deform_conv(x, offset, w, mask, stride=2, pad=1,
                              dilation=1, groups=2, dg=1)
        self.inputs = {"Input": x, "Offset": offset, "Filter": w,
                       "Mask": mask}
        self.attrs = {"strides": [2, 2], "paddings": [1, 1],
                      "dilations": [1, 1], "groups": 2,
                      "deformable_groups": 1}
        self.outputs = {"Output": out}

    def test_output(self):
        self.check_output(atol=1e-4, rtol=1e-4)


class TestDeformableConvZeroOffsetIsConv:
    """Zero offsets + all-ones mask must reduce to plain conv2d."""

    def test_matches_conv2d(self):
        import paddle_tpu as fluid

        rng = np.random.RandomState(13)
        xv = rng.randn(2, 3, 6, 6).astype(np.float32)
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            x = fluid.layers.data(name="x", shape=[3, 6, 6],
                                  dtype="float32")
            off = fluid.layers.fill_constant([2, 18, 6, 6], "float32",
                                             0.0)
            dc = fluid.layers.deformable_conv(
                x, off, num_filters=5, filter_size=3, padding=1,
                param_attr=fluid.ParamAttr(name="wshared"),
                bias_attr=False)
            c = fluid.layers.conv2d(
                x, num_filters=5, filter_size=3, padding=1,
                param_attr=fluid.ParamAttr(name="wshared"),
                bias_attr=False)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        a, b = exe.run(prog, feed={"x": xv}, fetch_list=[dc, c])
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


# ---------------------------------------------------------------------------
# r14 sampling/speculative kernels (ops/spec_ops.py). All are
# differentiable=False, so these are forward numpy-oracle checks; the
# stochastic draws are pinned through their DETERMINISTIC regimes
# (greedy one-hot distributions make spec_accept and
# sample_categorical exact — the kernel docstrings' design point).
# ---------------------------------------------------------------------------
def _np_filtered_softmax(logits, temperature, top_k, top_p):
    v = logits.shape[-1]
    if temperature == 0.0:
        out = np.zeros_like(logits, dtype=np.float32)
        np.put_along_axis(out, logits.argmax(-1)[..., None], 1.0, -1)
        return out
    z = (logits / temperature).astype(np.float32)
    if top_k and 0 < top_k < v:
        kth = np.sort(z, axis=-1)[..., -top_k][..., None]
        z = np.where(z >= kth, z, -np.inf)
    e = np.exp(z - np.nanmax(np.where(np.isfinite(z), z, np.nan),
                             axis=-1, keepdims=True))
    e = np.where(np.isfinite(z), e, 0.0)
    p = e / e.sum(-1, keepdims=True)
    if top_p and top_p < 1.0:
        ps = np.sort(p, axis=-1)[..., ::-1]
        cs = np.cumsum(ps, axis=-1)
        keep = (cs - ps) < top_p
        cutoff = np.min(np.where(keep, ps, np.inf), axis=-1,
                        keepdims=True)
        p = np.where(p >= cutoff, p, 0.0)
        p = p / p.sum(-1, keepdims=True)
    return p


class TestFilteredSoftmaxGreedy(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "filtered_softmax"
        x = np.random.RandomState(3).randn(4, 9).astype(np.float32)
        self.inputs = {"X": x}
        self.attrs = {"temperature": 0.0}
        self.outputs = {"Out": _np_filtered_softmax(x, 0.0, 0, 1.0)}

    def test_output(self):
        self.check_output()


class TestFilteredSoftmaxTopKTopP(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "filtered_softmax"
        x = np.random.RandomState(5).randn(6, 11).astype(np.float32)
        self.attrs = {"temperature": 1.7, "top_k": 5, "top_p": 0.8}
        self.inputs = {"X": x}
        self.outputs = {"Out": _np_filtered_softmax(x, 1.7, 5, 0.8)}

    def test_output(self):
        self.check_output(atol=1e-5)


class TestSampleCategoricalDegenerate(OpTest):
    """One-hot distributions: the categorical draw is (for every
    practical key) the hot index — the exact property greedy
    speculative decoding's token-exactness rests on."""

    def setUp(self):
        super().setUp()
        self.op_type = "sample_categorical"
        hot = np.array([2, 0, 5, 5], np.int64)
        probs = np.zeros((4, 6), np.float32)
        probs[np.arange(4), hot] = 1.0
        self.inputs = {"Probs": probs,
                       "Seed": np.array([7, 8, 9, 9], np.int64),
                       "Pos": np.array([1, 2, 3, 4], np.int64)}
        self.attrs = {"noise_tag": 3, "base_seed": 11}
        self.outputs = {"Out": hot}

    def test_output(self):
        self.check_output()


class TestSpanScatter(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "span_scatter"
        buf = np.arange(24, dtype=np.int64).reshape(3, 8)
        vals = np.array([[90, 91, 92], [80, 81, 82],
                         [70, 71, 72]], np.int64)
        start = np.array([2, 6, 0], np.int64)
        count = np.array([3, 0, 4], np.int64)  # row2: count > width
        want = buf.copy()
        want[0, 2:5] = [90, 91, 92]
        want[2, 0:3] = [70, 71, 72]  # clipped at vals width 3
        self.inputs = {"X": buf, "Vals": vals, "Start": start,
                       "Count": count}
        self.outputs = {"Out": want}

    def test_output(self):
        self.check_output()


class TestSpecAcceptGreedy(OpTest):
    """Greedy (one-hot) acceptance oracle covering the edge cases:
    full acceptance + bonus, first-position rejection, EOS clip
    INSIDE the accepted prefix, EOS at the bonus slot, and the
    buffer-room clip."""

    def setUp(self):
        super().setUp()
        self.op_type = "spec_accept"
        K, V, END, MAXL = 3, 7, 1, 16

        def oh(rows):
            out = np.zeros((len(rows), len(rows[0]), V), np.float32)
            for r, toks in enumerate(rows):
                for j, t in enumerate(toks):
                    out[r, j, t] = 1.0
            return out

        props = np.array([
            [4, 5, 6],   # r0: all accepted, bonus 3 -> adv 4
            [4, 5, 6],   # r1: target wants 2 at j=0 -> adv 1, tok 2
            [4, 1, 6],   # r2: accepts 4 then EOS at j=1 -> adv 2, fin
            [4, 5, 6],   # r3: all accepted, BONUS is EOS -> adv 4, fin
            [4, 5, 6],   # r4: room clip (pos=13 -> room 2) -> adv 2
        ], np.int64)
        tprobs = oh([[4, 5, 6, 3],
                     [2, 5, 6, 3],
                     [4, 1, 6, 3],
                     [4, 5, 6, 1],
                     [4, 5, 6, 3]])
        dprobs = oh([p for p in props])
        pos = np.array([0, 0, 0, 0, 13], np.int64)
        self.inputs = {"Proposals": props, "DraftProbs": dprobs,
                       "TargetProbs": tprobs,
                       "Seed": np.arange(5, dtype=np.int64),
                       "Pos": pos}
        self.attrs = {"k": K, "end_id": END, "max_len": MAXL,
                      "greedy": True, "base_seed": 0, "noise_tag": 0}
        self.outputs = {
            "Advance": np.array([4, 1, 2, 4, 2], np.int64),
            "Tokens": np.array([
                [4, 5, 6, 3],
                [2, 5, 6, 0],   # correction replaces slot 0
                [4, 1, 6, 3],   # EOS proposal is ACCEPTED (a=3, the
                #                 bonus fills slot 3); the clip only
                #                 shortens Advance/latches Fin
                [4, 5, 6, 1],
                [4, 5, 6, 3]], np.int64),
            "Accepted": np.array([3, 0, 2, 3, 2], np.int64),
            "Fin": np.array([0, 0, 1, 1, 0], np.int64),
        }

    def test_output(self):
        self.check_output()
