"""Transpiler tests.

Parity model: reference tests/unittests/test_dist_transpiler.py
(program-inspection of transpiled trainer/pserver programs) plus an
executable sync-mode loss-parity oracle in the spirit of
test_dist_base.py:236 (local run vs distributed run must match) — run
in-process through the io_callback host bridge instead of subprocesses.
"""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.transpiler import (DistributeTranspiler,
                                   DistributeTranspilerConfig, HashName,
                                   RoundRobin, memory_optimize,
                                   pserver_runtime)

PSERVERS = "127.0.0.1:6174,127.0.0.1:6175"
EPS = PSERVERS.split(",")


def _build_model(hidden=64, lr=0.1, optimizer="sgd"):
    x = fluid.layers.data(name="x", shape=[16], dtype="float32")
    y = fluid.layers.data(name="y", shape=[1], dtype="float32")
    h = fluid.layers.fc(input=x, size=hidden, act="relu")
    pred = fluid.layers.fc(input=h, size=1)
    loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
    if optimizer == "sgd":
        opt = fluid.optimizer.SGDOptimizer(learning_rate=lr)
    else:
        opt = fluid.optimizer.AdamOptimizer(learning_rate=lr)
    opt.minimize(loss)
    return loss


def _batches(n, bs=32, seed=3):
    rng = np.random.RandomState(seed)
    w = rng.randn(16, 1).astype(np.float32)
    for _ in range(n):
        xs = rng.randn(bs, 16).astype(np.float32)
        ys = xs @ w + 0.1 * rng.randn(bs, 1).astype(np.float32)
        yield xs, ys


class TestPSDispatcher:
    def test_round_robin(self):
        d = RoundRobin(EPS)
        assert d.dispatch(list("abcd")) == [EPS[0], EPS[1], EPS[0],
                                            EPS[1]]

    def test_hash_stable(self):
        d = HashName(EPS)

        class V:
            def __init__(self, n):
                self.name = n

        a = d.dispatch([V("w1"), V("w2"), V("w3")])
        b = d.dispatch([V("w1"), V("w2"), V("w3")])
        assert a == b


class TestTranspileStructure:
    def test_trainer_program_ops(self):
        _build_model()
        cfg = DistributeTranspilerConfig()
        cfg.slice_var_up = False
        t = DistributeTranspiler(cfg)
        t.transpile(0, pservers=PSERVERS, trainers=1)
        types = [op.type for op in
                 t.get_trainer_program().global_block.ops]
        assert "sgd" not in types  # optimize ops moved to pservers
        assert "send" in types and "recv" in types
        assert types.index("send") < types.index("send_barrier") \
            < types.index("recv") < types.index("fetch_barrier")

    def test_pserver_program_structure(self):
        _build_model()
        cfg = DistributeTranspilerConfig()
        cfg.slice_var_up = False
        t = DistributeTranspiler(cfg)
        t.transpile(0, pservers=PSERVERS, trainers=2)
        total_blocks = 0
        for ep in EPS:
            ps = t.get_pserver_program(ep)
            ls = ps.global_block.ops[0]
            assert ls.type == "listen_and_serv"
            assert ls.attr("Fanin") == 2
            assert ls.attr("sync_mode") is True
            n = len(ls.attr("grad_to_block_id"))
            total_blocks += n
            for entry in ls.attr("grad_to_block_id"):
                idx = int(entry.rsplit(":", 1)[1])
                blk = ps.blocks[idx]
                assert any(o.type in ("sgd", "adam") for o in blk.ops)
        # 4 params (2 fc layers w+b) spread over both endpoints
        assert total_blocks == 4
        for ep in EPS:
            assert len(t.ep_blocks[ep]) > 0

    def test_slice_var_up_splits_large_params(self):
        _build_model(hidden=256)
        cfg = DistributeTranspilerConfig()
        cfg.min_block_size = 512
        t = DistributeTranspiler(cfg)
        t.transpile(0, pservers=PSERVERS, trainers=1)
        w_blocks = [bs for name, bs in t.param_blocks.items()
                    if len(bs) > 1]
        assert w_blocks, "large fc weight should be sliced"
        types = [op.type for op in
                 t.get_trainer_program().global_block.ops]
        assert "split_byref" in types and "concat" in types

    def test_collective_mode_inserts_allreduce(self):
        _build_model()
        cfg = DistributeTranspilerConfig()
        cfg.mode = "collective"
        t = DistributeTranspiler(cfg)
        t.transpile(0, trainers=4)
        types = [op.type for op in
                 t.get_trainer_program().global_block.ops]
        # one allreduce per gradient (4 params: 2 fc layers w+b),
        # placed before the first optimize op
        assert types.count("allreduce") == 4
        first_opt = types.index("sgd")
        assert all(i < first_opt for i, tt in enumerate(types)
                   if tt == "allreduce")

    def test_collective_single_trainer_untouched(self):
        _build_model()
        before = len(fluid.default_main_program().global_block.ops)
        cfg = DistributeTranspilerConfig()
        cfg.mode = "collective"
        t = DistributeTranspiler(cfg)
        t.transpile(0, trainers=1)
        assert len(t.get_trainer_program().global_block.ops) == before


class TestExecutableSyncParity:
    """Loss parity: local program vs transpiled trainer+pserver pair
    (the reference's test_dist_base oracle, in-process)."""

    def _run_local(self, steps, optimizer):
        loss = _build_model(optimizer=optimizer)
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(fluid.default_startup_program())
        out = []
        for xs, ys in _batches(steps):
            l, = exe.run(feed={"x": xs, "y": ys}, fetch_list=[loss])
            out.append(float(np.asarray(l)))
        return out

    def _run_dist(self, steps, optimizer, slice_up):
        pserver_runtime.reset_endpoints()
        loss = _build_model(optimizer=optimizer)
        cfg = DistributeTranspilerConfig()
        cfg.slice_var_up = slice_up
        cfg.min_block_size = 16
        t = DistributeTranspiler(cfg)
        t.transpile(0, pservers=PSERVERS, trainers=1)
        for ep in EPS:
            pserver_runtime.configure_endpoint(
                ep, t.get_pserver_program(ep), num_trainers=1,
                sync_mode=True)
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(t.get_startup_program())
        trainer_prog = t.get_trainer_program()
        out = []
        for xs, ys in _batches(steps):
            l, = exe.run(trainer_prog, feed={"x": xs, "y": ys},
                         fetch_list=[loss.name])
            out.append(float(np.asarray(l)))
        return out

    @pytest.mark.parametrize("optimizer", ["sgd", "adam"])
    def test_sync_loss_parity(self, optimizer):
        local = self._run_local(8, optimizer)
        import paddle_tpu.core.program as prog_mod
        import paddle_tpu.unique_name as unique_name

        prog_mod._main_program = fluid.Program()
        prog_mod._startup_program = fluid.Program()
        fluid._reset_global_scope()
        unique_name.switch()
        fluid.seed(90)
        np.random.seed(90)
        dist = self._run_dist(8, optimizer, slice_up=False)
        assert local[0] == pytest.approx(dist[0], rel=1e-4)
        np.testing.assert_allclose(local, dist, rtol=2e-3, atol=1e-4)

    def test_sliced_params_parity(self):
        local = self._run_local(6, "sgd")
        import paddle_tpu.core.program as prog_mod
        import paddle_tpu.unique_name as unique_name

        prog_mod._main_program = fluid.Program()
        prog_mod._startup_program = fluid.Program()
        fluid._reset_global_scope()
        unique_name.switch()
        fluid.seed(90)
        np.random.seed(90)
        dist = self._run_dist(6, "sgd", slice_up=True)
        np.testing.assert_allclose(local, dist, rtol=2e-3, atol=1e-4)

    def test_two_trainers_threaded_sync(self):
        """2 trainers in threads (the reference launches subprocesses,
        test_dist_base.py:382): blocking barrier => both trainers see
        the merged update; their params stay identical every step."""
        import threading

        pserver_runtime.reset_endpoints()
        loss = _build_model(optimizer="sgd")
        base_main = fluid.default_main_program()
        base_startup = fluid.default_startup_program()
        progs = []
        for tid in range(2):
            cfg = DistributeTranspilerConfig()
            cfg.slice_var_up = False
            t = DistributeTranspiler(cfg)
            t.transpile(tid, program=base_main, pservers=PSERVERS,
                        trainers=2, startup_program=base_startup)
            progs.append(t)
        for ep in EPS:
            pserver_runtime.configure_endpoint(
                ep, progs[0].get_pserver_program(ep), num_trainers=2,
                sync_mode=True)
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(progs[0].get_startup_program())  # trainer 0 pushes init

        data = list(_batches(4))
        results = [None, None]
        errors = []

        def run_trainer(tid):
            try:
                my_exe = fluid.Executor(fluid.TPUPlace(0))
                scope = fluid.Scope()
                # each trainer starts from the same global params
                from paddle_tpu.core.scope import global_scope

                for n in global_scope().local_var_names():
                    v = global_scope()._get(n)
                    if v is not None:
                        scope.var(n)
                        # copy: the donated step buffers must not be
                        # shared between trainer scopes
                        scope._set(n, np.array(np.asarray(v)))
                out = []
                for xs, ys in data:
                    l, = my_exe.run(progs[tid].get_trainer_program(),
                                    feed={"x": xs, "y": ys},
                                    fetch_list=[loss.name], scope=scope)
                    out.append(float(np.asarray(l)))
                results[tid] = (out, {
                    n: np.asarray(scope._get(n))
                    for n in scope.local_var_names()
                    if n.startswith("fc_") and scope._get(n) is not None})
            except BaseException as e:  # surface thread failures
                errors.append(e)

        ths = [threading.Thread(target=run_trainer, args=(i,))
               for i in range(2)]
        for th in ths:
            th.start()
        for th in ths:
            th.join(timeout=120)
        assert not errors, errors
        assert results[0] and results[1]
        # both trainers fed identical data -> identical losses, and the
        # merged sync update keeps their params in lockstep
        np.testing.assert_allclose(results[0][0], results[1][0],
                                   rtol=1e-5)
        for n in results[0][1]:
            if n in results[1][1]:
                np.testing.assert_allclose(
                    results[0][1][n], results[1][1][n], rtol=1e-5,
                    err_msg=f"param {n} diverged between trainers")

    def test_async_mode_trains(self):
        pserver_runtime.reset_endpoints()
        loss = _build_model(optimizer="sgd")
        cfg = DistributeTranspilerConfig()
        cfg.slice_var_up = False
        t = DistributeTranspiler(cfg)
        t.transpile(0, pservers=PSERVERS, trainers=1, sync_mode=False)
        for ep in EPS:
            pserver_runtime.configure_endpoint(
                ep, t.get_pserver_program(ep), num_trainers=1,
                sync_mode=False)
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(t.get_startup_program())
        losses = []
        for xs, ys in _batches(20):
            l, = exe.run(t.get_trainer_program(),
                         feed={"x": xs, "y": ys},
                         fetch_list=[loss.name])
            losses.append(float(np.asarray(l)))
        assert np.mean(losses[-3:]) < np.mean(losses[:3])


class TestMemoryOptimize:
    def test_plan_reports_savings(self):
        _build_model()
        prog = fluid.default_main_program()
        plan = memory_optimize(prog, level=1)
        assert plan["bytes_saved"] >= 0
        assert hasattr(prog, "_memory_optimize_plan")

    def test_skip_set_respected(self):
        _build_model()
        prog = fluid.default_main_program()
        all_tmp = [n for n in prog.global_block.vars]
        plan = memory_optimize(prog, skip_opt_set=set(all_tmp))
        assert plan["pairs"] == []
