"""fluid.layers.* on dygraph VarBase (reference framework.py:1633
Block.append_op traces through the dygraph tracer when
_in_dygraph_mode(); layer_helper.py creates eager variables/params)."""
import numpy as np

import paddle_tpu as fluid
from paddle_tpu.dygraph.base import VarBase


def _data():
    rng = np.random.RandomState(0)
    xs = rng.randn(32, 8).astype(np.float32)
    w = rng.randn(8, 3).astype(np.float32)
    ys = np.argmax(xs @ w, 1).astype(np.int64)[:, None]
    return xs, ys


class TestFunctionalLayersInDygraph:
    def test_reduce_mean_returns_varbase_and_backprops(self):
        xs, _ = _data()
        with fluid.dygraph.guard():
            lin = fluid.dygraph.Linear(8, 3)
            out = lin(fluid.dygraph.to_variable(xs))
            loss = fluid.layers.reduce_mean(out)
            assert isinstance(loss, VarBase)
            loss.backward()
            g = lin.weight.gradient()
            assert g is not None and np.abs(g).sum() > 0

    def test_softmax_with_cross_entropy(self):
        xs, ys = _data()
        with fluid.dygraph.guard():
            lin = fluid.dygraph.Linear(8, 3)
            out = lin(fluid.dygraph.to_variable(xs))
            ce = fluid.layers.softmax_with_cross_entropy(
                out, fluid.dygraph.to_variable(ys))
            loss = fluid.layers.mean(ce)
            assert int(np.prod(loss.shape or (1,))) == 1
            loss.backward()
            assert lin.weight.gradient() is not None

    def test_activation_and_elementwise(self):
        xs, _ = _data()
        with fluid.dygraph.guard():
            xv = fluid.dygraph.to_variable(xs)
            r = fluid.layers.relu(xv)
            np.testing.assert_allclose(r.numpy(),
                                       np.maximum(xs, 0), rtol=1e-6)
            s = fluid.layers.elementwise_add(r, xv)
            np.testing.assert_allclose(s.numpy(),
                                       np.maximum(xs, 0) + xs,
                                       rtol=1e-6)


class TestParamLayersInDygraph:
    def test_fc_creates_eager_params_and_trains(self):
        xs, ys = _data()
        with fluid.dygraph.guard():
            xv = fluid.dygraph.to_variable(xs)
            yv = fluid.dygraph.to_variable(ys)
            h = fluid.layers.fc(xv, size=3)
            assert isinstance(h, VarBase) and h.shape == (32, 3)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(h, yv))
            loss.backward()

    def test_graph_mode_unaffected(self):
        # the dispatch must not leak into graph mode
        xs, ys = _data()
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            x = fluid.layers.data(name="x", shape=[8], dtype="float32")
            out = fluid.layers.fc(x, size=3)
        assert not isinstance(out, VarBase)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        got, = exe.run(prog, feed={"x": xs}, fetch_list=[out])
        assert got.shape == (32, 3)


def test_dygraph_nce_trains():
    """dygraph NCE (reference dygraph/nn.py NCE signature): eager cost,
    and backward gradients land ONLY on the rows the forward sampled
    (the vjp recomputation replays the forward's PRNG key)."""
    import paddle_tpu as fluid
    from paddle_tpu.dygraph import base as dybase

    with fluid.dygraph.guard():
        nce = fluid.dygraph.NCE(num_total_classes=50,
                                num_neg_samples=5)
        rng = np.random.RandomState(0)
        x = fluid.dygraph.to_variable(rng.rand(4, 8).astype("float32"))
        lbl = fluid.dygraph.to_variable(
            rng.randint(0, 50, (4, 1)).astype("int64"))
        cost = nce(x, lbl)
        assert cost.numpy().shape == (4, 1)
        # the tape's last entry holds the forward's SampleLabels
        op, ins, outs = dybase.tracer()._tape[-1]
        sampled = set(np.asarray(
            outs["SampleLabels"][0].value).ravel().tolist())
        cost.backward()
        g = np.asarray(nce.weight.gradient())
        grad_rows = set(np.where(np.abs(g).sum(1) > 0)[0].tolist())
        assert grad_rows, "no gradient reached the nce weight"
        assert grad_rows <= sampled, (
            f"grads on unsampled rows: {sorted(grad_rows - sampled)}")


def test_dygraph_nce_bias_attr_false():
    import paddle_tpu as fluid

    with fluid.dygraph.guard():
        nce = fluid.dygraph.NCE(num_total_classes=20,
                                num_neg_samples=3, bias_attr=False)
        x = fluid.dygraph.to_variable(
            np.random.RandomState(0).rand(2, 4).astype("float32"))
        lbl = fluid.dygraph.to_variable(
            np.array([[1], [2]], np.int64))
        _ = nce(x, lbl)
        assert nce.bias is None


class TestNewDygraphLayers:
    """BilinearTensorProduct / Conv2DTranspose / SequenceConv
    (reference dygraph/nn.py:1025,1117,1329) with numpy oracles and
    grad flow."""

    def test_bilinear_tensor_product(self):
        rng = np.random.RandomState(1)
        x = rng.randn(5, 3).astype(np.float32)
        y = rng.randn(5, 4).astype(np.float32)
        with fluid.dygraph.guard():
            layer = fluid.dygraph.BilinearTensorProduct(
                input1_dim=3, input2_dim=4, output_dim=2)
            out = layer(fluid.dygraph.to_variable(x),
                        fluid.dygraph.to_variable(y))
            w = layer.weight.numpy()
            b = layer.bias.numpy().reshape(-1)
            ref = np.einsum("bi,kij,bj->bk", x, w, y) + b
            np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5,
                                       atol=1e-5)
            loss = fluid.layers.reduce_mean(out)
            loss.backward()
            assert np.abs(layer.weight.gradient()).sum() > 0

    def test_conv2d_transpose_shape_and_grad(self):
        rng = np.random.RandomState(2)
        x = rng.randn(2, 3, 4, 4).astype(np.float32)
        with fluid.dygraph.guard():
            layer = fluid.dygraph.Conv2DTranspose(
                num_channels=3, num_filters=5, filter_size=3,
                stride=2, padding=1)
            out = layer(fluid.dygraph.to_variable(x))
            # H_out = (H-1)*s - 2p + k = 3*2 - 2 + 3 = 7
            assert tuple(out.shape) == (2, 5, 7, 7), out.shape
            loss = fluid.layers.reduce_mean(out)
            loss.backward()
            assert np.abs(layer.weight.gradient()).sum() > 0
            # torch oracle for the values
            import torch
            import torch.nn.functional as F
            ref = F.conv_transpose2d(
                torch.tensor(x), torch.tensor(layer.weight.numpy()),
                bias=torch.tensor(layer.bias.numpy()), stride=2,
                padding=1).numpy()
            np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4,
                                       atol=1e-4)

    def test_sequence_conv_matches_manual_window(self):
        rng = np.random.RandomState(3)
        x = rng.randn(2, 6, 4).astype(np.float32)  # B,T,D
        with fluid.dygraph.guard():
            layer = fluid.dygraph.SequenceConv(
                num_filters=7, filter_size=3, input_dim=4)
            out = layer(fluid.dygraph.to_variable(x))
            assert tuple(out.shape) == (2, 6, 7)
            w = layer.weight.numpy()  # [3*4, 7]
            b = layer.bias.numpy()
            # manual context windows: offsets -1, 0, +1 (zero padded)
            padded = np.pad(x, ((0, 0), (1, 1), (0, 0)))
            ctx = np.concatenate(
                [padded[:, 0:6], padded[:, 1:7], padded[:, 2:8]],
                axis=-1)  # B,T,3D
            ref = ctx @ w + b
            np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4,
                                       atol=1e-5)
            loss = fluid.layers.reduce_mean(out)
            loss.backward()
            assert np.abs(layer.weight.gradient()).sum() > 0


def test_pylayer_custom_forward_backward():
    """PyLayer (reference dygraph/layers.py PyLayer): user numpy
    forward/backward integrate with the tape."""

    class Double(fluid.dygraph.PyLayer):
        @staticmethod
        def forward(x):
            return 2.0 * x

        @staticmethod
        def backward(dout):
            return 2.0 * dout

    x = np.random.RandomState(0).randn(3, 4).astype(np.float32)
    with fluid.dygraph.guard():
        xv = fluid.dygraph.to_variable(x)
        xv.stop_gradient = False
        layer = Double()
        out = layer(xv)
        np.testing.assert_allclose(out.numpy(), 2 * x, rtol=1e-6)
        # chain through a traced op so the tape mixes builtin + custom
        loss = fluid.layers.reduce_sum(out)
        loss.backward()
        g = xv.gradient()
        np.testing.assert_allclose(g, np.full_like(x, 2.0), rtol=1e-6)


def test_pylayer_multi_output():
    class SplitHalf(fluid.dygraph.PyLayer):
        @staticmethod
        def forward(x):
            return x * 3.0, x + 1.0

        @staticmethod
        def backward(da, db):
            return 3.0 * da + db

    x = np.ones((2, 2), np.float32)
    with fluid.dygraph.guard():
        xv = fluid.dygraph.to_variable(x)
        xv.stop_gradient = False
        a, b = SplitHalf()(xv)
        s = fluid.layers.reduce_sum(a + b)
        s.backward()
        np.testing.assert_allclose(xv.gradient(),
                                   np.full_like(x, 4.0), rtol=1e-6)


def test_pylayer_partially_used_outputs():
    """An unused PyLayer output contributes zero grad instead of
    crashing the user's backward (review regression)."""

    class SplitTwo(fluid.dygraph.PyLayer):
        @staticmethod
        def forward(x):
            return x * 3.0, x + 1.0

        @staticmethod
        def backward(da, db):
            return 3.0 * da + db

    x = np.ones((2, 2), np.float32)
    with fluid.dygraph.guard():
        xv = fluid.dygraph.to_variable(x)
        xv.stop_gradient = False
        a, b = SplitTwo()(xv)
        fluid.layers.reduce_sum(a).backward()  # b unused
        np.testing.assert_allclose(xv.gradient(),
                                   np.full_like(x, 3.0), rtol=1e-6)
