"""Whole-layer fused attention block (ops/pallas/attention_block.py +
the `attention_block` op/layer): the PERF.md MFU lever, prepped so the
on-chip A/B is a 10-minute job (VERDICT r4 next #2). Kernel parity is
tested in pallas interpret mode; the op/layer path is tested through
the Executor against the unfused 7-op composition."""
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as fluid
from paddle_tpu.ops.pallas import attention as fa
from paddle_tpu.ops.pallas import attention_block as AB


@pytest.fixture
def interp():
    fa.force_interpret(True)
    yield
    fa.force_interpret(False)


def _mk(b=4, t=16, d=32, dtype=jnp.float32, seed=0):
    r = np.random.RandomState(seed)
    x = jnp.asarray(r.randn(b, t, d).astype(np.float32), dtype)
    wqkv = jnp.asarray(
        (r.randn(d, 3 * d) / np.sqrt(d)).astype(np.float32), dtype)
    wo = jnp.asarray(
        (r.randn(d, d) / np.sqrt(d)).astype(np.float32), dtype)
    return x, wqkv, wo


class TestKernelParity:
    @pytest.mark.parametrize("causal", [False, True])
    def test_forward_matches_reference(self, interp, causal):
        x, wqkv, wo = _mk()
        scale = (32 // 4) ** -0.5
        got = AB.attention_block(x, wqkv, wo, 4, scale, causal)
        want = AB.attention_block_reference(x, wqkv, wo, 4, scale,
                                            causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_match_reference(self, interp, causal):
        x, wqkv, wo = _mk(seed=3)
        scale = (32 // 4) ** -0.5

        def loss_k(x, wqkv, wo):
            return jnp.sum(
                AB.attention_block(x, wqkv, wo, 4, scale, causal) ** 2)

        def loss_r(x, wqkv, wo):
            return jnp.sum(
                AB.attention_block_reference(
                    x, wqkv, wo, 4, scale, causal) ** 2)

        gk = jax.grad(loss_k, argnums=(0, 1, 2))(x, wqkv, wo)
        gr = jax.grad(loss_r, argnums=(0, 1, 2))(x, wqkv, wo)
        # the kernel saves P in bf16 (the deliberate precision trade
        # of the saved-P backward): errors scale with the grad
        # magnitude, so the atol is scale-aware
        for a, e in zip(gk, gr):
            a, e = np.asarray(a), np.asarray(e)
            np.testing.assert_allclose(
                a, e, rtol=5e-2, atol=5e-3 * max(np.abs(e).max(), 1))

    def test_bf16_io(self, interp):
        x, wqkv, wo = _mk(dtype=jnp.bfloat16, seed=1)
        got = AB.attention_block(x, wqkv, wo, 4, 0.125, True)
        want = AB.attention_block_reference(x, wqkv, wo, 4, 0.125,
                                            True)
        assert got.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(got, np.float32), np.asarray(want, np.float32),
            rtol=2e-2, atol=2e-2)

    def test_usable_gate(self, interp):
        # interp fixture so the platform gate passes and the knobs
        # below are ACTUALLY exercised (not vacuous on CPU)
        x, wqkv, wo = _mk()
        assert AB.usable(x, wqkv, 4)
        os.environ["PADDLE_TPU_DISABLE_PALLAS_ATTN_BLOCK"] = "1"
        try:
            assert not AB.usable(x, wqkv, 4)
        finally:
            del os.environ["PADDLE_TPU_DISABLE_PALLAS_ATTN_BLOCK"]
        # too-long sequences stay on the jnp path (VMEM ceiling)
        xl = jnp.zeros((2, 1024, 32))
        assert not AB.usable(xl, jnp.zeros((32, 96)), 4)


class TestFfnKernelParity:
    """The MLP half of the whole-layer fusion
    (ops/pallas/ffn_block.py)."""

    def _mk(self, b=4, t=16, d=32, f=64, seed=0):
        r = np.random.RandomState(seed)
        return (jnp.asarray(r.randn(b, t, d).astype(np.float32)),
                jnp.asarray((r.randn(d, f) / np.sqrt(d)).astype(
                    np.float32)),
                jnp.asarray(r.randn(f).astype(np.float32) * 0.1),
                jnp.asarray((r.randn(f, d) / np.sqrt(f)).astype(
                    np.float32)),
                jnp.asarray(r.randn(d).astype(np.float32) * 0.1))

    def test_forward_matches_reference(self, interp):
        from paddle_tpu.ops.pallas import ffn_block as FB

        args = self._mk()
        got = FB.ffn_block(*args)
        want = FB.ffn_block_reference(*args)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_grads_match_reference(self, interp):
        from paddle_tpu.ops.pallas import ffn_block as FB

        args = self._mk(seed=3)

        def loss_k(*a):
            return jnp.sum(FB.ffn_block(*a) ** 2)

        def loss_r(*a):
            return jnp.sum(FB.ffn_block_reference(*a) ** 2)

        gk = jax.grad(loss_k, argnums=tuple(range(5)))(*args)
        gr = jax.grad(loss_r, argnums=tuple(range(5)))(*args)
        for a, e in zip(gk, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(e),
                                       rtol=1e-4, atol=1e-4)

    def test_usable_gate(self, interp):
        # interp fixture so the platform gate passes and the env knob
        # + VMEM estimate are ACTUALLY exercised (not vacuous on CPU)
        from paddle_tpu.ops.pallas import ffn_block as FB

        x = jnp.zeros((4, 16, 32))
        assert FB.usable(x, jnp.zeros((32, 64)))
        os.environ["PADDLE_TPU_DISABLE_PALLAS_FFN_BLOCK"] = "1"
        try:
            assert not FB.usable(x, jnp.zeros((32, 64)))
        finally:
            del os.environ["PADDLE_TPU_DISABLE_PALLAS_FFN_BLOCK"]
        # oversized weights refuse the kernel (VMEM estimate)
        assert not FB.usable(jnp.zeros((2, 512, 2048)),
                             jnp.zeros((2048, 8192)))
        # backward accumulators bind before the forward does
        assert not FB.usable(jnp.zeros((2, 64, 1024)),
                             jnp.zeros((1024, 1280)))


def _fresh():
    fluid._reset_global_scope()
    from paddle_tpu import unique_name
    unique_name.switch()


def _build():
    from paddle_tpu.models import transformer as T

    main, startup, cost = T.build_program(
        seq_len=8, d_model=32, n_heads=2, n_layers=2, d_inner=64,
        vocab=64, dropout_rate=0.0, learning_rate=1.0,
        warmup_steps=40)
    main._seed = 5
    return main, startup, cost


def _losses(fused, steps=5):
    _fresh()
    if fused:
        os.environ["PADDLE_TPU_FUSE_ATTN_BLOCK"] = "1"
    try:
        main, startup, cost = _build()
    finally:
        os.environ.pop("PADDLE_TPU_FUSE_ATTN_BLOCK", None)
    r = np.random.RandomState(0)
    feed = {k: r.randint(1, 64, (8, 8)).astype(np.int64)
            for k in ("src_ids", "tgt_ids", "label")}
    exe = fluid.Executor(fluid.CPUPlace())
    sc = fluid.Scope()
    exe.run(startup, scope=sc)
    out = []
    for _ in range(steps):
        l, = exe.run(main, feed=feed, fetch_list=[cost], scope=sc)
        out.append(float(np.asarray(l).reshape(-1)[0]))
    return out, main


class TestModelIntegration:
    def test_fused_route_emits_one_op_per_self_attention(self):
        _, fused_main = None, None
        os.environ["PADDLE_TPU_FUSE_ATTN_BLOCK"] = "1"
        try:
            _fresh()
            fused_main, _, _ = _build()
        finally:
            os.environ.pop("PADDLE_TPU_FUSE_ATTN_BLOCK", None)
        types = [op.type for op in fused_main.global_block.ops]
        # 2 enc self + 2 dec self = 4 fused ops; cross-attention stays
        # on the unfused path (separate q / kv sources)
        assert types.count("attention_block") == 4
        assert types.count("attention") == 2  # cross only
        # and every layer's MLP fused too (2 enc + 2 dec)
        assert types.count("ffn_block") == 4

    def test_fused_matches_unfused_through_training(self):
        base, _ = _losses(False)
        got, _ = _losses(True)
        np.testing.assert_allclose(got, base, rtol=5e-4, atol=5e-5)

    def test_fused_composes_with_scan_over_layers(self):
        """The batch-256 lowering (PipelineTrainer pp=1 scan) works on
        fused-block layers: the segments stay isomorphic with one
        attention_block + one ffn_block op each, and losses match the
        unfused Executor — the combined config transformer_scan_fused
        benches this on-chip."""
        from paddle_tpu.parallel.pipeline_program import (
            PipelineTrainer, propose_loops)

        base, _ = _losses(False)
        _fresh()
        os.environ["PADDLE_TPU_FUSE_ATTN_BLOCK"] = "1"
        try:
            main, startup, cost = _build()
        finally:
            os.environ.pop("PADDLE_TPU_FUSE_ATTN_BLOCK", None)
        loops = propose_loops(main, cost.name)
        assert len(loops) == 2  # enc + dec stacks detected when fused
        r = np.random.RandomState(0)
        feed = {k: r.randint(1, 64, (8, 8)).astype(np.int64)
                for k in ("src_ids", "tgt_ids", "label")}
        exe = fluid.Executor(fluid.CPUPlace())
        sc = fluid.Scope()
        exe.run(startup, scope=sc)
        tr = PipelineTrainer(main, cost, loops=loops)
        tr.initialize(sc)
        got = [float(np.asarray(tr.run(feed=feed)[0]).reshape(-1)[0])
               for _ in range(5)]
        np.testing.assert_allclose(got, base, rtol=5e-4, atol=5e-5)

    def test_dropout_and_decode_builds_stay_unfused(self):
        """dropout>0 and is_test builds keep the unfused path (the
        kernel has no dropout; decode While-loop bodies are validated
        against the op composition); the flag must not leak."""
        from paddle_tpu.models import transformer as T

        _fresh()
        os.environ["PADDLE_TPU_FUSE_ATTN_BLOCK"] = "1"
        try:
            main, _, _ = T.build_program(
                seq_len=8, d_model=32, n_heads=2, n_layers=1,
                d_inner=64, vocab=64, dropout_rate=0.1,
                learning_rate=1.0, warmup_steps=40)
            types = [op.type for op in main.global_block.ops]
            assert types.count("attention_block") == 0
            # is_test=True (decode-style build) declines too
            _fresh()
            prog, startup = None, None
            import paddle_tpu as fl
            prog, startup = fl.Program(), fl.Program()
            with fl.program_guard(prog, startup):
                x = fl.layers.data("x", shape=[8, 32],
                                   dtype="float32")
                T.multi_head_attention(x, x, 32, 2, 0.0,
                                       causal=True, is_test=True,
                                       name="t")
            types = [op.type for op in prog.global_block.ops]
            assert types.count("attention_block") == 0
        finally:
            os.environ.pop("PADDLE_TPU_FUSE_ATTN_BLOCK", None)
