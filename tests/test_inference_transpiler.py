"""InferenceTranspiler + downpour package tests.

Parity model: reference tests/unittests/test_inference_transpiler-era
coverage (the reference exercises it inside book tests) plus
test_downpoursgd-era desc checks.
"""
import numpy as np
import pytest

import paddle_tpu as fluid


def _build_conv_bn_relu():
    prog = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(prog, startup):
        img = fluid.layers.data("img", shape=(3, 8, 8), dtype="float32")
        conv = fluid.layers.conv2d(img, num_filters=4, filter_size=3,
                                   padding=1, act=None)
        bn = fluid.layers.batch_norm(conv, is_test=True)
        relu = fluid.layers.relu(bn)
    return prog, startup, img, relu


def test_inference_transpiler_conv_bn_relu_fold():
    prog, startup, img, out = _build_conv_bn_relu()
    exe = fluid.Executor()
    exe.run(startup)
    x = np.random.RandomState(0).rand(2, 3, 8, 8).astype("float32")
    before = np.asarray(
        exe.run(prog, feed={"img": x}, fetch_list=[out.name])[0])

    t = fluid.InferenceTranspiler()
    t.transpile(prog, scope=fluid.global_scope(),
                protected=[out.name])
    types = [op.type for op in prog.global_block.ops]
    assert "batch_norm" not in types, types
    # conv+bias+relu collapsed into the fused op
    assert "conv2d_fusion" in types, types
    after = np.asarray(
        exe.run(prog, feed={"img": x}, fetch_list=[out.name])[0])
    np.testing.assert_allclose(after, before, atol=1e-4, rtol=1e-4)


def test_conv_eltwiseadd_fuse_pass():
    from paddle_tpu.ir import apply_passes

    prog = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(prog, startup):
        img = fluid.layers.data("img", shape=(3, 8, 8), dtype="float32")
        res = fluid.layers.data("res", shape=(4, 8, 8), dtype="float32")
        conv = fluid.layers.conv2d(img, num_filters=4, filter_size=3,
                                   padding=1, act=None,
                                   bias_attr=False)
        out = fluid.layers.elementwise_add(conv, res)
    exe = fluid.Executor()
    exe.run(startup)
    rng = np.random.RandomState(1)
    feed = {"img": rng.rand(2, 3, 8, 8).astype("float32"),
            "res": rng.rand(2, 4, 8, 8).astype("float32")}
    before = np.asarray(
        exe.run(prog, feed=feed, fetch_list=[out.name])[0])
    apply_passes(prog, ["conv_eltwiseadd_fuse_pass"],
                 protected=[out.name])
    types = [op.type for op in prog.global_block.ops]
    assert "conv2d_fusion" in types and "elementwise_add" not in types
    after = np.asarray(
        exe.run(prog, feed=feed, fetch_list=[out.name])[0])
    np.testing.assert_allclose(after, before, atol=1e-5, rtol=1e-5)


def test_distribute_lookup_table_finders():
    from paddle_tpu.distribute_lookup_table import (
        find_distributed_lookup_table,
        find_distributed_lookup_table_inputs,
        find_distributed_lookup_table_outputs)

    prog = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(prog, startup):
        ids = fluid.layers.data("ids", shape=(1,), dtype="int64")
        emb = fluid.layers.embedding(ids, size=(100, 8),
                                     is_distributed=True)
    name = find_distributed_lookup_table(prog)
    assert name is not None
    ins = find_distributed_lookup_table_inputs(prog, name)
    outs = find_distributed_lookup_table_outputs(prog, name)
    assert [v.name for v in ins] == ["ids"]
    assert len(outs) == 1


def test_downpour_sgd_plan():
    prog = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(prog, startup):
        ids = fluid.layers.data("ids", shape=(1,), dtype="int64")
        lbl = fluid.layers.data("lbl", shape=(1,), dtype="float32")
        emb = fluid.layers.embedding(ids, size=(100, 8),
                                     is_distributed=True)
        emb.stop_gradient = False
        fcout = fluid.layers.fc(emb, size=1)
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(fcout, lbl))
        sgd = fluid.distributed.DownpourSGD(learning_rate=0.1, window=2)
        ps_param, skipped = sgd.minimize(loss)
    assert skipped == ["lookup_table", "lookup_table_grad"]
    server = ps_param["server_param"]
    tables = server["downpour_table_params"]
    assert tables[0]["type"] == "PS_SPARSE_TABLE"
    assert tables[0]["slot_key_vars"] == ["ids"]
    assert tables[1]["type"] == "PS_DENSE_TABLE"
    assert len(tables[1]["dense_param_vars"]) >= 2  # fc w + b
    trainer = ps_param["trainer_param"]
    assert trainer["window"] == 2
    assert trainer["skip_op"] == skipped


def test_ps_instance_roles():
    from paddle_tpu.distributed import PaddlePSInstance

    class FakeHelper:
        def __init__(self, rank, size):
            self._r, self._s = rank, size

        def get_rank(self):
            return self._r

        def get_size(self):
            return self._s

        def get_ip(self):
            return "127.0.0.1"

        def barrier(self):
            pass

        def finalize(self):
            pass

    # mode 1: even ranks servers, odd workers
    inst = PaddlePSInstance(server_worker_mode=1, proc_per_node=2,
                            helper=FakeHelper(0, 4))
    assert inst.is_server() and not inst.is_worker()
    inst = PaddlePSInstance(server_worker_mode=1, proc_per_node=2,
                            helper=FakeHelper(3, 4))
    assert inst.is_worker()
    assert inst.get_worker_index() == 1
    assert inst.get_node_cnt() == 2
    inst.barrier_all()  # no-op, must not raise
    ips = inst.gather_ips()
    assert len(ips) == 4

    # mode 0: first half workers, second half servers (zero-based
    # per-role indices)
    inst = PaddlePSInstance(server_worker_mode=0, proc_per_node=2,
                            helper=FakeHelper(0, 4))
    assert inst.is_worker() and inst.is_first_worker()
    inst = PaddlePSInstance(server_worker_mode=0, proc_per_node=2,
                            helper=FakeHelper(1, 4))
    assert inst.is_worker() and inst.get_worker_index() == 1
    inst = PaddlePSInstance(server_worker_mode=0, proc_per_node=2,
                            helper=FakeHelper(2, 4))
    assert inst.is_server() and inst.get_server_index() == 0
    inst = PaddlePSInstance(server_worker_mode=0, proc_per_node=2,
                            helper=FakeHelper(3, 4))
    assert inst.is_server() and inst.get_server_index() == 1


if __name__ == "__main__":
    import pytest

    pytest.main([__file__, "-q"])


def _opt_fuse_case(opt_name):
    from paddle_tpu import unique_name

    fluid._reset_global_scope()
    unique_name.switch()
    fluid.seed(21)
    prog = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.layers.data("x", shape=(8,), dtype="float32")
        y = fluid.layers.data("y", shape=(1,), dtype="float32")
        h = fluid.layers.fc(x, size=16, act="relu")
        h = fluid.layers.fc(h, size=16, act="tanh")
        pred = fluid.layers.fc(h, size=1)
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(pred, y))
        if opt_name == "sgd":
            fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)
        else:
            fluid.optimizer.Adam(learning_rate=0.01).minimize(loss)
    return prog, startup, loss


def _run_steps(prog, startup, loss, steps=6):
    rng = np.random.RandomState(4)
    x = rng.rand(16, 8).astype("float32")
    y = rng.rand(16, 1).astype("float32")
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    return [float(np.asarray(exe.run(prog, feed={"x": x, "y": y},
                                     fetch_list=[loss.name])[0]))
            for _ in range(steps)]


@pytest.mark.parametrize("opt_name,pass_name,op_type", [
    ("sgd", "fuse_sgd_op_pass", "sgd"),
    ("adam", "fuse_adam_op_pass", "adam"),
])
def test_fuse_optimizer_pass_loss_parity(opt_name, pass_name, op_type):
    """reference details/fuse_sgd_op_pass.cc / fuse_adam_op_pass.cc:
    N per-param updates -> 1 update over coalesced buffers, same
    training trajectory."""
    from paddle_tpu.ir import apply_passes

    prog, startup, loss = _opt_fuse_case(opt_name)
    plain = _run_steps(prog, startup, loss)

    prog2, startup2, loss2 = _opt_fuse_case(opt_name)
    n_before = sum(1 for op in prog2.global_block.ops
                   if op.type == op_type)
    assert n_before > 1
    apply_passes(prog2, [pass_name])
    n_after = sum(1 for op in prog2.global_block.ops
                  if op.type == op_type)
    assert n_after == 1, f"expected one fused {op_type} op"
    assert any(op.type == "alloc_continuous_space"
               for op in prog2.global_block.ops)
    fused = _run_steps(prog2, startup2, loss2)
    np.testing.assert_allclose(fused, plain, atol=1e-5, rtol=1e-5)
    assert fused[-1] < fused[0]
