"""Data-parallel tests on the virtual 8-device CPU mesh (reference
parallel_executor_test_base.py: PE-vs-Executor loss parity;
test_dist_base.py oracle: dist loss must match single-process)."""
import numpy as np

import paddle_tpu as fluid


def _build(seed=5):
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data("img", shape=[32], dtype="float32")
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        h = fluid.layers.fc(img, 32, act="relu")
        logits = fluid.layers.fc(h, 4)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, label))
        fluid.optimizer.SGD(0.1).minimize(loss)
    return main, startup, loss


def _batches(n_steps, batch=32):
    rng = np.random.RandomState(11)
    for _ in range(n_steps):
        y = rng.randint(0, 4, (batch, 1)).astype("int64")
        x = rng.rand(batch, 32).astype("float32") * 0.1
        for i in range(batch):
            x[i, y[i, 0] * 8:(y[i, 0] + 1) * 8] += 1.0
        yield x, y


def test_data_parallel_loss_parity():
    """CompiledProgram.with_data_parallel over 8 devices must track the
    single-device loss (same global batch, same init)."""
    fluid.seed(3)
    main, startup, loss = _build()
    scope_single = fluid.Scope()
    exe = fluid.Executor()
    exe.run(startup, scope=scope_single)
    single_losses = []
    for x, y in _batches(8):
        out = exe.run(main, feed={"img": x, "label": y},
                      fetch_list=[loss], scope=scope_single)
        single_losses.append(float(np.asarray(out[0]).reshape(-1)[0]))

    fluid.seed(3)
    scope_dp = fluid.Scope()
    exe.run(startup, scope=scope_dp)
    compiled = fluid.CompiledProgram(main).with_data_parallel(
        loss_name=loss.name)
    dp_losses = []
    for x, y in _batches(8):
        out = exe.run(compiled, feed={"img": x, "label": y},
                      fetch_list=[loss], scope=scope_dp)
        dp_losses.append(float(np.asarray(out[0]).reshape(-1)[0]))
    np.testing.assert_allclose(single_losses, dp_losses, rtol=2e-4,
                               atol=2e-5)


def test_parallel_executor_facade():
    fluid.seed(7)
    main, startup, loss = _build()
    exe = fluid.Executor()
    exe.run(startup)
    pe = fluid.ParallelExecutor(use_cuda=False, loss_name=loss.name,
                                main_program=main)
    for x, y in _batches(3):
        out = pe.run(feed={"img": x, "label": y},
                     fetch_list=[loss.name])
        val = float(np.asarray(out[0]).reshape(-1)[0])
        assert np.isfinite(val)


def test_dryrun_multichip_entrypoint():
    import importlib
    import sys

    sys.path.insert(0, "/root/repo")
    m = importlib.import_module("__graft_entry__")
    m.dryrun_multichip(8)


def test_fuse_all_optimizer_ops_knob():
    """BuildStrategy.fuse_all_optimizer_ops routes through the
    fuse_adam/sgd IR passes (reference build_strategy.cc pipeline)."""
    import paddle_tpu as fluid
    from paddle_tpu import unique_name

    fluid._reset_global_scope()
    unique_name.switch()
    fluid.seed(3)
    prog = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.layers.data("x", shape=(8,), dtype="float32")
        y = fluid.layers.data("y", shape=(1,), dtype="float32")
        h = fluid.layers.fc(x, size=8, act="relu")
        loss = fluid.layers.mean(fluid.layers.square_error_cost(
            fluid.layers.fc(h, size=1), y))
        fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)

    bs = fluid.BuildStrategy()
    bs.fuse_all_optimizer_ops = True
    compiled = fluid.CompiledProgram(prog).with_data_parallel(
        loss_name=loss.name, build_strategy=bs)
    assert sum(1 for op in prog.global_block.ops
               if op.type == "sgd") == 1
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(0)
    feed = {"x": rng.rand(16, 8).astype("float32"),
            "y": rng.rand(16, 1).astype("float32")}
    losses = [float(np.asarray(exe.run(compiled, feed=feed,
                                       fetch_list=[loss.name])[0])
                    .reshape(-1)[0])
              for _ in range(6)]
    assert losses[-1] < losses[0]


class TestComposedMeshDataParallel:
    """with_data_parallel(mesh=dp x tp) through the USER API (VERDICT
    r2 weak #6): structural TP placement composes with dp."""

    def test_transformer_dp2_tp2_matches_single_device(self):
        import jax
        from paddle_tpu.models import transformer as T
        from paddle_tpu.parallel.mesh import make_mesh, MeshConfig

        def build():
            fluid._reset_global_scope()
            from paddle_tpu import unique_name
            unique_name.switch()
            main, startup, cost = T.build_program(
                seq_len=8, d_model=32, n_heads=2, n_layers=2,
                d_inner=64, vocab=64, dropout_rate=0.0,
                learning_rate=0.5, warmup_steps=20)
            main._seed = 9
            return main, startup, cost

        r = np.random.RandomState(0)
        feed = {k: r.randint(0, 64, (8, 8)).astype(np.int64)
                for k in ("src_ids", "tgt_ids", "label")}

        main, startup, cost = build()
        exe = fluid.Executor(fluid.CPUPlace())
        sc = fluid.Scope()
        exe.run(startup, scope=sc)
        base = []
        for _ in range(3):
            l, = exe.run(main, feed=feed, fetch_list=[cost], scope=sc)
            base.append(float(np.asarray(l).reshape(-1)[0]))

        main2, startup2, cost2 = build()
        sc2 = fluid.Scope()
        exe.run(startup2, scope=sc2)
        mesh = make_mesh(MeshConfig(dp=2, tp=2),
                         devices=jax.devices()[:4])
        cp = fluid.CompiledProgram(main2).with_data_parallel(
            loss_name=cost2.name, mesh=mesh)
        got = []
        for _ in range(3):
            l, = exe.run(cp, feed=feed, fetch_list=[cost2], scope=sc2)
            got.append(float(np.asarray(l).reshape(-1)[0]))
        np.testing.assert_allclose(base, got, rtol=5e-4, atol=5e-5)

    def test_broken_equivalence_check_warns_and_replaces(self):
        """place() must not silently keep a possibly stale-sharded
        array when the equivalence CHECK itself fails (VERDICT r4 weak
        #6): it warns, re-places, and numerics stay correct."""
        import warnings

        import jax
        from paddle_tpu.core import compiler as C
        from paddle_tpu.parallel.mesh import make_mesh, MeshConfig

        fluid._reset_global_scope()
        from paddle_tpu import unique_name
        unique_name.switch()
        main, startup, cost = _build(seed=9)
        xs, ys = next(iter(_batches(1)))
        exe = fluid.Executor(fluid.CPUPlace())
        sc = fluid.Scope()
        exe.run(startup, scope=sc)
        mesh = make_mesh(MeshConfig(dp=2, tp=2),
                         devices=jax.devices()[:4])
        cp = fluid.CompiledProgram(main).with_data_parallel(
            loss_name=cost.name, mesh=mesh)
        feed = {"img": xs, "label": ys}
        l0, = exe.run(cp, feed=feed, fetch_list=[cost], scope=sc)
        orig = C._sharding_matches
        C._sharding_matches = lambda v, t: None
        try:
            with warnings.catch_warnings(record=True) as rec:
                warnings.simplefilter("always")
                l1, = exe.run(cp, feed=feed, fetch_list=[cost],
                              scope=sc)
            assert any("re-placing" in str(w.message) for w in rec)
        finally:
            C._sharding_matches = orig
        # and the step still trained correctly after re-placement
        assert np.isfinite(float(np.asarray(l1).reshape(-1)[0]))
        assert float(np.asarray(l1).reshape(-1)[0]) < \
            float(np.asarray(l0).reshape(-1)[0])

    def test_mesh_without_dp_axis_rejected(self):
        import jax
        import numpy as _np
        from jax.sharding import Mesh

        prog = fluid.Program()
        mesh = Mesh(_np.array(jax.devices()[:2]), ("tp",))
        import pytest

        with pytest.raises(ValueError, match="dp"):
            fluid.CompiledProgram(prog).with_data_parallel(mesh=mesh)
