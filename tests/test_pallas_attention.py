"""Flash-attention Pallas kernel tests (interpreter mode on CPU).

The real TPU lowering can't run in CI, but pallas interpret mode
executes the identical kernel code (grids, BlockSpecs, fori_loop online
softmax) with numpy semantics, so these tests pin the kernel math --
forward AND the FlashAttention-2 backward -- against the jnp oracle
(ops/pallas/__init__.py reference_attention).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops import pallas
from paddle_tpu.ops.pallas import attention as fa


@pytest.fixture(autouse=True)
def _interpret():
    fa.force_interpret(True)
    yield
    fa.force_interpret(False)


def _rand_qkv(b, h, tq, tk, d, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (b, h, tq, d), dtype=dtype)
    k = jax.random.normal(ks[1], (b, h, tk, d), dtype=dtype)
    v = jax.random.normal(ks[2], (b, h, tk, d), dtype=dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("tq,tk", [(32, 32), (16, 32)])
def test_forward_matches_oracle(causal, tq, tk):
    q, k, v = _rand_qkv(2, 2, tq, tk, 64)
    scale = 64 ** -0.5
    out = fa.flash_attention(q, k, v, scale, causal)
    ref = pallas.reference_attention(q, k, v, scale, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_backward_matches_oracle(causal):
    q, k, v = _rand_qkv(1, 2, 32, 32, 64, seed=3)
    scale = 64 ** -0.5

    def loss_flash(q, k, v):
        o = fa.flash_attention(q, k, v, scale, causal)
        return jnp.sum(jnp.sin(o))

    def loss_ref(q, k, v):
        o = pallas.reference_attention(q, k, v, scale, causal)
        return jnp.sum(jnp.sin(o))

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gr, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-5, rtol=5e-5,
            err_msg=f"d{name} mismatch (causal={causal})")


def test_backward_cross_attention_rect():
    """tq != tk exercises the bottom-right causal offset in backward."""
    q, k, v = _rand_qkv(1, 1, 16, 32, 64, seed=5)
    scale = 0.2

    def f(impl):
        def loss(q, k, v):
            return jnp.sum(impl(q, k, v, scale, True) ** 2)
        return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

    gf = f(fa.flash_attention)
    gr = f(pallas.reference_attention)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-5, rtol=5e-5)


def test_bf16_inputs():
    q, k, v = _rand_qkv(1, 1, 32, 32, 64, dtype=jnp.bfloat16, seed=7)
    scale = 64 ** -0.5
    out = fa.flash_attention(q, k, v, scale, True)
    assert out.dtype == jnp.bfloat16
    ref = pallas.reference_attention(q, k, v, scale, True)
    np.testing.assert_allclose(
        np.asarray(out, dtype=np.float32),
        np.asarray(ref, dtype=np.float32), atol=3e-2, rtol=3e-2)

    def loss(q, k, v):
        return jnp.sum(fa.flash_attention(q, k, v, scale, True)
                       .astype(jnp.float32))

    dq, dk, dv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    assert dq.dtype == dk.dtype == dv.dtype == jnp.bfloat16


def test_lse_saved_not_probs():
    """Residuals are O(T): q,k,v,out,lse -- never the [T,T] probs."""
    q, k, v = _rand_qkv(1, 1, 32, 32, 64)
    out, res = fa._flash_fwd(q, k, v, 1.0, False)
    assert len(res) == 5
    assert res[4].shape == (1, 1, 32)  # lse


@pytest.mark.parametrize("causal", [False, True])
def test_sdpa_short_forward_matches_oracle(causal):
    q, k, v = _rand_qkv(2, 4, 32, 32, 64, seed=3)
    q, k, v = q * 0.3, k * 0.3, v * 0.3
    got = fa.sdpa_short(q, k, v, 0.125, causal)
    ref = pallas.reference_attention(q, k, v, 0.125, causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-6, rtol=2e-6)


def test_sdpa_short_grads_match_oracle():
    q, k, v = _rand_qkv(2, 4, 32, 32, 64, seed=4)
    q, k, v = q * 0.3, k * 0.3, v * 0.3

    def f(q, k, v):
        return (fa.sdpa_short(q, k, v, 0.125, True) * jnp.cos(q)).sum()

    def fr(q, k, v):
        return (pallas.reference_attention(q, k, v, 0.125, True)
                * jnp.cos(q)).sum()

    ga = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(fr, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(ga, gr):
        # bf16 saved-P quantization bounds the grad error
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-3, rtol=5e-2)


def test_sdpa_short_routed_shape():
    """A shape inside sdpa_usable's actual window (T=512)."""
    q, k, v = _rand_qkv(1, 8, 512, 512, 64, seed=7)
    q, k, v = q * 0.2, k * 0.2, v * 0.2
    assert fa.sdpa_usable(q, k, v)
    got = fa.sdpa_short(q, k, v, 0.125, True)
    ref = pallas.reference_attention(q, k, v, 0.125, True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_sdpa_usable_window():
    mk = lambda t: _rand_qkv(1, 8, t, t, 64, seed=1)
    assert not fa.sdpa_usable(*mk(256))   # jnp path wins at short T
    assert fa.sdpa_usable(*mk(384))
    assert fa.sdpa_usable(*mk(512))
    assert not fa.sdpa_usable(*mk(1024))  # flash kernel territory
    q, k, v = _rand_qkv(1, 8, 384, 512, 64, seed=1)
    assert not fa.sdpa_usable(q, k, v)    # cross-length rejected


def test_pallas_xent_forward_backward_match_jnp():
    from paddle_tpu.ops.pallas import xent as px

    n, v = 64, 256
    r = np.random.RandomState(5)
    x = jnp.asarray(r.randn(n, v).astype(np.float32))
    lab = jnp.asarray(r.randint(0, v, (n,)).astype(np.int32))
    g = jnp.asarray(r.rand(n).astype(np.float32))
    for eps in (0.0, 0.1):
        loss, lse = px.xent_forward(x, lab, eps=eps)
        lse_ref = jax.scipy.special.logsumexp(x, axis=-1)
        picked = jnp.take_along_axis(x, lab[:, None], 1)[:, 0]
        ref = lse_ref - picked
        if eps:
            ref = (1 - eps) * ref + eps * (lse_ref - jnp.mean(x, axis=1))
        np.testing.assert_allclose(np.asarray(loss), np.asarray(ref),
                                   atol=2e-6, rtol=2e-6)
        np.testing.assert_allclose(np.asarray(lse), np.asarray(lse_ref),
                                   atol=2e-6, rtol=2e-6)
        dx = px.xent_backward(x, lab, g, eps=eps)
        sm = jax.nn.softmax(x, axis=-1)
        tgt = (1 - eps) * jax.nn.one_hot(lab, v) + (
            eps / v if eps else 0.0)
        dref = (sm - tgt) * g[:, None]
        np.testing.assert_allclose(np.asarray(dx), np.asarray(dref),
                                   atol=2e-6, rtol=2e-6)


def test_swce_op_routes_through_pallas_and_matches():
    import os

    import paddle_tpu as fluid
    from paddle_tpu.core.program import Operator
    from paddle_tpu.core.registry import run_op
    from paddle_tpu.ops.pallas import xent as px

    prog = fluid.Program()
    block = prog.global_block
    n, v = 64, 256
    r = np.random.RandomState(6)
    logits = r.randn(n, v).astype(np.float32)
    label = r.randint(0, v, (n, 1)).astype(np.int64)
    # the gate must actually accept this shape, else the comparison
    # below degenerates to jnp-vs-jnp
    assert px.usable(jnp.asarray(logits),
                     jnp.asarray(label[:, 0].astype(np.int32)))
    block.create_var(name="lg", shape=(n, v), dtype="float32")
    block.create_var(name="lb", shape=(n, 1), dtype="int64")
    op = Operator(block, "softmax_with_cross_entropy",
                  {"Logits": ["lg"], "Label": ["lb"]},
                  {"Loss": ["loss"], "Softmax": ["sm"]},
                  {"label_smooth_eps": 0.1})
    env = {"lg": jnp.asarray(logits), "lb": jnp.asarray(label)}
    run_op(op, env)
    pallas_loss = np.asarray(env["loss"])
    os.environ["PADDLE_TPU_DISABLE_PALLAS_XENT"] = "1"
    try:
        env2 = {"lg": jnp.asarray(logits), "lb": jnp.asarray(label)}
        run_op(op, env2)
    finally:
        os.environ.pop("PADDLE_TPU_DISABLE_PALLAS_XENT")
    np.testing.assert_allclose(pallas_loss, np.asarray(env2["loss"]),
                               atol=1e-5, rtol=1e-5)
