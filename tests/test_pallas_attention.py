"""Flash-attention Pallas kernel tests (interpreter mode on CPU).

The real TPU lowering can't run in CI, but pallas interpret mode
executes the identical kernel code (grids, BlockSpecs, fori_loop online
softmax) with numpy semantics, so these tests pin the kernel math --
forward AND the FlashAttention-2 backward -- against the jnp oracle
(ops/pallas/__init__.py reference_attention).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops import pallas
from paddle_tpu.ops.pallas import attention as fa


@pytest.fixture(autouse=True)
def _interpret():
    fa.force_interpret(True)
    yield
    fa.force_interpret(False)


def _rand_qkv(b, h, tq, tk, d, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (b, h, tq, d), dtype=dtype)
    k = jax.random.normal(ks[1], (b, h, tk, d), dtype=dtype)
    v = jax.random.normal(ks[2], (b, h, tk, d), dtype=dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("tq,tk", [(32, 32), (16, 32)])
def test_forward_matches_oracle(causal, tq, tk):
    q, k, v = _rand_qkv(2, 2, tq, tk, 64)
    scale = 64 ** -0.5
    out = fa.flash_attention(q, k, v, scale, causal)
    ref = pallas.reference_attention(q, k, v, scale, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_backward_matches_oracle(causal):
    q, k, v = _rand_qkv(1, 2, 32, 32, 64, seed=3)
    scale = 64 ** -0.5

    def loss_flash(q, k, v):
        o = fa.flash_attention(q, k, v, scale, causal)
        return jnp.sum(jnp.sin(o))

    def loss_ref(q, k, v):
        o = pallas.reference_attention(q, k, v, scale, causal)
        return jnp.sum(jnp.sin(o))

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gr, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-5, rtol=5e-5,
            err_msg=f"d{name} mismatch (causal={causal})")


def test_backward_cross_attention_rect():
    """tq != tk exercises the bottom-right causal offset in backward."""
    q, k, v = _rand_qkv(1, 1, 16, 32, 64, seed=5)
    scale = 0.2

    def f(impl):
        def loss(q, k, v):
            return jnp.sum(impl(q, k, v, scale, True) ** 2)
        return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

    gf = f(fa.flash_attention)
    gr = f(pallas.reference_attention)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-5, rtol=5e-5)


def test_bf16_inputs():
    q, k, v = _rand_qkv(1, 1, 32, 32, 64, dtype=jnp.bfloat16, seed=7)
    scale = 64 ** -0.5
    out = fa.flash_attention(q, k, v, scale, True)
    assert out.dtype == jnp.bfloat16
    ref = pallas.reference_attention(q, k, v, scale, True)
    np.testing.assert_allclose(
        np.asarray(out, dtype=np.float32),
        np.asarray(ref, dtype=np.float32), atol=3e-2, rtol=3e-2)

    def loss(q, k, v):
        return jnp.sum(fa.flash_attention(q, k, v, scale, True)
                       .astype(jnp.float32))

    dq, dk, dv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    assert dq.dtype == dk.dtype == dv.dtype == jnp.bfloat16


def test_lse_saved_not_probs():
    """Residuals are O(T): q,k,v,out,lse -- never the [T,T] probs."""
    q, k, v = _rand_qkv(1, 1, 32, 32, 64)
    out, res = fa._flash_fwd(q, k, v, 1.0, False)
    assert len(res) == 5
    assert res[4].shape == (1, 1, 32)  # lse
