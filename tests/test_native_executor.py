"""Round-5 native rungs (VERDICT r4 next #4): the C++ XLA builder
covers a SECOND model family (the ResNet slice: conv2d/pool2d/
batch_norm + grads), and the production Executor consumes the
natively-built computation in-process via FLAGS_native_build — the
trace path is the cross-check oracle at 1e-5."""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import native


def _fresh():
    fluid._reset_global_scope()
    from paddle_tpu import unique_name
    unique_name.switch()


def _native_ready():
    try:
        native.build_xla_train()
        return True
    except RuntimeError:
        return False


def _build_conv():
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        img = fluid.layers.data("img", shape=[1, 14, 14],
                                dtype="float32")
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        c1 = fluid.layers.conv2d(img, num_filters=4, filter_size=3,
                                 act="relu")
        p1 = fluid.layers.pool2d(c1, pool_size=2, pool_type="max",
                                 pool_stride=2)
        bn = fluid.layers.batch_norm(p1)
        c2 = fluid.layers.conv2d(bn, num_filters=6, filter_size=3,
                                 act="relu")
        p2 = fluid.layers.pool2d(c2, pool_size=2, pool_type="avg",
                                 pool_stride=2)
        pred = fluid.layers.fc(p2, size=5)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(pred, label))
        fluid.optimizer.Momentum(0.05, 0.9).minimize(loss)
    return prog, startup, loss


def _conv_data(seed=0):
    r = np.random.RandomState(seed)
    return {"img": r.randn(16, 1, 14, 14).astype(np.float32) * 0.5,
            "label": r.randint(0, 5, (16, 1)).astype(np.int64)}


@pytest.mark.skipif(not _native_ready(),
                    reason="no toolchain/XLA runtime for xla_train")
class TestConvSliceBinaryDriver:
    """Second model family through the Python-free C++ driver."""

    def test_conv_model_losses_match_python_to_1e5(self, tmp_path):
        _fresh()
        feed = _conv_data()
        prog, startup, loss = _build_conv()
        exe = fluid.Executor(fluid.CPUPlace())
        sc = fluid.Scope()
        exe.run(startup, scope=sc)
        from paddle_tpu.inference.export import export_train_program
        art = export_train_program(prog, sc, feed, [loss.name],
                                   str(tmp_path / "conv_native"))
        steps = 5
        py = []
        for _ in range(steps):
            l, = exe.run(prog, feed=feed, fetch_list=[loss], scope=sc)
            py.append(float(np.asarray(l).reshape(-1)[0]))
        rows = native.run_xla_train(art, steps)
        nat = [row[loss.name] for row in rows]
        np.testing.assert_allclose(nat, py, rtol=1e-5, atol=1e-6)
        assert py[-1] < py[0]

    def test_bn_running_stats_thread_through_native_steps(
            self, tmp_path):
        _fresh()
        feed = _conv_data(seed=1)
        prog, startup, loss = _build_conv()
        exe = fluid.Executor(fluid.CPUPlace())
        sc = fluid.Scope()
        exe.run(startup, scope=sc)
        from paddle_tpu.inference.export import export_train_program
        art = export_train_program(prog, sc, feed, [loss.name],
                                   str(tmp_path / "conv_bn"))
        steps = 4
        for _ in range(steps):
            exe.run(prog, feed=feed, fetch_list=[loss], scope=sc)
        native.run_xla_train(art, steps)
        import json
        import os
        with open(os.path.join(art, "manifest.json")) as f:
            man = json.load(f)
        spec = next(s for s in man["inputs"]
                    if "global_0" in s["name"])
        fin = np.fromfile(os.path.join(art, spec["file"] + ".final"),
                          dtype=spec["dtype"]).reshape(spec["shape"])
        np.testing.assert_allclose(
            fin, np.asarray(sc._get(spec["name"])),
            rtol=1e-5, atol=1e-6)


def _build_transformer():
    from paddle_tpu.models import transformer as T

    main, startup, cost = T.build_program(
        seq_len=8, d_model=32, n_heads=2, n_layers=1, d_inner=64,
        vocab=64, dropout_rate=0.0, learning_rate=1.0,
        warmup_steps=40)
    main._seed = 5
    return main, startup, cost


def _transformer_data(seed=0):
    r = np.random.RandomState(seed)
    return {k: r.randint(1, 64, (8, 8)).astype(np.int64)
            for k in ("src_ids", "tgt_ids", "label")}


@pytest.mark.skipif(not _native_ready(),
                    reason="no toolchain/XLA runtime for xla_train")
class TestTransformerSliceBinaryDriver:
    """THIRD model family through the C++ builder: the full
    encoder-decoder transformer (fused-QKV attention self+cross,
    layer_norm, label-smoothed CE, the noam lr chain, Adam)."""

    def test_transformer_losses_match_python_to_1e5(self, tmp_path):
        _fresh()
        feed = _transformer_data()
        main, startup, cost = _build_transformer()
        exe = fluid.Executor(fluid.CPUPlace())
        sc = fluid.Scope()
        exe.run(startup, scope=sc)
        from paddle_tpu.inference.export import export_train_program
        art = export_train_program(main, sc, feed, [cost.name],
                                   str(tmp_path / "tf_native"))
        steps = 5
        py = []
        for _ in range(steps):
            l, = exe.run(main, feed=feed, fetch_list=[cost], scope=sc)
            py.append(float(np.asarray(l).reshape(-1)[0]))
        rows = native.run_xla_train(art, steps)
        nat = [row[cost.name] for row in rows]
        np.testing.assert_allclose(nat, py, rtol=2e-5, atol=2e-6)
        assert py[-1] < py[0]


@pytest.mark.skipif(not _native_ready(),
                    reason="no toolchain/XLA runtime for xla_train")
class TestNativeControlFlow:
    """Sub-block control flow in the C++ builder (closes the 'block 0
    only, no control flow' limitation): the transformer's
    autoregressive greedy decode — a lax.while_loop program with a
    23-op loop body — builds as an xla::While and reproduces the
    traced path token for token."""

    def test_greedy_decode_matches_traced_tokens(self):
        from paddle_tpu.models import transformer as T

        _fresh()
        main, startup, cost = T.build_program(
            seq_len=8, d_model=32, n_heads=2, n_layers=1, d_inner=64,
            vocab=32, dropout_rate=0.0, learning_rate=2.0,
            warmup_steps=40)
        main._seed = 5
        r = np.random.RandomState(0)
        src = r.randint(3, 32, (8, 8)).astype(np.int64)
        tgt = np.concatenate(
            [np.ones((8, 1), np.int64), src[:, :-1]], 1)
        exe = fluid.Executor(fluid.CPUPlace())
        sc = fluid.Scope()
        exe.run(startup, scope=sc)
        for _ in range(40):
            exe.run(main, feed={"src_ids": src, "tgt_ids": tgt,
                                "label": src},
                    fetch_list=[cost], scope=sc)
        dec, _, _, out_ids = T.build_greedy_decode_program(
            seq_len=8, max_out_len=9, d_model=32, n_heads=2,
            n_layers=1, d_inner=64, vocab=32, start_id=1, end_id=2)
        ref, = exe.run(dec, feed={"src_ids": src},
                       fetch_list=[out_ids], scope=sc)
        fluid.set_flags({"FLAGS_native_build": True})
        try:
            nat, = exe.run(dec, feed={"src_ids": src},
                           fetch_list=[out_ids], scope=sc)
        finally:
            fluid.set_flags({"FLAGS_native_build": False})
        np.testing.assert_array_equal(np.asarray(nat),
                                      np.asarray(ref))
        # the KV-CACHED incremental decode (batched matmul/transpose2
        # cache reads, greater_than freeze masks) builds natively too
        inc, _, _, inc_out = T.build_incremental_decode_program(
            seq_len=8, max_out_len=9, d_model=32, n_heads=2,
            n_layers=1, d_inner=64, vocab=32, start_id=1, end_id=2)
        iref, = exe.run(inc, feed={"src_ids": src},
                        fetch_list=[inc_out], scope=sc)
        fluid.set_flags({"FLAGS_native_build": True})
        try:
            inat, = exe.run(inc, feed={"src_ids": src},
                            fetch_list=[inc_out], scope=sc)
        finally:
            fluid.set_flags({"FLAGS_native_build": False})
        np.testing.assert_array_equal(np.asarray(inat),
                                      np.asarray(iref))
        np.testing.assert_array_equal(np.asarray(inat),
                                      np.asarray(ref))
        # and BEAM SEARCH: the third generation flavor (dense beam
        # step + unrolled backtrack) builds natively too
        bm, _, _, bouts = T.build_beam_decode_program(
            seq_len=8, max_out_len=9, d_model=32, n_heads=2,
            n_layers=1, d_inner=64, vocab=32, start_id=1, end_id=2,
            beam_size=2)
        bfetch = list(bouts) if isinstance(bouts, (list, tuple)) \
            else [bouts]
        brefs = exe.run(bm, feed={"src_ids": src[:1]},
                        fetch_list=bfetch, scope=sc)
        fluid.set_flags({"FLAGS_native_build": True})
        try:
            bnats = exe.run(bm, feed={"src_ids": src[:1]},
                            fetch_list=bfetch, scope=sc)
        finally:
            fluid.set_flags({"FLAGS_native_build": False})
        for a, b in zip(brefs, bnats):
            a, b = np.asarray(a), np.asarray(b)
            if np.issubdtype(a.dtype, np.floating):
                np.testing.assert_allclose(b, a, rtol=1e-5,
                                           atol=1e-6)
            else:
                np.testing.assert_array_equal(b, a)


@pytest.mark.skipif(not _native_ready(),
                    reason="no toolchain/XLA runtime for xla_train")
class TestNativeBuildExecutor:
    """FLAGS_native_build: the Executor consumes the C++-built
    computation in-process (StableHLO), trace path as oracle."""

    def _losses(self, build, feed, steps, native_build):
        _fresh()
        prog, startup, loss = build()
        exe = fluid.Executor(fluid.CPUPlace())
        sc = fluid.Scope()
        exe.run(startup, scope=sc)
        if native_build:
            fluid.set_flags({"FLAGS_native_build": True})
        try:
            out = []
            for _ in range(steps):
                l, = exe.run(prog, feed=feed, fetch_list=[loss],
                             scope=sc)
                out.append(float(np.asarray(l).reshape(-1)[0]))
        finally:
            fluid.set_flags({"FLAGS_native_build": False})
        return out

    def test_conv_model_parity(self):
        feed = _conv_data()
        base = self._losses(_build_conv, feed, 5, False)
        got = self._losses(_build_conv, feed, 5, True)
        np.testing.assert_allclose(got, base, rtol=1e-5, atol=1e-6)
        assert got[-1] < got[0]

    def test_mlp_adam_parity(self):
        def build():
            prog, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(prog, startup):
                x = fluid.layers.data("x", shape=[32],
                                      dtype="float32")
                y = fluid.layers.data("y", shape=[1], dtype="int64")
                h = fluid.layers.fc(x, 32, act="tanh")
                logits = fluid.layers.fc(h, 4)
                loss = fluid.layers.mean(
                    fluid.layers.softmax_with_cross_entropy(logits, y))
                fluid.optimizer.Adam(0.01).minimize(loss)
            return prog, startup, loss

        r = np.random.RandomState(2)
        feed = {"x": r.randn(32, 32).astype(np.float32),
                "y": r.randint(0, 4, (32, 1)).astype(np.int64)}
        base = self._losses(build, feed, 6, False)
        got = self._losses(build, feed, 6, True)
        np.testing.assert_allclose(got, base, rtol=1e-5, atol=1e-6)

    def test_gradient_merge_parity(self):
        """run_block_if (the optimizer gate GradientMergeOptimizer
        emits) builds as an xla::Conditional: the k=3 loss staircase
        matches the traced path bit for bit."""
        def build():
            prog, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(prog, startup):
                x = fluid.layers.data("x", shape=[16],
                                      dtype="float32")
                y = fluid.layers.data("y", shape=[1], dtype="int64")
                h = fluid.layers.fc(x, 32, act="relu")
                logits = fluid.layers.fc(h, 4)
                loss = fluid.layers.mean(
                    fluid.layers.softmax_with_cross_entropy(logits,
                                                            y))
                fluid.optimizer.GradientMergeOptimizer(
                    fluid.optimizer.SGD(0.1), k_steps=3).minimize(
                    loss)
            return prog, startup, loss

        r = np.random.RandomState(0)
        feed = {"x": r.randn(16, 16).astype(np.float32),
                "y": r.randint(0, 4, (16, 1)).astype(np.int64)}
        base = self._losses(build, feed, 9, False)
        got = self._losses(build, feed, 9, True)
        np.testing.assert_allclose(got, base, rtol=1e-5, atol=1e-6)
        assert got[0] == got[1] == got[2]  # merge window
        assert got[3] < got[0]             # k-th step applied

    def test_transformer_parity(self):
        feed = _transformer_data()
        base = self._losses(_build_transformer, feed, 5, False)
        got = self._losses(_build_transformer, feed, 5, True)
        np.testing.assert_allclose(got, base, rtol=2e-5, atol=2e-6)

    def test_edge_semantics_match_traced(self):
        """Pin the decode-slice kernels' edge semantics against the
        traced oracle: floor-mod with negatives, expand tiling,
        gather, top_k values+indices, reduce_sum keep_dim and
        full-reduce shapes."""
        def both(build_fn, feeds):
            _fresh()
            prog, startup, fetches = build_fn()
            exe = fluid.Executor(fluid.CPUPlace())
            sc = fluid.Scope()
            exe.run(startup, scope=sc)
            ref = exe.run(prog, feed=feeds, fetch_list=fetches,
                          scope=sc)
            fluid.set_flags({"FLAGS_native_build": True})
            try:
                nat = exe.run(prog, feed=feeds, fetch_list=fetches,
                              scope=sc)
            finally:
                fluid.set_flags({"FLAGS_native_build": False})
            for i, (a, b) in enumerate(zip(ref, nat)):
                a, b = np.asarray(a), np.asarray(b)
                assert a.shape == b.shape, (i, a.shape, b.shape)
                if np.issubdtype(a.dtype, np.floating):
                    np.testing.assert_allclose(
                        b, a, rtol=1e-5, atol=1e-6, err_msg=str(i))
                else:
                    np.testing.assert_array_equal(
                        b, a, err_msg=str(i))

        def b_mod():
            prog, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(prog, startup):
                x = fluid.layers.data("x", shape=[6],
                                      dtype="float32")
                y = fluid.layers.data("y", shape=[6],
                                      dtype="float32")
                out = fluid.layers.elementwise_mod(x, y)
            return prog, startup, [out]

        both(b_mod,
             {"x": np.array([[-7., 7, -7, 5, -5, 0]], np.float32),
              "y": np.array([[3., 3, -3, -3, 5, 3]], np.float32)})

        def b_misc():
            prog, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(prog, startup):
                x = fluid.layers.data("x", shape=[3],
                                      dtype="float32")
                e = fluid.layers.expand(x, [2, 3])
                g = fluid.layers.gather(
                    x, fluid.layers.fill_constant([2], "int64", 1))
                tkv, tki = fluid.layers.topk(x, k=2)
                rs = fluid.layers.reduce_sum(x, dim=[1],
                                             keep_dim=True)
                rall = fluid.layers.reduce_sum(x, dim=[0, 1])
            return prog, startup, [e, g, tkv, tki, rs, rall]

        both(b_misc,
             {"x": np.array([[3., 1, 2], [6, 5, 4]], np.float32)})

    def test_unsupported_op_is_a_named_error(self):
        def build():
            prog, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(prog, startup):
                x = fluid.layers.data("x", shape=[8],
                                      dtype="float32")
                out = fluid.layers.atan(x)  # outside the native slice
            return prog, startup, out

        _fresh()
        prog, startup, out = build()
        exe = fluid.Executor(fluid.CPUPlace())
        sc = fluid.Scope()
        exe.run(startup, scope=sc)
        fluid.set_flags({"FLAGS_native_build": True})
        try:
            with pytest.raises(RuntimeError,
                               match="no native XLA kernel"):
                exe.run(prog, feed={"x": np.zeros((2, 8),
                                                  np.float32)},
                        fetch_list=[out], scope=sc)
        finally:
            fluid.set_flags({"FLAGS_native_build": False})
