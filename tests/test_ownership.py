"""Pool ownership & lifetime prover tests (the ownership domain of
paddle_tpu/analysis/absint.py + checkers PTA190/191/192).

Crafted fixtures pin the acceptance classes from ISSUE 14:

* the PROOF positive: the real block-table cell-addressing chain
  (``tab[lane, p//BS]*BS + p%BS`` through cast/scale/expand/add and
  the one-hot page/offset selection) resolves to a single exclusive
  source with the right bound, the named host assumption lands in the
  ledger, and PTA190/191/192 stay silent;
* ALIASED-WRITE fixtures: an index of unknown provenance (PTA190,
  chain printed), a direct non-masked_pool_write writer, a declared
  ``exclusive_via`` that disagrees with the proven provenance, and an
  index mixing two exclusive families (all PTA191, assumption named);
* the WRITE-WHILE-SHARED fixture: an index chaining to the refcounted
  ``prompt_entry_ref`` source is a PTA192 error — the COW contract;
* in-bounds: a mint-site bound exceeding the indexed axis is a PTA190
  error; an unbounded read is a warning;
* the PTA110 twin-dedupe and its non-convergence fallback.
"""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.analysis import ERROR, WARNING, absint, checkers, run_checks
from paddle_tpu.analysis.baseline import baseline_payload, collect_reports


def _guarded():
    main, startup = fluid.Program(), fluid.Program()
    return main, startup, fluid.program_guard(main, startup)


def _diags(program, code):
    return [d for d in run_checks(program) if d.code == code]


def _mk_pool(block, name="@own/self_k0@POOL", shape=(8, 4, 2, 8)):
    return block.create_var(name=name, shape=shape, dtype="float32",
                            persistable=True, stop_gradient=True)


def _mk_state(block, name, shape, dtype="int32"):
    return block.create_var(name=name, shape=shape, dtype=dtype,
                            persistable=True, stop_gradient=True)


def _block_table_chain(tab, act, rows=3, NP=2, BS=4, maxT=8):
    """The REAL paged addressing arithmetic (decode_engine._step_body
    condensed): write cell = tab[lane, page(t)]*BS + offset(t) via
    one-hot page/offset selection; gate = cast(active)."""
    stepv = _mk_state(tab.block, "@own/step", (rows,), "int64")
    tabf = layers.cast(tab, "float32")
    positions = layers.cast(layers.range(0, maxT, 1), "int64")
    step2 = layers.reshape(stepv, [rows, 1])
    t_mask = layers.cast(layers.equal(positions, step2), "float32")
    t_pages = layers.reshape(t_mask, [rows, NP, BS])
    page_oh = layers.reduce_sum(t_pages, dim=2)
    off_oh = layers.reduce_sum(t_pages, dim=1)
    offs = layers.assign(np.arange(BS, dtype="float32"))
    cur_block = layers.reduce_sum(
        layers.elementwise_mul(tabf, page_oh), dim=1)
    cur_off = layers.reduce_sum(
        layers.elementwise_mul(off_oh, offs), dim=1)
    write_idx = layers.cast(
        layers.elementwise_add(
            layers.scale(cur_block, scale=float(BS)), cur_off),
        "int32")
    gate = layers.cast(act, "float32")
    return write_idx, gate


class TestProvenanceEngine:
    def test_block_table_chain_proven_with_bound(self):
        main, startup, g = _guarded()
        with g:
            blk = main.global_block
            tab = _mk_state(blk, "@own/block_tab", (3, 2))
            act = _mk_state(blk, "@own/active", (3,), "int64")
            absint.mark_pool_index_source(tab, "block_table", bound=8)
            absint.mark_pool_index_source(act, "lane_active")
            write_idx, gate = _block_table_chain(tab, act)
        facts = absint.analyze(main)
        f = facts.prov_of(write_idx.name)
        assert f is not None and f.tags == ("block_table",)
        assert f.bound == 32        # NB*BS = 8*4: exactly the cells
        assert any("block_table mint" in c or "mark" in c
                   for c in f.chain)
        gf = facts.prov_of(gate.name)
        assert gf is not None and gf.tags == ("lane_active",)
        assert gf.indicator

    def test_mark_requires_registered_tag(self):
        main, startup, g = _guarded()
        with g:
            v = _mk_state(main.global_block, "@own/t", (3,))
            with pytest.raises(ValueError, match="unknown ownership"):
                absint.mark_pool_index_source(v, "no_such_source")

    def test_register_refuses_silent_redefinition(self):
        with pytest.raises(ValueError, match="already registered"):
            absint.register_pool_index_source(
                "block_table", "something else entirely",
                absint.TS_EXCLUSIVE)
        # idempotent re-registration of the identical entry is fine
        src = absint.pool_index_sources()["block_table"]
        absint.register_pool_index_source(
            src.tag, src.description, src.typestate, src.assumption,
            src.indicator)

    def test_typestate_seed_table_shape(self):
        srcs = absint.pool_index_sources()
        assert srcs["block_table"].typestate == absint.TS_EXCLUSIVE
        assert srcs["block_table"].assumption == \
            "HostBlockPool.alloc-disjoint"
        assert srcs["host_indices"].typestate == absint.TS_EXCLUSIVE
        assert srcs["prompt_entry_ref"].typestate == absint.TS_SHARED
        assert srcs["lane_active"].typestate == absint.TS_GATE


def _write_fixture(mark_idx=None, via="block_table", gate_mark=True,
                   idx_bound=32):
    """Pool + masked_pool_write through a FED index var, optionally
    marked; returns the program."""
    main, startup, g = _guarded()
    with g:
        blk = main.global_block
        pool = _mk_pool(blk)
        new = layers.data("new", shape=[3, 2, 8], dtype="float32",
                          append_batch_size=False)
        idx = layers.data("idx", shape=[3], dtype="int32",
                          append_batch_size=False)
        gate = layers.data("gate", shape=[3], dtype="float32",
                           append_batch_size=False)
        if mark_idx:
            absint.mark_pool_index_source(idx, mark_idx,
                                          bound=idx_bound)
        if gate_mark:
            absint.mark_pool_index_source(gate, "lane_active")
        layers.masked_pool_write(pool, new, idx, gate=gate,
                                 leading_dims=2, exclusive_via=via)
    return main


class TestPTA190:
    def test_unknown_provenance_write_is_error_with_chain(self):
        main = _write_fixture(mark_idx=None)
        ds = _diags(main, "PTA190")
        assert ds and ds[0].severity == ERROR
        assert "UNKNOWN provenance" in ds[0].message
        assert "chain" in ds[0].message  # the chain is printed

    def test_unmarked_gate_on_block_table_write_is_error(self):
        main = _write_fixture(mark_idx="block_table",
                              gate_mark=False)
        ds = [d for d in _diags(main, "PTA190")
              if "lane-active" in d.message]
        assert ds and ds[0].severity == ERROR

    def test_read_with_unknown_index_is_error(self):
        main, startup, g = _guarded()
        with g:
            blk = main.global_block
            pool = _mk_pool(blk)
            idx = layers.data("ridx", shape=[6], dtype="int32",
                              append_batch_size=False)
            flat = layers.reshape(pool, [32, 16])
            layers.gather(flat, idx)
        ds = _diags(main, "PTA190")
        assert ds and ds[0].severity == ERROR
        assert "read" in ds[0].message

    def test_bound_exceeding_axis_is_error(self):
        main, startup, g = _guarded()
        with g:
            blk = main.global_block
            pool = _mk_pool(blk)           # 8*4 = 32 cells
            idx = layers.data("ridx", shape=[6], dtype="int32",
                              append_batch_size=False)
            # the host invariant claims entries < 64: provably too
            # big for the 32-cell flattened view
            absint.mark_pool_index_source(idx, "block_table",
                                          bound=64)
            flat = layers.reshape(pool, [32, 16])
            layers.gather(flat, idx)
        ds = [d for d in _diags(main, "PTA190")
              if "exceeds" in d.message]
        assert ds and ds[0].severity == ERROR

    def test_unbounded_read_warns(self):
        main, startup, g = _guarded()
        with g:
            blk = main.global_block
            pool = _mk_pool(blk)
            idx = layers.data("ridx", shape=[6], dtype="int32",
                              append_batch_size=False)
            absint.mark_pool_index_source(idx, "block_table")
            flat = layers.reshape(pool, [32, 16])
            layers.gather(flat, idx)
        ds = [d for d in _diags(main, "PTA190")
              if "unprovable" in d.message]
        assert ds and ds[0].severity == WARNING

    def test_proven_chain_is_clean(self):
        main, startup, g = _guarded()
        with g:
            blk = main.global_block
            pool = _mk_pool(blk)
            tab = _mk_state(blk, "@own/block_tab", (3, 2))
            act = _mk_state(blk, "@own/active", (3,), "int64")
            absint.mark_pool_index_source(tab, "block_table", bound=8)
            absint.mark_pool_index_source(act, "lane_active")
            write_idx, gate = _block_table_chain(tab, act)
            new = layers.data("new", shape=[3, 2, 8],
                              dtype="float32",
                              append_batch_size=False)
            layers.masked_pool_write(pool, new, write_idx, gate=gate,
                                     leading_dims=2,
                                     exclusive_via="block_table")
        for code in ("PTA190", "PTA191", "PTA192", "PTA110"):
            assert not _diags(main, code), code


class TestProvenanceSoundness:
    """Regression pins for the review-found holes in the bound/
    one-hot algebra: each was a way to certify a LYING bound (a
    silent in-bounds pass — the exact failure class the prover
    exists to kill)."""

    def test_negative_constant_mints_no_fact(self):
        main, startup, g = _guarded()
        with g:
            neg = layers.fill_constant([3], "float32", -4.0)
            offs = layers.assign(np.array([-1.0, 2.0], "float32"))
        facts = absint.analyze(main)
        assert facts.prov_of(neg.name) is None
        assert facts.prov_of(offs.name) is None

    def test_sub_with_unsigned_subtrahend_drops_bound(self):
        # idx = tab - (a - b): (a - b) can be negative, so idx can
        # EXCEED tab's bound — the fact must not keep it
        main, startup, g = _guarded()
        with g:
            blk = main.global_block
            tab = _mk_state(blk, "@own/block_tab", (3,))
            absint.mark_pool_index_source(tab, "block_table", bound=8)
            a = layers.fill_constant([3], "float32", 2.0)
            b = layers.fill_constant([3], "float32", 5.0)
            maybe_neg = layers.elementwise_sub(a, b)
            idx = layers.elementwise_sub(layers.cast(tab, "float32"),
                                         maybe_neg)
        facts = absint.analyze(main)
        mn = facts.prov_of(maybe_neg.name)
        assert mn is not None and not mn.nonneg
        f = facts.prov_of(idx.name)
        assert f is not None and f.bound is None
        # the plain tab - const case keeps the bound (const >= 0)
        with fluid.program_guard(main):
            ok = layers.elementwise_sub(layers.cast(tab, "float32"),
                                        layers.fill_constant(
                                            [3], "float32", 1.0))
        f2 = absint.analyze(main).prov_of(ok.name)
        assert f2 is not None and f2.bound == 8

    def test_equal_same_shape_vector_is_not_onehot(self):
        # equal(range(N), ids[N]) can match EVERY position — only a
        # broadcast scalar-per-row comparison mints a one-hot
        main, startup, g = _guarded()
        with g:
            ids = layers.data("ids", shape=[8], dtype="int64",
                              append_batch_size=False)
            rng = layers.cast(layers.range(0, 8, 1), "int64")
            multi = layers.equal(rng, ids)
            scalar = layers.equal(rng, layers.reshape(
                layers.data("s", shape=[1], dtype="int64",
                            append_batch_size=False), [1, 1]))
        facts = absint.analyze(main)
        assert not facts.prov_of(multi.name).onehot
        assert facts.prov_of(scalar.name).onehot

    def test_row_reduce_drops_onehot(self):
        # the admission-mask shape: reduce_sum over axis 0 of an
        # [A, rows] one-hot COUNTS (up to A), it does not select
        main, startup, g = _guarded()
        with g:
            slots = layers.data("slots", shape=[4], dtype="int64",
                                append_batch_size=False)
            lane_range = layers.cast(layers.range(0, 6, 1), "int64")
            oh = layers.cast(layers.equal(
                lane_range, layers.reshape(slots, [4, 1])),
                "float32")
            counts = layers.reduce_sum(oh, dim=0)      # across rows
            per_row = layers.reduce_sum(
                layers.reshape(oh, [4, 2, 3]), dim=2)  # trailing
        facts = absint.analyze(main)
        assert facts.prov_of(oh.name).onehot
        cf = facts.prov_of(counts.name)
        assert cf is None or not (cf.onehot or cf.indicator)
        assert facts.prov_of(per_row.name).onehot

    def test_inverted_gate_is_rejected(self):
        # gate = 1 - active (a keep/write-mask mixup): the complement
        # is the IDLE mask — it must not inherit the lane_active tag,
        # or idle lanes write while active lanes freeze, proven-green
        main, startup, g = _guarded()
        with g:
            blk = main.global_block
            pool = _mk_pool(blk)
            idx = layers.data("idx", shape=[3], dtype="int32",
                              append_batch_size=False)
            absint.mark_pool_index_source(idx, "block_table",
                                          bound=32)
            act = _mk_state(blk, "@own/active", (3,), "int64")
            absint.mark_pool_index_source(act, "lane_active")
            inv = layers.elementwise_sub(
                layers.fill_constant([3], "float32", 1.0),
                layers.cast(act, "float32"))
            new = layers.data("new", shape=[3, 2, 8],
                              dtype="float32",
                              append_batch_size=False)
            layers.masked_pool_write(pool, new, idx, gate=inv,
                                     leading_dims=2,
                                     exclusive_via="block_table")
        ds = [d for d in _diags(main, "PTA190")
              if "lane-active" in d.message]
        assert ds and ds[0].severity == ERROR

    def test_row_merging_reshape_drops_onehot(self):
        # reshape folding the row axis INTO the block piles A
        # nonzeros into one block; only last-axis refactors keep it
        main, startup, g = _guarded()
        with g:
            slots = layers.data("slots", shape=[4], dtype="int64",
                                append_batch_size=False)
            lane_range = layers.cast(layers.range(0, 6, 1), "int64")
            oh = layers.cast(layers.equal(
                lane_range, layers.reshape(slots, [4, 1])),
                "float32")                         # [4, 6] one-hot
            merged = layers.reshape(oh, [24])      # rows folded in
            split = layers.reshape(oh, [4, 2, 3])  # block refactor
        facts = absint.analyze(main)
        assert not facts.prov_of(merged.name).onehot
        sf = facts.prov_of(split.name)
        assert sf.onehot and sf.oh_tail == 2

    def test_concat_of_onehots_is_not_onehot(self):
        main, startup, g = _guarded()
        with g:
            slots = layers.data("slots", shape=[4], dtype="int64",
                                append_batch_size=False)
            lane_range = layers.cast(layers.range(0, 6, 1), "int64")
            oh = layers.cast(layers.equal(
                lane_range, layers.reshape(slots, [4, 1])),
                "float32")
            both = layers.concat([oh, oh], axis=1)  # 2 nonzeros/row
        facts = absint.analyze(main)
        f = facts.prov_of(both.name)
        assert f is not None and not f.onehot and f.indicator

    def test_row_reduce_max_drops_onehot(self):
        # reduce_max over the row axis of a per-row one-hot is an
        # ANY-mask (up to A nonzeros), not a one-hot
        main, startup, g = _guarded()
        with g:
            slots = layers.data("slots", shape=[4], dtype="int64",
                                append_batch_size=False)
            lane_range = layers.cast(layers.range(0, 6, 1), "int64")
            oh = layers.cast(layers.equal(
                lane_range, layers.reshape(slots, [4, 1])),
                "float32")
            anymask = layers.reduce_max(oh, dim=0)
        facts = absint.analyze(main)
        f = facts.prov_of(anymask.name)
        assert f is not None and not f.onehot and f.indicator

    def test_transpose_drops_onehot(self):
        main, startup, g = _guarded()
        with g:
            slots = layers.data("slots", shape=[4], dtype="int64",
                                append_batch_size=False)
            lane_range = layers.cast(layers.range(0, 6, 1), "int64")
            oh = layers.cast(layers.equal(
                lane_range, layers.reshape(slots, [4, 1])),
                "float32")
            ohT = layers.transpose(oh, perm=[1, 0])
        facts = absint.analyze(main)
        f = facts.prov_of(ohT.name)
        assert f is not None and not f.onehot and f.indicator

    def test_rmw_counter_converges_via_widening(self):
        # a const-seeded counter RMW-bumped in a While used to grow
        # its bound by 1 per fixpoint iteration (an infinite
        # ascending chain): non-convergence silently disabled the
        # whole prover. The widening step jumps a re-grown bound to
        # unbounded, so the fixpoint terminates and the pool proofs
        # elsewhere in the program survive.
        main, startup, g = _guarded()
        with g:
            blk = main.global_block
            pool = _mk_pool(blk)
            tab = _mk_state(blk, "@own/block_tab", (3,))
            act = _mk_state(blk, "@own/active", (3,), "int64")
            absint.mark_pool_index_source(tab, "block_table", bound=8)
            absint.mark_pool_index_source(act, "lane_active")
            cnt = layers.fill_constant([1], "int64", 0)
            cond = layers.less_than(
                cnt, layers.fill_constant([1], "int64", 4.0))
            w = layers.While(cond)
            with w.block():
                one = layers.fill_constant([1], "int64", 1.0)
                layers.assign(layers.elementwise_add(cnt, one),
                              output=cnt)
                new = layers.fill_constant([3, 2, 8], "float32",
                                           0.0)
                idx = layers.cast(tab, "int32")
                gate = layers.cast(act, "float32")
                layers.masked_pool_write(
                    pool, new, idx, gate=gate, leading_dims=2,
                    exclusive_via="block_table")
                layers.less_than(
                    cnt, layers.fill_constant([1], "int64", 4.0),
                    cond=cond)
        facts = absint.analyze(main)
        assert facts.converged, facts.iterations
        cf = facts.prov_of(cnt.name)
        assert cf is not None and cf.bound is None  # widened
        # the in-loop pool write still PROVES
        writes = [a for a in facts.pool_accesses
                  if a.kind == "write"]
        assert writes and writes[0].index_fact.tags == \
            ("block_table",)
        for code in ("PTA190", "PTA191", "PTA192"):
            assert not _diags(main, code), code

    def test_ungated_write_is_one_incident_one_diagnostic(self):
        # no Gate input at all: PTA191 owns it; PTA190's gate check
        # only judges a gate that EXISTS (no double report)
        main, startup, g = _guarded()
        with g:
            blk = main.global_block
            pool = _mk_pool(blk)
            idx = layers.data("idx", shape=[3], dtype="int32",
                              append_batch_size=False)
            absint.mark_pool_index_source(idx, "block_table",
                                          bound=32)
            new = layers.data("new", shape=[3, 2, 8],
                              dtype="float32",
                              append_batch_size=False)
            blk.append_op(
                "masked_pool_write",
                {"Pool": [pool.name], "New": [new.name],
                 "Index": [idx.name]},
                {"Out": [pool.name]},
                {"leading_dims": 2, "exclusive_via": "block_table"})
        p190 = [d for d in _diags(main, "PTA190")
                if "gated" in d.message]
        p191 = [d for d in _diags(main, "PTA191")
                if "Gate" in d.message]
        assert len(p191) == 1 and len(p190) == 0

    def test_slice_of_pool_is_still_a_judged_read(self):
        # a pool read routed through slice must NOT escape PTA190
        main, startup, g = _guarded()
        with g:
            blk = main.global_block
            pool = _mk_pool(blk)
            idx = layers.data("ridx", shape=[4], dtype="int32",
                              append_batch_size=False)
            flat = layers.reshape(pool, [32, 16])
            part = layers.slice(flat, axes=[0], starts=[0],
                                ends=[16])
            layers.gather(part, idx)
        ds = _diags(main, "PTA190")
        assert ds and ds[0].severity == ERROR


class TestPTA191:
    def test_direct_write_is_error(self):
        main, startup, g = _guarded()
        with g:
            pool = _mk_pool(main.global_block)
            zeros = layers.fill_constant([8, 4, 2, 8], "float32",
                                         0.0)
            layers.assign(zeros, output=pool)
        ds = _diags(main, "PTA191")
        assert ds and ds[0].severity == ERROR
        assert "directly" in ds[0].message

    def test_via_mismatch_names_the_assumption(self):
        # the builder DECLARES per-lane block-table exclusivity but
        # wires host-admission indices: the declaration names an
        # invariant nobody maintains for these indices
        main = _write_fixture(mark_idx="host_indices",
                              via="block_table", idx_bound=32)
        ds = [d for d in _diags(main, "PTA191")
              if "declares exclusive_via" in d.message]
        assert ds and ds[0].severity == ERROR
        assert "PromptPrefixCache.fresh-exclusive" in ds[0].message

    def test_mixed_exclusive_families_is_error(self):
        main, startup, g = _guarded()
        with g:
            blk = main.global_block
            pool = _mk_pool(blk)
            a = layers.data("ia", shape=[3], dtype="int32",
                            append_batch_size=False)
            b = layers.data("ib", shape=[3], dtype="int32",
                            append_batch_size=False)
            gate = layers.data("gate", shape=[3], dtype="float32",
                               append_batch_size=False)
            absint.mark_pool_index_source(a, "block_table", bound=8)
            absint.mark_pool_index_source(b, "host_indices",
                                          bound=4)
            absint.mark_pool_index_source(gate, "lane_active")
            mixed = layers.elementwise_add(a, b)
            new = layers.data("new", shape=[3, 2, 8],
                              dtype="float32",
                              append_batch_size=False)
            layers.masked_pool_write(pool, new, mixed, gate=gate,
                                     leading_dims=2,
                                     exclusive_via="block_table")
        ds = [d for d in _diags(main, "PTA191")
              if "mixes exclusive" in d.message]
        assert ds and ds[0].severity == ERROR

    def test_pta110_twin_dedupe_and_fallback(self, monkeypatch):
        main, startup, g = _guarded()
        with g:
            pool = _mk_pool(main.global_block)
            zeros = layers.fill_constant([8, 4, 2, 8], "float32",
                                         0.0)
            layers.assign(zeros, output=pool)
        # covered site: the defect surfaces as PTA191, PTA110 silent
        assert _diags(main, "PTA191")
        assert not _diags(main, "PTA110")
        # prover unavailable (non-convergence/crash): the PTA110
        # declaration checker is the fallback and still fires
        monkeypatch.setattr(checkers, "_ownership_coverage",
                            lambda program: None)
        ds = list(checkers.check_shared_pool_writes(main))
        assert ds and ds[0].code == "PTA110" and \
            ds[0].severity == ERROR


class TestPTA192:
    def test_write_while_shared_is_error(self):
        # a write through the REFCOUNTED prompt-entry refs: the
        # exact COW violation the radix/beam prefix work must not
        # ship — writes are only legal in the exclusive typestate
        main = _write_fixture(mark_idx="prompt_entry_ref",
                              via="host_indices", gate_mark=False,
                              idx_bound=32)
        ds = _diags(main, "PTA192")
        assert ds and ds[0].severity == ERROR
        assert "exclusive typestate" in ds[0].message
        assert "prompt_entry_ref" in ds[0].message

    def test_fresh_entry_write_is_clean(self):
        # the COW-correct path: host-fed FRESH entries (refcount==1)
        main = _write_fixture(mark_idx="host_indices",
                              via="host_indices", gate_mark=False,
                              idx_bound=32)
        assert not _diags(main, "PTA192")
        assert not _diags(main, "PTA191")

    def test_shared_read_is_legal(self):
        main, startup, g = _guarded()
        with g:
            blk = main.global_block
            pool = _mk_pool(blk, name="@own/cross_k0@POOL",
                            shape=(4, 2, 8, 8))
            pref = _mk_state(blk, "@own/prompt_ref", (3,))
            absint.mark_pool_index_source(pref, "prompt_entry_ref",
                                          bound=4)
            flat = layers.reshape(pool, [4, 2 * 8 * 8])
            layers.gather(flat, pref)
        assert not _diags(main, "PTA192")
        assert not _diags(main, "PTA190")


class TestLedgerAndBaseline:
    def _paged_bundle(self):
        from paddle_tpu.models import transformer as T
        from paddle_tpu.models.decode_engine import CacheConfig

        return T.build_decode_step_program(
            seq_len=8, max_out_len=8, d_model=32, n_heads=2,
            n_layers=1, d_inner=64, vocab=50, n_slots=2,
            state_prefix="@ownled/",
            cache=CacheConfig(layout="paged", block_size=4,
                              n_blocks=4, n_prompt_entries=2))

    def test_ledger_names_assumptions_on_shipped_programs(self):
        bundle = self._paged_bundle()
        facts = absint.analyze(bundle.step)
        led = facts.ownership_ledger()
        assert led["unproven"] == 0
        assert led["proven_writes"] >= 2      # self k/v pools
        assert "HostBlockPool.alloc-disjoint" in led["assumptions"]
        assert led["obligations"].get("gate=lane_active", 0) >= 2
        miss = bundle.serves[("miss", 2)]
        led2 = absint.analyze(miss).ownership_ledger()
        assert "PromptPrefixCache.fresh-exclusive" in \
            led2["assumptions"]

    def test_stable_ownership_facts_and_baseline_drift(self):
        bundle = self._paged_bundle()
        facts = absint.analyze(bundle.step)
        stable = facts.stable_ownership_facts()
        pools = [k for k in stable if "@POOL" in k]
        assert pools and "@assumptions" in stable
        assert any("⊢HostBlockPool.alloc-disjoint" in v
                   for v in stable.values())
        # baseline payload carries the section; a drifted fact fails
        # the gate until a reviewed refresh
        from paddle_tpu.analysis.baseline import (
            TargetReport, diff_against_baseline)

        rep = TargetReport("own:step")
        rep.ownership = dict(stable)
        payload = baseline_payload([rep])
        assert payload["version"] == 4  # liveness_facts joined in PR 18
        key = f"own:step|{pools[0]}"
        assert key in payload["ownership_facts"]
        base = {"ownership_facts":
                {**payload["ownership_facts"],
                 key: "writes[somewhere-else]"}}
        new, _res = diff_against_baseline([rep], base)
        assert any("ownership drift" in n for n in new)

    def test_version_bump_invalidates_cached_facts(self):
        main, startup, g = _guarded()
        with g:
            blk = main.global_block
            tab = _mk_state(blk, "@own/block_tab", (3, 2))
            idx = layers.cast(tab, "int32")
        facts0 = absint.analyze(main)
        assert facts0.prov_of(idx.name) is None
        absint.mark_pool_index_source(tab, "block_table", bound=8)
        facts1 = absint.analyze(main)
        f = facts1.prov_of(idx.name)
        assert f is not None and f.tags == ("block_table",)
