"""OpTest harness: numpy-reference forward checks + finite-difference
gradient checks for single ops.

Parity: reference python/paddle/fluid/tests/unittests/op_test.py
(check_output :368, check_grad :532, get_numeric_gradient :45) -- the
single most load-bearing test asset of the reference (SURVEY.md §4.1).
A subclass declares op_type/inputs/outputs/attrs; check_output runs the
op through a real Executor-compiled program; check_grad compares the
registered grad op against central finite differences.
"""
from __future__ import annotations

import unittest
from typing import Dict

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.core.program import Operator, grad_var_name
from paddle_tpu.core.registry import make_grad_ops, run_op
from paddle_tpu.core.types import as_datatype


class OpTest(unittest.TestCase):
    op_type: str = None
    inputs: Dict = {}
    outputs: Dict = {}
    attrs: Dict = {}

    def setUp(self):
        import paddle_tpu.core.program as prog_mod
        from paddle_tpu import unique_name

        prog_mod._main_program = fluid.Program()
        prog_mod._startup_program = fluid.Program()
        fluid._reset_global_scope()
        unique_name.switch()
        np.random.seed(90)
        fluid.seed(90)

    # ------------------------------------------------------------------
    def _build(self):
        prog = fluid.Program()
        block = prog.global_block
        feed = {}
        input_names = {}
        for slot, val in self.inputs.items():
            entries = val if isinstance(val, list) else [(slot, val)]
            names = []
            for name, arr in entries:
                arr = np.asarray(arr)
                block.create_var(name=name, shape=arr.shape,
                                 dtype=str(arr.dtype), is_data=True,
                                 stop_gradient=False)
                feed[name] = arr
                names.append(name)
            input_names[slot] = names
        out_names = {}
        for slot, val in self.outputs.items():
            if isinstance(val, list):
                names = [n for n, _ in val]
            else:
                names = [slot]
            for n in names:
                block.create_var(name=n)
            out_names[slot] = names
        block.append_op(self.op_type, input_names, out_names, self.attrs)
        return prog, feed, out_names

    def check_output(self, atol=1e-5, rtol=1e-5, no_check_set=()):
        prog, feed, out_names = self._build()
        exe = fluid.Executor()
        fetch = []
        expect = []
        for slot, val in self.outputs.items():
            if slot in no_check_set:
                continue
            entries = val if isinstance(val, list) else [(slot, val)]
            for (name, arr), fetch_name in zip(entries, out_names[slot]):
                fetch.append(fetch_name)
                expect.append(np.asarray(arr))
        got = exe.run(prog, feed=feed, fetch_list=fetch)
        for g, e, name in zip(got, expect, fetch):
            np.testing.assert_allclose(
                np.asarray(g, dtype=np.float64),
                np.asarray(e, dtype=np.float64),
                atol=atol, rtol=rtol,
                err_msg=f"{self.op_type}: output {name} mismatch")

    # ------------------------------------------------------------------
    def check_grad(self, inputs_to_check, output_name,
                   max_relative_error=0.005, delta=5e-3,
                   no_grad_set=frozenset()):
        """Analytic grad (via the registered grad op) vs central finite
        differences of the forward kernel, like op_test.py:45.
        Runs under x64 so the fd quotient is not drowned by fp32 noise
        (the reference computes numeric grads in float64 too)."""
        # jax >= 0.4.3x removed the jax.enable_x64 alias; the context
        # manager lives in jax.experimental
        from jax.experimental import enable_x64

        with enable_x64():
            self._check_grad_impl(inputs_to_check, output_name,
                                  max_relative_error, delta, no_grad_set)

    def _check_grad_impl(self, inputs_to_check, output_name,
                         max_relative_error, delta, no_grad_set):
        prog, feed, out_names = self._build()
        feed = {k: (v.astype("float64")
                    if np.issubdtype(np.asarray(v).dtype, np.floating)
                    else v) for k, v in feed.items()}
        block = prog.global_block
        op = block.ops[-1]

        def run_forward(feed_vals):
            env = dict(feed_vals)
            import jax

            rng = [__import__("jax").random.PRNGKey(90)]
            run_op(op, env, rng_cell=rng, rng_salt=0)
            return env

        # analytic gradients: seed d(output)=1/N (mean-style reduction to
        # scalar for a well-defined scalar objective)
        out_var = output_name
        env = run_forward({k: np.asarray(v) for k, v in feed.items()})
        out_val = np.asarray(env[out_var])
        scale = 1.0 / out_val.size

        grad_ops = make_grad_ops(op, no_grad_set=no_grad_set)
        genv = dict(env)
        genv[grad_var_name(out_var)] = np.full_like(
            out_val, scale, dtype=out_val.dtype)
        # zero grads for other outputs
        for slot, names in op.outputs.items():
            for n in names:
                gname = grad_var_name(n)
                if gname not in genv:
                    genv[gname] = np.zeros_like(np.asarray(env[n]))
        import jax

        for gop in grad_ops:
            run_op(gop, genv, rng_cell=[jax.random.PRNGKey(90)],
                   rng_salt=0)

        import jax
        import jax.numpy as jnp

        for in_name in inputs_to_check:
            analytic = np.asarray(genv[grad_var_name(in_name)])
            base = np.asarray(feed[in_name], dtype=np.float64)
            others = {k: np.asarray(v) for k, v in feed.items()}

            def objective(xp):
                out = run_forward({**others, in_name: xp})[out_var]
                return jnp.sum(out, dtype=jnp.float64) * scale

            n = base.size
            eye = (jnp.eye(n, dtype=jnp.float64) * delta).reshape(
                (n,) + base.shape)
            hi = jax.jit(jax.vmap(lambda e: objective(base + e)))(eye)
            lo = jax.jit(jax.vmap(lambda e: objective(base - e)))(eye)
            numeric = np.asarray((hi - lo) / (2 * delta)).reshape(
                base.shape)
            abs_err = np.abs(analytic.astype(np.float64) - numeric)
            denom = np.maximum(np.maximum(np.abs(analytic), np.abs(
                numeric)), 1e-3)
            rel = (abs_err / denom).max()
            self.assertLessEqual(
                rel, max_relative_error,
                msg=f"{self.op_type}: grad mismatch for {in_name}: "
                    f"max rel err {rel}")
