"""Productized MoE: top-k routing + Switch aux loss + the
layers.switch_moe Program path + ep=N/ep=1 interchangeability
(parallel/moe.py, ops/nn_ops.py switch_moe). VERDICT r2 #4."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as fluid
from paddle_tpu.parallel.mesh import make_mesh, MeshConfig
from paddle_tpu.parallel.moe import (
    route_tokens, moe_dense, moe_apply, expert_parallel)


def _fresh():
    fluid._reset_global_scope()
    from paddle_tpu import unique_name
    unique_name.switch()


class TestRouting:
    def test_top1_aux_loss_formula(self):
        r = np.random.RandomState(0)
        x = jnp.asarray(r.randn(32, 8).astype(np.float32))
        wg = jnp.asarray(r.randn(8, 4).astype(np.float32))
        _, _, aux, gates, _ = route_tokens(x, wg, capacity=32, top_k=1)
        g = np.asarray(gates)
        f = np.bincount(g.argmax(1), minlength=4) / 32.0
        want = 4 * float((f * g.mean(0)).sum())
        np.testing.assert_allclose(float(aux), want, rtol=1e-5)

    def test_aux_is_one_at_perfect_balance(self):
        # uniform router -> f_e = P_e = 1/E -> aux = E * E*(1/E^2) = 1
        x = jnp.ones((16, 8), jnp.float32)
        wg = jnp.zeros((8, 4), jnp.float32)
        _, _, aux, _, _ = route_tokens(x, wg, capacity=16, top_k=1)
        np.testing.assert_allclose(float(aux), 1.0, rtol=1e-6)

    def test_top2_combine_weights_normalized(self):
        r = np.random.RandomState(1)
        x = jnp.asarray(r.randn(8, 6).astype(np.float32))
        wg = jnp.asarray(r.randn(6, 4).astype(np.float32))
        dispatch, combine, _, gates, _ = route_tokens(
            x, wg, capacity=8, top_k=2)
        # per token: dispatched to exactly 2 experts, weights sum to 1
        per_tok = np.asarray(dispatch.sum((1, 2)))
        np.testing.assert_allclose(per_tok, 2.0)
        wsum = np.asarray(combine.sum((1, 2)))
        np.testing.assert_allclose(wsum, 1.0, rtol=1e-5)

    def test_capacity_drops_in_fifo_priority_order(self):
        # all 4 tokens pick expert 0 (identical rows); capacity 2 ->
        # first two kept, later two dropped
        x = jnp.ones((4, 4), jnp.float32)
        wg = jnp.asarray(
            np.eye(4, 3, dtype=np.float32) * 5.0)
        dispatch, _, _, _, drop = route_tokens(x, wg, capacity=2,
                                               top_k=1)
        kept = np.asarray(dispatch.sum((1, 2)))
        np.testing.assert_array_equal(kept, [1, 1, 0, 0])

    def test_second_choice_yields_to_first_choices(self):
        # GShard priority: every token's first choice is placed before
        # any token's second choice
        r = np.random.RandomState(2)
        x = jnp.asarray(r.randn(12, 6).astype(np.float32))
        wg = jnp.asarray(r.randn(6, 3).astype(np.float32))
        d1, _, _, gates, _ = route_tokens(x, wg, capacity=4, top_k=2)
        g = np.asarray(gates)
        first = g.argmax(1)
        # every token whose FIRST choice expert has <= capacity primary
        # takers in front of it must be dispatched to that expert
        for i in range(12):
            e = first[i]
            ahead = int((first[:i] == e).sum())
            if ahead < 4:
                assert float(d1[i, e].sum()) == 1.0, (i, e)


class TestDenseVsExpertParallel:
    def test_ep2_matches_dense_top1_and_top2(self):
        mesh = make_mesh(MeshConfig(ep=2), devices=jax.devices()[:2])
        r = np.random.RandomState(3)
        t, d, f, E = 16, 8, 16, 4
        x = jnp.asarray(r.randn(t, d).astype(np.float32))
        wg = jnp.asarray(r.randn(d, E).astype(np.float32))
        w1 = jnp.asarray(r.randn(E, d, f).astype(np.float32) * 0.3)
        w2 = jnp.asarray(r.randn(E, f, d).astype(np.float32) * 0.3)
        for k in (1, 2):
            got, aux_ep, _ = moe_apply(x, wg, w1, w2, mesh,
                                    capacity_factor=float(2 * E),
                                    top_k=k)
            want, aux_d, _ = moe_dense(x, wg, w1, w2, capacity=2 * t,
                                    top_k=k)
            np.testing.assert_allclose(np.asarray(got),
                                       np.asarray(want),
                                       atol=1e-5, rtol=1e-4)
            np.testing.assert_allclose(float(aux_ep), float(aux_d),
                                       rtol=1e-5)

    def test_ep4_matches_ep1_numerics(self):
        mesh = make_mesh(MeshConfig(ep=4), devices=jax.devices()[:4])
        r = np.random.RandomState(4)
        t, d, f, E = 32, 8, 16, 4
        x = jnp.asarray(r.randn(t, d).astype(np.float32))
        wg = jnp.asarray(r.randn(d, E).astype(np.float32))
        w1 = jnp.asarray(r.randn(E, d, f).astype(np.float32) * 0.3)
        w2 = jnp.asarray(r.randn(E, f, d).astype(np.float32) * 0.3)
        got, _, _ = moe_apply(x, wg, w1, w2, mesh,
                              capacity_factor=float(2 * E), top_k=2)
        want, _, _ = moe_dense(x, wg, w1, w2, capacity=2 * t, top_k=2)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-5, rtol=1e-4)


def _build_moe_classifier(E=4, top_k=1, aux_coeff=0.01, seed=7):
    prog, startup = fluid.Program(), fluid.Program()
    prog._seed = seed
    with fluid.program_guard(prog, startup):
        x = fluid.layers.data(name="x", shape=[16], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="int64")
        h = fluid.layers.fc(x, size=16, act="tanh",
                            param_attr=fluid.ParamAttr(name="in_w"),
                            bias_attr=fluid.ParamAttr(name="in_b"))
        moe_out, aux = fluid.layers.switch_moe(
            h, num_experts=E, d_inner=32, top_k=top_k,
            capacity_factor=4.0, name="moe0")
        h = fluid.layers.elementwise_add(h, moe_out)
        logits = fluid.layers.fc(h, size=4,
                                 param_attr=fluid.ParamAttr(name="out_w"),
                                 bias_attr=fluid.ParamAttr(name="out_b"))
        ce = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, y))
        loss = fluid.layers.elementwise_add(
            ce, fluid.layers.scale(aux, scale=aux_coeff))
        fluid.optimizer.Adam(0.01).minimize(loss)
    return prog, startup, ce, aux


class TestSwitchMoeProgram:
    def _data(self):
        r = np.random.RandomState(0)
        xs = r.randn(64, 16).astype(np.float32)
        ys = np.argmax(xs[:, :4], 1).astype(np.int64)[:, None]
        return xs, ys

    def test_trains_through_executor(self):
        xs, ys = self._data()
        prog, startup, ce, aux = _build_moe_classifier()
        exe = fluid.Executor(fluid.CPUPlace())
        sc = fluid.Scope()
        exe.run(startup, scope=sc)
        losses = []
        for i in range(40):
            l, a = exe.run(prog, feed={"x": xs, "y": ys},
                           fetch_list=[ce, aux], scope=sc)
            losses.append(float(np.asarray(l).reshape(-1)[0]))
        assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])
        # expert weights actually trained (grads flow through a2a-free
        # dense path)
        w1 = np.asarray(sc._get("moe0_expert_w1"))
        assert np.isfinite(w1).all()

    def test_aux_loss_balances_experts(self):
        """With the aux loss, primary-assignment fractions stay near
        uniform; without it, routing is measurably less balanced."""
        xs, ys = self._data()

        def final_balance(aux_coeff, seed):
            _fresh()
            prog, startup, ce, aux = _build_moe_classifier(
                aux_coeff=aux_coeff, seed=seed)
            exe = fluid.Executor(fluid.CPUPlace())
            sc = fluid.Scope()
            exe.run(startup, scope=sc)
            for i in range(60):
                exe.run(prog, feed={"x": xs, "y": ys},
                        fetch_list=[ce], scope=sc)
            # measure primary assignment fractions with the trained
            # gate
            from paddle_tpu.parallel.moe import route_tokens
            h = np.tanh(xs @ np.asarray(sc._get("in_w"))
                        + np.asarray(sc._get("in_b")))
            gates = jax.nn.softmax(
                jnp.asarray(h) @ jnp.asarray(
                    np.asarray(sc._get("moe0_gate_w"))), axis=-1)
            f = np.bincount(np.asarray(gates).argmax(1), minlength=4) \
                / len(h)
            return float(((f - 0.25) ** 2).sum())

        imb_with = np.median([final_balance(0.05, s)
                              for s in (7, 8, 9)])
        imb_without = np.median([final_balance(0.0, s)
                                 for s in (7, 8, 9)])
        assert imb_with < imb_without + 1e-9, \
            (imb_with, imb_without)
        assert imb_with < 0.05, imb_with

    def test_top2_program_path(self):
        xs, ys = self._data()
        _fresh()
        prog, startup, ce, aux = _build_moe_classifier(top_k=2)
        exe = fluid.Executor(fluid.CPUPlace())
        sc = fluid.Scope()
        exe.run(startup, scope=sc)
        losses = []
        for i in range(30):
            l, = exe.run(prog, feed={"x": xs, "y": ys},
                         fetch_list=[ce], scope=sc)
            losses.append(float(np.asarray(l).reshape(-1)[0]))
        assert losses[-1] < losses[0] * 0.7

    def test_expert_parallel_scope_routes_the_op(self):
        """Same program, ep=2 scope vs no scope: same loss values in
        the no-drop capacity regime."""
        xs, ys = self._data()
        _fresh()
        prog, startup, ce, aux = _build_moe_classifier()
        exe = fluid.Executor(fluid.CPUPlace())
        sc = fluid.Scope()
        exe.run(startup, scope=sc)
        base, = exe.run(prog, feed={"x": xs, "y": ys},
                        fetch_list=[ce], scope=sc)

        _fresh()
        prog2, startup2, ce2, aux2 = _build_moe_classifier()
        sc2 = fluid.Scope()
        exe2 = fluid.Executor(fluid.CPUPlace())
        exe2.run(startup2, scope=sc2)
        mesh = make_mesh(MeshConfig(ep=2), devices=jax.devices()[:2])
        with expert_parallel(mesh):
            got, = exe2.run(prog2, feed={"x": xs, "y": ys},
                            fetch_list=[ce2], scope=sc2)
        np.testing.assert_allclose(np.asarray(base), np.asarray(got),
                                   rtol=1e-4, atol=1e-5)


class TestMoeTransformerVariant:
    """A transformer layer stack whose FFN is switch_moe, trained
    through the Program path (the VERDICT 'MoE transformer' bar)."""

    def test_moe_transformer_block_trains(self):
        V, T, D = 40, 8, 32
        r = np.random.RandomState(0)
        src = r.randint(1, V, (8, T)).astype(np.int64)
        lab = np.roll(src, -1, axis=1)

        prog, startup = fluid.Program(), fluid.Program()
        prog._seed = 5
        with fluid.program_guard(prog, startup):
            ids = fluid.layers.data(name="src", shape=[T],
                                    dtype="int64")
            y = fluid.layers.data(name="y", shape=[T], dtype="int64")
            emb = fluid.layers.embedding(
                ids, size=[V, D],
                param_attr=fluid.ParamAttr(name="emb"))
            aux_total = None
            h = emb
            for li in range(2):
                qkv = fluid.layers.reshape(h, [-1, T, 2, D // 2])
                attn = fluid.layers.attention(
                    qkv, qkv, qkv, causal=True, layout="bthd",
                    name=f"l{li}_attn")
                attn = fluid.layers.reshape(attn, [-1, T, D])
                h = fluid.layers.layer_norm(
                    fluid.layers.elementwise_add(h, attn),
                    param_attr=fluid.ParamAttr(name=f"l{li}_ln1_w"),
                    bias_attr=fluid.ParamAttr(name=f"l{li}_ln1_b"))
                moe_out, aux = fluid.layers.switch_moe(
                    h, num_experts=4, d_inner=64, top_k=2,
                    capacity_factor=4.0, name=f"l{li}_moe")
                h = fluid.layers.layer_norm(
                    fluid.layers.elementwise_add(h, moe_out),
                    param_attr=fluid.ParamAttr(name=f"l{li}_ln2_w"),
                    bias_attr=fluid.ParamAttr(name=f"l{li}_ln2_b"))
                aux_total = aux if aux_total is None else \
                    fluid.layers.elementwise_add(aux_total, aux)
            logits = fluid.layers.fc(
                h, size=V, num_flatten_dims=2, bias_attr=False,
                param_attr=fluid.ParamAttr(name="head_w"))
            ce = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(
                    logits, fluid.layers.unsqueeze(y, [2])))
            loss = fluid.layers.elementwise_add(
                ce, fluid.layers.scale(aux_total, scale=0.01))
            fluid.optimizer.Adam(0.005).minimize(loss)

        exe = fluid.Executor(fluid.CPUPlace())
        sc = fluid.Scope()
        exe.run(startup, scope=sc)
        losses = []
        for i in range(60):
            l, = exe.run(prog, feed={"src": src, "y": lab},
                         fetch_list=[ce], scope=sc)
            losses.append(float(np.asarray(l).reshape(-1)[0]))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0] * 0.6, (losses[0], losses[-1])


def _np_switch_moe(x, wg, w1, w2, capacity, top_k):
    """Independent numpy oracle for the switch_moe op (top-k routing,
    FIFO capacity, Switch/GShard combine scaling)."""
    t, d = x.shape
    E = wg.shape[1]
    logits = x.astype(np.float64) @ wg.astype(np.float64)
    z = np.exp(logits - logits.max(1, keepdims=True))
    gates = z / z.sum(1, keepdims=True)
    order = np.argsort(-gates, axis=1)[:, :top_k]
    gval = np.take_along_axis(gates, order, axis=1)
    if top_k > 1:
        scale = gval / np.maximum(gval.sum(1, keepdims=True), 1e-9)
    else:
        scale = gval
    counts = np.zeros(E, int)
    out = np.zeros((t, d), np.float64)
    assigned = []
    for j in range(top_k):
        for i in range(t):
            e = order[i, j]
            if counts[e] < capacity:
                assigned.append((i, e, scale[i, j]))
                counts[e] += 1
    for i, e, s in assigned:
        h = np.maximum(x[i].astype(np.float64) @ w1[e].astype(
            np.float64), 0.0)
        out[i] += s * (h @ w2[e].astype(np.float64))
    f = np.bincount(order[:, 0], minlength=E) / t
    aux = E * float((f * gates.mean(0)).sum())
    return out.astype(np.float32), np.float32(aux)


from tests.op_test import OpTest  # noqa: E402


class TestSwitchMoeOp(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "switch_moe"
        # seed chosen so no dispatched relu pre-activation sits
        # within the fd window of zero and routing margins are wide
        # (fd through a relu kink corrupts the quotient)
        r = np.random.RandomState(9)
        t, d, f, E = 12, 6, 10, 3
        # scale logits up so fd perturbations (5e-3) never flip the
        # routing argmax (discontinuity would break the fd quotient)
        x = (r.randn(t, d) * 1.0).astype(np.float32)
        wg = (r.randn(d, E) * 2.0).astype(np.float32)
        w1 = (r.randn(E, d, f) * 0.4).astype(np.float32)
        w2 = (r.randn(E, f, d) * 0.4).astype(np.float32)
        cf = 4.0
        cap = max(1, int(cf * 1 * t / E))
        out, aux = _np_switch_moe(x, wg, w1, w2, cap, top_k=1)
        self.inputs = {"X": x, "GateW": wg, "W1": w1, "W2": w2}
        self.attrs = {"top_k": 1, "capacity_factor": cf}
        self.outputs = {"Out": out, "AuxLoss": aux.reshape(1)}

    def test_output(self):
        self.check_output(atol=1e-4, rtol=1e-4)

    def test_grad(self):
        self.check_grad(["X", "GateW", "W1", "W2"], "Out",
                        max_relative_error=0.02, delta=1e-3)


class TestSwitchMoeOpTop2(OpTest):
    def setUp(self):
        super().setUp()
        self.op_type = "switch_moe"
        r = np.random.RandomState(22)
        t, d, f, E = 8, 6, 10, 4
        x = r.randn(t, d).astype(np.float32)
        wg = (r.randn(d, E) * 2.0).astype(np.float32)
        w1 = (r.randn(E, d, f) * 0.4).astype(np.float32)
        w2 = (r.randn(E, f, d) * 0.4).astype(np.float32)
        cf = 8.0
        cap = max(1, int(cf * 2 * t / E))
        out, aux = _np_switch_moe(x, wg, w1, w2, cap, top_k=2)
        self.inputs = {"X": x, "GateW": wg, "W1": w1, "W2": w2}
        self.attrs = {"top_k": 2, "capacity_factor": cf}
        self.outputs = {"Out": out, "AuxLoss": aux.reshape(1)}

    def test_output(self):
        self.check_output(atol=1e-4, rtol=1e-4)


class TestScopeCacheKey:
    def test_entering_scope_recompiles_cached_program(self):
        """Regression: the executable cache key must include the
        CP/EP scope state — running once OUTSIDE the scope then again
        INSIDE it (same shapes) must not serve the stale dense
        lowering."""
        _fresh()
        r = np.random.RandomState(0)
        xs = r.randn(16, 16).astype(np.float32)
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            x = fluid.layers.data(name="x", shape=[16],
                                  dtype="float32")
            out, aux = fluid.layers.switch_moe(
                x, num_experts=2, d_inner=8, capacity_factor=8.0,
                name="ck_moe")
        exe = fluid.Executor(fluid.CPUPlace())
        sc = fluid.Scope()
        exe.run(startup, scope=sc)
        # compile + run the dense lowering first
        dense, = exe.run(prog, feed={"x": xs}, fetch_list=[out],
                         scope=sc)
        mesh = make_mesh(MeshConfig(ep=2), devices=jax.devices()[:2])
        calls = {"n": 0}
        import paddle_tpu.parallel.moe as moe_mod
        orig = moe_mod.moe_apply

        def spy(*a, **k):
            calls["n"] += 1
            return orig(*a, **k)

        moe_mod.moe_apply = spy
        try:
            with expert_parallel(mesh):
                ep_out, = exe.run(prog, feed={"x": xs},
                                  fetch_list=[out], scope=sc)
        finally:
            moe_mod.moe_apply = orig
        assert calls["n"] == 1, "stale dense executable served"
        np.testing.assert_allclose(np.asarray(dense),
                                   np.asarray(ep_out),
                                   rtol=1e-4, atol=1e-5)


class TestPaddingAndDropStats:
    """VERDICT r3 weak #5: divisibility padding fallback + the
    drop-fraction observability surface."""

    def _setup(self, t, E, ep, seed=5):
        mesh = make_mesh(MeshConfig(ep=ep), devices=jax.devices()[:ep])
        r = np.random.RandomState(seed)
        d, f = 8, 16
        x = jnp.asarray(r.randn(t, d).astype(np.float32))
        wg = jnp.asarray(r.randn(d, E).astype(np.float32))
        w1 = jnp.asarray(r.randn(E, d, f).astype(np.float32) * 0.3)
        w2 = jnp.asarray(r.randn(E, f, d).astype(np.float32) * 0.3)
        return mesh, x, wg, w1, w2

    def test_nondivisible_tokens_match_dense(self):
        # 30 tokens over ep=4: padded to 32, pad rows masked out
        mesh, x, wg, w1, w2 = self._setup(t=30, E=4, ep=4)
        got, aux_ep, drop = moe_apply(x, wg, w1, w2, mesh,
                                      capacity_factor=float(2 * 4))
        want, aux_d, _ = moe_dense(x, wg, w1, w2, capacity=60)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-5, rtol=1e-4)
        np.testing.assert_allclose(float(aux_ep), float(aux_d),
                                   rtol=1e-5)
        assert float(drop) == 0.0

    def test_nondivisible_experts_match_dense(self):
        # 6 experts over ep=4: padded to 8 with -inf router columns
        mesh, x, wg, w1, w2 = self._setup(t=32, E=6, ep=4)
        got, aux_ep, _ = moe_apply(x, wg, w1, w2, mesh,
                                   capacity_factor=float(2 * 6))
        want, aux_d, _ = moe_dense(x, wg, w1, w2, capacity=64)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-5, rtol=1e-4)
        np.testing.assert_allclose(float(aux_ep), float(aux_d),
                                   rtol=1e-4)

    def test_drop_frac_counts_dropped_tokens(self):
        # all tokens want expert 0, capacity 2 of 8 -> 6/8 dropped
        x = jnp.ones((8, 4), jnp.float32)
        wg = jnp.asarray(np.eye(4, 3, dtype=np.float32) * 5.0)
        r = route_tokens(x, wg, capacity=2, top_k=1)
        np.testing.assert_allclose(float(r.drop_frac), 6.0 / 8.0)
        # big capacity -> nothing drops
        r2 = route_tokens(x, wg, capacity=8, top_k=1)
        assert float(r2.drop_frac) == 0.0

    def test_mask_excludes_pad_tokens_from_stats_and_capacity(self):
        r = np.random.RandomState(6)
        x = jnp.asarray(r.randn(8, 4).astype(np.float32))
        mask = jnp.asarray([1, 1, 1, 1, 1, 1, 0, 0], jnp.float32)
        res_m = route_tokens(x, wg := jnp.asarray(
            r.randn(4, 3).astype(np.float32)), capacity=8, mask=mask)
        res_6 = route_tokens(x[:6], wg, capacity=8)
        np.testing.assert_allclose(float(res_m.aux), float(res_6.aux),
                                   rtol=1e-6)
        np.testing.assert_allclose(float(res_m.drop_frac),
                                   float(res_6.drop_frac))
        # pad rows dispatch nowhere
        assert float(res_m.dispatch[6:].sum()) == 0.0

    def test_drop_frac_fetchable_through_program(self):
        _fresh()
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            x = fluid.layers.data(name="x", shape=[16],
                                  dtype="float32")
            out, aux, drop = fluid.layers.switch_moe(
                x, num_experts=4, d_inner=32, capacity_factor=0.25,
                name="m", return_drop_frac=True)
        exe = fluid.Executor(fluid.CPUPlace())
        sc = fluid.Scope()
        exe.run(startup, scope=sc)
        r = np.random.RandomState(0)
        d, a = exe.run(prog, feed={"x": r.randn(32, 16).astype(
            np.float32)}, fetch_list=[drop, aux], scope=sc)
        d = float(np.asarray(d).reshape(-1)[0])
        assert 0.0 <= d <= 1.0
        assert d > 0.0  # capacity_factor 0.25 must drop tokens

    def test_padded_capacity_not_shrunk(self):
        """Capacity must come from the padded per-shard token count:
        floor(t/n) would shrink real tokens' slots exactly when
        padding kicks in. t=30 over ep=4 pads to 32 -> full shards
        hold 8 real tokens, 2 per expert; capacity_factor=1.0 must
        give cap int(8/4) = 2 (zero drops), not
        int(floor(30/4)/4) = 1 (drops on every full shard)."""
        mesh = make_mesh(MeshConfig(ep=4), devices=jax.devices()[:4])
        d = E = 4
        # each shard of 8 tokens routes exactly 2 tokens per expert
        pattern = [0, 0, 1, 1, 2, 2, 3, 3]
        rows = []
        for shard in range(4):
            for e in pattern:
                rows.append(np.eye(d)[e] * 5.0)
        x = jnp.asarray(np.stack(rows[:30]).astype(np.float32))
        wg = jnp.asarray(np.eye(d, E, dtype=np.float32) * 5.0)
        r = np.random.RandomState(9)
        w1 = jnp.asarray(r.randn(E, d, 8).astype(np.float32) * 0.3)
        w2 = jnp.asarray(r.randn(E, 8, d).astype(np.float32) * 0.3)
        out, aux, drop = moe_apply(x, wg, w1, w2, mesh,
                                   capacity_factor=1.0, top_k=1)
        assert float(drop) == 0.0, float(drop)
        # every real token produced a nonzero row
        assert (np.abs(np.asarray(out)).sum(1) > 1e-7).all()
