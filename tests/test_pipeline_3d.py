"""3D parallelism through the pipeline path: 'dp' composes with the
pp ring (and tp) as an AUTO axis — GSPMD shards the microbatch rows
and inserts the grad reductions while the ring stays manual over 'pp'
(pipeline_program._dp_shard). Loss parity with the single-device
Executor under every composition, both schedules."""
import numpy as np

import jax

import paddle_tpu as fluid
from paddle_tpu.parallel.mesh import make_mesh, MeshConfig
from paddle_tpu.parallel.pipeline_program import PipelineTrainer


def _fresh():
    fluid._reset_global_scope()
    from paddle_tpu import unique_name
    unique_name.switch()


def _build_mlp(n_layers=4, seed=11):
    prog, startup = fluid.Program(), fluid.Program()
    prog._seed = seed
    with fluid.program_guard(prog, startup):
        x = fluid.layers.data(name="x", shape=[16], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="int64")
        h = x
        bounds = [h.name]
        for i in range(n_layers):
            h = fluid.layers.fc(
                h, size=16, act="tanh",
                param_attr=fluid.ParamAttr(name=f"l{i}_w"),
                bias_attr=fluid.ParamAttr(name=f"l{i}_b"))
            bounds.append(h.name)
        logits = fluid.layers.fc(
            h, size=3, param_attr=fluid.ParamAttr(name="head_w"),
            bias_attr=fluid.ParamAttr(name="head_b"))
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, y))
        fluid.optimizer.Adam(0.01).minimize(loss)
    return prog, startup, loss, bounds


def _mlp_data():
    rng = np.random.RandomState(0)
    xs = rng.randn(32, 16).astype(np.float32)
    ys = np.argmax(xs[:, :3], 1).astype(np.int64)[:, None]
    return {"x": xs, "y": ys}


def _exec_losses(prog, startup, loss, feed, steps):
    exe = fluid.Executor(fluid.CPUPlace())
    sc = fluid.Scope()
    exe.run(startup, scope=sc)
    out = []
    for _ in range(steps):
        l, = exe.run(prog, feed=feed, fetch_list=[loss], scope=sc)
        out.append(float(np.asarray(l).reshape(-1)[0]))
    return out


class TestPpDp:
    def _trainer_losses(self, schedule, steps=5):
        feed = _mlp_data()
        _fresh()
        prog, startup, loss, bounds = _build_mlp()
        base = _exec_losses(prog, startup, loss, feed, steps)
        _fresh()
        prog2, startup2, loss2, bounds2 = _build_mlp()
        exe = fluid.Executor(fluid.CPUPlace())
        sc = fluid.Scope()
        exe.run(startup2, scope=sc)
        mesh = make_mesh(MeshConfig(pp=2, dp=2),
                         devices=jax.devices()[:4])
        tr = PipelineTrainer(prog2, loss2, loops=[bounds2], mesh=mesh,
                             n_micro=4, schedule=schedule)
        tr.initialize(sc)
        got = [float(np.asarray(tr.run(feed=feed)[0]).reshape(-1)[0])
               for _ in range(steps)]
        np.testing.assert_allclose(base, got, rtol=2e-4, atol=2e-5)

    def test_gpipe_pp2_dp2_parity(self):
        self._trainer_losses("gpipe")

    def test_1f1b_pp2_dp2_parity(self):
        self._trainer_losses("1f1b")


class TestFull3D:
    def test_transformer_pp2_dp2_tp2_via_compiled_program(self):
        """pp x dp x tp on ONE 8-device mesh through the user API —
        ring manual over pp, matmuls partitioned over tp by the
        structural rules, batch rows over dp — with Executor loss
        parity."""
        from paddle_tpu.models import transformer as T

        def build():
            _fresh()
            main, startup, cost = T.build_program(
                seq_len=8, d_model=32, n_heads=2, n_layers=4,
                d_inner=64, vocab=60, dropout_rate=0.0,
                learning_rate=1.0, warmup_steps=40)
            main._seed = 5
            return main, startup, cost

        r = np.random.RandomState(0)
        feed = {k: r.randint(1, 60, (16, 8)).astype(np.int64)
                for k in ("src_ids", "tgt_ids", "label")}
        main, startup, cost = build()
        base = _exec_losses(main, startup, cost, feed, 4)
        main2, startup2, cost2 = build()
        exe = fluid.Executor(fluid.CPUPlace())
        sc = fluid.Scope()
        exe.run(startup2, scope=sc)
        mesh = make_mesh(MeshConfig(pp=2, dp=2, tp=2),
                         devices=jax.devices()[:8])
        cp = fluid.CompiledProgram(main2).with_data_parallel(
            loss_name=cost2.name, mesh=mesh, n_micro=4)
        got = []
        for _ in range(4):
            l, = exe.run(cp, feed=feed, fetch_list=[cost2], scope=sc)
            got.append(float(np.asarray(l).reshape(-1)[0]))
        np.testing.assert_allclose(base, got, rtol=5e-4, atol=5e-5)
        # tp placement really happened alongside dp
        tr = cp._pp_trainer
        from jax.sharding import PartitionSpec as P
        assert tr.state["logits.w"].sharding.spec == P(None, "tp")
