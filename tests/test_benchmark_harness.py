"""Benchmark harness tests (parity model: reference benchmark/fluid/
fluid_benchmark.py CLI semantics — per-pass examples/sec)."""
import numpy as np

from benchmark.fluid_benchmark import MODELS, parse_args, run_benchmark


def _args(**kw):
    argv = []
    for k, v in kw.items():
        if isinstance(v, bool):
            if v:
                argv.append(f"--{k}")
        else:
            argv += [f"--{k}", str(v)]
    args = parse_args(argv)
    if "batch_size" not in kw:
        args.batch_size = 8
    if "skip_batch_num" not in kw:
        args.skip_batch_num = 1
    if "iterations" not in kw:
        args.iterations = 2
    return args


class TestBenchmarkHarness:
    def test_model_registry_complete(self):
        # the reference benchmark model set must all be present
        for name in ("mnist", "resnet", "vgg", "se_resnext",
                     "stacked_dynamic_lstm", "machine_translation",
                     "transformer"):
            assert name in MODELS

    def test_mnist_speed_positive(self):
        res = run_benchmark(_args(model="mnist"))
        assert len(res) == 1
        assert res[0]["speed"] > 0
        assert res[0]["unit"] == "examples/sec"
        assert np.isfinite(res[0]["loss"])

    def test_lstm_counts_tokens(self):
        res = run_benchmark(_args(model="stacked_dynamic_lstm",
                                  batch_size=4))
        assert res[0]["unit"] == "tokens/sec"
        assert res[0]["speed"] > 0

    def test_parallel_mode_runs(self):
        res = run_benchmark(_args(model="mnist", parallel=True,
                                  batch_size=16))
        assert res[0]["speed"] > 0

    def test_multi_pass(self):
        res = run_benchmark(_args(model="word2vec", pass_num=2))
        assert len(res) == 2


    def test_zero_iterations_rejected(self):
        import pytest

        with pytest.raises(ValueError):
            run_benchmark(_args(model="word2vec", iterations=0))


class TestMeasurementHarness:
    """benchmark/harness.py: the interleaved best-of-N / fail-fast /
    telemetry scaffolding the seven bench configs share (extracted
    from their ad-hoc copies; no measured-number changes — these
    tests pin the selection semantics the configs relied on)."""

    def test_interleave_rounds_preserves_leg_order(self):
        from benchmark.harness import interleave_rounds

        calls = []
        legs = [("a", lambda: calls.append("a") or {"wall_s": 1.0}),
                ("b", lambda: calls.append("b") or {"wall_s": 2.0})]
        rounds = interleave_rounds(legs, rounds=3)
        # INTERLEAVED: a,b,a,b,a,b — never a,a,a,b,b,b (sequential
        # best-of-N lands whole legs in different throttle windows)
        assert calls == ["a", "b"] * 3
        assert len(rounds) == 3 and all(
            set(r) == {"a", "b"} for r in rounds)

    def test_best_leg_and_paired_ratio(self):
        from benchmark.harness import (best_leg, interleave_rounds,
                                       paired_ratio_max)

        data = iter([
            {"wall_s": 4.0, "tok_s": 100.0},   # a round 1
            {"wall_s": 1.0, "tok_s": 50.0},    # b round 1
            {"wall_s": 2.0, "tok_s": 400.0},   # a round 2
            {"wall_s": 3.0, "tok_s": 100.0},   # b round 2
        ])
        rounds = interleave_rounds(
            [("a", lambda: next(data)), ("b", lambda: next(data))],
            rounds=2)
        assert best_leg(rounds, "a")["wall_s"] == 2.0
        # PAIRED ratios: round1 100/50=2, round2 400/100=4 — the max
        # is 4, NOT best(a)/best(b) = 400/50 = 8 (window luck)
        assert paired_ratio_max(rounds, "a", "b") == 4.0

    def test_best_of_scalar(self):
        from benchmark.harness import best_of

        vals = iter([3.0, 9.0, 5.0])
        assert best_of(lambda: next(vals), 3) == 9.0

    def test_paired_median_ab_alternates_and_medians(self):
        from benchmark.harness import paired_median_ab

        modes_seen = []
        vals = {"a": iter([10.0, 20.0, 30.0]),
                "b": iter([10.0, 10.0, 10.0])}

        def run_leg():
            return next(vals[modes_seen[-1]]), None

        med, ratios, legs = paired_median_ab(
            run_leg, modes_seen.append, "a", "b", 3)
        # back-to-back pairs with alternating order per rep
        assert modes_seen == ["a", "b", "b", "a", "a", "b"]
        assert ratios == [1.0, 2.0, 3.0] and med == 2.0
        assert len(legs["a"]) == len(legs["b"]) == 3

    def test_write_bench_self_guards_schema(self, tmp_path,
                                            monkeypatch):
        import json

        import pytest

        from benchmark import harness

        monkeypatch.setattr(harness, "BENCH_DIR", str(tmp_path))
        res = harness.write_bench_self(
            "BENCH_SELF_t.json", {"metric": "m", "value": 1})
        assert "telemetry" in res  # r12 contract: every record
        on_disk = json.loads(
            (tmp_path / "BENCH_SELF_t.json").read_text())
        assert set(on_disk) == {"metric", "value", "telemetry"}
        # same schema: rewrites fine
        harness.write_bench_self("BENCH_SELF_t.json",
                                 {"metric": "m", "value": 2})
        # dropped field: the refactor-thins-the-record failure mode
        with pytest.raises(AssertionError, match="schema drifted"):
            harness.write_bench_self("BENCH_SELF_t.json",
                                     {"metric": "m"})
        # intentional evolution: explicit opt-in
        harness.write_bench_self("BENCH_SELF_t.json", {"metric": "m"},
                                 allow_schema_change=True)

    def test_bench_py_routes_through_harness(self):
        # the seven configs' scaffolding is the ONE implementation:
        # bench.py's module-level helpers must BE the harness's
        import bench
        from benchmark import harness

        assert bench._telemetry_snapshot is harness.telemetry_snapshot
        assert bench._write_bench_self is harness.write_bench_self
        assert bench._probe_backend is harness.probe_backend

    def test_committed_records_parse_with_schema_keys(self):
        # every committed BENCH_SELF record the configs would diff
        # against parses and carries the r12 telemetry key (the
        # schema guard compares against these files)
        import glob
        import json
        import os

        from benchmark.harness import BENCH_DIR

        # r12 introduced the telemetry key; every LATER record must
        # carry it (r11 and earlier are pre-contract history — listed
        # explicitly so records from r20 on are never silently
        # excluded from the check)
        pre_contract = {f"BENCH_SELF_r{n:02d}.json"
                        for n in range(0, 12)}
        recent = [p for p in glob.glob(
            os.path.join(BENCH_DIR, "BENCH_SELF_r*.json"))
            if os.path.basename(p) not in pre_contract]
        assert recent, "committed BENCH_SELF records missing"
        for p in recent:
            with open(p) as f:
                rec = json.load(f)
            assert "telemetry" in rec, p


class TestTrendSentinel:
    """benchmark/trend.py: the perf-trend drift gate over the
    committed BENCH_SELF history (the analysis_baseline.json
    discipline applied to the measured record). The fast lane runs
    the REAL gate in-process: the committed bench_trend.json must be
    current, and a synthetically regressed headline must fail."""

    def test_committed_store_is_current(self):
        # the tier-1-adjacent assertion: `python bench.py trend` on
        # this checkout is green — the store matches the files
        from benchmark import trend

        records = trend.build_records()
        store = trend.load_store()
        assert store is not None, \
            "bench_trend.json missing; run bench.py trend --write-trend"
        regressions, stale = trend.diff_against_store(records, store)
        assert not regressions, regressions
        assert not stale, stale

    def _tmp_history(self, tmp_path):
        import json
        import os
        import shutil

        from benchmark import trend
        from benchmark.harness import BENCH_DIR

        for f in os.listdir(BENCH_DIR):
            if f.startswith("BENCH_SELF_r") and f.endswith(".json"):
                shutil.copy(os.path.join(BENCH_DIR, f), tmp_path)
        store_path = str(tmp_path / "bench_trend.json")
        trend.write_store(path=store_path, bench_dir=str(tmp_path))
        return trend, json, store_path

    def test_synthetic_headline_regression_fails_loudly(self, tmp_path):
        trend, json, store_path = self._tmp_history(tmp_path)
        p = tmp_path / "BENCH_SELF_r13.json"
        rec = json.loads(p.read_text())
        rec["value"] = rec["value"] * 0.1  # collapse the headline
        p.write_text(json.dumps(rec))
        regs, stale = trend.diff_against_store(
            trend.build_records(str(tmp_path)),
            trend.load_store(store_path))
        assert any("REGRESSED" in r for r in regs), (regs, stale)
        assert trend.check(path=store_path,
                           bench_dir=str(tmp_path)) == 2

    def test_lost_parity_flag_is_a_regression(self, tmp_path):
        trend, json, store_path = self._tmp_history(tmp_path)
        p = tmp_path / "BENCH_SELF_r14.json"
        rec = json.loads(p.read_text())
        rec["token_parity_vs_whole_loop"] = False
        p.write_text(json.dumps(rec))
        regs, _ = trend.diff_against_store(
            trend.build_records(str(tmp_path)),
            trend.load_store(store_path))
        assert any("parity" in r for r in regs), regs

    def test_steady_state_compiles_appearing_is_a_regression(
            self, tmp_path):
        trend, json, store_path = self._tmp_history(tmp_path)
        p = tmp_path / "BENCH_SELF_r13.json"
        rec = json.loads(p.read_text())
        rec["steady_state_compiles"] = 3
        p.write_text(json.dumps(rec))
        regs, _ = trend.diff_against_store(
            trend.build_records(str(tmp_path)),
            trend.load_store(store_path))
        assert any("steady-state" in r for r in regs), regs

    def test_new_record_is_stale_until_appended(self, tmp_path):
        trend, json, store_path = self._tmp_history(tmp_path)
        src = json.loads((tmp_path / "BENCH_SELF_r14.json").read_text())
        (tmp_path / "BENCH_SELF_r99.json").write_text(json.dumps(src))
        regs, stale = trend.diff_against_store(
            trend.build_records(str(tmp_path)),
            trend.load_store(store_path))
        assert not regs
        assert any("BENCH_SELF_r99" in s and "--write-trend" in s
                   for s in stale), stale
        # the refresh appends it and goes green
        trend.write_store(path=store_path, bench_dir=str(tmp_path))
        assert trend.check(path=store_path,
                           bench_dir=str(tmp_path)) == 0

    def test_schema_drift_is_stale(self, tmp_path):
        trend, json, store_path = self._tmp_history(tmp_path)
        p = tmp_path / "BENCH_SELF_r12.json"
        rec = json.loads(p.read_text())
        rec.pop("observability_overhead")
        p.write_text(json.dumps(rec))
        _, stale = trend.diff_against_store(
            trend.build_records(str(tmp_path)),
            trend.load_store(store_path))
        assert any("schema drifted" in s for s in stale), stale

    def test_store_schema_version_guard(self, tmp_path):
        import pytest as _pytest

        trend, json, store_path = self._tmp_history(tmp_path)
        store = json.loads(open(store_path).read())
        store["schema_version"] = 99
        open(store_path, "w").write(json.dumps(store))
        with _pytest.raises(ValueError, match="schema_version"):
            trend.load_store(store_path)
        assert trend.check(path=store_path,
                           bench_dir=str(tmp_path)) == 2

    def test_headline_extraction_covers_every_era(self):
        # r02 results-list, r10 nested dict, r11+ flat — each era's
        # committed records must yield at least one headline (r05/r06
        # are TPU-outage rounds with no headline, excluded)
        from benchmark import trend

        by_round = {r["round"]: r for r in trend.build_records()}
        for rnd in (2, 7, 9, 10, 11, 12, 13, 14):
            assert by_round[rnd]["headlines"], rnd
        # parity flags surfaced from both nesting styles
        assert any("parity" in k
                   for k in by_round[13]["parity"])
        assert any(k.endswith("steady_state_compiles")
                   for k in by_round[13]["parity"])
