"""Benchmark harness tests (parity model: reference benchmark/fluid/
fluid_benchmark.py CLI semantics — per-pass examples/sec)."""
import numpy as np

from benchmark.fluid_benchmark import MODELS, parse_args, run_benchmark


def _args(**kw):
    argv = []
    for k, v in kw.items():
        if isinstance(v, bool):
            if v:
                argv.append(f"--{k}")
        else:
            argv += [f"--{k}", str(v)]
    args = parse_args(argv)
    if "batch_size" not in kw:
        args.batch_size = 8
    if "skip_batch_num" not in kw:
        args.skip_batch_num = 1
    if "iterations" not in kw:
        args.iterations = 2
    return args


class TestBenchmarkHarness:
    def test_model_registry_complete(self):
        # the reference benchmark model set must all be present
        for name in ("mnist", "resnet", "vgg", "se_resnext",
                     "stacked_dynamic_lstm", "machine_translation",
                     "transformer"):
            assert name in MODELS

    def test_mnist_speed_positive(self):
        res = run_benchmark(_args(model="mnist"))
        assert len(res) == 1
        assert res[0]["speed"] > 0
        assert res[0]["unit"] == "examples/sec"
        assert np.isfinite(res[0]["loss"])

    def test_lstm_counts_tokens(self):
        res = run_benchmark(_args(model="stacked_dynamic_lstm",
                                  batch_size=4))
        assert res[0]["unit"] == "tokens/sec"
        assert res[0]["speed"] > 0

    def test_parallel_mode_runs(self):
        res = run_benchmark(_args(model="mnist", parallel=True,
                                  batch_size=16))
        assert res[0]["speed"] > 0

    def test_multi_pass(self):
        res = run_benchmark(_args(model="word2vec", pass_num=2))
        assert len(res) == 2


    def test_zero_iterations_rejected(self):
        import pytest

        with pytest.raises(ValueError):
            run_benchmark(_args(model="word2vec", iterations=0))
