"""Misc parity module tests: lod_tensor, average, debugger,
net_drawer, evaluator, install_check, py_func, chunk_eval, Go.

Parity model: reference tests test_lod_tensor.py, test_py_func_op.py,
test_chunk_eval_op.py, test_install_check.py.
"""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import average, debugger, lod_tensor, net_drawer


def _run(fetches, feed=None):
    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(fluid.default_startup_program())
    return exe.run(feed=feed or {}, fetch_list=fetches)


class TestLodTensor:
    def test_create_and_validate(self):
        t = lod_tensor.create_lod_tensor(
            np.arange(10).reshape(10, 1).astype(np.float32),
            [[3, 3, 4]])
        assert t.has_valid_recursive_sequence_lengths()
        assert t.lod() == [[0, 3, 6, 10]]
        assert t.recursive_sequence_lengths() == [[3, 3, 4]]

    def test_invalid_lens_rejected(self):
        with pytest.raises(AssertionError):
            lod_tensor.create_lod_tensor(
                np.zeros((5, 1), np.float32), [[3, 3]])

    def test_from_list(self):
        t = lod_tensor.create_lod_tensor([[1, 2], [3, 4, 5]],
                                         [[2, 3]])
        assert np.asarray(t).shape == (5, 1)

    def test_padded_roundtrip(self):
        t = lod_tensor.create_lod_tensor(
            np.arange(7).reshape(7, 1).astype(np.float32), [[3, 4]])
        padded, lens = lod_tensor.to_padded(t)
        assert padded.shape == (2, 4, 1)
        assert lens.tolist() == [3, 4]
        back = lod_tensor.from_padded(padded, lens)
        np.testing.assert_allclose(np.asarray(back), np.asarray(t))

    def test_random_int(self):
        t = lod_tensor.create_random_int_lodtensor(
            [[2, 3]], [1], None, 0, 9)
        a = np.asarray(t)
        assert a.shape == (5, 1) and a.min() >= 0 and a.max() <= 9


class TestAverage:
    def test_weighted(self):
        wa = average.WeightedAverage()
        wa.add(1.0, 1)
        wa.add(3.0, 3)
        assert wa.eval() == pytest.approx(2.5)
        wa.reset()
        with pytest.raises(ValueError):
            wa.eval()


class TestDebugger:
    def test_program_print_and_dot(self, tmp_path):
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        y = fluid.layers.fc(input=x, size=2, act="relu")
        prog = fluid.default_main_program()
        text = debugger.pprint_program_codes(prog)
        assert "mul" in text and "var x" in text
        dot = debugger.draw_block_graphviz(
            prog.global_block, path=str(tmp_path / "g.dot"))
        assert "digraph" in dot and "mul" in dot
        assert (tmp_path / "g.dot").exists()

    def test_net_drawer(self, tmp_path):
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        fluid.layers.fc(input=x, size=2)
        out = net_drawer.draw_graph(fluid.default_startup_program(),
                                    fluid.default_main_program(),
                                    path=str(tmp_path / "n.dot"))
        assert "digraph" in out
        g = net_drawer.Graph("T")
        g.node("a")
        g.node("b")
        g.edge("a", "b")
        assert "a -> b" in str(g)


class TestPyFunc:
    def test_forward_and_backward(self):
        x = fluid.layers.data(name="x", shape=[3], dtype="float32",
                              stop_gradient=False)
        block = fluid.default_main_program().global_block
        out = block.create_var(name="pyf_out", shape=(-1, 3),
                               dtype="float32")

        def fwd(a):
            return np.asarray(a) * 2.0

        def bwd(a, o, do):
            return np.asarray(do) * 2.0

        fluid.layers.py_func(fwd, x, out, backward_func=bwd)
        loss = fluid.layers.reduce_sum(out)
        g, = fluid.gradients(loss, [x])
        xs = np.random.RandomState(0).randn(2, 3).astype(np.float32)
        o, gx = _run([out, g], {"x": xs})
        np.testing.assert_allclose(o, xs * 2.0, rtol=1e-6)
        np.testing.assert_allclose(gx, np.full_like(xs, 2.0),
                                   rtol=1e-6)

    def test_no_backward_func_stops_grad(self):
        x = fluid.layers.data(name="x", shape=[3], dtype="float32",
                              stop_gradient=False)
        block = fluid.default_main_program().global_block
        out = block.create_var(name="pyf2_out", shape=(-1, 3),
                               dtype="float32")
        fluid.layers.py_func(lambda a: np.asarray(a) + 1, x, out)
        loss = fluid.layers.reduce_sum(out)
        g = fluid.gradients(loss, [x])
        assert g[0] is None


class TestChunkEval:
    def test_perfect_iob(self):
        # IOB, 1 type: tags B=0, I=1, O=2
        seq = np.array([[0, 1, 2, 0, 1, 1]], np.int64)
        inf = fluid.layers.data(name="inf", shape=[6], dtype="int64")
        lab = fluid.layers.data(name="lab", shape=[6], dtype="int64")
        p, r, f1, ni, nl, nc = fluid.layers.chunk_eval(
            inf, lab, chunk_scheme="IOB", num_chunk_types=1)
        pv, rv, fv, niv, nlv, ncv = _run(
            [p, r, f1, ni, nl, nc], {"inf": seq, "lab": seq})
        assert fv[0] == pytest.approx(1.0)
        assert niv[0] == 2 and nlv[0] == 2 and ncv[0] == 2

    def test_partial_match(self):
        lab = np.array([[0, 1, 2, 0, 1, 1]], np.int64)
        inf = np.array([[0, 1, 2, 2, 2, 2]], np.int64)  # 1 of 2 chunks
        i = fluid.layers.data(name="inf", shape=[6], dtype="int64")
        l = fluid.layers.data(name="lab", shape=[6], dtype="int64")
        p, r, f1, *_ = fluid.layers.chunk_eval(
            i, l, chunk_scheme="IOB", num_chunk_types=1)
        pv, rv = _run([p, r], {"inf": inf, "lab": lab})
        assert pv[0] == pytest.approx(1.0)
        assert rv[0] == pytest.approx(0.5)


class TestChunkExtraction:
    def test_ioe_terminating_e_included(self):
        from paddle_tpu.ops.host_ops import _extract_chunks

        # I, E (one type): ONE chunk spanning both tokens
        assert _extract_chunks([0, 1], "IOE", 1, set()) == {(0, 1, 0)}
        # lone E is a complete chunk
        assert _extract_chunks([1], "IOE", 1, set()) == {(0, 0, 0)}

    def test_iobes_stray_tags_not_chunks(self):
        from paddle_tpu.ops.host_ops import _extract_chunks

        assert _extract_chunks([1], "IOBES", 1, set()) == set()  # I
        assert _extract_chunks([2], "IOBES", 1, set()) == set()  # E
        assert _extract_chunks([3], "IOBES", 1, set()) == \
            {(0, 0, 0)}  # S
        assert _extract_chunks([0, 1, 2], "IOBES", 1, set()) == \
            {(0, 2, 0)}  # B I E


class TestPyFuncMixedInputs:
    def test_no_grad_input_filtered(self):
        x = fluid.layers.data(name="x", shape=[3], dtype="float32",
                              stop_gradient=False)
        idx = fluid.layers.data(name="idx", shape=[3], dtype="int64")
        block = fluid.default_main_program().global_block
        out = block.create_var(name="mix_out", shape=(-1, 3),
                               dtype="float32")

        def fwd(a, i):
            return np.asarray(a) * np.asarray(i)

        def bwd(a, i, o, do):
            return (np.asarray(do) * np.asarray(i),
                    np.zeros_like(np.asarray(i)))

        fluid.layers.py_func(fwd, [x, idx], out, backward_func=bwd)
        loss = fluid.layers.reduce_sum(out)
        g, = fluid.gradients(loss, [x])
        xs = np.ones((2, 3), np.float32)
        iv = np.arange(6).reshape(2, 3).astype(np.int64)
        o, gx = _run([out, g], {"x": xs, "idx": iv})
        np.testing.assert_allclose(o, xs * iv, rtol=1e-6)
        np.testing.assert_allclose(gx, iv.astype(np.float32),
                                   rtol=1e-6)

    def test_skip_vars_in_backward_input(self):
        x = fluid.layers.data(name="x", shape=[2], dtype="float32",
                              stop_gradient=False)
        block = fluid.default_main_program().global_block
        out = block.create_var(name="sk_out", shape=(-1, 2),
                               dtype="float32")

        def fwd(a):
            return np.asarray(a) * 3.0

        def bwd(do):  # x skipped, out skipped -> only dout arrives
            return np.asarray(do) * 3.0

        fluid.layers.py_func(fwd, x, out, backward_func=bwd,
                             skip_vars_in_backward_input=[x, out])
        loss = fluid.layers.reduce_sum(out)
        g, = fluid.gradients(loss, [x])
        gx, = _run([g], {"x": np.ones((1, 2), np.float32)})
        np.testing.assert_allclose(gx, np.full((1, 2), 3.0))


class TestEvaluator:
    def test_chunk_evaluator_accumulates_and_resets(self):
        from paddle_tpu import evaluator

        inf = fluid.layers.data(name="inf", shape=[6], dtype="int64")
        lab = fluid.layers.data(name="lab", shape=[6], dtype="int64")
        ev = evaluator.ChunkEvaluator(inf, lab, chunk_scheme="IOB",
                                      num_chunk_types=1)
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(fluid.default_startup_program())
        seq = np.array([[0, 1, 2, 0, 1, 1]], np.int64)
        for _ in range(3):
            exe.run(feed={"inf": seq, "lab": seq},
                    fetch_list=[m.name for m in ev.metrics])
        p, r, f1 = ev.eval(exe)
        assert f1 == pytest.approx(1.0)
        ni = float(np.asarray(fluid.global_scope()._get(
            ev.num_infer_chunks.name)))
        assert ni == 6  # 2 chunks x 3 steps accumulated
        ev.reset(exe)
        assert float(np.asarray(fluid.global_scope()._get(
            ev.num_infer_chunks.name))) == 0


class TestGo:
    def test_go_runs_sub_block_concurrently(self):
        from paddle_tpu.ops.host_ops import wait_all_go

        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        with fluid.layers.Go(inputs=[x]):
            # side-effecting goroutine: doubles x into a host list
            import paddle_tpu.layers as L

            y = L.scale(x, scale=2.0)
        out = fluid.layers.scale(x, scale=3.0)
        xs = np.ones((2, 4), np.float32)
        o, = _run([out], {"x": xs})
        wait_all_go()
        np.testing.assert_allclose(o, xs * 3.0)


class TestInstallCheck:
    def test_run_check(self, capsys):
        fluid.install_check.run_check()
        assert "install check success" in capsys.readouterr().out
