"""DGC (Deep Gradient Compression) + gradient accumulation.

Parity: reference optimizer.py:589 DGCMomentumOptimizer,
details/all_reduce_op_handle.cc:65-227 encoded sparse allreduce,
ir/multi_batch_merge_pass.cc + distribute_transpiler.py:1649 grad merge.
"""
import numpy as np
import pytest

import paddle_tpu as fluid


def _toy_problem(seed=0, n=64, d=8, c=3):
    rng = np.random.RandomState(seed)
    xs = rng.randn(n, d).astype(np.float32)
    w = rng.randn(d, c).astype(np.float32)
    ys = np.argmax(xs @ w, 1).astype(np.int64)[:, None]
    return xs, ys


def _build(optimizer_fn, seed=7):
    prog, startup = fluid.Program(), fluid.Program()
    prog._seed = seed
    with fluid.program_guard(prog, startup):
        x = fluid.layers.data(name="x", shape=[8], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="int64")
        h = fluid.layers.fc(x, size=16, act="tanh",
                            param_attr=fluid.ParamAttr(name="w0"),
                            bias_attr=fluid.ParamAttr(name="b0"))
        logits = fluid.layers.fc(h, size=3,
                                 param_attr=fluid.ParamAttr(name="w1"),
                                 bias_attr=fluid.ParamAttr(name="b1"))
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, y))
        optimizer_fn(loss)
    return prog, startup, loss


def _train(optimizer_fn, steps, batch_iter, seed=7):
    prog, startup, loss = _build(optimizer_fn, seed)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    out = []
    for i in range(steps):
        xb, yb = batch_iter(i)
        l, = exe.run(prog, feed={"x": xb, "y": yb},
                     fetch_list=[loss], scope=scope)
        out.append(float(np.asarray(l).reshape(-1)[0]))
    return out, scope


class TestDGCPureFunctions:
    def test_rampup_schedule(self):
        import jax.numpy as jnp

        from paddle_tpu.parallel.dgc import rampup_sparsity

        s = [0.75, 0.9375, 0.999]
        get = lambda t: float(rampup_sparsity(
            jnp.asarray(t), s, rampup_begin_step=10, rampup_step=9))
        assert get(0) == 0.0 and get(9) == 0.0
        assert get(10) == pytest.approx(0.75)
        assert get(13) == pytest.approx(0.9375)
        assert get(16) == pytest.approx(0.999)
        assert get(100) == pytest.approx(0.999)  # stays at the top

    def test_pre_rampup_equals_momentum_kernel(self):
        import jax.numpy as jnp

        from paddle_tpu.parallel.dgc import dgc_momentum_step

        rng = np.random.RandomState(0)
        p = jnp.asarray(rng.randn(32).astype(np.float32))
        g = jnp.asarray(rng.randn(32).astype(np.float32))
        u = jnp.asarray(rng.randn(32).astype(np.float32))
        v = jnp.zeros(32, np.float32)
        mu, lr = 0.9, 0.1
        p1, u1, v1 = dgc_momentum_step(
            p, g, u, v, lr, mu=mu, step=jnp.asarray(3),
            sparsity=[0.999], rampup_begin_step=1000, rampup_step=1)
        u_ref = mu * u + g
        np.testing.assert_allclose(u1, u_ref, rtol=1e-6)
        np.testing.assert_allclose(p1, p - lr * u_ref, rtol=1e-6)
        np.testing.assert_array_equal(v1, v)

    def test_momentum_factor_masking_and_residual(self):
        import jax.numpy as jnp

        from paddle_tpu.parallel.dgc import dgc_momentum_step

        # 4 elements, sparsity 0.75 -> exactly the largest |v| is sent
        p = jnp.zeros(4, np.float32)
        g = jnp.asarray([0.1, -0.2, 3.0, 0.05], np.float32)
        u = jnp.zeros(4, np.float32)
        v = jnp.zeros(4, np.float32)
        p1, u1, v1 = dgc_momentum_step(
            p, g, u, v, 1.0, mu=0.9, step=jnp.asarray(5),
            sparsity=[0.75], rampup_begin_step=0, rampup_step=1)
        # element 2 transmitted: p updated there, u/v zeroed there
        np.testing.assert_allclose(p1[2], -3.0, rtol=1e-6)
        assert float(u1[2]) == 0.0 and float(v1[2]) == 0.0
        # untransmitted elements accumulate locally, params untouched
        np.testing.assert_allclose(np.asarray(p1)[[0, 1, 3]], 0.0)
        np.testing.assert_allclose(np.asarray(v1)[[0, 1, 3]],
                                   [0.1, -0.2, 0.05], rtol=1e-6)

    def test_compressed_allreduce_matches_dense_oracle(self):
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, PartitionSpec as P

        from paddle_tpu.parallel.dgc import compressed_allreduce

        devs = np.array(jax.devices()[:8])
        mesh = Mesh(devs, ("dp",))
        rng = np.random.RandomState(0)
        vs = rng.randn(8, 16).astype(np.float32)
        k = 3

        def worker(v):
            v = v[0]  # [16]
            agg, mask = compressed_allreduce(v, k, "dp")
            return agg[None], mask[None]

        agg, mask = jax.jit(jax.shard_map(
            worker, mesh=mesh, in_specs=P("dp"),
            out_specs=P("dp")))(vs)
        # oracle: per-worker top-k masked, then summed
        dense = np.zeros((8, 16), np.float32)
        for i in range(8):
            idx = np.argsort(-np.abs(vs[i]))[:k]
            dense[i, idx] = vs[i, idx]
        oracle = dense.sum(0)
        for i in range(8):
            np.testing.assert_allclose(np.asarray(agg)[i], oracle,
                                       rtol=1e-5)
            assert np.asarray(mask)[i].sum() == k

    def test_dgc_allreduce_step_trains_linear_regression(self):
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, PartitionSpec as P

        from paddle_tpu.parallel.dgc import dgc_allreduce_step

        devs = np.array(jax.devices()[:8])
        mesh = Mesh(devs, ("dp",))
        rng = np.random.RandomState(0)
        w_true = rng.randn(16).astype(np.float32)
        xs = rng.randn(64, 16).astype(np.float32)
        ys = xs @ w_true

        def step(p, u, v, x, y):
            p, u, v = p[0], u[0], v[0]

            def loss_fn(w):
                return jnp.mean((x @ w - y) ** 2)

            g = jax.grad(loss_fn)(p)
            p, u, v = dgc_allreduce_step(p, g, u, v, 0.05, mu=0.9,
                                         k=4, axis_name="dp")
            return p[None], u[None], v[None]

        smap = jax.jit(jax.shard_map(
            step, mesh=mesh,
            in_specs=(P("dp"), P("dp"), P("dp"), P("dp"), P("dp")),
            out_specs=(P("dp"), P("dp"), P("dp"))))
        p = jnp.zeros((8, 16), np.float32)
        u = jnp.zeros((8, 16), np.float32)
        v = jnp.zeros((8, 16), np.float32)

        def mse(w):
            return float(np.mean((xs @ np.asarray(w) - ys) ** 2))

        l0 = mse(p[0])
        for _ in range(60):
            p, u, v = smap(p, u, v, xs.reshape(8, 8, 16),
                           ys.reshape(8, 8))
        # replicas stay in sync (same aggregated update everywhere)
        np.testing.assert_allclose(np.asarray(p)[0],
                                   np.asarray(p)[7], rtol=1e-5)
        assert mse(p[0]) < l0 * 0.2


class TestDGCOptimizerGraphPath:
    def test_pre_rampup_matches_plain_momentum(self):
        xs, ys = _toy_problem()
        batch = lambda i: (xs, ys)
        dense, _ = _train(
            lambda l: fluid.optimizer.Momentum(0.2, 0.9).minimize(l),
            8, batch)
        dgc, _ = _train(
            lambda l: fluid.optimizer.DGCMomentumOptimizer(
                0.2, 0.9, rampup_begin_step=10**6).minimize(l),
            8, batch)
        np.testing.assert_allclose(dense, dgc, rtol=1e-5)

    def test_sparsified_training_still_converges(self):
        xs, ys = _toy_problem()
        batch = lambda i: (xs, ys)
        losses, _ = _train(
            lambda l: fluid.optimizer.DGCMomentumOptimizer(
                0.2, 0.9, rampup_begin_step=5, rampup_step=5,
                sparsity=[0.5, 0.75]).minimize(l),
            60, batch)
        assert losses[-1] < losses[0] * 0.3
        dense, _ = _train(
            lambda l: fluid.optimizer.Momentum(0.2, 0.9).minimize(l),
            60, batch)
        # loss parity vs dense within a loose band
        assert losses[-1] < max(dense[-1] * 3.0, 0.3)


class TestGradientMerge:
    def test_merged_equals_big_batch_sgd(self):
        # k micro-batches with GradientMerge == 1 big batch with plain
        # SGD (averaged merge, identical init via fixed param names)
        xs, ys = _toy_problem()
        k = 4
        micro = [(xs[i::k], ys[i::k]) for i in range(k)]

        merged, scope_m = _train(
            lambda l: fluid.optimizer.GradientMergeOptimizer(
                fluid.optimizer.SGD(0.5), k_steps=k).minimize(l),
            k, lambda i: micro[i])

        big, scope_b = _train(
            lambda l: fluid.optimizer.SGD(0.5).minimize(l),
            1, lambda i: (np.concatenate([m[0] for m in micro]),
                          np.concatenate([m[1] for m in micro])))
        for name in ("w0", "b0", "w1", "b1"):
            np.testing.assert_allclose(
                np.asarray(scope_m._get(name)),
                np.asarray(scope_b._get(name)), rtol=2e-4, atol=1e-6)

    def test_params_frozen_between_apply_steps(self):
        xs, ys = _toy_problem()
        prog, startup, loss = _build(
            lambda l: fluid.optimizer.GradientMergeOptimizer(
                fluid.optimizer.SGD(0.5), k_steps=3).minimize(l))
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        exe.run(startup, scope=scope)
        w_before = np.asarray(scope._get("w0")).copy()
        for i in range(2):  # steps 1..2: no apply yet
            exe.run(prog, feed={"x": xs, "y": ys},
                    fetch_list=[loss], scope=scope)
        np.testing.assert_array_equal(np.asarray(scope._get("w0")),
                                      w_before)
        exe.run(prog, feed={"x": xs, "y": ys},
                fetch_list=[loss], scope=scope)  # step 3: apply
        assert np.abs(np.asarray(scope._get("w0")) - w_before).sum() > 0

    def test_momentum_state_advances_only_on_apply(self):
        xs, ys = _toy_problem()
        k = 2
        losses, scope = _train(
            lambda l: fluid.optimizer.GradientMergeOptimizer(
                fluid.optimizer.Momentum(0.2, 0.9),
                k_steps=k).minimize(l),
            8, lambda i: (xs, ys))
        assert losses[-1] < losses[0]

    def test_trains_to_convergence(self):
        xs, ys = _toy_problem()
        losses, _ = _train(
            lambda l: fluid.optimizer.GradientMergeOptimizer(
                fluid.optimizer.SGD(1.0), k_steps=4).minimize(l),
            40, lambda i: (xs, ys))
        assert losses[-1] < losses[0] * 0.3


class TestDGCEncodeOp:
    """The in-graph `dgc` encode op (reference operators/dgc_op.h:38;
    wired by reference optimizer.py:813 _dgc_op)."""

    def _run_op(self, u, v, g, step, **attrs):
        from tests.op_test import OpTest

        class _T(OpTest):
            op_type = "dgc"
            inputs = {"U": u, "V": v, "Grad": g,
                      "current_step": np.asarray([step], np.float32)}
            outputs = {"U_out": u, "V_out": v, "EncodeGrad": g,
                       "Grad_out": g, "k": np.zeros((), np.float32)}

        t = _T("check_output")
        t.attrs = attrs
        t.setUp()
        prog, feed, out_names = t._build()
        exe = fluid.Executor(fluid.CPUPlace())
        outs = exe.run(prog, feed=feed,
                       fetch_list=["U_out", "V_out", "EncodeGrad",
                                   "Grad_out", "k"])
        return [np.asarray(o) for o in outs]

    def test_pre_rampup_is_a_noop_passthrough(self):
        rng = np.random.RandomState(3)
        u = rng.randn(6).astype(np.float32)
        v = rng.randn(6).astype(np.float32)
        g = rng.randn(6).astype(np.float32)
        u1, v1, enc, g1, k = self._run_op(
            u, v, g, step=2, m=0.9, use_nesterov=False,
            sparsity=[0.75], rampup_begin_step=10.0, rampup_step=1.0)
        np.testing.assert_array_equal(u1, u)
        np.testing.assert_array_equal(v1, v)
        np.testing.assert_array_equal(enc, np.zeros_like(g))
        np.testing.assert_array_equal(g1, g)
        assert float(k) == 0.0

    def test_post_rampup_encode_and_masking(self):
        u = np.zeros(4, np.float32)
        v = np.zeros(4, np.float32)
        g = np.asarray([0.1, -0.2, 3.0, 0.05], np.float32)
        u1, v1, enc, g1, k = self._run_op(
            u, v, g, step=5, m=0.9, use_nesterov=False,
            sparsity=[0.75], rampup_begin_step=0.0, rampup_step=1.0)
        # u_c = g, v_c = g; only |v|=3.0 clears the 75% quantile
        np.testing.assert_allclose(enc, [0, 0, 3.0, 0], rtol=1e-6)
        # transmitted entry zeroed from both accumulators
        np.testing.assert_allclose(u1, [0.1, -0.2, 0.0, 0.05],
                                   rtol=1e-6)
        np.testing.assert_allclose(v1, [0.1, -0.2, 0.0, 0.05],
                                   rtol=1e-6)
        # dense grad replaced by the encoded wire (reference zeroes it)
        np.testing.assert_array_equal(g1, np.zeros_like(g))
        assert float(k) == 1.0

    def test_nesterov_momentum_correction(self):
        u = np.asarray([1.0, -1.0], np.float32)
        v = np.asarray([0.5, 0.5], np.float32)
        g = np.asarray([0.2, 0.4], np.float32)
        m = 0.9
        u1, v1, enc, g1, k = self._run_op(
            u, v, g, step=5, m=m, use_nesterov=True,
            sparsity=[0.0], rampup_begin_step=0.0, rampup_step=1.0)
        u_c = m * (u + g)
        v_c = v + u_c + g
        # sparsity 0 -> everything is sent, accumulators fully drain
        np.testing.assert_allclose(enc, v_c, rtol=1e-6)
        np.testing.assert_allclose(u1, 0.0)
        np.testing.assert_allclose(v1, 0.0)
        assert float(k) == 2.0
