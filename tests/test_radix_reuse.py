"""Radix block-prefix reuse (ISSUE 16): shared decoded-token self-KV
chains, COW branching, multi-turn chat sessions.

The invariants this module pins:

* a session's first turn is byte-identical to the cold decode, and a
  RESUBMIT admits through the radix tier — shared blocks mapped
  read-only (``radix_hit_blocks`` counts them), replayed prefix
  byte-identical, ``extend_tokens`` echoed in place — so resumed
  decoding is token-exact vs the history it resumes from;
* ``radix_reuse=False`` keeps the session API but re-prefills full
  history into fresh blocks: SAME tokens (the baseline bench.py
  multiturn measures against), ZERO radix hits;
* best-of-n fan-out shares the prompt entry; greedy branches are
  identical rows;
* the pool never leaks: after close_session the only retained blocks
  are the radix tree's, and evicting the tree drains the pool to
  fully free;
* PagedBeamDecoder — beam branching as COW block branching — is
  token-exact AND score-exact vs the whole-loop
  ``build_beam_decode_program`` oracle, including decodes that cross
  multiple block boundaries, and returns every block to the pool;
* the radix tier composes with tp=2 sharded bundles token-exactly
  (block tables are host-owned and replicated — the tree is oblivious
  to the KV layout a ShardingConfig picks).
"""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.inference import (PagedBeamDecoder,
                                  PagedContinuousGenerationServer,
                                  apply_eos_sentinel)
from paddle_tpu.models.decode_engine import CacheConfig, ShardingConfig

V, D, H, L, S, MAXT = 16, 32, 2, 1, 10, 32
BS, NB, E = 8, 24, 3
END_ID = 1
N_SLOTS = 4
EXT = [5, 6, 7]


def _mixed_len_prompts(rng, n):
    src = rng.randint(3, V, (n, S)).astype(np.int64)
    for r in range(n):
        p = rng.randint(1, S + 1)
        if p < S:
            src[r, p:] = END_ID
    return src


@pytest.fixture(scope="module")
def trained():
    """Train the tiny terminator-copy transformer once; build the
    paged serving bundle and pick a session prompt BY DECODE (the
    test_paged_decode discipline): its generation must cross a block
    boundary yet leave buffer room for two extension turns."""
    from paddle_tpu import unique_name
    from paddle_tpu.core.scope import Scope
    from paddle_tpu.models import transformer as T

    fluid.seed(0)
    scope = Scope()
    with unique_name.guard():
        main, startup, loss = T.build_program(
            seq_len=S, d_model=D, n_heads=H, n_layers=L, d_inner=64,
            vocab=V, with_optimizer=False, dropout_rate=0.0)
        with fluid.program_guard(main, startup):
            fluid.optimizer.Adam(learning_rate=0.02).minimize(loss)
    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(7)
    for _ in range(200):
        src = _mixed_len_prompts(rng, 8)
        tgt_in = np.concatenate(
            [np.full((8, 1), 2, np.int64), src[:, :-1]], 1)
        exe.run(main, feed={"src_ids": src, "tgt_ids": tgt_in,
                            "label": src}, fetch_list=[loss],
                scope=scope)
    kwargs = dict(seq_len=S, max_out_len=MAXT, d_model=D, n_heads=H,
                  n_layers=L, d_inner=64, vocab=V, start_id=2,
                  end_id=END_ID)
    with unique_name.guard():
        paged = T.build_decode_step_program(
            n_slots=N_SLOTS, state_prefix="@rx/",
            cache=CacheConfig(layout="paged", block_size=BS,
                              n_blocks=NB, n_prompt_entries=E),
            **kwargs)
    cands = rng.randint(3, V, (12, S)).astype(np.int64)
    p1 = cold = None
    with PagedContinuousGenerationServer(paged, executor=exe,
                                         scope=scope) as srv:
        for c in cands:
            out = srv.submit(c).result(timeout=120)
            n = int((out != -1).sum())
            if BS + 2 <= n <= MAXT - 2 * (len(EXT) + 1) \
                    and out[n - 1] == END_ID:
                p1, cold = c, np.asarray(out)
                break
    assert p1 is not None, "no candidate generated 10..24 tokens"
    return {"exe": exe, "scope": scope, "paged": paged,
            "kwargs": kwargs, "T": T, "unique_name": unique_name,
            "p1": p1, "cold": cold, "rng": rng}


def _server(tr, **kw):
    return PagedContinuousGenerationServer(
        tr["paged"], executor=tr["exe"], scope=tr["scope"], **kw)


def _two_turns(tr, srv):
    """Turn 1 (fresh session) + turn 2 (extend_tokens) on the picked
    prompt; returns (r1, history-after-turn-1, r2)."""
    r1 = np.asarray(srv.submit(tr["p1"],
                               session_id="chat").result(120.0))
    h1 = list(srv.session_history("chat"))
    r2 = np.asarray(srv.submit(tr["p1"], session_id="chat",
                               extend_tokens=EXT).result(120.0))
    return r1, h1, r2


class TestSessions:
    def test_turn1_byte_identical_to_cold_decode(self, trained):
        with _server(trained) as srv:
            r1 = srv.submit(trained["p1"],
                            session_id="chat").result(120.0)
        assert np.array_equal(r1, trained["cold"])

    def test_turn2_resumes_via_radix_tier(self, trained):
        with _server(trained) as srv:
            r1, h1, r2 = _two_turns(trained, srv)
            st = srv.pool_stats()
        # the harvested history holds >= 1 full block, so turn 2 MUST
        # come back through the radix tier with real block reuse
        assert st["radix_admissions"] >= 1, st
        assert st["radix_hit_blocks"] >= 1, st
        assert st["radix_inserts"] >= 1, st
        # resumed decode replays the retained history byte-exactly,
        # then echoes the user turn in place
        assert np.array_equal(r2[:len(h1)], r1[:len(h1)])
        assert list(r2[len(h1):len(h1) + len(EXT)]) == EXT
        # ... and keeps decoding PAST the first turn's terminator
        assert int((r2 != -1).sum()) > len(h1)

    def test_radix_reuse_false_baseline_same_tokens_zero_hits(
            self, trained):
        with _server(trained) as radix_srv:
            _, _, want = _two_turns(trained, radix_srv)
        with _server(trained, radix_reuse=False) as replay_srv:
            _, _, got = _two_turns(trained, replay_srv)
            st = replay_srv.pool_stats()
        # the re-prefill baseline serves the SAME tokens (it is the
        # cold full-history decode) without touching the tree
        assert np.array_equal(got, want)
        assert st["radix_hit_blocks"] == 0, st
        assert st["radix_inserts"] == 0, st

    def test_close_session_releases_and_evict_drains_pool(
            self, trained):
        with _server(trained) as srv:
            _two_turns(trained, srv)
            srv.close_session("chat")
            assert srv.session_history("chat") is None
            held = len(srv._radix.tree_blocks())
            assert held >= 1
            # only the tree retains blocks once the session is gone
            assert srv._blocks.free_count == NB - held, (
                NB, held, srv._blocks.free_count)
            assert srv._radix.evict(held) == held
            assert srv._blocks.free_count == NB

    def test_best_of_n_shares_prompt_entry_greedy_identical(
            self, trained):
        p2 = _mixed_len_prompts(trained["rng"], 1)[0]
        with _server(trained) as srv:
            hits0 = srv.pool_stats()["prefix_hits"]
            rs = [np.asarray(r.result(120.0))
                  for r in srv.submit(p2, n_best=3)]
            st = srv.pool_stats()
        for r in rs[1:]:
            assert np.array_equal(r, rs[0])
        # branches 2..n admit through the prompt-entry HIT tier (the
        # fan-out shares one refcounted encoder entry)
        assert st["prefix_hits"] - hits0 >= 2, st


class TestBeamCOW:
    """PagedBeamDecoder vs the whole-loop beam oracle. Slow-marked:
    the While-loop beam reference is a multi-minute compile (the
    test_control_flow_decode class of program)."""

    @pytest.fixture(scope="class")
    def beam(self, trained):
        T, unique_name = trained["T"], trained["unique_name"]
        with unique_name.guard():
            beam_m, _, _, (b_ids, b_scores) = \
                T.build_beam_decode_program(
                    beam_size=3, batch_size=1, **trained["kwargs"])
        # params are already trained in the shared scope (explicit
        # enc/dec names) — running the beam startup would re-init them
        with unique_name.guard():
            paged2 = T.build_decode_step_program(
                n_slots=N_SLOTS, state_prefix="@rxb/",
                cache=CacheConfig(layout="paged", block_size=BS,
                                  n_blocks=NB, n_prompt_entries=E),
                **trained["kwargs"])
        dec = PagedBeamDecoder(paged2, beam_size=3,
                               executor=trained["exe"],
                               scope=trained["scope"])
        return {"m": beam_m, "ids": b_ids, "scores": b_scores,
                "dec": dec}

    def _check_parity(self, tr, beam, prompt):
        ref_ids, ref_scores = tr["exe"].run(
            beam["m"], feed={"src_ids": prompt[None]},
            fetch_list=[beam["ids"], beam["scores"]],
            scope=tr["scope"])
        ref_rows = apply_eos_sentinel(np.asarray(ref_ids).T, END_ID)
        ref_sc = sorted(float(s) for s in np.asarray(ref_scores))
        hyps = beam["dec"].decode(prompt, return_all=True)
        got_sc = sorted(sc for _, sc in hyps)
        for g, r in zip(got_sc, ref_sc):
            assert abs(g - r) < 1e-4, (got_sc, ref_sc)
        assert {tuple(t) for t, _ in hyps} \
            == {tuple(r) for r in ref_rows}
        # every block came back (sharing/COW balanced its refcounts)
        assert beam["dec"]._pool.free_count == NB

    @pytest.mark.slow
    def test_short_decode_token_and_score_exact(self, trained, beam):
        p = _mixed_len_prompts(np.random.RandomState(11), 1)[0]
        self._check_parity(trained, beam, p)

    @pytest.mark.slow
    def test_long_decode_crosses_block_boundaries(self, trained,
                                                  beam):
        # the fixture prompt decodes > BS tokens greedily: beam
        # hypotheses cross >= 1 boundary, exercising full-block
        # sharing, sole-heir inheritance and partial-block COW
        self._check_parity(trained, beam, trained["p1"])
        assert beam["dec"].cow_blocks >= 1


class TestTpComposition:
    @pytest.mark.slow
    def test_radix_session_token_exact_on_tp2_bundle(self, trained):
        """The tree keys on token content and block INDICES — both
        host-side and replicated — so a tp=2 placement must not move
        a single token of the resumed decode."""
        import jax

        from paddle_tpu.core.scope import Scope

        T, unique_name = trained["T"], trained["unique_name"]
        with _server(trained) as srv:
            r1, h1, r2 = _two_turns(trained, srv)
        with unique_name.guard():
            tp_bundle = T.build_decode_step_program(
                n_slots=N_SLOTS, state_prefix="@rxtp/",
                sharding=ShardingConfig(tp=2),
                cache=CacheConfig(layout="paged", block_size=BS,
                                  n_blocks=NB, n_prompt_entries=E),
                **trained["kwargs"])
        assert tp_bundle.sharding_plan is not None
        # fork the trained scope to host numpy: the sharded server
        # places ITS OWN copy on its mesh slice
        fork = Scope()
        for name in list(trained["scope"]._vars):
            val = trained["scope"]._get(name)
            if isinstance(val, jax.Array):
                val = np.asarray(val)
            fork._set(name, np.copy(val)
                      if isinstance(val, np.ndarray) else val)
        with PagedContinuousGenerationServer(
                tp_bundle, executor=trained["exe"],
                scope=fork) as tp_srv:
            t1, th1, t2 = _two_turns(trained, tp_srv)
            st = tp_srv.pool_stats()
        assert st["radix_hit_blocks"] >= 1, st
        assert np.array_equal(t1, r1)
        assert th1 == h1
        assert np.array_equal(t2, r2)
