"""Ring/Ulysses context-parallel attention vs dense reference.

Mirrors the reference's dist-test oracle style (test_dist_base.py:
distributed result must match single-process within tight delta), but
for the sequence-parallel attention the reference lacks (SURVEY.md §5).
Runs on the virtual 8-device CPU mesh from conftest.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.parallel import make_mesh, MeshConfig
from paddle_tpu.parallel.ring_attention import ring_self_attention


def dense_reference(q, k, v, scale, causal):
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        t = q.shape[2]
        mask = jnp.arange(t)[:, None] >= jnp.arange(t)[None, :]
        s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))


def _rand_qkv(b=2, h=8, t=64, d=16, seed=0):
    r = np.random.RandomState(seed)
    mk = lambda: r.randn(b, h, t, d).astype(np.float32)
    return mk(), mk(), mk()


@pytest.fixture(scope="module")
def sp_mesh():
    return make_mesh(MeshConfig(sp=8))


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("impl", ["ring", "ulysses"])
    def test_matches_dense(self, sp_mesh, causal, impl):
        q, k, v = _rand_qkv()
        scale = q.shape[-1] ** -0.5
        want = dense_reference(q, k, v, scale, causal)
        got = ring_self_attention(q, k, v, sp_mesh, scale=scale,
                                  causal=causal, impl=impl)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-5, rtol=2e-5)

    def test_grads_match_dense(self, sp_mesh):
        q, k, v = _rand_qkv(t=32)
        scale = q.shape[-1] ** -0.5

        def loss_ring(q, k, v):
            o = ring_self_attention(q, k, v, sp_mesh, scale=scale,
                                    causal=True)
            return (o ** 2).sum()

        def loss_dense(q, k, v):
            return (dense_reference(q, k, v, scale, True) ** 2).sum()

        g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
        g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
        for gr, gd in zip(g_ring, g_dense):
            np.testing.assert_allclose(np.asarray(gr), np.asarray(gd),
                                       atol=5e-4, rtol=5e-4)

    def test_output_stays_sequence_sharded(self, sp_mesh):
        q, k, v = _rand_qkv(t=32)
        out = ring_self_attention(q, k, v, sp_mesh, causal=True)
        shard_shapes = {s.data.shape for s in out.addressable_shards}
        assert shard_shapes == {(2, 8, 4, 16)}  # T=32 split 8 ways


class TestContextParallelProgramPath:
    """The framework `attention` op must route through ring attention
    inside `context_parallel` and produce the same loss as the plain
    single-shard execution of the same Program."""

    def test_transformer_loss_parity(self, sp_mesh):
        import paddle_tpu as fluid
        from paddle_tpu.models import transformer as T
        from paddle_tpu.parallel import context_parallel

        def run_once(cp_mesh=None):
            fluid.seed(5)
            main, startup, cost = T.build_program(
                seq_len=32, d_model=32, n_heads=4, n_layers=1,
                d_inner=64, vocab=128, dropout_rate=0.0,
                with_optimizer=False)
            scope = fluid.Scope()
            exe = fluid.Executor()
            exe.run(startup, scope=scope)
            r = np.random.RandomState(0)
            feed = {k: r.randint(0, 128, (4, 32)).astype(np.int64)
                    for k in ("src_ids", "tgt_ids", "label")}
            if cp_mesh is not None:
                with context_parallel(cp_mesh, impl="ring"):
                    out = exe.run(main, feed=feed, fetch_list=[cost],
                                  scope=scope)
            else:
                out = exe.run(main, feed=feed, fetch_list=[cost],
                              scope=scope)
            return float(np.asarray(out[0]).reshape(-1)[0])

        plain = run_once()
        cp = run_once(sp_mesh)
        np.testing.assert_allclose(cp, plain, rtol=1e-4, atol=1e-5)
