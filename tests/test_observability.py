"""Unified observability layer (paddle_tpu/observability) — r12.

Covers the tentpole and its satellites:

* **Histograms** — the fixed-bucket percentile estimator that replaced
  the servers'/router's raw-sample deques: bucketed p50/p99 must land
  within one bucket width of the EXACT sorted-sample percentile
  (serving._pct is kept as the oracle), memory must stay O(buckets)
  regardless of sample count, and the window-reset contract must hold.
* **Profiler window** — the r12 capture-rule fix: a RecordEvent is
  recorded iff capture was on when the span STARTED (pre-window starts
  excluded whole, in-window starts kept whole past stop_profiler), plus
  the previously-uncovered reset_profiler, plus capture under
  FLAGS_observability=trace with no profiler window open.
* **Trace propagation** — requests submitted through ServingRuntime at
  FLAGS_observability=trace produce a CONNECTED span tree per request
  id in the dumped chrome trace (router.queue -> server.queue ->
  server.dispatch -> execute -> readback under the request root), with
  compile events only during warmup (zero steady-state compile spans)
  carrying fingerprint/tier annotations — and ``off`` emits nothing.
* **Flight recorder** — SLO violations and errors retain full
  timelines; ``incident_report()`` dumps them; metrics level records
  coarse timelines with O(1) cost.
* **Schema stability** — golden key-sets for ``stats_json()`` and the
  metric families in ``expose()`` so dashboards don't silently break.
"""
import bisect
import json
import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import observability as obs
from paddle_tpu import profiler
from paddle_tpu.flags import FLAGS
from paddle_tpu.inference.runtime import ServingRuntime, zoo
from paddle_tpu.inference.serving import _pct, _pct_dict
from paddle_tpu.observability.metrics import (Histogram, MetricsRegistry,
                                              default_ms_buckets)


@pytest.fixture(autouse=True)
def _obs_hermetic():
    """Restore FLAGS_observability and clear the trace/flight sinks
    around every test in this module (the registry's weakref providers
    self-prune, so it is left alone)."""
    saved = FLAGS._values["observability"]
    profiler.reset_profiler()
    obs.reset()
    yield
    FLAGS._values["observability"] = saved
    profiler.reset_profiler()
    obs.reset()


def _set_level(level):
    FLAGS._values["observability"] = level


# --------------------------------------------------------------------
# fixed-bucket histograms (the satellite replacing raw-sample deques)
# --------------------------------------------------------------------
class TestHistogram:
    def test_p99_within_one_bucket_of_exact(self):
        """The pinned accuracy contract: the bucketed estimate must
        land inside the bucket that contains the exact nearest-rank
        sample, for a spread of realistic latency distributions."""
        rng = np.random.RandomState(7)
        edges = default_ms_buckets()
        for dist in (rng.lognormal(3.0, 1.0, 5000),     # ~20ms median
                     rng.exponential(120.0, 5000),       # heavy tail
                     rng.uniform(0.5, 400.0, 5000)):
            h = Histogram("t")
            for v in dist:
                h.observe(float(v))
            samples = sorted(float(v) for v in dist)
            for p in (0.50, 0.99):
                exact = _pct(samples, p)
                est = h.percentile(p)
                idx = bisect.bisect_left(edges, exact)
                lo = edges[idx - 1] if idx > 0 else 0.0
                hi = edges[idx] if idx < len(edges) else samples[-1]
                assert lo <= est <= hi, (
                    f"p{int(p * 100)}: estimate {est} outside the "
                    f"exact sample's bucket [{lo}, {hi}] "
                    f"(exact {exact})")

    def test_memory_is_o1_in_sample_count(self):
        """A million-request run must hold bucket counts, not raw
        samples: the storage footprint is fixed at construction."""
        h = Histogram("t")
        n_cells = len(h._counts)
        for v in np.random.RandomState(0).exponential(50.0, 20000):
            h.observe(float(v))
        assert len(h._counts) == n_cells          # no growth
        assert h.count == 20000
        assert not hasattr(h, "maxlen")           # not a deque

    def test_overflow_bucket_reports_tracked_max(self):
        h = Histogram("t", buckets=[1.0, 10.0])
        for v in (0.5, 5.0, 1e9):
            h.observe(v)
        assert h.percentile(0.99) == 1e9

    def test_reset_window(self):
        h = Histogram("t")
        h.observe(5.0)
        assert h.count == 1
        h.reset()
        assert h.count == 0 and h.percentile(0.5) is None
        h.observe(2.0)
        assert h.count == 1

    def test_pct_dict_handles_both_shapes(self):
        """_pct_dict serves the Histogram path (serving/router) and
        the legacy raw-sample path with one surface."""
        h = Histogram("t")
        for v in (1.0, 2.0, 3.0):
            h.observe(v)
        d = _pct_dict(h)
        assert set(d) == {"p50", "p99"} and d["p50"] is not None
        d2 = _pct_dict([1.0, 2.0, 3.0])
        assert set(d2) == {"p50", "p99"} and d2["p50"] == 2.0

    def test_empty_histogram(self):
        h = Histogram("t")
        assert h.percentile(0.5) is None
        assert _pct_dict(h) == {"p50": None, "p99": None}


# --------------------------------------------------------------------
# profiler window consistency (the r12 capture-rule fix)
# --------------------------------------------------------------------
class TestProfilerWindow:
    def test_pre_window_start_excluded_whole(self, tmp_path, capsys):
        """An event that STARTED before start_profiler must not be
        recorded at all, even though it ends inside the window (the
        old end-sampled rule half-recorded it with a pre-window t0)."""
        ev = profiler.RecordEvent("pre_window")
        ev.__enter__()
        profiler.start_profiler()
        ev.__exit__(None, None, None)
        profiler.stop_profiler(
            profile_path=str(tmp_path / "profile"))
        names = [e[0] for e in profiler._snapshot_events()]
        assert "pre_window" not in names

    def test_in_window_start_kept_past_stop(self, tmp_path, capsys):
        """An event that started inside the window is kept WHOLE even
        when it ends after stop_profiler (the old rule silently
        dropped it)."""
        profiler.start_profiler()
        ev = profiler.RecordEvent("straddles_stop")
        ev.__enter__()
        profiler.stop_profiler(
            profile_path=str(tmp_path / "profile"))
        ev.__exit__(None, None, None)
        names = [e[0] for e in profiler._snapshot_events()]
        assert "straddles_stop" in names

    def test_reset_profiler_clears_events(self, tmp_path, capsys):
        profiler.start_profiler()
        with profiler.record_event("to_reset"):
            pass
        profiler.stop_profiler(
            profile_path=str(tmp_path / "profile"))
        assert profiler._snapshot_events()
        profiler.reset_profiler()
        assert profiler._snapshot_events() == []

    def test_trace_flag_captures_without_profiler_window(self):
        """FLAGS_observability=trace opens capture for the absorbed
        RecordEvent API with no start_profiler call — the host spans
        land in the same _events the unified dump merges."""
        _set_level("trace")
        with profiler.record_event("obs_trace_host_span"):
            pass
        names = [e[0] for e in profiler._snapshot_events()]
        assert "obs_trace_host_span" in names

    def test_event_ring_is_bounded(self):
        """Under FLAGS_observability=trace capture runs outside any
        start/stop window, so the host-span sink must be a bounded
        ring (oldest age out), not an unbounded list that grows with
        traffic for the life of a serving process."""
        _set_level("trace")
        assert profiler._events.maxlen == profiler._MAX_EVENTS

    def test_off_records_nothing(self):
        _set_level("off")
        with profiler.record_event("dropped"):
            pass
        assert profiler._snapshot_events() == []


# --------------------------------------------------------------------
# metrics registry
# --------------------------------------------------------------------
class TestMetricsRegistry:
    def test_off_exposition_is_empty(self):
        _set_level("off")
        text = obs.metrics.expose()
        assert text.startswith("# observability disabled")
        assert "paddle_tpu" not in text

    def test_instruments_dedupe_by_name_and_labels(self):
        reg = MetricsRegistry()
        c1 = reg.counter("c", labels={"a": "1"})
        c2 = reg.counter("c", labels={"a": "1"})
        c3 = reg.counter("c", labels={"a": "2"})
        assert c1 is c2 and c1 is not c3
        c1.inc(2)
        assert c2.value == 2.0 and c3.value == 0.0

    def test_provider_weakref_pruned(self):
        _set_level("metrics")
        reg = MetricsRegistry()

        class P:
            def _metrics_samples(self):
                return [("ephemeral_metric", {}, 1.0)]

        p = P()
        reg.register_provider(p)
        assert any(n == "ephemeral_metric"
                   for n, _, _ in reg.collect())
        del p
        assert not any(n == "ephemeral_metric"
                       for n, _, _ in reg.collect())

    def test_broken_provider_never_breaks_expose(self):
        _set_level("metrics")
        reg = MetricsRegistry()

        class Broken:
            def _metrics_samples(self):
                raise RuntimeError("boom")

        b = Broken()
        reg.register_provider(b)
        reg.counter("survives").inc()
        assert "survives 1" in reg.expose()

    def test_histogram_exposition_shape(self):
        _set_level("metrics")
        reg = MetricsRegistry()
        h = reg.histogram("lat_ms", labels={"server": "s1"})
        for v in (1.0, 5.0, 9.0):
            h.observe(v)
        text = reg.expose()
        assert 'lat_ms{quantile="0.5",server="s1"}' in text
        assert 'lat_ms_count{server="s1"} 3' in text
        assert 'lat_ms_sum{server="s1"} 15' in text

    def test_no_duplicate_series_across_instances(self):
        """Every provider labels its samples with a unique instance
        id: two co-resident registries/routers (same tenant names)
        must not emit duplicate (name, labels) series — duplicates
        make a scraper reject the WHOLE exposition."""
        _set_level("metrics")
        from paddle_tpu.inference.runtime.registry import ModelRegistry
        from paddle_tpu.inference.runtime.router import Router
        regs = [ModelRegistry() for _ in range(2)]
        routers = [Router(r, start=False) for r in regs]
        for r in routers:
            r.add_tenant("same-name", weight=1.0)
        try:
            samples = obs.metrics.REGISTRY.collect()
            keys = [(n, tuple(sorted(l.items())))
                    for n, l, _ in samples]
            dupes = sorted({k for k in keys if keys.count(k) > 1})
            assert not dupes, dupes
        finally:
            for r in routers:
                r.close()

    def test_label_values_are_escaped(self):
        """Tenant/model names are arbitrary caller strings; one
        quote/backslash/newline must not make the whole Prometheus
        scrape unparseable (label-value escaping is required by the
        text exposition format)."""
        _set_level("metrics")
        reg = MetricsRegistry()
        reg.counter("hits", labels={"tenant": 'team"a\\b\nc'}).inc()
        text = reg.expose()
        assert 'hits{tenant="team\\"a\\\\b\\nc"} 1' in text


# --------------------------------------------------------------------
# runtime-driven tracing / flight recorder / schema
# --------------------------------------------------------------------
def _small_runtime(max_batch_size=4):
    """One tiny fc model + one tenant ServingRuntime (module-local
    prefix so scopes never collide with the zoo tests)."""
    rt = ServingRuntime()
    server, scope = zoo.make_fc_server(
        "obsm", 16, 32, 8, executor=rt.executor(),
        max_batch_size=max_batch_size, max_wait_ms=1.0)
    rt.load_model("obsm", server)
    rt.add_tenant("acme", weight=1.0, max_queue=4096)
    return rt, scope


def _submit_n(rt, n, rows=1, rng=None):
    rng = rng or np.random.RandomState(0)
    reps = [rt.submit("acme", "obsm",
                      {"obsm_x": rng.randn(rows, 16).astype(np.float32)})
            for _ in range(n)]
    return [r.result(120.0) for r in reps]


_CHAIN = {"request", "router.queue", "server.queue",
          "server.dispatch", "execute", "readback"}


class TestTracePropagation:
    def test_span_tree_connected_per_request(self, tmp_path):
        """The acceptance criterion: every traced request's chrome
        events form ONE connected tree rooted at its `request` span,
        containing the router->queue->dispatch->execute->readback
        chain, with cache-tier annotations on the dispatch/execute
        spans; compile events appear during warmup ONLY, annotated
        with fingerprint + tier."""
        _set_level("trace")
        rt, _ = _small_runtime()
        try:
            # warmup happened inside load_model: compile events with
            # fingerprint/tier annotations must be in the sink
            with obs.TRACER._lock:
                compiles = [dict(s.attrs)
                            for s in obs.TRACER.global_events]
            assert compiles, "warmup produced no compile events"
            for a in compiles:
                assert a["tier"] in ("cold", "disk")
                assert len(a["fingerprint"]) == 16
            obs.reset()  # end of warmup: steady-state window begins

            _submit_n(rt, 12)
            trace = rt.dump_trace(str(tmp_path / "trace"))
        finally:
            rt.close()

        reqs = {}
        for e in trace["traceEvents"]:
            if e.get("cat") == "request":
                reqs.setdefault(e["args"]["request_id"], []).append(e)
            assert e.get("cat") != "compile", (
                f"steady-state compile span leaked: {e}")
        assert len(reqs) == 12
        for rid, events in reqs.items():
            names = {e["name"] for e in events}
            assert _CHAIN <= names, (
                f"{rid}: incomplete chain {sorted(names)}")
            # connectivity: exactly one root (the request span), and
            # every other span's parent is another span of the SAME
            # request
            ids = {e["args"]["span"] for e in events}
            roots = [e for e in events if e["args"]["parent"] is None]
            assert len(roots) == 1 and roots[0]["name"] == "request"
            for e in events:
                parent = e["args"]["parent"]
                assert parent is None or parent in ids
            # cache-tier annotations ride on the dispatch/execute spans
            by_name = {e["name"]: e for e in events}
            assert by_name["execute"]["args"]["cache"] == "memory"
            assert by_name["server.dispatch"]["args"]["cache"] \
                == "memory"
            assert by_name["request"]["args"]["tenant"] == "acme"

    def test_off_emits_nothing(self, tmp_path):
        _set_level("off")
        rt, _ = _small_runtime()
        try:
            _submit_n(rt, 4)
            trace = rt.dump_trace(str(tmp_path / "trace_off"))
        finally:
            rt.close()
        payload = [e for e in trace["traceEvents"]
                   if e.get("ph") != "M"]
        assert payload == []
        assert obs.RECORDER.recorded_total == 0
        assert obs.start_request() is None
        assert rt.metrics_expose().startswith(
            "# observability disabled")

    def test_host_spans_merge_into_one_dump(self, tmp_path):
        """profiler.py is absorbed: RecordEvent host spans land in the
        same chrome dump (pid 0) as request trees (pid 1)."""
        _set_level("trace")
        with profiler.record_event("host_side_work"):
            time.sleep(0.001)
        trace = obs.dump_trace(str(tmp_path / "merged"))
        host = [e for e in trace["traceEvents"]
                if e.get("cat") == "host"]
        assert any(e["name"] == "host_side_work" for e in host)
        assert all(e["pid"] == 0 for e in host)

    def test_standalone_server_owns_its_traces(self, tmp_path):
        """A server used WITHOUT the router still traces: it opens
        server-owned traces at submit and finishes them at demux."""
        _set_level("trace")
        exe = fluid.Executor(fluid.TPUPlace(0))
        server, _scope = zoo.make_fc_server(
            "obss", 16, 32, 8, executor=exe, max_batch_size=4,
            max_wait_ms=1.0)
        rng = np.random.RandomState(0)
        with server:
            reps = [server.submit(
                {"obss_x": rng.randn(1, 16).astype(np.float32)})
                for _ in range(3)]
            for r in reps:
                r.result(120.0)
        with obs.TRACER._lock:
            traces = list(obs.TRACER.completed)
        assert len(traces) == 3
        for tr in traces:
            assert tr.owner == "server"
            names = {s.name for s in tr.spans}
            assert {"request", "server.queue", "server.dispatch",
                    "execute", "readback"} <= names

    def test_cache_tier_cold_then_memory(self):
        """The dispatch/execute spans derive their cache annotation
        from executor counter deltas around the call (including the
        prepared-lookup compile on a miss): an UNWARMED server's
        first request must say cold, the repeat must say memory —
        'this slow request was compiling' must be readable off the
        incident timeline itself."""
        _set_level("trace")
        exe = fluid.Executor(fluid.TPUPlace(0))
        server, _scope = zoo.make_fc_server(
            "obst", 16, 32, 8, executor=exe, max_batch_size=4,
            max_wait_ms=1.0)
        rng = np.random.RandomState(0)
        feed = {"obst_x": rng.randn(1, 16).astype(np.float32)}
        with server:
            server.submit(dict(feed)).result(120.0)
            server.submit(dict(feed)).result(120.0)
        with obs.TRACER._lock:
            cold_t, warm_t = list(obs.TRACER.completed)[-2:]

        def tiers(tr):
            return {s.name: s.attrs.get("cache") for s in tr.spans
                    if s.name in ("execute", "server.dispatch")}

        assert set(tiers(cold_t).values()) == {"cold"}, tiers(cold_t)
        assert set(tiers(warm_t).values()) == {"memory"}, tiers(warm_t)

    def test_error_path_keeps_server_queue_span(self):
        """Dispatch failure: the server must record its spans BEFORE
        fulfilling the future — set_exception fires the router's
        done-callback synchronously, which seals router-owned traces,
        and a span added after that is dropped. Errored requests are
        exactly the incidents whose timelines must stay complete."""
        _set_level("trace")
        rt = ServingRuntime()
        server, _ = zoo.make_fc_server(
            "obse", 16, 32, 8, executor=rt.executor(),
            max_batch_size=4, max_wait_ms=1.0)
        rt.load_model("obse", server)
        rt.add_tenant("acme", weight=1.0, max_queue=64)

        def boom(feed):
            raise RuntimeError("injected dispatch failure")

        server._runner.run_batch = boom
        try:
            obs.reset()
            with pytest.raises(RuntimeError, match="injected"):
                rt.infer("acme", "obse",
                         {"obse_x": np.zeros((1, 16), np.float32)},
                         timeout=30.0)
        finally:
            rt.close()
        report = obs.incident_report()
        assert report["incidents"], "errored request not retained"
        inc = report["incidents"][-1]
        assert inc["status"] == "error"
        names = {s["name"] for s in inc["spans"]}
        assert "server.queue" in names, sorted(names)


class TestFlightRecorder:
    def test_slo_violation_retained_with_span_tree(self):
        """An SLO-violating request's FULL span tree survives in the
        incident ring and is dumpable via incident_report()."""
        _set_level("trace")
        rt = ServingRuntime()
        server, _ = zoo.make_fc_server(
            "obsm", 16, 32, 8, executor=rt.executor(),
            max_batch_size=4, max_wait_ms=1.0)
        rt.load_model("obsm", server)
        # any real request blows a 1 us target
        rt.add_tenant("acme", weight=1.0, max_queue=4096,
                      target_p99_ms=0.001)
        try:
            obs.reset()
            _submit_n(rt, 3)
            report = rt.incident_report()
        finally:
            rt.close()
        assert report["incidents_total"] == 3
        assert report["incidents"], "no incident retained"
        inc = report["incidents"][-1]
        assert inc["slo_violated"] is True
        assert inc["status"] == "ok"
        assert inc["tenant"] == "acme"
        names = {s["name"] for s in inc["spans"]}
        assert _CHAIN <= names
        json.dumps(report)  # must be JSON-able end to end

    def test_error_is_an_incident(self):
        _set_level("trace")
        rt, _ = _small_runtime()
        try:
            obs.reset()
            rep = rt.submit("acme", "obsm",
                            {"obsm_x": np.zeros((1, 7), np.float32)})
            with pytest.raises(Exception):
                rep.result(120.0)
            report = rt.incident_report()
        finally:
            rt.close()
        assert report["incidents_total"] >= 1
        inc = report["incidents"][-1]
        assert inc["status"] == "error" and "error" in inc

    def test_metrics_level_records_coarse_timelines(self):
        """At metrics level the recorder still names requests and
        keeps coarse timelines (no span capture)."""
        _set_level("metrics")
        rt, _ = _small_runtime()
        try:
            obs.reset()
            _submit_n(rt, 5)
        finally:
            rt.close()
        assert obs.RECORDER.recorded_total == 5
        entry = obs.RECORDER.recent[-1]
        assert entry["request_id"].startswith("req-")
        assert entry["latency_ms"] is not None
        assert "spans" not in entry
        assert len(obs.TRACER.completed) == 0  # no span capture

    def test_ring_bounds(self):
        _set_level("metrics")
        rec = obs.flight.FlightRecorder(max_recent=4, max_incidents=2)
        for i in range(10):
            rec.record({"request_id": f"r{i}"}, incident=(i % 2 == 0))
        assert len(rec.recent) == 4
        assert len(rec.incidents) == 2
        assert rec.recorded_total == 10 and rec.incidents_total == 5

    def test_private_rings_are_not_providers(self):
        """Only the global RECORDER exports paddle_tpu_flight_*
        series: a private ring (tests, bench microbench spins) must
        not emit a duplicate — ambiguous — series into expose()."""
        _set_level("metrics")
        scratch = obs.flight.FlightRecorder(max_recent=4)
        for i in range(7):
            scratch.record({"request_id": f"s{i}"})
        lines = [l for l in obs.metrics.expose().splitlines()
                 if l.startswith("paddle_tpu_flight_recorded_total")]
        assert len(lines) == 1, lines
        assert lines[0].endswith(f" {obs.RECORDER.recorded_total}")


class TestSchemaStability:
    """Golden key-sets: a dashboard scraping stats_json()/expose()
    must not silently break. Extend these sets deliberately when a
    surface grows; never shrink them casually."""

    STATS_TOP = {"uptime_s", "tenants", "models", "registry", "cache"}
    TENANT_KEYS = {"weight", "rate", "target_p99_ms", "queue_depth",
                   "admitted", "rejected", "completed", "failed",
                   "slo_violations", "queue_ms", "latency_ms",
                   "ttft_ms"}
    MODEL_KEYS = {"fingerprint", "kind", "max_inflight", "inflight",
                  "requests", "completed", "batches", "rows",
                  "padded_rows", "batch_occupancy", "queue_depth",
                  "uptime_s", "window_s", "compile_count",
                  "cache_hit_count", "disk_load_count",
                  "cache_evict_count", "warmed_compiles",
                  "latency_ms", "ttft_ms", "per_token_ms", "tokens",
                  "retired_per_s"}
    CACHE_KEYS = {"executable", "compile_count", "cache_hit_count",
                  "disk_load_count", "disk"}
    EXPOSE_FAMILIES = {
        "paddle_tpu_executor_compiles_total",
        "paddle_tpu_executor_cache_hits_total",
        "paddle_tpu_executor_disk_loads_total",
        "paddle_tpu_executor_cache_evictions_total",
        "paddle_tpu_executable_cache_size",
        "paddle_tpu_executable_cache_capacity",
        "paddle_tpu_executable_cache_inserts_total",
        "paddle_tpu_executable_cache_evictions_total",
        "paddle_tpu_registry_models_loaded",
        "paddle_tpu_registry_swaps_total",
        "paddle_tpu_registry_retired_total",
        "paddle_tpu_server_requests_total",
        "paddle_tpu_server_completed_total",
        "paddle_tpu_server_batches_total",
        "paddle_tpu_server_queue_depth",
        "paddle_tpu_server_batch_occupancy",
        "paddle_tpu_server_tokens_total",
        "paddle_tpu_request_latency_ms",
        "paddle_tpu_request_ttft_ms",
        "paddle_tpu_per_token_ms",
        "paddle_tpu_tenant_admitted_total",
        "paddle_tpu_tenant_rejected_total",
        "paddle_tpu_tenant_completed_total",
        "paddle_tpu_tenant_failed_total",
        "paddle_tpu_tenant_slo_violations_total",
        "paddle_tpu_tenant_queue_depth",
        "paddle_tpu_tenant_latency_ms",
        "paddle_tpu_tenant_queue_ms",
        "paddle_tpu_tenant_ttft_ms",
        "paddle_tpu_flight_recorded_total",
        "paddle_tpu_flight_incidents_total",
    }

    @staticmethod
    def _family(line):
        """Metric family name from one exposition line, folding the
        histogram sub-series back onto their family."""
        name = line.split("{")[0].split(" ")[0]
        for suffix in ("_count", "_sum"):
            if name.endswith(suffix):
                name = name[: -len(suffix)]
        return name

    def test_stats_json_golden_keyset(self):
        _set_level("metrics")
        rt, _ = _small_runtime()
        try:
            _submit_n(rt, 4)
            stats = json.loads(rt.stats_json())
        finally:
            rt.close()
        assert set(stats) == self.STATS_TOP
        assert set(stats["tenants"]["acme"]) == self.TENANT_KEYS
        assert set(stats["models"]["obsm"]) == self.MODEL_KEYS
        assert set(stats["cache"]) == self.CACHE_KEYS
        for hist_key in ("latency_ms", "ttft_ms", "queue_ms"):
            assert set(stats["tenants"]["acme"][hist_key]) \
                == {"p50", "p99"}

    def test_expose_golden_families(self):
        _set_level("metrics")
        rt, _ = _small_runtime()
        try:
            _submit_n(rt, 4)
            text = rt.metrics_expose()
        finally:
            rt.close()
        families = {self._family(ln) for ln in text.splitlines()
                    if ln and not ln.startswith("#")}
        missing = self.EXPOSE_FAMILIES - families
        assert not missing, f"expose() lost families: {sorted(missing)}"
